"""Physical plan nodes and their (streaming, batch-at-a-time) execution.

Reference analog: DuckDB physical operators driven by morsel pipelines
(SURVEY.md §3.2 hot loop). Here nodes pull iterators of column batches;
Scan→Filter→Aggregate chains are intercepted by the device offload
(exec/device_agg.py) when compilable — the TPU analog of the reference's
parallel pipeline sink.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Iterator, Optional

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Batch, Column, concat_batches, merge_dictionaries
from ..sql.expr import AggSpec, BoundColumn, BoundExpr
from ..utils.config import SessionSettings
from .tables import TableProvider


@dataclass
class ExecContext:
    settings: SessionSettings = field(default_factory=SessionSettings)
    params: list = field(default_factory=list)
    #: sideways information passing (JoinNode → probe-side ScanNode):
    #: id(scan node) → synthetic build-key-range conjuncts. Keyed on the
    #: EXECUTION context, never on plan nodes — cached plans execute
    #: concurrently and must not see each other's filters.
    join_filters: dict = field(default_factory=dict)
    #: per-query span collector (obs/trace.QueryProfile) or None.
    #: Observation only: executors stamp rows/time/prune counters into
    #: it but never read it back, so a profile can't perturb results.
    profile: object = None
    #: per-query memory accountant (obs/resources.MemoryAccountant) or
    #: None. Same observe-only contract as `profile`: executors charge
    #: live/peak bytes and progress counters into it but never read it
    #: back, so accounting can't perturb results.
    mem: object = None


def empty_batch(names: list[str], types: list[dt.SqlType]) -> Batch:
    cols = [Column(t, np.empty(0, dtype=t.np_dtype), None,
                   np.empty(0, dtype=object) if t.is_string else None)
            for t in types]
    return Batch(list(names), cols)


def _profiled_batches(fn):
    """Wrap one node class's raw batch generator with the span collector
    and/or the memory accountant. With neither on the context this is
    two attribute checks that return the raw generator — zero extra
    frames during iteration, so `serene_profile = off` +
    `serene_mem_account = off` costs nothing in the hot loop."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, ctx):
        prof = getattr(ctx, "profile", None)
        mem = getattr(ctx, "mem", None)
        if prof is None and mem is None:
            return fn(self, ctx)
        gen = prof.wrap_batches(self, fn, ctx) if prof is not None \
            else fn(self, ctx)
        if mem is not None:
            gen = mem.wrap_batches(self, gen)
        return gen

    wrapper._obs_wrapped = True
    wrapper._obs_raw = fn
    return wrapper


class PlanNode:
    names: list[str]
    types: list[dt.SqlType]

    def __init_subclass__(cls, **kwargs):
        # every operator that defines its own batches() is profiled
        # automatically (search_scan/window nodes included) — the span
        # layer can never drift out of sync with new operators
        super().__init_subclass__(**kwargs)
        impl = cls.__dict__.get("batches")
        if impl is not None and not getattr(impl, "_obs_wrapped", False):
            cls.batches = _profiled_batches(impl)

    def batches(self, ctx: ExecContext) -> Iterator[Batch]:
        raise NotImplementedError

    def execute(self, ctx: ExecContext) -> Batch:
        bs = list(self.batches(ctx))
        if not bs:
            return empty_batch(self.names, self.types)
        return concat_batches(bs)

    def children(self) -> list["PlanNode"]:
        return []

    def explain(self, depth: int = 0) -> list[str]:
        line = "  " * depth + self.label()
        out = [line]
        for c in self.children():
            out.extend(c.explain(depth + 1))
        return out

    def label(self) -> str:
        return type(self).__name__


def check_cancel():
    """Cooperative cancellation point at executor batch boundaries
    (reference: interrupt checks inside execution tasks,
    pg_wire_session.h:205-220). Reads the executing connection from the
    contextvar; free when no connection or no cancel pending."""
    from ..engine import CURRENT_CONNECTION
    conn = CURRENT_CONNECTION.get()
    if conn is not None:
        conn.check_cancel()


class ScanNode(PlanNode):
    def __init__(self, provider: TableProvider, columns: list[str],
                 alias: str, filter_expr: Optional[BoundExpr] = None):
        self.provider = provider
        self.columns = columns
        self.alias = alias
        self.filter = filter_expr  # pushed-down predicate (bound to scan schema)
        self.names = list(columns)
        self.types = [provider.type_of(c) for c in columns]

    def batches(self, ctx: ExecContext) -> Iterator[Batch]:
        join_filters = ctx.join_filters.get(id(self)) \
            if ctx.join_filters else None
        if self.filter is not None or join_filters:
            pruned = self._pruned_batches(ctx, join_filters)
            if pruned is not None:
                yield from pruned
                return
        for b in self.provider.batches(self.columns):
            check_cancel()
            if self.filter is not None:
                mask_col = self.filter.eval(b)
                mask = mask_col.data.astype(bool) & mask_col.valid_mask()
                b = b.filter(mask)
            yield b

    def _pruned_batches(self, ctx: ExecContext, join_filters=None):
        """Zone-map skip-scan for a serial scan: blocks whose stats prove
        no row matches are never sliced, blocks that provably match whole
        skip predicate evaluation. `join_filters` are build-key-range
        conjuncts a JoinNode published for this scan (probe side of an
        inner/right hash join) — they prune blocks like filter conjuncts
        but never run per row: rows in surviving blocks that miss the
        range are simply non-matching probe rows. None → plain scan."""
        from . import shard as shard_mod
        from . import zonemap
        pin = self.provider.try_pin()
        block_rows = int(ctx.settings.get("serene_morsel_rows"))
        sharded = isinstance(join_filters, shard_mod.ShardedRanges)
        v_scan = zonemap.block_verdicts(
            self.provider, ctx.settings, [self.filter], self.columns,
            block_rows, pin) if self.filter is not None else None
        if sharded:
            v_join = shard_mod.sharded_verdicts(
                self.provider, ctx.settings, join_filters, self.columns,
                block_rows, pin)
        else:
            v_join = zonemap.block_verdicts(
                self.provider, ctx.settings, list(join_filters),
                self.columns, block_rows, pin) if join_filters else None
        verdicts = zonemap.combine_verdicts(v_scan, v_join)
        if verdicts is None:
            return None
        if v_join is not None:
            zonemap.count_join_filter(v_join)
            if sharded:
                shard_mod.count_shard_pruned(v_join)
                shard_mod.stamp_profile(
                    ctx, id(self), len(join_filters),
                    int((v_join == zonemap.SKIP).sum()))
        zonemap.count_pruned(verdicts)
        prof = getattr(ctx, "profile", None)
        if prof is not None:
            # disjoint attribution (scheduled + pruned + jf_pruned =
            # total blocks): a block both analyses would skip counts
            # once, under the join filter
            total = int((verdicts == zonemap.SKIP).sum())
            jf = int((v_join == zonemap.SKIP).sum()) \
                if v_join is not None else 0
            prof.add_scan_morsels(id(self),
                                  scheduled=len(verdicts) - total,
                                  pruned=total - jf, jf_pruned=jf)
        if pin is not None and all(c in pin[0] for c in self.columns):
            full = Batch(list(self.columns),
                         [pin[0].column(c) for c in self.columns])
        else:
            full = self.provider.full_batch(self.columns)
        nrows = full.num_rows
        scan_exprs = [self.filter] if self.filter is not None else []
        exprs = scan_exprs + (list(join_filters or [])
                              if not sharded else [])

        def gen():
            if zonemap.verify_enabled(ctx.settings):
                spans = [(b * block_rows, min((b + 1) * block_rows, nrows))
                         for b in np.flatnonzero(verdicts == zonemap.SKIP)]
                if sharded:
                    # OR semantics: a pruned block must fail EVERY build
                    # shard's range conjunction (plus the scan filter)
                    for grp in join_filters:
                        zonemap.verify_pruned_blocks(
                            scan_exprs + list(grp), full, spans,
                            f"scan {self.provider.name}")
                else:
                    zonemap.verify_pruned_blocks(
                        exprs, full, spans, f"scan {self.provider.name}")
            emitted = False
            for b, v in enumerate(verdicts):
                check_cancel()
                if v == zonemap.SKIP:
                    continue
                sl = full.slice(b * block_rows,
                                min((b + 1) * block_rows, nrows))
                # the filter-skip decision reads the SCAN verdict: a
                # join-range SCAN must not force a re-eval the zone maps
                # already proved all-match, and a join-range ALL says
                # nothing about the scan filter
                if self.filter is not None and \
                        (v_scan is None or v_scan[b] != zonemap.ALL):
                    c = self.filter.eval(sl)
                    sl = sl.filter(c.data.astype(bool) & c.valid_mask())
                emitted = True
                yield sl
            if not emitted:
                yield full.slice(0, 0)
        return gen()

    def label(self) -> str:
        f = " filter=yes" if self.filter is not None else ""
        return f"Scan {self.provider.name} [{', '.join(self.columns)}]{f}"


def _take_null_extended(batch: Batch, idx: np.ndarray) -> list[Column]:
    """Row gather where idx == -1 yields a NULL row (outer-join extension)."""
    nullmask = idx < 0
    out = []
    for c in batch.columns:
        if batch.num_rows == 0:
            out.append(Column.from_pylist([None] * len(idx), c.type))
            continue
        t = c.take(np.where(nullmask, 0, idx))
        validity = t.valid_mask() & ~nullmask
        out.append(Column(t.type, t.data,
                          None if validity.all() else validity, t.dictionary))
    return out


def _merge_using_columns(lc: Column, rc: Column,
                         right_only: np.ndarray) -> Column:
    """FULL JOIN USING merged key: COALESCE(l, r) realized as one
    np.where over the null-extended sides (right-only rows take the
    right value). Dictionary strings re-encode onto a shared dictionary
    first so the select works on codes."""
    from ..columnar.column import merge_dictionaries
    if lc.type.is_string and rc.type.is_string:
        ml, mr = merge_dictionaries([lc, rc])
        data = np.where(right_only, mr.data, ml.data).astype(ml.data.dtype)
        validity = np.where(right_only, mr.valid_mask(), ml.valid_mask())
        return Column(lc.type, data,
                      None if validity.all() else validity, ml.dictionary)
    if lc.type.is_string != rc.type.is_string:   # heterogeneous USING pair
        lvals, rvals = lc.to_pylist(), rc.to_pylist()
        merged = [rvals[i] if right_only[i] else lvals[i]
                  for i in range(len(lvals))]
        return Column.from_pylist(merged, lc.type)
    if rc.data.dtype != lc.data.dtype and lc.data.dtype.kind in "iu":
        # astype would WRAP a wider right value that overflows the left
        # key's physical type; the row merge this replaced raised 22003
        merged = rc.data[right_only & rc.valid_mask()]
        if len(merged):
            info = np.iinfo(lc.data.dtype)
            if merged.min() < info.min or merged.max() > info.max:
                raise errors.SqlError(
                    "22003", f"value out of range for type "
                    f"{lc.type.id.name.lower()}")
    data = np.where(right_only, rc.data.astype(lc.data.dtype), lc.data)
    validity = np.where(right_only, rc.valid_mask(), lc.valid_mask())
    return Column(lc.type, data,
                  None if validity.all() else validity, lc.dictionary)


class ValuesNode(PlanNode):
    def __init__(self, batch: Batch):
        self.batch = batch
        self.names = list(batch.names)
        self.types = [c.type for c in batch.columns]

    def batches(self, ctx):
        yield self.batch

    def label(self):
        return f"Values ({self.batch.num_rows} rows)"


class FilterNode(PlanNode):
    def __init__(self, child: PlanNode, pred: BoundExpr):
        self.child = child
        self.pred = pred
        self.names = child.names
        self.types = child.types

    def children(self):
        return [self.child]

    def batches(self, ctx):
        for b in self.child.batches(ctx):
            c = self.pred.eval(b)
            mask = c.data.astype(bool) & c.valid_mask()
            yield b.filter(mask)

    def label(self):
        return "Filter"


class ProjectNode(PlanNode):
    def __init__(self, child: PlanNode, exprs: list[BoundExpr],
                 names: list[str]):
        self.child = child
        self.exprs = exprs
        self.names = names
        self.types = [e.type for e in exprs]

    def children(self):
        return [self.child]

    def batches(self, ctx):
        for b in self.child.batches(ctx):
            cols = [e.eval(b) for e in self.exprs]
            yield Batch(list(self.names), cols)

    def label(self):
        return f"Project [{', '.join(self.names)}]"


class LimitNode(PlanNode):
    def __init__(self, child: PlanNode, limit: Optional[int], offset: int = 0):
        if limit is not None and limit < 0:
            raise errors.SqlError("2201W", "LIMIT must not be negative")
        if offset and offset < 0:
            raise errors.SqlError("2201X", "OFFSET must not be negative")
        self.child = child
        self.limit = limit
        self.offset = offset
        self.names = child.names
        self.types = child.types

    def children(self):
        return [self.child]

    def batches(self, ctx):
        if isinstance(self.child, SortNode):
            # chained device residency first: a fused aggregate under
            # the sort runs agg → top-N as two dispatches with the
            # accumulators handed off in HBM. Then fused top-N (owns
            # the FILTERED scan shape: predicate masks to the sort
            # sentinel inside the same program as top_k); the
            # unfiltered shape stays with device_topn, and all three
            # decline overlapping territory
            from .device_pipeline import (try_device_chained_topn,
                                          try_device_fused_topn)
            out = try_device_chained_topn(self, ctx)
            if out is None:
                out = try_device_fused_topn(self, ctx)
            if out is None:
                from .device_topn import try_device_topn
                out = try_device_topn(self, ctx)
            if out is not None:
                yield out
                return
        skipped = 0
        emitted = 0
        for b in self.child.batches(ctx):
            if self.offset and skipped < self.offset:
                take = min(b.num_rows, self.offset - skipped)
                skipped += take
                b = b.slice(take, b.num_rows)
            if b.num_rows == 0:
                continue
            if self.limit is not None:
                remaining = self.limit - emitted
                if remaining <= 0:
                    return
                if b.num_rows > remaining:
                    b = b.slice(0, remaining)
            emitted += b.num_rows
            yield b

    def label(self):
        return f"Limit {self.limit} offset {self.offset}"


def _record_sort_ranks(col: Column) -> np.ndarray:
    """Dense field-wise sort ranks for a record column (PG record_cmp
    order, not physical-text order — text would put ROW(10) before
    ROW(2))."""
    import functools

    from ..columnar.pgcopy import record_cmp_total
    vals = [str(v) for v in col.to_pylist()]
    n = len(vals)
    order = sorted(range(n),
                   key=functools.cmp_to_key(
                       lambda i, j: record_cmp_total(vals[i], vals[j])))
    ranks = np.zeros(n, dtype=np.int64)
    r = 0
    for k, i in enumerate(order):
        if k > 0 and record_cmp_total(vals[order[k - 1]], vals[i]) != 0:
            r += 1
        ranks[i] = r
    return ranks


class SortNode(PlanNode):
    """Full materializing sort. keys are column indices into the child
    output; PG default null ordering: NULLS LAST asc, NULLS FIRST desc."""

    def __init__(self, child: PlanNode, key_indices: list[int],
                 descs: list[bool], nulls_first: list[Optional[bool]]):
        self.child = child
        self.key_indices = key_indices
        self.descs = descs
        self.nulls_first = nulls_first
        self.names = child.names
        self.types = child.types

    def children(self):
        return [self.child]

    def batches(self, ctx):
        full = concat_batches(list(self.child.batches(ctx)))
        mem = getattr(ctx, "mem", None)
        sort_bytes = 0
        if mem is not None:
            # the materialized sort buffer (input copy + key ranks are
            # the same order of bytes; the input batch is the charge)
            from ..obs.trace import batch_nbytes
            sort_bytes = batch_nbytes(full)
            mem.charge(id(self), sort_bytes)
        try:
            yield from self._sorted(full)
        finally:
            if sort_bytes:
                mem.release(id(self), sort_bytes)

    def _sorted(self, full):
        if full.num_rows <= 1:
            yield full
            return
        # np.lexsort: last key is primary. Keys are densified to int64 ranks
        # (np.unique inverse) so DESC negation and NULL placement are exact
        # for any dtype, including int64 beyond 2^53.
        keys = []
        for ki, desc, nf in zip(reversed(self.key_indices),
                                reversed(self.descs),
                                reversed(self.nulls_first)):
            col = full.columns[ki]
            null_first = nf if nf is not None else desc
            if col.type.id is dt.TypeId.RECORD:
                ranks = _record_sort_ranks(col)
            else:
                _, ranks = np.unique(col.data, return_inverse=True)
            ranks = ranks.astype(np.int64)
            if desc:
                ranks = -ranks
            nulls = ~col.valid_mask()
            nullkey = np.where(nulls, -1, 1) if null_first \
                else np.where(nulls, 1, -1)
            keys.append(np.where(nulls, 0, ranks))
            keys.append(nullkey)
        order = np.lexsort(tuple(keys))
        yield full.take(order)

    def label(self):
        return f"Sort {list(zip(self.key_indices, self.descs))}"


class DropColumnsNode(PlanNode):
    """Drops hidden sort columns after Sort."""

    def __init__(self, child: PlanNode, keep: int):
        self.child = child
        self.keep = keep
        self.names = child.names[:keep]
        self.types = child.types[:keep]

    def children(self):
        return [self.child]

    def batches(self, ctx):
        for b in self.child.batches(ctx):
            yield Batch(list(self.names), b.columns[:self.keep])

    def label(self):
        return f"Project(keep {self.keep})"


class JoinNode(PlanNode):
    """Hash join (inner/left/right/full/cross). Equi-keys are extracted
    by the planner; residual predicates run over candidate pairs.

    The default path is vectorized (ISSUE 3): both sides' keys factorize
    into one dense int64 code space (ops/agg.factorize_codes via
    morsel.combined_codes), the build side becomes an argsort/bincount
    offset index, and probe morsels expand matches on the shared worker
    pool with repeat/cumsum arithmetic — no python dicts or row tuples.
    The build side also publishes its key min/max to the probe scan's
    zone-map analyzer (`serene_join_filter`) so provably partner-less
    probe morsels are never enqueued (inner/right only: left/full must
    emit unmatched probe rows). `SET serene_join_vectorized = off` runs
    the legacy row-tuple interpreter; results are bit-identical."""

    def __init__(self, kind: str, left: PlanNode, right: PlanNode,
                 left_keys: list[BoundExpr], right_keys: list[BoundExpr],
                 residual: Optional[BoundExpr], names: list[str],
                 types: list[dt.SqlType],
                 merge_pairs: Optional[list] = None):
        self.kind = kind
        self.left = left
        self.right = right
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.residual = residual
        self.names = names
        self.types = types
        #: FULL JOIN USING: (left col idx, right col idx) pairs whose
        #: left copy takes the right side's value on right-only rows
        self.merge_pairs = merge_pairs or []

    def children(self):
        return [self.left, self.right]

    def batches(self, ctx):
        from ..obs.trace import batch_nbytes
        mem = getattr(ctx, "mem", None)
        held = 0          # input/pair bytes charged to this node

        def hold(n):
            nonlocal held
            if mem is not None and n:
                mem.charge(id(self), n)
                held += n

        scan = self._join_filter_target(ctx)
        scan_id = None
        rkey_cols = None
        if scan is None:
            # no sideways filter possible: keep the pre-filter left-then-
            # right evaluation order (side-effect parity with the oracle)
            lb = concat_batches(list(self.left.batches(ctx)))
            rb = concat_batches(list(self.right.batches(ctx)))
        else:
            # build side (right) materializes FIRST so its key range can
            # prune the probe scan's morsels before they are enqueued
            rb = concat_batches(list(self.right.batches(ctx)))
            if rb.num_rows:
                from . import shard as shard_mod
                from . import zonemap
                rkey_cols = [k.eval(rb) for k in self.right_keys]
                # shard-to-shard sideways passing: with serene_shards >
                # 1 the build side publishes PER-SHARD key ranges (one
                # min/max per round-robin block group) — probe blocks in
                # the gaps between shard ranges prune where the single
                # global envelope could not
                published = None
                n_shards = shard_mod.shard_count(ctx.settings)
                if n_shards > 1:
                    # the build side here is a materialized subtree
                    # batch (no provider), so the view comes straight
                    # from the partitioning function
                    published = shard_mod.build_shard_ranges(
                        self.left_keys, rkey_cols,
                        shard_mod.shard_spans(
                            rb.num_rows,
                            int(ctx.settings.get("serene_morsel_rows")),
                            n_shards))
                if published is None:
                    exprs = zonemap.build_key_range_exprs(
                        self.left_keys, rkey_cols)
                    published = exprs if exprs else None
                if published:
                    ctx.join_filters[id(scan)] = published
                    scan_id = id(scan)
            try:
                lb = concat_batches(list(self.left.batches(ctx)))
            finally:
                if scan_id is not None:
                    ctx.join_filters.pop(scan_id, None)
        # memory accounting: the materialized build + probe sides are
        # this operator's dominant buffers; the candidate pair index
        # arrays join them below. Charged here, released when the
        # output batch has been consumed (generator close).
        hold(batch_nbytes(rb))
        hold(batch_nbytes(lb))
        li, ri = self._match_inner(lb, rb, ctx, rkey_cols)
        hold(int(li.nbytes) + int(ri.nbytes))
        # ON-clause residual applies to *candidate pairs* (outer-join
        # semantics: a pair failing the residual is unmatched, the left row
        # survives null-extended — PG LEFT JOIN ... ON a AND b)
        if self.residual is not None and len(li):
            pair = Batch(list(self.names),
                         lb.take(li).columns + rb.take(ri).columns)
            c = self.residual.eval(pair)
            keep = c.data.astype(bool) & c.valid_mask()
            li, ri = li[keep], ri[keep]
        if self.kind in ("left", "full"):
            matched = np.zeros(lb.num_rows, dtype=bool)
            matched[li] = True
            extra = np.flatnonzero(~matched)
            li = np.concatenate([li, extra])
            ri = np.concatenate([ri, np.full(len(extra), -1, dtype=np.int64)])
        if self.kind in ("right", "full"):
            matched = np.zeros(rb.num_rows, dtype=bool)
            matched[ri[ri >= 0]] = True
            extra = np.flatnonzero(~matched)
            ri = np.concatenate([ri, extra])
            li = np.concatenate([li, np.full(len(extra), -1, dtype=np.int64)])
        lcols = _take_null_extended(lb, li)
        rcols = _take_null_extended(rb, ri)
        if self.merge_pairs:
            right_only = li < 0
            if right_only.any():
                for lk, rk in self.merge_pairs:
                    lcols[lk] = _merge_using_columns(
                        lcols[lk], rcols[rk], right_only)
        try:
            yield Batch(list(self.names), lcols + rcols)
        finally:
            if mem is not None and held:
                mem.release(id(self), held)

    def _join_filter_target(self, ctx) -> Optional["ScanNode"]:
        """The probe-side scan the build key range could prune, when the
        sideways filter is sound: inner/right joins only (left/full emit
        unmatched probe rows and must scan everything), at least one
        bare-column probe key, a probe subtree whose scan indices are
        stable (Filter chains only), and no volatile build-key
        expressions (pre-probe evaluation would double-draw their
        state). None ⇒ run the join in plain left-then-right order."""
        from . import zonemap
        if self.kind not in ("inner", "right") or not self.left_keys:
            return None
        if not zonemap.join_filter_enabled(ctx.settings) or \
                not zonemap.enabled(ctx.settings):
            return None
        if not any(isinstance(k, BoundColumn) for k in self.left_keys):
            return None
        scan = self.left
        while isinstance(scan, FilterNode):
            scan = scan.child
        if type(scan) is not ScanNode:
            return None
        from ..sql.binder import _VOLATILE_FUNCS
        for k in self.right_keys:
            for sub in k.walk():
                if getattr(sub, "name", None) in _VOLATILE_FUNCS:
                    return None
        return scan

    def _match_inner(self, lb: Batch, rb: Batch, ctx,
                     rkey_cols=None) -> tuple[np.ndarray, np.ndarray]:
        """Candidate (inner) pairs; left-join null extension happens later."""
        if self.kind == "cross" or not self.left_keys:
            li = np.repeat(np.arange(lb.num_rows), rb.num_rows)
            ri = np.tile(np.arange(rb.num_rows), lb.num_rows)
            return li, ri
        lkeys = [k.eval(lb) for k in self.left_keys]
        rkeys = rkey_cols if rkey_cols is not None \
            else [k.eval(rb) for k in self.right_keys]
        from .morsel import join_pairs, vectorized_enabled
        if vectorized_enabled(ctx.settings):
            out = join_pairs(lkeys, rkeys, ctx.settings,
                             lb.num_rows, rb.num_rows)
            if out is not None:
                return out
        return self._match_legacy(lkeys, rkeys, lb.num_rows, rb.num_rows)

    def _match_legacy(self, lkeys: list[Column], rkeys: list[Column],
                      nl: int, nr: int) -> tuple[np.ndarray, np.ndarray]:
        """Row-tuple parity oracle (pre-ISSUE-3 interpreter): a python
        dict of build-side tuples probed row by row."""
        lt = list(zip(*(c.to_pylist() for c in lkeys))) \
            if lkeys else [()] * nl
        rt = list(zip(*(c.to_pylist() for c in rkeys))) \
            if rkeys else [()] * nr
        table: dict = {}
        for j, key in enumerate(rt):
            if any(k is None for k in key):
                continue  # NULL never joins
            table.setdefault(key, []).append(j)
        li, ri = [], []
        for i, key in enumerate(lt):
            if any(k is None for k in key):
                continue
            for j in table.get(key, ()):
                li.append(i)
                ri.append(j)
        return (np.asarray(li, dtype=np.int64),
                np.asarray(ri, dtype=np.int64))

    def label(self):
        return f"HashJoin {self.kind}"


class SetOpNode(PlanNode):
    """UNION / INTERSECT / EXCEPT with set (default) or bag (ALL) semantics.
    Row-tuple based on CPU; schema/names come from the left arm."""

    def __init__(self, op: str, all_: bool, left: PlanNode, right: PlanNode):
        self.op = op
        self.all = all_
        self.left = left
        self.right = right
        self.names = list(left.names)
        self.types = [_unify_setop_type(lt, rt)
                      for lt, rt in zip(left.types, right.types)]

    def children(self):
        return [self.left, self.right]

    def label(self):
        return f"SetOp {self.op.upper()}{' ALL' if self.all else ''}"

    def batches(self, ctx):
        if self.op == "union" and self.all:
            # pure concatenation: stay columnar, no python row tuples
            from ..sql.binder import cast_column
            for arm in (self.left, self.right):
                for b in arm.batches(ctx):
                    cols = [cast_column(c, t)
                            for c, t in zip(b.columns, self.types)]
                    yield Batch(list(self.names), cols)
            return
        from .morsel import vectorized_enabled
        if vectorized_enabled(ctx.settings):
            out = self._batches_vectorized(ctx)
            if out is not None:
                yield out
                return
        yield from self._batches_legacy(ctx)

    def _batches_vectorized(self, ctx) -> Optional[Batch]:
        """Set semantics over dense key codes (ISSUE 3): both arms cast
        to the unified types, factorize into ONE code space, and every
        variant becomes bincount/first-occurrence arithmetic — identical
        row selection and order to the row-tuple oracle (NULL = NULL,
        each NaN occurrence distinct). None → unsupported column shape,
        run the legacy path."""
        from ..sql.binder import cast_column
        from .morsel import (combined_codes, first_occurrence_mask,
                             occurrence_ranks)
        if any(t.id is dt.TypeId.NULL for t in self.types):
            return None
        lb = self.left.execute(ctx)
        rb = self.right.execute(ctx)
        for arm in (lb, rb):
            for c, t in zip(arm.columns, self.types):
                # an integer arm unified to DOUBLE collapses beyond 2**53
                # under the cast; the row-tuple oracle compares int ==
                # float exactly, so those shapes stay on it
                if t.is_float and c.data.dtype.kind in "iu" and \
                        len(c.data) and \
                        (int(c.data.max()) > 2 ** 53 or
                         int(c.data.min()) < -(2 ** 53)):
                    return None
        try:
            lcols = [cast_column(c, t)
                     for c, t in zip(lb.columns, self.types)]
            rcols = [cast_column(c, t)
                     for c, t in zip(rb.columns, self.types)]
        except errors.SqlError:
            return None
        pair = combined_codes(lcols, rcols)
        if pair is None:
            return None
        cl, cr, g = pair
        nl = len(cl)
        if self.op == "union":                      # UNION (distinct)
            codes = np.concatenate([cl, cr])
            keep = first_occurrence_mask(codes, g)
            both = concat_batches([Batch(list(self.names), lcols),
                                   Batch(list(self.names), rcols)])
            return both if keep.all() else both.filter(keep)
        counts_r = np.bincount(cr, minlength=g)
        if self.all:
            # bag semantics: the k-th occurrence of a value on the left
            # pairs off against (INTERSECT) or outlives (EXCEPT) the
            # right side's multiplicity
            occ = occurrence_ranks(cl, g)
            if self.op == "intersect":
                keep = occ < counts_r[cl]
            else:                                   # except
                keep = occ >= counts_r[cl]
        else:
            first = first_occurrence_mask(cl, g)
            if self.op == "intersect":
                keep = first & (counts_r[cl] > 0)
            else:                                   # except
                keep = first & (counts_r[cl] == 0)
        left = Batch(list(self.names), lcols)
        return left if keep.all() else left.filter(keep)

    def _batches_legacy(self, ctx):
        """Row-tuple parity oracle (pre-ISSUE-3 interpreter)."""
        lrows = self.left.execute(ctx).rows()
        rrows = self.right.execute(ctx).rows()
        if self.op == "union":
            out = lrows + rrows
            if not self.all:
                out = _dedup(out)
        elif self.op == "intersect":
            from collections import Counter
            rc = Counter(rrows)
            if self.all:
                out = []
                for row in lrows:
                    if rc[row] > 0:
                        rc[row] -= 1
                        out.append(row)
            else:
                rset = set(rrows)
                out = _dedup([row for row in lrows if row in rset])
        else:  # except
            from collections import Counter
            rc = Counter(rrows)
            if self.all:
                out = []
                for row in lrows:
                    if rc[row] > 0:
                        rc[row] -= 1
                    else:
                        out.append(row)
            else:
                rset = set(rrows)
                out = _dedup([row for row in lrows if row not in rset])
        cols = []
        for i, t in enumerate(self.types):
            cols.append(Column.from_pylist([r[i] for r in out], t))
        yield Batch(list(self.names), cols)


class DistinctOnNode(PlanNode):
    """SELECT DISTINCT ON (keys): keep the FIRST row (in the incoming,
    already-sorted order) of each distinct key tuple (PG semantics)."""

    def __init__(self, child: PlanNode, key_indices: list):
        self.child = child
        self.key_indices = list(key_indices)
        self.names = list(child.names)
        self.types = list(child.types)

    def children(self):
        return [self.child]

    def label(self):
        return f"DistinctOn {self.key_indices}"

    def batches(self, ctx):
        from .morsel import (factorize_codes, first_occurrence_mask,
                             vectorized_enabled)
        vectorized = vectorized_enabled(ctx.settings) and \
            bool(self.key_indices)
        # cross-batch dedup state: within-batch duplicates fall to one
        # code-based first-occurrence pass; across batches only the
        # WINNERS' decoded keys enter a python set (O(distinct keys)
        # total, never O(rows)). The set is seeded lazily so the common
        # single-batch plan never decodes a key at all.
        seen: Optional[set] = None
        pending: Optional[list[Column]] = None   # first batch's winners

        def flush_pending():
            nonlocal seen, pending
            if seen is None:
                seen = set()
            if pending is not None:
                seen.update(zip(*(c.to_pylist() for c in pending)))
                pending = None

        for b in self.child.batches(ctx):
            key_cols = [b.columns[i] for i in self.key_indices]
            supported = vectorized and all(
                (c.type.is_string and c.dictionary is not None) or
                (not c.type.is_string and c.data.dtype.kind in "biuf")
                for c in key_cols)
            if supported:
                codes, g = factorize_codes(
                    [c.data for c in key_cols],
                    [c.validity for c in key_cols])
                all_unique = g == b.num_rows
                keep = None if all_unique \
                    else first_occurrence_mask(codes, g)
                if seen is None and pending is None:
                    pending = key_cols if keep is None \
                        else [c.filter(keep) for c in key_cols]
                    yield b if keep is None else b.filter(keep)
                    continue
                flush_pending()
                if keep is None:
                    keep = np.ones(b.num_rows, dtype=bool)
                cand = np.flatnonzero(keep)
                if len(cand):
                    rows = zip(*(kc.take(cand).to_pylist()
                                 for kc in key_cols))
                    for j, row in enumerate(rows):
                        if row in seen:
                            keep[cand[j]] = False
                        else:
                            seen.add(row)
                yield b if keep.all() else b.filter(keep)
                continue
            # row-tuple path (legacy mode or unsupported key shape)
            flush_pending()
            key_vals = [kc.to_pylist() for kc in key_cols]
            keep = np.zeros(b.num_rows, dtype=bool)
            for r in range(b.num_rows):
                k = tuple(kc[r] for kc in key_vals)
                if k not in seen:
                    seen.add(k)
                    keep[r] = True
            yield b if keep.all() else b.filter(keep)


class RenameNode(PlanNode):
    """Output-column rename (CTE column lists: WITH c(a, b) AS ...)."""

    def __init__(self, child: PlanNode, names: list):
        self.child = child
        if len(names) != len(child.names):
            raise errors.SqlError(
                "42P10", "column list does not match the number of "
                "output columns")
        self.names = list(names)
        self.types = list(child.types)

    def children(self):
        return [self.child]

    def batches(self, ctx):
        for b in self.child.batches(ctx):
            yield Batch(list(self.names), list(b.columns))


class RecursiveCteNode(PlanNode):
    """WITH RECURSIVE fixpoint: run the base term, then re-run the step
    term against the previous iteration's rows (exposed as the `work`
    MemTable the step plan scans) until no new rows arrive. UNION (not
    ALL) deduplicates across ALL accumulated rows, so cyclic graphs
    terminate (PG semantics, src/backend/executor/nodeRecursiveunion.c
    re-expressed over columnar batches)."""

    MAX_ITERATIONS = 20_000

    def __init__(self, names, base: PlanNode, step: PlanNode, work,
                 union_all: bool):
        self.names = list(names)
        self.types = list(base.types)
        self.base = base
        self.step = step
        self.work = work
        self.union_all = union_all

    def children(self):
        return [self.base, self.step]

    def label(self):
        return f"RecursiveCte {self.work.name}" + \
            (" ALL" if self.union_all else "")

    def batches(self, ctx):
        from ..sql.binder import cast_column
        seen: set = set()
        acc: list[Batch] = []

        def conform(b: Batch) -> Batch:
            cols = [cast_column(c, t) for c, t in zip(b.columns, self.types)]
            return Batch(list(self.names), cols)

        def dedup(b: Batch) -> Batch:
            rows = b.rows()
            keep = np.ones(len(rows), dtype=bool)
            for i, r in enumerate(rows):
                if r in seen:
                    keep[i] = False
                else:
                    seen.add(r)
            return b if keep.all() else b.filter(keep)

        cur = conform(self.base.execute(ctx))
        if not self.union_all:
            cur = dedup(cur)
        it = 0
        while cur.num_rows:
            check_cancel()
            acc.append(cur)
            it += 1
            if it > self.MAX_ITERATIONS:
                raise errors.SqlError(
                    "54001", "recursive query iteration limit exceeded")
            self.work.replace(cur)
            cur = conform(self.step.execute(ctx))
            if not self.union_all:
                cur = dedup(cur)
        # leave the working table empty so a cached plan re-executes from
        # a clean slate
        self.work.replace(Batch(list(self.names),
                                [Column.from_pylist([], t)
                                 for t in self.types]))
        if not acc:
            yield empty_batch(self.names, self.types)
            return
        for b in acc:
            yield b


def _unify_setop_type(lt: dt.SqlType, rt: dt.SqlType) -> dt.SqlType:
    if lt.id is dt.TypeId.NULL:
        return rt
    if rt.id is dt.TypeId.NULL:
        return lt
    if lt == rt:
        return lt
    if lt.is_numeric and rt.is_numeric:
        return dt.common_numeric(lt, rt)
    raise errors.SqlError(errors.DATATYPE_MISMATCH,
                          f"UNION types {lt} and {rt} cannot be matched")


def _dedup(rows: list[tuple]) -> list[tuple]:
    seen = set()
    out = []
    for r in rows:
        if r not in seen:
            seen.add(r)
            out.append(r)
    return out


def _sort_key(v, desc: bool, nulls_first=None):
    """Orderable wrapper for aggregate ORDER BY keys; NULL placement
    defaults to last asc / first desc, override via NULLS FIRST/LAST."""
    null_first = nulls_first if nulls_first is not None else desc
    if v is None:
        return (-1 if null_first else 1, 0)
    return (0, _Rev(v) if desc else v)


class _Rev:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return other.v == self.v


#: aggregates whose result is unchanged by duplicate elimination — a
#: DISTINCT qualifier on them runs the plain accumulator
_DISTINCT_INVARIANT = {"min", "max", "bool_and", "bool_or", "every"}


class AggregateNode(PlanNode):
    def __init__(self, child: PlanNode, group_exprs: list[BoundExpr],
                 aggs: list[AggSpec], names: list[str] = None):
        self.child = child
        self.group_exprs = group_exprs
        self.aggs = aggs
        self._names = names

    # names/types derive from the LIVE agg list: ORDER BY / HAVING binding
    # may append aggregates after construction (ORDER BY sum(x) when
    # sum(x) is not in the select list), so a constructor-time snapshot
    # can go stale; explicit names are honored while they still match
    @property
    def names(self) -> list[str]:
        n = len(self.group_exprs) + len(self.aggs)
        if self._names is not None and len(self._names) == n:
            return self._names
        return [f"#g{k}" for k in range(len(self.group_exprs))] + \
               [f"#agg{k}" for k in range(len(self.aggs))]

    @property
    def types(self) -> list:
        return ([g.type for g in self.group_exprs] +
                [a.type for a in self.aggs])

    def children(self):
        return [self.child]

    def label(self):
        return (f"Aggregate groups={len(self.group_exprs)} "
                f"aggs=[{', '.join(a.func for a in self.aggs)}]")

    def batches(self, ctx):
        fast = self._try_count_fast_path(ctx)
        if fast is not None:
            yield fast
            return
        # fused relational pipeline first: Aggregate over an inner
        # equi-join of two (filtered) scans runs as ONE device dispatch
        # (exec/device_pipeline.py); single-table chains stay with
        # try_device_aggregate below
        from .device_pipeline import try_device_pipeline
        result = try_device_pipeline(self, ctx)
        if result is not None:
            yield result
            return
        from .device_agg import try_device_aggregate
        result = try_device_aggregate(self, ctx)
        if result is not None:
            yield result
            return
        # the device path declined the pipeline — morsel-parallel host
        # execution over the shared worker pool, serial oracle last
        from .morsel import try_parallel_aggregate
        result = try_parallel_aggregate(self, ctx)
        if result is not None:
            yield result
            return
        yield self._cpu_aggregate(ctx)

    def _try_count_fast_path(self, ctx):
        """count(*)-only over an index scan skips row materialization
        (reference: ScanMode::Count/CountFast,
        duckdb_search_full_scan.hpp:58-62). The scan node owns the
        counting semantics (count_matching) so they can never diverge
        from its row-returning path."""
        if self.group_exprs or not self.aggs or \
                any(s.func != "count_star" or s.filter is not None
                    for s in self.aggs):
            return None
        count_fn = getattr(self.child, "count_matching", None)
        if count_fn is None:
            return None
        n = count_fn()
        if n is None:
            return None
        return Batch(list(self.names),
                     [Column.from_pylist([n], s.type) for s in self.aggs])

    # -- CPU reference aggregation ----------------------------------------

    def _cpu_aggregate(self, ctx) -> Batch:
        if not self.group_exprs:
            return self._cpu_scalar_agg(ctx)
        full = concat_batches(list(self.child.batches(ctx)))
        from ..ops.agg import factorize_keys
        key_cols = [g.eval(full) for g in self.group_exprs]
        codes, uniq_vals, uniq_valid = factorize_keys(
            [c.data for c in key_cols],
            [c.validity for c in key_cols])
        num_groups = len(uniq_vals[0]) if uniq_vals else 0
        out_cols: list[Column] = []
        for k, (kc, uv) in enumerate(zip(key_cols, uniq_vals)):
            validity = uniq_valid[k] if uniq_valid.size else None
            if validity is not None and validity.all():
                validity = None
            out_cols.append(Column(kc.type, uv, validity, kc.dictionary))
        for spec in self.aggs:
            out_cols.append(self._cpu_group_agg(spec, full, codes, num_groups))
        return Batch(list(self.names), out_cols)

    def _cpu_group_agg(self, spec: AggSpec, full: Batch, codes: np.ndarray,
                       g: int) -> Column:
        if spec.filter is not None:
            c = spec.filter.eval(full)
            fm = c.data.astype(bool) & c.valid_mask()
            full = full.filter(fm)
            codes = codes[fm]
        if spec.func == "count_star":
            data = np.bincount(codes, minlength=g).astype(np.int64)
            return Column(dt.BIGINT, data)
        arg = spec.arg.eval(full)
        valid = arg.valid_mask()
        if spec.distinct:
            if spec.func in ("count", "sum", "avg"):
                return self._cpu_group_distinct(spec, arg, codes, g)
            if spec.func not in _DISTINCT_INVARIANT:
                # string_agg/array_agg/stddev & co. would need real dedup
                raise errors.unsupported(f"DISTINCT {spec.func}")
            # min/max/bool aggs are DISTINCT-invariant: run them plain
        vc = codes[valid]
        if spec.func == "count":
            data = np.bincount(vc, minlength=g).astype(np.int64)
            return Column(dt.BIGINT, data)
        vals = arg.data[valid]
        counts = np.bincount(vc, minlength=g)
        empty = counts == 0
        if spec.func == "sum":
            if arg.type.is_integer or arg.type.id is dt.TypeId.BOOL:
                data = np.bincount(vc, weights=vals.astype(np.float64),
                                   minlength=g)
                # exact: redo in int64 via add.at
                acc = np.zeros(g, dtype=np.int64)
                np.add.at(acc, vc, vals.astype(np.int64))
                return Column(dt.BIGINT, acc, ~empty if empty.any() else None)
            acc = np.zeros(g, dtype=np.float64)
            np.add.at(acc, vc, vals.astype(np.float64))
            return Column(dt.DOUBLE, acc, ~empty if empty.any() else None)
        if spec.func == "avg":
            acc = np.zeros(g, dtype=np.float64)
            np.add.at(acc, vc, vals.astype(np.float64))
            with np.errstate(invalid="ignore", divide="ignore"):
                data = acc / counts
            return Column(dt.DOUBLE, np.where(empty, 0.0, data),
                          ~empty if empty.any() else None)
        if spec.func in ("min", "max"):
            if arg.type.is_string:
                # operate on codes (sorted dictionary ⇒ order-preserving)
                ident = np.iinfo(np.int64).max if spec.func == "min" else -1
                acc = np.full(g, ident, dtype=np.int64)
                ufunc = np.minimum if spec.func == "min" else np.maximum
                ufunc.at(acc, vc, vals.astype(np.int64))
                acc2 = np.where(empty, 0, acc).astype(np.int32)
                return Column(dt.VARCHAR, acc2,
                              ~empty if empty.any() else None, arg.dictionary)
            if arg.type.is_float:
                ident = np.inf if spec.func == "min" else -np.inf
                acc = np.full(g, ident, dtype=np.float64)
            else:
                info = np.iinfo(np.int64)
                ident = info.max if spec.func == "min" else info.min
                acc = np.full(g, ident, dtype=np.int64)
            # PG float total order: NaN is the greatest — np.fmin skips
            # NaN for min; np.maximum propagates it for max
            if spec.func == "min":
                ufunc = np.fmin if arg.type.is_float else np.minimum
            else:
                ufunc = np.maximum
            with np.errstate(invalid="ignore"):   # NaN propagation is wanted
                ufunc.at(acc, vc, vals)
            if spec.func == "min" and arg.type.is_float:
                # all-NaN groups keep the identity: stamp them NaN
                # (~empty already says which groups have valid rows)
                has_non_nan = np.zeros(g, dtype=bool)
                np.logical_or.at(has_non_nan, vc, ~np.isnan(vals))
                acc = np.where(~empty & ~has_non_nan, np.nan, acc)
            acc = np.where(empty, 0, acc).astype(arg.type.np_dtype)
            return Column(arg.type, acc, ~empty if empty.any() else None)
        if spec.func in ("stddev", "stddev_samp", "var_samp", "variance",
                         "stddev_pop", "var_pop"):
            pop = spec.func.endswith("_pop")
            s1 = np.zeros(g)
            s2 = np.zeros(g)
            fv = vals.astype(np.float64)
            np.add.at(s1, vc, fv)
            np.add.at(s2, vc, fv * fv)
            cnt = counts.astype(np.float64)
            with np.errstate(invalid="ignore", divide="ignore"):
                var = (s2 - s1 * s1 / cnt) / (cnt if pop else cnt - 1)
            # float cancellation can drive the variance fractionally
            # negative (PG clamps to zero)
            var = np.maximum(var, 0.0)
            bad = counts < (1 if pop else 2)
            data = np.sqrt(var) if spec.func.startswith("stddev") else var
            return Column(dt.DOUBLE, np.where(bad, 0.0, data),
                          ~bad if bad.any() else None)
        if spec.func in ("bool_and", "bool_or"):
            vb = vals.astype(bool)
            if spec.func == "bool_and":
                acc = np.ones(g, dtype=bool)
                np.logical_and.at(acc, vc, vb)
            else:
                acc = np.zeros(g, dtype=bool)
                np.logical_or.at(acc, vc, vb)
            return Column(dt.BOOL, acc, ~empty if empty.any() else None)
        if spec.func in ("string_agg", "array_agg"):
            import json as _json
            vals_all = arg.to_pylist()
            row_order = range(len(codes))
            if spec.order_by:
                # aggregate ORDER BY: feed rows in key order (PG),
                # honoring NULLS FIRST/LAST (default: last asc, first
                # desc)
                keys = []
                for e, desc, nf in reversed(spec.order_by):
                    c = e.eval(full)
                    _, rk = np.unique(c.data, return_inverse=True)
                    rk = rk.astype(np.int64)
                    if desc:
                        rk = -rk
                    null_first = nf if nf is not None else desc
                    nulls = ~c.valid_mask()
                    keys.append(np.where(nulls, 0, rk))
                    keys.append(np.where(nulls,
                                         -1 if null_first else 1,
                                         1 if null_first else -1))
                row_order = np.lexsort(tuple(keys))
            groups: dict[int, list] = {}
            for i in row_order:
                code = codes[i]
                v = vals_all[i]
                if v is None:
                    continue
                groups.setdefault(int(code), []).append(v)
            out = []
            for gi in range(g):
                items = groups.get(gi)
                if items is None:
                    out.append(None)
                elif spec.func == "string_agg":
                    out.append((spec.sep or "").join(str(x) for x in items))
                else:
                    out.append(_json.dumps(items))
            return Column.from_pylist(out, dt.VARCHAR)
        raise errors.unsupported(f"aggregate {spec.func}")

    def _cpu_group_distinct(self, spec: AggSpec, arg: Column,
                            codes: np.ndarray, g: int) -> Column:
        valid = arg.valid_mask()
        vc = codes[valid]
        vals = arg.data[valid]
        if len(vc):
            order = np.lexsort((vals, vc))
            sc, sv = vc[order], vals[order]
            keep = np.concatenate([[True], (sc[1:] != sc[:-1]) | (sv[1:] != sv[:-1])])
            uc, uv = sc[keep], sv[keep]
        else:
            uc, uv = vc, vals
        if spec.func == "count":
            data = np.bincount(uc, minlength=g).astype(np.int64)
            return Column(dt.BIGINT, data)
        if spec.func in ("sum", "avg"):
            cnt = np.bincount(uc, minlength=g).astype(np.int64)
            empty = cnt == 0    # all-NULL group: SUM/AVG are NULL (PG)
            validity = ~empty if empty.any() else None
            if spec.func == "avg" or not arg.type.is_integer:
                acc = np.zeros(g, dtype=np.float64)
                np.add.at(acc, uc, uv.astype(np.float64))
                if spec.func == "avg":
                    with np.errstate(invalid="ignore", divide="ignore"):
                        acc = np.where(empty, 0.0, acc / np.maximum(cnt, 1))
                return Column(dt.DOUBLE, acc, validity)
            acc = np.zeros(g, dtype=np.int64)
            np.add.at(acc, uc, uv.astype(np.int64))
            return Column(dt.BIGINT, acc, validity)
        raise errors.unsupported(f"DISTINCT {spec.func}")

    def _cpu_scalar_agg(self, ctx) -> Batch:
        accs = [_ScalarAcc(spec) for spec in self.aggs]
        for b in self.child.batches(ctx):
            for acc in accs:
                acc.update(b)
        cols = [acc.result() for acc in accs]
        return Batch(list(self.names), cols)


class _ScalarAcc:
    def __init__(self, spec: AggSpec):
        self.spec = spec
        self.count = 0
        self.sum_i = 0
        self.sum_f = 0.0
        self.sum_sq = 0.0
        self.min_v = None
        self.max_v = None
        if spec.distinct and spec.func not in ("count", "sum", "avg") \
                and spec.func not in _DISTINCT_INVARIANT:
            raise errors.unsupported(f"DISTINCT {spec.func}")
        # min/max & friends are DISTINCT-invariant — no dedup set needed
        self.distinct: Optional[set] = set() \
            if spec.distinct and spec.func in ("count", "sum", "avg") \
            else None
        self.strings: list[str] = []
        self.bool_acc = None

    def update(self, b: Batch):
        spec = self.spec
        if spec.filter is not None:
            c = spec.filter.eval(b)
            b = b.filter(c.data.astype(bool) & c.valid_mask())
        if spec.func == "count_star":
            self.count += b.num_rows
            return
        col = spec.arg.eval(b)
        valid = col.valid_mask()
        n_valid = int(valid.sum())
        if n_valid == 0:
            return
        if self.distinct is not None:
            vals = col.to_pylist()
            self.distinct.update(v for v in vals if v is not None)
            return
        self.count += n_valid
        if spec.func in ("sum", "avg", "stddev", "stddev_samp", "var_samp",
                         "variance", "stddev_pop", "var_pop"):
            vals = col.data[valid]
            if col.type.is_integer or col.type.id is dt.TypeId.BOOL:
                self.sum_i += int(vals.astype(np.int64).sum())
            self.sum_f += float(vals.astype(np.float64).sum())
            self.sum_sq += float((vals.astype(np.float64) ** 2).sum())
        elif spec.func in ("min", "max"):
            if col.type.is_string:
                vals = [v for v in col.to_pylist() if v is not None]
                lo, hi = min(vals), max(vals)
                self.min_v = lo if self.min_v is None \
                    else min(self.min_v, lo)
                self.max_v = hi if self.max_v is None \
                    else max(self.max_v, hi)
            else:
                vals = col.data[valid]
                # PG float total order: NaN is the GREATEST value — max
                # returns NaN when any NaN exists, min skips NaN unless
                # every value is NaN
                if vals.dtype.kind == "f" and np.isnan(vals).any():
                    nn = vals[~np.isnan(vals)]
                    lo = nn.min() if len(nn) else np.nan
                    hi = np.nan
                else:
                    lo, hi = vals.min(), vals.max()
                self.min_v = lo if self.min_v is None \
                    else np.fmin(self.min_v, lo)
                # np.maximum propagates NaN — exactly PG's max
                self.max_v = hi if self.max_v is None \
                    else np.maximum(self.max_v, hi)
        elif spec.func in ("bool_and", "bool_or"):
            vals = col.data[valid].astype(bool)
            v = vals.all() if spec.func == "bool_and" else vals.any()
            if self.bool_acc is None:
                self.bool_acc = bool(v)
            else:
                self.bool_acc = (self.bool_acc and bool(v)) \
                    if spec.func == "bool_and" else (self.bool_acc or bool(v))
        elif spec.func in ("string_agg", "array_agg"):
            if spec.order_by:
                keycols = [(e.eval(b).to_pylist(), desc, nf)
                           for e, desc, nf in spec.order_by]
                for i, v in enumerate(col.to_pylist()):
                    if v is not None:
                        self.strings.append(
                            (tuple(_sort_key(kc[i], desc, nf)
                                   for kc, desc, nf in keycols), v))
            else:
                self.strings.extend(
                    v for v in col.to_pylist() if v is not None)
        elif spec.func == "count":
            pass
        else:
            raise errors.unsupported(f"aggregate {spec.func}")

    def result(self) -> Column:
        spec = self.spec
        t = spec.type
        if spec.func == "count_star":
            return Column.from_pylist([self.count], t)
        if self.distinct is not None:
            if spec.func == "count":
                return Column.from_pylist([len(self.distinct)], t)
            if spec.func == "sum":
                s = sum(self.distinct) if self.distinct else None
                return Column.from_pylist([s], t)
            if spec.func == "avg":
                a = (sum(self.distinct) / len(self.distinct)
                     if self.distinct else None)
                return Column.from_pylist([a], t)
            raise errors.unsupported(f"DISTINCT {spec.func}")
        if spec.func == "count":
            return Column.from_pylist([self.count], t)
        if self.count == 0 and spec.func != "count":
            return Column.from_pylist([None], t)
        if spec.func == "sum":
            v = self.sum_i if t.is_integer else self.sum_f
            return Column.from_pylist([v], t)
        if spec.func == "avg":
            return Column.from_pylist([self.sum_f / self.count], t)
        if spec.func == "min":
            v = self.min_v
            return Column.from_pylist([v.item() if hasattr(v, "item") else v], t)
        if spec.func == "max":
            v = self.max_v
            return Column.from_pylist([v.item() if hasattr(v, "item") else v], t)
        if spec.func in ("stddev", "stddev_samp", "var_samp", "variance",
                         "stddev_pop", "var_pop"):
            pop = spec.func.endswith("_pop")
            if self.count < (1 if pop else 2):
                return Column.from_pylist([None], t)
            var = max((self.sum_sq - self.sum_f ** 2 / self.count) /
                      (self.count if pop else self.count - 1), 0.0)
            v = math.sqrt(var) if spec.func.startswith("stddev") else var
            return Column.from_pylist([v], t)
        if spec.func in ("bool_and", "bool_or"):
            return Column.from_pylist([self.bool_acc], t)
        if spec.func in ("string_agg", "array_agg"):
            items = self.strings
            if spec.order_by and items:
                items = [v for _k, v in sorted(items, key=lambda p: p[0])]
            if spec.func == "string_agg":
                sep = spec.sep if spec.sep is not None else ""
                v = sep.join(str(x) for x in items) if items else None
                return Column.from_pylist([v], t)
            import json as _json
            v = _json.dumps(items) if items else None
            return Column.from_pylist([v], t)
        raise errors.unsupported(f"aggregate {spec.func}")
