"""Device-offloaded Scan→Filter→Aggregate (the flagship TPU path).

Mirrors the reference's hottest analytics loop (morsel-parallel filter +
hash aggregate over the columnstore; ClickBench shapes in BASELINE.md) as a
single jitted XLA program per (table, query) over HBM-cached columns:

    mask   = predicate(cols) & validity          (fused elementwise)
    counts = one-hot matmul / scatter over codes (ops/agg.py)
    sums   = exact int64 via limb scatter        (ops/agg.py)

Falls back to the CPU oracle (plan.AggregateNode._cpu_aggregate) whenever
anything in the query shape isn't device-compilable — result parity between
the two paths is asserted in tests.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.column import Batch, Column
from ..columnar.device import DeviceNarrowingError, pad_len
from ..ops import agg as ops_agg
from ..sql.binder import _expr_key
from ..sql.expr import AggSpec, BoundColumn, BoundExpr
from ..utils import log, metrics
from .device import DeviceExpr, NotCompilable, compile_expr
from .tables import TableProvider

MAX_GROUP_PRODUCT = 1 << 21   # combined-key code-space cap
MAX_INT_KEY_RANGE = 1 << 20   # direct-coding range cap for integer keys
MAX_DISTINCT_CELLS = 1 << 22  # (group_space x value_space) presence cap

import threading as _threading

_factorize_guard = _threading.Lock()


def _factorize_lock(provider) -> "_threading.Lock":
    """Per-provider lock guarding _factorize_cache (lazily attached)."""
    lk = getattr(provider, "_factorize_cache_lock", None)
    if lk is None:
        with _factorize_guard:
            lk = getattr(provider, "_factorize_cache_lock", None)
            if lk is None:
                lk = _threading.Lock()
                provider._factorize_cache_lock = lk
    return lk

_AGG_FUNCS = {"count_star", "count", "sum", "min", "max", "avg"}


def try_device_aggregate(node, ctx) -> Optional[Batch]:
    """Attempt device execution of an AggregateNode; None → CPU fallback."""
    from .plan import FilterNode, ScanNode

    device = ctx.settings.get("serene_device")
    if device == "cpu":
        return None
    # unwrap Filter(Scan) / Scan
    child = node.child
    preds: list[BoundExpr] = []
    while isinstance(child, FilterNode):
        preds.append(child.pred)
        child = child.child
    if not isinstance(child, ScanNode):
        return None
    scan = child
    if scan.filter is not None:
        preds.append(scan.filter)
    provider = scan.provider
    if device == "auto" and \
            provider.row_count() < ctx.settings.get("serene_device_min_rows"):
        return None
    for spec in node.aggs:
        if spec.func not in _AGG_FUNCS or spec.filter is not None:
            return None
        if spec.distinct and spec.func in ("count", "sum", "avg") and \
                not isinstance(spec.arg, BoundColumn):
            # DISTINCT runs as a (group, value)-presence scatter; value
            # coding needs a plain column (min/max ignore DISTINCT)
            return None
    try:
        prof = getattr(ctx, "profile", None)
        from ..obs.trace import current_trace
        trace = current_trace()
        # host-vs-device attribution: everything inside _run (upload,
        # compile-cache lookup, dispatch, readback) is device-path
        # time, stamped on the aggregate node the offload replaced.
        # The histogram observes UNCONDITIONALLY — the device latency
        # signal must not vanish when profiling/tracing are off (two
        # clock reads per ms-scale offload)
        import time as _time

        from ..utils import metrics as _metrics
        t0 = _time.perf_counter_ns()
        out = _run(node, scan, provider, preds, ctx)
        t1 = _time.perf_counter_ns()
        if prof is not None:
            prof.add_device_ns(id(node), t1 - t0)
        _metrics.DEVICE_DISPATCH_HIST.observe_ns(t1 - t0)
        if trace is not None:
            trace.add("device_dispatch", "device", t0, t1, op="agg")
        return out
    except (NotCompilable, DeviceNarrowingError) as e:
        log.debug("device", f"aggregate fell back to CPU: {e}")
        return None


def _run(node, scan, provider: TableProvider, preds: list[BoundExpr], ctx) -> Batch:
    col_names = scan.columns

    # ONE publication observation for the WHOLE query: dictionaries, key
    # planning, factorized codes, the device column environment and the
    # row mask must all come from the same (batch, version) — per-column
    # fetches could straddle a concurrent publish and hand the device
    # program columns of different lengths/row orders. Immutable
    # providers (parquet) pin nothing and read per column lazily.
    pin = provider.try_pin()
    pin_batch = pin[0] if pin is not None else None
    dev_ver = pin[1] if pin is not None else provider.data_version

    def host_col(name):
        if pin_batch is not None:
            return pin_batch.column(name)
        return provider.host_column(name)

    # only referenced string columns need their dictionary materialized
    referenced: set[int] = set()
    for e in preds + list(node.group_exprs) + \
            [s.arg for s in node.aggs if s.arg is not None]:
        for sub in e.walk():
            if isinstance(sub, BoundColumn):
                referenced.add(sub.index)
    dictionaries: dict[int, np.ndarray] = {}
    for i in sorted(referenced):
        if scan.types[i].is_string:
            col = host_col(col_names[i])
            if col.dictionary is not None:
                dictionaries[i] = col.dictionary

    compiled_preds = [compile_expr(p, scan.types, dictionaries) for p in preds]

    # group keys: direct coding (dict codes / small-range ints) when it
    # fits, else composite host factorization (arbitrary keys/cardinality)
    fact = None
    try:
        key_plans, group_space = _plan_direct_keys(
            node, scan, host_col, col_names, dictionaries)
    except NotCompilable:
        if not node.group_exprs:
            raise
        fact = _factorize_group_keys(node, scan, provider, pin_batch,
                                     dev_ver)
        key_plans, group_space = [], max(fact["g"], 1)

    agg_plans = []
    for spec in node.aggs:
        if spec.func == "count_star":
            agg_plans.append((spec, None))
        else:
            if spec.arg.type.is_string and spec.func != "count":
                raise NotCompilable(f"{spec.func} over strings")
            agg_plans.append((spec, compile_expr(spec.arg, scan.types,
                                                 dictionaries)))

    # DISTINCT value plans: each count/sum/avg DISTINCT column gets a
    # direct value coding (dict codes / small-range ints); the program
    # scatters a (group, value) presence matrix and shards combine it
    # with max (reference analog: DuckDB's distinct hash aggregate —
    # re-expressed as a dense presence bitmap so the per-row work is one
    # scatter on the device and the cross-shard merge one pmax)
    distinct_plans: dict[int, tuple] = {}
    for si, (spec, ce) in enumerate(agg_plans):
        if not (spec.distinct and spec.func in ("count", "sum", "avg")):
            continue
        vi = spec.arg.index
        vt = scan.types[vi]
        if vt.is_string:
            d = dictionaries.get(vi)
            if d is None:
                raise NotCompilable("DISTINCT string without dictionary")
            distinct_plans[si] = ("dict", vi, 0, len(d) + 1)
        elif vt.is_integer or vt.id in (dt.TypeId.BOOL, dt.TypeId.DATE):
            col = host_col(col_names[vi])
            if col.data.size == 0:
                lo, hi = 0, 0
            else:
                lo, hi = int(col.data.min()), int(col.data.max())
            rng = hi - lo + 1
            if rng > MAX_INT_KEY_RANGE:
                raise NotCompilable("DISTINCT value range too large")
            if not (-2**31 <= lo and hi < 2**31):
                raise NotCompilable("DISTINCT value offset beyond int32")
            distinct_plans[si] = ("int", vi, lo, rng + 1)
        else:
            raise NotCompilable(f"DISTINCT over {vt}")
    for si in distinct_plans:
        if max(group_space, 1) * distinct_plans[si][3] > MAX_DISTINCT_CELLS:
            raise NotCompilable("DISTINCT presence matrix too large")

    # zone maps: when the filter conjuncts prove a prefix/suffix of
    # morsel blocks can't match, upload (and aggregate) only the
    # surviving contiguous row range — the skip-scan analog of the
    # chunked dispatch, applied to the transfer itself. The factorized
    # code buffer is whole-table, so the shrink only engages on directly
    # coded keys.
    nrows = pin_batch.num_rows if pin_batch is not None \
        else provider.row_count()
    zrange = None
    if preds and fact is None:
        zrange = _zonemap_range(scan, provider, preds, pin, nrows, ctx)

    # collect needed device columns
    needed: set[int] = set()
    for ce in compiled_preds:
        needed.update(ce.inputs)
    for kp in key_plans:
        needed.add(kp[1])
    for spec, ce in agg_plans:
        if ce is not None:
            needed.update(ce.inputs)
    needed = sorted(needed)
    if zrange is None:
        by_name = provider.device_columns([col_names[i] for i in needed],
                                          pin)
    else:
        by_name = _range_device_columns(
            provider, [col_names[i] for i in needed], pin, zrange)
    env_cols = {i: by_name[col_names[i]] for i in needed}
    metrics.DEVICE_OFFLOADS.add()

    import jax.numpy as jnp

    def env_for(ce: DeviceExpr, arrays):
        return [arrays[i] for i in ce.inputs]

    group_mode = bool(node.group_exprs)
    # capture only the flag, not the fact dict — the closure lives in the
    # program cache and must not pin the codes buffer in HBM
    has_fact = fact is not None

    # frame-of-reference columns decode in-kernel right at program entry
    # (one widen+add), so every downstream op sees logical int32 values
    decode_specs = [(env_cols[i].scheme, env_cols[i].offset)
                    for i in needed]

    def program(*flat):
        arrays = {}
        for k, i in enumerate(needed):
            data = flat[2 * k]
            scheme, off = decode_specs[k]
            if scheme != "raw":
                data = data.astype(jnp.int32) + jnp.int32(off)
            arrays[i] = (data, flat[2 * k + 1])
        rowmask = flat[-1]
        mask = rowmask
        for ce in compiled_preds:
            v, ok = ce.fn(env_for(ce, arrays))
            b = v if v.dtype == jnp.bool_ else (v != 0)
            mask = jnp.logical_and(mask, jnp.logical_and(b, ok))
        outputs = []
        if group_mode:
            if has_fact:
                codes = flat[2 * len(needed)]  # precomputed composite codes
            else:
                codes = jnp.zeros_like(mask, dtype=jnp.int32)
                for kind, idx, lo, size in key_plans:
                    data, ok = arrays[idx]
                    if kind == "dict":
                        c = data.astype(jnp.int32)
                    else:
                        c = (data.astype(jnp.int32) - jnp.int32(lo))
                    c = jnp.where(ok, c, jnp.int32(size - 1))
                    codes = codes * jnp.int32(size) + jnp.clip(c, 0, size - 1)
            outputs.append(
                ops_agg.group_count_scatter(codes, mask, group_space))
            for si, (spec, ce) in enumerate(agg_plans):
                if si in distinct_plans:
                    outputs.append(_presence_scatter(
                        distinct_plans[si], arrays, codes, mask,
                        group_space))
                else:
                    outputs.extend(
                        _group_agg_device(spec, ce, arrays, codes, mask,
                                          env_for, group_space))
        else:
            outputs.append(jnp.sum(mask, dtype=jnp.int32))
            for si, (spec, ce) in enumerate(agg_plans):
                if si in distinct_plans:
                    zc = jnp.zeros_like(mask, dtype=jnp.int32)
                    outputs.append(_presence_scatter(
                        distinct_plans[si], arrays, zc, mask, 1))
                else:
                    outputs.extend(
                        _scalar_agg_device(spec, ce, arrays, mask,
                                           env_for))
        return tuple(outputs)

    mesh_n = int(ctx.settings.get("serene_mesh") or 0)
    if mesh_n > 1 and len(jax.devices()) < mesh_n:
        mesh_n = 0
    # zrange is part of the key: the frame-of-reference scheme/offset of a
    # sliced upload differs from the whole column's, and the range itself
    # flips with SET serene_zonemap — a cached program must never decode
    # an environment built under the other setting
    key = (id(provider), dev_ver,
           tuple(_expr_key(p) for p in preds),
           tuple(_expr_key(g) for g in node.group_exprs),
           tuple((s.func, s.distinct, _expr_key(s.arg))
                 for s in node.aggs), mesh_n, zrange)
    from ..obs import device as obs_device

    def build():
        if mesh_n > 1:
            combines = _out_combines(node, agg_plans, group_mode)
            return _mesh_wrap(program, mesh_n, combines,
                              n_inputs=2 * len(needed) +
                              (1 if fact is not None else 0) + 1)
        return program

    jitted = obs_device.compiled("device_agg", key, build,
                                 profile=getattr(ctx, "profile", None),
                                 node_key=id(node))

    flat_args = []
    for i in needed:
        dc = env_cols[i]
        flat_args.extend([dc.data, dc.mask])
    if fact is not None:
        flat_args.append(fact["codes2d"])
    if mesh_n > 1:
        flat_args = [_pad_shard_axis(a, mesh_n) for a in flat_args]
    # A column's device mask excludes padding but ALSO that column's NULLs —
    # wrong as a row mask for count(*). Use a pure row-validity mask built
    # from the logical length of the SAME publication as the columns
    # (cached per version on the provider).
    mask_rows = nrows if zrange is None else zrange[1] - zrange[0]
    prows = pad_len(mask_rows)
    rm_entry = getattr(provider, "_device_rowmask", None)
    if rm_entry is None or rm_entry[0] != (dev_ver, zrange) or \
            rm_entry[1].shape != (prows // 128, 128):
        rm = np.zeros(prows, dtype=bool)
        rm[:mask_rows] = True
        rowmask_arr = jnp.asarray(rm.reshape(-1, 128))
        provider._device_rowmask = ((dev_ver, zrange), rowmask_arr)
    else:
        rowmask_arr = rm_entry[1]
    if mesh_n > 1:
        rowmask_arr = _pad_shard_axis(rowmask_arr, mesh_n)
    chunk_rows = int(ctx.settings.get("serene_device_chunk_rows") or 0)
    # clamp to one tile: tiny values must mean "maximum responsiveness",
    # never silently disable chunking
    chunk_tiles = max(1, chunk_rows // 128) if chunk_rows > 0 else 0
    n_tiles = int(rowmask_arr.shape[0])
    if chunk_tiles and n_tiles > chunk_tiles:
        # chunked dispatch: cancel/statement_timeout can fire between
        # chunks instead of waiting out one monolithic program
        # (reference: the session interrupt check inside execution
        # tasks, pg_wire_session.h:205-220)
        if mesh_n > 1:
            chunk_tiles += (-chunk_tiles) % mesh_n
        combines = _out_combines(node, agg_plans, group_mode)
        results = _chunked_dispatch(jitted, flat_args, rowmask_arr,
                                    chunk_tiles, combines, mesh_n)
    else:
        results = obs_device.fetch_all(jitted(*flat_args, rowmask_arr))

    if group_mode:
        return _build_group_batch(node, key_plans, agg_plans, results,
                                  provider, col_names, dictionaries,
                                  group_space, fact, distinct_plans)
    return _build_scalar_batch(node, agg_plans, results, distinct_plans)


def _zonemap_range(scan, provider, preds, pin, nrows,
                   ctx) -> Optional[tuple[int, int]]:
    """Contiguous surviving row range [lo, hi) under the filter
    conjuncts' zone-map verdicts, or None when nothing prunes. Raises
    NotCompilable when EVERY block is pruned — the morsel path then
    resolves the query from the same verdicts without touching data.
    lo is block-aligned and therefore a multiple of the 128-lane tile."""
    from . import zonemap
    block_rows = int(ctx.settings.get("serene_morsel_rows"))
    verdicts = zonemap.block_verdicts(provider, ctx.settings, preds,
                                      scan.columns, block_rows, pin)
    if verdicts is None:
        return None
    lo, hi = zonemap.surviving_range(verdicts, block_rows, nrows)
    if hi <= lo:
        # don't touch the counters here: the host morsel path resolves
        # the query from the same verdict vector and does the counting
        raise NotCompilable("zone maps pruned every block")
    if (lo, hi) == (0, nrows):
        return None
    # only the envelope shrink is real pruning on the device path —
    # interior SKIP blocks inside [lo, hi) still upload and scan
    n_blocks = len(verdicts)
    lo_b, hi_b = lo // block_rows, (hi + block_rows - 1) // block_rows
    metrics.ZONEMAP_PRUNED.add(n_blocks - (hi_b - lo_b))
    metrics.ZONEMAP_SCANNED.add(hi_b - lo_b)
    if zonemap.verify_enabled(ctx.settings):
        full = pin[0] if pin is not None else \
            provider.full_batch(scan.columns)
        from ..columnar.column import Batch as _B
        full = _B(list(scan.columns),
                  [full.column(c) for c in scan.columns])
        spans = [(s, e) for s, e in ((0, lo), (hi, nrows)) if e > s]
        zonemap.verify_pruned_blocks(preds, full, spans,
                                     f"device aggregate {provider.name}")
    return lo, hi


def _range_device_columns(provider, names, pin, zrange) -> dict:
    """{name: DeviceColumn} for a row subrange, one publication
    observation (mirrors TableProvider.device_columns). Cached per
    (version, range) with one entry per column — repeated queries with
    the same shape reuse the upload, a different range rebuilds."""
    from . import zonemap as _zm
    from ..columnar.device import to_device_column
    lo, hi = zrange
    lock = _zm._zone_lock(provider)
    if pin is not None:
        batch, ver = pin[0], pin[1]
    else:
        batch, ver = None, provider.data_version
    with lock:
        cache = getattr(provider, "_zonemap_devcache", None)
        if cache is None:
            cache = provider._zonemap_devcache = {}
        hits = {n: e[1] for n in names
                if (e := cache.get(n)) is not None and e[0] == (ver, lo, hi)}
    out = dict(hits)
    # uploads run OUTSIDE the lock: a multi-hundred-MB host→device copy
    # must not serialize every other query's zone-stats access on this
    # provider (a racing duplicate upload is wasted work, never wrong —
    # entries are (version, range)-stamped either way)
    for name in names:
        if name in out:
            continue
        col = (batch.column(name) if batch is not None
               else provider.full_batch([name]).column(name))
        dc = to_device_column(col.slice(lo, hi))
        metrics.DEVICE_BYTES.add(
            int(dc.data.size * dc.data.dtype.itemsize))
        with lock:
            cache[name] = ((ver, lo, hi), dc)
        out[name] = dc
    return out


def _presence_scatter(dplan, arrays, gcodes, mask, group_space):
    """(group, value) presence matrix for one DISTINCT aggregate: int32
    0/1 cells, scatter-max over the coded pairs. NULL values contribute 0
    (their row mask is False), so no cell lights up for them."""
    import jax.numpy as jnp
    kind, vi, lo, vsize = dplan
    data, ok = arrays[vi]
    vc = data.astype(jnp.int32)
    if kind == "int":
        vc = vc - jnp.int32(lo)
    vc = jnp.clip(vc, 0, vsize - 1)
    m = jnp.logical_and(mask, ok)
    pair = (gcodes * jnp.int32(vsize) + vc).ravel()
    pres = jnp.zeros((group_space * vsize,), jnp.int32)
    pres = pres.at[pair].max(m.ravel().astype(jnp.int32))
    return pres.reshape(group_space, vsize)


def _out_combines(node, agg_plans, group_mode) -> list:
    """Per-output cross-shard combine kinds for the mesh wrap, mirroring
    the output order of `program`: 'sum' → psum (counts, float sums, the
    additive int limb arrays), 'min'/'max' → pmin/pmax, 'rows' → per-row
    partials that stay sharded (concatenated by the out_spec; the host
    combiner sums over rows, and zero-padded rows contribute nothing)."""
    out = ["sum"]        # group counts / scalar row count
    for spec, ce in agg_plans:
        if spec.func == "count_star":
            continue
        if spec.distinct and spec.func in ("count", "sum", "avg"):
            out.append("max")    # presence matrix: cross-shard union
            continue
        if spec.func == "count":
            out.append("sum")
            continue
        is_float = spec.arg is not None and spec.arg.type.is_float
        if spec.func in ("sum", "avg"):
            if group_mode or is_float:
                out.extend(["sum", "sum"])      # (limbs|float sum) + count
            else:
                out.extend(["rows", "sum"])     # per-row int partials
        elif spec.func in ("min", "max"):
            out.extend([spec.func, "sum"])
        else:
            raise NotCompilable(f"mesh combine for {spec.func}")
    return out


def _pad_shard_axis(arr, mesh_n: int):
    from ..parallel.mesh import pad_to_multiple
    return pad_to_multiple(arr, mesh_n)


def _chunked_dispatch(jitted, flat_args, rowmask_arr, chunk_tiles: int,
                      combines: list, mesh_n: int):
    """Run the aggregate program chunk by chunk over the row-block axis,
    combining per-output partials on host ('sum' adds exactly in
    int64/float64, 'min'/'max' fold elementwise, 'rows' concatenates).
    check_cancel() runs between dispatches, so a cancel or a statement
    timeout interrupts a long aggregate within one chunk's latency. All
    chunks share one compiled shape (the tail pads with empty rows)."""
    from .plan import check_cancel
    import jax.numpy as jnp

    from ..obs import device as obs_device
    n_tiles = int(rowmask_arr.shape[0])
    acc = None
    for start in range(0, n_tiles, chunk_tiles):
        check_cancel()
        end = min(start + chunk_tiles, n_tiles)

        def cut(a):
            part = a[start:end]
            if end - start < chunk_tiles:
                pad = chunk_tiles - (end - start)
                widths = [(0, pad)] + [(0, 0)] * (part.ndim - 1)
                part = jnp.pad(part, widths)
            return part

        outs = obs_device.fetch_all(
            jitted(*[cut(a) for a in flat_args], cut(rowmask_arr)))
        def widen(o, c):
            if c != "sum":
                return o
            # chunk-size-stable host accumulation: ints widen to int64,
            # floats to float64
            return o.astype(np.int64 if o.dtype.kind in "iu"
                            else np.float64)

        if acc is None:
            acc = [widen(o, c) for o, c in zip(outs, combines)]
            continue
        for k, (o, c) in enumerate(zip(outs, combines)):
            if c == "sum":
                acc[k] = acc[k] + widen(o, c)
            elif c == "min":
                acc[k] = np.minimum(acc[k], o)
            elif c == "max":
                acc[k] = np.maximum(acc[k], o)
            else:   # per-row partials: stack chunks back together
                acc[k] = np.concatenate([acc[k], o])
    return tuple(acc)


def _mesh_wrap(program, mesh_n: int, combines: list, n_inputs: int):
    """shard_map the single-device aggregate program over an N-device
    mesh: row-block inputs shard on the leading axis, reductions merge
    with psum/pmin/pmax over ICI, per-row partial outputs stay sharded
    (reference analog: morsel-parallel pipelines re-expressed as XLA
    collectives — SURVEY.md §2.11/§5.7). Returns the un-jitted wrapped
    callable — the obs/device compile ledger owns the jit."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import AXIS, apply_axis_combines, data_mesh
    mesh = data_mesh(mesh_n)

    def core(*flat):
        return apply_axis_combines(program(*flat), combines)

    in_specs = tuple(P(AXIS, None) for _ in range(n_inputs))
    out_specs = tuple(P() if c in ("sum", "min", "max")
                      else P(AXIS, None) for c in combines)
    return shard_map(core, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs)


def _plan_direct_keys(node, scan, host_col, col_names, dictionaries):
    """Direct group-key coding: dictionary codes / small-range integers.
    Raises NotCompilable when any key needs factorization. host_col reads
    from the query's pinned publication."""
    key_plans = []
    group_space = 1
    for g in node.group_exprs:
        if not isinstance(g, BoundColumn):
            raise NotCompilable("group key is not a plain column")
        t = scan.types[g.index]
        if t.is_string:
            d = dictionaries.get(g.index)
            if d is None:
                raise NotCompilable("string key without dictionary")
            size = len(d) + 1      # +1: NULL group
            key_plans.append(("dict", g.index, 0, size))
        elif t.is_integer or t.id in (dt.TypeId.BOOL, dt.TypeId.DATE):
            col = host_col(col_names[g.index])
            if col.data.size == 0:
                lo, hi = 0, 0
            else:
                lo, hi = int(col.data.min()), int(col.data.max())
            rng = hi - lo + 1
            if rng > MAX_INT_KEY_RANGE:
                raise NotCompilable("integer key range too large for direct coding")
            if not (-2**31 <= lo and hi < 2**31):
                # small range but offset beyond int32 (snowflake-style ids):
                # the raw column can't upload exactly — factorize instead
                raise NotCompilable("integer key offset beyond int32")
            size = rng + 1
            key_plans.append(("int", g.index, lo, size))
        else:
            raise NotCompilable(f"group key type {t}")
        group_space *= size
        if group_space > MAX_GROUP_PRODUCT:
            raise NotCompilable("group code space too large")
    return key_plans, group_space


def _factorize_group_keys(node, scan, provider, pin_batch, dev_ver) -> dict:
    """Composite host factorization of arbitrary GROUP BY keys: evaluate
    the key expressions over the host columns, build dense codes with
    ops_agg.factorize_keys (NULLs group per PG semantics), upload the
    codes as device tiles. Cached per (data_version, key exprs) — the
    factorize pass is O(n log n) once, amortized across queries.

    Reference analog: DuckDB's RadixPartitionedHashTable grouped
    aggregate (SURVEY.md §1 L3) — re-expressed as host factorize +
    device scatter so the hot per-row work stays on the TPU."""
    import jax.numpy as jnp

    ekeys = tuple(_expr_key(g) for g in node.group_exprs)
    # version + batch are ONE observation (passed in from the query's
    # pin): codes factorized over batch N+1 must never cache under N
    ver = dev_ver
    lock = _factorize_lock(provider)
    with lock:
        # readers are lock-free and concurrent: all cache scans and
        # mutations go through this per-provider lock (two concurrent
        # GROUP BYs after an UPDATE would otherwise race the stale purge)
        cache = getattr(provider, "_factorize_cache", None)
        if cache is None:
            cache = provider._factorize_cache = {}
        stale = [k2 for k2 in cache if k2[0] != ver]
        for k2 in stale:  # old data versions can never be read again
            del cache[k2]
        hit = cache.get((ver, ekeys))
    if hit is not None:
        return hit
    if pin_batch is not None:
        full = Batch(list(scan.columns),
                     [pin_batch.column(c) for c in scan.columns])
    else:
        full = provider.full_batch(scan.columns)
    try:
        key_cols = [g.eval(full) for g in node.group_exprs]
    except Exception as e:
        # the CPU path evaluates keys only over WHERE-surviving rows; an
        # eval error on a filtered-out row (e.g. division by zero) must
        # fall back, not surface
        raise NotCompilable(f"group key eval over unfiltered rows: {e}")
    # shared with the host morsel sink: direct (perfect-hash) coding for
    # small int/dict key spaces — no composite sort — with the factorize
    # fallback for arbitrary keys; group order is identical either way
    from .morsel import _group_codes
    codes, uniq_vals, uniq_valid, g_count = _group_codes(key_cols)
    if g_count > MAX_GROUP_PRODUCT:
        raise NotCompilable(
            f"{g_count} distinct groups exceeds the device code-space cap")
    n_pad = pad_len(len(codes))
    padded = np.zeros(n_pad, dtype=np.int32)
    padded[:len(codes)] = codes
    value = {
        "codes2d": jnp.asarray(padded.reshape(-1, 128)),
        "uniq_vals": uniq_vals,
        "uniq_valid": uniq_valid,
        "g": g_count,
        "key_meta": [(c.type, c.dictionary) for c in key_cols],
    }
    with lock:
        if len(cache) >= 16:  # bound HBM held by codes buffers
            cache.pop(next(iter(cache)))
        cache[(ver, ekeys)] = value
    return value


def _scalar_agg_device(spec: AggSpec, ce, arrays, mask, env_for):
    import jax.numpy as jnp
    if spec.func == "count_star":
        return []  # uses the shared row count output
    v, ok = ce.fn(env_for(ce, arrays))
    m = jnp.logical_and(mask, ok)
    if spec.func == "count":
        return [jnp.sum(m, dtype=jnp.int32)]
    is_float = jnp.issubdtype(v.dtype, jnp.floating)
    if spec.func in ("sum", "avg"):
        cnt = jnp.sum(m, dtype=jnp.int32)
        if is_float:
            s = jnp.sum(jnp.where(m, v, 0.0).astype(jnp.float32))
            return [s, cnt]
        return [ops_agg.masked_sum_int_partials(v, m), cnt]
    if spec.func in ("min", "max"):
        if is_float:
            ident = jnp.inf if spec.func == "min" else -jnp.inf
        else:
            info = jnp.iinfo(v.dtype)
            ident = info.max if spec.func == "min" else info.min
        cnt = jnp.sum(m, dtype=jnp.int32)
        if is_float and spec.func == "min":
            m_nn = jnp.logical_and(m, jnp.logical_not(jnp.isnan(v)))
            red = jnp.min(jnp.where(m_nn, v, ident))
            red = jnp.where(jnp.logical_and(
                cnt > 0, jnp.sum(m_nn, dtype=jnp.int32) == 0),
                jnp.nan, red)
            return [red, cnt]
        vv = jnp.where(m, v, ident)
        red = jnp.min(vv) if spec.func == "min" else jnp.max(vv)
        return [red, cnt]
    raise NotCompilable(spec.func)


def _group_agg_device(spec: AggSpec, ce, arrays, codes, mask, env_for, g):
    import jax.numpy as jnp
    if spec.func == "count_star":
        return []  # shared group counts output
    v, ok = ce.fn(env_for(ce, arrays))
    m = jnp.logical_and(mask, ok)
    if spec.func == "count":
        return [ops_agg.group_count_scatter(codes, m, g)]
    is_float = jnp.issubdtype(v.dtype, jnp.floating)
    if spec.func in ("sum", "avg"):
        cnt = ops_agg.group_count_scatter(codes, m, g)
        if is_float:
            return [ops_agg.group_sum_float(codes, m, v, g), cnt]
        if codes.shape[0] > ops_agg.SCATTER_CHUNK_TILES:
            return [ops_agg.group_sum_int_limbs_chunked(codes, m, v, g), cnt]
        return [ops_agg.group_sum_int_limbs(codes, m, v, g), cnt]
    if spec.func in ("min", "max"):
        if is_float and spec.func == "min":
            # PG: NaN is the greatest float — MIN skips NaN unless a
            # group is ALL NaN (then it IS NaN). Counts keep the
            # original mask so NULL detection is untouched. (Under the
            # mesh, a group all-NaN on one shard only is a known edge.)
            counts = ops_agg.group_count_scatter(codes, m, g)
            m_nn = jnp.logical_and(m, jnp.logical_not(jnp.isnan(v)))
            nonnan = ops_agg.group_count_scatter(codes, m_nn, g)
            red = ops_agg.group_min_max(codes, m_nn, v, g, "min")
            red = jnp.where(jnp.logical_and(counts > 0, nonnan == 0),
                            jnp.nan, red)
            return [red, counts]
        return [ops_agg.group_min_max(codes, m, v, g, spec.func),
                ops_agg.group_count_scatter(codes, m, g)]
    raise NotCompilable(spec.func)


def _build_scalar_batch(node, agg_plans, results,
                        distinct_plans=None) -> Batch:
    ri = iter(results)
    total = int(np.asarray(next(ri)))
    cols = []
    for si, (spec, ce) in enumerate(agg_plans):
        dplan = (distinct_plans or {}).get(si)
        if dplan is not None:
            pres = np.asarray(next(ri)).reshape(1, -1)
            cols.append(_distinct_result_col(spec, dplan, pres,
                                             np.asarray([0]))[0])
        else:
            cols.append(_scalar_result_col(spec, ri, total))
    return Batch(list(node.names), cols)


def _distinct_result_col(spec: AggSpec, dplan, pres: np.ndarray,
                         present: np.ndarray):
    """Presence matrix -> one result column, rows selected by `present`.
    Returns a 1-element list for uniform use."""
    kind, vi, lo, vsize = dplan
    sub = pres[present].astype(np.int64)
    cnt = sub.sum(axis=1)
    if spec.func == "count":
        return [Column(dt.BIGINT, cnt)]
    vals = (lo + np.arange(vsize, dtype=np.int64))
    sums = sub @ vals
    empty = cnt == 0
    if spec.func == "avg":
        with np.errstate(invalid="ignore", divide="ignore"):
            data = np.where(empty, 0.0, sums / np.maximum(cnt, 1))
        return [Column(dt.DOUBLE, data, ~empty if empty.any() else None)]
    t = spec.type
    if t.is_integer:
        return [Column(dt.BIGINT, sums,
                       ~empty if empty.any() else None)]
    return [Column(dt.DOUBLE, sums.astype(np.float64),
                   ~empty if empty.any() else None)]


def _scalar_result_col(spec: AggSpec, ri, total: int) -> Column:
    t = spec.type
    if spec.func == "count_star":
        return Column.from_pylist([total], t)
    if spec.func == "count":
        return Column.from_pylist([int(np.asarray(next(ri)))], t)
    if spec.func in ("sum", "avg"):
        first = np.asarray(next(ri))
        cnt = int(np.asarray(next(ri)))
        if first.ndim == 0:
            s = float(first)
        else:
            parts = first.astype(np.int64)
            s = int((parts[:, 0].sum() << 16) + parts[:, 1].sum())
        if cnt == 0:
            return Column.from_pylist([None], t)
        if spec.func == "avg":
            return Column.from_pylist([s / cnt], t)
        return Column.from_pylist([s if t.is_integer else float(s)], t)
    if spec.func in ("min", "max"):
        v = np.asarray(next(ri))
        cnt = int(np.asarray(next(ri)))
        if cnt == 0:
            return Column.from_pylist([None], t)
        out = v.item()
        if t.is_integer:
            out = int(out)
        return Column.from_pylist([out], t)
    raise NotCompilable(spec.func)


def _build_group_batch(node, key_plans, agg_plans, results, provider,
                       col_names, dictionaries, g, fact=None,
                       distinct_plans=None) -> Batch:
    ri = iter(results)
    counts = np.asarray(next(ri)).astype(np.int64)
    present = np.flatnonzero(counts > 0)
    cols: list[Column] = []
    if fact is not None:
        for k2, (t, d) in enumerate(fact["key_meta"]):
            uv = np.asarray(fact["uniq_vals"][k2])[present]
            validity = fact["uniq_valid"][k2][present] \
                if fact["uniq_valid"].size else None
            if validity is not None and validity.all():
                validity = None
            cols.append(Column(t, uv, validity, d))
        for si, (spec, ce) in enumerate(agg_plans):
            dplan = (distinct_plans or {}).get(si)
            if dplan is not None:
                pres = np.asarray(next(ri))
                cols.extend(_distinct_result_col(spec, dplan, pres,
                                                 present))
            else:
                cols.append(_group_result_col(spec, ri, counts, present))
        return Batch(list(node.names), cols)
    # decode combined codes back to per-key codes
    sizes = [kp[3] for kp in key_plans]
    rem = present.copy()
    key_codes = []
    for size in reversed(sizes):
        key_codes.append(rem % size)
        rem //= size
    key_codes.reverse()
    for (kind, idx, lo, size), kc in zip(key_plans, key_codes):
        null_mask = kc == (size - 1)
        t = provider.type_of(col_names[idx])
        if kind == "dict":
            d = dictionaries[idx]
            data = np.where(null_mask, 0, kc).astype(np.int32)
            cols.append(Column(t, data,
                               ~null_mask if null_mask.any() else None, d))
        else:
            data = (kc + lo).astype(t.np_dtype)
            data = np.where(null_mask, 0, data).astype(t.np_dtype)
            cols.append(Column(t, data,
                               ~null_mask if null_mask.any() else None))
    for si, (spec, ce) in enumerate(agg_plans):
        dplan = (distinct_plans or {}).get(si)
        if dplan is not None:
            pres = np.asarray(next(ri))
            cols.extend(_distinct_result_col(spec, dplan, pres, present))
        else:
            cols.append(_group_result_col(spec, ri, counts, present))
    return Batch(list(node.names), cols)


def _group_result_col(spec: AggSpec, ri, star_counts, present) -> Column:
    t = spec.type
    if spec.func == "count_star":
        return Column(dt.BIGINT, star_counts[present])
    if spec.func == "count":
        c = np.asarray(next(ri)).astype(np.int64)
        return Column(dt.BIGINT, c[present])
    if spec.func in ("sum", "avg"):
        first = np.asarray(next(ri))
        cnt = np.asarray(next(ri)).astype(np.int64)[present]
        if first.ndim >= 2:  # int limbs (G,5) or chunked (C,G,5)
            sums = ops_agg.combine_sum_int_limbs(first)[present]
        else:
            sums = first.astype(np.float64)[present]
        empty = cnt == 0
        if spec.func == "avg":
            with np.errstate(invalid="ignore", divide="ignore"):
                data = np.where(empty, 0.0, sums / np.maximum(cnt, 1))
            return Column(dt.DOUBLE, data, ~empty if empty.any() else None)
        if t.is_integer:
            return Column(dt.BIGINT, sums.astype(np.int64),
                          ~empty if empty.any() else None)
        return Column(dt.DOUBLE, sums.astype(np.float64),
                      ~empty if empty.any() else None)
    if spec.func in ("min", "max"):
        v = np.asarray(next(ri))[present]
        cnt = np.asarray(next(ri)).astype(np.int64)[present]
        empty = cnt == 0
        data = np.where(empty, 0, v).astype(t.np_dtype)
        return Column(t, data, ~empty if empty.any() else None)
    raise NotCompilable(spec.func)
