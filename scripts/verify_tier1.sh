#!/usr/bin/env bash
# Tier-1 verification (ROADMAP.md) plus structural/parity passes.
#
# Pass 1 is the canonical tier-1 suite. Pass 2 re-runs the zone-map and
# morsel parity suites with SERENE_ZONEMAP_VERIFY=1 (tests/conftest.py
# arms the serene_zonemap_verify global): every morsel the zone maps
# prune is re-scanned with the real predicate, so block-statistics/data
# divergence fails the run loudly instead of hiding behind whatever
# queries happened to sample the stale blocks.
set -o pipefail
cd "$(dirname "$0")/.."

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)

echo "== zone-map structural verification pass (serene_zonemap_verify=on) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu SERENE_ZONEMAP_VERIFY=1 \
    python -m pytest tests/test_zonemap.py tests/test_parallel_exec.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
rc2=$?

# Pass 3 mirrors pass 2 for the join filter: the sideways min/max
# pushdown is forced ON with the zone-map verifier armed, so every
# probe morsel the build-key range prunes is re-scanned with the real
# conjuncts — a range/stats divergence fails the join parity suite
# loudly instead of silently dropping matched rows.
echo "== join-filter structural verification pass (serene_join_filter=on) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu SERENE_JOIN_FILTER=on \
    SERENE_ZONEMAP_VERIFY=1 \
    python -m pytest tests/test_join_exec.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
rc3=$?

# Pass 4 is the profiler parity leg: the per-operator span collector is
# forced ON (the conftest env hook arms the serene_profile global) over
# the profiler suite plus the morsel/join parity suites, proving the
# instrumentation observes without changing a single result bit at any
# worker count.
echo "== profiler parity pass (serene_profile=on) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu SERENE_PROFILE=on \
    python -m pytest tests/test_profile.py tests/test_parallel_exec.py \
    tests/test_join_exec.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
rc4=$?

# Pass 5 is the result-cache parity leg: both cache tiers are forced ON
# (the conftest env hook arms the serene_result_cache global) over the
# cache suite plus the morsel/join parity suites — repeat statements
# serve from cache in those suites, so a single stale or perturbed bit
# fails the parity assertions loudly.
echo "== result-cache parity pass (serene_result_cache=on) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu SERENE_RESULT_CACHE=on \
    python -m pytest tests/test_result_cache.py tests/test_parallel_exec.py \
    tests/test_join_exec.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
rc5=$?

# Pass 6 is the fused-device-pipeline parity leg: the fused tier is
# forced OFF globally (the conftest env hook arms serene_device_fused)
# over the device parity suites plus the join parity suite — proving
# the one-dispatch tier is an optimization layer only: every result is
# bit-identical with it dark, and the suites' own differential tests
# still exercise both paths via their explicit session SETs.
echo "== fused device pipeline parity pass (serene_device_fused=off) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu SERENE_DEVICE_FUSED=off \
    python -m pytest tests/test_device_pipeline.py tests/test_device_agg.py \
    tests/test_join_exec.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
rc6=$?

# Pass 7 is the search-batch parity leg: the query batcher is forced
# OFF globally (the conftest env hook arms serene_search_batch) over the
# search, search-batch, and ES API suites — proving batched ragged
# serving is a dispatch-coalescing layer only: every per-query result is
# bit-identical with serial dispatch, and the suites' own parity
# matrices still exercise both modes via their explicit session SETs.
echo "== search-batch parity pass (serene_search_batch=off) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu SERENE_SEARCH_BATCH=off \
    python -m pytest tests/test_search_batch.py tests/test_search.py \
    tests/test_search_regressions.py tests/test_es_api.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc7=$?

# Pass 8 is the sharded-execution parity leg: serene_shards is forced
# to 4 globally (the conftest env hook arms the global) over the shard,
# parallel, join, device, and search parity suites — every morsel
# pipeline, fused device dispatch, and multi-segment search then runs
# through per-shard pipelines with cross-shard combiners, and a single
# diverged bit fails the suites' parity assertions loudly.
echo "== sharded execution parity pass (serene_shards=4) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_SHARDS=4 \
    python -m pytest tests/test_shard_exec.py tests/test_parallel_exec.py \
    tests/test_join_exec.py tests/test_device_pipeline.py \
    tests/test_search.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc8=$?

# Pass 9 is the timeline-tracing parity leg: serene_trace is forced ON
# globally (the conftest env hook arms the global) over the trace,
# profiler, parallel, shard and search-batch suites — every statement
# then records span timelines (pool queue waits, coalesced-batch
# fan-out, per-shard pipelines, device phases) into the flight recorder
# while the suites' parity matrices assert results stay bit-identical.
echo "== timeline tracing parity pass (serene_trace=on) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_TRACE=on \
    python -m pytest tests/test_trace.py tests/test_profile.py \
    tests/test_parallel_exec.py tests/test_shard_exec.py \
    tests/test_search_batch.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc9=$?

# Pass 10 is the multichip in-program-combine parity leg: the sharded
# tier is forced to 4 shards WITH serene_shard_combine=device (the
# conftest env hook arms both globals) over the multichip, shard,
# device and search parity suites — every sharded fused join/aggregate
# then runs as ONE shard_map collective dispatch (psum/pmin/pmax in
# HBM) and every sharded search merge as an in-program all_gather hop,
# and a single diverged bit fails the suites' parity assertions loudly.
echo "== multichip in-program combine parity pass (serene_shard_combine=device) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_SHARDS=4 \
    SERENE_SHARD_COMBINE=device \
    python -m pytest tests/test_multichip.py tests/test_shard_exec.py \
    tests/test_device_pipeline.py tests/test_search.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc10=$?

# Pass 11 is the memory-accounting parity leg: serene_mem_account is
# forced ON globally (the conftest env hook arms the global) over the
# resources, profiler, parallel and shard parity suites — every
# statement then charges live/peak bytes at its materialization sites
# and registers live progress rows while the suites' parity matrices
# assert results stay bit-identical at any worker/shard count.
echo "== memory accounting parity pass (serene_mem_account=on) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_MEM_ACCOUNT=on \
    python -m pytest tests/test_resources.py tests/test_profile.py \
    tests/test_parallel_exec.py tests/test_shard_exec.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc11=$?

# Pass 12 is the workload-governor parity leg: admission control is
# armed suite-wide (SERENE_MAX_CONCURRENT_STATEMENTS=8 — every
# non-exempt statement takes or queues for a governor slot) with a
# generous global SERENE_WORK_MEM ceiling (2GB — the budget check runs
# against every accounted statement without firing) and fair-share
# picking forced on, over the admission, parallel, shard and resources
# suites — proving the governor steers WHEN statements run, never what
# they return: a single diverged bit fails the parity assertions
# loudly.
echo "== workload governor parity pass (admission armed suite-wide) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    SERENE_MAX_CONCURRENT_STATEMENTS=8 SERENE_WORK_MEM=2GB \
    SERENE_FAIR_SHARE=on \
    python -m pytest tests/test_admission.py tests/test_parallel_exec.py \
    tests/test_shard_exec.py tests/test_resources.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc12=$?

# Pass 13 is the device-telemetry parity leg: telemetry is forced ON
# with the compiled-program LRU capped at 4 entries (the conftest env
# hooks arm both globals) over the device-observability, device,
# multichip, shard and trace suites — the tiny cap exercises program
# eviction + re-compile on practically every suite query, proving the
# bounded compile ledger changes WHEN programs compile, never a result
# bit, while the telemetry ledgers record suite-wide.
echo "== device telemetry parity pass (telemetry on, program cache capped at 4) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_DEVICE_TELEMETRY=on \
    SERENE_PROGRAM_CACHE_ENTRIES=4 \
    python -m pytest tests/test_device_obs.py tests/test_device_pipeline.py \
    tests/test_device_agg.py tests/test_multichip.py \
    tests/test_shard_exec.py tests/test_trace.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc13=$?

# Pass 14 is the posting-pool stress leg: the device-resident paged
# posting tier is forced ON with the page budget pinned at a tiny 16
# pages (the conftest env hooks arm both globals) over the search,
# search-batch, posting-pool and device-observability suites — the
# starved budget forces partial residency and mid-stream LRU eviction
# on practically every ragged search, proving the pool changes WHERE
# postings are scored (HBM page tables vs host flatten), never a
# result bit, while its gauges/relations record suite-wide.
echo "== posting pool stress pass (pool on, 16-page budget) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_POSTING_POOL=on \
    SERENE_POSTING_PAGES=16 \
    python -m pytest tests/test_search.py tests/test_search_batch.py \
    tests/test_posting_pool.py tests/test_device_obs.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc14=$?

# Pass 16 is the fused-admission parity leg, two runs over the new
# admission/chaining suites: (a) the whole fused tier forced OFF
# globally — every widened shape (string/FILTER/DISTINCT aggregates,
# outer joins, residual predicates, chained agg→top-N) answers from
# the host oracle and the suites' differential assertions still
# exercise both paths via their explicit session SETs; (b) the tier ON
# with SERENE_DEVICE_FUSED_EXT=off — the PR-7 admission walls
# restored, proving the widening is strictly additive: old shapes
# still admit, new shapes decline cleanly to bit-identical host runs.
echo "== fused admission parity pass (fused off / ext off) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_DEVICE_FUSED=off \
    python -m pytest tests/test_fused_admission.py \
    tests/test_device_pipeline.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc16=$?
if [ "$rc16" -eq 0 ]; then
    timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_DEVICE_FUSED_EXT=off \
        python -m pytest tests/test_fused_admission.py \
        tests/test_device_pipeline.py -q \
        -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
    rc16=$?
fi

# Pass 17 is the streaming-ingest parity leg: parallel analysis is
# forced ON with the segment-merge ladder pinned at a tiny cap of 3
# (the conftest env hooks arm serene_parallel_ingest and
# serene_max_segments) over the storage, segment, search, ES API and
# ingest-stream suites — every index build then chunk-splits across
# the worker pool and practically every append walks the tiered merge
# ladder, proving the parallel analysis merge and the background
# maintenance tiers are publish-mechanics only: a single diverged
# result bit fails the suites' parity assertions loudly.
echo "== streaming ingest parity pass (parallel ingest on, 3-segment cap) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_PARALLEL_INGEST=on \
    SERENE_INGEST_CHUNK_DOCS=64 SERENE_MAX_SEGMENTS=3 \
    python -m pytest tests/test_storage.py tests/test_segments.py \
    tests/test_search.py tests/test_es_api.py \
    tests/test_ingest_stream.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc17=$?

# Pass 18 is the vector-retrieval leg, two runs over the vector/search
# serving suites: (a) the paged vector pool forced ON with the page
# budget starved at 16 pages — practically every knn/MaxSim dispatch
# then walks partial residency, cold-path fallback and LRU eviction,
# proving the pool changes WHERE vectors are scored (HBM region vs
# per-call upload), never a result bit; (b) serene_nprobe pinned at
# 4096 — every probe search degenerates to a full-cluster scan, so the
# suites' brute-force parity oracles must match bit-for-bit, proving
# the cluster-probe path IS the exact path restricted to a candidate
# set, not an approximation of it.
echo "== vector retrieval pass (pool starved at 16 pages / full probe) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_VECTOR_POOL=on \
    SERENE_VECTOR_PAGES=16 \
    python -m pytest tests/test_vector_store.py tests/test_vector.py \
    tests/test_search.py tests/test_es_api.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc18=$?
if [ "$rc18" -eq 0 ]; then
    timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_NPROBE=4096 \
        python -m pytest tests/test_vector_store.py tests/test_vector.py \
        tests/test_es_api.py -q \
        -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
    rc18=$?
fi

echo "== front-door serving pass (socket admission forced at 8 connections) =="
# PR 20's asyncio front door: the pgwire/HTTP/ES suites plus the new
# transport suite all run with serene_max_connections=8 FORCED, so every
# keep-alive leak or unreleased gate slot in any suite turns into a hard
# 429/53300 failure within eight connections instead of surviving unseen
timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_MAX_CONNECTIONS=8 \
    python -m pytest tests/test_frontdoor.py tests/test_pgwire.py \
    tests/test_es_api.py tests/test_admission.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc19=$?
if [ "$rc19" -eq 0 ]; then
    # parity leg: the same serving suites with the front door OFF (the
    # legacy thread-per-connection oracle kept for one release) — the
    # route tables are shared, so divergence here is a transport bug
    timeout -k 10 600 env JAX_PLATFORMS=cpu SERENE_FRONTDOOR=off \
        python -m pytest tests/test_pgwire.py tests/test_es_api.py -q \
        -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
    rc19=$?
fi

# Structural grep lint: every jit compilation in the engine must route
# through the PR 15 compile ledger (obs/device.compiled) so the program
# cache stays bounded and observable — a bare jax.jit( call site
# anywhere outside obs/device.py (or the ops/ kernel modules, which
# pre-date the ledger and are wrapped at their call sites) regresses
# the invariant. The posting pool's gather-accumulate programs are the
# newest client; assert they compile through the ledger.
echo "== compile-ledger grep lint =="
rc15=0
if grep -rn "jax\.jit(" serenedb_tpu/ \
        --include='*.py' \
        | grep -v "^serenedb_tpu/obs/device.py:" \
        | grep -v "^serenedb_tpu/ops/" \
        | grep -v "#.*jax\.jit("; then
    echo "FAIL: bare jax.jit( outside obs/device.py and ops/ kernels"
    rc15=1
fi
if ! grep -q 'obs_device\.compiled(\s*$\|obs_device\.compiled(' \
        serenedb_tpu/search/posting_pool.py; then
    echo "FAIL: posting_pool.py does not compile through obs.device.compiled"
    rc15=1
fi
# PR 17's widened fused tier: the chained agg→top-N stage-2 builder is
# the newest program family — it must compile (and donate the stage-1
# buffers) through the ledger, never via a bare jit
if ! grep -q '"fused_chain"' serenedb_tpu/exec/device_pipeline.py || \
        ! grep -q 'obs_device\.compiled(' \
            serenedb_tpu/exec/device_pipeline.py; then
    echo "FAIL: chained fused top-N does not compile through obs.device.compiled"
    rc15=1
fi
# PR 19's vector subsystem: unlike the older ops/ kernels, ops/vector.py
# post-dates the ledger — it gets NO bare-jit exemption, and both it and
# the paged vector store must compile every program family through the
# ledger so probe/rescore/MaxSim programs show up in the bounded cache.
if grep -n "jax\.jit(" serenedb_tpu/ops/vector.py \
        | grep -v "#.*jax\.jit("; then
    echo "FAIL: bare jax.jit( in ops/vector.py — vector kernels must use the ledger"
    rc15=1
fi
if ! grep -q 'obs_device\.compiled(' serenedb_tpu/ops/vector.py; then
    echo "FAIL: ops/vector.py does not compile through obs.device.compiled"
    rc15=1
fi
if ! grep -q 'obs_device\.compiled(' serenedb_tpu/search/vector_store.py; then
    echo "FAIL: vector_store.py does not compile through obs.device.compiled"
    rc15=1
fi

[ "$rc" -ne 0 ] && exit "$rc"
[ "$rc2" -ne 0 ] && exit "$rc2"
[ "$rc3" -ne 0 ] && exit "$rc3"
[ "$rc4" -ne 0 ] && exit "$rc4"
[ "$rc5" -ne 0 ] && exit "$rc5"
[ "$rc6" -ne 0 ] && exit "$rc6"
[ "$rc7" -ne 0 ] && exit "$rc7"
[ "$rc8" -ne 0 ] && exit "$rc8"
[ "$rc9" -ne 0 ] && exit "$rc9"
[ "$rc10" -ne 0 ] && exit "$rc10"
[ "$rc11" -ne 0 ] && exit "$rc11"
[ "$rc12" -ne 0 ] && exit "$rc12"
[ "$rc13" -ne 0 ] && exit "$rc13"
[ "$rc14" -ne 0 ] && exit "$rc14"
[ "$rc16" -ne 0 ] && exit "$rc16"
[ "$rc17" -ne 0 ] && exit "$rc17"
[ "$rc18" -ne 0 ] && exit "$rc18"
[ "$rc19" -ne 0 ] && exit "$rc19"
exit "$rc15"
