#!/usr/bin/env bash
# Opportunistic device-evidence loop (VERDICT r4 #1): keep trying to
# capture real-TPU bench numbers into BENCH_LEDGER.json all round, so a
# round-end tunnel outage can no longer leave the round blind on perf.
#
#   nohup scripts/ledger_loop.sh >> ledger_loop.log 2>&1 &
#
# Behavior: every cycle, `python bench.py --ledger` probes the device
# (75s cap). Down -> retry after SLEEP_DOWN. Up -> run all shapes (each
# in its own hard-timeout subprocess), persist successes, then sleep
# SLEEP_OK before refreshing (fresher evidence after new commits).
# Stops after MAX_HOURS or when stop file exists.
set -u
cd "$(dirname "$0")/.."
MAX_HOURS="${LEDGER_MAX_HOURS:-11}"
SLEEP_DOWN="${LEDGER_SLEEP_DOWN:-240}"
SLEEP_OK="${LEDGER_SLEEP_OK:-3600}"
STOP_FILE=".ledger_stop"
rm -f "$STOP_FILE"   # a previous round's stop must not disable this one
end=$(( $(date +%s) + MAX_HOURS * 3600 ))
while [ "$(date +%s)" -lt "$end" ] && [ ! -f "$STOP_FILE" ]; do
  echo "[$(date -u +%FT%TZ)] ledger attempt"
  if python bench.py --ledger; then
    sleep "$SLEEP_OK"
  else
    sleep "$SLEEP_DOWN"
  fi
done
echo "[$(date -u +%FT%TZ)] ledger loop done"
