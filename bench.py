"""Benchmark driver: prints ONE JSON line with the headline metric.

Flagship shapes from BASELINE.md, measured on whatever jax device is
available (real TPU under the driver):

- q1:   ClickBench-Q1-shaped aggregates over a synthetic 10M-row table —
        device path vs the engine's own CPU path.
- bm25: BM25 top-10 over a synthetic corpus (100k docs) — device
        block-scoring QPS vs the CPU reference scorer on the same index.

value = geometric mean speedup (device vs single-socket CPU paths) over
the shapes that completed; vs_baseline = the same ratio (BASELINE.json
targets 3x / 2x on these shapes).

Cold vs warm: for the analytics shapes (q1, hits) the HEADLINE number is
the COLD device run — first dispatch after data lands in the engine,
including host→HBM upload, tile compression and key factorization —
because BASELINE.md's ClickBench target says "cold". A persistent XLA
compilation cache (.jax_cache/) keeps the *binary* warm across
processes, mirroring the reference's cold runs with a prebuilt release
build (scripts/perf/run_hits_perf.sh: release binary, 3 timed runs,
cold first). Warm numbers are reported alongside in detail.

Robustness: the tunneled TPU on this rig can hang any dispatch forever
during tunnel outages (not an error — a hang). So the driver process
never dispatches to the device itself. Instead it:
  1. probes device liveness in a short-timeout subprocess, retrying with
     backoff while the time budget allows;
  2. runs each bench shape in its own subprocess with a hard timeout, so
     one mid-shape hang costs that shape, not the round;
  3. always prints the one JSON line, with per-shape partial results and
     errors, before exiting;
  4. falls back to BENCH_LEDGER.json — device results captured
     opportunistically DURING the round via `python bench.py --ledger`
     — marking them "stale": true, so a round-end tunnel outage reports
     the freshest real device evidence instead of 0.0.
Budget via SDB_BENCH_BUDGET_S (default 1200s total).
"""

from __future__ import annotations

import json
import math
import os
import re
import subprocess
import sys
import threading
import time

METRIC = ("geomean device-vs-CPU speedup (ClickBench Q1 agg, ClickBench "
          "Q5-Q20 hash GROUP BY, BM25 top-10 QPS); result parity asserted")


# ---------------------------------------------------------------- shapes

def bench_q1() -> float:
    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    rng = np.random.default_rng(0)
    n = 10_000_000
    db = Database()
    c = db.connect()
    batch = Batch.from_pydict({
        "adv": Column.from_numpy(
            rng.choice(np.array([0, 0, 0, 0, 1, 2, 3], dtype=np.int32), n)),
        "region": Column.from_numpy(rng.integers(0, 200, n).astype(np.int32)),
        "x": Column.from_numpy(
            rng.integers(0, 100000, n).astype(np.int32)),
    })
    db.schemas["main"].tables["hits"] = MemTable("hits", batch)
    queries = [
        "SELECT count(*) FROM hits WHERE adv <> 0",
        "SELECT count(*), sum(x) FROM hits WHERE adv <> 0 AND x < 90000",
        "SELECT region, count(*), sum(x) FROM hits GROUP BY region",
    ]

    def run_all():
        return [tuple(c.execute(q).rows()) for q in queries]

    c.execute("SET serene_device = 'cpu'")
    run_all()
    t0 = time.perf_counter()
    cpu_res = run_all()
    t_cpu = time.perf_counter() - t0

    c.execute("SET serene_device = 'tpu'")
    t0 = time.perf_counter()
    dev_cold = run_all()  # upload + (cached-)compile + first dispatch
    t_cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    dev_res = run_all()
    t_dev = time.perf_counter() - t0
    assert cpu_res == dev_res == dev_cold, \
        "device/CPU result mismatch in Q1 bench"
    _EXTRA["cold_s"] = round(t_cold, 3)
    _EXTRA["warm_s"] = round(t_dev, 3)
    _EXTRA["cpu_s"] = round(t_cpu, 3)
    _EXTRA["speedup_warm"] = round(t_cpu / t_dev, 3)
    return t_cpu / t_cold


def bench_hits() -> float:
    """ClickBench Q5–Q20-style hash GROUP BY aggregates over a faithful
    10M-row hits generator: full-range int64 UserID (zipf-skewed user
    activity), skewed RegionID, mostly-zero AdvEngineID, mostly-empty
    SearchPhrase, SearchEngineID. Exercises direct-coded, dictionary and
    host-factorized device GROUP BY paths. ORDER BY gets deterministic
    tie-breaks so result parity is assertable (reference harness:
    scripts/perf/run_hits_perf.sh)."""
    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    rng = np.random.default_rng(3)
    n = 10_000_000
    n_users = 500_000
    user_hashes = rng.integers(0, 1 << 62, n_users, dtype=np.int64)
    uid = user_hashes[rng.zipf(1.4, n).astype(np.int64) % n_users]
    region = (rng.zipf(1.5, n) % 9000).astype(np.int32)
    adv = np.where(rng.random(n) < 0.96, 0,
                   rng.integers(1, 64, n)).astype(np.int32)
    n_phrases = 100_000
    phrase_pool = np.asarray([""] + [f"phrase {i}" for i in range(n_phrases)],
                             dtype=object)
    pid = np.where(rng.random(n) < 0.7, 0,
                   1 + rng.zipf(1.3, n) % n_phrases).astype(np.int64)
    seid = (rng.zipf(1.6, n) % 100).astype(np.int32)
    width = rng.integers(0, 4000, n).astype(np.int32)

    db = Database()
    c = db.connect()
    batch = Batch.from_pydict({
        "UserID": Column.from_numpy(uid),
        "RegionID": Column.from_numpy(region),
        "AdvEngineID": Column.from_numpy(adv),
        "SearchPhrase": Column.from_numpy(phrase_pool[pid]),
        "SearchEngineID": Column.from_numpy(seid),
        "ResolutionWidth": Column.from_numpy(width),
    })
    db.schemas["main"].tables["hits"] = MemTable("hits", batch)
    queries = [
        # Q8: low-card direct-coded key
        "SELECT AdvEngineID, count(*) AS c FROM hits WHERE AdvEngineID <> 0 "
        "GROUP BY AdvEngineID ORDER BY c DESC, AdvEngineID",
        # Q10-shape (no distinct): region rollup
        "SELECT RegionID, sum(AdvEngineID), count(*) AS c, "
        "avg(ResolutionWidth) FROM hits GROUP BY RegionID "
        "ORDER BY c DESC, RegionID LIMIT 10",
        # Q13: dictionary string key
        "SELECT SearchPhrase, count(*) AS c FROM hits "
        "WHERE SearchPhrase <> '' GROUP BY SearchPhrase "
        "ORDER BY c DESC, SearchPhrase LIMIT 10",
        # Q15: composite key beyond the direct code space → factorize
        "SELECT SearchEngineID, SearchPhrase, count(*) AS c FROM hits "
        "WHERE SearchPhrase <> '' GROUP BY SearchEngineID, SearchPhrase "
        "ORDER BY c DESC, SearchEngineID, SearchPhrase LIMIT 10",
        # Q16: full-range int64 key → factorize
        "SELECT UserID, count(*) AS c FROM hits GROUP BY UserID "
        "ORDER BY c DESC, UserID LIMIT 10",
        # Q17: wide composite key → factorize
        "SELECT UserID, SearchPhrase, count(*) AS c FROM hits "
        "GROUP BY UserID, SearchPhrase ORDER BY c DESC, UserID, "
        "SearchPhrase LIMIT 10",
    ]

    def run_all():
        return [tuple(c.execute(q).rows()) for q in queries]

    c.execute("SET serene_device = 'cpu'")
    run_all()
    t0 = time.perf_counter()
    cpu_res = run_all()
    t_cpu = time.perf_counter() - t0

    c.execute("SET serene_device = 'tpu'")
    t0 = time.perf_counter()
    dev_cold = run_all()   # compile + upload + cold factorize — reported
    t_dev_cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    dev_res = run_all()
    t_dev = time.perf_counter() - t0
    assert cpu_res == dev_res == dev_cold, \
        "device/CPU result mismatch in hits bench"
    # HBM working set after the run: compressed tiles (frame-of-reference
    # uint8/16) vs the raw-int32/f32 equivalent
    t = db.schemas["main"].tables["hits"]
    comp = raw = 0
    for cname in t.column_names:
        dc = t._device_cache.get(cname)
        if dc is None:
            continue
        dc = dc[1]
        comp += int(dc.data.size) * dc.data.dtype.itemsize
        raw += int(dc.data.size) * 4
    _EXTRA["hbm_bytes_compressed"] = comp
    _EXTRA["hbm_bytes_raw_equiv"] = raw
    _EXTRA["cold_s"] = round(t_dev_cold, 3)
    _EXTRA["warm_s"] = round(t_dev, 3)
    _EXTRA["cpu_s"] = round(t_cpu, 3)
    _EXTRA["speedup_warm"] = round(t_cpu / t_dev, 3)
    return t_cpu / t_dev_cold


def bench_bm25() -> float:
    import numpy as np

    from serenedb_tpu.search.analysis import get_analyzer
    from serenedb_tpu.search.query import parse_query
    from serenedb_tpu.search.searcher import SegmentSearcher
    from serenedb_tpu.search.segment import build_field_index

    rng = np.random.default_rng(1)
    vocab = [f"w{i}" for i in range(2000)]
    zipf = rng.zipf(1.3, size=4_000_000) % len(vocab)
    n_docs = 100_000
    lens = rng.integers(8, 40, n_docs)
    docs = []
    pos = 0
    for ln in lens:
        docs.append(" ".join(vocab[z] for z in zipf[pos:pos + ln]))
        pos += ln
    an = get_analyzer("simple")
    fi = build_field_index(docs, an)
    searcher = SegmentSearcher(fi, an, n_docs)

    # benchmark-game-style query set: single terms across the frequency
    # spectrum, 2-term disjunctions pairing common with rare terms (the
    # shape WAND/MaxScore exists for), 2-term conjunctions (256 queries)
    idxs = [1 + 3 * i for i in range(128)]
    qterms = [vocab[i] for i in idxs]
    queries = ([parse_query(t, an) for t in qterms] +
               [parse_query(f"{a} | {b}", an)
                for a, b in zip(qterms[:64], qterms[64:][::-1])] +
               [parse_query(f"{a} & {b}", an)
                for a, b in zip(qterms[1::2], qterms[::2])])

    # warmup/compile — the QPS regime batches queries per dispatch
    out_dev = searcher.topk_batch(queries, 10)
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        searcher.topk_batch(queries, 10)
    t_dev = time.perf_counter() - t0
    qps_dev = reps * len(queries) / t_dev

    # CPU baseline: block-max WAND + MaxScore (cpu_topk_wand) — the same
    # optimization family the reference's CPU engine runs
    # (search/block_disjunction.hpp), NOT the exhaustive scorer, so the
    # reported ratio survives scrutiny. Warm pass first: plan/bucket
    # caches mirror the device path's compile+upload warmup.
    shapes = [searcher._query_shape(q) for q in queries]
    for (tids, req, _, _) in shapes:
        searcher.cpu_topk_wand(tids, 10, require_all=req)
    t0 = time.perf_counter()
    cpu_out = []
    for (tids, req, _, _) in shapes:
        cpu_out.append(searcher.cpu_topk_wand(tids, 10, require_all=req))
    t_cpu = time.perf_counter() - t0
    qps_cpu = len(queries) / t_cpu
    # top-10 parity device vs CPU on a spanning sample
    for si in range(0, len(queries), 7):
        dev_s, dev_d = out_dev[si]
        ref_s, ref_d = cpu_out[si]
        assert len(dev_s) == len(ref_s), \
            f"query {si}: {len(dev_s)} vs {len(ref_s)} results"
        np.testing.assert_allclose(dev_s, ref_s, rtol=2e-3, atol=1e-3)
        for j, (dd, rd) in enumerate(zip(dev_d.tolist(), ref_d.tolist())):
            if dd != rd:  # doc ids may differ only on score ties
                assert abs(float(ref_s[j]) - float(dev_s[j])) < 1e-3, \
                    f"query {si} rank {j}: doc {dd} != {rd}"
    return qps_dev / qps_cpu


def bench_bm25_1m() -> float:
    """BM25 top-10 at 1M docs (an MS-MARCO-scale step): the query batch
    auto-splits so the device accumulator never exceeds the HBM cap, and
    WAND/MaxScore pruning keeps per-dispatch work bounded. Measures QPS
    against the exhaustive CPU scorer on a query sample; asserts top-10
    parity."""
    import numpy as np

    from serenedb_tpu.search.analysis import get_analyzer
    from serenedb_tpu.search.query import parse_query
    from serenedb_tpu.search.searcher import SegmentSearcher
    from serenedb_tpu.search.segment import build_field_index

    rng = np.random.default_rng(5)
    n_docs = 1_000_000
    vocab = np.asarray([f"w{i}" for i in range(30_000)], dtype=object)
    lens = rng.integers(8, 40, n_docs)
    zipf = rng.zipf(1.25, size=int(lens.sum())) % len(vocab)
    bounds = np.concatenate([[0], np.cumsum(lens)])
    words = vocab[zipf]
    docs = [" ".join(words[bounds[i]:bounds[i + 1]])
            for i in range(n_docs)]
    an = get_analyzer("simple")
    fi = build_field_index(docs, an)
    del docs, words, zipf
    searcher = SegmentSearcher(fi, an, n_docs)

    idxs = [1 + 9 * i for i in range(64)]
    qterms = [f"w{i}" for i in idxs]
    queries = ([parse_query(t, an) for t in qterms] +
               [parse_query(f"{a} | {b}", an)
                for a, b in zip(qterms[:32], qterms[32:][::-1])] +
               [parse_query(f"{a} & {b}", an)
                for a, b in zip(qterms[1::2], qterms[::2])])

    out_dev = searcher.topk_batch(queries, 10)  # warmup/compile
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        searcher.topk_batch(queries, 10)
    qps_dev = reps * len(queries) / (time.perf_counter() - t0)

    # WAND/MaxScore CPU reference (warm) on a spanning sample + parity
    sample = list(range(0, len(queries), 8))
    shapes = [searcher._query_shape(queries[si]) for si in sample]
    for (tids, req, _, _) in shapes:
        searcher.cpu_topk_wand(tids, 10, require_all=req)
    t0 = time.perf_counter()
    cpu_out = [searcher.cpu_topk_wand(tids, 10, require_all=req)
               for (tids, req, _, _) in shapes]
    qps_cpu = len(sample) / (time.perf_counter() - t0)
    for pos, si in enumerate(sample):
        ref_s, ref_d = cpu_out[pos]
        dev_s, dev_d = out_dev[si]
        assert len(dev_s) == min(10, len(ref_s)), \
            f"query {si}: {len(dev_s)} results, expected {min(10, len(ref_s))}"
        np.testing.assert_allclose(dev_s, ref_s[:len(dev_s)],
                                   rtol=2e-3, atol=1e-3)
        # doc ids must agree except where scores tie at the boundary
        for j, (dd, rd) in enumerate(zip(dev_d.tolist(), ref_d.tolist())):
            if dd != rd:
                assert abs(float(ref_s[j]) - float(dev_s[j])) < 1e-3, \
                    f"query {si} rank {j}: doc {dd} != {rd}"
    return qps_dev / qps_cpu


def _synth_posting_index(n_docs: int, vocab: int, total_postings: int,
                         seed: int):
    """Build a FieldIndex directly from a synthetic posting distribution
    (vectorized — no string tokenization; this shape measures scoring QPS,
    not indexing). Term document-frequencies follow a zipf law, tfs are
    small-integer zipf, norms are the consistent per-doc tf sums."""
    import numpy as np

    from serenedb_tpu.search.segment import FieldIndex, _add_block_max

    rng = np.random.default_rng(seed)
    # zipf df profile scaled to the posting budget
    raw = 1.0 / np.arange(1, vocab + 1) ** 0.9
    df_target = np.maximum((raw / raw.sum() * total_postings), 1.0)
    df_target = np.minimum(df_target, n_docs * 0.8).astype(np.int64)
    terms_rep = np.repeat(np.arange(vocab, dtype=np.int64), df_target)
    docs_rnd = rng.integers(0, n_docs, len(terms_rep), dtype=np.int64)
    keys = terms_rep * n_docs + docs_rnd
    keys = np.unique(keys)   # sorted by (term, doc); drops dup samples
    post_terms = (keys // n_docs).astype(np.int64)
    post_docs = (keys % n_docs).astype(np.int32)
    post_tfs = np.minimum(rng.zipf(1.7, len(keys)), 64).astype(np.int32)
    doc_freq = np.bincount(post_terms, minlength=vocab).astype(np.int32)
    offsets = np.zeros(vocab + 1, dtype=np.int64)
    np.cumsum(doc_freq, out=offsets[1:])
    norms = np.bincount(post_docs, weights=post_tfs,
                        minlength=n_docs).astype(np.int32)
    fi = FieldIndex(
        terms=np.asarray([f"w{i:07d}" for i in range(vocab)], dtype=object),
        doc_freq=doc_freq,
        offsets=offsets,
        post_docs=post_docs,
        post_tfs=post_tfs,
        pos_offsets=np.zeros(len(post_docs) + 1, dtype=np.int64),
        positions=np.empty(0, dtype=np.int32),
        norms=norms,
        block_max_tf=np.empty(0, dtype=np.int32),
        block_offsets=np.zeros(vocab + 1, dtype=np.int64),
        total_tokens=int(post_tfs.sum()),
    )
    _add_block_max(fi)
    return fi


def bench_bm25_8m() -> float:
    """BM25 top-10 at 8M docs — MS-MARCO scale (8.8M passages). Proves the
    HBM-capped query splitting + WAND planning hold at target size; CPU
    baseline is the WAND/MaxScore host scorer; asserts top-10 parity."""
    import numpy as np

    from serenedb_tpu.search.analysis import get_analyzer
    from serenedb_tpu.search.query import parse_query
    from serenedb_tpu.search.searcher import SegmentSearcher

    n_docs = 8_000_000
    vocab = 200_000
    fi = _synth_posting_index(n_docs, vocab, 120_000_000, seed=9)
    an = get_analyzer("simple")
    searcher = SegmentSearcher(fi, an, n_docs)

    idxs = [1 + 97 * i for i in range(48)]
    qterms = [f"w{i:07d}" for i in idxs]
    queries = ([parse_query(t, an) for t in qterms] +
               [parse_query(f"{a} | {b}", an)
                for a, b in zip(qterms[:24], qterms[24:][::-1])] +
               [parse_query(f"{a} & {b}", an)
                for a, b in zip(qterms[1::2], qterms[::2])])

    out_dev = searcher.topk_batch(queries, 10)  # warmup/compile
    store = searcher._device_store()
    _EXTRA["hbm_tiles_mb"] = round(store.hbm_bytes / (1 << 20), 1)
    _EXTRA["hbm_raw_equiv_mb"] = round(
        store.hbm_bytes_raw_equiv / (1 << 20), 1)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        searcher.topk_batch(queries, 10)
    qps_dev = reps * len(queries) / (time.perf_counter() - t0)

    sample = list(range(0, len(queries), 6))
    shapes = [searcher._query_shape(queries[si]) for si in sample]
    for (tids, req, _, _) in shapes:
        searcher.cpu_topk_wand(tids, 10, require_all=req)
    t0 = time.perf_counter()
    cpu_out = [searcher.cpu_topk_wand(tids, 10, require_all=req)
               for (tids, req, _, _) in shapes]
    qps_cpu = len(sample) / (time.perf_counter() - t0)
    for pos, si in enumerate(sample):
        ref_s, ref_d = cpu_out[pos]
        dev_s, dev_d = out_dev[si]
        assert len(dev_s) == min(10, len(ref_s)), \
            f"query {si}: {len(dev_s)} results, expected {min(10, len(ref_s))}"
        np.testing.assert_allclose(dev_s, ref_s[:len(dev_s)],
                                   rtol=2e-3, atol=1e-3)
        for j, (dd, rd) in enumerate(zip(dev_d.tolist(), ref_d.tolist())):
            if dd != rd:
                assert abs(float(ref_s[j]) - float(dev_s[j])) < 1e-3, \
                    f"query {si} rank {j}: doc {dd} != {rd}"
    return qps_dev / qps_cpu


def bench_ingest() -> float:
    """Production streaming-ingest shape (ISSUE 18): (a) raw parallel
    analysis MB/s vs the serial oracle, bit-identity asserted; (b)
    sustained END-TO-END engine ingest — MB/s + docs/s under 1/4/8
    concurrent writers WITH concurrent readers against a durable db
    (WAL group commit + the maintenance ticker live), read p99 during
    ingest recorded per writer count; (c) read p99 under background vs
    foreground segment maintenance — the headline HTAP number; (d)
    relational + search results bit-identical with parallel ingest
    on/off. Returns the raw-analysis speedup (parallel/serial); the
    scaling assert fires only on multi-core hosts (the PR 5/10 noise
    lesson), everything else is recorded in extras."""
    import tempfile
    import threading

    import numpy as np

    from serenedb_tpu.engine import Database
    from serenedb_tpu.search.analysis import get_analyzer
    from serenedb_tpu.search.segment import (build_field_index,
                                             build_field_index_auto)
    from serenedb_tpu.utils.config import REGISTRY

    n_cores = os.cpu_count() or 1
    _EXTRA["threads"] = n_cores

    # ---- (a) raw parallel analysis vs the serial oracle --------------
    rng = np.random.default_rng(7)
    vocab = np.asarray([f"w{i}" for i in range(50_000)], dtype=object)
    n_docs = 60_000
    lens = rng.integers(40, 160, n_docs)
    zipf = rng.zipf(1.2, size=int(lens.sum())) % len(vocab)
    bounds = np.concatenate([[0], np.cumsum(lens)])
    words = vocab[zipf]
    docs = [" ".join(words[bounds[i]:bounds[i + 1]]) for i in range(n_docs)]
    del words, zipf
    mb = sum(len(d) for d in docs) / (1 << 20)
    an = get_analyzer("simple")

    REGISTRY.set_global("serene_parallel_ingest", False)
    t0 = time.perf_counter()
    fi_ser = build_field_index(list(docs), an)
    t_ser = time.perf_counter() - t0
    REGISTRY.set_global("serene_parallel_ingest", True)
    REGISTRY.set_global("serene_workers", n_cores)
    t0 = time.perf_counter()
    fi_par = build_field_index_auto(list(docs), an)
    t_par = time.perf_counter() - t0
    # bit-identity: the deterministic merge must reproduce the serial
    # build exactly, not just approximately
    import numpy.testing as npt
    assert [str(t) for t in fi_ser.terms] == [str(t) for t in fi_par.terms]
    for f in ("doc_freq", "offsets", "post_docs", "post_tfs",
              "pos_offsets", "positions", "norms", "block_max_tf",
              "block_offsets"):
        npt.assert_array_equal(getattr(fi_ser, f), getattr(fi_par, f), f)
    assert fi_ser.total_tokens == fi_par.total_tokens
    _EXTRA["mb"] = round(mb, 1)
    _EXTRA["mbps_1t"] = round(mb / t_ser, 1)
    _EXTRA["mbps_mt"] = round(mb / t_par, 1)
    del fi_ser, fi_par

    # ---- (b) end-to-end writers × readers against a durable db -------
    body = [" ".join(f"w{int(x)}" for x in rng.integers(0, 3000, 14))
            for _ in range(400)]

    def _stream(db, n_writers, total_docs, batch=50):
        """Insert total_docs across n_writers threads while 2 readers
        hammer search queries; returns (seconds, read latencies ms)."""
        stmts = []
        for s in range(0, total_docs, batch):
            vals = ", ".join(
                f"({s + j}, '{body[(s + j) % len(body)]}')"
                for j in range(min(batch, total_docs - s)))
            stmts.append(f"INSERT INTO docs VALUES {vals}")
        nbytes = sum(len(body[i % len(body)]) for i in range(total_docs))
        cursor = {"i": 0}
        lock = threading.Lock()
        stop = threading.Event()
        lat_ms, errs = [], []

        def writer():
            c = db.connect()
            try:
                while True:
                    with lock:
                        i = cursor["i"]
                        cursor["i"] += 1
                    if i >= len(stmts):
                        return
                    c.execute(stmts[i])
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        def reader():
            c = db.connect()
            while not stop.is_set():
                t0 = time.perf_counter()
                c.execute("SELECT count(*) FROM docs WHERE body @@ 'w1'")
                c.execute("SELECT id, bm25(body) AS s FROM docs "
                          "WHERE body @@ 'w7' ORDER BY s DESC, id LIMIT 10")
                lat_ms.append((time.perf_counter() - t0) * 1e3)

        rs = [threading.Thread(target=reader, daemon=True)
              for _ in range(2)]
        ws = [threading.Thread(target=writer) for _ in range(n_writers)]
        t0 = time.perf_counter()
        for t in rs + ws:
            t.start()
        for t in ws:
            t.join()
        dt = time.perf_counter() - t0
        stop.set()
        for t in rs:
            t.join(timeout=30)
        if errs:
            raise errs[0]
        return dt, nbytes, lat_ms

    def _fresh_db(tmp, tag):
        d = Database(os.path.join(tmp, tag))
        c = d.connect()
        c.execute("CREATE TABLE docs (id INT, body TEXT)")
        c.execute(f"INSERT INTO docs VALUES (-1, '{body[0]}')")
        c.execute("CREATE INDEX ON docs USING inverted (body)")
        return d

    curve = {}
    with tempfile.TemporaryDirectory() as tmp:
        for w in (1, 4, 8):
            db = _fresh_db(tmp, f"w{w}")
            dt, nbytes, lat = _stream(db, w, 3000)
            curve[str(w)] = {
                "docs_per_s": round(3000 / dt, 1),
                "mbps": round(nbytes / (1 << 20) / dt, 2),
                "read_p99_ms": round(float(np.percentile(lat, 99)), 2)
                if lat else None,
                "reads": len(lat)}
            db.close()

        # ---- (c) read p99: background vs foreground maintenance ------
        p99 = {}
        for mode, bg in (("bg", True), ("fg", False)):
            REGISTRY.set_global("serene_background_merge", bg)
            REGISTRY.set_global("serene_max_segments", 4)
            db = _fresh_db(tmp, mode)
            _, _, lat = _stream(db, 4, 3000)
            p99[mode] = round(float(np.percentile(lat, 99)), 2) \
                if lat else None
            db.close()
        REGISTRY.set_global("serene_background_merge", True)
        REGISTRY.set_global("serene_max_segments", 8)
    _EXTRA["writers_curve"] = curve
    _EXTRA["read_p99_bg_ms"] = p99["bg"]
    _EXTRA["read_p99_fg_ms"] = p99["fg"]

    # ---- (d) end-to-end parity: parallel ingest on vs off ------------
    REGISTRY.set_global("serene_ingest_chunk_docs", 64)
    states = {}
    for on in (False, True):
        REGISTRY.set_global("serene_parallel_ingest", on)
        db = Database()
        c = db.connect()
        c.execute("CREATE TABLE docs (id INT, body TEXT)")
        for s in range(0, 2000, 100):
            vals = ", ".join(f"({s + j}, '{body[(s + j) % len(body)]}')"
                             for j in range(100))
            c.execute(f"INSERT INTO docs VALUES {vals}")
        c.execute("CREATE INDEX ON docs USING inverted (body)")
        states[on] = (
            c.execute("SELECT count(*) FROM docs WHERE body @@ 'w1'"
                      ).scalar(),
            c.execute("SELECT id, bm25(body) AS s FROM docs "
                      "WHERE body @@ 'w7' ORDER BY s DESC, id LIMIT 20"
                      ).rows(),
            c.execute("SELECT id % 7, count(*) FROM docs "
                      "WHERE body @@ 'w2 | w3' GROUP BY id % 7 "
                      "ORDER BY 1").rows())
    assert states[False] == states[True], "parallel-ingest parity broke"
    REGISTRY.set_global("serene_ingest_chunk_docs", 4096)

    ratio = t_ser / t_par
    if n_cores >= 2:
        assert ratio > 1.3, \
            f"parallel ingest does not scale: {ratio:.2f}x on {n_cores} cores"
    return ratio


def bench_host_agg() -> float:
    """Host morsel-parallel hash-GROUP-BY scaling (reference: DuckDB's
    morsel-driven pipeline workers; ISSUE 1 tentpole): one
    Scan→Filter→GroupBy shape through the engine with the device path
    disabled, at serene_workers=1 vs all cores. Returns the scaling
    ratio t_1t/t_mt; extras carry the full worker→seconds curve so the
    ledger shows the curve, not a flat 1t≈mt. Results must be
    bit-identical across worker counts (asserted)."""
    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    n_cores = os.cpu_count() or 1
    rng = np.random.default_rng(13)
    n = 6_000_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE hits (k INT, v BIGINT, f DOUBLE)")
    batch = Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 1000, n).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(-(10 ** 6), 10 ** 6, n, dtype=np.int64)),
        "f": Column.from_numpy(rng.normal(size=n)),
    })
    db.schemas["main"].tables["hits"] = MemTable("hits", batch)
    c.execute("SET serene_device = 'cpu'")
    q = ("SELECT k, count(*), sum(v), min(f), max(f), avg(f), stddev(f) "
         "FROM hits WHERE v % 7 <> 0 GROUP BY k")

    workers = sorted({1, 2, n_cores} - {0})
    workers = [w for w in workers if w <= n_cores]
    curve: dict[str, float] = {}
    results: dict[int, list] = {}
    for w in workers:
        c.execute(f"SET serene_workers = {w}")
        results[w] = c.execute(q).rows()      # warm + correctness capture
        t0 = time.perf_counter()
        reps = 3
        for _ in range(reps):
            c.execute(q)
        curve[str(w)] = round((time.perf_counter() - t0) / reps, 4)
    for w in workers[1:]:
        assert results[w] == results[workers[0]], \
            f"workers={w} diverged from workers=1"
    _EXTRA["rows"] = n
    _EXTRA["threads"] = n_cores
    _EXTRA["curve_s"] = curve
    _EXTRA["t_1t_s"] = curve[str(workers[0])]
    _EXTRA["t_mt_s"] = curve[str(workers[-1])]
    return curve[str(workers[0])] / curve[str(workers[-1])]


def bench_filter_scan() -> float:
    """Zone-map skip-scan (ISSUE 2 tentpole): one selective-filter
    aggregate over a position-clustered column at selectivities 100%,
    10%, 1%, 0.1% with `serene_zonemap` on vs off. Returns the off/on
    speedup at 1% selectivity; extras carry the full
    selectivity→seconds curve for both settings. Results must be
    bit-identical on/off (asserted), and 100% selectivity must not
    regress (all-match blocks skip predicate evaluation, so the on path
    is never slower than off)."""
    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    rng = np.random.default_rng(17)
    n = 6_000_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE fs (ts BIGINT, v BIGINT, f DOUBLE)")
    batch = Batch.from_pydict({
        # clustered scan axis (ingest order / time): the realistic shape
        # zone maps exist for
        "ts": Column.from_numpy(np.arange(n, dtype=np.int64)),
        "v": Column.from_numpy(
            rng.integers(-(10 ** 6), 10 ** 6, n, dtype=np.int64)),
        "f": Column.from_numpy(rng.normal(size=n)),
    })
    db.schemas["main"].tables["fs"] = MemTable("fs", batch)
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_morsel_rows = 65536")   # ~92 prunable blocks
    selectivities = [1.0, 0.1, 0.01, 0.001]
    curve: dict[str, dict[str, float]] = {}
    reps = 3
    for sel in selectivities:
        cut = int(n * sel)
        q = (f"SELECT count(*), sum(v), max(f) FROM fs "
             f"WHERE ts < {cut}")
        entry: dict[str, float] = {}
        rows = {}
        for zm in ("on", "off"):
            c.execute(f"SET serene_zonemap = {zm}")
            rows[zm] = repr(c.execute(q).rows())    # warm + correctness
            t0 = time.perf_counter()
            for _ in range(reps):
                c.execute(q)
            entry[zm] = round((time.perf_counter() - t0) / reps, 5)
        assert rows["on"] == rows["off"], f"zonemap diverged at sel={sel}"
        curve[str(sel)] = entry
    _EXTRA["rows"] = n
    _EXTRA["curve_s"] = curve
    speedup_1pct = curve["0.01"]["off"] / curve["0.01"]["on"]
    _EXTRA["speedup_0.1pct"] = round(
        curve["0.001"]["off"] / curve["0.001"]["on"], 2)
    _EXTRA["full_scan_ratio"] = round(
        curve["1.0"]["on"] / curve["1.0"]["off"], 3)
    assert speedup_1pct >= 3.0, \
        f"zone maps under-deliver: {speedup_1pct:.2f}x at 1% selectivity"
    assert curve["1.0"]["on"] <= curve["1.0"]["off"] * 1.25, \
        "zone maps regress the 100%-selectivity scan"
    return speedup_1pct


def bench_join() -> float:
    """Vectorized parallel hash join vs the legacy row-tuple join
    (ISSUE 3 tentpole): one inner equi-join aggregate through the engine
    at build×probe shapes (100k×100k, 1M×1M) × probe-hit selectivity
    (100%, 10%, 1%), `serene_join_vectorized` on vs off. Build keys are
    a permutation of [0, nb) and probe keys draw uniformly from
    [0, nb/sel), so a `sel` fraction of probe rows finds exactly one
    partner and the probe side is unclustered (zone maps can't prune —
    this measures the matching tier, not the join filter). Returns the
    legacy/vectorized speedup at 1M×1M 10% selectivity; extras carry the
    whole curve. Results must be bit-identical (asserted)."""
    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    rng = np.random.default_rng(23)
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE p (k BIGINT, v BIGINT)")
    c.execute("CREATE TABLE b (k BIGINT, w BIGINT)")
    c.execute("SET serene_device = 'cpu'")
    q = "SELECT count(*), sum(v+w) FROM p JOIN b ON p.k = b.k"
    curve: dict[str, dict[str, float]] = {}
    headline = None
    for nb, npr in ((100_000, 100_000), (1_000_000, 1_000_000)):
        for sel in (1.0, 0.1, 0.01):
            keyspace = int(nb / sel)
            db.schemas["main"].tables["b"] = MemTable("b", Batch.from_pydict({
                "k": Column.from_numpy(
                    rng.permutation(np.arange(nb, dtype=np.int64))),
                "w": Column.from_numpy(
                    rng.integers(0, 100, nb, dtype=np.int64))}))
            db.schemas["main"].tables["p"] = MemTable("p", Batch.from_pydict({
                "k": Column.from_numpy(
                    rng.integers(0, keyspace, npr, dtype=np.int64)),
                "v": Column.from_numpy(
                    rng.integers(0, 100, npr, dtype=np.int64))}))
            c.execute("SET serene_join_vectorized = on")
            rows_vec = c.execute(q).rows()     # warm + correctness capture
            reps = 2
            t0 = time.perf_counter()
            for _ in range(reps):
                c.execute(q)
            t_vec = (time.perf_counter() - t0) / reps
            c.execute("SET serene_join_vectorized = off")
            t0 = time.perf_counter()
            rows_leg = c.execute(q).rows()     # legacy is slow: 1 reps
            t_leg = time.perf_counter() - t0
            assert rows_vec == rows_leg, \
                f"vectorized join diverged at {nb}x{npr} sel={sel}"
            entry = {"vec": round(t_vec, 4), "legacy": round(t_leg, 4),
                     "speedup": round(t_leg / t_vec, 2)}
            curve[f"{nb}x{npr}@{sel}"] = entry
            if (nb, npr, sel) == (1_000_000, 1_000_000, 0.1):
                headline = t_leg / t_vec
    _EXTRA["curve"] = curve
    _EXTRA["speedup_1m_100pct"] = curve["1000000x1000000@1.0"]["speedup"]
    _EXTRA["speedup_1m_1pct"] = curve["1000000x1000000@0.01"]["speedup"]
    assert headline >= 5.0, \
        f"vectorized join under-delivers: {headline:.2f}x at 1Mx1M"
    return headline


def bench_profile_overhead() -> float:
    """Profiler overhead budget (ISSUE 4, <3%): the host_agg filtered
    parallel aggregate plus the vectorized join at 1M rows, with
    `serene_profile` on vs off. Per-batch span stamps and morsel stage
    clocks are the only difference; results are asserted bit-identical.
    Returns t_off/t_on (≈1.0; 0.97 ⇔ 3% overhead) so the ledger's
    "faster is better" convention holds; extras carry the measured
    overhead percentage per query shape. Single-digit-percent deltas
    drown in scheduler noise under naive A/B timing, so executions
    alternate on/off pairwise and the overhead is a ratio of per-mode
    MEDIANS — order and drift hit both modes equally."""
    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    rng = np.random.default_rng(31)
    n = 1_000_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE po (k INT, v BIGINT)")
    c.execute("CREATE TABLE pb (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["po"] = MemTable("po", Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 1000, n).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(-(10 ** 6), 10 ** 6, n, dtype=np.int64))}))
    db.schemas["main"].tables["pb"] = MemTable("pb", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.permutation(np.arange(n, dtype=np.int64))),
        "w": Column.from_numpy(
            rng.integers(0, 100, n, dtype=np.int64))}))
    c.execute("SET serene_device = 'cpu'")
    queries = {
        "host_agg": ("SELECT k, count(*), sum(v) FROM po "
                     "WHERE v % 7 <> 0 GROUP BY k"),
        "join": ("SELECT count(*), sum(v + w) FROM po "
                 "JOIN pb ON po.v = pb.k"),
    }
    import statistics
    pairs = 7
    detail: dict[str, dict] = {}
    t_on_total = t_off_total = 0.0
    for name, q in queries.items():
        rows = {}
        samples: dict[str, list[float]] = {"on": [], "off": []}
        for prof in ("on", "off"):          # warm both paths + capture
            c.execute(f"SET serene_profile = {prof}")
            rows[prof] = c.execute(q).rows()
        assert rows["on"] == rows["off"], f"profiling perturbed {name}"
        for _ in range(pairs):
            for prof in ("off", "on"):
                c.execute(f"SET serene_profile = {prof}")
                t0 = time.perf_counter()
                c.execute(q)
                samples[prof].append(time.perf_counter() - t0)
        med = {p: statistics.median(s) for p, s in samples.items()}
        overhead = med["on"] / med["off"] - 1.0
        detail[name] = {"on_s": round(med["on"], 5),
                        "off_s": round(med["off"], 5),
                        "overhead_pct": round(overhead * 100, 2)}
        t_on_total += med["on"]
        t_off_total += med["off"]
    _EXTRA["rows"] = n
    _EXTRA["detail"] = detail
    overall = t_on_total / t_off_total - 1.0
    _EXTRA["overhead_pct"] = round(overall * 100, 2)
    assert overall < 0.03, \
        f"profiler overhead over budget: {overall * 100:.2f}% (>3%)"
    return t_off_total / t_on_total


def bench_trace_overhead() -> float:
    """Timeline-tracing overhead budget (ISSUE 10, <3%): the host_agg
    filtered parallel aggregate plus the vectorized join at 1M rows,
    with `serene_trace` on vs off (profiling stays at its default in
    both modes — this isolates the TRACING delta: per-statement trace
    setup, per-pool-task span stamps, flight-recorder finalize).
    Results are asserted bit-identical and the end-to-end
    alternating-pairs medians are recorded per shape — but like the
    result_cache miss-overhead leg, a single-digit-percent delta drowns
    in this host's ±10%+ serial drift end to end, so the ASSERTED
    number is a direct decomposition: the measured cost of one traced
    statement's actual span traffic (trace setup + 4x the observed span
    count + ring merge + flight record), divided by the query's off-mode
    median. Returns t_off/t_on (≈1.0; 0.97 ⇔ 3% overhead)."""
    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    rng = np.random.default_rng(31)
    n = 1_000_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE po (k INT, v BIGINT)")
    c.execute("CREATE TABLE pb (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["po"] = MemTable("po", Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 1000, n).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(-(10 ** 6), 10 ** 6, n, dtype=np.int64))}))
    db.schemas["main"].tables["pb"] = MemTable("pb", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.permutation(np.arange(n, dtype=np.int64))),
        "w": Column.from_numpy(
            rng.integers(0, 100, n, dtype=np.int64))}))
    c.execute("SET serene_device = 'cpu'")
    queries = {
        "host_agg": ("SELECT k, count(*), sum(v) FROM po "
                     "WHERE v % 7 <> 0 GROUP BY k"),
        "join": ("SELECT count(*), sum(v + w) FROM po "
                 "JOIN pb ON po.v = pb.k"),
    }
    import statistics

    from serenedb_tpu.obs.trace import FLIGHT, QueryTrace
    pairs = 7
    detail: dict[str, dict] = {}
    t_on_total = t_off_total = 0.0
    max_spans = 1
    for name, q in queries.items():
        rows = {}
        samples: dict[str, list[float]] = {"on": [], "off": []}
        for tr in ("on", "off"):            # warm both paths + capture
            c.execute(f"SET serene_trace = {tr}")
            rows[tr] = c.execute(q).rows()
        assert rows["on"] == rows["off"], f"tracing perturbed {name}"
        for _ in range(pairs):
            for tr in ("off", "on"):
                c.execute(f"SET serene_trace = {tr}")
                t0 = time.perf_counter()
                c.execute(q)
                samples[tr].append(time.perf_counter() - t0)
        # the query's REAL span count (its last traced run is the
        # newest flight entry) feeds the direct probe below
        spans = len(FLIGHT.last()["spans"])
        max_spans = max(max_spans, spans)
        med = {p: statistics.median(s) for p, s in samples.items()}
        overhead = med["on"] / med["off"] - 1.0
        detail[name] = {"on_s": round(med["on"], 5),
                        "off_s": round(med["off"], 5),
                        "spans": spans,
                        "e2e_overhead_pct": round(overhead * 100, 2)}
        t_on_total += med["on"]
        t_off_total += med["off"]
    # direct decomposition: one traced statement costs (setup + span
    # stamps + ring merge + flight record); probe it at 4x the widest
    # observed span count and charge it against the FASTEST query's
    # off-mode median (the worst case for a fixed per-statement cost)
    reps = 200
    t0 = time.perf_counter()
    for _ in range(reps):
        qt = QueryTrace("bench probe")
        now = qt.t0_ns
        for i in range(4 * max_spans):
            qt.add("probe_span", "bench", now + i, now + i + 100, k=i)
        FLIGHT.record(qt.finish())
    per_stmt_s = (time.perf_counter() - t0) / reps
    fastest_off = min(d["off_s"] for d in detail.values())
    direct = per_stmt_s / fastest_off
    _EXTRA["rows"] = n
    _EXTRA["detail"] = detail
    _EXTRA["per_statement_trace_ms"] = round(per_stmt_s * 1e3, 4)
    _EXTRA["probe_spans"] = 4 * max_spans
    _EXTRA["overhead_pct"] = round(direct * 100, 3)
    _EXTRA["e2e_overhead_pct"] = round(
        (t_on_total / t_off_total - 1.0) * 100, 2)
    assert direct < 0.03, \
        f"tracing overhead over budget: {direct * 100:.2f}% (>3%)"
    return t_off_total / t_on_total


def bench_mem_overhead() -> float:
    """Memory-accounting overhead budget (ISSUE 13, <3%): the host_agg
    filtered parallel aggregate plus the vectorized join at 1M rows,
    with `serene_mem_account` on vs off (profile/trace stay at their
    defaults in both modes — this isolates the ACCOUNTING delta:
    per-statement accountant setup + ACTIVE registration, per-batch /
    per-morsel charge+release pairs, statement-end totals). Results are
    asserted bit-identical and the end-to-end alternating-pairs medians
    are recorded per shape — but like trace_overhead (the PR 5/PR 10
    noise lesson), a single-digit-percent delta drowns in this host's
    serial drift end to end, so the ASSERTED number is a direct
    decomposition: the measured cost of one accounted statement's
    actual charge/release traffic (setup + register + 4x the observed
    event count + merge/totals + retire), divided by the query's
    off-mode median. Returns t_off/t_on (≈1.0; 0.97 ⇔ 3% overhead)."""
    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    rng = np.random.default_rng(31)
    n = 1_000_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE po (k INT, v BIGINT)")
    c.execute("CREATE TABLE pb (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["po"] = MemTable("po", Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 1000, n).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(-(10 ** 6), 10 ** 6, n, dtype=np.int64))}))
    db.schemas["main"].tables["pb"] = MemTable("pb", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.permutation(np.arange(n, dtype=np.int64))),
        "w": Column.from_numpy(
            rng.integers(0, 100, n, dtype=np.int64))}))
    c.execute("SET serene_device = 'cpu'")
    queries = {
        "host_agg": ("SELECT k, count(*), sum(v) FROM po "
                     "WHERE v % 7 <> 0 GROUP BY k"),
        "join": ("SELECT count(*), sum(v + w) FROM po "
                 "JOIN pb ON po.v = pb.k"),
    }
    import statistics

    from serenedb_tpu.obs.resources import ACTIVE, MemoryAccountant
    from serenedb_tpu.utils import metrics as _metrics
    pairs = 7
    detail: dict[str, dict] = {}
    t_on_total = t_off_total = 0.0
    max_events = 1
    for name, q in queries.items():
        rows = {}
        samples: dict[str, list[float]] = {"on": [], "off": []}
        for mode in ("on", "off"):          # warm both paths + capture
            c.execute(f"SET serene_mem_account = {mode}")
            ev0 = _metrics.MEM_ACCOUNT_EVENTS.value
            rows[mode] = c.execute(q).rows()
            if mode == "on":
                # the query's REAL charge/release traffic feeds the
                # direct probe below
                events = _metrics.MEM_ACCOUNT_EVENTS.delta(ev0)
                max_events = max(max_events, events)
        assert rows["on"] == rows["off"], f"accounting perturbed {name}"
        for _ in range(pairs):
            for mode in ("off", "on"):
                c.execute(f"SET serene_mem_account = {mode}")
                t0 = time.perf_counter()
                c.execute(q)
                samples[mode].append(time.perf_counter() - t0)
        med = {p: statistics.median(s) for p, s in samples.items()}
        overhead = med["on"] / med["off"] - 1.0
        detail[name] = {"on_s": round(med["on"], 5),
                        "off_s": round(med["off"], 5),
                        "e2e_overhead_pct": round(overhead * 100, 2)}
        t_on_total += med["on"]
        t_off_total += med["off"]
    # direct decomposition: one accounted statement costs (accountant
    # setup + ACTIVE register + charge/release traffic + merge/totals +
    # retire); probe it at 4x the widest observed event count and
    # charge it against the FASTEST query's off-mode median (the worst
    # case for a fixed per-statement cost)
    reps = 200
    t0 = time.perf_counter()
    for _ in range(reps):
        acct = MemoryAccountant("bench probe", pid=0)
        ACTIVE.register(acct)
        for i in range(2 * max_events):     # 2x charge+release = 4x events
            acct.charge(i & 15, 4096)
            acct.release(i & 15, 4096)
        acct.add_progress(rows=1024, nbytes=8192, morsels=1)
        acct.merged()
        acct.totals()
        acct.event_count()
        ACTIVE.retire(acct)
    per_stmt_s = (time.perf_counter() - t0) / reps
    fastest_off = min(d["off_s"] for d in detail.values())
    direct = per_stmt_s / fastest_off
    _EXTRA["rows"] = n
    _EXTRA["detail"] = detail
    _EXTRA["per_statement_account_ms"] = round(per_stmt_s * 1e3, 4)
    _EXTRA["probe_events"] = 4 * max_events
    _EXTRA["overhead_pct"] = round(direct * 100, 3)
    _EXTRA["e2e_overhead_pct"] = round(
        (t_on_total / t_off_total - 1.0) * 100, 2)
    assert direct < 0.03, \
        f"accounting overhead over budget: {direct * 100:.2f}% (>3%)"
    return t_off_total / t_on_total


def bench_concurrency() -> float:
    """Workload governor (ISSUE 14): p50/p99 latency of SMALL dashboard
    queries while heavy scans run, fair-share + admission off vs on.

    Three heavy aggregate statements loop continuously over 2M rows
    (each keeps its map_ordered window of morsel tasks in the shared
    pool queue) while a fourth session runs 30 small aggregates; per
    small query the flight-recorder timeline yields its WIDEST pool
    queue-wait span. ASSERTED (the PR 5/PR 10 noise discipline: claim
    the decomposition, record the end to end): results bit-identical
    off vs on, and the small queries' p99 queue-wait DROPS with fair
    share on — under FIFO a small morsel provably waits behind every
    heavy morsel already queued, under stride picking it overtakes
    them. End-to-end p50/p99 latencies are recorded in the extra
    payload, not asserted. Returns wait_p99_off / wait_p99_on."""
    import statistics

    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable
    from serenedb_tpu.obs.trace import FLIGHT
    from serenedb_tpu.utils.config import REGISTRY

    rng = np.random.default_rng(23)
    n_heavy, n_small = 2_000_000, 30_000
    # an 8-worker pool regardless of host cores (set BEFORE first
    # get_pool()): the fair-share story is about deep per-statement
    # backlogs, and map_ordered windows in-flight tasks at
    # min(serene_workers, pool size) — a 2-worker floor pool on a
    # small box would cap every heavy statement at 2 queued morsels
    # and hide the starvation this shape measures
    REGISTRY.set_global("serene_workers", 8)
    db = Database()
    boot = db.connect()
    boot.execute("CREATE TABLE hv (k INT, v BIGINT)")
    boot.execute("CREATE TABLE sm (k INT, v BIGINT)")
    db.schemas["main"].tables["hv"] = MemTable("hv", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.integers(0, 1000, n_heavy).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(0, n_heavy, n_heavy, dtype=np.int64))}))
    db.schemas["main"].tables["sm"] = MemTable("sm", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.integers(0, 50, n_small).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(0, n_small, n_small, dtype=np.int64))}))

    HEAVY_Q = ("SELECT k, count(*), sum(v) FROM hv WHERE v % 7 <> 0 "
               "GROUP BY k")
    SMALL_Q = ("SELECT k, count(*), sum(v) FROM sm WHERE v % 3 <> 0 "
               "GROUP BY k ORDER BY k")

    def connect(morsel_rows):
        cc = db.connect()
        cc.execute("SET serene_device = 'cpu'")
        cc.execute(f"SET serene_morsel_rows = {morsel_rows}")
        cc.execute("SET serene_parallel_min_rows = 1024")
        cc.execute("SET serene_workers = 8")
        return cc

    quiet = connect(4096)
    oracle_small = quiet.execute(SMALL_Q).rows()
    oracle_heavy = quiet.execute(HEAVY_Q).rows()

    samples = 30

    def measure(governor_on: bool, mode: str):
        REGISTRY.set_global("serene_fair_share", governor_on)
        REGISTRY.set_global("serene_max_concurrent_statements",
                            8 if governor_on else 0)
        stop = threading.Event()
        heavy_rows = []
        heavy_errs = []

        def heavy_loop():
            # a dead heavy thread would let the A/B measure ZERO
            # contention and ledger a vacuous ratio — surface the
            # first failure instead of letting the excepthook eat it
            try:
                hc = connect(65536)     # ~30 multi-ms morsels per pass
                while not stop.is_set():
                    heavy_rows.append(hc.execute(HEAVY_Q).rows())
            except BaseException as e:  # noqa: BLE001 — re-raised below
                heavy_errs.append(e)

        threads = [threading.Thread(target=heavy_loop) for _ in range(3)]
        for t in threads:
            t.start()
        sc = connect(4096)
        sc.execute("SET serene_trace = on")
        # the dashboard session rides a high fair-share weight: its
        # morsels take ~10 picks per heavy-tag pick instead of an
        # equal 1-in-4 share (3 heavy tags dilute equal weights); a
        # no-op under FIFO, which is exactly the A/B this shape runs
        sc.execute("SET serene_priority = 1000")
        lat, waits = [], []
        rows = None
        try:
            time.sleep(0.2)             # heavy loops reach steady state
            for i in range(samples):
                marker = f"conc_{mode}_{i}"
                t0 = time.perf_counter()
                rows = sc.execute(
                    SMALL_Q.replace("GROUP BY",
                                    f"/* {marker} */ GROUP BY")).rows()
                lat.append(time.perf_counter() - t0)
                entry = next(e for e in reversed(FLIGHT.snapshot())
                             if marker in e["query"])
                spans = [s["end_ns"] - s["begin_ns"]
                         for s in entry["spans"]
                         if s["name"] == "queue_wait" and
                         s["cat"] == "pool"]
                waits.append(max(spans) / 1e9 if spans else 0.0)
        finally:
            stop.set()
            for t in threads:
                t.join()
        if heavy_errs:
            raise heavy_errs[0]
        assert heavy_rows, f"no heavy statements completed ({mode})"
        assert rows == oracle_small, f"small-query parity broke ({mode})"
        assert all(r == oracle_heavy for r in heavy_rows), \
            f"heavy-query parity broke ({mode})"
        return lat, waits, len(heavy_rows)

    def pcts(xs):
        s = sorted(xs)
        return (statistics.median(s), s[min(len(s) - 1,
                                            int(0.99 * len(s)))])

    try:
        lat_off, wait_off, heavy_off = measure(False, "off")
        lat_on, wait_on, heavy_on = measure(True, "on")
    finally:
        REGISTRY.set_global("serene_fair_share", True)
        REGISTRY.set_global("serene_max_concurrent_statements", 0)
    lat_p50_off, lat_p99_off = pcts(lat_off)
    lat_p50_on, lat_p99_on = pcts(lat_on)
    wait_p50_off, wait_p99_off = pcts(wait_off)
    wait_p50_on, wait_p99_on = pcts(wait_on)
    _EXTRA["heavy_rows"] = n_heavy
    _EXTRA["small_rows"] = n_small
    _EXTRA["samples"] = samples
    _EXTRA["heavy_statements"] = {"off": heavy_off, "on": heavy_on}
    _EXTRA["small_latency_ms"] = {
        "off": {"p50": round(lat_p50_off * 1e3, 2),
                "p99": round(lat_p99_off * 1e3, 2)},
        "on": {"p50": round(lat_p50_on * 1e3, 2),
               "p99": round(lat_p99_on * 1e3, 2)}}
    _EXTRA["small_queue_wait_ms"] = {
        "off": {"p50": round(wait_p50_off * 1e3, 2),
                "p99": round(wait_p99_off * 1e3, 2)},
        "on": {"p50": round(wait_p50_on * 1e3, 2),
               "p99": round(wait_p99_on * 1e3, 2)}}
    _EXTRA["parity"] = "identical"
    # the asserted decomposition: fair share bounds the widest wait
    assert wait_p99_on < wait_p99_off, \
        f"p99 queue wait did not drop: off={wait_p99_off:.4f}s " \
        f"on={wait_p99_on:.4f}s"
    return wait_p99_off / max(wait_p99_on, 1e-9)


def bench_result_cache() -> float:
    """Multi-tier query cache (ISSUE 5 tentpole): the host_agg filtered
    aggregate and the vectorized join at 1M rows through the engine with
    the result cache on. Measures the three latencies a cache story is
    made of — cold (first execution, stores), warm (served from cache),
    invalidated (a write bumped the publication, full re-execution) —
    plus the miss-path overhead: cache ON but invalidated-every-run vs
    cache OFF, alternating pairwise with per-mode medians (the
    profile_overhead methodology: single-digit deltas drown in scheduler
    drift under naive A/B). Returns the cold/warm speedup at the
    host_agg shape (≥10x asserted); extras carry per-shape latencies and
    the measured overhead (<3% asserted). Warm results are asserted
    bit-identical to cold ones."""
    import statistics

    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    rng = np.random.default_rng(41)
    n = 1_000_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE co (k INT, v BIGINT)")
    c.execute("CREATE TABLE cb (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["co"] = MemTable("co", Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 1000, n).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(-(10 ** 6), 10 ** 6, n, dtype=np.int64))}))
    db.schemas["main"].tables["cb"] = MemTable("cb", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.permutation(np.arange(n, dtype=np.int64))),
        "w": Column.from_numpy(
            rng.integers(0, 100, n, dtype=np.int64))}))
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_result_cache = on")
    queries = {
        "host_agg": ("SELECT k, count(*), sum(v) FROM co "
                     "WHERE v % 7 <> 0 GROUP BY k"),
        "join": ("SELECT count(*), sum(v + w) FROM co "
                 "JOIN cb ON co.v = cb.k"),
    }
    detail: dict[str, dict] = {}
    headline = None
    for name, q in queries.items():
        t0 = time.perf_counter()
        cold_rows = c.execute(q).rows()
        t_cold = time.perf_counter() - t0
        warm_samples = []
        for _ in range(9):
            t0 = time.perf_counter()
            rows = c.execute(q).rows()
            warm_samples.append(time.perf_counter() - t0)
            assert rows == cold_rows, f"warm hit diverged on {name}"
        t_warm = statistics.median(warm_samples)
        # invalidated: a write bumps the publication tuple → full rerun
        c.execute("INSERT INTO co VALUES (0, 1)")
        t0 = time.perf_counter()
        c.execute(q)
        t_inval = time.perf_counter() - t0
        detail[name] = {"cold_s": round(t_cold, 5),
                        "warm_s": round(t_warm, 6),
                        "invalidated_s": round(t_inval, 5),
                        "warm_speedup": round(t_cold / t_warm, 1)}
        if name == "host_agg":
            headline = t_cold / t_warm
    # miss-path overhead, measured by DIRECT DECOMPOSITION: on a miss
    # the cache adds exactly its probe legs (begin -> fast_lookup ->
    # prepare -> lookup -> store) around an otherwise unchanged
    # execution, so time those legs explicitly and ratio them against
    # the statement's own serial execution time. An end-to-end A/B
    # cannot resolve a 3% budget on this host: the SAME serial query
    # with the cache fully off swings +/-10% run to run (scheduler/
    # frequency drift), while the probe legs are deterministic
    # sub-millisecond work. Distinct tautology literals force every
    # probe through the full miss path (parse/plan excluded from the
    # timed region -- both arms pay those identically).
    import statistics as _stats

    from serenedb_tpu.cache.result import RESULT_CACHE
    from serenedb_tpu.sql import parser as _parser
    c.execute("SET serene_workers = 1")
    c.execute("SET serene_result_cache = off")
    qtext = ("SELECT k, count(*), sum(v) FROM co "
             "WHERE v % 7 <> 0 AND 424242 = 424242 GROUP BY k")
    exec_samples = []
    for i in range(7):
        t0 = time.perf_counter()
        res = c.execute(qtext.replace("424242", str(10 ** 6 + i)))
        exec_samples.append(time.perf_counter() - t0)
    exec_s = _stats.median(exec_samples)
    batch = res.batch
    c.execute("SET serene_result_cache = on")
    st0 = _parser.parse(qtext)[0]
    plan = c._plan(st0, [])
    reps = 50
    variants = [_parser.parse(qtext.replace("424242",
                                            str(2 * 10 ** 6 + r)))[0]
                for r in range(reps)]
    t0 = time.perf_counter()
    for stv in variants:
        probe = RESULT_CACHE.begin(c, stv, [], qtext)
        probe.fast_lookup()
        probe.prepare(plan)
        probe.lookup()
        probe.store(batch)
    probe_s = (time.perf_counter() - t0) / reps
    overhead = probe_s / exec_s
    _EXTRA["probe_ms"] = round(probe_s * 1000, 3)
    _EXTRA["miss_exec_ms"] = round(exec_s * 1000, 2)
    _EXTRA["rows"] = n
    _EXTRA["detail"] = detail
    _EXTRA["miss_overhead_pct"] = round(overhead * 100, 2)
    assert overhead < 0.03, \
        f"result-cache miss-path overhead over budget: " \
        f"{overhead * 100:.2f}% (>3%)"
    assert headline >= 10.0, \
        f"warm hits under-deliver: {headline:.1f}x (<10x) on host_agg"
    return headline


def bench_device_pipeline() -> float:
    """Fused device relational pipeline (ISSUE 7 tentpole): a 1M-row
    filter→join→agg chain through the engine, three ways — host oracle
    (`serene_device_fused = off`), cold fused dispatch (data caches
    cleared: key factorize + host→device upload + one dispatch), and
    device-cached repeat (publication-keyed columns resident: one
    dispatch, zero transfer). The build side is 200k permuted keys and
    the probe draws from a 2x keyspace (~50% hit rate, unclustered so
    zone maps can't prune — this measures the fused matching tier).
    Returns the host/device-cached speedup (>1x asserted: the cached
    repeat dispatch must beat the host path); extras carry all three
    latencies. Results are asserted bit-identical to the host oracle."""
    import statistics

    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec import device_pipeline as dp
    from serenedb_tpu.exec.tables import MemTable
    from serenedb_tpu.utils import metrics as _metrics

    rng = np.random.default_rng(53)
    npr, nb, keyspace = 1_000_000, 200_000, 400_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE dpp (jk BIGINT, g INT, v BIGINT)")
    c.execute("CREATE TABLE dpb (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["dpp"] = MemTable("dpp", Batch.from_pydict({
        "jk": Column.from_numpy(
            rng.integers(0, keyspace, npr, dtype=np.int64)),
        "g": Column.from_numpy(rng.integers(0, 16, npr).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(-1000, 1000, npr, dtype=np.int64))}))
    db.schemas["main"].tables["dpb"] = MemTable("dpb", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.permutation(np.arange(nb, dtype=np.int64))),
        "w": Column.from_numpy(
            rng.integers(0, 100, nb, dtype=np.int64))}))
    q = ("SELECT g, count(*), sum(v), sum(w) FROM dpp "
         "JOIN dpb ON dpp.jk = dpb.k WHERE v > 0 GROUP BY g ORDER BY g")

    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_device_fused = off")
    host_rows = c.execute(q).rows()
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        c.execute(q)
        samples.append(time.perf_counter() - t0)
    host_s = statistics.median(samples)

    c.execute("SET serene_device = 'tpu'")
    c.execute("SET serene_device_fused = on")
    off0 = _metrics.DEVICE_OFFLOADS.value
    fused_rows = c.execute(q).rows()          # compile warm-up + parity
    assert _metrics.DEVICE_OFFLOADS.value > off0, "fused path did not fire"
    assert fused_rows == host_rows, "fused pipeline diverged from host"
    # cold = DATA cold: publication-keyed device cache and the host-side
    # factorize cache cleared; the compiled program persists (the same
    # policy as device shapes: cold means upload, not recompile)
    dp.DEVICE_CACHE.clear()
    dp.clear_codes_cache()
    t0 = time.perf_counter()
    c.execute(q)
    cold_s = time.perf_counter() - t0
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        c.execute(q)
        samples.append(time.perf_counter() - t0)
    cached_s = statistics.median(samples)

    _EXTRA["rows"] = npr
    _EXTRA["host_s"] = round(host_s, 4)
    _EXTRA["cold_transfer_s"] = round(cold_s, 4)
    _EXTRA["device_cached_s"] = round(cached_s, 4)
    _EXTRA["cold_vs_cached"] = round(cold_s / cached_s, 2)
    headline = host_s / cached_s
    # the "one dispatch beats N host kernels" claim is a DEVICE claim:
    # on the CPU jit backend (dead-tunnel fallback, tier-1's platform)
    # a scatter-heavy XLA program can legitimately trail the optimized
    # numpy host path, so record the honest ratio instead of failing
    import jax
    if jax.default_backend() != "cpu":
        assert headline > 1.0, \
            f"device-cached dispatch loses to host: {headline:.2f}x"
    return headline


def bench_fused_admission() -> float:
    """Fused-tier admission widening (ISSUE 17 tentpole): the
    join-bearing slice of the sqllogic corpus runs twice — with the
    PR-7 admission walls restored (`serene_device_fused_ext = off`)
    and with extended admission on (string/FILTER/DISTINCT aggregates,
    outer joins, residual join predicates, chained agg→top-N) — and
    the admitted fraction of fused-eligible join→agg plans is read
    from the compile ledger's `fused`/`fused_chain` lookups vs the
    per-reason decline counters (the same numbers `sdb_device()`
    serves). Parity is implicit: every corpus file's expected output
    IS the host oracle's. A chained leg then proves whole-query
    residency: the warm repeat of ORDER BY count(*) LIMIT over a fused
    aggregate must move ZERO host→device bytes — the stage-1
    accumulators hand off to the top-N program inside HBM. Returns
    admitted_after / admitted_before (>1 ⇔ walls demolished)."""
    import glob as _glob

    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable
    from serenedb_tpu.obs import device as obs_device
    from serenedb_tpu.utils import metrics as _metrics

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)
    from tests.sqllogic_runner import run_test_file

    root = os.path.join(here, "tests", "sqllogic")
    files = sorted(
        _glob.glob(os.path.join(root, "*.test"))
        + _glob.glob(os.path.join(root, "any", "**", "*.test"),
                     recursive=True)
        + _glob.glob(os.path.join(root, "sdb", "**", "*.test"),
                     recursive=True))
    corpus = []
    for path in files:
        with open(path) as f:
            if "JOIN" in f.read():
                corpus.append(path)

    def counts() -> tuple[int, int]:
        fams = {p["family"]: p
                for p in obs_device.stats_section()["programs"]}
        admits = 0
        for fam in ("fused", "fused_chain"):
            f = fams.get(fam, {})
            admits += int(f.get("hits", 0)) + int(f.get("misses", 0))
        return admits, sum(obs_device.fused_declines().values())

    def run_corpus(ext_on: bool) -> tuple[int, int, float, int]:
        import tempfile
        a0, d0 = counts()
        fails = 0
        cwd = os.getcwd()
        for path in corpus:
            db = Database()
            try:
                with tempfile.TemporaryDirectory() as tmp:
                    os.chdir(tmp)   # relative COPY paths land here
                    conn = db.connect()
                    conn.execute("SET serene_device = 'tpu'")
                    conn.execute("SET serene_device_fused = on")
                    conn.execute("SET serene_device_fused_ext = "
                                 + ("on" if ext_on else "off"))
                    fails += len(run_test_file(conn, path, tmpdir=tmp))
            finally:
                os.chdir(cwd)
                db.close()
        a1, d1 = counts()
        admits, declines = a1 - a0, d1 - d0
        return admits, declines, admits / max(1, admits + declines), fails

    adm_b, dec_b, frac_b, fail_b = run_corpus(ext_on=False)
    adm_a, dec_a, frac_a, fail_a = run_corpus(ext_on=True)
    assert fail_b == 0 and fail_a == 0, \
        f"sqllogic corpus diverged under fused tier: {fail_b}/{fail_a}"
    assert adm_a > adm_b, \
        f"extended admission did not widen the tier: {adm_b} → {adm_a}"

    # chained leg: fused agg → top-N with the handoff in HBM
    rng = np.random.default_rng(71)
    npr, nb, keyspace = 200_000, 50_000, 100_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE fap (jk BIGINT, g INT, v BIGINT)")
    c.execute("CREATE TABLE fab (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["fap"] = MemTable("fap", Batch.from_pydict({
        "jk": Column.from_numpy(
            rng.integers(0, keyspace, npr, dtype=np.int64)),
        "g": Column.from_numpy(rng.integers(0, 64, npr).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(-1000, 1000, npr, dtype=np.int64))}))
    db.schemas["main"].tables["fab"] = MemTable("fab", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.permutation(np.arange(nb, dtype=np.int64))),
        "w": Column.from_numpy(
            rng.integers(0, 100, nb, dtype=np.int64))}))
    q = ("SELECT g, count(*) AS n, sum(v) FROM fap JOIN fab "
         "ON fap.jk = fab.k GROUP BY g ORDER BY n DESC LIMIT 5")
    c.execute("SET serene_device = 'tpu'")
    c.execute("SET serene_device_fused = on")
    chain0 = _metrics.REGISTRY.gauge("DeviceChainedStages").value
    c.execute("SET serene_device_fused = off")
    host = c.execute(q).rows()
    c.execute("SET serene_device_fused = on")
    dev = c.execute(q).rows()             # cold: uploads + two compiles
    assert dev == host, "chained agg→top-N diverged from host"
    assert _metrics.REGISTRY.gauge("DeviceChainedStages").value > chain0, \
        "chained device path did not fire"
    ups0 = _metrics.DEVICE_TRANSFERS_UP.value
    t0 = time.perf_counter()
    warm = c.execute(q).rows()            # warm: both stages in HBM
    warm_s = time.perf_counter() - t0
    assert warm == host
    ups1 = _metrics.DEVICE_TRANSFERS_UP.value
    assert ups1 == ups0, \
        f"warm chained repeat moved host→device bytes ({ups1 - ups0})"
    db.close()

    _EXTRA["corpus_files"] = len(corpus)
    _EXTRA["admitted_before"] = adm_b
    _EXTRA["declined_before"] = dec_b
    _EXTRA["admitted_frac_before"] = round(frac_b, 4)
    _EXTRA["admitted_after"] = adm_a
    _EXTRA["declined_after"] = dec_a
    _EXTRA["admitted_frac_after"] = round(frac_a, 4)
    _EXTRA["chained_warm_s"] = round(warm_s, 4)
    _EXTRA["chained_warm_uploads"] = int(ups1 - ups0)
    _EXTRA["parity"] = "identical"
    return frac_a / max(frac_b, 1e-9) if frac_b else float(adm_a)


def bench_search_batch() -> float:
    """Batched ragged search serving (ISSUE 8 tentpole): aggregate QPS of
    concurrent 2-term top-10 searches over the 1M-doc synthetic corpus,
    batched (`serene_search_batch = on`: concurrent queries coalesce
    through search/batcher.py into shared ragged scoring dispatches) vs
    serial dispatch (`= off`, the parity oracle), at 1/8/64 concurrent
    submitters. Per-query results are asserted BIT-identical between the
    modes (scores, doc ids, tie order). Returns the 64-concurrency QPS
    ratio (≥5x asserted on the host backend, where the ragged numpy
    accumulate replaces per-query score planes; on a real device the
    ratio reflects dispatch-RTT amortization and is recorded honestly)."""
    import threading as _threading

    import jax
    import numpy as np

    from serenedb_tpu.search.analysis import get_analyzer
    from serenedb_tpu.search.batcher import batched_topk
    from serenedb_tpu.search.query import parse_query
    from serenedb_tpu.search.searcher import MultiSearcher, SegmentSearcher
    from serenedb_tpu.utils import metrics as _metrics
    from serenedb_tpu.utils.config import REGISTRY as _settings

    an = get_analyzer("simple")
    n_docs = 1_000_000
    fi = _synth_posting_index(n_docs, 30_000, 12_000_000, 7)
    ms = MultiSearcher(an)
    ms.add_segment(SegmentSearcher(fi, an, n_docs), 0)
    terms = [f"w{100 + 13 * i:07d}" for i in range(128)]
    nodes = [parse_query(f"{terms[2 * i]} | {terms[2 * i + 1]}", an)
             for i in range(64)]

    def run_level(conc: int, on: bool, reps: int):
        _settings.set_global("serene_search_batch", on)
        results = [None] * len(nodes)
        bar = _threading.Barrier(conc)

        def worker(wi):
            bar.wait()
            for r in range(reps):
                for qi in range(wi, len(nodes), conc):
                    out, _ = batched_topk(ms, nodes[qi], 10, "bm25", 0,
                                          None)
                    if r == 0:
                        results[qi] = out

        ts = [_threading.Thread(target=worker, args=(i,))
              for i in range(conc)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        dt = time.perf_counter() - t0
        return reps * len(nodes) / dt, results

    import statistics as _stats

    # warm every compile bucket both modes will touch: serial per-query
    # shapes, the ragged contrib kernel's entry-count buckets, and the
    # coalesced batch sizes the 64-thread level produces
    run_level(1, False, 1)
    run_level(8, True, 1)
    run_level(64, True, 1)
    detail: dict[str, dict] = {}
    headline = None
    d0, q0 = (_metrics.SEARCH_BATCH_DISPATCHES.value,
              _metrics.SEARCH_BATCH_QUERIES.value)
    for conc in (1, 8, 64):
        # alternating pairs + per-mode medians (the profile_overhead
        # methodology): 64 GIL-thrashing threads swing a single serial
        # leg run-to-run far more than the batching effect under test
        pairs = 3 if conc == 64 else 2
        reps = 2 if conc >= 8 else 1
        on_s, off_s = [], []
        res_on = res_off = None
        for _ in range(pairs):
            qps_on, res_on = run_level(conc, True, reps)
            qps_off, res_off = run_level(conc, False, reps)
            on_s.append(qps_on)
            off_s.append(qps_off)
        for qi, (a, b) in enumerate(zip(res_on, res_off)):
            assert np.array_equal(a[0].view(np.uint32),
                                  b[0].view(np.uint32)) and \
                np.array_equal(a[1], b[1]), \
                f"batched result diverged from serial at conc={conc} " \
                f"query={qi}"
        qps_on = _stats.median(on_s)
        qps_off = _stats.median(off_s)
        detail[str(conc)] = {"qps_batched": round(qps_on, 1),
                             "qps_serial": round(qps_off, 1),
                             "ratio": round(qps_on / qps_off, 2)}
        if conc == 64:
            headline = qps_on / qps_off
    dn = _metrics.SEARCH_BATCH_DISPATCHES.value - d0
    _EXTRA["detail"] = detail
    _EXTRA["rows"] = n_docs
    _EXTRA["mean_batch"] = round(
        (_metrics.SEARCH_BATCH_QUERIES.value - q0) / max(dn, 1), 1)
    if jax.default_backend() == "cpu":
        assert headline >= 5.0, \
            f"batched serving under-delivers: {headline:.2f}x (<5x) at " \
            f"64 concurrent"
    return headline


def bench_paged_search() -> float:
    """Device-resident paged postings (ISSUE 16 tentpole): QPS of
    repeated coalesced ragged top-10 dispatches over the 1M-doc
    synthetic corpus at 1/8/64 queries per coalesced batch, page-
    resident (`serene_posting_pool = on`: warm batches score as ONE
    jitted gather-and-accumulate program over the pool's HBM page
    tables, uploading zero posting bytes) vs the host ragged path
    (`= off`, the parity oracle). Per-query results are asserted
    BIT-identical between the modes. Returns the 64-batch QPS ratio —
    recorded honestly on the CPU backend (the jitted gather competes
    with a numpy accumulate over host RAM there); on a real device the
    resident path must win (>1x asserted), because the oracle re-reads
    every posting from host memory per dispatch."""
    import statistics as _stats

    import jax
    import numpy as np

    from serenedb_tpu.search.analysis import get_analyzer
    from serenedb_tpu.search.posting_pool import POOL
    from serenedb_tpu.search.query import parse_query
    from serenedb_tpu.search.searcher import SegmentSearcher
    from serenedb_tpu.utils import metrics as _metrics
    from serenedb_tpu.utils.config import REGISTRY as _settings

    an = get_analyzer("simple")
    n_docs = 1_000_000
    fi = _synth_posting_index(n_docs, 30_000, 12_000_000, 7)
    seg = SegmentSearcher(fi, an, n_docs)
    terms = [f"w{100 + 13 * i:07d}" for i in range(128)]
    nodes = [parse_query(f"{terms[2 * i]} | {terms[2 * i + 1]}", an)
             for i in range(64)]

    def run_level(batch: int, on: bool, reps: int):
        _settings.set_global("serene_posting_pool", on)
        results = []
        t0 = time.perf_counter()
        for _ in range(reps):
            results = []
            for i in range(0, len(nodes), batch):
                results.extend(seg.topk_batch(nodes[i:i + batch], 10,
                                              ragged=True))
        dt = time.perf_counter() - t0
        return reps * len(nodes) / dt, results

    old = _settings.get_global("serene_posting_pool")
    try:
        # warm every bucket both modes touch: pool page residency +
        # batch descriptor memos + program compiles per batch size
        for batch in (1, 8, 64):
            run_level(batch, True, 1)
            run_level(batch, False, 1)
        d0 = _metrics.POSTING_POOL_DEVICE_QUERIES.value
        detail: dict[str, dict] = {}
        headline = None
        for batch in (1, 8, 64):
            on_s, off_s = [], []
            res_on = res_off = None
            for _ in range(2):    # alternating pairs + medians
                qps_on, res_on = run_level(batch, True, 1)
                qps_off, res_off = run_level(batch, False, 1)
                on_s.append(qps_on)
                off_s.append(qps_off)
            for qi, (a, b) in enumerate(zip(res_on, res_off)):
                assert np.array_equal(a[0].view(np.uint32),
                                      b[0].view(np.uint32)) and \
                    np.array_equal(a[1], b[1]), \
                    f"pool result diverged from host ragged at " \
                    f"batch={batch} query={qi}"
            qps_on = _stats.median(on_s)
            qps_off = _stats.median(off_s)
            detail[str(batch)] = {"qps_resident": round(qps_on, 1),
                                  "qps_host": round(qps_off, 1),
                                  "ratio": round(qps_on / qps_off, 2)}
            if batch == 64:
                headline = qps_on / qps_off
        assert _metrics.POSTING_POOL_DEVICE_QUERIES.value > d0, \
            "pool tier never engaged — bench measured host vs host"
        _EXTRA["detail"] = detail
        _EXTRA["rows"] = n_docs
        _EXTRA["pool"] = POOL.stats()
    finally:
        _settings.set_global("serene_posting_pool", old)
    if jax.default_backend() != "cpu":
        assert headline > 1.0, \
            f"resident paged scoring loses to host ragged: {headline:.2f}x"
    return headline


def bench_vector_search() -> float:
    """Vector retrieval subsystem (ISSUE 19 tentpole): knn top-10 QPS
    over a 100k x 256-d clustered corpus at 1/8/64 queries per
    coalesced dispatch — IVF cluster-probe (`nprobe = 8` of 64 lists:
    one jitted program gathers only the probed clusters' pages from the
    HBM region and exact-rescores the candidates) vs the device
    brute-force oracle (same program body, one all-rows list). The
    corpus is grid-quantized (entries k/16 with every squared-distance
    chain exact in f32 — see ops/vector.host_dist), so the probe path
    at `nprobe = lists` is asserted BIT-identical to the oracle: the
    probe tier is the exact path restricted to a candidate set, not an
    approximation of it. Returns the 64-batch probe/brute QPS ratio
    (work scales with probed clusters, so the probe path must win) and
    records recall@10 at the production nprobe in the detail."""
    import statistics as _stats

    import jax
    import jax.numpy as jnp
    import numpy as np

    from serenedb_tpu.ops import vector as vops
    from serenedb_tpu.search.ivf import IvfIndex, VecSegment
    from serenedb_tpu.search.vector_store import VPOOL
    from serenedb_tpu.utils import metrics as _metrics
    from serenedb_tpu.utils.config import REGISTRY as _settings

    rng = np.random.default_rng(7)
    n, dim, lists, nprobe, kk = 100_000, 256, 64, 8, 10
    # clustered grid corpus: centers k/16 (|k|<48) + noise k/16 (|k|<16)
    # keeps every coordinate a multiple of 2^-4 with |v| < 4 — products
    # are multiples of 2^-8 bounded by 16, and 256-dim sums stay far
    # under 2^24 such units, so device and host distance bits agree
    # regardless of FMA grouping
    centers = rng.integers(-48, 48, (lists, dim)).astype(np.float32)
    noise = rng.integers(-16, 16, (n, dim)).astype(np.float32)
    mat = (centers[rng.integers(0, lists, n)] + noise) / np.float32(16.0)
    # build the index straight from the matrix (100k INSERTs would
    # bench the ingest path, not the probe path)
    init = vops.init_centroids(mat, lists)
    cents = np.asarray(vops.kmeans_fit(
        jnp.asarray(vops.pad_rows(mat)), jnp.asarray(init), lists, 4))
    codes = np.asarray(vops.assign_clusters(
        jnp.asarray(vops.pad_rows(mat)), jnp.asarray(cents)))[:n]
    idx = IvfIndex(
        column="v", dim=dim, lists=lists, metric="l2", centroids=cents,
        segs=[VecSegment(mat, np.arange(n, dtype=np.int64), codes, lists)],
        num_rows=n, data_version=1)
    queries = (centers[rng.integers(0, lists, 64)]
               + rng.integers(-16, 16, (64, dim))) / np.float32(16.0)

    def run_level(batch: int, probe, reps: int):
        outs = []
        t0 = time.perf_counter()
        for _ in range(reps):
            outs = []
            for i in range(0, len(queries), batch):
                qs = queries[i:i + batch]
                if probe is None:
                    outs.append(idx.brute_search(qs, kk))
                else:
                    outs.append(idx.search(qs, kk, probe))
        dt = time.perf_counter() - t0
        return reps * len(queries) / dt, outs

    headline = None
    detail: dict[str, dict] = {}
    d0 = _metrics.VECTOR_SEARCH_DISPATCHES.value
    # 100k x 256-d = 6250 pages: widen the page budget past the 64 MiB
    # default so the probe path measures HBM-resident, not cold-upload
    old_pages = _settings.get_global("serene_vector_pages")
    _settings.set_global("serene_vector_pages", 8192)
    # full-probe parity gate: nprobe=lists probes every cluster, so the
    # probe program and the brute oracle must agree to the bit
    dq, rq = idx.search(queries, kk, lists)
    db, rb = idx.brute_search(queries, kk)
    assert np.array_equal(dq.view(np.uint32), db.view(np.uint32)) and \
        np.array_equal(rq, rb.astype(np.int64)), \
        "nprobe=lists diverged from the device brute-force oracle"
    brute_top = [set(rb[i][np.isfinite(db[i])].tolist())
                 for i in range(len(queries))]
    d8, r8 = idx.search(queries, kk, nprobe)
    got = sum(len(set(r8[i][np.isfinite(d8[i])].tolist()) & brute_top[i])
              for i in range(len(queries)))
    recall = got / max(sum(len(s) for s in brute_top), 1)
    assert recall >= 0.3, f"recall@10 collapsed: {recall:.2f}"
    for batch in (1, 8, 64):
        run_level(batch, nprobe, 1)    # warm compiles per batch size
        run_level(batch, None, 1)
        probe_s, brute_s = [], []
        for _ in range(2):    # alternating pairs + medians
            qps_p, _ = run_level(batch, nprobe, 1)
            qps_b, _ = run_level(batch, None, 1)
            probe_s.append(qps_p)
            brute_s.append(qps_b)
        qps_p = _stats.median(probe_s)
        qps_b = _stats.median(brute_s)
        detail[str(batch)] = {"qps_probe": round(qps_p, 1),
                              "qps_brute": round(qps_b, 1),
                              "ratio": round(qps_p / qps_b, 2)}
        if batch == 64:
            headline = qps_p / qps_b
    assert _metrics.VECTOR_SEARCH_DISPATCHES.value > d0, \
        "vector tier never dispatched — bench measured nothing"
    _EXTRA["detail"] = detail
    _EXTRA["rows"] = n
    _EXTRA["recall_at_10"] = round(recall, 4)
    _EXTRA["pool"] = VPOOL.stats()
    assert _EXTRA["pool"]["pages_used"] > 0, \
        "corpus never went HBM-resident — bench measured the cold path"
    _settings.set_global("serene_vector_pages", old_pages)
    VPOOL.clear()
    assert headline > 1.0, \
        f"cluster probe loses to brute force: {headline:.2f}x"
    return headline


def bench_shard_exec() -> float:
    """Sharded execution tier (ISSUE 9 tentpole): the 1M-row
    filter→join→agg chain through the engine at `serene_shards` 1/2/4 —
    shards=1 is the single fused dispatch (the parity oracle), shards=N
    runs the SAME fused program once per round-robin probe shard as
    concurrent pool tasks pinned across jax.devices(), with the build
    phase publication-cached and the exact integer cross-shard combine
    on host. Plus a search leg: 2-term top-10 WAND over a 1M-doc
    4-segment index with the segment set sharded. Every leg asserts
    results BIT-identical to shards=1; timing uses alternating pairs +
    medians (the profile_overhead methodology — this 2-core box swings
    serial legs run-to-run). Returns the best relational-leg speedup
    (≥1.5x asserted on the CPU backend: the shard fan-out must beat the
    single dispatch on at least one shard count)."""
    import statistics

    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    rng = np.random.default_rng(53)
    npr, nb, keyspace = 1_000_000, 200_000, 400_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE sp (jk BIGINT, g INT, v BIGINT)")
    c.execute("CREATE TABLE sb (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["sp"] = MemTable("sp", Batch.from_pydict({
        "jk": Column.from_numpy(
            rng.integers(0, keyspace, npr, dtype=np.int64)),
        "g": Column.from_numpy(rng.integers(0, 16, npr).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(-1000, 1000, npr, dtype=np.int64))}))
    db.schemas["main"].tables["sb"] = MemTable("sb", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.permutation(np.arange(nb, dtype=np.int64))),
        "w": Column.from_numpy(rng.integers(0, 100, nb, dtype=np.int64))}))
    q = ("SELECT g, count(*), sum(v), sum(w) FROM sp "
         "JOIN sb ON sp.jk = sb.k WHERE v > 0 GROUP BY g ORDER BY g")
    c.execute("SET serene_result_cache = off")
    c.execute("SET serene_device = 'tpu'")
    c.execute("SET serene_device_fused = on")
    c.execute("SET serene_morsel_rows = 131072")   # 8 probe blocks
    c.execute("SET serene_workers = 4")

    ref = None
    for sh in (1, 2, 4):                  # warm compiles + upload caches
        c.execute(f"SET serene_shards = {sh}")
        rows = c.execute(q).rows()
        if ref is None:
            ref = rows
        assert rows == ref, f"shards={sh} diverged from the oracle"
        c.execute(q)

    def once(sh):
        c.execute(f"SET serene_shards = {sh}")
        t0 = time.perf_counter()
        c.execute(q)
        return time.perf_counter() - t0

    detail: dict[str, dict] = {}
    best = 0.0
    for target in (2, 4):
        base_s, shard_s = [], []
        for _ in range(6):                # alternating pairs
            base_s.append(once(1))
            shard_s.append(once(target))
        b = statistics.median(base_s)
        s = statistics.median(shard_s)
        detail[f"join_agg_shards_{target}"] = {
            "single_s": round(b, 4), "sharded_s": round(s, 4),
            "speedup": round(b / s, 2)}
        best = max(best, b / s)
    c.execute("SET serene_shards = 1")

    # -- search leg: sharded segment sets, bit-exact merge ---------------
    from serenedb_tpu.search.analysis import get_analyzer
    from serenedb_tpu.search.query import parse_query
    from serenedb_tpu.search.searcher import MultiSearcher, SegmentSearcher
    from serenedb_tpu.utils.config import REGISTRY as _settings

    an = get_analyzer("simple")
    seg_docs = 250_000
    ms = MultiSearcher(an)
    for si in range(4):
        fi = _synth_posting_index(seg_docs, 20_000, 3_000_000, 11 + si)
        ms.add_segment(SegmentSearcher(fi, an, seg_docs), si * seg_docs)
    terms = [f"w{100 + 13 * i:07d}" for i in range(96)]
    nodes = [parse_query(f"{terms[2 * i]} | {terms[2 * i + 1]}", an)
             for i in range(48)]

    def run_search(sh, offset):
        _settings.set_global("serene_shards", sh)
        out = []
        t0 = time.perf_counter()
        for node in nodes[offset:offset + 16]:
            out.append(ms.cpu_topk(node, 10))
        return time.perf_counter() - t0, out

    # fragment tier OFF for the whole leg (it gates on the
    # serene_result_cache global): the parity loop runs every query at
    # every shard count, so with fragments on the timed passes would
    # measure cached-merge overhead instead of sharded WAND scoring
    rc_prior = _settings.get_global("serene_result_cache")
    _settings.set_global("serene_result_cache", False)
    try:
        # parity first: every query, shards 1 vs 2 vs 4
        _settings.set_global("serene_shards", 1)
        refs = [ms.cpu_topk(n, 10) for n in nodes]
        for sh in (2, 4):
            _settings.set_global("serene_shards", sh)
            for node, (rs, rd) in zip(nodes, refs):
                s2, d2 = ms.cpu_topk(node, 10)
                assert np.array_equal(s2.view(np.uint32),
                                      rs.view(np.uint32)) and \
                    np.array_equal(d2, rd), "sharded search diverged"
        # same slice both modes (fragments are off, so repeats re-score
        # fully), alternating pairs + medians like the relational leg
        t1s, t4s = [], []
        for _ in range(3):
            t1s.append(run_search(1, 0)[0])
            t4s.append(run_search(4, 0)[0])
        t1, t4 = statistics.median(t1s), statistics.median(t4s)
        detail["search_topk_shards_4"] = {
            "single_s": round(t1, 4), "sharded_s": round(t4, 4),
            "ratio": round(t1 / t4, 2)}
    finally:
        _settings.set_global("serene_shards", 1)
        _settings.set_global("serene_result_cache", rc_prior)

    _EXTRA["rows"] = npr
    _EXTRA["detail"] = detail
    _EXTRA["search_docs"] = 4 * seg_docs
    import jax
    if jax.default_backend() == "cpu" and (os.cpu_count() or 1) >= 2:
        # thread fan-out cannot beat serial on a single core — the
        # bar applies only where the host can actually overlap shards
        # (the test_parallel_exec single-worker-host skip idiom)
        assert best >= 1.5, \
            f"shard fan-out under-delivers: best {best:.2f}x (<1.5x)"
    return best


def bench_multichip() -> float:
    """In-program multi-chip combine (ISSUE 12 tentpole): the 1M-row
    filter→join→agg chain and a 1M-doc 4-segment search at
    `serene_shards` 1/2/4 over a 4-device virtual CPU mesh
    (xla_force_host_platform_device_count, armed by the harness for
    this shape), A/B-ing `serene_shard_combine=host` (PR 9's build +
    N probe dispatches + numpy combine) against `=device` (ONE
    shard_map-partitioned dispatch with psum/pmin/pmax reducing the
    integer accumulators in HBM; search merges with an in-program
    per-shard top-k + one all_gather hop). Every cell asserts results
    BIT-identical to shards=1; timing uses alternating pairs + medians
    (the profile_overhead methodology). The asserted facts follow the
    PR 5/PR 10 lesson — assert only what this host's timing noise
    cannot blur: the DISPATCH decomposition (device combine = exactly
    ONE offload per execution, host combine = one per shard) is
    asserted exactly, while the end-to-end shards=4 A/B is RECORDED,
    not asserted (measured 0.95-1.02x across runs on this shared
    1-core host — the paired-median estimator cannot stably resolve a
    ~1% effect under its ±3% drift, the exact trace_overhead lesson;
    a 1-core virtual mesh cannot show parallel speedup, so parity at
    1/4th the dispatches is the honest single-host result). Returns
    the shards=4 device-vs-host relational speedup."""
    import statistics

    import jax
    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    _EXTRA["mesh_devices"] = len(jax.devices())
    rng = np.random.default_rng(53)
    npr, nb, keyspace = 1_000_000, 200_000, 400_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE sp (jk BIGINT, g INT, v BIGINT)")
    c.execute("CREATE TABLE sb (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["sp"] = MemTable("sp", Batch.from_pydict({
        "jk": Column.from_numpy(
            rng.integers(0, keyspace, npr, dtype=np.int64)),
        "g": Column.from_numpy(rng.integers(0, 16, npr).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(-1000, 1000, npr, dtype=np.int64))}))
    db.schemas["main"].tables["sb"] = MemTable("sb", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.permutation(np.arange(nb, dtype=np.int64))),
        "w": Column.from_numpy(rng.integers(0, 100, nb, dtype=np.int64))}))
    # min/max ride pmin/pmax, count/sum the psum limb/direct paths
    q = ("SELECT g, count(*), sum(v), sum(w), min(w), max(v) FROM sp "
         "JOIN sb ON sp.jk = sb.k WHERE v > 0 GROUP BY g ORDER BY g")
    c.execute("SET serene_result_cache = off")
    c.execute("SET serene_device = 'tpu'")
    c.execute("SET serene_device_fused = on")
    c.execute("SET serene_morsel_rows = 131072")   # 8 probe blocks
    c.execute("SET serene_workers = 4")

    c.execute("SET serene_shards = 1")
    ref = c.execute(q).rows()
    for sh in (2, 4):                 # parity + warm compiles/uploads
        for combine in ("host", "device"):
            c.execute(f"SET serene_shards = {sh}")
            c.execute(f"SET serene_shard_combine = {combine}")
            rows = c.execute(q).rows()
            assert rows == ref, \
                f"shards={sh} combine={combine} diverged from the oracle"
            c.execute(q)

    # structural decomposition (deterministic): the in-program combine
    # is ONE dispatch where the host combine pays one per shard — the
    # replaced-dispatch claim, asserted exactly via the offload gauge
    from serenedb_tpu.utils import metrics as _metrics
    c.execute("SET serene_shards = 4")
    c.execute("SET serene_shard_combine = device")
    d0 = _metrics.DEVICE_OFFLOADS.value
    c.execute(q)
    assert _metrics.DEVICE_OFFLOADS.value - d0 == 1, \
        "device combine must execute as ONE collective dispatch"
    c.execute("SET serene_shard_combine = host")
    d0 = _metrics.DEVICE_OFFLOADS.value
    c.execute(q)
    host_dispatches = _metrics.DEVICE_OFFLOADS.value - d0
    assert host_dispatches >= 4, \
        "host combine should pay one probe dispatch per shard"
    _EXTRA["dispatches_per_exec"] = {"device": 1, "host": host_dispatches}

    def once(sh, combine):
        c.execute(f"SET serene_shards = {sh}")
        c.execute(f"SET serene_shard_combine = {combine}")
        t0 = time.perf_counter()
        c.execute(q)
        return time.perf_counter() - t0

    detail: dict[str, dict] = {}
    ratio4 = 0.0
    for target in (2, 4):
        hs, ds = [], []
        for _ in range(12):           # alternating pairs (the ~1%
            hs.append(once(target, "host"))   # effect needs a tight
            ds.append(once(target, "device"))  # median on this host)
        h = statistics.median(hs)
        d = statistics.median(ds)
        detail[f"join_agg_shards_{target}"] = {
            "host_combine_s": round(h, 4),
            "device_combine_s": round(d, 4),
            "speedup": round(h / d, 2)}
        if target == 4:
            ratio4 = h / d
    c.execute("SET serene_shards = 1")
    c.execute("SET serene_shard_combine = auto")

    # -- search leg: in-program per-shard top-k + all_gather merge -------
    from serenedb_tpu.search.analysis import get_analyzer
    from serenedb_tpu.search.query import parse_query
    from serenedb_tpu.search.searcher import MultiSearcher, SegmentSearcher
    from serenedb_tpu.utils.config import REGISTRY as _settings

    an = get_analyzer("simple")
    seg_docs = 250_000
    ms = MultiSearcher(an)
    for si in range(4):
        fi = _synth_posting_index(seg_docs, 20_000, 3_000_000, 11 + si)
        ms.add_segment(SegmentSearcher(fi, an, seg_docs), si * seg_docs)
    terms = [f"w{100 + 13 * i:07d}" for i in range(32)]
    nodes = [parse_query(f"{terms[2 * i]} | {terms[2 * i + 1]}", an)
             for i in range(16)]

    rc_prior = _settings.get_global("serene_result_cache")
    cb_prior = _settings.get_global("serene_shard_combine")
    _settings.set_global("serene_result_cache", False)
    try:
        _settings.set_global("serene_shards", 1)
        refs = [ms.cpu_topk(n, 10) for n in nodes]
        for sh in (2, 4):
            _settings.set_global("serene_shards", sh)
            for combine in ("host", "device"):
                _settings.set_global("serene_shard_combine", combine)
                for node, (rs, rd) in zip(nodes, refs):
                    s2, d2 = ms.cpu_topk(node, 10)
                    assert np.array_equal(s2.view(np.uint32),
                                          rs.view(np.uint32)) and \
                        np.array_equal(d2, rd), \
                        f"sharded search diverged ({sh}, {combine})"

        def run_search(combine):
            _settings.set_global("serene_shard_combine", combine)
            t0 = time.perf_counter()
            for node in nodes:
                ms.cpu_topk(node, 10)
            return time.perf_counter() - t0

        _settings.set_global("serene_shards", 4)
        th, td = [], []
        for _ in range(3):
            th.append(run_search("host"))
            td.append(run_search("device"))
        h, d = statistics.median(th), statistics.median(td)
        detail["search_topk_shards_4"] = {
            "host_combine_s": round(h, 4),
            "device_combine_s": round(d, 4),
            "ratio": round(h / d, 2)}
    finally:
        _settings.set_global("serene_shards", 1)
        _settings.set_global("serene_result_cache", rc_prior)
        _settings.set_global("serene_shard_combine", cb_prior)

    _EXTRA["rows"] = npr
    _EXTRA["search_docs"] = 4 * seg_docs
    _EXTRA["detail"] = detail
    # end-to-end ratio recorded, not asserted (docstring): the exact
    # structural claims — bit parity and the 1-vs-N dispatch
    # decomposition — were asserted above
    return ratio4


def bench_device_observe() -> float:
    """Device telemetry overhead budget (ISSUE 15, <3%): the 1M-row
    fused join (the device_pipeline shape's workload) with
    `serene_device_telemetry` on vs off. Results are asserted
    bit-identical and the end-to-end alternating-pairs medians are
    recorded per mode — but like trace/mem_overhead (the PR 5/PR 10
    noise lesson) a sub-percent delta drowns in host drift end to end,
    so the ASSERTED number is a direct per-DISPATCH decomposition: the
    measured cost of one warm dispatch's actual telemetry traffic
    (compile-ledger hit probe + per-device dispatch note + one
    upload note + one fetch note + the enabled() reads), times the
    query's observed dispatch/transfer counts, divided by the off-mode
    median. Extras also record the cold-compile vs warm-hit latency
    split of the fused program (program LRU cleared → first dispatch
    pays the XLA compile; the ledger's compile_ms is the measured
    stall). Returns t_off/t_on (≈1.0; 0.97 ⇔ 3% overhead)."""
    import statistics

    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec import device_pipeline as dp
    from serenedb_tpu.exec.tables import MemTable
    from serenedb_tpu.obs import device as obs_device
    from serenedb_tpu.utils import metrics as _metrics
    from serenedb_tpu.utils.config import REGISTRY as _settings

    rng = np.random.default_rng(67)
    npr, nb, keyspace = 1_000_000, 200_000, 400_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE dto (jk BIGINT, g INT, v BIGINT)")
    c.execute("CREATE TABLE dtb (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["dto"] = MemTable("dto", Batch.from_pydict({
        "jk": Column.from_numpy(
            rng.integers(0, keyspace, npr, dtype=np.int64)),
        "g": Column.from_numpy(rng.integers(0, 16, npr).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(-1000, 1000, npr, dtype=np.int64))}))
    db.schemas["main"].tables["dtb"] = MemTable("dtb", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.permutation(np.arange(nb, dtype=np.int64))),
        "w": Column.from_numpy(
            rng.integers(0, 100, nb, dtype=np.int64))}))
    c.execute("SET serene_device = 'tpu'")
    c.execute("SET serene_device_fused = on")
    c.execute("SET serene_result_cache = off")
    q = ("SELECT g, count(*), sum(v), sum(w) FROM dto "
         "JOIN dtb ON dto.jk = dtb.k WHERE v > 0 GROUP BY g ORDER BY g")

    old = _settings.get_global("serene_device_telemetry")
    try:
        # parity + warm-up (compile once, fill the data caches)
        _settings.set_global("serene_device_telemetry", True)
        rows_on = c.execute(q).rows()
        _settings.set_global("serene_device_telemetry", False)
        rows_off = c.execute(q).rows()
        assert rows_on == rows_off, "telemetry perturbed the fused join"

        # cold-compile vs warm-hit split (telemetry on so the ledger
        # measures the compile): program LRU cleared, data caches warm
        # → the delta IS the XLA compile stall
        _settings.set_global("serene_device_telemetry", True)
        obs_device.PROGRAMS.clear()
        t0 = time.perf_counter()
        c.execute(q)
        cold_s = time.perf_counter() - t0
        samples = []
        for _ in range(5):
            t0 = time.perf_counter()
            c.execute(q)
            samples.append(time.perf_counter() - t0)
        warm_s = statistics.median(samples)
        fused_fam = [r for r in obs_device.PROGRAMS.snapshot()
                     if r["family"] == "fused"]
        compile_ms = fused_fam[0]["compile_ms_total"] if fused_fam else 0.0

        # per-query telemetry event counts (warm regime)
        led0 = obs_device.LEDGER.snapshot()
        off0 = _metrics.DEVICE_OFFLOADS.value
        c.execute(q)
        led1 = obs_device.LEDGER.snapshot()
        dispatches = max(1, _metrics.DEVICE_OFFLOADS.value - off0)

        def total(snap, field):
            return sum(d[field] for d in snap.values())

        transfers = (total(led1, "transfers_up") -
                     total(led0, "transfers_up")) + \
            (total(led1, "transfers_down") - total(led0, "transfers_down"))

        # e2e alternating pairs, recorded not asserted
        pairs = 7
        e2e: dict[str, list[float]] = {"on": [], "off": []}
        for _ in range(pairs):
            for mode, flag in (("off", False), ("on", True)):
                _settings.set_global("serene_device_telemetry", flag)
                t0 = time.perf_counter()
                c.execute(q)
                e2e[mode].append(time.perf_counter() - t0)
        med = {m: statistics.median(s) for m, s in e2e.items()}

        # direct decomposition: one warm dispatch's telemetry traffic,
        # probed at the real call sites' granularity
        _settings.set_global("serene_device_telemetry", True)
        probe_key = ("bench_probe",)
        prog = obs_device.compiled("bench_probe", probe_key,
                                   lambda: (lambda x: x))
        reps = 2000
        t0 = time.perf_counter()
        for _ in range(reps):
            obs_device.compiled("bench_probe", probe_key,
                                lambda: (lambda x: x))   # ledger hit
            obs_device.LEDGER.note_dispatch((0,))
            obs_device.note_upload(4096, (0,), 1000)
            obs_device.note_fetch(4096, (0,), 1000)
        per_event_s = (time.perf_counter() - t0) / reps
        assert prog is not None
        per_query_s = per_event_s * max(dispatches, transfers, 1)
        direct = per_query_s / med["off"]
    finally:
        _settings.set_global("serene_device_telemetry", old)

    _EXTRA["rows"] = npr
    _EXTRA["dispatches_per_query"] = dispatches
    _EXTRA["transfers_per_query"] = transfers
    _EXTRA["cold_compile_s"] = round(cold_s, 4)
    _EXTRA["warm_hit_s"] = round(warm_s, 4)
    _EXTRA["cold_vs_warm"] = round(cold_s / max(warm_s, 1e-9), 2)
    _EXTRA["fused_compile_ms"] = compile_ms
    _EXTRA["per_dispatch_telemetry_ms"] = round(per_event_s * 1e3, 5)
    _EXTRA["overhead_pct"] = round(direct * 100, 3)
    _EXTRA["e2e_overhead_pct"] = round(
        (med["on"] / med["off"] - 1.0) * 100, 2)
    assert direct < 0.03, \
        f"device telemetry over budget: {direct * 100:.2f}% (>3%)"
    return med["off"] / med["on"]


def bench_production() -> float:
    """The production mixed-fleet macrobench (ISSUE 20): a realistic
    serving day against the asyncio front door — dashboard clients
    re-running the same aggregate (result-cache hits between writer
    invalidations), live-search clients on ES `_search`, writer clients
    alternating `_bulk` appends with SQL INSERTs that invalidate the
    dashboards' cached aggregate, and ONE background heavy scan with a
    varying literal (never cache-served). The whole fleet speaks real
    HTTP/1.1 keep-alive over loopback from a single-thread asyncio
    client, so 512 clients is 512 concurrent SOCKETS against the tier —
    the thing PR 20 exists to survive — not 512 Python threads.

    Per fleet size (8 / 64 / 512) the extras record client-observed
    p50/p99 latency and qps PER CLASS (the acceptance numbers), plus
    the gate's accept-wait p99 and pause/reject counters. Returns
    qps_512 / qps_8 — total-throughput retention as the connection
    count scales 64x; a thread-per-connection tier degrades here, an
    event-loop tier should hold near (or above) 1.0."""
    import asyncio
    import resource

    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable
    from serenedb_tpu.sched.governor import CONNGATE
    from serenedb_tpu.server.http_server import HttpServer
    from serenedb_tpu.utils import metrics as _m
    from serenedb_tpu.utils.config import REGISTRY

    # 512 clients = 1024+ fds in this one process; lift the soft limit
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < 4096:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(hard, 4096), hard))
        except (ValueError, OSError):
            pass

    REGISTRY.set_global("serene_device", "cpu")
    REGISTRY.set_global("serene_frontdoor", True)
    REGISTRY.set_global("serene_max_connections", 0)
    REGISTRY.set_global("serene_idle_conn_timeout_s", 0.0)

    rng = np.random.default_rng(20)
    n_dash, n_big = 200_000, 2_000_000
    db = Database()
    boot = db.connect()
    boot.execute("CREATE TABLE dash (k INT, v BIGINT)")
    boot.execute("CREATE TABLE big (k INT, v BIGINT)")
    db.schemas["main"].tables["dash"] = MemTable("dash", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.integers(0, 200, n_dash).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(0, n_dash, n_dash, dtype=np.int64))}))
    db.schemas["main"].tables["big"] = MemTable("big", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.integers(0, 1000, n_big).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(0, n_big, n_big, dtype=np.int64))}))

    srv = HttpServer(db, port=0)
    srv.start()
    port = srv.port
    words = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot",
             "golf", "hotel", "india", "juliet"]

    def _req(method, path, payload=b""):
        return (f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n\r\n"
                ).encode() + payload

    def _sql(q):
        return _req("POST", "/_sql",
                    json.dumps({"query": q}).encode())

    # seed the search corpus over the wire (the bulk path under test)
    seed_lines = []
    for i in range(2000):
        seed_lines.append(json.dumps(
            {"index": {"_index": "logs", "_id": str(i)}}))
        seed_lines.append(json.dumps(
            {"msg": " ".join(rng.choice(words, 6).tolist()),
             "n": int(i)}))
    import http.client
    hc = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    hc.request("POST", "/_bulk", "\n".join(seed_lines) + "\n",
               {"Content-Type": "application/x-ndjson"})
    r = hc.getresponse()
    r.read()
    assert r.status == 200

    DASH_Q = ("SELECT k, count(*), sum(v) FROM dash "
              "GROUP BY k ORDER BY k")

    # warm every class's cold path before any fleet measures: the
    # text index builds lazily on first search, the dashboard aggregate
    # pays its first (cache-miss) compute, the heavy scan compiles its
    # plan — none of that belongs in a serving percentile
    for w in words:
        hc.request("POST", "/logs/_search", json.dumps(
            {"query": {"match": {"msg": w}}, "size": 10}),
            {"Content-Type": "application/json"})
        r = hc.getresponse()
        r.read()
        assert r.status == 200
    for q in (DASH_Q, "SELECT count(*), sum(v % 11) FROM big "
                      "WHERE v % 13 <> 0"):
        hc.request("POST", "/_sql", json.dumps({"query": q}),
                   {"Content-Type": "application/json"})
        r = hc.getresponse()
        r.read()
        assert r.status == 200
    hc.close()

    class Cls:
        def __init__(self, name):
            self.name = name
            self.samples = []      # (t_done, latency_s)
            self.seq = 0

    async def _read_resp(reader):
        line = await reader.readline()
        if not line:
            raise ConnectionResetError
        status = int(line.split()[1])
        ln = 0
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            if h.lower().startswith(b"content-length"):
                ln = int(h.split(b":")[1])
        body = await reader.readexactly(ln) if ln else b""
        return status, body

    def build(cls, cid):
        if cls.name == "dashboard":
            return _sql(DASH_Q)
        if cls.name == "search":
            cls.seq += 1
            w = words[(cls.seq + cid) % len(words)]
            return _req("POST", "/logs/_search", json.dumps(
                {"query": {"match": {"msg": w}}, "size": 10}).encode())
        if cls.name == "writer":
            cls.seq += 1
            if cls.seq % 8:
                doc_id = f"w{cid}-{cls.seq}"
                nd = (json.dumps({"index": {"_index": "logs",
                                            "_id": doc_id}}) + "\n" +
                      json.dumps({"msg": " ".join(
                          words[(cls.seq + j) % len(words)]
                          for j in range(4)), "n": cls.seq}) + "\n")
                return _req("POST", "/_bulk", nd.encode())
            # every 8th write lands in `dash`, evicting the dashboards'
            # cached aggregate: the fleet's steady state is a MIX of
            # result-cache hits and real recomputes, like production
            return _sql(f"INSERT INTO dash VALUES "
                        f"({cls.seq % 200}, {cls.seq})")
        # heavy: varying literal defeats the result cache every time
        cls.seq += 1
        return _sql(f"SELECT count(*), sum(v % {11 + cls.seq % 7}) "
                    f"FROM big WHERE v % 13 <> {cls.seq % 13}")

    async def client(cls, cid, t_stop):
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port)
        except OSError:
            return
        try:
            while time.perf_counter() < t_stop:
                payload = build(cls, cid)
                t0 = time.perf_counter()
                writer.write(payload)
                await writer.drain()
                status, _body = await _read_resp(reader)
                t1 = time.perf_counter()
                if status == 200:
                    cls.samples.append((t1, t1 - t0))
        except (ConnectionResetError, asyncio.IncompleteReadError,
                OSError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, OSError):
                pass

    def pct(vals, q):
        if not vals:
            return None
        s = sorted(vals)
        return s[min(len(s) - 1, int(q * len(s)))]

    from serenedb_tpu.obs.statements import STATEMENTS, normalize
    dash_norm = normalize(DASH_Q)

    fleet_stats = {}
    measure_s, settle_s = 2.5, 0.5
    total_qps = {}
    for n_clients in (8, 64, 512):
        STATEMENTS.reset()
        classes = {n: Cls(n) for n in
                   ("dashboard", "search", "writer", "heavy")}
        # mixed fleet: 50% dashboards, ~30% search, ~10% writers,
        # ONE background heavy scan; remainder tops up search
        n_d = max(1, n_clients * 5 // 10)
        n_w = max(1, n_clients // 10)
        n_s = max(1, n_clients - n_d - n_w - 1)
        roster = (["dashboard"] * n_d + ["search"] * n_s +
                  ["writer"] * n_w + ["heavy"])

        async def fleet():
            t_stop = time.perf_counter() + settle_s + measure_s
            await asyncio.gather(*(
                client(classes[name], i, t_stop)
                for i, name in enumerate(roster)))

        t_start = time.perf_counter()
        asyncio.run(fleet())
        t_cut = t_start + settle_s
        per_class = {}
        n_total = 0
        for name, cls in classes.items():
            lats = [lat for (t, lat) in cls.samples if t >= t_cut]
            n_total += len(lats)
            per_class[name] = {
                "n": len(lats),
                "qps": round(len(lats) / measure_s, 1),
                "p50_ms": round((pct(lats, 0.50) or 0) * 1e3, 2),
                "p99_ms": round((pct(lats, 0.99) or 0) * 1e3, 2),
            }
        # the PR 10 statement histograms give the server-side view of
        # the SQL classes: the dashboard aggregate matches its exact
        # fingerprint, the heavy scan is the big-table fingerprint
        # (its varying literals collapse to `?` when normalized)
        for e in STATEMENTS.snapshot():
            if e["query"] == dash_norm:
                per_class["dashboard"]["stmt_p50_ms"] = e.get("p50_ms")
                per_class["dashboard"]["stmt_p99_ms"] = e.get("p99_ms")
            elif "from big" in e["query"]:
                per_class["heavy"]["stmt_p50_ms"] = e.get("p50_ms")
                per_class["heavy"]["stmt_p99_ms"] = e.get("p99_ms")
        fleet_stats[str(n_clients)] = per_class
        total_qps[n_clients] = n_total / measure_s
        print(f"  fleet={n_clients:4d}  total={n_total / measure_s:8.1f} "
              f"qps  dash p99="
              f"{per_class['dashboard']['p99_ms']:8.2f} ms  search p99="
              f"{per_class['search']['p99_ms']:8.2f} ms", flush=True)

    gate = CONNGATE.snapshot()
    wait_counts, _ = _m.ACCEPT_QUEUE_WAIT_HIST.snapshot()
    srv.stop()
    db.close()

    _EXTRA["fleet"] = fleet_stats
    _EXTRA["qps_8"] = round(total_qps[8], 1)
    _EXTRA["qps_64"] = round(total_qps[64], 1)
    _EXTRA["qps_512"] = round(total_qps[512], 1)
    _EXTRA["accepts"] = int(sum(wait_counts))
    _EXTRA["rejected_total"] = gate["rejected_total"]
    _EXTRA["pause_reads_total"] = gate["pause_reads_total"]
    # every class must have actually run at every fleet size — a silent
    # zero would ledger a vacuous mix
    for size, per_class in fleet_stats.items():
        for name, st in per_class.items():
            assert st["n"] > 0, f"class {name} starved at fleet {size}"
    return total_qps[512] / total_qps[8]


SHAPES = {
    "q1": bench_q1,
    "hits": bench_hits,
    "bm25": bench_bm25,
    "bm25_1m": bench_bm25_1m,
    "bm25_8m": bench_bm25_8m,
    "ingest": bench_ingest,
    "host_agg": bench_host_agg,
    "filter_scan": bench_filter_scan,
    "join": bench_join,
    "profile_overhead": bench_profile_overhead,
    "trace_overhead": bench_trace_overhead,
    "mem_overhead": bench_mem_overhead,
    "concurrency": bench_concurrency,
    "result_cache": bench_result_cache,
    "device_pipeline": bench_device_pipeline,
    "fused_admission": bench_fused_admission,
    "device_observe": bench_device_observe,
    "search_batch": bench_search_batch,
    "paged_search": bench_paged_search,
    "vector_search": bench_vector_search,
    "shard_exec": bench_shard_exec,
    "multichip": bench_multichip,
    "production": bench_production,
}

#: shapes whose ratio is a device-vs-CPU speedup and enters the headline
#: geomean; "ingest" is a host-side thread-scaling ratio, reported in
#: detail only.
HEADLINE_SHAPES = ("q1", "hits", "bm25", "bm25_1m", "bm25_8m")

#: shapes that never touch the device — they run even when the liveness
#: probe fails (a dead tunnel must not blind the round on host numbers)
#: device_pipeline rides along so a dead tunnel doesn't blind the round
#: on the fused-tier numbers, but its programs DO jit: the harness forces
#: JAX_PLATFORMS=cpu into its child when the probe failed (initializing
#: the tunneled backend with the tunnel down is a hard hang, see
#: _run_shape_child), and the >1x assert applies only on a real device
HOST_SHAPES = ("ingest", "host_agg", "filter_scan", "join",
               "profile_overhead", "trace_overhead", "mem_overhead",
               "concurrency", "result_cache", "device_pipeline",
               "fused_admission", "device_observe", "search_batch",
               "paged_search", "vector_search", "shard_exec", "multichip",
               "production")

#: host shapes that nevertheless run jitted programs — with the device
#: probe down their children must pin JAX_PLATFORMS=cpu, because
#: initializing the tunneled backend with the tunnel dead is a hard hang
JIT_HOST_SHAPES = ("device_pipeline", "fused_admission", "device_observe",
                   "search_batch", "paged_search", "vector_search",
                   "shard_exec", "multichip")

#: shapes that measure the in-program multi-chip combine: their child
#: always runs on a 4-device VIRTUAL cpu mesh
#: (xla_force_host_platform_device_count=4 + pinned cpu backend) — the
#: single tunneled chip can't provide a real data axis, and XLA parses
#: XLA_FLAGS once per process so the env must be set before the child
#: starts
VIRTUAL_MESH_SHAPES = ("multichip",)


# ------------------------------------------------------------- harness

#: side-channel for shapes to report extra metrics (HBM footprint, ...);
#: merged into the parent's detail dict as "<shape>_<key>"
_EXTRA: dict = {}


def _run_shape_child(name: str) -> None:
    """Child mode: run one shape, print its JSON result, exit."""
    try:
        import jax
        if os.environ.get("SDB_BENCH_FORCE_CPU") == "1":
            # test hook: sitecustomize overrides JAX_PLATFORMS, so force
            # the CPU backend explicitly (harness validation off-device)
            jax.config.update("jax_platforms", "cpu")
        # Persistent XLA compilation cache: "cold" means the DATA is cold
        # (upload + compress + factorize + first dispatch), not that the
        # binary recompiles — the reference's cold runs use a prebuilt
        # release build too (scripts/perf/run_hits_perf.sh).
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".jax_cache")
        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        except Exception:  # noqa: BLE001 — cache is an optimization only
            pass
        # every shape times the SUBSYSTEM it measures: the result cache
        # would legitimately serve the repeat executions without running
        # them, so it is off by default in bench children — the
        # result_cache shape turns it back on per session
        from serenedb_tpu.utils.config import REGISTRY as _sdb_settings
        _sdb_settings.set_global("serene_result_cache", False)
        speedup = SHAPES[name]()
        if name in HOST_SHAPES and name not in JIT_HOST_SHAPES:
            _EXTRA["platform"] = "host"
        else:
            # device shapes (and device_pipeline, which runs jitted
            # programs despite riding in HOST_SHAPES) already initialized
            # the backend, so this is a cache hit; calling it for host
            # shapes would *initialize* the tunneled backend — a hard
            # hang when the tunnel is down
            _EXTRA["platform"] = jax.default_backend()
        print(json.dumps({"shape": name, "speedup": round(speedup, 4),
                          "extra": _EXTRA}),
              flush=True)
    except Exception as e:  # noqa: BLE001 — report, don't crash silently
        print(json.dumps({"shape": name, "error": f"{type(e).__name__}: {e}"}),
              flush=True)
        sys.exit(1)


LEDGER_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_LEDGER.json")
_LOCK_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".bench.lock")
_STOP_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".ledger_stop")


def _acquire_bench_lock(wait_s: float):
    """One bench at a time on this machine: the opportunistic ledger loop
    and the round-end run must not contend for the single TPU (a ledger
    child holding the device would make the official probe fail and the
    round report stale numbers). Returns the held fd, or None."""
    import fcntl
    fd = os.open(_LOCK_PATH, os.O_CREAT | os.O_RDWR)
    deadline = time.monotonic() + wait_s
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return fd
        except OSError:
            if time.monotonic() >= deadline:
                os.close(fd)
                return None
            time.sleep(2.0)


def _load_ledger() -> dict:
    try:
        with open(LEDGER_PATH) as f:
            led = json.load(f)
        if isinstance(led, dict) and isinstance(led.get("entries"), dict):
            return led
        return {"entries": {}}
    except (OSError, json.JSONDecodeError):
        return {"entries": {}}


def _save_ledger(led: dict) -> None:
    tmp = f"{LEDGER_PATH}.{os.getpid()}.tmp"  # unique per writer
    with open(tmp, "w") as f:
        json.dump(led, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, LEDGER_PATH)  # last-writer-wins, never corrupt


def _git_head() -> str:
    try:
        r = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                           capture_output=True, text=True, timeout=10,
                           cwd=os.path.dirname(os.path.abspath(__file__)))
        return r.stdout.strip()
    except Exception:  # noqa: BLE001
        return ""


def _run_shape_subprocess(name: str, timeout_s: float,
                          force_cpu: bool = False) -> tuple[dict, str]:
    """Run one shape in a child process; returns (record, error).
    force_cpu pins the child to the CPU backend — required for shapes
    that jit (device_pipeline) when the device probe failed, because
    initializing the tunneled backend with the tunnel down is a hard
    hang, not an error."""
    env = None
    if force_cpu:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
    if name in VIRTUAL_MESH_SHAPES:
        env = dict(env or os.environ)
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=4").strip()
        env["JAX_PLATFORMS"] = "cpu"
        # sitecustomize silently overrides JAX_PLATFORMS; this makes the
        # child re-pin the cpu backend after the jax import
        env["SDB_BENCH_FORCE_CPU"] = "1"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--shape", name],
            capture_output=True, text=True, timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        # typed prefix — _infra_failure keys on it, never on stderr text
        return {}, "timeout: shape timed out (device hang mid-run?)"
    rec = None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):
            rec = parsed
            break
    if rec and isinstance(rec.get("speedup"), (int, float)) \
            and rec["speedup"] > 0:
        return rec, ""
    msg = (rec or {}).get("error") or r.stderr[-400:] or "no output"
    return {}, str(msg)


def ledger_main(shape_names: list[str]) -> None:
    """Opportunistic device-evidence capture: probe once (short), then run
    the requested shapes and persist every success into BENCH_LEDGER.json
    with a timestamp + git sha. Safe to run repeatedly in a loop during
    the round — each success overwrites that shape's entry with fresher
    evidence. Prints a one-line JSON status."""
    import datetime

    names = shape_names or list(SHAPES)
    bad = [n for n in names if n not in SHAPES]
    if bad:
        print(json.dumps({"ledger": "error", "unknown_shapes": bad}))
        sys.exit(2)
    if os.path.exists(_STOP_PATH):
        print(json.dumps({"ledger": "stopped", "reason": ".ledger_stop"}))
        sys.exit(4)
    lock = _acquire_bench_lock(0.0)
    if lock is None:
        print(json.dumps({"ledger": "busy",
                          "reason": "another bench holds the device lock"}))
        sys.exit(4)
    alive, _, err = _probe_device(75.0)
    if not alive:
        # host-only shapes don't need the device — capture them, but only
        # when the ledger entry is missing, stale (>6h) or from another
        # commit (each attempt costs real CPU on the build host)
        led = _load_ledger()["entries"]
        head = _git_head()

        def fresh(n: str) -> bool:
            try:
                if head and led[n].get("git") != head:
                    return False
                ts = datetime.datetime.fromisoformat(led[n]["ts"])
                age = datetime.datetime.now(datetime.timezone.utc) - ts
                return age.total_seconds() < 6 * 3600
            except (KeyError, TypeError, ValueError):
                return False

        host_stale = [n for n in names
                      if n in HOST_SHAPES and not fresh(n)]
        host_fresh = [n for n in names if n in HOST_SHAPES and fresh(n)]
        names = host_stale
        if not names:
            if host_fresh:
                # nonzero exit keeps the loop on the short retry cadence
                # so a tunnel-up moment is still caught quickly
                print(json.dumps({"ledger": "fresh", "skipped": host_fresh,
                                  "device_error": err}), flush=True)
                sys.exit(3)
            print(json.dumps({"ledger": "device-down", "error": err}),
                  flush=True)
            sys.exit(3)
    git = _git_head()
    updated, errors = [], {}
    for name in names:
        if os.path.exists(_STOP_PATH):  # round-end run preempts us
            errors[name] = "stopped: .ledger_stop appeared"
            break
        # cap below main()'s lock wait so an in-flight child can't make
        # the official run miss its preemption window
        rec, err = _run_shape_subprocess(
            name, 480.0,
            force_cpu=not alive and name in JIT_HOST_SHAPES)
        if not rec:
            errors[name] = err
            continue
        led = _load_ledger()  # reload each time: concurrent-writer safe
        led["entries"][name] = {
            "speedup": rec["speedup"],
            "extra": rec.get("extra") or {},
            "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
                timespec="seconds"),
            "git": git,
        }
        _save_ledger(led)
        updated.append(name)
    out = {"ledger": "ok" if updated else "no-results", "updated": updated}
    if errors:
        out["errors"] = errors
    if not alive:
        out["device"] = "down"
    print(json.dumps(out), flush=True)
    if not alive:
        # host-only capture with the device down: nonzero keeps the
        # retry loop on its short cadence so a tunnel-up moment is
        # caught within minutes, not an hour
        sys.exit(3)


def _probe_device(timeout_s: float = 75.0) -> tuple[bool, bool, str]:
    """(alive, transient, error) for a tiny dispatch on the default device.

    transient=True only for a timeout (plausible tunnel outage — worth a
    retry); a fast nonzero exit is an environment problem and fails fast,
    with the child's stderr tail surfaced."""
    force_cpu = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
                 if os.environ.get("SDB_BENCH_FORCE_CPU") == "1" else "")
    code = (force_cpu + "import jax.numpy as jnp; "
            "assert float(jnp.ones(8).sum()) == 8.0; print('ALIVE')")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, True, f"probe timed out after {timeout_s:.0f}s"
    if r.returncode == 0 and "ALIVE" in r.stdout:
        return True, False, ""
    return False, False, r.stderr.strip()[-400:] or "probe exited nonzero"


def main() -> None:
    budget = float(os.environ.get("SDB_BENCH_BUDGET_S", "1200"))
    deadline = time.monotonic() + budget
    t_start = time.monotonic()

    # The official run preempts the opportunistic ledger loop: signal it
    # to stop, then wait (bounded) for any in-flight ledger child to
    # release the single device before probing.
    try:
        with open(_STOP_PATH, "w") as f:
            f.write("round-end bench run\n")
    except OSError:
        pass
    # wait should exceed the ledger child timeout (480s) so an in-flight
    # ledger dispatch drains before we probe; with a small budget the
    # wait is clipped and a lock miss is surfaced in the output instead
    lock = _acquire_bench_lock(min(600.0, budget / 2))  # held till exit
    lock_missed = lock is None

    # 1. liveness: retry across a possible transient outage, but keep at
    # least ~2/3 of the budget for the shapes themselves; scale the probe
    # timeout down for small validation budgets
    probe_window_end = t_start + budget / 3
    probe_timeout = max(20.0, min(75.0, budget / 3))
    probes = 0
    alive = False
    probe_err = ""
    while time.monotonic() < probe_window_end:
        probes += 1
        alive, transient, probe_err = _probe_device(probe_timeout)
        if alive or not transient:
            break
        backoff = min(60.0, 10.0 * probes)
        if time.monotonic() + backoff >= probe_window_end:
            break
        time.sleep(backoff)

    results: dict[str, float] = {}
    extras: dict[str, float] = {}
    errors: dict[str, str] = {}
    stale_shapes: list[str] = []
    if lock_missed:
        errors["lock"] = ("bench lock busy past the wait window: a "
                          "ledger child may contend for the device")
    if not alive:
        errors["device"] = (
            f"device liveness probe failed {probes}x: {probe_err}")
    shape_floor = max(30.0, min(90.0, budget / 8))
    for name in SHAPES:
        if not alive and name not in HOST_SHAPES:
            continue  # covered by the "device" error + ledger fallback
        remaining = deadline - time.monotonic()
        if remaining < shape_floor:
            errors[name] = "skipped: bench budget exhausted"
            continue
        rec, err = _run_shape_subprocess(
            name, min(600.0, remaining),
            force_cpu=not alive and name in JIT_HOST_SHAPES)
        if rec:
            results[name] = float(rec["speedup"])
            for ek, ev in (rec.get("extra") or {}).items():
                extras[f"{name}_{ek}"] = ev
        else:
            errors[name] = err

    # Ledger fallback: a shape without a live result falls back to the
    # freshest opportunistic device run captured during the round
    # (bench.py --ledger), clearly marked stale — but ONLY when the live
    # attempt failed for infrastructure reasons (device unreachable,
    # hang/timeout, budget exhausted). A deterministic in-shape failure
    # (parity assertion, crash) means the CURRENT code is broken and must
    # not be papered over by an older passing number. Entries also expire
    # (default 24h) so a later blind round can't resurrect ancient runs.
    def _infra_failure(name: str) -> bool:
        if not alive:
            # host-only shapes ran live even with the device down — a
            # failure there is the current code's fault, not the tunnel's
            return name not in HOST_SHAPES
        e = errors.get(name, "")
        return e.startswith("timeout:") or e.startswith("skipped:")

    max_age_h = float(os.environ.get("SDB_BENCH_LEDGER_MAX_AGE_H", "24"))
    ledger = _load_ledger()["entries"]
    for name in SHAPES:
        if name in results or name not in ledger:
            continue
        if not _infra_failure(name):
            continue
        ent = ledger[name]
        if not isinstance(ent.get("speedup"), (int, float)):
            continue
        try:
            import datetime
            ts = datetime.datetime.fromisoformat(ent["ts"])
            age_h = (datetime.datetime.now(datetime.timezone.utc)
                     - ts).total_seconds() / 3600.0
            expiry = f"ledger entry expired: {age_h:.0f}h old"
        except (KeyError, TypeError, ValueError):
            age_h = float("inf")
            expiry = "ledger entry has no parsable timestamp"
        if age_h > max_age_h:
            base = errors.get(name) or "device unreachable"
            errors[name] = f"{base} [{expiry}]"
            continue
        results[name] = float(ent["speedup"])
        stale_shapes.append(name)
        for ek, ev in (ent.get("extra") or {}).items():
            extras[f"{name}_{ek}"] = ev
        extras[f"{name}_ledger_ts"] = ent.get("ts", "")
        extras[f"{name}_ledger_git"] = ent.get("git", "")

    headline = {k: v for k, v in results.items() if k in HEADLINE_SHAPES}
    if headline:
        logs = [math.log(v) for v in headline.values()]
        value = round(math.exp(sum(logs) / len(logs)), 3)
    else:
        value = 0.0
    out = {
        "metric": METRIC,
        "value": value,
        "unit": "x",
        "vs_baseline": value,
        "detail": {**{f"{k}_speedup": v for k, v in results.items()},
                   **extras},
    }
    if stale_shapes:
        out["stale"] = True
        out["stale_shapes"] = stale_shapes
    if errors:
        out["errors"] = errors
        if results:
            out["partial"] = True
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--shape":
        _run_shape_child(sys.argv[2])
    elif len(sys.argv) >= 2 and sys.argv[1] == "--ledger":
        ledger_main(sys.argv[2:])
    else:
        try:
            main()
        except Exception as e:  # noqa: BLE001 — the one JSON line is a contract
            print(json.dumps({
                "metric": METRIC, "value": 0.0, "unit": "x",
                "vs_baseline": 0.0,
                "errors": {"harness": f"{type(e).__name__}: {e}"},
            }), flush=True)
            sys.exit(0)
