"""Benchmark driver: prints ONE JSON line with the headline metric.

Two flagship shapes from BASELINE.md, measured on whatever jax device is
available (real TPU under the driver):

1. ClickBench-Q1-shaped aggregate: SELECT count(*), sum(x) WHERE filter over
   a synthetic 10M-row table — device path vs the engine's own CPU path.
2. BM25 top-10 over a synthetic corpus (100k docs) — device block-scoring
   QPS vs the CPU reference scorer on the same index.

value = geometric mean speedup (device vs single-socket CPU paths);
vs_baseline = the same ratio (the BASELINE.json targets are 3x / 2x on these
two shapes respectively).
"""

from __future__ import annotations

import json
import math
import sys
import time

import numpy as np


def bench_q1() -> float:
    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.engine import Database
    from serenedb_tpu.exec.tables import MemTable

    rng = np.random.default_rng(0)
    n = 10_000_000
    db = Database()
    c = db.connect()
    batch = Batch.from_pydict({
        "adv": Column.from_numpy(
            rng.choice(np.array([0, 0, 0, 0, 1, 2, 3], dtype=np.int32), n)),
        "region": Column.from_numpy(rng.integers(0, 200, n).astype(np.int32)),
        "x": Column.from_numpy(
            rng.integers(0, 100000, n).astype(np.int32)),
    })
    db.schemas["main"].tables["hits"] = MemTable("hits", batch)
    queries = [
        "SELECT count(*) FROM hits WHERE adv <> 0",
        "SELECT count(*), sum(x) FROM hits WHERE adv <> 0 AND x < 90000",
        "SELECT region, count(*), sum(x) FROM hits GROUP BY region",
    ]

    def run_all():
        return [tuple(c.execute(q).rows()) for q in queries]

    c.execute("SET serene_device = 'cpu'")
    run_all()
    t0 = time.perf_counter()
    cpu_res = run_all()
    t_cpu = time.perf_counter() - t0

    c.execute("SET serene_device = 'tpu'")
    run_all()  # compile + upload
    t0 = time.perf_counter()
    dev_res = run_all()
    t_dev = time.perf_counter() - t0
    assert cpu_res == dev_res, "device/CPU result mismatch in Q1 bench"
    return t_cpu / t_dev


def bench_bm25() -> float:
    from serenedb_tpu.search.analysis import get_analyzer
    from serenedb_tpu.search.query import parse_query
    from serenedb_tpu.search.searcher import SegmentSearcher
    from serenedb_tpu.search.segment import build_field_index

    rng = np.random.default_rng(1)
    vocab = [f"w{i}" for i in range(2000)]
    zipf = rng.zipf(1.3, size=4_000_000) % len(vocab)
    n_docs = 100_000
    lens = rng.integers(8, 40, n_docs)
    docs = []
    pos = 0
    for ln in lens:
        docs.append(" ".join(vocab[z] for z in zipf[pos:pos + ln]))
        pos += ln
    an = get_analyzer("simple")
    fi = build_field_index(docs, an)
    searcher = SegmentSearcher(fi, an, n_docs)

    # benchmark-game-style query set: single terms across the frequency
    # spectrum, 2-term disjunctions, 2-term conjunctions (256 queries)
    idxs = [1 + 3 * i for i in range(128)]
    qterms = [vocab[i] for i in idxs]
    queries = ([parse_query(t, an) for t in qterms] +
               [parse_query(f"{a} | {b}", an)
                for a, b in zip(qterms[::2], qterms[1::2])] +
               [parse_query(f"{a} & {b}", an)
                for a, b in zip(qterms[1::2], qterms[::2])])

    # warmup/compile — the QPS regime batches queries per dispatch
    searcher.topk_batch(queries, 10)
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        searcher.topk_batch(queries, 10)
    t_dev = time.perf_counter() - t0
    qps_dev = reps * len(queries) / t_dev

    t0 = time.perf_counter()
    for q in queries[:64]:
        match = searcher.eval_filter(q)
        tids = searcher.scoring_terms(q)
        searcher._cpu_score(match, tids, 10)
    t_cpu = time.perf_counter() - t0
    qps_cpu = 64 / t_cpu
    return qps_dev / qps_cpu


def _watchdog(seconds: int = 480):
    """The tunneled TPU can hang a dispatch indefinitely; the driver must
    still get its one JSON line. A stuck main thread can't be interrupted,
    so the watchdog prints an error record and hard-exits."""
    import os
    import threading

    def fire():
        print(json.dumps({
            "metric": "geomean device-vs-CPU speedup (ClickBench-Q1 agg, "
                      "BM25 top-10 QPS); result parity asserted",
            "value": 0.0,
            "unit": "x",
            "vs_baseline": 0.0,
            "error": f"device unresponsive for {seconds}s (tunnel outage?)",
        }), flush=True)
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def main():
    timer = _watchdog()
    s_q1 = bench_q1()
    s_bm = bench_bm25()
    timer.cancel()
    geomean = math.sqrt(s_q1 * s_bm)
    print(json.dumps({
        "metric": "geomean device-vs-CPU speedup (ClickBench-Q1 agg, BM25 "
                  "top-10 QPS); result parity asserted",
        "value": round(geomean, 3),
        "unit": "x",
        "vs_baseline": round(geomean, 3),
        "detail": {"q1_speedup": round(s_q1, 3),
                   "bm25_qps_ratio": round(s_bm, 3)},
    }))


if __name__ == "__main__":
    main()
