"""Workload governor (ISSUE 14): admission control, fair-share
scheduling, and enforced per-query memory budgets.

The contract under test: the governor steers WHEN statements run —
admission queueing (state 'queued', Admission/AdmissionQueue wait
event, queue_wait trace spans, SQLSTATE 53300 on queue overflow),
fair-share morsel picking (serene_fair_share / serene_priority), and
cooperative budget aborts (serene_work_mem → 53200,
serene_statement_timeout_ms → 57014 through the cancellation drain) —
but never WHAT they return: results are bit-identical with the
governor on or off at any worker/shard count (the deterministic merge
sinks), asserted by the parity matrix and the concurrent-burst
oracle. The ROADMAP's stated check rides along: a starved small
query's pool queue-wait is VISIBLE in the flight recorder with
fair-share off and bounded with it on.
"""

import threading
import time

import numpy as np
import pytest

from serenedb_tpu import errors
from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.exec.tables import MemTable
from serenedb_tpu.obs.resources import ACTIVE
from serenedb_tpu.obs.trace import FLIGHT
from serenedb_tpu.sched.governor import (CURRENT_SCHED, GOVERNOR,
                                         admission_exempt)
from serenedb_tpu.utils import metrics
from serenedb_tpu.utils.config import REGISTRY, parse_memory_bytes


class _globals:
    """Set registry globals for one test, restoring previous values on
    exit — the suite must leave the process-wide governor unarmed for
    whatever runs next (and must not clobber the verify_tier1.sh env
    hooks' values beyond its own scope)."""

    def __init__(self, **kv):
        self.kv = kv
        self.prev = {}

    def __enter__(self):
        for k, v in self.kv.items():
            self.prev[k] = REGISTRY.get_global(k)
            REGISTRY.set_global(k, v)
        return self

    def __exit__(self, *exc):
        for k, v in self.prev.items():
            REGISTRY.set_global(k, v)
        return False


def _db(n=40_000, seed=7):
    rng = np.random.default_rng(seed)
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE facts (k INT, v BIGINT)")
    c.execute("CREATE TABLE dims (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["facts"] = MemTable("facts", Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 50, n).astype(np.int32)),
        "v": Column.from_numpy(rng.integers(0, n, n, dtype=np.int64))}))
    db.schemas["main"].tables["dims"] = MemTable("dims", Batch.from_pydict({
        "k": Column.from_numpy(np.arange(n, dtype=np.int64)),
        "w": Column.from_numpy(rng.integers(0, 9, n, dtype=np.int64))}))
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_morsel_rows = 4096")
    c.execute("SET serene_parallel_min_rows = 1024")
    # pool-engaged regardless of host core count (a 1-core box would
    # otherwise default serene_workers to 1 = inline execution, and
    # the scheduling tests would never touch the shared pool)
    c.execute("SET serene_workers = 4")
    return db, c


AGG_Q = ("SELECT k, count(*), sum(v) FROM facts WHERE v % 3 <> 0 "
         "GROUP BY k ORDER BY k")
JOIN_Q = ("SELECT count(*), sum(v + w) FROM facts "
          "JOIN dims ON facts.v = dims.k")


# -- satellite: PG-style memory units ----------------------------------------


def test_memory_unit_parsing():
    assert parse_memory_bytes(12345) == 12345
    assert parse_memory_bytes("4096") == 4096
    assert parse_memory_bytes("64MB") == 64 << 20
    assert parse_memory_bytes("1GB") == 1 << 30
    assert parse_memory_bytes("512kB") == 512 << 10
    assert parse_memory_bytes("2TB") == 2 << 40
    assert parse_memory_bytes("100B") == 100
    assert parse_memory_bytes(" 8 mb ") == 8 << 20
    for bad in ("64XB", "-1MB", "MB", "1.5GB", ""):
        with pytest.raises(ValueError):
            parse_memory_bytes(bad)


def test_memory_units_via_set_and_catalog():
    db, c = _db(n=1000)
    c.execute("SET serene_work_mem = '64MB'")
    assert c.settings.get("serene_work_mem") == 64 << 20
    c.execute("SET serene_work_mem = 1048576")
    assert c.settings.get("serene_work_mem") == 1 << 20
    with pytest.raises(Exception):
        c.execute("SET serene_work_mem = '64XB'")
    rows = c.execute("SELECT setting FROM pg_settings "
                     "WHERE name = 'serene_work_mem'").rows()
    # session override never leaks globally (the global may itself be
    # armed by the verify_tier1.sh SERENE_WORK_MEM env hook)
    assert rows == [(str(REGISTRY.get_global("serene_work_mem")),)]


# -- parity: the governor never changes a result -----------------------------


@pytest.mark.parametrize("workers", [1, 4])
@pytest.mark.parametrize("shards", [1, 4])
def test_parity_matrix_governor_on_off(workers, shards):
    """Bit-identity across governor off vs armed (admission limit +
    fair share + generous budget) at any worker/shard count."""
    db, c = _db()
    c.execute(f"SET serene_workers = {workers}")
    c.execute(f"SET serene_shards = {shards}")
    got = {}
    for mode in ("off", "on"):
        arm = {"serene_max_concurrent_statements": 2 if mode == "on" else 0,
               "serene_fair_share": mode == "on"}
        with _globals(**arm):
            if mode == "on":
                c.execute("SET serene_work_mem = '1GB'")
                c.execute("SET serene_priority = 7")
            else:
                c.execute("RESET serene_work_mem")
                c.execute("RESET serene_priority")
            got[mode] = (c.execute(AGG_Q).rows(), c.execute(JOIN_Q).rows())
    assert got["on"] == got["off"]


def test_concurrent_burst_parity_under_admission():
    """Eight concurrent sessions through a max=2 governor with fair
    share on: every result equals the serial oracle — admission order
    and interleaved morsel picking perturb nothing."""
    db, c = _db()
    oracle = {"agg": c.execute(AGG_Q).rows(), "join": c.execute(JOIN_Q).rows()}
    results, errs = [], []

    def session():
        try:
            cc = db.connect()
            cc.execute("SET serene_device = 'cpu'")
            cc.execute("SET serene_morsel_rows = 4096")
            cc.execute("SET serene_parallel_min_rows = 1024")
            cc.execute("SET serene_workers = 4")
            results.append(("agg", cc.execute(AGG_Q).rows()))
            results.append(("join", cc.execute(JOIN_Q).rows()))
        except BaseException as e:      # noqa: BLE001 — surfaced below
            errs.append(e)

    with _globals(serene_max_concurrent_statements=2,
                  serene_fair_share=True):
        ts = [threading.Thread(target=session) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert not errs, errs
    assert len(results) == 16
    for kind, rows in results:
        assert rows == oracle[kind]
    snap = GOVERNOR.snapshot()
    assert snap["running"] == 0 and snap["queued"] == 0


# -- admission queue: state, wait event, span, overflow, cancel --------------


def test_queued_state_wait_event_span_and_gauges():
    """While a statement waits for admission it shows state 'queued'
    with an Admission/AdmissionQueue wait event (readable via SQL from
    an exempt catalog query), the Admission gauges move, and the wait
    lands in the statement's timeline as a queue_wait/admission span."""
    db, c = _db(n=2000)
    base = metrics.REGISTRY.snapshot()
    with _globals(serene_max_concurrent_statements=1):
        blocker = GOVERNOR.admit(c, "blocker")
        cb = db.connect()
        cb.execute("SET serene_device = 'cpu'")
        marker = "queued_span_probe"
        done = threading.Event()
        out = []

        def run():
            out.append(cb.execute(
                f"SELECT count(*) /* {marker} */ FROM facts").rows())
            done.set()

        t = threading.Thread(target=run)
        t.start()
        observer = db.connect()
        seen = []
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            rows = observer.execute(
                "SELECT state, wait_event_type, wait_event "
                "FROM pg_stat_activity "
                f"WHERE pid = {cb._session_id}").rows()
            if rows and rows[0][0] == "queued":
                seen.append(rows[0])
                break
            time.sleep(0.002)
        assert seen == [("queued", "Admission", "AdmissionQueue")]
        live = GOVERNOR.snapshot()
        assert live["running"] == 1 and live["queued"] == 1
        assert metrics.ADMISSION_QUEUE_DEPTH.value >= 1
        GOVERNOR.release(blocker)
        t.join()
        assert done.is_set() and out == [[(2000,)]]
    assert metrics.ADMISSION_QUEUED.delta(base["AdmissionQueued"]) >= 1
    assert metrics.ADMISSION_WAIT_NS.delta(base["AdmissionWaitNs"]) > 0
    sess = db.sessions[cb._session_id]
    assert sess["state"] == "idle"
    assert sess["wait_event"] is None
    entry = next(e for e in reversed(FLIGHT.snapshot())
                 if marker in e["query"])
    spans = [s for s in entry["spans"]
             if s["name"] == "queue_wait" and s["cat"] == "admission"]
    assert spans, "admission queue wait must land in the timeline"
    assert spans[0]["end_ns"] > spans[0]["begin_ns"]


def test_admission_queue_overflow_rejects_53300():
    db, c = _db(n=2000)
    base_rej = metrics.ADMISSION_REJECTED.value
    with _globals(serene_max_concurrent_statements=1,
                  serene_admission_queue_depth=1):
        blocker = GOVERNOR.admit(c, "blocker")
        cb = db.connect()
        done = threading.Event()
        t = threading.Thread(target=lambda: (
            cb.execute("SELECT count(*) FROM facts"), done.set()))
        t.start()
        deadline = time.monotonic() + 5.0
        while GOVERNOR.snapshot()["queued"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        cc = db.connect()
        with pytest.raises(errors.SqlError) as ei:
            cc.execute("SELECT count(*) FROM dims")
        assert ei.value.sqlstate == "53300"
        GOVERNOR.release(blocker)
        t.join()
        assert done.is_set()
    assert metrics.ADMISSION_REJECTED.delta(base_rej) == 1
    # the rejected session is usable immediately (no poisoned state)
    assert cc.execute("SELECT count(*) FROM dims").rows() == [(2000,)]


def test_cancel_and_timeout_fire_while_queued():
    """A queued statement honors CancelRequest and the statement
    timeout exactly like a running one — and leaves the queue."""
    db, c = _db(n=2000)
    with _globals(serene_max_concurrent_statements=1):
        blocker = GOVERNOR.admit(c, "blocker")
        # -- cancel
        cb = db.connect()
        errs = []
        t = threading.Thread(target=lambda: (
            _expect_sqlstate(errs, cb, "SELECT count(*) FROM facts")))
        t.start()
        _wait_for(lambda: GOVERNOR.snapshot()["queued"] >= 1)
        cb.request_cancel()
        t.join()
        assert errs == ["57014"]
        # -- timeout
        cd = db.connect()
        cd.execute("SET serene_statement_timeout_ms = 40")
        errs2 = []
        t2 = threading.Thread(target=lambda: (
            _expect_sqlstate(errs2, cd, "SELECT count(*) FROM facts")))
        t2.start()
        t2.join(timeout=10)
        assert errs2 == ["57014"]
        assert GOVERNOR.snapshot()["queued"] == 0
        GOVERNOR.release(blocker)


def _expect_sqlstate(sink, conn, q):
    try:
        conn.execute(q)
        sink.append("no error")
    except errors.SqlError as e:
        sink.append(e.sqlstate)


def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, "condition never held"
        time.sleep(0.002)


def test_nested_statement_while_portal_holds_slot():
    """A session interleaving a statement with its own suspended
    streaming portal cannot deadlock itself at max=1: the nested
    statement rides the connection's held slot."""
    from serenedb_tpu.sql import parser
    db, c = _db(n=4000)
    with _globals(serene_max_concurrent_statements=1):
        st = parser.parse("SELECT k, v FROM facts")[0]
        names, types, gen = c.execute_streaming(st, [],
                                                sql_text="SELECT k, v "
                                                         "FROM facts")
        first = next(gen)               # portal open, slot held
        assert first.num_rows > 0
        assert c.execute("SELECT count(*) FROM dims").rows() == [(4000,)]
        gen.close()
        snap = GOVERNOR.snapshot()
        assert snap["running"] == 0 and snap["queued"] == 0


def test_out_of_order_release_keeps_slot_occupied():
    """The governor slot follows the connection's LAST outstanding
    hold: releasing the first-admitted (slot-carrying) portal while a
    nested portal still executes must NOT free the slot — else two
    non-exempt statements run at max=1."""
    from serenedb_tpu.sql import parser
    db, c = _db(n=4000)
    with _globals(serene_max_concurrent_statements=1):
        st = parser.parse("SELECT k, v FROM facts")[0]
        _, _, g1 = c.execute_streaming(st, [], sql_text="SELECT k, v "
                                                        "FROM facts")
        next(g1)                        # P1: non-nested ticket, slot
        st2 = parser.parse("SELECT v, k FROM facts")[0]
        _, _, g2 = c.execute_streaming(st2, [], sql_text="SELECT v, k "
                                                         "FROM facts")
        next(g2)                        # P2: nested hold on P1's slot
        g1.close()                      # out-of-order: P1 dies first
        assert GOVERNOR.snapshot()["running"] == 1, \
            "slot freed while the nested portal still executes"
        g2.close()
        snap = GOVERNOR.snapshot()
        assert snap["running"] == 0 and snap["queued"] == 0


def test_admission_exemption_rules():
    from serenedb_tpu.sql import parser

    def one(sql):
        return admission_exempt(parser.parse(sql)[0])

    assert one("SELECT * FROM pg_stat_activity")
    assert one("SELECT * FROM sdb_admission")
    assert one("SELECT metric FROM sdb_metrics WHERE value > 0")
    assert one("SELECT 1 + 2")
    # the schema qualifier marks the catalog too
    assert one("SELECT * FROM information_schema.tables")
    assert one("SELECT * FROM pg_catalog.pg_class")
    assert not one("SELECT * FROM facts")
    assert not one("SELECT a.pid FROM pg_stat_activity a "
                   "JOIN facts f ON f.k = a.pid")
    assert not one("INSERT INTO facts VALUES (1, 2)")
    assert not one("CREATE TABLE zz (a INT)")


# -- budgets: serene_work_mem + serene_statement_timeout_ms ------------------


def test_work_mem_abort_53200_and_cleanup():
    db, c = _db(n=200_000)
    c.execute("SET serene_mem_account = on")
    c.execute("SET serene_work_mem = '256kB'")
    with pytest.raises(errors.SqlError) as ei:
        c.execute(JOIN_Q)
    assert ei.value.sqlstate == "53200"
    assert "serene_work_mem" in str(ei.value)
    # partial state cleaned up: no phantom progress row, no queue
    # residue, and the SAME session runs the SAME query fine afterwards
    assert all("join dims" not in r["query"].lower()
               for r in ACTIVE.snapshot())
    snap = GOVERNOR.snapshot()
    assert snap["running"] == 0 and snap["queued"] == 0
    c.execute("SET serene_work_mem = '1GB'")
    big = c.execute(JOIN_Q).rows()
    c.execute("RESET serene_work_mem")
    assert big == c.execute(JOIN_Q).rows()


def test_work_mem_abort_marks_txn_failed():
    """The budget abort behaves like any SQL error inside a txn: the
    transaction is failed until ROLLBACK (no half-applied state)."""
    db, c = _db(n=200_000)
    c.execute("SET serene_mem_account = on")
    c.execute("BEGIN")
    c.execute("SET serene_work_mem = '256kB'")
    with pytest.raises(errors.SqlError):
        c.execute(JOIN_Q)
    with pytest.raises(errors.SqlError) as ei:
        c.execute("SELECT 1")
    assert ei.value.sqlstate == errors.IN_FAILED_TRANSACTION
    c.execute("ROLLBACK")
    c.execute("RESET serene_work_mem")
    assert c.execute("SELECT count(*) FROM facts").rows() == [(200_000,)]


def test_work_mem_disabled_without_accounting():
    """Enforcement requires the measured number: with accounting off
    the ceiling cannot fire (documented contract, not a crash)."""
    db, c = _db(n=200_000)
    c.execute("SET serene_mem_account = off")
    c.execute("SET serene_work_mem = '256kB'")
    assert c.execute(JOIN_Q).rows()     # runs to completion


def test_statement_timeout_fires_mid_aggregate():
    """serene_statement_timeout_ms fires through the cancellation
    drain while the statement's morsel tasks run (pool saturated so
    the deadline provably passes before the work can finish)."""
    from serenedb_tpu.parallel.pool import get_pool
    db, c = _db(n=100_000)
    pool = get_pool().ensure_started()
    tok = CURRENT_SCHED.set(("timeout-saturator", 100))
    try:
        sleepers = [pool.submit(time.sleep, 0.05)
                    for _ in range(pool.size * 2)]
    finally:
        CURRENT_SCHED.reset(tok)
    c.execute("SET serene_statement_timeout_ms = 30")
    with pytest.raises(errors.SqlError) as ei:
        c.execute(AGG_Q)
    assert ei.value.sqlstate == "57014"
    assert "timeout" in str(ei.value)
    for f in sleepers:
        f.result()
    c.execute("SET serene_statement_timeout_ms = 0")
    assert c.execute("SELECT count(*) FROM facts").rows() == [(100_000,)]


def test_statement_timeout_lower_value_wins():
    """Both timeout settings armed: the lower one (1ms) governs, so
    the statement dies long before the 5s PG setting would fire."""
    db, c = _db(n=100_000)
    c.execute("SET statement_timeout = 5000")
    c.execute("SET serene_statement_timeout_ms = 1")
    t0 = time.monotonic()
    with pytest.raises(errors.SqlError) as ei:
        c.execute(AGG_Q)
    assert ei.value.sqlstate == "57014"
    assert time.monotonic() - t0 < 4.0


# -- fair-share scheduling ---------------------------------------------------


def test_fair_share_pool_interleave_and_preemptions():
    """Deterministic pool-level check: with fair share ON a later
    statement's tasks interleave into a saturated heavy backlog (and
    SchedPreemptions counts the overtakes); with it OFF the backlog
    runs strictly first."""
    from serenedb_tpu.parallel.pool import WorkerPool
    for fair, max_small_pos in ((True, 7), (False, None)):
        with _globals(serene_fair_share=fair):
            pool = WorkerPool(2).ensure_started()
            order = []
            lock = threading.Lock()

            def work(tag, dur):
                with lock:
                    order.append(tag)
                time.sleep(dur)

            base_pre = metrics.SCHED_PREEMPTIONS.value
            tok = CURRENT_SCHED.set(("heavy", 100))
            try:
                futs = [pool.submit(work, "H", 0.02) for _ in range(12)]
            finally:
                CURRENT_SCHED.reset(tok)
            time.sleep(0.01)            # two H tasks are running
            tok = CURRENT_SCHED.set(("small", 100))
            try:
                futs += [pool.submit(work, "S", 0.0) for _ in range(2)]
            finally:
                CURRENT_SCHED.reset(tok)
            for f in futs:
                f.result()
            pool.shutdown()
            pos = [i for i, t in enumerate(order) if t == "S"]
            if fair:
                assert max(pos) <= max_small_pos, order
                assert metrics.SCHED_PREEMPTIONS.delta(base_pre) >= 1
            else:
                # FIFO-ish: the S tasks run at the tail of the backlog
                # (>= 10, not 12 exactly — an idle worker may steal a
                # just-submitted task from a sibling's TAIL right as
                # its own deque drains)
                assert min(pos) >= 10, order


def test_priority_weight_shares():
    """serene_priority weights bias the stride picker: a weight-1000
    statement's tasks are picked ~10x as often as a weight-100 one
    while both queues are non-empty."""
    from serenedb_tpu.parallel.pool import WorkerPool
    with _globals(serene_fair_share=True):
        pool = WorkerPool(1).ensure_started()
        order = []
        lock = threading.Lock()
        gate = threading.Event()

        def work(tag):
            gate.wait()
            with lock:
                order.append(tag)

        hold = pool.submit(time.sleep, 0.05)    # keep the worker busy
        tok = CURRENT_SCHED.set(("lo", 100))
        try:
            futs = [pool.submit(work, "lo") for _ in range(30)]
        finally:
            CURRENT_SCHED.reset(tok)
        tok = CURRENT_SCHED.set(("hi", 1000))
        try:
            futs += [pool.submit(work, "hi") for _ in range(30)]
        finally:
            CURRENT_SCHED.reset(tok)
        gate.set()
        hold.result()
        for f in futs:
            f.result()
        pool.shutdown()
        first = order[:22]
        assert first.count("hi") >= 2 * first.count("lo"), first


def test_fair_share_off_no_tagged_routing():
    """With the global off, tagged submissions take the legacy FIFO
    deques — the fair structure stays empty (toggle safety)."""
    from serenedb_tpu.parallel.pool import WorkerPool
    with _globals(serene_fair_share=False):
        pool = WorkerPool(2).ensure_started()
        tok = CURRENT_SCHED.set(("tagged", 100))
        try:
            futs = [pool.submit(time.sleep, 0.0) for _ in range(4)]
        finally:
            CURRENT_SCHED.reset(tok)
        for f in futs:
            f.result()
        assert not pool._fair
        pool.shutdown()


# -- flight-recorder proof (the ROADMAP's stated check) ----------------------


def _starved_query_queue_wait(fair_on: bool, marker: str) -> tuple:
    """Run a small aggregate while the SHARED pool is saturated by a
    heavy tag's sleeper backlog; return (widest single pool queue-wait
    span in seconds from the query's flight-recorder timeline, result
    rows). The WIDEST span is the discriminator: under FIFO the small
    query's first morsel provably sits behind the whole remaining
    backlog (~6 sleeper rounds), under fair share every morsel waits
    at most the running round plus one tie-break pick (~2 rounds) —
    the map_ordered in-flight window caps SUMMED waits either way, so
    the sum would hide exactly the starvation this test exists to
    show."""
    from serenedb_tpu.parallel.pool import get_pool
    db, c = _db(n=30_000, seed=3)
    c.execute("SET serene_trace = on")
    c.execute("SET serene_result_cache = off")
    pool = get_pool().ensure_started()
    # warm the whole path (plan cache, zone maps, kernel imports) so
    # the measured run submits its morsels while the sleeper backlog
    # is still queued — on a cold process the first plan alone can
    # outlast the backlog and the starvation would vanish
    c.execute("SELECT k, count(*) FROM facts GROUP BY k ORDER BY k")
    with _globals(serene_fair_share=fair_on):
        tok = CURRENT_SCHED.set((f"heavy-{marker}", 100))
        try:
            sleepers = [pool.submit(time.sleep, 0.03)
                        for _ in range(pool.size * 6)]
        finally:
            CURRENT_SCHED.reset(tok)
        time.sleep(0.005)               # workers are mid-sleeper
        rows = c.execute(
            f"SELECT k, count(*) /* {marker} */ FROM facts "
            "GROUP BY k ORDER BY k").rows()
        for f in sleepers:
            f.result()
    entry = next(e for e in reversed(FLIGHT.snapshot())
                 if marker in e["query"])
    waits = [s["end_ns"] - s["begin_ns"] for s in entry["spans"]
             if s["name"] == "queue_wait" and s["cat"] == "pool"]
    assert waits, "the query must have pool morsels with queue waits"
    return max(waits) / 1e9, rows


def test_flight_recorder_starvation_proof():
    """ROADMAP check: the starved small query's queue-wait is VISIBLE
    in its flight-recorder timeline with fair-share off (its first
    morsel sat behind the whole heavy backlog) and BOUNDED with it on
    (morsels interleave, so no wait exceeds ~two sleeper rounds) —
    with bit-identical results either way."""
    wait_off, rows_off = _starved_query_queue_wait(False, "starve_off")
    wait_on, rows_on = _starved_query_queue_wait(True, "starve_on")
    assert rows_on == rows_off
    # FIFO lower bound: ~6 sleeper rounds ahead of the first morsel,
    # minus the round already running at submit (structural, not a
    # timing guess: those sleepers MUST run first under FIFO)
    assert wait_off > 0.08, f"starvation not visible: {wait_off:.4f}s"
    assert wait_on < wait_off / 2, (wait_on, wait_off)


# -- surfaces: gauges, EXPLAIN, exports --------------------------------------


def test_gauges_explain_and_exports_under_governor():
    from serenedb_tpu.obs.export import prometheus_text, stats_json
    db, c = _db(n=5000)
    with _globals(serene_max_concurrent_statements=4,
                  serene_fair_share=True):
        c.execute("SET serene_work_mem = '1GB'")
        plan = c.execute(f"EXPLAIN (ANALYZE) {AGG_Q}").rows()
        assert any("rows=" in r[0] for r in plan)
        rows = c.execute("SELECT * FROM sdb_admission").rows()
        assert rows[0][2] == 4          # max_concurrent_statements
        s = stats_json()
        assert s["admission"]["max_concurrent_statements"] == 4
        assert {"running", "queued", "rejected_total",
                "wait_ns_total"} <= set(s["admission"])
        text = prometheus_text()
        for series in ("serenedb_admission_queued",
                       "serenedb_admission_rejected",
                       "serenedb_admission_wait_ns",
                       "serenedb_sched_preemptions"):
            assert series in text
        got = c.execute("SELECT metric FROM sdb_metrics "
                        "WHERE metric LIKE 'Admission%'").rows()
        assert {("AdmissionQueueDepth",), ("AdmissionQueued",),
                ("AdmissionRejected",), ("AdmissionWaitNs",)} <= set(got)


def test_governor_settings_not_result_affecting():
    from serenedb_tpu.cache.result import RESULT_AFFECTING_SETTINGS
    for s in ("serene_max_concurrent_statements",
              "serene_admission_queue_depth", "serene_fair_share",
              "serene_priority", "serene_work_mem",
              "serene_statement_timeout_ms"):
        assert s not in RESULT_AFFECTING_SETTINGS
