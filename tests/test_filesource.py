"""Zero-ETL file sources: read_parquet/read_csv, globs, remote gating
(reference: index_source_view_file.cpp)."""

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError


@pytest.fixture
def conn():
    return Database().connect()


def _write_parquet(conn, tmp_path, name, rows):
    conn.execute(f"CREATE TABLE _w_{name} (id INT, v DOUBLE)")
    vals = ", ".join(f"({a}, {b})" for a, b in rows)
    conn.execute(f"INSERT INTO _w_{name} VALUES {vals}")
    p = str(tmp_path / f"{name}.parquet")
    conn.execute(f"COPY _w_{name} TO '{p}' WITH (FORMAT parquet)")
    conn.execute(f"DROP TABLE _w_{name}")
    return p


def test_read_parquet_single_and_view(conn, tmp_path):
    p = _write_parquet(conn, tmp_path, "one", [(1, 1.5), (2, 2.5)])
    rows = conn.execute(
        f"SELECT id, v FROM read_parquet('{p}') ORDER BY id").rows()
    assert rows == [(1, 1.5), (2, 2.5)]
    # zero-ETL view over the file
    conn.execute(f"CREATE VIEW pv AS SELECT * FROM read_parquet('{p}')")
    assert conn.execute("SELECT count(*) FROM pv").scalar() == 2
    assert conn.execute(
        "SELECT sum(v) FROM pv WHERE id > 1").scalar() == 2.5


def test_read_parquet_glob_union(conn, tmp_path):
    _write_parquet(conn, tmp_path, "part1", [(1, 1.0)])
    _write_parquet(conn, tmp_path, "part2", [(2, 2.0), (3, 3.0)])
    g = str(tmp_path / "part*.parquet")
    rows = conn.execute(
        f"SELECT id FROM read_parquet('{g}') ORDER BY id").rows()
    assert rows == [(1,), (2,), (3,)]
    with pytest.raises(SqlError):
        conn.execute(
            f"SELECT * FROM read_parquet('{tmp_path}/nope*.parquet')")


def test_read_csv_inference_and_header(conn, tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("id,name,score\n1,ann,1.5\n2,bob,\n3,cy,3.25\n")
    rows = conn.execute(
        f"SELECT id, name, score FROM read_csv('{p}') ORDER BY id").rows()
    assert rows == [(1, "ann", 1.5), (2, "bob", None), (3, "cy", 3.25)]
    # headerless numeric file → column0..n names, int inference
    q = tmp_path / "raw.csv"
    q.write_text("10,x\n20,y\n")
    rows = conn.execute(
        f'SELECT column0, column1 FROM read_csv(\'{q}\') '
        "ORDER BY column0").rows()
    assert rows == [(10, "x"), (20, "y")]
    # explicit header flag overrides detection
    rows = conn.execute(
        f"SELECT count(*) FROM read_csv('{q}', true)").scalar()
    assert rows == 1


def test_read_csv_bool_and_delim(conn, tmp_path):
    p = tmp_path / "flags.tsv"
    p.write_text("a\tb\ntrue\t1\nfalse\t2\n")
    rows = conn.execute(
        f"SELECT a, b FROM read_csv('{p}', true, E'\\t') "
        "ORDER BY b").rows()
    assert rows == [(True, 1), (False, 2)]


def test_remote_fetch_gated(conn):
    with pytest.raises(SqlError) as e:
        conn.execute("SELECT * FROM "
                     "read_parquet('https://198.51.100.1/x.parquet')")
    assert e.value.sqlstate == "58030"


def test_header_only_csv(conn, tmp_path):
    p = tmp_path / "empty.csv"
    p.write_text("a,b\n")
    assert conn.execute(
        f"SELECT a, b FROM read_csv('{p}', true)").rows() == []


def test_glob_type_mismatch(conn, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    pq.write_table(pa.table({"id": [1]}), str(tmp_path / "t1.parquet"))
    pq.write_table(pa.table({"id": ["x"]}), str(tmp_path / "t2.parquet"))
    with pytest.raises(SqlError):
        conn.execute(f"SELECT * FROM read_parquet('{tmp_path}/t*.parquet')")
