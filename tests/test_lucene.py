"""Lucene query-string parser (search/lucene.py) + ES query_string
end-to-end.

Reference analog: libs/iresearch/include/iresearch/parser/lucene_parser
— boosts, field groups, ranges, occurs (+/-), fuzzy, proximity,
wildcards, escapes.
"""

import json
import urllib.request

import pytest

from serenedb_tpu.search.lucene import (LBool, LMatchAll, LPhrase, LRange,
                                        LRegex, LTerm, LuceneError,
                                        lower_to_sql, parse_lucene)


def qi(name):
    return '"' + name + '"'


# ------------------------------------------------------------ parse only

def test_single_term():
    n = parse_lucene("hello")
    assert isinstance(n, LTerm) and n.text == "hello" and n.boost == 1.0


def test_default_operator_or_and():
    n = parse_lucene("a b")
    assert isinstance(n, LBool) and n.occur == ["", ""]
    n = parse_lucene("a b", default_operator="AND")
    assert isinstance(n, LBool) and n.occur == ["+", "+"]


def test_explicit_and_requires_both_sides():
    n = parse_lucene("a AND b")
    assert isinstance(n, LBool) and n.occur == ["+", "+"]


def test_or_groups():
    n = parse_lucene("a OR b OR c")
    assert isinstance(n, LBool) and len(n.clauses) == 3
    assert all(o == "" for o in n.occur)


def test_plus_minus_not():
    n = parse_lucene("+must -banned plain")
    assert n.occur == ["+", "-", ""]
    n2 = parse_lucene("NOT x")
    assert n2.occur == ["-"] if isinstance(n2, LBool) else True


def test_boost():
    n = parse_lucene("title:fox^2.5")
    assert isinstance(n, LTerm) and n.field == "title" and n.boost == 2.5


def test_field_group():
    n = parse_lucene("title:(quick OR brown)")
    assert isinstance(n, LBool)
    assert all(c.field == "title" for c in n.clauses)


def test_field_group_does_not_override_inner_field():
    n = parse_lucene("a:(x OR b:y)")
    assert n.clauses[0].field == "a"
    assert n.clauses[1].field == "b"


def test_phrase_and_slop():
    n = parse_lucene('"quick fox"')
    assert isinstance(n, LPhrase) and n.slop == 0
    n = parse_lucene('"quick fox"~3')
    assert n.slop == 3


def test_fuzzy():
    n = parse_lucene("roam~")
    assert isinstance(n, LTerm) and n.fuzzy == 1
    n = parse_lucene("roam~2")
    assert n.fuzzy == 2


def test_ranges():
    n = parse_lucene("pages:[100 TO 200]")
    assert isinstance(n, LRange)
    assert (n.lo, n.hi, n.incl_lo, n.incl_hi) == ("100", "200", True, True)
    n = parse_lucene("pages:{100 TO 200}")
    assert (n.incl_lo, n.incl_hi) == (False, False)
    n = parse_lucene("pages:[* TO 200}")
    assert n.lo is None and n.incl_hi is False
    n = parse_lucene("date:[2020-01-01 TO 2020-12-31]")
    assert n.lo == "2020-01-01"
    n = parse_lucene("delta:[-5 TO 5]")
    assert n.lo == "-5"


def test_wildcards_and_regex():
    n = parse_lucene("te?t")
    assert isinstance(n, LTerm) and n.text == "te?t"
    n = parse_lucene("/fo[xo]/")
    assert isinstance(n, LRegex) and n.pattern == "fo[xo]"


def test_hyphen_inside_word_is_literal():
    n = parse_lucene("state-of-the-art")
    assert isinstance(n, LTerm) and n.text == "state-of-the-art"


def test_escapes():
    n = parse_lucene(r"foo\:bar")
    assert isinstance(n, LTerm) and n.text == "foo:bar"


def test_match_all():
    assert isinstance(parse_lucene("*"), LMatchAll)
    assert isinstance(parse_lucene(""), LMatchAll)


def test_parse_errors():
    with pytest.raises(LuceneError):
        parse_lucene("(a OR b")
    with pytest.raises(LuceneError):
        parse_lucene("pages:[1 200]")
    with pytest.raises(LuceneError):
        parse_lucene("a AND")


# -------------------------------------------------------------- lowering

def test_lower_term_and_range():
    sql, claims = lower_to_sql(
        parse_lucene("title:fox AND pages:[10 TO 20]"), "body", qi)
    assert '"title" @@ \'fox\'' in sql
    assert '"pages" >= 10.0' in sql and '"pages" <= 20.0' in sql
    assert [(f, b) for f, b, _ in claims] == [("title", 1.0)]
    assert claims[0][2] == '"title" @@ \'fox\''


def test_lower_boost_claims():
    _, claims = lower_to_sql(parse_lucene("title:a^3 body:b"), "body", qi)
    pairs = [(f, b) for f, b, _ in claims]
    assert ("title", 3.0) in pairs and ("body", 1.0) in pairs


def test_lower_must_not_never_claims():
    _, claims = lower_to_sql(parse_lucene("title:a -body:b"), "body", qi)
    assert [f for f, _, _ in claims] == ["title"]


def test_lower_field_star_is_exists():
    sql, claims = lower_to_sql(parse_lucene("title:* AND x"), "f", qi)
    assert '"title" IS NOT NULL' in sql
    assert [f for f, _, _ in claims] == ["f"]


def test_lower_should_with_must_is_scoring_only():
    sql, _ = lower_to_sql(parse_lucene("+a b"), "f", qi)
    # must present -> should dropped from the filter
    assert sql.count("@@") >= 1
    assert "'b'" not in sql


def test_lower_prohibit():
    sql, _ = lower_to_sql(parse_lucene("a -b"), "f", qi)
    assert "NOT (" in sql


def test_lower_slop_phrase_and_fuzzy():
    sql, _ = lower_to_sql(parse_lucene('"a b"~2 x~1'), "f", qi)
    assert '"a b"~2' in sql and "x~1" in sql


# ------------------------------------------------- end-to-end over HTTP

def _put(srv, path, body):
    r = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="PUT")
    with urllib.request.urlopen(r, timeout=30) as resp:
        return json.loads(resp.read().decode())


@pytest.fixture(scope="module")
def srv():
    from serenedb_tpu.engine import Database
    from serenedb_tpu.server.http_server import HttpServer
    db = Database()
    s = HttpServer(db, port=0)
    s.start()
    docs = [
        (1, "quick brown fox", "the quick brown fox jumps", 100),
        (2, "lazy dog", "a lazy dog sleeps all day", 150),
        (3, "quick dog", "the quick dog runs far away", 200),
        (4, "brown bear", "a big brown bear eats honey", 250),
    ]
    for i, title, body, pages in docs:
        _put(s, f"/lqs/_doc/{i}", {"id": i, "title": title,
                                   "body": body, "pages": pages})
    yield s
    s.stop()


def search(srv, q):
    body = json.dumps({"query": q, "size": 10}).encode()
    r = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/lqs/_search", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(r, timeout=30) as resp:
        out = json.loads(resp.read().decode())
    return sorted(int(h["_source"]["id"])
                  for h in out["hits"]["hits"]), out


def test_e2e_simple_term(srv):
    ids, _ = search(srv, {"query_string": {
        "default_field": "body", "query": "quick"}})
    assert ids == [1, 3]


def test_e2e_boolean_and_field(srv):
    ids, _ = search(srv, {"query_string": {
        "default_field": "body",
        "query": "title:quick AND body:runs"}})
    assert ids == [3]


def test_e2e_default_operator_and(srv):
    ids, _ = search(srv, {"query_string": {
        "default_field": "body", "query": "quick fox",
        "default_operator": "AND"}})
    assert ids == [1]


def test_e2e_prohibit(srv):
    ids, _ = search(srv, {"query_string": {
        "default_field": "body", "query": "quick -fox"}})
    assert ids == [3]


def test_e2e_range(srv):
    ids, _ = search(srv, {"query_string": {
        "default_field": "body", "query": "pages:[150 TO 250}"}})
    assert ids == [2, 3]


def test_e2e_phrase_slop(srv):
    ids, _ = search(srv, {"query_string": {
        "default_field": "body", "query": '"quick jumps"'}})
    assert ids == []
    ids, _ = search(srv, {"query_string": {
        "default_field": "body", "query": '"quick jumps"~2'}})
    assert ids == [1]


def test_e2e_wildcard_and_fuzzy(srv):
    # wildcards match ANALYZED terms (stemmed): d?g -> 'dog'
    ids, _ = search(srv, {"query_string": {
        "default_field": "body", "query": "d?g"}})
    assert ids == [2, 3]
    ids, _ = search(srv, {"query_string": {
        "default_field": "body", "query": "b*wn"}})
    assert ids == [1, 4]
    ids, _ = search(srv, {"query_string": {
        "default_field": "body", "query": "quikc~2"}})
    assert ids == [1, 3]


def test_e2e_field_group_with_boost_scores(srv):
    ids, out = search(srv, {"query_string": {
        "default_field": "body", "query": "title:(fox^5 OR dog)"}})
    assert ids == [1, 2, 3]
    # same-column OR is index-claimed, so scores are real (nonzero) and
    # the 5x fox boost must put doc 1 on top
    top = out["hits"]["hits"][0]
    assert int(top["_source"]["id"]) == 1
    assert top["_score"] > 0


def test_e2e_parse_error_is_400(srv):
    import urllib.error
    body = json.dumps({"query": {"query_string": {
        "default_field": "body", "query": "(broken"}}}).encode()
    r = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/lqs/_search", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(r, timeout=30)
    assert ei.value.code == 400


def test_e2e_multifield_scoring(srv):
    """Cross-field OR must produce real summed scores (not 0.0) via the
    per-claim scoring passes."""
    ids, out = search(srv, {"query_string": {
        "default_field": "body", "query": "title:bear OR jumps"}})
    assert ids == [1, 4]
    for h in out["hits"]["hits"]:
        assert h["_score"] > 0, h
    # doc 4 matches on the boosted field when boosted -> outranks doc 1
    ids, out = search(srv, {"query_string": {
        "default_field": "body", "query": "title:bear^20 OR jumps"}})
    assert int(out["hits"]["hits"][0]["_source"]["id"]) == 4


def test_e2e_wildcard_fuzzy_combo_is_wildcard(srv):
    # `d?g~2` — fuzzy cannot combine with wildcards; the suffix drops
    ids, _ = search(srv, {"query_string": {
        "default_field": "body", "query": "d?g~2"}})
    assert ids == [2, 3]


def test_float_fuzziness_legacy():
    n = parse_lucene("title:foo~0.8", default_operator="AND")
    assert isinstance(n, LTerm) and n.fuzzy == 1 and n.field == "title"
    n = parse_lucene('"a b"~1.5')
    assert isinstance(n, LPhrase) and n.slop == 1
