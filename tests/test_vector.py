"""Vector search tests: kernels, IVF index, SQL pushdown, ES knn + RRF."""

import json

import numpy as np
import pytest

from serenedb_tpu.engine import Database


def make_vec_table(conn, n=200, d=16, seed=5):
    rng = np.random.default_rng(seed)
    vecs = rng.normal(size=(n, d)).astype(np.float32)
    conn.execute("CREATE TABLE vt (id INT, v TEXT)")
    rows = ", ".join(
        f"({i}, '{json.dumps([round(float(x), 4) for x in vecs[i]])}')"
        for i in range(n))
    conn.execute(f"INSERT INTO vt VALUES {rows}")
    return vecs


def test_vec_functions_cpu():
    c = Database().connect()
    assert c.execute("SELECT vec_l2('[0,0]', '[3,4]')").scalar() == 25.0
    assert c.execute("SELECT vec_ip('[1,2]', '[3,4]')").scalar() == -11.0
    assert c.execute("SELECT vec_cos('[1,0]', '[0,1]')").scalar() == \
        pytest.approx(1.0)
    assert c.execute("SELECT '[0,0]' <-> '[3,4]'").scalar() == 25.0
    assert c.execute("SELECT vec_dims('[1,2,3]')").scalar() == 3
    from serenedb_tpu.errors import SqlError
    with pytest.raises(SqlError):
        c.execute("SELECT vec_l2('[1,2]', '[1,2,3]')")
    with pytest.raises(SqlError):
        c.execute("SELECT vec_l2('not json', '[1]')")


def test_ivf_exact_parity_full_probe():
    db = Database()
    c = db.connect()
    vecs = make_vec_table(c, n=150, d=8)
    c.execute("CREATE INDEX ON vt USING ivf (v) WITH (lists = 10)")
    c.execute("SET sdb_nprobe = 10")  # probe all lists → exact
    q = [round(float(x), 4) for x in vecs[7]]
    qs = json.dumps(q)
    ex = c.execute(
        f"EXPLAIN SELECT id, v <-> '{qs}' AS d FROM vt ORDER BY d LIMIT 5"
    ).rows()
    assert any("IvfScan" in r[0] for r in ex)
    got = c.execute(
        f"SELECT id, v <-> '{qs}' AS d FROM vt ORDER BY d LIMIT 5").rows()
    # CPU oracle via subquery (defeats the pushdown pattern)
    ref = c.execute(
        f"SELECT id FROM (SELECT id, v <-> '{qs}' AS d FROM vt) s "
        "ORDER BY d LIMIT 5").rows()
    assert [r[0] for r in got] == [r[0] for r in ref]
    assert got[0][0] == 7 and got[0][1] == pytest.approx(0.0, abs=1e-4)
    # distances ascending
    ds = [r[1] for r in got]
    assert ds == sorted(ds)


def test_ivf_recall_with_small_nprobe():
    db = Database()
    c = db.connect()
    vecs = make_vec_table(c, n=300, d=8, seed=6)
    c.execute("CREATE INDEX ON vt USING ivf (v) WITH (lists = 16)")
    c.execute("SET sdb_nprobe = 4")
    hits = 0
    for qi in range(20):
        qs = json.dumps([round(float(x), 4) for x in vecs[qi]])
        got = c.execute(
            f"SELECT id FROM vt ORDER BY v <-> '{qs}' LIMIT 1").rows()
        hits += int(got and got[0][0] == qi)
    assert hits >= 15  # nprobe=4/16 recall@1 well above chance


def test_ivf_index_append_keeps_serving():
    # pure appends no longer orphan the index: read-repair assigns the
    # tail rows to the existing centroids incrementally and the scan
    # keeps serving — including the appended row
    db = Database()
    c = db.connect()
    make_vec_table(c, n=50, d=4)
    c.execute("CREATE INDEX ON vt USING ivf (v)")
    c.execute("INSERT INTO vt VALUES (999, '[0,0,0,0]')")
    ex = c.execute("EXPLAIN SELECT id FROM vt ORDER BY v <-> '[0,0,0,0]' "
                   "LIMIT 1").rows()
    assert any("IvfScan" in r[0] for r in ex)
    got = c.execute("SELECT id FROM vt ORDER BY v <-> '[0,0,0,0]' "
                    "LIMIT 1").rows()
    assert got[0][0] == 999


def test_ivf_index_destructive_mutation_falls_back():
    # UPDATE/DELETE advance the mutation epoch: the stale index is
    # orphaned (rebuild reason logged, maintenance rebuilds later) and
    # the query answers from the CPU oracle meanwhile
    db = Database()
    c = db.connect()
    make_vec_table(c, n=50, d=4)
    c.execute("CREATE INDEX ON vt USING ivf (v)")
    c.execute("DELETE FROM vt WHERE id = 7")
    ex = c.execute("EXPLAIN SELECT id FROM vt ORDER BY v <-> '[0,0,0,0]' "
                   "LIMIT 60").rows()
    assert not any("IvfScan" in r[0] for r in ex)
    got = c.execute("SELECT id FROM vt ORDER BY v <-> '[0,0,0,0]' "
                    "LIMIT 60").rows()
    assert 7 not in [r[0] for r in got] and len(got) == 49


def test_null_vectors_skipped():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE vt (id INT, v TEXT)")
    c.execute("INSERT INTO vt VALUES (1, '[1,1]'), (2, NULL), (3, '[5,5]')")
    c.execute("CREATE INDEX ON vt USING ivf (v) WITH (lists = 2)")
    got = c.execute("SELECT id FROM vt ORDER BY v <-> '[1,1]' LIMIT 3").rows()
    assert [r[0] for r in got] == [1, 3]  # NULL row never surfaces


# -- ES knn + hybrid -------------------------------------------------------

@pytest.fixture()
def es_srv():
    from serenedb_tpu.server.http_server import HttpServer
    db = Database()
    s = HttpServer(db, port=0)
    s.start()
    yield s
    s.stop()


def test_es_knn_and_hybrid_rrf(es_srv):
    from tests.test_es_api import req
    req(es_srv, "PUT", "/emb", {
        "mappings": {"properties": {
            "body": {"type": "text"},
            "vec": {"type": "dense_vector", "dims": 4}}}})
    docs = [
        ("1", "alpha topic words", [1, 0, 0, 0]),
        ("2", "beta topic words", [0, 1, 0, 0]),
        ("3", "alpha unrelated", [0.9, 0.1, 0, 0]),
    ]
    for did, body, vec in docs:
        req(es_srv, "PUT", f"/emb/_doc/{did}", {"body": body, "vec": vec})
    req(es_srv, "POST", "/emb/_refresh")
    # pure knn
    status, res = req(es_srv, "POST", "/emb/_search", {
        "knn": {"field": "vec", "query_vector": [1, 0, 0, 0], "k": 2}})
    assert status == 200
    assert [h["_id"] for h in res["hits"]["hits"]] == ["1", "3"]
    # hybrid: text match 'alpha' + vector near doc 2 → RRF fuses
    status, res = req(es_srv, "POST", "/emb/_search", {
        "query": {"match": {"body": "alpha"}},
        "knn": {"field": "vec", "query_vector": [0, 1, 0, 0], "k": 3},
        "size": 3})
    assert status == 200
    ids = [h["_id"] for h in res["hits"]["hits"]]
    assert set(ids) == {"1", "2", "3"}
    # doc in both rankings (3: alpha + close-ish vector) should beat
    # single-list docs... at minimum scores are descending and positive
    scores = [h["_score"] for h in res["hits"]["hits"]]
    assert scores == sorted(scores, reverse=True) and scores[0] > 0


def test_vec_functions_null_propagation():
    c = Database().connect()
    c.execute("CREATE TABLE nv (id INT, v TEXT)")
    c.execute("INSERT INTO nv VALUES (1, '[1,2]'), (2, NULL)")
    rows = c.execute("SELECT id, vec_l2(v, '[1,2]') FROM nv ORDER BY id").rows()
    assert rows == [(1, 0.0), (2, None)]
    assert c.execute("SELECT vec_dims(NULL)").scalar() is None


def test_es_knn_uses_ivf_pushdown(es_srv):
    from tests.test_es_api import req
    req(es_srv, "PUT", "/pk", {"mappings": {"properties": {
        "vec": {"type": "dense_vector", "dims": 2}}}})
    for i in range(6):
        req(es_srv, "PUT", f"/pk/_doc/{i}", {"vec": [i, 0]})
    req(es_srv, "POST", "/pk/_refresh")
    # the SQL the ES layer generates must hit the IvfScan (no IS NOT NULL)
    status, body = req(es_srv, "POST", "/_sql", {
        "query": "EXPLAIN SELECT \"_id\" FROM \"pk\" "
                 "ORDER BY vec_l2(\"vec\", '[0,0]') LIMIT 3"})
    text = "\n".join(r[0] for r in body["rows"])
    assert "IvfScan" in text
    # knn pagination
    status, body = req(es_srv, "POST", "/pk/_search", {
        "knn": {"field": "vec", "query_vector": [0, 0], "k": 6},
        "from": 2, "size": 2})
    assert [h["_id"] for h in body["hits"]["hits"]] == ["2", "3"]


def test_es_knn_nprobe_knob(es_srv):
    # the knn DSL's optional "nprobe" pins serene_nprobe for the one
    # statement (restored after), overriding the session default
    from tests.test_es_api import req
    req(es_srv, "PUT", "/np", {"mappings": {"properties": {
        "vec": {"type": "dense_vector", "dims": 2}}}})
    for i in range(12):
        req(es_srv, "PUT", f"/np/_doc/{i}", {"vec": [i, 0]})
    req(es_srv, "POST", "/np/_refresh")
    status, body = req(es_srv, "POST", "/np/_search", {
        "knn": {"field": "vec", "query_vector": [0, 0], "k": 4,
                "nprobe": 64}})
    assert status == 200
    assert [h["_id"] for h in body["hits"]["hits"]] == \
        ["0", "1", "2", "3"]
    # and a plain knn afterwards still behaves (the SET was restored)
    status, body = req(es_srv, "POST", "/np/_search", {
        "knn": {"field": "vec", "query_vector": [11, 0], "k": 1}})
    assert [h["_id"] for h in body["hits"]["hits"]] == ["11"]


def test_sq8_quantized_index_recall_and_rerank():
    db = Database()
    c = db.connect()
    vecs = make_vec_table(c, n=200, d=16, seed=11)
    c.execute("CREATE INDEX ON vt USING ivf (v) "
              "WITH (lists = 8, quantization = 'sq8')")
    c.execute("SET sdb_nprobe = 8")  # full probe: rerank makes it exact
    hits = 0
    for qi in range(15):
        qs = json.dumps([round(float(x), 4) for x in vecs[qi]])
        got = c.execute(
            f"SELECT id FROM vt ORDER BY v <-> '{qs}' LIMIT 1").rows()
        hits += int(got and got[0][0] == qi)
    assert hits == 15   # exact self-recall via rerank despite quantization
    # distances are the exact (reranked) values
    qs = json.dumps([round(float(x), 4) for x in vecs[3]])
    d0 = c.execute(f"SELECT v <-> '{qs}' FROM vt ORDER BY 1 LIMIT 1"
                   ).scalar()
    assert d0 == pytest.approx(0.0, abs=1e-4)


def test_sq8_helpers_roundtrip_error_small():
    from serenedb_tpu.ops.vector import sq8_quantize, sq8_dequantize
    rng = np.random.default_rng(0)
    x = rng.normal(size=(100, 8)).astype(np.float32)
    q, lo, scale = sq8_quantize(x)
    err = np.abs(sq8_dequantize(q, lo, scale) - x).max()
    assert err <= (scale.max() / 255.0) * 0.51
