"""Device telemetry suite (ISSUE 15): the XLA compile ledger, transfer
accounting, per-device HBM attribution, and their surfaces.

Contract under test: `serene_device_telemetry` (default on) observes
only — results are BIT-IDENTICAL with telemetry on or off across the
full matrix (workers 1/4 × shards 1/4 × host/fused/collective
combines); the compile ledger's hit/miss counts match a
dispatch-count-style oracle across repeat queries; the bounded program
LRU (`serene_program_cache_entries`, the PR 7 `_PROGRAM_CACHE` leak
fix) genuinely evicts and re-compiles; recompile storms warn; and the
`sdb_device()` / `sdb_programs()` / `sdb_device_cache()` relations,
`GET /device`, `/metrics` / `/_stats` exports, and the EXPLAIN ANALYZE
`compile=hit|miss` key all round-trip.
"""

import json
import urllib.request

import numpy as np
import pytest

from serenedb_tpu.columnar import dtypes as dt
from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.exec.tables import MemTable
from serenedb_tpu.obs import device as obs_device
from serenedb_tpu.utils import metrics
from serenedb_tpu.utils.config import REGISTRY as SETTINGS


def _mk_conn(nl=6000, nr=3000, seed=9):
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE l (ik INT, sk TEXT, v BIGINT, ts BIGINT)")
    c.execute("CREATE TABLE r (ik INT, w BIGINT)")
    rng = np.random.default_rng(seed)

    def mk(n, payload):
        ik = rng.integers(0, 40, n).astype(np.int32)
        cols = {"ik": Column(dt.INT, ik, rng.random(n) > 0.1)}
        if payload == "v":
            cols["sk"] = Column.from_numpy(
                rng.choice(["alpha", "beta", "gamma"], n))
        cols[payload] = Column.from_numpy(
            rng.integers(-500, 500, n, dtype=np.int64))
        if payload == "v":
            cols["ts"] = Column.from_numpy(np.arange(n, dtype=np.int64))
        return Batch.from_pydict(cols)

    db.schemas["main"].tables["l"] = MemTable("l", mk(nl, "v"))
    db.schemas["main"].tables["r"] = MemTable("r", mk(nr, "w"))
    c.execute("SET serene_device = 'tpu'")
    c.execute("SET serene_device_fused = on")
    c.execute("SET serene_result_cache = off")   # assert EXECUTION internals
    c.execute("SET serene_morsel_rows = 1024")
    c.execute("SET serene_parallel_min_rows = 1024")
    return c


def _rows(c, q):
    return repr(c.execute(q).rows())


class _global:
    """Set a GLOBAL setting for the scope, restore on exit."""

    def __init__(self, name, value):
        self.name, self.value = name, value

    def __enter__(self):
        self.old = SETTINGS.get_global(self.name)
        SETTINGS.set_global(self.name, self.value)

    def __exit__(self, *exc):
        SETTINGS.set_global(self.name, self.old)
        return False


PARITY_QUERIES = [
    "SELECT count(*), sum(v), sum(w), min(v), max(w) "
    "FROM l JOIN r ON l.ik = r.ik WHERE v > 0",
    "SELECT l.sk, count(*), sum(v) FROM l JOIN r ON l.ik = r.ik "
    "GROUP BY l.sk ORDER BY l.sk",
    "SELECT ik, count(*), sum(v) FROM l WHERE v % 3 = 0 "
    "GROUP BY ik ORDER BY ik NULLS LAST",
    "SELECT * FROM l WHERE v > 250 ORDER BY v DESC LIMIT 7",
]


@pytest.mark.parametrize("q", PARITY_QUERIES)
def test_telemetry_parity_matrix(q):
    """Telemetry on/off × workers 1/4 × shards 1/4 × combine
    host/device: every cell bit-identical — telemetry never steers
    (host path, fused single dispatch, sharded host combine, AND the
    collective shard_map combine all run under both switch values)."""
    c = _mk_conn()
    with _global("serene_device_telemetry", True):
        oracle = _rows(c, q)
    for tele in (True, False):
        with _global("serene_device_telemetry", tele):
            for workers in (1, 4):
                c.execute(f"SET serene_workers = {workers}")
                for shards in (1, 4):
                    c.execute(f"SET serene_shards = {shards}")
                    combines = ("host", "device") if shards > 1 \
                        else ("host",)
                    for comb in combines:
                        c.execute(f"SET serene_shard_combine = {comb}")
                        got = _rows(c, q)
                        assert got == oracle, \
                            (f"telemetry={tele} workers={workers} "
                             f"shards={shards} combine={comb} diverged")
    c.execute("SET serene_shards = 1")


def test_compile_ledger_hit_miss_dispatch_oracle():
    """sdb_programs() hit/miss counts must match the dispatch-count
    oracle: a fresh fused shape compiles exactly once (miss), every
    repeat dispatch is a ledger hit, and hits+misses equals the number
    of fused dispatches the offload gauge counted."""
    c = _mk_conn()
    q = ("SELECT count(*), sum(v), sum(w) FROM l JOIN r "
         "ON l.ik = r.ik WHERE v > 100")
    fam0 = obs_device.PROGRAMS.family("fused")
    off0 = metrics.DEVICE_OFFLOADS.value
    c.execute(q)                                   # cold: compile
    fam1 = obs_device.PROGRAMS.family("fused")
    assert fam1["misses"] == fam0["misses"] + 1
    assert fam1["compiles"] == fam0["compiles"] + 1
    repeats = 3
    for _ in range(repeats):
        c.execute(q)                               # warm: ledger hits
    fam2 = obs_device.PROGRAMS.family("fused")
    assert fam2["misses"] == fam1["misses"]
    assert fam2["hits"] == fam1["hits"] + repeats
    dispatches = metrics.DEVICE_OFFLOADS.value - off0
    probes = (fam2["hits"] - fam0["hits"]) + \
        (fam2["misses"] - fam0["misses"])
    assert probes == dispatches == repeats + 1
    # the SQL relation reports the same ledger
    row = [r for r in c.execute(
        "SELECT family, compiles, hits, misses FROM sdb_programs()"
    ).rows() if r[0] == "fused"]
    assert row and row[0][1] == fam2["compiles"] and \
        row[0][2] == fam2["hits"] and row[0][3] == fam2["misses"]
    # compile wall time was recorded (first-dispatch trace)
    snap = [r for r in obs_device.PROGRAMS.snapshot()
            if r["family"] == "fused"][0]
    assert snap["compile_ms_total"] > 0


def test_program_cache_lru_eviction_and_recompile():
    """The bugfix satellite: the program LRU actually frees entries at
    the cap, and a re-request of an evicted key re-compiles through the
    builder (the PR 7 dict leaked one executable per novel shape)."""
    import jax.numpy as jnp
    builds = []

    def builder_for(tag):
        def build():
            builds.append(tag)
            return lambda x: x + 1
        return build

    with _global("serene_program_cache_entries", 2):
        n0 = obs_device.PROGRAMS.entries()
        progs = {}
        for tag in ("a", "b", "c"):
            progs[tag] = obs_device.compiled(
                "lru_unit", ("lru_unit", tag), builder_for(tag))
            assert int(progs[tag](jnp.int32(1))) == 2   # compile + run
        assert builds == ["a", "b", "c"]
        # cap 2: the whole ledger is bounded, so 'a' (oldest) is gone
        assert obs_device.PROGRAMS.entries() <= 2
        assert obs_device.PROGRAMS.entries() <= n0 + 2
        fam = obs_device.PROGRAMS.family("lru_unit")
        assert fam["compiles"] == 3
        # re-request the evicted key: the builder runs again
        again = obs_device.compiled("lru_unit", ("lru_unit", "a"),
                                    builder_for("a"))
        assert builds == ["a", "b", "c", "a"]
        assert int(again(jnp.int32(2))) == 3
        fam = obs_device.PROGRAMS.family("lru_unit")
        assert fam["compiles"] == 4 and fam["evictions"] >= 2


def test_ledger_hit_returns_same_program_no_rebuild():
    """A ledger hit must hand back the SAME compiled wrapper without
    invoking the builder (telemetry may count, never re-trace)."""
    calls = []

    def build():
        calls.append(1)
        return lambda x: x * 2

    p1 = obs_device.compiled("hit_unit", ("k",), build)
    p2 = obs_device.compiled("hit_unit", ("k",), build)
    assert p1 is p2 and calls == [1]


def test_recompile_storm_warns():
    """> RECOMPILE_STORM_PER_MIN fresh compiles of one family within
    the window fire the DeviceRecompileStorms gauge and a device-topic
    warning (rate-limited)."""
    from serenedb_tpu.utils import log as _log
    storms0 = metrics.DEVICE_RECOMPILE_STORMS.value
    for i in range(obs_device.RECOMPILE_STORM_PER_MIN + 2):
        obs_device.compiled("storm_unit", ("storm", i),
                            lambda: (lambda x: x))
    assert metrics.DEVICE_RECOMPILE_STORMS.value == storms0 + 1
    assert obs_device.PROGRAMS.family("storm_unit")["storms"] == 1
    recs = [r for r in _log.MANAGER.records()
            if r.topic == "device" and "recompile storm" in r.message]
    assert recs and "storm_unit" in recs[-1].message


def test_sdb_device_and_device_cache_round_trip():
    """sdb_device: dispatches/bytes land on the executing device;
    sdb_device_cache: per-publication/column occupancy with resolved
    table names, hits counting on repeat queries."""
    c = _mk_conn()
    q = ("SELECT count(*), sum(v), sum(w) FROM l JOIN r "
         "ON l.ik = r.ik WHERE v > 0")
    c.execute(q)
    dev = c.execute(
        "SELECT device, dispatches, bytes_up, hbm_bytes_est "
        "FROM sdb_device WHERE dispatches > 0").rows()
    assert dev, "no device recorded a dispatch"
    assert any(r[2] > 0 for r in dev), "no upload bytes attributed"
    assert any(r[3] > 0 for r in dev), "no HBM occupancy estimated"
    rows = c.execute(
        "SELECT table_name, column_name, kind, bytes, hits "
        "FROM sdb_device_cache").rows()
    tables = {r[0] for r in rows}
    assert {"l", "r"} <= tables
    assert all(r[3] > 0 for r in rows)
    hits_before = {(r[0], r[1], r[2]): r[4] for r in rows}
    c.execute(q)                       # warm repeat: cache entries hit
    rows2 = c.execute(
        "SELECT table_name, column_name, kind, bytes, hits "
        "FROM sdb_device_cache").rows()
    assert any(r[4] > hits_before.get((r[0], r[1], r[2]), 0)
               for r in rows2)
    # device->host fetch accounting moved bytes too
    down = c.execute(
        "SELECT sum(bytes_down) FROM sdb_device").rows()[0][0]
    assert down > 0


def test_http_device_stats_and_metrics_export():
    """GET /device parses; /_stats carries the device section; /metrics
    exports the compile-ledger gauges and the DeviceCompile histogram."""
    from serenedb_tpu.server.http_server import HttpServer
    c = _mk_conn()
    c.execute("SELECT count(*), sum(v), sum(w) FROM l JOIN r "
              "ON l.ik = r.ik WHERE v > 0")
    srv = HttpServer(c.db)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        dev = json.load(urllib.request.urlopen(base + "/device"))
        assert {"devices", "programs", "program_cache",
                "column_cache"} <= set(dev)
        assert any(d["dispatches"] > 0 for d in dev["devices"])
        assert any(p["family"] == "fused" for p in dev["programs"])
        assert dev["program_cache"]["cap"] >= 1
        stats = json.load(urllib.request.urlopen(base + "/_stats"))
        assert "device" in stats and "devices" in stats["device"]
        text = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "serenedb_device_programs_compiled" in text
        assert "serenedb_device_program_cache_hits" in text
        assert "serenedb_device_compile_seconds_bucket" in text
        assert "serenedb_device_recompile_storms" in text
    finally:
        srv.stop()


def test_explain_compile_key_text_and_json():
    """First execution of a fresh fused shape pays the compile (EXPLAIN
    ANALYZE says compile=miss); the repeat says compile=hit. FORMAT
    JSON carries the same as "Device Compile"."""
    c = _mk_conn(seed=123)              # fresh providers => fresh keys
    q = ("SELECT count(*), sum(v), sum(w) FROM l JOIN r "
         "ON l.ik = r.ik WHERE v > 17")
    out = "\n".join(r[0] for r in
                    c.execute(f"EXPLAIN ANALYZE {q}").rows())
    assert "compile=miss" in out
    out2 = "\n".join(r[0] for r in
                     c.execute(f"EXPLAIN ANALYZE {q}").rows())
    assert "compile=hit" in out2 and "compile=miss" not in out2
    j = json.loads(c.execute(
        f"EXPLAIN (ANALYZE, FORMAT JSON) {q}").rows()[0][0])

    def compile_keys(node, acc):
        if "Device Compile" in node:
            acc.append(node["Device Compile"])
        for sub in node.get("Plans", []):
            compile_keys(sub, acc)
        return acc

    keys = compile_keys(j[0]["Plan"] if isinstance(j, list) else j, [])
    assert keys and all(k == "hit" for k in keys)


def test_device_compile_trace_spans_at_all_sites():
    """The satellite: device_compile spans appear in the flight
    recorder for every program family's first dispatch — fused join,
    device aggregate, device top-N (the sites that stamped nothing
    before this PR)."""
    from serenedb_tpu.obs.trace import FLIGHT
    c = _mk_conn(seed=77)
    cases = [
        ("SELECT count(*), sum(v), sum(w) FROM l JOIN r "
         "ON l.ik = r.ik WHERE v > 31", "fused"),
        ("SELECT ik, count(*), sum(v) FROM l WHERE v > 13 "
         "GROUP BY ik ORDER BY ik NULLS LAST", "device_agg"),
        ("SELECT * FROM l ORDER BY v DESC LIMIT 5", "device_topn"),
    ]
    for q, family in cases:
        c.execute(q)
        entry = FLIGHT.last()
        spans = [s for s in entry["spans"]
                 if s["name"] == "device_compile" and s["args"] and
                 s["args"].get("family") == family]
        assert spans, f"no device_compile span for {family}"
        assert all(s["end_ns"] > s["begin_ns"] for s in spans)


def test_telemetry_off_keeps_ledgers_dark():
    """With the switch off the program cache still works (bounded,
    identical keys) but no stats/transfer accounting accumulates."""
    with _global("serene_device_telemetry", False):
        c = _mk_conn(seed=31)
        q = ("SELECT count(*), sum(v), sum(w) FROM l JOIN r "
             "ON l.ik = r.ik WHERE v > 5")
        fam0 = obs_device.PROGRAMS.family("fused")
        led0 = obs_device.LEDGER.snapshot()
        up0 = sum(d["bytes_up"] for d in led0.values())
        r1 = _rows(c, q)
        r2 = _rows(c, q)
        assert r1 == r2
        fam1 = obs_device.PROGRAMS.family("fused")
        led1 = obs_device.LEDGER.snapshot()
        assert fam1["hits"] == fam0["hits"] and \
            fam1["misses"] == fam0["misses"]
        assert sum(d["bytes_up"] for d in led1.values()) == up0


def test_settings_declared_and_not_result_affecting():
    from serenedb_tpu.cache.result import RESULT_AFFECTING_SETTINGS
    assert SETTINGS.get_global("serene_device_telemetry") in (True, False)
    assert SETTINGS.get_global("serene_program_cache_entries") >= 1
    assert "serene_device_telemetry" not in RESULT_AFFECTING_SETTINGS
    assert "serene_program_cache_entries" not in RESULT_AFFECTING_SETTINGS
    # both are GLOBAL scope: SET per session must be rejected
    c = Database().connect()
    from serenedb_tpu import errors
    for name in ("serene_device_telemetry",
                 "serene_program_cache_entries"):
        with pytest.raises(errors.SqlError):
            c.execute(f"SET {name} = 1")
