"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware is single-chip in this environment; multi-chip sharding is
validated on virtual CPU devices (jax's xla_force_host_platform_device_count),
matching how the driver dry-runs `__graft_entry__.dryrun_multichip`.

Must run before anything imports jax, hence top-of-conftest env mutation.
"""

import os

# This environment preloads jax via sitecustomize and pins
# jax_platforms='axon,cpu' (the tunneled TPU), which silently overrides
# JAX_PLATFORMS env vars — tests must force the config back to the virtual
# 8-device CPU mesh BEFORE any backend initializes.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, "virtual CPU mesh not active"

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# scripts/verify_tier1.sh arms the zone-map debug assert for one extra
# parity pass: every pruned morsel is re-scanned and any block-stats/data
# divergence fails the query loudly instead of sampling its way past.
if os.environ.get("SERENE_ZONEMAP_VERIFY"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REGISTRY

    _SDB_REGISTRY.set_global("serene_zonemap_verify", True)

# scripts/verify_tier1.sh join-filter parity leg: force the sideways
# min/max join filter to the given value ("on"/"off") for a whole run —
# the off pass proves the filter is an optimization layer only (results
# identical without it), the on pass combines with SERENE_ZONEMAP_VERIFY
# so every join-filter-pruned probe morsel is re-scanned structurally.
if os.environ.get("SERENE_JOIN_FILTER"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_JF

    _SDB_REG_JF.set_global("serene_join_filter",
                           os.environ["SERENE_JOIN_FILTER"])

# scripts/verify_tier1.sh profiler parity leg: force serene_profile to
# the given value ("on"/"off") for a whole run — the on pass proves the
# span instrumentation observes without changing a single result bit,
# the off pass that the engine runs clean with the collector absent.
if os.environ.get("SERENE_PROFILE"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_PROF

    _SDB_REG_PROF.set_global("serene_profile",
                             os.environ["SERENE_PROFILE"])


# scripts/verify_tier1.sh result-cache parity leg: force
# serene_result_cache to the given value ("on"/"off") for a whole run —
# the on pass proves cached statements are bit-identical to executed
# ones across the parity suites, the off pass that the engine runs
# clean with both cache tiers absent.
if os.environ.get("SERENE_RESULT_CACHE"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_RC

    _SDB_REG_RC.set_global("serene_result_cache",
                           os.environ["SERENE_RESULT_CACHE"])


# scripts/verify_tier1.sh fused-pipeline parity leg: force
# serene_device_fused to the given value ("on"/"off") for a whole run —
# the off pass proves the fused device tier is an optimization layer
# only (every suite passes with it globally dark), the on pass that the
# one-dispatch programs are bit-identical to the host oracle.
if os.environ.get("SERENE_DEVICE_FUSED"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_DF

    _SDB_REG_DF.set_global("serene_device_fused",
                           os.environ["SERENE_DEVICE_FUSED"])


# scripts/verify_tier1.sh fused-admission parity leg: force
# serene_device_fused_ext to the given value ("on"/"off") for a whole
# run — the off pass restores the PR-7 admission walls (string/FILTER/
# DISTINCT aggregates, outer joins, residual predicates and the
# chained agg→top-N all fall back to the host oracle), proving the
# widened tier is an optimization layer only.
if os.environ.get("SERENE_DEVICE_FUSED_EXT"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_DFX

    _SDB_REG_DFX.set_global("serene_device_fused_ext",
                            os.environ["SERENE_DEVICE_FUSED_EXT"])


# scripts/verify_tier1.sh search-batch parity leg: force
# serene_search_batch to the given value ("on"/"off") for a whole run —
# the off pass proves the query batcher is a dispatch-coalescing layer
# only (the search and ES suites are bit-identical with every query
# dispatched serially), the on pass that coalesced scoring perturbs
# nothing.
if os.environ.get("SERENE_SEARCH_BATCH"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_SB

    _SDB_REG_SB.set_global("serene_search_batch",
                           os.environ["SERENE_SEARCH_BATCH"])


# scripts/verify_tier1.sh sharded-execution parity leg: force
# serene_shards to the given count (e.g. "4") for a whole run — the
# parallel/join/device/search parity suites then execute everything
# through the sharded tier, proving per-shard pipelines plus the
# cross-shard combiners are bit-identical to unsharded execution.
if os.environ.get("SERENE_SHARDS"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_SH

    _SDB_REG_SH.set_global("serene_shards", os.environ["SERENE_SHARDS"])


# scripts/verify_tier1.sh multichip parity leg: force
# serene_shard_combine to the given value ("device"/"host"/"auto") for
# a whole run — combined with SERENE_SHARDS=4 the device pass executes
# every sharded fused pipeline as ONE shard_map collective dispatch and
# every sharded search merge as an in-program all_gather hop, proving
# the in-program combine is bit-identical to the host combine across
# the parity suites.
if os.environ.get("SERENE_SHARD_COMBINE"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_SC

    _SDB_REG_SC.set_global("serene_shard_combine",
                           os.environ["SERENE_SHARD_COMBINE"])


# scripts/verify_tier1.sh timeline-tracing parity leg: force
# serene_trace to the given value ("on"/"off") for a whole run — the on
# pass proves span recording (pool queue waits, batcher fan-out, shard
# pipelines, device phases) observes without changing a single result
# bit, the off pass that the engine runs clean with the tracer absent.
if os.environ.get("SERENE_TRACE"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_TR

    _SDB_REG_TR.set_global("serene_trace", os.environ["SERENE_TRACE"])


# scripts/verify_tier1.sh memory-accounting parity leg: force
# serene_mem_account to the given value ("on"/"off") for a whole run —
# the on pass proves per-query live/peak byte accounting + progress
# registration observe without changing a single result bit at any
# worker/shard count, the off pass that the engine runs clean with the
# accountant absent.
if os.environ.get("SERENE_MEM_ACCOUNT"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_MA

    _SDB_REG_MA.set_global("serene_mem_account",
                           os.environ["SERENE_MEM_ACCOUNT"])


# scripts/verify_tier1.sh device-telemetry parity leg: force
# serene_device_telemetry to the given value ("on"/"off") and/or cap
# the compiled-program LRU at a tiny SERENE_PROGRAM_CACHE_ENTRIES
# (e.g. "4") for a whole run — the capped pass exercises program
# eviction + re-compile on every suite query, proving the bounded
# ledger changes WHEN programs compile, never what they compute.
if os.environ.get("SERENE_DEVICE_TELEMETRY"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_DT

    _SDB_REG_DT.set_global("serene_device_telemetry",
                           os.environ["SERENE_DEVICE_TELEMETRY"])

if os.environ.get("SERENE_PROGRAM_CACHE_ENTRIES"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_PC

    _SDB_REG_PC.set_global("serene_program_cache_entries",
                           os.environ["SERENE_PROGRAM_CACHE_ENTRIES"])


# scripts/verify_tier1.sh workload-governor parity leg: arm the
# admission gate suite-wide (e.g. "8" — every non-exempt statement then
# takes/queues for a governor slot), a generous global serene_work_mem
# ceiling (e.g. "2GB" — the budget check runs against every accounted
# statement without ever firing) and/or fair-share picking, proving the
# governor steers scheduling only: the admission/parallel/shard/
# resources suites must stay bit-identical with it armed.
# scripts/verify_tier1.sh pass 19 (front door): run the serving suites
# with the socket accept gate forced tiny (SERENE_MAX_CONNECTIONS=8 —
# the rejection path exercised suite-wide), or the asyncio tier swapped
# for the legacy ThreadingHTTPServer parity oracle
# (SERENE_FRONTDOOR=off), or idle reaping pinned on
_FRONTDOOR_ENV_HOOKS = {
    "SERENE_FRONTDOOR": "serene_frontdoor",
    "SERENE_MAX_CONNECTIONS": "serene_max_connections",
    "SERENE_IDLE_CONN_TIMEOUT_S": "serene_idle_conn_timeout_s",
}
for _env, _setting in _FRONTDOOR_ENV_HOOKS.items():
    if os.environ.get(_env):
        from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_FD

        _SDB_REG_FD.set_global(_setting, os.environ[_env])


_GOVERNOR_ENV_HOOKS = {
    "SERENE_MAX_CONCURRENT_STATEMENTS": "serene_max_concurrent_statements",
    "SERENE_WORK_MEM": "serene_work_mem",
    "SERENE_FAIR_SHARE": "serene_fair_share",
}
for _env, _setting in _GOVERNOR_ENV_HOOKS.items():
    if os.environ.get(_env):
        from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_GOV

        _SDB_REG_GOV.set_global(_setting, os.environ[_env])


# scripts/verify_tier1.sh posting-pool parity leg: force
# serene_posting_pool to the given value ("on"/"off") and/or pin the
# page budget at a tiny SERENE_POSTING_PAGES (e.g. "16") for a whole
# run — the tiny-budget pass forces partial residency and mid-stream
# LRU eviction on every ragged search, proving the device-resident
# paged tier changes WHERE postings are scored, never a result bit.
if os.environ.get("SERENE_POSTING_POOL"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_PP

    _SDB_REG_PP.set_global("serene_posting_pool",
                           os.environ["SERENE_POSTING_POOL"])

if os.environ.get("SERENE_POSTING_PAGES"):
    from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_PPG

    _SDB_REG_PPG.set_global("serene_posting_pages",
                            os.environ["SERENE_POSTING_PAGES"])


# scripts/verify_tier1.sh streaming-ingest parity leg: force the
# write-path knobs to the given values for a whole run —
# SERENE_PARALLEL_INGEST=on (with a small SERENE_INGEST_CHUNK_DOCS so
# modest suite corpora actually chunk-split) proves the parallel
# analysis merge is bit-identical to the serial oracle suite-wide; a
# tiny SERENE_MAX_SEGMENTS walks the tiered merge ladder on practically
# every append; SERENE_BACKGROUND_MERGE/SERENE_GROUP_COMMIT flip the
# maintenance placement and fsync coalescing without a result-bit
# anywhere.
_INGEST_ENV_HOOKS = {
    "SERENE_PARALLEL_INGEST": "serene_parallel_ingest",
    "SERENE_INGEST_CHUNK_DOCS": "serene_ingest_chunk_docs",
    "SERENE_MAX_SEGMENTS": "serene_max_segments",
    "SERENE_BACKGROUND_MERGE": "serene_background_merge",
    "SERENE_GROUP_COMMIT": "serene_group_commit",
}
for _env, _setting in _INGEST_ENV_HOOKS.items():
    if os.environ.get(_env):
        from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_ING

        _SDB_REG_ING.set_global(_setting, os.environ[_env])


# scripts/verify_tier1.sh vector-retrieval leg: force the paged vector
# pool to the given value ("on"/"off") and/or starve its page budget at
# a tiny SERENE_VECTOR_PAGES (e.g. "16") for a whole run — the starved
# pass forces cold-path fallback and LRU eviction on practically every
# knn/MaxSim dispatch, proving the pool changes WHERE vectors are
# scored (resident HBM region vs per-call upload), never a result bit.
# SERENE_NPROBE pins the probe width suite-wide (e.g. "4096" = every
# probe search degenerates to a full-cluster scan, so the brute-force
# parity oracles must match bit-for-bit); SERENE_MAXSIM flips the
# MaxSim scorer between the device program and the f64 host oracle.
_VECTOR_ENV_HOOKS = {
    "SERENE_VECTOR_POOL": "serene_vector_pool",
    "SERENE_VECTOR_PAGES": "serene_vector_pages",
    "SERENE_NPROBE": "serene_nprobe",
    "SERENE_MAXSIM": "serene_maxsim",
}
for _env, _setting in _VECTOR_ENV_HOOKS.items():
    if os.environ.get(_env):
        from serenedb_tpu.utils.config import REGISTRY as _SDB_REG_VEC

        _SDB_REG_VEC.set_global(_setting, os.environ[_env])


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running throughput tests, excluded from "
        "the tier-1 `-m 'not slow'` runs")


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    from serenedb_tpu.utils import faults
    faults.clear()
