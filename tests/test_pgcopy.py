"""PG binary COPY format (reference: duckdb_pg_binary_copy.cpp).

Codec unit tests, engine file round-trips, and the wire sub-protocol with
format=1 announcements."""

import asyncio
import struct
import threading

import pytest

from serenedb_tpu.columnar import dtypes as dt
from serenedb_tpu.columnar import pgcopy
from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError
from serenedb_tpu.server.pgwire import PgServer


def test_codec_roundtrip_scalars():
    cases = [
        (True, dt.BOOL), (False, dt.BOOL),
        (7, dt.SMALLINT), (-123456, dt.INT), (2**40, dt.BIGINT),
        (1.5, dt.FLOAT), (2.25, dt.DOUBLE),
        ("héllo", dt.VARCHAR),
        (946_684_800_000_000, dt.TIMESTAMP),   # 2000-01-01 → binary 0
        (10_957, dt.DATE),
        (90_000_000, dt.INTERVAL),
    ]
    for v, t in cases:
        raw = pgcopy.encode_value(v, t)
        back = pgcopy.decode_value(raw, t)
        if t is dt.FLOAT:
            assert back == pytest.approx(v)
        else:
            assert back == v, t
    assert pgcopy.encode_value(946_684_800_000_000,
                               dt.TIMESTAMP) == b"\x00" * 8
    assert pgcopy.encode_value(10_957, dt.DATE) == b"\x00" * 4


def test_codec_malformed():
    with pytest.raises(SqlError):
        pgcopy.decode_value(b"\x01", dt.INT)       # short payload
    with pytest.raises(SqlError):
        pgcopy.decode_stream(b"NOTPGCOPY", [dt.INT])
    # truncated tuple
    bad = pgcopy.header() + struct.pack("!h", 1) + struct.pack("!i", 4)
    with pytest.raises(SqlError):
        pgcopy.decode_stream(bad, [dt.INT])


def test_file_roundtrip(tmp_path):
    c = Database().connect()
    c.execute("CREATE TABLE src (a INT, b DOUBLE, s TEXT, "
              "ts TIMESTAMP, d DATE)")
    c.execute("INSERT INTO src VALUES "
              "(1, 1.5, 'x', TIMESTAMP '2024-06-01 12:00:00', "
              " DATE '2024-06-01'), "
              "(2, NULL, NULL, NULL, NULL)")
    p = str(tmp_path / "out.bin")
    r = c.execute(f"COPY src TO '{p}' WITH (FORMAT binary)")
    assert r.command_tag == "COPY 2"
    raw = open(p, "rb").read()
    assert raw.startswith(pgcopy.SIGNATURE)
    assert raw.endswith(struct.pack("!h", -1))
    c.execute("CREATE TABLE dst (a INT, b DOUBLE, s TEXT, "
              "ts TIMESTAMP, d DATE)")
    r = c.execute(f"COPY dst FROM '{p}' WITH (FORMAT binary)")
    assert r.command_tag == "COPY 2"
    assert c.execute("SELECT * FROM dst ORDER BY a").rows() == \
        c.execute("SELECT * FROM src ORDER BY a").rows()


@pytest.fixture(scope="module")
def server():
    import sys
    db = Database()
    srv = PgServer(db, port=0)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def go():
            await srv.start()
            started.set()
            await asyncio.Event().wait()
        try:
            loop.run_until_complete(go())
        except RuntimeError:
            pass
    t = threading.Thread(target=run, daemon=True)
    t.start()
    started.wait(10)
    return srv


def _client(server):
    import sys
    sys.path.insert(0, "tests")
    from test_pgwire import RawPg
    return RawPg(server.port)


def test_wire_binary_copy_roundtrip(server):
    c = _client(server)
    c.query("CREATE TABLE wb (a INT, s TEXT)")
    # binary COPY IN: the response must announce format 1
    c.send(b"Q", b"COPY wb FROM STDIN WITH (FORMAT binary)\x00")
    kind, payload = c.read_msg()
    assert kind == b"G"
    overall, ncols = struct.unpack_from("!bH", payload)
    assert overall == 1 and ncols == 2
    body = pgcopy.header()
    body += struct.pack("!h", 2)
    body += struct.pack("!i", 4) + struct.pack("!i", 42)
    body += struct.pack("!i", 5) + b"hello"
    body += struct.pack("!h", 2)
    body += struct.pack("!i", 4) + struct.pack("!i", 7)
    body += struct.pack("!i", -1)                      # NULL text
    body += pgcopy.trailer()
    c.send(b"d", body)
    c.send(b"c")
    tags = []
    while True:
        kind, payload = c.read_msg()
        if kind == b"C":
            tags.append(payload[:-1].decode())
        elif kind == b"Z":
            break
    assert tags == ["COPY 2"]
    _, rows, _, _ = c.query("SELECT a, s FROM wb ORDER BY a")
    assert rows == [("7", None), ("42", "hello")]

    # binary COPY OUT round-trips the same bytes semantically
    c.send(b"Q", b"COPY wb TO STDOUT WITH (FORMAT binary)\x00")
    kind, payload = c.read_msg()
    assert kind == b"H"
    overall, _ = struct.unpack_from("!bH", payload)
    assert overall == 1
    data = []
    while True:
        kind, payload = c.read_msg()
        if kind == b"d":
            data.append(payload)
        elif kind == b"Z":
            break
    blob = b"".join(data)
    cols = pgcopy.decode_stream(blob, [dt.INT, dt.VARCHAR])
    assert sorted(zip(cols[0], cols[1]),
                  key=lambda t: t[0]) == [(7, None), (42, "hello")]
    c.query("DROP TABLE wb")
    c.close()


def test_copy_from_file_column_subset(tmp_path):
    """COPY t (cols) FROM file maps by NAME for parquet and positionally
    over the LISTED columns for csv (PG semantics) — never positionally
    over the table schema."""
    c = Database().connect()
    c.execute("CREATE TABLE s2 (a INT, b INT)")
    c.execute("INSERT INTO s2 VALUES (1, 100)")
    pq = str(tmp_path / "s2.parquet")
    c.execute(f"COPY s2 TO '{pq}' WITH (FORMAT parquet)")
    c.execute("CREATE TABLE d2 (a INT, b INT)")
    c.execute(f"COPY d2 (b) FROM '{pq}' WITH (FORMAT parquet)")
    assert c.execute("SELECT a, b FROM d2").rows() == [(None, 100)]
    with pytest.raises(SqlError):
        c.execute(f"COPY d2 (a, b, a) FROM '{pq}' WITH (FORMAT parquet)")
    # csv subset: the file holds exactly the listed column
    csvp = str(tmp_path / "only_b.csv")
    open(csvp, "w").write("7\n8\n")
    c.execute("CREATE TABLE d3 (a INT, b INT)")
    c.execute(f"COPY d3 (b) FROM '{csvp}' WITH (FORMAT csv)")
    assert c.execute("SELECT a, b FROM d3 ORDER BY b").rows() == \
        [(None, 7), (None, 8)]
    with pytest.raises(SqlError):
        c.execute(f"COPY d3 (nope) FROM '{csvp}' WITH (FORMAT csv)")
