import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError


@pytest.fixture
def conn():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE t (a INT, b DOUBLE, s TEXT)")
    c.execute("INSERT INTO t VALUES (1, 1.5, 'x'), (2, 2.5, 'y'), "
              "(3, 3.5, 'x'), (NULL, NULL, NULL)")
    return c


def test_select_literal():
    c = Database().connect()
    assert c.execute("SELECT 1 + 2").scalar() == 3
    assert c.execute("SELECT 'a' || 'b'").scalar() == "ab"
    assert c.execute("SELECT NULL").scalar() is None


def test_select_star(conn):
    r = conn.execute("SELECT * FROM t")
    assert r.names == ["a", "b", "s"]
    assert len(r.rows()) == 4


def test_where_filter(conn):
    r = conn.execute("SELECT a FROM t WHERE a > 1")
    assert sorted(x[0] for x in r.rows()) == [2, 3]


def test_where_null_semantics(conn):
    # NULL comparisons never match
    r = conn.execute("SELECT a FROM t WHERE a <> 2")
    assert sorted(x[0] for x in r.rows()) == [1, 3]
    r = conn.execute("SELECT a FROM t WHERE a IS NULL")
    assert [x[0] for x in r.rows()] == [None]


def test_order_by_nulls(conn):
    r = conn.execute("SELECT a FROM t ORDER BY a")
    assert [x[0] for x in r.rows()] == [1, 2, 3, None]  # nulls last asc
    r = conn.execute("SELECT a FROM t ORDER BY a DESC")
    assert [x[0] for x in r.rows()] == [None, 3, 2, 1]  # nulls first desc
    r = conn.execute("SELECT a FROM t ORDER BY a DESC NULLS LAST")
    assert [x[0] for x in r.rows()] == [3, 2, 1, None]


def test_limit_offset(conn):
    r = conn.execute("SELECT a FROM t ORDER BY a LIMIT 2 OFFSET 1")
    assert [x[0] for x in r.rows()] == [2, 3]


def test_scalar_aggregates(conn):
    r = conn.execute("SELECT count(*), count(a), sum(a), avg(b), min(s), "
                     "max(s) FROM t")
    row = r.rows()[0]
    assert row[0] == 4
    assert row[1] == 3
    assert row[2] == 6
    assert row[3] == pytest.approx(2.5)
    assert row[4] == "x"
    assert row[5] == "y"


def test_empty_aggregate():
    c = Database().connect()
    c.execute("CREATE TABLE e (a INT)")
    r = c.execute("SELECT count(*), sum(a), min(a) FROM e")
    assert r.rows()[0] == (0, None, None)


def test_group_by(conn):
    r = conn.execute(
        "SELECT s, count(*), sum(a) FROM t GROUP BY s ORDER BY s NULLS LAST")
    assert r.rows() == [("x", 2, 4), ("y", 1, 2), (None, 1, None)]


def test_group_by_alias_and_position(conn):
    r = conn.execute("SELECT s AS k, count(*) FROM t GROUP BY k ORDER BY 1 "
                     "NULLS LAST")
    assert [x[0] for x in r.rows()] == ["x", "y", None]
    r2 = conn.execute("SELECT s, count(*) FROM t GROUP BY 1 ORDER BY 1 NULLS LAST")
    assert [x[0] for x in r2.rows()] == ["x", "y", None]


def test_having(conn):
    r = conn.execute("SELECT s, count(*) AS c FROM t GROUP BY s "
                     "HAVING count(*) > 1")
    assert r.rows() == [("x", 2)]


def test_group_expr_in_select(conn):
    r = conn.execute("SELECT a % 2, count(*) FROM t WHERE a IS NOT NULL "
                     "GROUP BY a % 2 ORDER BY 1")
    assert r.rows() == [(0, 1), (1, 2)]


def test_ungrouped_column_rejected(conn):
    with pytest.raises(SqlError) as e:
        conn.execute("SELECT a, count(*) FROM t GROUP BY s")
    assert e.value.sqlstate == "42803"


def test_distinct(conn):
    r = conn.execute("SELECT DISTINCT s FROM t ORDER BY s NULLS LAST")
    assert [x[0] for x in r.rows()] == ["x", "y", None]


def test_count_distinct(conn):
    assert conn.execute("SELECT count(DISTINCT s) FROM t").scalar() == 2


def test_case(conn):
    r = conn.execute("SELECT CASE WHEN a > 2 THEN 'big' WHEN a > 1 THEN 'mid' "
                     "ELSE 'small' END FROM t WHERE a IS NOT NULL ORDER BY a")
    assert [x[0] for x in r.rows()] == ["small", "mid", "big"]


def test_in_between_like(conn):
    assert conn.execute(
        "SELECT count(*) FROM t WHERE a IN (1, 3)").scalar() == 2
    assert conn.execute(
        "SELECT count(*) FROM t WHERE a BETWEEN 2 AND 3").scalar() == 2
    assert conn.execute(
        "SELECT count(*) FROM t WHERE s LIKE 'x%'").scalar() == 2
    assert conn.execute(
        "SELECT count(*) FROM t WHERE s NOT LIKE 'x%'").scalar() == 1


def test_string_functions():
    c = Database().connect()
    assert c.execute("SELECT upper('ab')").scalar() == "AB"
    assert c.execute("SELECT length('hello')").scalar() == 5
    assert c.execute("SELECT substr('hello', 2, 3)").scalar() == "ell"
    assert c.execute("SELECT replace('aaa', 'a', 'b')").scalar() == "bbb"
    assert c.execute("SELECT split_part('a,b,c', ',', 2)").scalar() == "b"
    assert c.execute("SELECT coalesce(NULL, 'x')").scalar() == "x"


def test_math_and_division():
    c = Database().connect()
    assert c.execute("SELECT 7 / 2").scalar() == 3       # PG int division
    assert c.execute("SELECT -7 / 2").scalar() == -3     # trunc toward zero
    assert c.execute("SELECT 7.0 / 2").scalar() == 3.5
    assert c.execute("SELECT 7 % 3").scalar() == 1
    assert c.execute("SELECT abs(-5)").scalar() == 5
    with pytest.raises(SqlError) as e:
        c.execute("SELECT 1 / 0")
    assert e.value.sqlstate == "22012"


def test_cast():
    c = Database().connect()
    assert c.execute("SELECT '42'::INT").scalar() == 42
    assert c.execute("SELECT CAST(1.7 AS INT)").scalar() == 2
    assert c.execute("SELECT 1::BOOLEAN").scalar() is True
    with pytest.raises(SqlError) as e:
        c.execute("SELECT 'xyz'::INT")
    assert e.value.sqlstate == "22P02"


def test_update_delete(conn):
    conn.execute("UPDATE t SET b = 0.0 WHERE a = 2")
    assert conn.execute("SELECT b FROM t WHERE a = 2").scalar() == 0.0
    conn.execute("DELETE FROM t WHERE a = 1")
    assert conn.execute("SELECT count(*) FROM t").scalar() == 3


def test_join():
    c = Database().connect()
    c.execute("CREATE TABLE l (id INT, v TEXT)")
    c.execute("CREATE TABLE r (id INT, w TEXT)")
    c.execute("INSERT INTO l VALUES (1,'a'), (2,'b'), (3,'c')")
    c.execute("INSERT INTO r VALUES (2,'B'), (3,'C'), (4,'D')")
    rows = c.execute("SELECT l.v, r.w FROM l JOIN r ON l.id = r.id "
                     "ORDER BY l.id").rows()
    assert rows == [("b", "B"), ("c", "C")]
    rows = c.execute("SELECT l.v, r.w FROM l LEFT JOIN r ON l.id = r.id "
                     "ORDER BY l.id").rows()
    assert rows == [("a", None), ("b", "B"), ("c", "C")]


def test_subquery_from(conn):
    r = conn.execute("SELECT s, c FROM (SELECT s, count(*) AS c FROM t "
                     "GROUP BY s) sub WHERE c > 1")
    assert r.rows() == [("x", 2)]


def test_views(conn):
    conn.execute("CREATE VIEW v AS SELECT a FROM t WHERE a > 1")
    assert conn.execute("SELECT count(*) FROM v").scalar() == 2
    conn.execute("DROP VIEW v")
    with pytest.raises(SqlError):
        conn.execute("SELECT * FROM v")


def test_create_table_as(conn):
    conn.execute("CREATE TABLE t2 AS SELECT a, b FROM t WHERE a IS NOT NULL")
    assert conn.execute("SELECT count(*) FROM t2").scalar() == 3


def test_set_show(conn):
    conn.execute("SET sdb_nprobe = 32")
    assert conn.execute("SHOW sdb_nprobe").rows()[0][0] == "32"
    conn.execute("RESET sdb_nprobe")
    assert conn.execute("SHOW sdb_nprobe").rows()[0][0] == "8"


def test_error_codes(conn):
    with pytest.raises(SqlError) as e:
        conn.execute("SELECT * FROM no_such_table")
    assert e.value.sqlstate == "42P01"
    with pytest.raises(SqlError) as e:
        conn.execute("SELECT no_such_col FROM t")
    assert e.value.sqlstate == "42703"
    with pytest.raises(SqlError) as e:
        conn.execute("SELECT no_such_fn(a) FROM t")
    assert e.value.sqlstate == "42883"


def test_explain(conn):
    r = conn.execute("EXPLAIN SELECT s, count(*) FROM t WHERE a > 1 GROUP BY s")
    text = "\n".join(x[0] for x in r.rows())
    assert "Aggregate" in text and "Scan" in text


def test_system_tables(conn):
    r = conn.execute("SELECT tablename FROM pg_tables")
    assert ("t",) in r.rows()
    r = conn.execute("SELECT count(*) FROM sdb_settings")
    assert r.scalar() > 5


def test_multi_statement(conn):
    rs = conn.execute_all("SELECT 1; SELECT 2;")
    assert [r.scalar() for r in rs] == [1, 2]


def test_values_clause():
    c = Database().connect()
    r = c.execute("VALUES (1, 'a'), (2, 'b')")
    assert r.rows() == [(1, "a"), (2, "b")]


def test_full_text_operators(conn):
    c = Database().connect()
    c.execute("CREATE TABLE docs (body TEXT)")
    c.execute("INSERT INTO docs VALUES ('The quick brown fox'), "
              "('a lazy dog sleeps'), ('quick dogs run')")
    assert c.execute(
        "SELECT count(*) FROM docs WHERE body ## 'quick'").scalar() == 2
    # phrase: consecutive terms
    assert c.execute(
        "SELECT count(*) FROM docs WHERE body ## 'brown fox'").scalar() == 1
    assert c.execute(
        "SELECT count(*) FROM docs WHERE body ## 'quick fox'").scalar() == 0
    # boolean query
    assert c.execute(
        "SELECT count(*) FROM docs WHERE body @@ 'quick & dog'").scalar() == 1
    assert c.execute(
        "SELECT count(*) FROM docs WHERE body @@ 'fox | dog'").scalar() == 3


def test_lexer_longest_match_operators():
    # regression: <=> must not lex as <= + > (operator table ordering)
    c = Database().connect()
    assert c.execute("SELECT '[1,0]' <=> '[0,1]'").scalar() == pytest.approx(1.0)
    assert c.execute("SELECT '[1,2]' <#> '[3,4]'").scalar() == -11.0
    assert c.execute("SELECT 2 <= 3").scalar() is True


def test_window_int_sum_exact_past_2_53():
    c = Database().connect()
    c.execute("CREATE TABLE big (x BIGINT)")
    c.execute("INSERT INTO big VALUES (9007199254740993), (1)")
    win = c.execute("SELECT sum(x) OVER () FROM big LIMIT 1").scalar()
    agg = c.execute("SELECT sum(x) FROM big").scalar()
    assert win == agg == 9007199254740994


def test_date_trunc_per_row_units():
    c = Database().connect()
    c.execute("CREATE TABLE dtr (u TEXT, t TIMESTAMP)")
    c.execute("INSERT INTO dtr VALUES "
              "('month', TIMESTAMP '2024-03-17 14:25:11'), "
              "('day', TIMESTAMP '2024-03-17 14:25:11'), "
              "(NULL, TIMESTAMP '2024-03-17 14:25:11')")
    rows = c.execute("SELECT date_trunc(u, t)::VARCHAR FROM dtr").rows()
    assert rows[0][0] == "2024-03-01 00:00:00"
    assert rows[1][0] == "2024-03-17 00:00:00"
    assert rows[2][0] is None


def test_parse_cache_does_not_corrupt_reexecution():
    c = Database().connect()
    c.execute("CREATE TABLE pc (a INT)")
    c.execute("INSERT INTO pc VALUES (1), (2)")
    q = "SELECT a, 100 + row_number() OVER (ORDER BY a) FROM pc"
    first = c.execute(q).rows()
    second = c.execute(q).rows()
    assert first == second == [(1, 101), (2, 102)]


def test_pg_stat_activity():
    import gc
    from serenedb_tpu.engine import Database
    db = Database()
    c1, c2 = db.connect(), db.connect()
    c2.execute("BEGIN")
    rows = c1.execute("SELECT pid, usename, state, query "
                      "FROM pg_stat_activity ORDER BY pid").rows()
    assert len(rows) == 2
    assert rows[0][2] == "active"
    assert rows[0][3].startswith("SELECT pid")   # full SQL text, like PG
    assert rows[1][2] == "idle in transaction"
    c2.execute("ROLLBACK")
    del c2
    gc.collect()
    assert c1.execute(
        "SELECT count(*) FROM pg_stat_activity").scalar() == 1


def test_insert_select_maps_positionally():
    # review finding: name-based alignment silently inserted NULLs
    c = Database().connect()
    c.execute("CREATE TABLE src (x INT, y TEXT)")
    c.execute("INSERT INTO src VALUES (1, 'a')")
    c.execute("CREATE TABLE dst (a INT, b TEXT)")
    rows = c.execute("INSERT INTO dst SELECT x, y FROM src "
                     "RETURNING a, b").rows()
    assert rows == [(1, "a")]
    assert c.execute("SELECT a, b FROM dst").rows() == [(1, "a")]
    from serenedb_tpu.errors import SqlError
    import pytest as _pytest
    with _pytest.raises(SqlError) as e:
        c.execute("INSERT INTO dst SELECT x FROM src")
    assert e.value.sqlstate == "42601"


def test_update_returning_zero_rows_keeps_shape():
    c = Database().connect()
    c.execute("CREATE TABLE zr (a INT)")
    r = c.execute("UPDATE zr SET a = 1 WHERE false RETURNING a")
    assert r.names == ["a"] and r.rows() == []


def test_window_frame_validation_and_framed_minmax():
    import pytest as _pytest

    from serenedb_tpu import errors as _errors
    from serenedb_tpu.engine import Database
    c = Database().connect()
    c.execute("CREATE TABLE wf (t INT, v INT)")
    c.execute("INSERT INTO wf VALUES (1, 5), (2, 1), (3, 9), (4, 3)")
    # invalid frames raise 42P20 like PG
    for bad in [
        "SELECT sum(v) OVER (ORDER BY t ROWS BETWEEN CURRENT ROW AND "
        "1 PRECEDING) FROM wf",
        "SELECT sum(v) OVER (ORDER BY t ROWS 2 FOLLOWING) FROM wf",
        "SELECT sum(v) OVER (ORDER BY t ROWS BETWEEN 3 PRECEDING AND "
        "5 PRECEDING) FROM wf",
    ]:
        with _pytest.raises(_errors.SqlError):
            c.execute(bad)
    # unbounded-side framed min/max use the linear scan paths
    r = [x[0] for x in c.execute(
        "SELECT min(v) OVER (ORDER BY t ROWS BETWEEN UNBOUNDED PRECEDING "
        "AND CURRENT ROW) FROM wf ORDER BY t").rows()]
    assert r == [5, 1, 1, 1]
    r = [x[0] for x in c.execute(
        "SELECT max(v) OVER (ORDER BY t ROWS BETWEEN CURRENT ROW AND "
        "UNBOUNDED FOLLOWING) FROM wf ORDER BY t").rows()]
    assert r == [9, 9, 9, 3]
    r = [x[0] for x in c.execute(
        "SELECT max(v) OVER (ORDER BY t ROWS BETWEEN UNBOUNDED PRECEDING "
        "AND UNBOUNDED FOLLOWING) FROM wf ORDER BY t").rows()]
    assert r == [9, 9, 9, 9]


def test_array_literal_cast_and_errors():
    import pytest as _pytest

    from serenedb_tpu import errors as _errors
    from serenedb_tpu.engine import Database
    c = Database().connect()
    c.execute("CREATE TABLE al (a INT[])")
    c.execute("INSERT INTO al VALUES ('{1,2,3}'), ('[4,5]'), (NULL)")
    r = sorted(x[0] for x in c.execute(
        "SELECT array_length(a, 1) FROM al WHERE a IS NOT NULL").rows())
    assert r == [2, 3]
    with _pytest.raises(_errors.SqlError):
        c.execute("INSERT INTO al VALUES ('nonsense')")
    with _pytest.raises(_errors.SqlError):
        c.execute("SELECT regexp_split_to_array('a', '[')")
    with _pytest.raises(_errors.SqlError):
        c.execute("SELECT trunc()")


def test_natural_join_view_replans_after_alter():
    """NATURAL JOIN resolution must not freeze into shared ASTs (views
    re-plan against the live schema)."""
    from serenedb_tpu.engine import Database
    c = Database().connect()
    c.execute("CREATE TABLE na (id INT, x TEXT)")
    c.execute("CREATE TABLE nb (id INT, y TEXT)")
    c.execute("INSERT INTO na VALUES (1, 'p'), (2, 'q')")
    c.execute("INSERT INTO nb VALUES (2, 'Q')")
    c.execute("CREATE VIEW nv AS SELECT * FROM na NATURAL JOIN nb")
    assert c.execute("SELECT count(*) FROM nv").scalar() == 1
    # run twice: the second plan must re-resolve, not reuse mutated state
    assert c.execute("SELECT count(*) FROM nv").scalar() == 1


def test_review_fixes_wave2():
    import pytest as _pytest

    from serenedb_tpu import errors as _errors
    from serenedb_tpu.engine import Database
    c = Database().connect()
    # cascade recursion through view chains
    c.execute("CREATE TABLE base (v INT)")
    c.execute("CREATE VIEW va AS SELECT * FROM base")
    c.execute("CREATE VIEW vb AS SELECT * FROM va")
    with _pytest.raises(_errors.SqlError):
        c.execute("DROP VIEW va")             # vb depends
    c.execute("DROP TABLE base CASCADE")
    with _pytest.raises(_errors.SqlError):
        c.execute("SELECT * FROM vb")         # dropped along
    # same-named tables in different schemas don't cross-block
    c.execute("CREATE SCHEMA s1")
    c.execute("CREATE SCHEMA s2")
    c.execute("CREATE TABLE s1.dup (v INT)")
    c.execute("CREATE TABLE s2.dup (v INT)")
    c.execute("CREATE VIEW vd AS SELECT * FROM s1.dup")
    c.execute("DROP TABLE s2.dup")            # must not 2BP01
    with _pytest.raises(_errors.SqlError):
        c.execute("DROP TABLE s1.dup")
    # separator is part of the aggregate identity
    c.execute("CREATE TABLE sg (s TEXT)")
    c.execute("INSERT INTO sg VALUES ('a'), ('b')")
    r = c.execute("SELECT string_agg(s, ',' ORDER BY s), "
                  "string_agg(s, ';' ORDER BY s) FROM sg").rows()[0]
    assert r == ("a,b", "a;b")
    # NULLS FIRST inside aggregate ORDER BY
    c.execute("CREATE TABLE nf (x INT, s TEXT)")
    c.execute("INSERT INTO nf VALUES (1, 'a'), (NULL, 'n'), (2, 'b')")
    assert c.execute("SELECT string_agg(s, ',' ORDER BY x NULLS FIRST) "
                     "FROM nf").scalar() == "n,a,b"
    assert c.execute("SELECT string_agg(s, ',' ORDER BY x) "
                     "FROM nf").scalar() == "a,b,n"
    # ORDER BY rejected in non-aggregate calls
    with _pytest.raises(_errors.SqlError):
        c.execute("SELECT upper(s ORDER BY s) FROM sg")
    # temporal values in json builders render as text
    assert c.execute(
        "SELECT json_build_object('d', DATE '2024-01-02')").scalar() \
        == '{"d": "2024-01-02"}'


def test_dml_join_schema_qualified_and_atomic_returning():
    import pytest as _pytest

    from serenedb_tpu import errors as _errors
    from serenedb_tpu.engine import Database
    c = Database().connect()
    c.execute("CREATE SCHEMA s1")
    c.execute("CREATE SCHEMA s2")
    c.execute("CREATE TABLE s1.t (id INT, v INT)")
    c.execute("CREATE TABLE s2.t (id INT, v INT)")
    c.execute("INSERT INTO s1.t VALUES (1, 100), (2, 200)")
    c.execute("INSERT INTO s2.t VALUES (1, 999)")
    c.execute("UPDATE s1.t SET v = x.v FROM s2.t x WHERE s1.t.id = x.id")
    assert sorted(c.execute("SELECT id, v FROM s1.t").rows()) == \
        [(1, 999), (2, 200)]
    # an invalid RETURNING aborts BEFORE the mutation applies
    c.execute("CREATE TABLE tgt (id INT, v INT)")
    c.execute("CREATE TABLE src (id INT, w INT)")
    c.execute("INSERT INTO tgt VALUES (1, 0)")
    c.execute("INSERT INTO src VALUES (1, 10)")
    with _pytest.raises(_errors.SqlError):
        c.execute("UPDATE tgt SET v = src.w FROM src "
                  "WHERE tgt.id = src.id RETURNING src.w")
    assert c.execute("SELECT v FROM tgt WHERE id = 1").scalar() == 0
    with _pytest.raises(_errors.SqlError):
        c.execute("DELETE FROM tgt USING src "
                  "WHERE tgt.id = src.id RETURNING src.w")
    assert c.execute("SELECT count(*) FROM tgt").scalar() == 1
