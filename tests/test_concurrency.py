"""Concurrency stress: parallel readers/writers/maintenance on one
Database (the reference's race-safety tier is sanitizer builds + named
connections; here threads + invariants)."""

import threading

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError


def test_parallel_readers_and_writers(tmp_path):
    db = Database(str(tmp_path / "data"))
    c0 = db.connect()
    c0.execute("CREATE TABLE t (a INT, body TEXT)")
    c0.execute("CREATE INDEX ON t USING inverted (body)")
    errors_seen = []
    N_WRITERS, N_READERS, ROUNDS = 3, 3, 30

    def writer(wid):
        conn = db.connect()
        try:
            for i in range(ROUNDS):
                conn.execute(
                    f"INSERT INTO t VALUES ({wid * 1000 + i}, "
                    f"'doc {wid} {i} common')")
        except Exception as e:  # pragma: no cover
            errors_seen.append(e)

    def reader():
        conn = db.connect()
        try:
            for _ in range(ROUNDS):
                n = conn.execute("SELECT count(*) FROM t").scalar()
                assert 0 <= n <= N_WRITERS * ROUNDS
                conn.execute("SELECT count(*) FROM t WHERE body @@ 'common'")
                conn.execute("SELECT a, sum(a) OVER () FROM t LIMIT 5")
        except Exception as e:  # pragma: no cover
            errors_seen.append(e)

    def maintainer():
        try:
            for _ in range(10):
                db.maintenance.run_once()
        except Exception as e:  # pragma: no cover
            errors_seen.append(e)

    threads = ([threading.Thread(target=writer, args=(w,))
                for w in range(N_WRITERS)] +
               [threading.Thread(target=reader) for _ in range(N_READERS)] +
               [threading.Thread(target=maintainer)])
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "worker thread hung"
    assert not errors_seen, errors_seen[:3]
    # all writes landed exactly once
    assert c0.execute("SELECT count(*) FROM t").scalar() == \
        N_WRITERS * ROUNDS
    db.close()

    # recovery agrees after concurrent WAL traffic
    db2 = Database(str(tmp_path / "data"))
    assert db2.connect().execute("SELECT count(*) FROM t").scalar() == \
        N_WRITERS * ROUNDS
    db2.close()


def test_parallel_ddl_no_corruption():
    db = Database()
    errs = []

    def ddl(k):
        conn = db.connect()
        for i in range(10):
            try:
                conn.execute(f"CREATE TABLE c{k}_{i} (x INT)")
                conn.execute(f"INSERT INTO c{k}_{i} VALUES ({i})")
            except SqlError:
                pass
            except Exception as e:  # pragma: no cover
                errs.append(e)
            finally:
                try:
                    conn.execute(f"DROP TABLE IF EXISTS c{k}_{i}")
                except Exception as e:  # pragma: no cover
                    errs.append(e)

    threads = [threading.Thread(target=ddl, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "ddl thread hung"
    assert not errs, errs[:3]
    assert db.connect().execute(
        "SELECT count(*) FROM pg_tables").scalar() == 0
