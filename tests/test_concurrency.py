"""Concurrency stress: parallel readers/writers/maintenance on one
Database (the reference's race-safety tier is sanitizer builds + named
connections; here threads + invariants)."""

import threading

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError


def test_parallel_readers_and_writers(tmp_path):
    db = Database(str(tmp_path / "data"))
    c0 = db.connect()
    c0.execute("CREATE TABLE t (a INT, body TEXT)")
    c0.execute("CREATE INDEX ON t USING inverted (body)")
    errors_seen = []
    N_WRITERS, N_READERS, ROUNDS = 3, 3, 30

    def writer(wid):
        conn = db.connect()
        try:
            for i in range(ROUNDS):
                conn.execute(
                    f"INSERT INTO t VALUES ({wid * 1000 + i}, "
                    f"'doc {wid} {i} common')")
        except Exception as e:  # pragma: no cover
            errors_seen.append(e)

    def reader():
        conn = db.connect()
        try:
            for _ in range(ROUNDS):
                n = conn.execute("SELECT count(*) FROM t").scalar()
                assert 0 <= n <= N_WRITERS * ROUNDS
                conn.execute("SELECT count(*) FROM t WHERE body @@ 'common'")
                conn.execute("SELECT a, sum(a) OVER () FROM t LIMIT 5")
        except Exception as e:  # pragma: no cover
            errors_seen.append(e)

    def maintainer():
        try:
            for _ in range(10):
                db.maintenance.run_once()
        except Exception as e:  # pragma: no cover
            errors_seen.append(e)

    threads = ([threading.Thread(target=writer, args=(w,))
                for w in range(N_WRITERS)] +
               [threading.Thread(target=reader) for _ in range(N_READERS)] +
               [threading.Thread(target=maintainer)])
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "worker thread hung"
    assert not errors_seen, errors_seen[:3]
    # all writes landed exactly once
    assert c0.execute("SELECT count(*) FROM t").scalar() == \
        N_WRITERS * ROUNDS
    db.close()

    # recovery agrees after concurrent WAL traffic
    db2 = Database(str(tmp_path / "data"))
    assert db2.connect().execute("SELECT count(*) FROM t").scalar() == \
        N_WRITERS * ROUNDS
    db2.close()


def test_parallel_ddl_no_corruption():
    db = Database()
    errs = []

    def ddl(k):
        conn = db.connect()
        for i in range(10):
            try:
                conn.execute(f"CREATE TABLE c{k}_{i} (x INT)")
                conn.execute(f"INSERT INTO c{k}_{i} VALUES ({i})")
            except SqlError:
                pass
            except Exception as e:  # pragma: no cover
                errs.append(e)
            finally:
                try:
                    conn.execute(f"DROP TABLE IF EXISTS c{k}_{i}")
                except Exception as e:  # pragma: no cover
                    errs.append(e)

    threads = [threading.Thread(target=ddl, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "ddl thread hung"
    assert not errs, errs[:3]
    assert db.connect().execute(
        "SELECT count(*) FROM pg_tables").scalar() == 0


class TestSnapshotIsolation:
    def test_repeatable_reads(self):
        db = Database()
        c1, c2 = db.connect(), db.connect()
        c1.execute("CREATE TABLE si (a INT)")
        c1.execute("INSERT INTO si VALUES (1), (2)")
        c1.execute("BEGIN")
        assert c1.execute("SELECT count(*) FROM si").scalar() == 2
        c2.execute("INSERT INTO si VALUES (3)")
        # txn keeps its snapshot; outside sees the new row
        assert c1.execute("SELECT count(*) FROM si").scalar() == 2
        assert c2.execute("SELECT count(*) FROM si").scalar() == 3
        c1.execute("COMMIT")
        assert c1.execute("SELECT count(*) FROM si").scalar() == 3

    def test_buffered_writes_and_rollback(self):
        db = Database()
        c1, c2 = db.connect(), db.connect()
        c1.execute("CREATE TABLE bw (a INT)")
        c1.execute("INSERT INTO bw VALUES (1)")
        c1.execute("BEGIN")
        c1.execute("INSERT INTO bw VALUES (2)")
        c1.execute("UPDATE bw SET a = a + 10")
        assert sorted(c1.execute("SELECT a FROM bw").rows()) == \
            [(11,), (12,)]
        assert c2.execute("SELECT a FROM bw").rows() == [(1,)]
        c1.execute("ROLLBACK")
        assert c1.execute("SELECT a FROM bw").rows() == [(1,)]
        c1.execute("BEGIN")
        c1.execute("DELETE FROM bw")
        c1.execute("COMMIT")
        assert c2.execute("SELECT count(*) FROM bw").scalar() == 0

    def test_first_committer_wins(self):
        db = Database()
        c1, c2 = db.connect(), db.connect()
        c1.execute("CREATE TABLE fc (a INT)")
        c1.execute("INSERT INTO fc VALUES (1)")
        c1.execute("BEGIN")
        c1.execute("UPDATE fc SET a = 99")
        c2.execute("UPDATE fc SET a = 77")          # commits first
        with pytest.raises(SqlError) as e:
            c1.execute("COMMIT")
        assert e.value.sqlstate == "40001"
        assert c2.execute("SELECT a FROM fc").rows() == [(77,)]
        # the aborted session is usable again
        assert c1.execute("SELECT a FROM fc").rows() == [(77,)]

    def test_commit_of_failed_txn_rolls_back(self):
        db = Database()
        c = db.connect()
        c.execute("CREATE TABLE ft (a INT)")
        c.execute("BEGIN")
        c.execute("INSERT INTO ft VALUES (1)")
        with pytest.raises(SqlError):
            c.execute("SELECT 1/0")
        res = c.execute("COMMIT")
        assert res.command_tag == "ROLLBACK"
        assert c.execute("SELECT count(*) FROM ft").scalar() == 0

    def test_txn_commit_is_durable(self, tmp_path):
        path = str(tmp_path / "data")
        db = Database(path)
        c = db.connect()
        c.execute("CREATE TABLE dur (a INT)")
        c.execute("INSERT INTO dur VALUES (1)")
        c.execute("BEGIN")
        c.execute("INSERT INTO dur VALUES (2), (3)")
        c.execute("UPDATE dur SET a = a * 10 WHERE a = 1")
        c.execute("COMMIT")
        # rolled-back txns must leave no WAL trace
        c.execute("BEGIN")
        c.execute("INSERT INTO dur VALUES (999)")
        c.execute("ROLLBACK")
        db.close()
        db2 = Database(path)
        rows = sorted(db2.connect().execute("SELECT a FROM dur").rows())
        assert rows == [(2,), (3,), (10,)]
        db2.close()

    def test_nested_begin_preserves_txn(self):
        db = Database()
        c = db.connect()
        c.execute("CREATE TABLE nb (a INT)")
        c.execute("BEGIN")
        c.execute("INSERT INTO nb VALUES (1)")
        c.execute("BEGIN")            # PG: warning no-op
        c.execute("COMMIT")
        assert c.execute("SELECT count(*) FROM nb").scalar() == 1

    def test_copy_out_sees_txn_snapshot(self):
        db = Database()
        c1, c2 = db.connect(), db.connect()
        c1.execute("CREATE TABLE co (a INT)")
        c1.execute("INSERT INTO co VALUES (1)")
        c1.execute("BEGIN")
        c1.execute("INSERT INTO co VALUES (2)")
        c2.execute("INSERT INTO co VALUES (99)")
        lines, n, _ = c1.copy_out_data(
            __import__("serenedb_tpu.sql.ast", fromlist=["ast"]).CopyStmt(
                ["co"], None, True, {}))
        vals = sorted(int(ln.strip()) for ln in lines)
        # own write visible, concurrent commit not
        assert vals == [1, 2] and n == 2
        c1.execute("ROLLBACK")

    def test_commit_after_table_recreated_conflicts(self):
        db = Database()
        c1, c2 = db.connect(), db.connect()
        c1.execute("CREATE TABLE rc (a INT)")
        c1.execute("BEGIN")
        c1.execute("INSERT INTO rc VALUES (1)")
        c2.execute("DROP TABLE rc")
        c2.execute("CREATE TABLE rc (a INT)")
        with pytest.raises(SqlError) as e:
            c1.execute("COMMIT")
        assert e.value.sqlstate == "40001"
        assert c2.execute("SELECT count(*) FROM rc").scalar() == 0

    def test_txn_snapshot_uses_search_index(self):
        db = Database()
        c1, c2 = db.connect(), db.connect()
        c1.execute("CREATE TABLE sx (body TEXT)")
        c1.execute("INSERT INTO sx VALUES ('quick fox'), ('lazy dog')")
        c1.execute("CREATE INDEX ON sx USING inverted (body)")
        c1.execute("BEGIN")
        assert c1.execute(
            "SELECT count(*) FROM sx WHERE body @@ 'quick'").scalar() == 1
        # concurrent write does not disturb the pinned indexed snapshot
        c2.execute("INSERT INTO sx VALUES ('quick wit')")
        assert c1.execute(
            "SELECT count(*) FROM sx WHERE body @@ 'quick'").scalar() == 1
        c1.execute("COMMIT")
        assert c1.execute(
            "SELECT count(*) FROM sx WHERE body @@ 'quick'").scalar() == 2

    def test_alter_table_in_txn_is_autocommit(self):
        db = Database()
        c1, c2 = db.connect(), db.connect()
        c1.execute("CREATE TABLE at (a INT)")
        c1.execute("INSERT INTO at VALUES (1)")
        c1.execute("BEGIN")
        c1.execute("ALTER TABLE at ADD COLUMN b INT")
        c1.execute("COMMIT")
        # column survives COMMIT (previously silently lost)
        assert "b" in [r[0] for r in c2.execute(
            "SELECT column_name FROM information_schema.columns "
            "WHERE table_name = 'at'").rows()]
        # RENAME in txn: the real table renames; no uncommitted rows leak
        c1.execute("BEGIN")
        c1.execute("INSERT INTO at VALUES (5, 5)")
        c1.execute("ALTER TABLE at RENAME TO at2")
        assert c2.execute("SELECT count(*) FROM at2").scalar() == 1
        c1.execute("ROLLBACK")
        assert c2.execute("SELECT count(*) FROM at2").scalar() == 1
        # table is fully usable afterwards (no stale _txn_key KeyError)
        c2.execute("INSERT INTO at2 VALUES (2, 2)")
        assert c2.execute("SELECT count(*) FROM at2").scalar() == 2

    def test_concurrent_txn_increments_lose_nothing(self):
        # classic lost-update check: N threads x M increments in txns with
        # retry-on-40001 must sum exactly
        db = Database()
        c0 = db.connect()
        c0.execute("CREATE TABLE ctr (v INT)")
        c0.execute("INSERT INTO ctr VALUES (0)")
        N_THREADS, N_INCR = 4, 12
        errs = []

        def worker():
            c = db.connect()
            for _ in range(N_INCR):
                for attempt in range(60):
                    try:
                        c.execute("BEGIN")
                        c.execute("UPDATE ctr SET v = v + 1")
                        c.execute("COMMIT")
                        break
                    except SqlError as e:
                        if e.sqlstate != "40001":
                            errs.append(e)
                            return
                        # aborted: txn state already cleared; retry
                else:
                    errs.append(RuntimeError("retries exhausted"))
                    return

        ts = [threading.Thread(target=worker) for _ in range(N_THREADS)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not errs, errs[:2]
        assert c0.execute("SELECT v FROM ctr").scalar() == \
            N_THREADS * N_INCR

    def test_savepoints(self):
        db = Database()
        c = db.connect()
        c.execute("CREATE TABLE sp (a INT)")
        c.execute("BEGIN")
        c.execute("INSERT INTO sp VALUES (1)")
        c.execute("SAVEPOINT s1")
        c.execute("INSERT INTO sp VALUES (2)")
        c.execute("SAVEPOINT s2")
        c.execute("DELETE FROM sp")
        assert c.execute("SELECT count(*) FROM sp").scalar() == 0
        c.execute("ROLLBACK TO s2")
        assert c.execute("SELECT count(*) FROM sp").scalar() == 2
        c.execute("ROLLBACK TO SAVEPOINT s1")
        assert c.execute("SELECT count(*) FROM sp").scalar() == 1
        c.execute("RELEASE s1")
        with pytest.raises(SqlError) as e:
            c.execute("ROLLBACK TO s1")   # released: gone, and the error
        assert e.value.sqlstate == "3B001"
        # ... aborts the txn (PG semantics) so COMMIT rolls back
        assert c.execute("COMMIT").command_tag == "ROLLBACK"
        assert c.execute("SELECT a FROM sp").rows() == []
        # clean txn: the kept work commits
        c.execute("BEGIN")
        c.execute("INSERT INTO sp VALUES (1)")
        c.execute("SAVEPOINT s1")
        c.execute("INSERT INTO sp VALUES (2)")
        c.execute("ROLLBACK TO s1")
        c.execute("COMMIT")
        assert c.execute("SELECT a FROM sp").rows() == [(1,)]

    def test_savepoint_recovers_failed_txn(self):
        db = Database()
        c = db.connect()
        c.execute("CREATE TABLE spf (a INT)")
        c.execute("BEGIN")
        c.execute("INSERT INTO spf VALUES (1)")
        c.execute("SAVEPOINT s")
        c.execute("INSERT INTO spf VALUES (2)")
        with pytest.raises(SqlError):
            c.execute("SELECT 1/0")
        with pytest.raises(SqlError) as e:
            c.execute("SELECT 1")
        assert e.value.sqlstate == "25P02"
        c.execute("ROLLBACK TO s")          # PG: un-fails the txn
        c.execute("INSERT INTO spf VALUES (3)")
        c.execute("COMMIT")
        assert sorted(c.execute("SELECT a FROM spf").rows()) == \
            [(1,), (3,)]

    def test_savepoint_errors(self):
        db = Database()
        c = db.connect()
        with pytest.raises(SqlError) as e:
            c.execute("SAVEPOINT x")
        assert e.value.sqlstate == "25P01"
        c.execute("BEGIN")
        with pytest.raises(SqlError) as e:
            c.execute("RELEASE nope")
        assert e.value.sqlstate == "3B001"
        c.execute("ROLLBACK")

    def test_rolled_back_writes_do_not_conflict(self):
        # review finding: a net-zero ROLLBACK TO left the table in the
        # conflict check -> spurious 40001
        db = Database()
        c1, c2 = db.connect(), db.connect()
        c1.execute("CREATE TABLE za (a INT)")
        c1.execute("CREATE TABLE zb (a INT)")
        c1.execute("BEGIN")
        c1.execute("SAVEPOINT s")
        c1.execute("INSERT INTO za VALUES (1)")
        c1.execute("ROLLBACK TO s")          # net-zero on za
        c2.execute("INSERT INTO za VALUES (9)")
        c1.execute("INSERT INTO zb VALUES (2)")
        c1.execute("COMMIT")                 # must not 40001
        assert c2.execute("SELECT count(*) FROM zb").scalar() == 1

    def test_release_rejected_in_failed_txn(self):
        db = Database()
        c = db.connect()
        c.execute("BEGIN")
        c.execute("SAVEPOINT s")
        with pytest.raises(SqlError):
            c.execute("SELECT 1/0")
        with pytest.raises(SqlError) as e:
            c.execute("RELEASE s")
        assert e.value.sqlstate == "25P02"
        c.execute("ROLLBACK TO s")           # the recovery point survives
        c.execute("COMMIT")


def test_parallel_bulk_ingest_group_commit(tmp_path):
    """Concurrent bulk INSERTs (no PK) take the parallel-ingest fast path:
    WAL encode + group-commit fsync outside the DML lock. Every row must
    land, survive recovery, and the WAL must replay to the same state
    (reference: ParallelSink + per-thread ChunkWriters,
    duckdb_physical_search_insert.cpp:107-369)."""
    import threading

    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c0 = db.connect()
    c0.execute("CREATE TABLE bulk (t INT, v INT)")
    c0.execute("CREATE TABLE other (v INT)")

    N_THREADS, N_STMTS, N_ROWS = 6, 8, 50
    errs = []

    def worker(tid):
        try:
            c = db.connect()
            for s in range(N_STMTS):
                vals = ", ".join(f"({tid}, {s * N_ROWS + r})"
                                 for r in range(N_ROWS))
                c.execute(f"INSERT INTO bulk VALUES {vals}")
            c.execute(f"INSERT INTO other VALUES ({tid})")
        except Exception as e:  # surface into the main thread
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs

    expect = N_THREADS * N_STMTS * N_ROWS
    assert c0.execute("SELECT count(*) FROM bulk").scalar() == expect
    # per-thread rows are complete and distinct
    rows = c0.execute(
        "SELECT t, count(*), count(DISTINCT v) FROM bulk GROUP BY t").rows()
    assert all(n == N_STMTS * N_ROWS and d == N_STMTS * N_ROWS
               for _t, n, d in rows)
    db.close()

    # crash-free reopen replays the group-committed WAL identically
    db2 = Database(d)
    c2 = db2.connect()
    assert c2.execute("SELECT count(*) FROM bulk").scalar() == expect
    assert c2.execute("SELECT count(*) FROM other").scalar() == N_THREADS
    assert c2.execute("SELECT sum(v) FROM bulk").scalar() == \
        N_THREADS * sum(range(N_STMTS * N_ROWS))
    db2.close()


def test_fast_path_insert_vs_truncate_and_vacuum(tmp_path):
    """Mutators and checkpoint capture quiesce in-flight fast-path commits:
    live state must equal recovered state no matter how inserts interleave
    with TRUNCATE and VACUUM (review regression: a checkpoint capturing a
    tick past an unpublished commit would lose fsynced rows)."""
    import random
    import threading

    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c0 = db.connect()
    c0.execute("CREATE TABLE t (v INT)")
    stop = threading.Event()
    errs = []

    def inserter():
        c = db.connect()
        try:
            while not stop.is_set():
                c.execute("INSERT INTO t VALUES (1), (2), (3)")
        except Exception as e:
            errs.append(e)

    def mutator():
        c = db.connect()
        try:
            for _ in range(20):
                r = random.random()
                if r < 0.4:
                    c.execute("TRUNCATE t")
                elif r < 0.7:
                    c.execute("VACUUM t")
                else:
                    c.execute("DELETE FROM t WHERE v = 2")
        except Exception as e:
            errs.append(e)

    ins = [threading.Thread(target=inserter) for _ in range(3)]
    for t in ins:
        t.start()
    mut = threading.Thread(target=mutator)
    mut.start()
    mut.join()
    stop.set()
    for t in ins:
        t.join()
    assert not errs, errs

    live = c0.execute("SELECT count(*), coalesce(sum(v), 0) FROM t").rows()
    db.close()
    db2 = Database(d)
    rec = db2.connect().execute(
        "SELECT count(*), coalesce(sum(v), 0) FROM t").rows()
    assert rec == live, (live, rec)
    db2.close()


def test_fast_path_publish_order_matches_replay(tmp_path):
    """Review regression: DELETE WAL records are positional, so fast-path
    publishes MUST land in tick order — distinct per-thread payloads +
    a positional delete + crash must replay to the IDENTICAL physical
    row order, or the delete removes different rows after recovery."""
    import threading

    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c0 = db.connect()
    c0.execute("CREATE TABLE t (tid INT, seq INT)")
    errs = []

    def worker(tid):
        try:
            c = db.connect()
            for s in range(30):
                c.execute(f"INSERT INTO t VALUES ({tid}, {s})")
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    # positional delete over the live order
    c0.execute("DELETE FROM t WHERE seq % 3 = 0")
    live = c0.execute("SELECT tid, seq FROM t").rows()   # physical order
    db.crash()   # no checkpoint: reopen replays the WAL from scratch

    db2 = Database(d)
    rec = db2.connect().execute("SELECT tid, seq FROM t").rows()
    assert rec == live, "replayed row order diverged from live order"
    db2.close()


def test_readers_never_block_on_dml():
    """The round-4 lock redesign: SELECTs pin the table's atomic
    (batch, version, epoch) publication without any lock, so a reader
    that lands mid-UPDATE sees either the full before- or the full
    after-state — never a torn intermediate and never a wait on the
    writer (reference: morsel-parallel reads vs the old global RLock,
    server_engine.cpp:225-244)."""
    db = Database(None)
    c0 = db.connect()
    c0.execute("CREATE TABLE t (k INT, v INT)")
    c0.execute("INSERT INTO t VALUES " +
               ", ".join(f"({i}, 1)" for i in range(5000)))
    stop = threading.Event()
    errs = []

    def updater():
        c = db.connect()
        try:
            while not stop.is_set():
                # delete+reinsert of every row: any torn intermediate
                # would show up as a partial count or a mixed sum
                c.execute("UPDATE t SET v = v + 1")
        except Exception as e:
            errs.append(e)

    def reader():
        c = db.connect()
        try:
            for _ in range(60):
                rows = c.execute(
                    "SELECT count(*), count(DISTINCT v) FROM t").rows()
                n, distinct = rows[0]
                assert n == 5000, f"torn read: {n} rows"
                assert distinct == 1, f"torn read: {distinct} versions mixed"
        except Exception as e:
            errs.append(e)

    upd = threading.Thread(target=updater)
    readers = [threading.Thread(target=reader) for _ in range(3)]
    upd.start()
    for r in readers:
        r.start()
    for r in readers:
        r.join(timeout=120)
        assert not r.is_alive(), "reader hung behind DML"
    stop.set()
    upd.join(timeout=60)
    assert not upd.is_alive()
    assert not errs, errs[:3]


def test_dml_on_distinct_tables_not_serialized():
    """Writers of DIFFERENT tables hold different write_locks: a writer
    stalled inside its critical section must not delay DML on another
    table (the old global RLock serialized them)."""
    import time

    db = Database(None)
    c0 = db.connect()
    c0.execute("CREATE TABLE slow_t (a INT)")
    c0.execute("CREATE TABLE fast_t (a INT)")
    c0.execute("INSERT INTO slow_t VALUES (1)")
    slow = db.resolve_table(["slow_t"])
    entered = threading.Event()
    release = threading.Event()
    errs = []

    def slow_writer():
        # hold slow_t's write lock the way a long UPDATE would
        try:
            with db.quiesced([slow]):
                entered.set()
                assert release.wait(timeout=60)
        except Exception as e:
            errs.append(e)

    t = threading.Thread(target=slow_writer)
    t.start()
    assert entered.wait(timeout=10)
    c = db.connect()
    t0 = time.monotonic()
    for i in range(20):
        c.execute(f"INSERT INTO fast_t VALUES ({i})")
    n = c.execute("SELECT count(*) FROM fast_t").scalar()
    elapsed = time.monotonic() - t0
    release.set()
    t.join(timeout=30)
    assert not errs, errs
    assert n == 20
    # generous bound: 20 tiny inserts must not wait on slow_t's writer
    assert elapsed < 10, f"DML serialized across tables ({elapsed:.1f}s)"


def test_alter_vs_dml_subquery_no_deadlock():
    """Lock-order regression: DML holds the table write_lock and takes
    db.lock when its WHERE subquery resolves tables; ALTER must use the
    same order (write_lock outer, db.lock inner) or the pair deadlocks."""
    db = Database(None)
    c0 = db.connect()
    c0.execute("CREATE TABLE big (a INT)")
    c0.execute("CREATE TABLE sel (a INT)")
    c0.execute("INSERT INTO big VALUES " +
               ", ".join(f"({i})" for i in range(2000)))
    c0.execute("INSERT INTO sel VALUES (1), (3), (5)")
    errs = []

    def dml():
        c = db.connect()
        try:
            for _ in range(25):
                c.execute("UPDATE big SET a = a WHERE a IN "
                          "(SELECT a FROM sel)")
        except Exception as e:
            errs.append(e)

    def alter():
        c = db.connect()
        try:
            for i in range(25):
                c.execute(f"ALTER TABLE big ADD COLUMN c{i} INT")
        except Exception as e:
            errs.append(e)

    ts = [threading.Thread(target=dml), threading.Thread(target=alter)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=90)
        assert not t.is_alive(), "ALTER/DML deadlocked"
    assert not errs, errs[:2]
