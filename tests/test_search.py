"""Search core tests: segment build, filter parity vs the brute-force
semantics contract, BM25 top-k correctness, SQL pushdown."""

import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.search.analysis import get_analyzer
from serenedb_tpu.search.query import (eval_query_on_text, match_phrase_brute,
                                       parse_query)
from serenedb_tpu.search.searcher import SegmentSearcher
from serenedb_tpu.search.segment import build_field_index

WORDS = ("apple banana cherry quick brown fox jumps over lazy dog search "
         "engine database index query term").split()


def make_corpus(n=300, seed=3):
    rng = np.random.default_rng(seed)
    docs = []
    for _ in range(n):
        ln = rng.integers(3, 30)
        docs.append(" ".join(rng.choice(WORDS, ln)))
    return docs


@pytest.fixture(scope="module")
def corpus():
    return make_corpus()


@pytest.fixture(scope="module")
def searcher(corpus):
    an = get_analyzer("text")
    fi = build_field_index(corpus, an)
    return SegmentSearcher(fi, an, len(corpus))


QUERIES = [
    "apple",
    "apple & banana",
    "apple | cherry",
    "quick & !lazy",
    '"quick brown"',
    '"quick brown fox"',
    "qui*",
    "(apple | banana) & cherry",
    "!apple",
    "nonexistentterm",
    "apple & nonexistentterm",
]


@pytest.mark.parametrize("q", QUERIES)
def test_filter_parity_with_brute_force(searcher, corpus, q):
    an = get_analyzer("text")
    node = parse_query(q, an)
    expected = {i for i, text in enumerate(corpus)
                if eval_query_on_text(node, an, text)}
    got = set(searcher.eval_filter(node).tolist())
    assert got == expected, q


@pytest.mark.parametrize("q", ["apple", "apple | cherry", "apple & banana",
                               '"quick brown"', "qui*", "quick & !lazy"])
def test_topk_matches_cpu_reference(searcher, q):
    an = get_analyzer("text")
    node = parse_query(q, an)
    k = 10
    scores, docs = searcher.topk(node, k)
    # every returned doc must match the filter semantics
    match = set(searcher.eval_filter(node).tolist())
    assert all(int(d) in match for d in docs), q
    # scores descending
    assert all(scores[i] >= scores[i + 1] - 1e-5
               for i in range(len(scores) - 1)), q
    # exact score check vs the CPU reference over the match set
    tids = searcher.scoring_terms(node)
    if match and tids:
        ref_scores, ref_docs = searcher._cpu_score(
            np.asarray(sorted(match), dtype=np.int32), tids, k)
        np.testing.assert_allclose(scores, ref_scores[:len(scores)],
                                   rtol=2e-3, atol=1e-3)


def test_bm25_manual_formula(searcher):
    """Single-term score equals the hand-computed BM25 on one doc."""
    an = get_analyzer("text")
    node = parse_query("apple", an)
    scores, docs = searcher.topk(node, 1)
    d = int(docs[0])
    fi = searcher.index
    tid = fi.term_id("apple")
    pd, pt = fi.postings(tid)
    tf = float(pt[np.searchsorted(pd, d)])
    df = float(fi.doc_freq[tid])
    n = searcher.num_docs
    idf = np.log(1 + (n - df + 0.5) / (df + 0.5))
    dl = float(fi.norms[d])
    expected = idf * (1.2 + 1) * tf / (tf + 1.2 * (1 - 0.75 + 0.75 * dl / fi.avgdl))
    assert scores[0] == pytest.approx(expected, rel=1e-3)


def test_phrase_positions(searcher, corpus):
    an = get_analyzer("text")
    node = parse_query('"brown fox"', an)
    got = set(searcher.eval_filter(node).tolist())
    expected = set(np.flatnonzero(
        match_phrase_brute(np.asarray(corpus, dtype=object),
                           np.asarray(["brown fox"] * len(corpus),
                                      dtype=object))).tolist())
    assert got == expected


# -- SQL integration -------------------------------------------------------

@pytest.fixture
def sql_conn(corpus):
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT)")
    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.exec.tables import MemTable
    batch = Batch.from_pydict({
        "id": list(range(len(corpus))),
        "body": list(corpus),
    })
    db.schemas["main"].tables["docs"] = MemTable("docs", batch)
    return c


def test_sql_index_pushdown_parity(sql_conn):
    q = "SELECT count(*) FROM docs WHERE body @@ 'apple & banana'"
    brute = sql_conn.execute(q).scalar()
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    ex = sql_conn.execute("EXPLAIN " + q).rows()
    assert any("SearchScan" in r[0] for r in ex)
    assert sql_conn.execute(q).scalar() == brute


def test_sql_phrase_pushdown_parity(sql_conn):
    q = "SELECT count(*) FROM docs WHERE body ## 'quick brown'"
    brute = sql_conn.execute(q).scalar()
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    assert sql_conn.execute(q).scalar() == brute


def test_sql_topk_scored(sql_conn):
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    r = sql_conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple' "
        "ORDER BY s DESC LIMIT 5")
    ex = sql_conn.execute(
        "EXPLAIN SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple' "
        "ORDER BY s DESC LIMIT 5").rows()
    assert any("TopK" in row[0] for row in ex)
    rows = r.rows()
    assert 0 < len(rows) <= 5
    scores = [row[1] for row in rows]
    assert scores == sorted(scores, reverse=True)
    assert all(s > 0 for s in scores)


def test_sql_index_stale_after_insert_falls_back(sql_conn):
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    sql_conn.execute("INSERT INTO docs VALUES (9999, 'zzzuniqueterm here')")
    # stale index must NOT be used (data_version mismatch) — brute force
    assert sql_conn.execute(
        "SELECT count(*) FROM docs WHERE body @@ 'zzzuniqueterm'"
    ).scalar() == 1
    ex = sql_conn.execute(
        "EXPLAIN SELECT count(*) FROM docs WHERE body @@ 'zzzuniqueterm'"
    ).rows()
    assert not any("SearchScan" in r[0] for r in ex)


def test_sql_mixed_predicate_residual(sql_conn):
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    q = ("SELECT count(*) FROM docs WHERE body @@ 'apple' AND id < 100")
    with_index = sql_conn.execute(q).scalar()
    # oracle: no index (different table name, same data via subquery trick)
    brute = sql_conn.execute(
        "SELECT count(*) FROM (SELECT * FROM docs) d "
        "WHERE body @@ 'apple' AND id < 100").scalar()
    assert with_index == brute


def test_tfidf_scorer_differs_from_bm25(sql_conn):
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    bm = sql_conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple' "
        "ORDER BY s DESC LIMIT 500").rows()
    tf = sql_conn.execute(
        "SELECT id, tfidf(body) AS s FROM docs WHERE body @@ 'apple' "
        "ORDER BY s DESC LIMIT 500").rows()
    assert len(bm) == len(tf)
    # same match set (full), different score values (different formulas)
    assert {r[0] for r in bm} == {r[0] for r in tf}
    bm_scores = dict(bm)
    assert any(abs(bm_scores[i] - s) > 1e-6 for i, s in tf)
    # tfidf = idf * sqrt(tf) — verify one score by hand
    import numpy as np
    from serenedb_tpu.search.index import find_index
    t = sql_conn.db.schemas["main"].tables["docs"]
    idx = find_index(t, "body")
    ms = idx.searcher("body")
    searcher = ms.segments[0][0]   # single-segment index
    fi = searcher.index
    tid = fi.term_id("apple")
    if tid >= 0 and tf:
        d = int(tf[0][0])
        # find the row index of doc with id==d
        ids = t.full_batch(["id"]).column("id").to_pylist()
        row = ids.index(d)
        pd, pt = fi.postings(tid)
        tfreq = float(pt[np.searchsorted(pd, row)])
        idf = 1.0 + np.log(searcher.num_docs / (fi.doc_freq[tid] + 1.0))
        assert tf[0][1] == pytest.approx(idf * np.sqrt(tfreq), rel=1e-3)


def test_fuzzy_expansion_uncapped_matches_brute(sql_conn):
    # >128 near-terms: indexed fuzzy must equal brute force (no silent cap)
    c = sql_conn
    c.execute("CREATE TABLE many (body TEXT)")
    rows = ", ".join(f"('aaaa{chr(97 + i % 26)}{j}')"
                     for i in range(26) for j in range(6))
    c.execute(f"INSERT INTO many VALUES {rows}")
    q = "SELECT count(*) FROM many WHERE body @@ 'aaaax1~2'"
    brute = c.execute(q).scalar()
    c.execute("CREATE INDEX ON many USING inverted (body)")
    assert c.execute(q).scalar() == brute
    neg = "SELECT count(*) FROM many WHERE body @@ '!aaaax1~2'"
    assert c.execute(neg).scalar() == 156 - brute


def test_fuzzy_highlight(sql_conn):
    c = sql_conn
    r = c.execute("SELECT ts_headline('databose quirks', 'database~1')"
                  ).scalar()
    assert r == "<b>databose</b> quirks"
