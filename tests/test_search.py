"""Search core tests: segment build, filter parity vs the brute-force
semantics contract, BM25 top-k correctness, SQL pushdown."""

import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.search.analysis import get_analyzer
from serenedb_tpu.search.query import (eval_query_on_text, match_phrase_brute,
                                       parse_query)
from serenedb_tpu.search.searcher import SegmentSearcher
from serenedb_tpu.search.segment import build_field_index

WORDS = ("apple banana cherry quick brown fox jumps over lazy dog search "
         "engine database index query term").split()


def make_corpus(n=300, seed=3):
    rng = np.random.default_rng(seed)
    docs = []
    for _ in range(n):
        ln = rng.integers(3, 30)
        docs.append(" ".join(rng.choice(WORDS, ln)))
    return docs


@pytest.fixture(scope="module")
def corpus():
    return make_corpus()


@pytest.fixture(scope="module")
def searcher(corpus):
    an = get_analyzer("text")
    fi = build_field_index(corpus, an)
    return SegmentSearcher(fi, an, len(corpus))


QUERIES = [
    "apple",
    "apple & banana",
    "apple | cherry",
    "quick & !lazy",
    '"quick brown"',
    '"quick brown fox"',
    "qui*",
    "(apple | banana) & cherry",
    "!apple",
    "nonexistentterm",
    "apple & nonexistentterm",
]


@pytest.mark.parametrize("q", QUERIES)
def test_filter_parity_with_brute_force(searcher, corpus, q):
    an = get_analyzer("text")
    node = parse_query(q, an)
    expected = {i for i, text in enumerate(corpus)
                if eval_query_on_text(node, an, text)}
    got = set(searcher.eval_filter(node).tolist())
    assert got == expected, q


@pytest.mark.parametrize("q", ["apple", "apple | cherry", "apple & banana",
                               '"quick brown"', "qui*", "quick & !lazy"])
def test_topk_matches_cpu_reference(searcher, q):
    an = get_analyzer("text")
    node = parse_query(q, an)
    k = 10
    scores, docs = searcher.topk(node, k)
    # every returned doc must match the filter semantics
    match = set(searcher.eval_filter(node).tolist())
    assert all(int(d) in match for d in docs), q
    # scores descending
    assert all(scores[i] >= scores[i + 1] - 1e-5
               for i in range(len(scores) - 1)), q
    # exact score check vs the CPU reference over the match set
    tids = searcher.scoring_terms(node)
    if match and tids:
        ref_scores, ref_docs = searcher._cpu_score(
            np.asarray(sorted(match), dtype=np.int32), tids, k)
        np.testing.assert_allclose(scores, ref_scores[:len(scores)],
                                   rtol=2e-3, atol=1e-3)


def test_bm25_manual_formula(searcher):
    """Single-term score equals the hand-computed BM25 on one doc."""
    an = get_analyzer("text")
    node = parse_query("apple", an)
    scores, docs = searcher.topk(node, 1)
    d = int(docs[0])
    fi = searcher.index
    tid = fi.term_id(an.terms("apple")[0])   # analyzed (stemmed) form
    pd, pt = fi.postings(tid)
    tf = float(pt[np.searchsorted(pd, d)])
    df = float(fi.doc_freq[tid])
    n = searcher.num_docs
    idf = np.log(1 + (n - df + 0.5) / (df + 0.5))
    dl = float(fi.norms[d])
    expected = idf * (1.2 + 1) * tf / (tf + 1.2 * (1 - 0.75 + 0.75 * dl / fi.avgdl))
    assert scores[0] == pytest.approx(expected, rel=1e-3)


def test_phrase_positions(searcher, corpus):
    an = get_analyzer("text")
    node = parse_query('"brown fox"', an)
    got = set(searcher.eval_filter(node).tolist())
    expected = set(np.flatnonzero(
        match_phrase_brute(np.asarray(corpus, dtype=object),
                           np.asarray(["brown fox"] * len(corpus),
                                      dtype=object))).tolist())
    assert got == expected


# -- SQL integration -------------------------------------------------------

@pytest.fixture
def sql_conn(corpus):
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT)")
    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.exec.tables import MemTable
    batch = Batch.from_pydict({
        "id": list(range(len(corpus))),
        "body": list(corpus),
    })
    db.schemas["main"].tables["docs"] = MemTable("docs", batch)
    return c


def test_sql_index_pushdown_parity(sql_conn):
    q = "SELECT count(*) FROM docs WHERE body @@ 'apple & banana'"
    brute = sql_conn.execute(q).scalar()
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    ex = sql_conn.execute("EXPLAIN " + q).rows()
    assert any("SearchScan" in r[0] for r in ex)
    assert sql_conn.execute(q).scalar() == brute


def test_sql_phrase_pushdown_parity(sql_conn):
    q = "SELECT count(*) FROM docs WHERE body ## 'quick brown'"
    brute = sql_conn.execute(q).scalar()
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    assert sql_conn.execute(q).scalar() == brute


def test_sql_topk_scored(sql_conn):
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    r = sql_conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple' "
        "ORDER BY s DESC LIMIT 5")
    ex = sql_conn.execute(
        "EXPLAIN SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple' "
        "ORDER BY s DESC LIMIT 5").rows()
    assert any("TopK" in row[0] for row in ex)
    rows = r.rows()
    assert 0 < len(rows) <= 5
    scores = [row[1] for row in rows]
    assert scores == sorted(scores, reverse=True)
    assert all(s > 0 for s in scores)


def test_sql_index_stale_after_insert_read_repairs(sql_conn):
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    sql_conn.execute("INSERT INTO docs VALUES (9999, 'zzzuniqueterm here')")
    # a stale index (data_version mismatch) is refreshed in place and
    # USED — falling back to a brute scan would silently analyze with the
    # default analyzer instead of the column's tokenizer
    assert sql_conn.execute(
        "SELECT count(*) FROM docs WHERE body @@ 'zzzuniqueterm'"
    ).scalar() == 1
    ex = sql_conn.execute(
        "EXPLAIN SELECT count(*) FROM docs WHERE body @@ 'zzzuniqueterm'"
    ).rows()
    assert any("SearchScan" in r[0] for r in ex)


def test_sql_mixed_predicate_residual(sql_conn):
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    q = ("SELECT count(*) FROM docs WHERE body @@ 'apple' AND id < 100")
    with_index = sql_conn.execute(q).scalar()
    # oracle: no index (different table name, same data via subquery trick)
    brute = sql_conn.execute(
        "SELECT count(*) FROM (SELECT * FROM docs) d "
        "WHERE body @@ 'apple' AND id < 100").scalar()
    assert with_index == brute


def test_tfidf_scorer_differs_from_bm25(sql_conn):
    sql_conn.execute("CREATE INDEX ON docs USING inverted (body)")
    bm = sql_conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple' "
        "ORDER BY s DESC LIMIT 500").rows()
    tf = sql_conn.execute(
        "SELECT id, tfidf(body) AS s FROM docs WHERE body @@ 'apple' "
        "ORDER BY s DESC LIMIT 500").rows()
    assert len(bm) == len(tf)
    # same match set (full), different score values (different formulas)
    assert {r[0] for r in bm} == {r[0] for r in tf}
    bm_scores = dict(bm)
    assert any(abs(bm_scores[i] - s) > 1e-6 for i, s in tf)
    # tfidf = idf * sqrt(tf) — verify one score by hand
    import numpy as np
    from serenedb_tpu.search.index import find_index
    t = sql_conn.db.schemas["main"].tables["docs"]
    idx = find_index(t, "body")
    ms = idx.searcher("body")
    searcher = ms.segments[0][0]   # single-segment index
    fi = searcher.index
    tid = fi.term_id("apple")
    if tid >= 0 and tf:
        d = int(tf[0][0])
        # find the row index of doc with id==d
        ids = t.full_batch(["id"]).column("id").to_pylist()
        row = ids.index(d)
        pd, pt = fi.postings(tid)
        tfreq = float(pt[np.searchsorted(pd, row)])
        idf = 1.0 + np.log(searcher.num_docs / (fi.doc_freq[tid] + 1.0))
        assert tf[0][1] == pytest.approx(idf * np.sqrt(tfreq), rel=1e-3)


def test_fuzzy_expansion_uncapped_matches_brute(sql_conn):
    # >128 near-terms: indexed fuzzy must equal brute force (no silent cap)
    c = sql_conn
    c.execute("CREATE TABLE many (body TEXT)")
    rows = ", ".join(f"('aaaa{chr(97 + i % 26)}{j}')"
                     for i in range(26) for j in range(6))
    c.execute(f"INSERT INTO many VALUES {rows}")
    q = "SELECT count(*) FROM many WHERE body @@ 'aaaax1~2'"
    brute = c.execute(q).scalar()
    c.execute("CREATE INDEX ON many USING inverted (body)")
    assert c.execute(q).scalar() == brute
    neg = "SELECT count(*) FROM many WHERE body @@ '!aaaax1~2'"
    assert c.execute(neg).scalar() == 156 - brute


def test_fuzzy_highlight(sql_conn):
    c = sql_conn
    r = c.execute("SELECT ts_headline('databose quirks', 'database~1')"
                  ).scalar()
    assert r == "<b>databose</b> quirks"


# -- block-max WAND pruning (reference: wand_writer.hpp / block_disjunction) --

def _wand_fixture(n_docs=6000, seed=11):
    """A corpus with realistic block-max variance: a clustered 'hot' doc-id
    region (short docs with high tf of a few terms) and a long cold tail
    (long docs, background tf only). Blocks covering the cold region get
    provably-low upper bounds — the structure WAND exploits."""
    rng = np.random.default_rng(seed)
    vocab = [f"t{i}" for i in range(40)]
    docs = []
    for d in range(n_docs):
        if d < 600:  # hot cluster: short docs, two boosted terms
            words = list(rng.choice(vocab, int(rng.integers(20, 60))))
            words += [vocab[d % 7]] * 30 + [vocab[(d + 1) % 7]] * 30
        else:        # cold tail: long docs, background term frequencies
            words = list(rng.choice(vocab, int(rng.integers(150, 300))))
        docs.append(" ".join(words))
    an = get_analyzer("simple")
    fi = build_field_index(docs, an)
    return SegmentSearcher(fi, an, n_docs), docs, an


def test_wand_pruning_parity_and_reduction():
    """Pruned top-k must equal the unpruned top-k exactly, and the pruning
    must actually drop block rows on a skewed corpus."""
    from serenedb_tpu.ops import bm25 as bm25_ops
    searcher, docs, an = _wand_fixture()
    store = searcher._device_store()
    fi = searcher.index
    qs = ["t0 | t1", "t2 | t3 | t4", "t5", "t0 | t6 | t1"]
    nodes = [parse_query(q, an) for q in qs]
    k = 10

    # unpruned assembly (wand off) vs pruned assembly row counts
    shapes = [searcher._query_shape(n) for n in nodes]
    queries = [(np.asarray(t, dtype=np.int64), r)
               for t, r, _, _ in shapes]
    qb_off = bm25_ops.assemble_query_batch(store, searcher.num_docs,
                                           queries, fi.doc_freq)
    plans = [bm25_ops.wand_plan(
        store, t, bm25_ops.idf_lucene(searcher.num_docs, fi.doc_freq[t]),
        k, fi.avgdl, 1.2, 0.75, "bm25") for t, r, _, _ in shapes]
    qb_on = bm25_ops.assemble_query_batch(
        store, searcher.num_docs, queries, fi.doc_freq, plans=plans)
    def live_rows(qb):
        return (int((qb.row_idx != store.n_packed).sum()) +
                int((qb.raw_idx != store.n_raw).sum()))
    rows_off = live_rows(qb_off)
    rows_on = live_rows(qb_on)
    assert rows_on < rows_off, (rows_on, rows_off)

    # end-to-end parity: device top-k with pruning equals CPU reference
    out = searcher.topk_batch(nodes, k)
    for node, (scores, dd) in zip(nodes, out):
        match = searcher.eval_filter(node)
        tids = searcher.scoring_terms(node)
        ref_s, ref_d = searcher._cpu_score(match, tids, k)
        np.testing.assert_allclose(scores, ref_s[:len(scores)],
                                   rtol=2e-3, atol=1e-3)
        # doc sets must agree wherever scores are not tied at the cut
        assert set(dd.tolist()) == set(ref_d[:len(dd)].tolist()) or \
            abs(float(ref_s[len(dd) - 1]) - float(ref_s[min(len(dd), len(ref_s) - 1)])) < 1e-4


def test_wand_prune_never_drops_topk_docs():
    """Direct unit check of wand_prune: every true top-k doc's rows survive."""
    from serenedb_tpu.ops import bm25 as bm25_ops
    searcher, docs, an = _wand_fixture(n_docs=4000, seed=5)
    store = searcher._device_store()
    fi = searcher.index
    tids = [fi.term_id("t0"), fi.term_id("t1"), fi.term_id("t2")]
    assert all(t >= 0 for t in tids)
    k = 7
    idf = bm25_ops.idf_lucene(searcher.num_docs, fi.doc_freq[np.asarray(tids)])
    plan = bm25_ops.wand_plan(store, tids, idf, k, fi.avgdl, 1.2, 0.75,
                              "bm25")
    if plan is None:
        return  # nothing prunable on this corpus — parity covered above
    kept = plan.kept
    ref_s, ref_d = searcher._cpu_score(
        np.arange(searcher.num_docs, dtype=np.int32), tids, k)
    for d in ref_d:
        d = int(d)
        for tid in tids:
            if not store.heavy[tid]:
                continue
            s, e = int(store.offsets[tid]), int(store.offsets[tid + 1])
            pd = store.flat_docs[s:e]
            i = int(np.searchsorted(pd, d))
            if i >= len(pd) or pd[i] != d:
                continue  # term doesn't hit this doc
            row = int(store.block_offsets[tid]) + i // 128
            assert row in set(kept[tid].tolist()), (d, tid)


def test_query_batch_chunking_parity():
    """The accumulator-cap query chunking must not change results: force a
    tiny cap so a batch splits, compare against the unsplit batch."""
    searcher, docs, an = _wand_fixture(n_docs=3000, seed=7)
    qs = ["t0 | t1", "t2", "t3 & t4", "t5 | t6 | t0", "t1", "t2 | t5"]
    nodes = [parse_query(q, an) for q in qs]
    base = searcher.topk_batch(nodes, 10)
    old = SegmentSearcher.ACC_ENTRY_CAP
    try:
        SegmentSearcher.ACC_ENTRY_CAP = searcher._device_store().ndocs_pad * 2
        chunked = searcher.topk_batch(nodes, 10)
    finally:
        SegmentSearcher.ACC_ENTRY_CAP = old
    for (s1, d1), (s2, d2) in zip(base, chunked):
        np.testing.assert_allclose(s1, s2, rtol=1e-6)
        assert d1.tolist() == d2.tolist()


def test_dense_path_parity_vs_scatter_and_cpu(monkeypatch):
    """The dense matmul path (small-corpus regime) must return exactly the
    scatter path's results, which must match the exhaustive CPU scorer."""
    from serenedb_tpu.ops import bm25 as bm25_ops
    searcher, docs, an = _wand_fixture(n_docs=2500, seed=11)
    qs = ["t0 | t1", "t2", "t3 & t4", "t5 | t6 | t0", "t1 ## t2", "t9"]
    nodes = [parse_query(q, an) for q in qs]
    assert bm25_ops.dense_fits(searcher._device_store().ndocs_pad,
                               len(searcher.index.doc_freq))
    dense_out = searcher.topk_batch(nodes, 10)
    monkeypatch.setattr(bm25_ops, "DENSE_HBM_BUDGET", 0)
    scatter_out = searcher.topk_batch(nodes, 10)
    for node, (s1, d1), (s2, d2) in zip(nodes, dense_out, scatter_out):
        match = searcher.eval_filter(node)
        tids = searcher.scoring_terms(node)
        ref_s, ref_d = searcher._cpu_score(match, tids, 10)
        keep = ref_s > 0
        ref_s, ref_d = ref_s[keep][:10], ref_d[keep][:10]
        np.testing.assert_allclose(s1, ref_s, rtol=2e-3, atol=1e-3)
        np.testing.assert_allclose(s2, ref_s, rtol=2e-3, atol=1e-3)
        for j, (a, b) in enumerate(zip(d1.tolist(), ref_d.tolist())):
            if a != b:
                assert abs(float(s1[j]) - float(ref_s[j])) < 1e-3
        for j, (a, b) in enumerate(zip(d2.tolist(), ref_d.tolist())):
            if a != b:
                assert abs(float(s2[j]) - float(ref_s[j])) < 1e-3


def test_dense_path_tfidf_parity():
    searcher, docs, an = _wand_fixture(n_docs=1500, seed=13)
    nodes = [parse_query(q, an) for q in ["t0 | t3", "t7", "t1 & t2"]]
    out = searcher.topk_batch(nodes, 8, scorer="tfidf")
    for node, (s1, d1) in zip(nodes, out):
        match = searcher.eval_filter(node)
        tids = searcher.scoring_terms(node)
        ref_s, ref_d = searcher._cpu_score(match, tids, 8, scorer="tfidf")
        keep = ref_s > 0
        ref_s = ref_s[keep][:8]
        np.testing.assert_allclose(s1, ref_s, rtol=2e-3, atol=1e-3)


def test_cpu_wand_topk_matches_exhaustive():
    """cpu_topk_wand (block-max WAND + MaxScore host scorer — the honest
    bench baseline) must equal exhaustive scoring exactly."""
    searcher, docs, an = _wand_fixture(n_docs=4000, seed=17)
    qs = ["t0 | t1", "t2 | t3 | t4", "t5", "t0 | t6 | t1", "t1 & t3"]
    for q in qs:
        node = parse_query(q, an)
        tids, req, mask, empty = searcher._query_shape(node)
        assert not (mask or empty)
        ws, wd = searcher.cpu_topk_wand(tids, 10, require_all=req)
        match = searcher.eval_filter(node)
        es, ed = searcher._cpu_score(match, tids, 10)
        keep = es > 0
        es, ed = es[keep][:10], ed[keep][:10]
        np.testing.assert_allclose(ws, es, rtol=1e-6)
        for j, (a, b) in enumerate(zip(wd.tolist(), ed.tolist())):
            if a != b:
                assert abs(float(ws[j]) - float(es[j])) < 1e-6


def test_packed_store_exception_rows_and_compression():
    """Posting rows with doc gaps ≥ 2^16 or tf ≥ 2^8 must fall back to the
    raw exception plane with exact scores, and the packed layout must
    actually shrink the HBM tile footprint."""
    from serenedb_tpu.ops import bm25 as bm25_ops
    rng = np.random.default_rng(3)
    n_docs = 300_000
    # term 0: sparse spread over the full doc space → huge gaps (raw rows);
    # term 1: dense cluster with one giant tf (raw via tf overflow);
    # term 2: a normal dense term (packed rows)
    # deterministic gap > 2^16 between the first two postings → the row
    # must take the raw exception plane
    d0 = np.concatenate([[0], 70_000 + np.arange(63) * 3000]) \
        .astype(np.int32)
    d1 = np.arange(100, 356, dtype=np.int32)
    d2 = np.sort(rng.choice(5000, 2000, replace=False)).astype(np.int32)
    post_docs = np.concatenate([d0, d1, d2])
    t1 = np.ones(len(d1), dtype=np.int32)
    t1[7] = 5000   # tf overflow
    post_tfs = np.concatenate([
        rng.integers(1, 5, len(d0)).astype(np.int32), t1,
        rng.integers(1, 5, len(d2)).astype(np.int32)])
    offsets = np.asarray([0, len(d0), len(d0) + len(d1),
                          len(post_docs)], dtype=np.int64)
    doc_freq = np.asarray([len(d0), len(d1), len(d2)], dtype=np.int32)
    norms = rng.integers(5, 60, n_docs).astype(np.int32)
    store = bm25_ops.build_block_store(offsets, post_docs, post_tfs,
                                      doc_freq, norms, n_docs)
    assert store.n_raw > 1, "expected raw exception rows"
    assert store.n_packed > 0, "expected packed rows"
    # the gap-overflow row (term 0) and the tf-overflow row (term 1, first
    # block holds tf=5000) must be in the raw plane
    assert store.row_plane[int(store.block_offsets[0])] == 1
    assert store.row_plane[int(store.block_offsets[1])] == 1
    # term 2 is dense and small-valued → packed
    assert store.row_plane[int(store.block_offsets[2])] == 0
    assert store.hbm_bytes < store.hbm_bytes_raw_equiv * 0.6, \
        (store.hbm_bytes, store.hbm_bytes_raw_equiv)

    from serenedb_tpu.search.segment import FieldIndex, _add_block_max
    fi = FieldIndex(
        terms=np.asarray(["aa", "bb", "cc"], dtype=object),
        doc_freq=doc_freq, offsets=offsets, post_docs=post_docs,
        post_tfs=post_tfs,
        pos_offsets=np.zeros(len(post_docs) + 1, dtype=np.int64),
        positions=np.empty(0, dtype=np.int32), norms=norms,
        block_max_tf=np.empty(0, dtype=np.int32),
        block_offsets=np.zeros(4, dtype=np.int64),
        total_tokens=int(post_tfs.sum()))
    _add_block_max(fi)
    s = SegmentSearcher(fi, get_analyzer("simple"), n_docs)
    s._dev = store
    for q, req in [(parse_query("aa", s.analyzer), 0),
                   (parse_query("bb", s.analyzer), 0),
                   (parse_query("aa | cc", s.analyzer), 0),
                   (parse_query("bb & cc", s.analyzer), 2)]:
        tids = s.scoring_terms(q)
        dev_s, dev_d = s.topk_batch([q], 10)[0]
        match = s.eval_filter(q)
        ref_s, ref_d = s._cpu_score(match, tids, 10)
        keep = ref_s > 0
        ref_s, ref_d = ref_s[keep][:10], ref_d[keep][:10]
        np.testing.assert_allclose(dev_s, ref_s, rtol=2e-3, atol=1e-3)
        for j, (a, b) in enumerate(zip(dev_d.tolist(), ref_d.tolist())):
            if a != b:
                assert abs(float(dev_s[j]) - float(ref_s[j])) < 1e-3
