"""Parity tests: device-offloaded Scan→Filter→Aggregate vs the CPU oracle."""

import numpy as np
import pytest

from serenedb_tpu.engine import Database


@pytest.fixture
def conn():
    rng = np.random.default_rng(7)
    n = 5000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE h (k INT, g TEXT, v INT, f DOUBLE, nv INT)")
    ks = rng.integers(0, 50, n)
    gs = rng.choice(["alpha", "beta", "gamma", "delta"], n)
    vs = rng.integers(-1000000, 1000000, n)
    fs = rng.normal(size=n)
    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.exec.tables import MemTable
    validity = rng.random(n) > 0.1
    batch = Batch.from_pydict({
        "k": Column.from_numpy(ks.astype(np.int32)),
        "g": Column.from_numpy(gs),
        "v": Column.from_numpy(vs.astype(np.int64)),
        "f": Column.from_numpy(fs),
        "nv": Column(Column.from_numpy(ks.astype(np.int32)).type,
                     ks.astype(np.int32), validity),
    })
    db.schemas["main"].tables["h"] = MemTable("h", batch)
    return c


QUERIES = [
    "SELECT count(*) FROM h",
    "SELECT count(*) FROM h WHERE k <> 0",
    "SELECT count(*), sum(v) FROM h WHERE k > 10 AND k < 40",
    "SELECT count(nv) FROM h",
    "SELECT sum(v), min(v), max(v), avg(v) FROM h WHERE v > 0",
    "SELECT count(*) FROM h WHERE g = 'alpha'",
    "SELECT count(*) FROM h WHERE g >= 'beta' AND g < 'delta'",
    "SELECT count(*) FROM h WHERE g = 'nonexistent'",
    "SELECT g, count(*), sum(v) FROM h GROUP BY g ORDER BY g",
    "SELECT k, count(*) FROM h GROUP BY k ORDER BY k",
    "SELECT g, k, count(*), min(v), max(v) FROM h WHERE k < 25 "
    "GROUP BY g, k ORDER BY g, k",
    "SELECT nv, count(*) FROM h GROUP BY nv ORDER BY nv NULLS LAST",
    "SELECT g, avg(f) FROM h GROUP BY g ORDER BY g",
    "SELECT count(*) FROM h WHERE k + 1 > 25",
    "SELECT count(*) FROM h WHERE k * 2 <= 40 OR v < 0",
    "SELECT count(*) FROM h WHERE NOT (k > 10)",
    "SELECT count(*) FROM h WHERE nv IS NULL",
]


@pytest.mark.parametrize("q", QUERIES)
def test_device_cpu_parity(conn, q):
    conn.execute("SET serene_device = 'cpu'")
    cpu = conn.execute(q).rows()
    conn.execute("SET serene_device = 'tpu'")  # force device path
    dev = conn.execute(q).rows()
    assert len(cpu) == len(dev)
    for rc, rd in zip(cpu, dev):
        for a, b in zip(rc, rd):
            if isinstance(a, float) or isinstance(b, float):
                assert b == pytest.approx(a, rel=1e-4, abs=1e-4), q
            else:
                assert a == b, q


def test_device_path_actually_used(conn):
    from serenedb_tpu.utils import metrics
    before = metrics.DEVICE_OFFLOADS.value
    conn.execute("SET serene_device = 'tpu'")
    conn.execute("SELECT count(*) FROM h WHERE k <> 0")
    assert metrics.DEVICE_OFFLOADS.value > before


def test_device_falls_back_for_strings_minmax(conn):
    conn.execute("SET serene_device = 'tpu'")
    # min over strings is not device-compilable; must still be correct
    r = conn.execute("SELECT min(g) FROM h").scalar()
    assert r == "alpha"
