"""Parity tests: device-offloaded Scan→Filter→Aggregate vs the CPU oracle."""

import numpy as np
import pytest

from serenedb_tpu.engine import Database


@pytest.fixture
def conn():
    rng = np.random.default_rng(7)
    n = 5000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE h (k INT, g TEXT, v INT, f DOUBLE, nv INT)")
    ks = rng.integers(0, 50, n)
    gs = rng.choice(["alpha", "beta", "gamma", "delta"], n)
    vs = rng.integers(-1000000, 1000000, n)
    fs = rng.normal(size=n)
    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.exec.tables import MemTable
    validity = rng.random(n) > 0.1
    batch = Batch.from_pydict({
        "k": Column.from_numpy(ks.astype(np.int32)),
        "g": Column.from_numpy(gs),
        "v": Column.from_numpy(vs.astype(np.int64)),
        "f": Column.from_numpy(fs),
        "nv": Column(Column.from_numpy(ks.astype(np.int32)).type,
                     ks.astype(np.int32), validity),
    })
    db.schemas["main"].tables["h"] = MemTable("h", batch)
    return c


QUERIES = [
    "SELECT count(*) FROM h",
    "SELECT count(*) FROM h WHERE k <> 0",
    "SELECT count(*), sum(v) FROM h WHERE k > 10 AND k < 40",
    "SELECT count(nv) FROM h",
    "SELECT sum(v), min(v), max(v), avg(v) FROM h WHERE v > 0",
    "SELECT count(*) FROM h WHERE g = 'alpha'",
    "SELECT count(*) FROM h WHERE g >= 'beta' AND g < 'delta'",
    "SELECT count(*) FROM h WHERE g = 'nonexistent'",
    "SELECT g, count(*), sum(v) FROM h GROUP BY g ORDER BY g",
    "SELECT k, count(*) FROM h GROUP BY k ORDER BY k",
    "SELECT g, k, count(*), min(v), max(v) FROM h WHERE k < 25 "
    "GROUP BY g, k ORDER BY g, k",
    "SELECT nv, count(*) FROM h GROUP BY nv ORDER BY nv NULLS LAST",
    "SELECT g, avg(f) FROM h GROUP BY g ORDER BY g",
    "SELECT count(*) FROM h WHERE k + 1 > 25",
    "SELECT count(*) FROM h WHERE k * 2 <= 40 OR v < 0",
    "SELECT count(*) FROM h WHERE NOT (k > 10)",
    "SELECT count(*) FROM h WHERE nv IS NULL",
    # DISTINCT aggregates: (group, value) presence scatter on device
    "SELECT count(DISTINCT k) FROM h",
    "SELECT count(DISTINCT g) FROM h WHERE k > 10",
    "SELECT sum(DISTINCT k), avg(DISTINCT k) FROM h",
    "SELECT count(DISTINCT nv) FROM h",
    "SELECT g, count(DISTINCT k) FROM h GROUP BY g ORDER BY g",
    "SELECT k, count(DISTINCT g), sum(DISTINCT k) FROM h WHERE k < 25 "
    "GROUP BY k ORDER BY k",
    "SELECT g, count(DISTINCT nv), min(DISTINCT k) FROM h "
    "GROUP BY g ORDER BY g",
]


@pytest.mark.parametrize("q", QUERIES)
def test_device_cpu_parity(conn, q):
    conn.execute("SET serene_device = 'cpu'")
    cpu = conn.execute(q).rows()
    conn.execute("SET serene_device = 'tpu'")  # force device path
    dev = conn.execute(q).rows()
    assert len(cpu) == len(dev)
    for rc, rd in zip(cpu, dev):
        for a, b in zip(rc, rd):
            if isinstance(a, float) or isinstance(b, float):
                assert b == pytest.approx(a, rel=1e-4, abs=1e-4), q
            else:
                assert a == b, q


def test_device_path_actually_used(conn):
    from serenedb_tpu.utils import metrics
    before = metrics.DEVICE_OFFLOADS.value
    conn.execute("SET serene_device = 'tpu'")
    conn.execute("SELECT count(*) FROM h WHERE k <> 0")
    assert metrics.DEVICE_OFFLOADS.value > before


def test_device_falls_back_for_strings_minmax(conn):
    conn.execute("SET serene_device = 'tpu'")
    # min over strings is not device-compilable; must still be correct
    r = conn.execute("SELECT min(g) FROM h").scalar()
    assert r == "alpha"


# -- hash GROUP BY over arbitrary keys (host factorize + device scatter) ----

@pytest.fixture
def wide_conn():
    """Table with ClickBench-shaped keys: full-range int64 UserID (values
    far beyond int32), an expression-worthy small int, and a wide int64
    value column that must NOT be narrowed to f32."""
    rng = np.random.default_rng(11)
    n = 20000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE hits2 (uid BIGINT, region INT, w BIGINT, x INT)")
    uids = rng.integers(0, 1 << 62, n, dtype=np.int64)
    uids = uids[rng.integers(0, n, n)]  # repeats → real groups
    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.exec.tables import MemTable
    batch = Batch.from_pydict({
        "uid": Column.from_numpy(uids),
        "region": Column.from_numpy(rng.integers(0, 200, n).astype(np.int32)),
        "w": Column.from_numpy(
            rng.integers(-(1 << 40), 1 << 40, n, dtype=np.int64)),
        "x": Column.from_numpy(rng.integers(0, 1000, n).astype(np.int32)),
    })
    db.schemas["main"].tables["hits2"] = MemTable("hits2", batch)
    return c


WIDE_QUERIES = [
    # full-range int64 key → factorize path
    "SELECT uid, count(*) FROM hits2 GROUP BY uid ORDER BY uid LIMIT 20",
    "SELECT uid, count(*), sum(x) FROM hits2 WHERE x < 900 "
    "GROUP BY uid ORDER BY count(*) DESC, uid LIMIT 10",
    # expression key → factorize path
    "SELECT region % 10, count(*) FROM hits2 GROUP BY region % 10 "
    "ORDER BY region % 10",
    # composite wide + narrow keys
    "SELECT uid, region, count(*) FROM hits2 GROUP BY uid, region "
    "ORDER BY uid, region LIMIT 20",
]


@pytest.mark.parametrize("q", WIDE_QUERIES)
def test_factorized_groupby_parity(wide_conn, q):
    wide_conn.execute("SET serene_device = 'cpu'")
    cpu = wide_conn.execute(q).rows()
    wide_conn.execute("SET serene_device = 'tpu'")
    dev = wide_conn.execute(q).rows()
    assert cpu == dev, q


def test_factorized_groupby_uses_device(wide_conn):
    from serenedb_tpu.utils import metrics
    wide_conn.execute("SET serene_device = 'tpu'")
    before = metrics.DEVICE_OFFLOADS.value
    wide_conn.execute("SELECT uid, count(*) FROM hits2 GROUP BY uid LIMIT 5")
    assert metrics.DEVICE_OFFLOADS.value > before


def test_wide_int64_sum_exact_not_narrowed(wide_conn):
    """SUM over int64 values beyond 2^31 must be bit-exact on both paths
    (the device path either represents it exactly or falls back)."""
    wide_conn.execute("SET serene_device = 'cpu'")
    cpu = wide_conn.execute("SELECT sum(w), min(w), max(w) FROM hits2").rows()
    wide_conn.execute("SET serene_device = 'tpu'")
    dev = wide_conn.execute("SELECT sum(w), min(w), max(w) FROM hits2").rows()
    assert cpu == dev
    # and grouped
    q = ("SELECT region, sum(w) FROM hits2 GROUP BY region "
         "ORDER BY region LIMIT 10")
    wide_conn.execute("SET serene_device = 'cpu'")
    cpu = wide_conn.execute(q).rows()
    wide_conn.execute("SET serene_device = 'tpu'")
    dev = wide_conn.execute(q).rows()
    assert cpu == dev


def test_expr_key_eval_error_on_filtered_rows_falls_back():
    """GROUP BY a/b WHERE b <> 0: the device factorize path evaluates keys
    over UNFILTERED rows, where b=0 raises — must fall back to CPU, which
    only evaluates surviving rows (review regression)."""
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE t0 (a INT, b INT)")
    c.execute("INSERT INTO t0 VALUES (10, 2), (16, 2), (30, 2), (7, 0)")
    c.execute("SET serene_device = 'tpu'")
    rows = c.execute("SELECT a / b, count(*) FROM t0 WHERE b <> 0 "
                     "GROUP BY a / b ORDER BY a / b").rows()
    assert rows == [(5, 1), (8, 1), (15, 1)]


class TestCompressedTiles:
    """Frame-of-reference HBM tiles (reference analog: iresearch
    formats/column adaptive compression): range-fitting int columns ship
    as uint8/uint16 deltas, decode in-kernel, and aggregate identically."""

    def test_schemes_chosen_by_range(self):
        import numpy as np

        from serenedb_tpu.columnar import dtypes as dt
        from serenedb_tpu.columnar.column import Column
        from serenedb_tpu.columnar.device import to_device_column
        small = to_device_column(Column(
            dt.INT, np.arange(100, 200, dtype=np.int32)))
        assert small.scheme == "for8" and small.data.dtype.name == "uint8"
        mid = to_device_column(Column(
            dt.INT, np.arange(0, 40_000, dtype=np.int32)))
        assert mid.scheme == "for16"
        wide = to_device_column(Column(
            dt.INT, np.asarray([0, 1 << 20], dtype=np.int32)))
        assert wide.scheme == "raw"
        # decode round-trips
        import numpy as _np
        dec = _np.asarray(small.decode(small.data)).reshape(-1)[:100]
        assert (dec == _np.arange(100, 200)).all()

    def test_sql_parity_over_compressed_tiles(self):
        import random

        from serenedb_tpu.engine import Database
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE ct (k INT, v INT, w INT)")
        rng = random.Random(1)
        c.execute("INSERT INTO ct VALUES " + ", ".join(
            f"({rng.randint(0, 40)}, {rng.randint(-100, 100)}, "
            f"{rng.randint(100000, 163000)})" for _ in range(30000)))
        q = ("SELECT k, count(*), sum(v), min(w), max(w) FROM ct "
             "WHERE w < 150000 GROUP BY k ORDER BY k")
        c.execute("SET serene_device = 'cpu'")
        ref = c.execute(q).rows()
        c.execute("SET serene_device = 'device'")
        assert c.execute(q).rows() == ref
        # footprint: k fits uint8, v/w fit uint16 — vs raw int32
        t = db.resolve_table(["ct"])
        for name, want in [("k", "uint8"), ("v", "uint8"),
                           ("w", "uint16")]:
            dc = t.device_column(name)
            assert dc.data.dtype.name == want, (name, dc.data.dtype)



def test_distinct_device_path_used(conn):
    from serenedb_tpu.utils import metrics
    conn.execute("SET serene_device = 'tpu'")
    before = metrics.DEVICE_OFFLOADS.value
    conn.execute("SELECT g, count(DISTINCT k) FROM h GROUP BY g")
    assert metrics.DEVICE_OFFLOADS.value > before


def test_distinct_all_null_group_is_null_sum(conn):
    conn.execute("CREATE TABLE dn (k INT, v INT)")
    conn.execute("INSERT INTO dn VALUES (1, NULL), (1, NULL), (2, 5)")
    for dev in ("cpu", "tpu"):
        conn.execute(f"SET serene_device = '{dev}'")
        rows = conn.execute(
            "SELECT k, count(DISTINCT v), sum(DISTINCT v), "
            "avg(DISTINCT v) FROM dn GROUP BY k ORDER BY k").rows()
        assert rows == [(1, 0, None, None), (2, 1, 5, 5.0)], (dev, rows)
    conn.execute("DROP TABLE dn")


# -- device/mesh top-N (ORDER BY col LIMIT k) ------------------------------

TOPN_QUERIES = [
    "SELECT k, v FROM h ORDER BY v DESC LIMIT 8",
    "SELECT k, v FROM h ORDER BY v LIMIT 8",
    "SELECT v FROM h ORDER BY v DESC LIMIT 5 OFFSET 2",
    "SELECT f, k FROM h ORDER BY f LIMIT 6",
]


@pytest.mark.parametrize("q", TOPN_QUERIES)
def test_topn_device_cpu_parity(conn, q):
    conn.execute("SET serene_device = 'cpu'")
    cpu = conn.execute(q).rows()
    conn.execute("SET serene_device = 'tpu'")
    dev = conn.execute(q).rows()
    # the sort key is the first ORDER BY column; non-key columns may
    # differ on exact key ties, so compare the key sequences and row sets
    assert len(cpu) == len(dev)
    assert cpu == dev, q


def test_topn_mesh_parity(conn):
    conn.execute("SET serene_device = 'tpu'")
    conn.execute("SET serene_mesh = 8")
    try:
        for q in TOPN_QUERIES:
            mesh = conn.execute(q).rows()
            conn.execute("SET serene_mesh = 0")
            single = conn.execute(q).rows()
            conn.execute("SET serene_mesh = 8")
            assert mesh == single, q
    finally:
        conn.execute("SET serene_mesh = 0")


def test_topn_fallback_shapes(conn):
    """NULLs / strings / filters / explicit NULLS placement fall back to
    the CPU sort and stay correct."""
    conn.execute("SET serene_device = 'tpu'")
    for q in [
        "SELECT nv FROM h ORDER BY nv LIMIT 5",           # has NULLs
        "SELECT g FROM h ORDER BY g LIMIT 5",             # string key
        "SELECT v FROM h WHERE k > 25 ORDER BY v LIMIT 5",  # filter
        "SELECT v FROM h ORDER BY v DESC NULLS LAST LIMIT 5",
        "SELECT k, v FROM h ORDER BY k, v LIMIT 5",       # two keys
    ]:
        dev = conn.execute(q).rows()
        conn.execute("SET serene_device = 'cpu'")
        cpu = conn.execute(q).rows()
        conn.execute("SET serene_device = 'tpu'")
        assert [r[0] for r in dev] == [r[0] for r in cpu], q


def test_topn_mesh_underfilled_shards(conn):
    """A table smaller than mesh_n * k leaves most shards all-padding;
    their sentinel candidates must not leak into the merged top-k."""
    conn.execute("CREATE TABLE small (v INT)")
    conn.execute("INSERT INTO small VALUES " + ", ".join(
        f"({i * 3 - 50})" for i in range(100)))
    conn.execute("SET serene_device = 'tpu'")
    conn.execute("SET serene_mesh = 8")
    try:
        got = conn.execute(
            "SELECT v FROM small ORDER BY v DESC LIMIT 10").rows()
        conn.execute("SET serene_device = 'cpu'")
        want = conn.execute(
            "SELECT v FROM small ORDER BY v DESC LIMIT 10").rows()
        assert got == want
        conn.execute("SET serene_device = 'tpu'")
        got_asc = conn.execute(
            "SELECT v FROM small ORDER BY v LIMIT 10").rows()
        conn.execute("SET serene_device = 'cpu'")
        want_asc = conn.execute(
            "SELECT v FROM small ORDER BY v LIMIT 10").rows()
        assert got_asc == want_asc
    finally:
        conn.execute("SET serene_mesh = 0")
        conn.execute("DROP TABLE small")


def test_distinct_unsupported_aggs_still_error(conn):
    import pytest as _pytest

    from serenedb_tpu import errors as _errors
    for q in ["SELECT string_agg(DISTINCT g, ',') FROM h",
              "SELECT stddev(DISTINCT v) FROM h",
              "SELECT g, string_agg(DISTINCT g, ',') FROM h GROUP BY g"]:
        with _pytest.raises(_errors.SqlError):
            conn.execute(q)


def test_distinct_invariant_minmax(conn):
    for dev in ("cpu", "tpu"):
        conn.execute(f"SET serene_device = '{dev}'")
        a = conn.execute("SELECT min(DISTINCT v), max(DISTINCT v) "
                         "FROM h").rows()
        b = conn.execute("SELECT min(v), max(v) FROM h").rows()
        assert a == b, dev
