"""Pytest integration of the sqllogic golden files — each file runs on a
fresh in-memory database AND on a fresh durable database with a
close/reopen in the middle... (the durable variant comes with multi-run
support; for now files run against both engine configurations)."""

import glob
import os

import pytest

from serenedb_tpu.engine import Database
from tests.sqllogic_runner import run_test_file

FILES = sorted(glob.glob(
    os.path.join(os.path.dirname(__file__), "sqllogic", "*.test")))


@pytest.mark.parametrize("path", FILES, ids=[os.path.basename(f)
                                             for f in FILES])
def test_sqllogic_memory(path):
    conn = Database().connect()
    failures = run_test_file(conn, path)
    assert not failures, "\n".join(failures)


@pytest.mark.parametrize("path", FILES, ids=[os.path.basename(f)
                                             for f in FILES])
def test_sqllogic_durable(path, tmp_path):
    db = Database(str(tmp_path / "data"))
    try:
        failures = run_test_file(db.connect(), path)
        assert not failures, "\n".join(failures)
    finally:
        db.close()
