"""Pytest integration of the sqllogic golden files.

Layout mirrors the reference's corpus split (reference:
tests/sqllogic/{any,sdb,pg,recovery}/ — SURVEY.md §4):

  tests/sqllogic/*.test            legacy flat files (both runners)
  tests/sqllogic/any/**.test       portable SQL behavior (both runners)
  tests/sqllogic/sdb/**.test       SereneDB-specific surface (both runners)
  tests/sqllogic/recovery/*.test   crash/restart scenarios (durable only;
                                   may use `restart` / `statement crash`)

Every non-recovery file runs twice: on a fresh in-memory database and on a
fresh durable datadir (close/reopen covered by recovery files)."""

import contextlib
import glob
import os

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.utils import faults
from tests.sqllogic_runner import run_test_file

_ROOT = os.path.join(os.path.dirname(__file__), "sqllogic")

FILES = sorted(
    glob.glob(os.path.join(_ROOT, "*.test"))
    + glob.glob(os.path.join(_ROOT, "any", "**", "*.test"), recursive=True)
    + glob.glob(os.path.join(_ROOT, "sdb", "**", "*.test"), recursive=True)
    # concurrency/: multi-session files using the `connection` directive
    # (direct runners only — one wire socket is one session)
    + glob.glob(os.path.join(_ROOT, "concurrency", "**", "*.test"),
                recursive=True))

RECOVERY_FILES = sorted(glob.glob(os.path.join(_ROOT, "recovery", "*.test")))


def _ids(files):
    return [os.path.relpath(f, _ROOT) for f in files]


@contextlib.contextmanager
def _scratch_cwd(tmp_path):
    """Relative COPY TO/FROM paths in test files land in the test's tmp
    dir, never the repo root."""
    old = os.getcwd()
    os.chdir(tmp_path)
    try:
        yield
    finally:
        os.chdir(old)


@pytest.mark.parametrize("path", FILES, ids=_ids(FILES))
def test_sqllogic_memory(path, tmp_path):
    db = Database()
    try:
        with _scratch_cwd(tmp_path):
            failures = run_test_file(db.connect(), path,
                                     tmpdir=str(tmp_path))
        assert not failures, "\n".join(failures)
    finally:
        db.close()   # releases process-global analyzer registrations


@pytest.mark.parametrize("path", FILES, ids=_ids(FILES))
def test_sqllogic_durable(path, tmp_path):
    db = Database(str(tmp_path / "data"))
    try:
        with _scratch_cwd(tmp_path):
            failures = run_test_file(db.connect(), path,
                                     tmpdir=str(tmp_path))
        assert not failures, "\n".join(failures)
    finally:
        db.close()


@pytest.mark.parametrize("path", RECOVERY_FILES, ids=_ids(RECOVERY_FILES))
def test_sqllogic_recovery(path, tmp_path):
    """Durable-only: files may crash (fault-armed) and restart the db."""
    datadir = str(tmp_path / "data")
    state = {"db": Database(datadir)}
    faults.set_crash_mode("raise")

    def reopen():
        state["db"].close()
        faults.clear()  # a restarted "process" starts with no armed faults
        state["db"] = Database(datadir)
        return state["db"].connect()

    def crash_reopen():
        state["db"].crash()  # abandon: no close/flush, lock released
        faults.clear()
        state["db"] = Database(datadir)
        return state["db"].connect()

    try:
        with _scratch_cwd(tmp_path):
            failures = run_test_file(state["db"].connect(), path,
                                     reopen=reopen,
                                     crash_reopen=crash_reopen,
                                     tmpdir=str(tmp_path))
        assert not failures, "\n".join(failures)
    finally:
        faults.set_crash_mode("exit")
        faults.clear()
        state["db"].close()
