"""Composite-PK byte encoding + sorted key index.

Reference analog: order-preserving PK terms (key_encoding.cpp,
duckdb_primary_key.h) — point lookups, leading-column range scans, and
PK-based remove filters that replay identically after a crash.
"""

import numpy as np
import pytest

from serenedb_tpu.columnar import dtypes as dt
from serenedb_tpu.columnar import keyenc
from serenedb_tpu.engine import Database


class TestKeyEncoding:
    def test_int_order_preserved(self):
        vals = [-(1 << 62), -5, -1, 0, 1, 7, 1 << 62]
        encs = [keyenc.encode_value(v, dt.BIGINT) for v in vals]
        assert encs == sorted(encs)

    def test_float_order_preserved(self):
        vals = [-1e308, -2.5, -0.0, 0.0, 1e-9, 3.14, 1e308]
        encs = [keyenc.encode_value(v, dt.DOUBLE) for v in vals]
        assert sorted(encs) == encs

    def test_string_order_and_prefix_freedom(self):
        vals = ["", "a", "ab", "b", "ba"]
        encs = [keyenc.encode_value(v, dt.VARCHAR) for v in vals]
        assert encs == sorted(encs)
        # 'a' < 'ab' even with a suffix after the composite terminator:
        # a shorter string followed by MORE key bytes must not outrank
        k1 = keyenc.encode_row(["a", 9], [dt.VARCHAR, dt.INT])
        k2 = keyenc.encode_row(["ab", 0], [dt.VARCHAR, dt.INT])
        assert k1 < k2

    def test_string_nul_escape(self):
        a = keyenc.encode_value("x\x00y", dt.VARCHAR)
        b = keyenc.encode_value("x", dt.VARCHAR)
        c = keyenc.encode_value("x\x01", dt.VARCHAR)
        assert b < a  # 'x' sorts before 'x\0y'
        assert a < c  # '\0' sorts before '\1'

    def test_composite_order(self):
        rows = [(1, "b"), (1, "ba"), (2, "a"), (2, "a\x00"), (10, "")]
        encs = [keyenc.encode_row(r, [dt.INT, dt.VARCHAR]) for r in rows]
        assert encs == sorted(encs)

    def test_prefix_upper_bound(self):
        p = keyenc.encode_value(5, dt.INT)
        hi = keyenc.prefix_upper_bound(p)
        assert p < hi
        assert keyenc.encode_row([5, "zzz"], [dt.INT, dt.VARCHAR]) < hi
        assert keyenc.encode_value(6, dt.INT) >= hi


class TestPkScans:
    def test_point_and_range_plans(self):
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE t (a INT, b TEXT, v INT, PRIMARY KEY (a, b))")
        c.execute("INSERT INTO t VALUES " + ", ".join(
            f"({i // 10}, 'k{i % 10}', {i})" for i in range(1000)))
        assert c.execute(
            "SELECT v FROM t WHERE a = 5 AND b = 'k3'").rows() == [(53,)]
        plan = "\n".join(r[0] for r in c.execute(
            "EXPLAIN SELECT v FROM t WHERE a = 5 AND b = 'k3'").rows())
        assert "PkScan" in plan and "point" in plan
        assert c.execute(
            "SELECT count(*) FROM t WHERE a >= 3 AND a < 5"
        ).scalar() == 20
        plan = "\n".join(r[0] for r in c.execute(
            "EXPLAIN SELECT count(*) FROM t WHERE a >= 3 AND a < 5"
        ).rows())
        assert "PkScan" in plan and "range" in plan

    def test_range_parity_vs_full_scan(self):
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE t (k INT PRIMARY KEY, v INT)")
        rng = np.random.default_rng(7)
        keys = rng.permutation(5000)
        c.execute("INSERT INTO t VALUES " + ", ".join(
            f"({int(k)}, {int(k) * 3})" for k in keys))
        got = c.execute(
            "SELECT sum(v), count(*) FROM t WHERE k > 100 AND k <= 900"
        ).rows()
        expect = (sum(k * 3 for k in range(101, 901)), 800)
        assert got == [expect]

    def test_pk_scan_bounded_work(self):
        """The range scan must touch O(result) rows, not O(table) — the
        point of the sorted key index."""
        from serenedb_tpu.search.pkindex import pk_index
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE t (k INT PRIMARY KEY, v INT)")
        c.execute("INSERT INTO t VALUES " + ", ".join(
            f"({i}, {i})" for i in range(20000)))
        t = db.resolve_table(["t"])
        idx = pk_index(t)
        lo = keyenc.encode_value(17, dt.INT)
        hi = keyenc.encode_value(42, dt.INT)
        rows = idx.range_rows(lo, hi)
        assert len(rows) == 25
        assert list(rows) == list(range(17, 42))

    def test_index_repairs_after_mutation(self):
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE t (k INT PRIMARY KEY, v INT)")
        c.execute("INSERT INTO t VALUES (1, 1), (2, 2), (3, 3)")
        c.execute("DELETE FROM t WHERE k = 2")
        assert c.execute("SELECT v FROM t WHERE k = 3").rows() == [(3,)]
        assert c.execute("SELECT count(*) FROM t WHERE k = 2").scalar() == 0
        c.execute("INSERT INTO t VALUES (2, 22)")
        assert c.execute("SELECT v FROM t WHERE k = 2").rows() == [(22,)]


class TestPkRemoveFilterDurability:
    def test_crash_replay_resolves_keys(self, tmp_path):
        d = str(tmp_path / "data")
        db = Database(d)
        c = db.connect()
        c.execute("CREATE TABLE t (a INT, b TEXT, v INT, PRIMARY KEY (a, b))")
        c.execute("INSERT INTO t VALUES (1,'x',10), (2,'y',20), (3,'z',30)")
        c.execute("UPDATE t SET v = v * 10 WHERE a = 2")
        c.execute("DELETE FROM t WHERE a = 1")
        live = sorted(c.execute("SELECT a, b, v FROM t").rows())
        db.crash()   # replay the WAL from scratch on reopen

        db2 = Database(d)
        rec = sorted(db2.connect().execute("SELECT a, b, v FROM t").rows())
        assert rec == live == [(2, "y", 200), (3, "z", 30)]
        db2.close()

    def test_wal_logs_keys_not_positions(self, tmp_path):
        """The WAL record for a PK delete must carry key bytes, so replay
        does not depend on positional row identity."""
        from serenedb_tpu.storage.wal import SearchDbWal
        d = str(tmp_path / "data")
        db = Database(d)
        c = db.connect()
        c.execute("CREATE TABLE t (k INT PRIMARY KEY, v INT)")
        c.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        c.execute("DELETE FROM t WHERE k = 1")
        db.close()
        wal = SearchDbWal(str(tmp_path / "data" / "wal"))
        kinds = []
        wal.recover(lambda tbl: -1,
                    lambda tick, op: kinds.append(op.kind))
        assert "delete_pk" in kinds
        assert "delete" not in kinds


class TestReviewRegressions:
    def test_out_of_range_literal_no_alias(self):
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE t (k BIGINT PRIMARY KEY, v INT)")
        c.execute("INSERT INTO t VALUES (-1, 1)")
        # 2**64-1 must NOT alias -1 through encoding wraparound
        import serenedb_tpu.errors as errors
        try:
            rows = c.execute(
                "SELECT v FROM t WHERE k = 18446744073709551615").rows()
            assert rows == [], rows
        except errors.SqlError:
            pass  # an out-of-range error is also acceptable (PG: 22003)

    def test_negative_zero_is_one_key(self):
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE t (f DOUBLE PRIMARY KEY, v INT)")
        c.execute("INSERT INTO t VALUES (0.0, 1)")
        with pytest.raises(Exception):
            c.execute("INSERT INTO t VALUES (-0.0, 2)")

    def test_pk_extend_skips_when_reader_rebuilt(self):
        """A lock-free reader rebuilding the index between publish and
        pk_extend must not cause duplicate entries."""
        from serenedb_tpu.search.pkindex import pk_extend, pk_index
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE t (k INT PRIMARY KEY, v INT)")
        c.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        t = db.resolve_table(["t"])
        idx = pk_index(t)
        # simulate: reader already rebuilt at the current version, then a
        # stale pk_extend fires with the PRE-append version
        keys = idx.keys.copy()
        pk_extend(t, keys, 0, base_version=t.data_version - 1)
        idx2 = pk_index(t)
        assert len(idx2.keys) == 2, "duplicate keys merged into index"
        rows = c.execute("SELECT v FROM t WHERE k >= 0 AND k < 100").rows()
        assert sorted(rows) == [(10,), (20,)]
