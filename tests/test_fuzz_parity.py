"""Randomized differential testing: the device aggregate path vs the CPU
oracle on generated predicates/aggregates (reference analog:
tests/fuzz/null_semantics_fuzz.py vs the Postgres oracle — here the oracle
is our own exact CPU path)."""

import numpy as np
import pytest

from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.exec.tables import MemTable

N_ROWS = 3000
N_QUERIES = 60


def _mk_db(seed):
    rng = np.random.default_rng(seed)
    db = Database()
    validity = rng.random(N_ROWS) > 0.15
    batch = Batch.from_pydict({
        "a": Column(Column.from_numpy(
            rng.integers(-50, 50, N_ROWS).astype(np.int32)).type,
            rng.integers(-50, 50, N_ROWS).astype(np.int32), validity.copy()),
        "b": Column.from_numpy(
            rng.integers(0, 1000000, N_ROWS).astype(np.int64)),
        "f": Column.from_numpy(rng.normal(size=N_ROWS)),
        "s": Column.from_numpy(
            rng.choice(["red", "green", "blue", "teal"], N_ROWS)),
        "g": Column.from_numpy(rng.integers(0, 12, N_ROWS).astype(np.int32)),
    })
    db.schemas["main"].tables["fz"] = MemTable("fz", batch)
    return db, rng


def _rand_pred(rng) -> str:
    def leaf():
        kind = rng.integers(0, 10)
        if kind == 0:
            return f"a {rng.choice(['<', '<=', '>', '>=', '=', '<>'])} " \
                   f"{rng.integers(-60, 60)}"
        if kind == 1:
            return f"b {rng.choice(['<', '>'])} {rng.integers(0, 1000000)}"
        if kind == 2:
            return f"s {rng.choice(['=', '<>', '<', '>'])} " \
                   f"'{rng.choice(['red', 'green', 'blue', 'zz'])}'"
        if kind == 3:
            return "a IS NULL"
        if kind == 4:
            return "a IS NOT NULL"
        if kind == 5:
            return f"a + {rng.integers(1, 9)} > g * {rng.integers(1, 4)}"
        if kind == 6:
            lo = int(rng.integers(-50, 20))
            return f"a BETWEEN {lo} AND {lo + int(rng.integers(0, 40))}"
        if kind == 7:
            vals = ", ".join(str(int(v))
                             for v in rng.integers(-50, 50, 3))
            neg = "NOT " if rng.random() < 0.3 else ""
            return f"a {neg}IN ({vals})"
        if kind == 8:
            opts = ", ".join(f"'{o}'" for o in
                             rng.choice(["red", "green", "teal"],
                                        rng.integers(1, 3), replace=False))
            return f"s IN ({opts})"
        return f"g {rng.choice(['=', '<>'])} {rng.integers(0, 14)}"

    e = leaf()
    for _ in range(int(rng.integers(0, 3))):
        op = rng.choice(["AND", "OR"])
        nxt = leaf()
        if rng.random() < 0.25:
            nxt = f"NOT ({nxt})"
        e = f"({e}) {op} ({nxt})"
    return e


def _rand_query(rng) -> str:
    pred = _rand_pred(rng)
    aggs = list(rng.choice(
        ["count(*)", "count(a)", "sum(a)", "sum(b)", "min(a)", "max(g)",
         "avg(a)"], size=rng.integers(1, 4), replace=False))
    shape = rng.integers(0, 6)
    if shape == 5:   # string group keys (dictionary codes on device)
        return (f"SELECT s, count(*), sum(b) FROM fz WHERE {pred} "
                "GROUP BY s ORDER BY s NULLS LAST")
    if shape == 0:
        return f"SELECT {', '.join(aggs)} FROM fz WHERE {pred}"
    if shape == 1:
        return (f"SELECT g, {', '.join(aggs)} FROM fz WHERE {pred} "
                "GROUP BY g ORDER BY g NULLS LAST")
    if shape == 2:   # HAVING over an aggregate
        return (f"SELECT g, count(*) FROM fz WHERE {pred} GROUP BY g "
                f"HAVING count(*) > {rng.integers(0, 40)} "
                "ORDER BY g NULLS LAST")
    if shape == 3:   # expressions over aggregates in the projection
        return (f"SELECT g, sum(a) + count(*), "
                f"CASE WHEN count(*) > {rng.integers(5, 50)} THEN 'big' "
                f"ELSE 'small' END "
                f"FROM fz WHERE {pred} GROUP BY g ORDER BY g NULLS LAST")
    # plain scan with ORDER BY + LIMIT; (g, b, a) pins the order — rows
    # still tied after all three keys are identical in every projected
    # column, so any order compares equal
    return (f"SELECT a, b, g FROM fz WHERE {pred} "
            f"ORDER BY g NULLS LAST, b, a NULLS LAST "
            f"LIMIT {rng.integers(1, 30)}")


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_device_cpu_parity_fuzz(seed):
    db, rng = _mk_db(seed)
    conn = db.connect()
    mismatches = []
    for qi in range(N_QUERIES):
        q = _rand_query(rng)
        conn.execute("SET serene_device = 'cpu'")
        cpu = conn.execute(q).rows()
        conn.execute("SET serene_device = 'tpu'")
        dev = conn.execute(q).rows()
        if len(cpu) != len(dev):
            mismatches.append((q, "row count", len(cpu), len(dev)))
            continue
        for rc, rd in zip(cpu, dev):
            for a, b in zip(rc, rd):
                if isinstance(a, float) or isinstance(b, float):
                    if not (a == b or
                            (a is not None and b is not None and
                             abs(a - b) <= 1e-4 + 1e-4 * abs(a))):
                        mismatches.append((q, rc, rd))
                        break
                elif a != b:
                    mismatches.append((q, rc, rd))
                    break
    assert not mismatches, mismatches[:3]
