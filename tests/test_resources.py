"""Resource observability (ISSUE 13): per-query memory accounting,
wait events, live query progress.

The contract under test is the same one serene_profile/serene_trace
carry: accounting OBSERVES, never steers — results are bit-identical
with it on or off at any worker/shard count — while the resource axis
becomes visible everywhere it should: per-operator Memory lines in
EXPLAIN ANALYZE (text + FORMAT JSON), peak_mem columns in
sdb_stat_statements, the QueryPeakBytes histogram in /metrics and
/_stats.memory, peak_bytes on flight-recorder entries, PG-style
wait_event columns in pg_stat_activity, and advancing
sdb_query_progress() rows for running statements.
"""

import threading
import time

import numpy as np
import pytest

from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.exec.tables import MemTable
from serenedb_tpu.obs.resources import (ACTIVE, CURRENT_MEM,
                                        MemoryAccountant, read_rss_bytes,
                                        sample_process_gauges, wait_scope)
from serenedb_tpu.utils import metrics


def _db(n=40_000, seed=11):
    rng = np.random.default_rng(seed)
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE facts (k INT, v BIGINT)")
    c.execute("CREATE TABLE dims (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["facts"] = MemTable("facts", Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 50, n).astype(np.int32)),
        "v": Column.from_numpy(rng.integers(0, n, n, dtype=np.int64))}))
    db.schemas["main"].tables["dims"] = MemTable("dims", Batch.from_pydict({
        "k": Column.from_numpy(np.arange(n, dtype=np.int64)),
        "w": Column.from_numpy(rng.integers(0, 9, n, dtype=np.int64))}))
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_morsel_rows = 4096")
    c.execute("SET serene_parallel_min_rows = 1024")
    # session-pinned so the suite is invariant to the global the
    # verify_tier1.sh env hooks may have forced either way
    c.execute("SET serene_mem_account = on")
    return db, c


AGG_Q = ("SELECT k, count(*), sum(v) FROM facts WHERE v % 3 <> 0 "
         "GROUP BY k ORDER BY k")
JOIN_Q = ("SELECT count(*), sum(v + w) FROM facts "
          "JOIN dims ON facts.v = dims.k")


# -- parity matrix -----------------------------------------------------------


@pytest.mark.parametrize("workers", [1, 4])
@pytest.mark.parametrize("shards", [1, 4])
def test_parity_matrix_agg_join(workers, shards):
    """Results are bit-identical with accounting on/off at any
    worker/shard count — the observe-only contract."""
    db, c = _db()
    c.execute(f"SET serene_workers = {workers}")
    c.execute(f"SET serene_shards = {shards}")
    got = {}
    for mode in ("on", "off"):
        c.execute(f"SET serene_mem_account = {mode}")
        got[mode] = (c.execute(AGG_Q).rows(), c.execute(JOIN_Q).rows())
    assert got["on"] == got["off"]


def test_mem_account_not_result_affecting():
    """The setting must never split the result cache: accounting
    cannot change what a result CONTAINS."""
    from serenedb_tpu.cache.result import RESULT_AFFECTING_SETTINGS
    assert "serene_mem_account" not in RESULT_AFFECTING_SETTINGS


# -- peak-bytes sanity -------------------------------------------------------


def test_join_peak_bounds_build_side_1m():
    """The accounted peak of a 1M-row hash join bounds the measured
    build-side array bytes from above and stays within 2x of the total
    arrays the join demonstrably materializes (build + probe + pair
    indices + output/partials slack)."""
    rng = np.random.default_rng(31)
    n = 1_000_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE po (k INT, v BIGINT)")
    c.execute("CREATE TABLE pb (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["po"] = MemTable("po", Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 1000, n).astype(np.int32)),
        "v": Column.from_numpy(
            rng.permutation(np.arange(n, dtype=np.int64)))}))
    db.schemas["main"].tables["pb"] = MemTable("pb", Batch.from_pydict({
        "k": Column.from_numpy(
            rng.permutation(np.arange(n, dtype=np.int64))),
        "w": Column.from_numpy(rng.integers(0, 100, n, dtype=np.int64))}))
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_mem_account = on")
    q = "SELECT count(*), sum(v + w) FROM po JOIN pb ON po.v = pb.k"
    c.execute(q)
    rows = c.execute(
        "SELECT last_peak_mem_bytes FROM sdb_stat_statements "
        "WHERE query LIKE '%from po join pb%'").rows()
    assert rows, "statement not recorded"
    peak = rows[0][0]
    build_bytes = 16 * n            # pb: two int64 columns
    probe_bytes = 12 * n            # po: int32 + int64
    pair_bytes = 2 * 8 * n          # li/ri int64 index arrays
    assert peak >= build_bytes, (peak, build_bytes)
    # generous-but-meaningful cap: everything the join materializes,
    # doubled (morsel partials, merged dictionaries, output)
    cap = 2 * (build_bytes + probe_bytes + pair_bytes + (1 << 20))
    assert peak <= cap, (peak, cap)


def test_accountant_merged_peak_is_upper_bound():
    """Unit property: Σ per-thread peaks >= the true simultaneous
    total, and per-key live returns to zero on balanced traffic."""
    acct = MemoryAccountant("unit")
    stop = threading.Barrier(3)

    def worker():
        stop.wait()
        for _ in range(200):
            acct.charge("op", 1000)
            acct.release("op", 1000)

    ts = [threading.Thread(target=worker) for _ in range(2)]
    for t in ts:
        t.start()
    stop.wait()
    for t in ts:
        t.join()
    live, peak = acct.totals()
    assert live == 0
    assert 1000 <= peak <= 2000     # each thread's peak is exactly 1000
    m = acct.merged()
    assert m["op"][0] == 0 and m["op"][1] >= 1000
    assert acct.event_count() == 800


# -- wait events -------------------------------------------------------------


def test_wait_event_visible_during_pool_saturated_query():
    """A statement blocked on worker-pool tasks publishes a non-null
    wait_event into its pg_stat_activity row while it waits, and the
    row is clean again after completion."""
    from serenedb_tpu.engine import CURRENT_CONNECTION
    from serenedb_tpu.parallel.pool import get_pool
    db = Database()
    c = db.connect()
    sess = db.sessions[c._session_id]
    seen = []
    done = threading.Event()

    def blocked():
        tok = CURRENT_CONNECTION.set(c)
        try:
            pool = get_pool().ensure_started()
            futs = [pool.submit(time.sleep, 0.15) for _ in range(4)]
            for f in futs:
                if not f.done():
                    with wait_scope("IPC", "PoolTaskWait"):
                        f.result()
                else:
                    f.result()
        finally:
            CURRENT_CONNECTION.reset(tok)
            done.set()

    t = threading.Thread(target=blocked)
    t.start()
    while not done.is_set():
        ev = (sess.get("wait_event_type"), sess.get("wait_event"))
        if ev[0] is not None:
            seen.append(ev)
        time.sleep(0.002)
    t.join()
    assert ("IPC", "PoolTaskWait") in seen
    assert sess.get("wait_event_type") is None
    assert sess.get("wait_event") is None


def test_wait_event_via_sql_during_saturated_pool():
    """Acceptance shape: a REAL statement whose morsel tasks queue
    behind a saturated pool shows a non-null wait_event in
    pg_stat_activity (read via SQL from another connection) while it
    waits, and advancing sdb_query_progress() counters."""
    from serenedb_tpu.parallel.pool import get_pool
    db, c = _db(n=200_000, seed=5)
    c.execute("SET serene_workers = 4")
    observer = db.connect()
    pool = get_pool().ensure_started()
    # occupy every worker so the query's morsel tasks must queue
    blockers = [pool.submit(time.sleep, 0.3) for _ in range(pool.size)]
    done = threading.Event()
    t = threading.Thread(target=lambda: (c.execute(AGG_Q), done.set()))
    t.start()
    waits, progressed = [], []
    while not done.is_set():
        rows = observer.execute(
            "SELECT wait_event_type, wait_event FROM pg_stat_activity "
            f"WHERE pid = {c._session_id}").rows()
        if rows and rows[0][0] is not None:
            waits.append(rows[0])
        for r in ACTIVE.snapshot():
            if "facts" in r["query"]:
                progressed.append(r["morsels_done"])
        time.sleep(0.002)
    t.join()
    for f in blockers:
        f.result()
    assert ("IPC", "PoolTaskWait") in waits
    assert progressed and max(progressed) >= 1


def test_wait_scope_nests_and_restores():
    db = Database()
    c = db.connect()
    from serenedb_tpu.engine import CURRENT_CONNECTION
    sess = db.sessions[c._session_id]
    tok = CURRENT_CONNECTION.set(c)
    try:
        with wait_scope("IPC", "Outer"):
            assert sess["wait_event"] == "Outer"
            with wait_scope("Device", "Inner"):
                assert sess["wait_event_type"] == "Device"
                assert sess["wait_event"] == "Inner"
            assert sess["wait_event"] == "Outer"
        assert sess["wait_event"] is None
    finally:
        CURRENT_CONNECTION.reset(tok)


def test_pg_stat_activity_wait_columns_null_when_running():
    db, c = _db()
    rows = c.execute(
        "SELECT pid, state, wait_event_type, wait_event "
        "FROM pg_stat_activity").rows()
    me = [r for r in rows if r[1] == "active"]
    assert me and me[0][2] is None and me[0][3] is None


# -- live query progress -----------------------------------------------------


def test_progress_rows_monotone_and_retired():
    """A running aggregate's progress counters only grow while it
    executes, and its row leaves the registry on completion."""
    db, c = _db(n=300_000, seed=3)
    c.execute("SET serene_workers = 4")
    done = threading.Event()
    err = []

    def run():
        try:
            c.execute(AGG_Q)
        except Exception as e:       # pragma: no cover — surfaced below
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=run)
    t.start()
    samples = []
    while not done.is_set():
        for r in ACTIVE.snapshot():
            if "facts" in r["query"]:
                samples.append((r["morsels_done"], r["rows"], r["bytes"]))
        time.sleep(0.001)
    t.join()
    assert not err, err
    assert samples, "statement finished before any progress sample"
    for a, b in zip(samples, samples[1:]):
        assert b[0] >= a[0] and b[1] >= a[1] and b[2] >= a[2]
    assert samples[-1][0] >= 1      # morsels really advanced
    # retired on completion: no phantom running query remains
    assert not [r for r in ACTIVE.snapshot() if "facts" in r["query"]]


def test_progress_retired_on_error():
    db, c = _db()
    with pytest.raises(Exception):
        c.execute("SELECT 1 / 0 FROM facts")
    assert not [r for r in ACTIVE.snapshot() if "facts" in r["query"]]


def test_sdb_query_progress_relation_lists_self():
    """The observing statement is itself a running statement (PG
    pg_stat_activity semantics) — the relation and the table function
    both resolve and carry the full column set."""
    db, c = _db()
    rows = c.execute(
        "SELECT pid, query, operator, morsels_scheduled, morsels_done, "
        "rows, bytes, live_bytes, peak_bytes, elapsed_ms "
        "FROM sdb_query_progress()").rows()
    assert rows and any("sdb_query_progress" in r[1] for r in rows)
    rows2 = c.execute(
        "SELECT pid FROM sdb_query_progress").rows()
    assert rows2


def test_streaming_statement_registers_and_retires_progress():
    from serenedb_tpu.sql import parser
    db, c = _db()
    st = parser.parse("SELECT k, v FROM facts")[0]
    names, types, gen = c.execute_streaming(
        st, sql_text="SELECT k, v FROM facts")
    first = next(gen)
    assert first.num_rows
    assert any("facts" in r["query"] for r in ACTIVE.snapshot())
    for _ in gen:
        pass
    assert not [r for r in ACTIVE.snapshot() if "facts" in r["query"]]


# -- surfaces ----------------------------------------------------------------


def test_explain_analyze_memory_lines_text_and_json():
    import json
    db, c = _db()
    txt = "\n".join(r[0] for r in c.execute(
        f"EXPLAIN ANALYZE {JOIN_Q}").rows())
    assert "Memory: peak=" in txt
    assert "Peak Memory:" in txt
    doc = json.loads(c.execute(
        f"EXPLAIN (ANALYZE, FORMAT JSON) {JOIN_Q}").rows()[0][0])[0]
    assert doc["Peak Memory Bytes"] > 0

    def any_node(d):
        if d.get("Peak Memory Bytes", 0) > 0:
            return True
        return any(any_node(k) for k in d.get("Plans", []))

    assert any_node(doc["Plan"])


def test_stat_statements_peak_columns_and_max_semantics():
    from serenedb_tpu.obs.statements import STATEMENTS
    db, c = _db()
    c.execute(JOIN_Q)
    rows = c.execute(
        "SELECT peak_mem_bytes, last_peak_mem_bytes "
        "FROM sdb_stat_statements WHERE query LIKE '%join dims%'").rows()
    assert rows and rows[0][0] > 0
    assert rows[0][0] >= rows[0][1]
    # direct store semantics: peak_mem_bytes is the max across calls
    STATEMENTS.record("SELECT x FROM peakprobe_tbl", 1000, 1, 0, 100,
                      peak_bytes=500)
    STATEMENTS.record("SELECT x FROM peakprobe_tbl", 1000, 1, 0, 100,
                      peak_bytes=200)
    e = [x for x in STATEMENTS.snapshot()
         if "peakprobe_tbl" in x["query"]][-1]
    assert e["peak_mem_bytes"] == 500
    assert e["last_peak_mem_bytes"] == 200


def test_query_peak_histogram_in_metrics_and_stats():
    from serenedb_tpu.obs.export import prometheus_text, stats_json
    db, c = _db()
    base = metrics.QUERY_PEAK_BYTES_HIST.count
    c.execute(JOIN_Q)
    assert metrics.QUERY_PEAK_BYTES_HIST.count > base
    text = prometheus_text()
    # byte-unit histogram: raw-byte buckets, no _seconds suffix
    assert "serenedb_query_peak_bytes_bucket" in text
    assert "serenedb_query_peak_bytes_seconds" not in text
    sj = stats_json()
    assert sj["memory"]["query_peak"]["count"] > 0
    assert sj["memory"]["query_peak"]["p99_bytes"] > 0
    # byte histograms stay OUT of the latency percentile section
    assert "QueryPeakBytes" not in sj["latency"]
    assert isinstance(sj["memory"]["progress"], list)


def test_flight_recorder_entries_carry_peak_bytes():
    db, c = _db()
    c.execute(JOIN_Q)
    rows = c.execute(
        "SELECT query, peak_bytes FROM sdb_trace").rows()
    mine = [r for r in rows if "JOIN dims" in r[0]]
    assert mine and mine[-1][1] > 0
    from serenedb_tpu.obs.trace import FLIGHT, flight_summary
    entry = FLIGHT.last()
    assert "peak_bytes" in flight_summary(entry)


def test_slow_query_log_attaches_memory():
    from serenedb_tpu.utils import log
    db, c = _db()
    c.execute("SET serene_log_min_duration_ms = 0")
    c.execute(AGG_Q)
    recs = [r for r in log.MANAGER.records() if r.topic == "slow_query"]
    assert recs
    msg = recs[-1].message
    assert "memory: peak=" in msg


def test_mem_account_off_disables_surfaces():
    from serenedb_tpu.obs.statements import fingerprint, normalize
    db, c = _db()
    c.execute("SET serene_mem_account = off")
    c.execute(JOIN_Q)
    qid = fingerprint(normalize(JOIN_Q))
    rows = c.execute(
        "SELECT last_peak_mem_bytes FROM sdb_stat_statements "
        f"WHERE queryid = {qid}").rows()
    assert rows and rows[0][0] == 0
    txt = "\n".join(r[0] for r in c.execute(
        "EXPLAIN ANALYZE SELECT 1").rows())
    # EXPLAIN ANALYZE always instruments (PG semantics), even with the
    # session setting off — same rule as the profiler
    assert "Peak Memory:" in txt


# -- process-level gauges ----------------------------------------------------


def test_process_gauges_sampled():
    sample_process_gauges()
    assert read_rss_bytes() > 0              # linux CI: procfs present
    assert metrics.PROCESS_RSS_BYTES.value > 0
    assert metrics.PROCESS_UPTIME_SECONDS.value >= 0
    assert metrics.GC_GEN0_COLLECTIONS.value >= 0


def test_process_gauges_via_sdb_metrics_and_http_progress():
    db, c = _db()
    rows = dict(c.execute(
        "SELECT metric, value FROM sdb_metrics "
        "WHERE metric LIKE 'Process%'").rows())
    assert rows.get("ProcessRssBytes", 0) > 0
    # GET /progress serves the live registry
    from serenedb_tpu.server.http_server import HttpServer
    import json as _json
    import urllib.request
    srv = HttpServer(db, port=0)
    srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/progress", timeout=10) as r:
            payload = _json.loads(r.read())
        assert isinstance(payload, list)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/_stats", timeout=10) as r:
            stats = _json.loads(r.read())
        assert stats["memory"]["process"]["rss_bytes"] > 0
    finally:
        srv.stop()


# -- contextvar hygiene ------------------------------------------------------


def test_current_mem_clean_after_statements():
    db, c = _db()
    c.execute(AGG_Q)
    assert CURRENT_MEM.get() is None
    with pytest.raises(Exception):
        c.execute("SELECT nope FROM facts")
    assert CURRENT_MEM.get() is None
