"""Behavior files over the LIVE wire — simple, extended-text and
extended-binary protocol modes.

Reference analog: sqllogictest-rs runs every .test file over 4 wire
protocol modes against a live serened (tests/sqllogic/run.sh,
CONTRIBUTING.md:57-72). Here every non-recovery behavior file runs against
an in-process PgServer through a raw-socket client in three modes:

  simple            one 'Q' message per record
  extended          Parse/Bind(text)/Describe/Execute/Sync
  extended-binary   Parse/Describe(stmt)/Bind with per-column BINARY result
                    formats for every binary-capable OID, client-side decode

Values are normalized to the sqllogic golden format per column type OID
(bool t/f → true/false, float repr → trimmed %.3f — the same rules
tests/sqllogic_runner.format_value applies in-process), which is exactly
what sqllogictest-rs does with its type strings."""

import asyncio
import glob
import math
import os
import struct
import threading

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.server.pgwire import PgServer
from tests.sqllogic_runner import run_test_file_wire
from tests.test_pgwire import RawPg, _parse_err

_ROOT = os.path.join(os.path.dirname(__file__), "sqllogic")

FILES = sorted(
    glob.glob(os.path.join(_ROOT, "*.test"))
    + glob.glob(os.path.join(_ROOT, "any", "**", "*.test"), recursive=True)
    + glob.glob(os.path.join(_ROOT, "sdb", "**", "*.test"), recursive=True))

MODES = ["simple", "extended", "extended-binary"]

# OIDs the client can decode from PG binary format back to golden text
_BINARY_OIDS = {16, 20, 21, 23, 25, 26, 700, 701, 1043, 1082, 1114, 1186}
_PG_EPOCH_US = 946_684_800_000_000   # 2000-01-01 vs unix epoch, µs
_PG_EPOCH_DAYS = 10_957


def _fmt_float(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "Infinity" if v > 0 else "-Infinity"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.3f}".rstrip("0").rstrip(".")


def _norm_text(oid: int, s: str) -> str:
    if oid == 16:
        return {"t": "true", "f": "false"}.get(s, s)
    if oid in (700, 701):
        return _fmt_float(float(s))
    return s


def _decode_binary(oid: int, raw: bytes) -> str:
    if oid == 16:
        return "false" if raw == b"\x00" else "true"
    if oid == 21:
        return str(struct.unpack("!h", raw)[0])
    if oid == 23:
        return str(struct.unpack("!i", raw)[0])
    if oid == 20:
        return str(struct.unpack("!q", raw)[0])
    if oid == 26:
        return str(struct.unpack("!I", raw)[0])
    if oid == 700:
        return _fmt_float(struct.unpack("!f", raw)[0])
    if oid == 701:
        return _fmt_float(struct.unpack("!d", raw)[0])
    if oid == 1114:
        from serenedb_tpu.sql.binder import format_timestamp
        return format_timestamp(struct.unpack("!q", raw)[0] + _PG_EPOCH_US)
    if oid == 1082:
        import numpy as np
        return str(np.datetime64(
            struct.unpack("!i", raw)[0] + _PG_EPOCH_DAYS, "D"))
    if oid == 1186:
        from serenedb_tpu.sql.binder import format_interval
        return format_interval(struct.unpack("!qii", raw)[0])
    return raw.decode()


class WireClient:
    """sqllogic executor over one raw pg-wire connection."""

    def __init__(self, pg: RawPg, mode: str):
        self.pg = pg
        self.mode = mode

    def execute(self, sql: str):
        if self.mode == "simple":
            return self._simple(sql)
        return self._extended(sql, binary=self.mode == "extended-binary")

    # -- simple protocol ---------------------------------------------------

    def _simple(self, sql: str):
        pg = self.pg
        pg.send(b"Q", sql.encode() + b"\x00")
        oids, rows, err = [], [], None
        while True:
            kind, payload = pg.read_msg()
            if kind == b"T":
                oids = self._row_desc_oids(payload)
            elif kind == b"D":
                rows.append(self._data_row(payload, oids, binary=False))
            elif kind == b"E":
                f = _parse_err(payload)
                err = err or (f.get("C", ""), f.get("M", ""))
            elif kind == b"Z":
                return rows, err

    # -- extended protocol -------------------------------------------------

    def _extended(self, sql: str, binary: bool):
        pg = self.pg
        pg.send(b"P", b"\x00" + sql.encode() + b"\x00" + b"\x00\x00")
        fmts: list[int] = []
        oids: list[int] = []
        if binary:
            # Describe the statement first: result formats are chosen per
            # column OID (binary where the client can decode it)
            pg.send(b"D", b"S\x00")
            pg.send(b"S", b"")
            err = None
            while True:
                kind, payload = pg.read_msg()
                if kind == b"T":
                    oids = self._row_desc_oids(payload)
                    fmts = [1 if o in _BINARY_OIDS else 0 for o in oids]
                elif kind == b"E":
                    f = _parse_err(payload)
                    err = err or (f.get("C", ""), f.get("M", ""))
                elif kind == b"Z":
                    break
            if err is not None:
                return [], err
        parts = [b"\x00", b"\x00", struct.pack("!H", 0),
                 struct.pack("!H", 0), struct.pack("!H", len(fmts))]
        parts.extend(struct.pack("!h", f) for f in fmts)
        pg.send(b"B", b"".join(parts))
        pg.send(b"D", b"P\x00")
        pg.send(b"E", b"\x00" + struct.pack("!I", 0))
        pg.send(b"S", b"")
        rows, err = [], None
        while True:
            kind, payload = pg.read_msg()
            if kind == b"T":
                oids = self._row_desc_oids(payload)
            elif kind == b"D":
                rows.append(self._data_row(payload, oids, binary, fmts))
            elif kind == b"E":
                f = _parse_err(payload)
                err = err or (f.get("C", ""), f.get("M", ""))
            elif kind == b"Z":
                return rows, err

    # -- frame decoding ----------------------------------------------------

    @staticmethod
    def _row_desc_oids(payload: bytes) -> list[int]:
        (n,) = struct.unpack("!H", payload[:2])
        off = 2
        oids = []
        for _ in range(n):
            end = payload.index(b"\x00", off)
            oids.append(struct.unpack("!I", payload[end + 7:end + 11])[0])
            off = end + 1 + 18
        return oids

    @staticmethod
    def _data_row(payload: bytes, oids, binary: bool,
                  fmts=()) -> list[str]:
        (n,) = struct.unpack("!H", payload[:2])
        off = 2
        row = []
        for i in range(n):
            (ln,) = struct.unpack("!i", payload[off:off + 4])
            off += 4
            if ln < 0:
                row.append("NULL")
                continue
            raw = payload[off:off + ln]
            off += ln
            oid = oids[i] if i < len(oids) else 25
            col_binary = binary and i < len(fmts) and fmts[i] == 1
            row.append(_decode_binary(oid, raw) if col_binary
                       else _norm_text(oid, raw.decode()))
        return row


@pytest.fixture
def wire_db(tmp_path):
    """Fresh database + live PgServer per behavior file."""
    db = Database()
    srv = PgServer(db, port=0)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def go():
            await srv.start()
            started.set()
            await asyncio.Event().wait()
        try:
            loop.run_until_complete(go())
        except RuntimeError:
            pass
    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(20), "pg server failed to start"
    old = os.getcwd()
    os.chdir(tmp_path)   # relative COPY paths land in tmp
    try:
        yield srv
    finally:
        os.chdir(old)
        # stop the server ON its loop before stopping the loop — closing
        # transports after loop shutdown raises "Event loop is closed"
        done = threading.Event()

        def _shutdown():
            task = loop.create_task(srv.stop())
            task.add_done_callback(lambda _: (loop.stop(), done.set()))
        loop.call_soon_threadsafe(_shutdown)
        done.wait(10)
        db.close()


def _ids(files):
    return [os.path.relpath(f, _ROOT) for f in files]


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("path", FILES, ids=_ids(FILES))
def test_sqllogic_wire(path, mode, wire_db, tmp_path):
    pg = RawPg(wire_db.port)
    try:
        failures = run_test_file_wire(WireClient(pg, mode).execute, path,
                                      tmpdir=str(tmp_path))
        assert not failures, "\n".join(failures[:8])
    finally:
        pg.close()
