"""Automaton ∩ sorted-dictionary intersection (reference: openfst
automata over the burst trie, burst_trie.cpp). Parity with brute force +
a bounded-work assertion at large vocab."""

import time

import numpy as np

from serenedb_tpu.search.automaton import (intersect_sorted,
                                           levenshtein_nfa)
from serenedb_tpu.search.regexp import compile_regexp


def _vocab(n=1_200_000, seed=9):
    rng = np.random.default_rng(seed)
    syll = np.asarray(["ba", "ko", "ri", "zu", "ten", "mar", "vel", "qu",
                       "ix", "lo", "pre", "sta", "ing", "er"])
    parts = syll[rng.integers(0, len(syll), (n, 6))]
    words = parts[:, 0]
    for k in range(1, 6):
        words = np.char.add(words, parts[:, k])
    # numeric suffix forces uniqueness past the syllable combinatorics
    words = np.char.add(words, (rng.integers(0, 1000, n)).astype(str))
    terms = np.unique(words)
    return terms


class TestIntersection:
    def test_regex_parity_small(self):
        terms = np.asarray(sorted(
            ["alpha", "alps", "beta", "better", "bet", "gamma", "gap",
             "", "zzz", "alp"]))
        for pat in [".*a.*", "al.*", "bet(ter)?", "g.p", "[ab].*",
                    ".*", "x.*", "(alp|gap)s?"]:
            rx = compile_regexp(pat)
            got = intersect_sorted(rx.start, rx.end, terms)
            want = [i for i, t in enumerate(terms)
                    if rx.fullmatch(str(t))]
            assert got == want, (pat, got, want)

    def test_fuzzy_parity_small(self):
        from serenedb_tpu.search.query import edit_distance_at_most
        terms = np.asarray(sorted(
            ["cat", "cats", "bat", "hat", "chat", "cart", "dog", "doge",
             "catalog", "ct", "at"]))
        for term, k in [("cat", 1), ("cat", 2), ("dog", 1), ("xyz", 1)]:
            start, end = levenshtein_nfa(term, k)
            got = intersect_sorted(start, end, terms)
            want = [i for i, t in enumerate(terms)
                    if edit_distance_at_most(str(t), term, k)]
            assert got == want, (term, k, got, want)

    def test_large_vocab_parity_and_bounded_work(self):
        terms = _vocab()
        assert len(terms) > 1_000_000
        # selective prefix regex: the seek walk must not touch the
        # whole dictionary
        rx = compile_regexp("zu(ten|mar)..ba.*")
        t0 = time.perf_counter()
        got = intersect_sorted(rx.start, rx.end, terms)
        dt_idx = time.perf_counter() - t0
        lo = np.searchsorted(terms, "zu")
        hi = np.searchsorted(terms, "zv")
        want = [int(i) for i in range(lo, hi)
                if rx.fullmatch(str(terms[i]))]
        assert got == want
        # brute force over the whole vocab for comparison
        t0 = time.perf_counter()
        sample = terms[:: max(1, len(terms) // 20_000)]
        for t in sample:                       # 20k-term sample
            rx.fullmatch(str(t))
        dt_sample = (time.perf_counter() - t0) * (len(terms) / len(sample))
        assert dt_idx < dt_sample / 5, \
            f"intersection {dt_idx:.3f}s not ≪ projected scan {dt_sample:.3f}s"

    def test_large_vocab_fuzzy_bounded(self):
        terms = _vocab()
        start, end = levenshtein_nfa("kotenmarvel", 1)
        t0 = time.perf_counter()
        got = intersect_sorted(start, end, terms)
        dt = time.perf_counter() - t0
        assert dt < 5.0, f"fuzzy intersection took {dt:.1f}s at 1M vocab"
        from serenedb_tpu.search.query import edit_distance_at_most
        band = [i for i in got
                if not edit_distance_at_most(str(terms[i]),
                                             "kotenmarvel", 1)]
        assert not band, "false positives from the automaton"
        # recall: every brute-force match in a sampled band must be found
        lo = int(np.searchsorted(terms, "ko"))
        hi = int(np.searchsorted(terms, "kp"))
        want_band = [i for i in range(lo, hi)
                     if edit_distance_at_most(str(terms[i]),
                                              "kotenmarvel", 1)]
        got_set = set(got)
        missing = [i for i in want_band if i not in got_set]
        assert not missing, "false negatives (over-skipping)"


class TestDfaBudget:
    def test_blowup_pattern_falls_back(self):
        """Counting patterns explode subset construction; the walk must
        degrade to per-term NFA matching, not allocate without bound."""
        from serenedb_tpu.search import automaton as am
        terms = np.asarray(sorted(
            {"".join(np.random.default_rng(i).choice(
                list("ab"), 24)) for i in range(4000)}))
        rx = compile_regexp(".*a.{18}")
        got = am.intersect_sorted(rx.start, rx.end, terms)
        want = [i for i, t in enumerate(terms) if rx.fullmatch(str(t))]
        assert got == want
        assert want, "test vector should have matches"
