"""Multi-spec listeners: tcp:// and unix:// endpoints (reference:
server/network/listen_spec.h) + build id."""

import asyncio
import socket
import struct
import threading

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.server.listen import ListenSpec, parse_listen_spec
from serenedb_tpu.server.pgwire import PgServer


def test_parse_listen_specs():
    assert parse_listen_spec("tcp://0.0.0.0:5433") == \
        ListenSpec("tcp", host="0.0.0.0", port=5433)
    assert parse_listen_spec("127.0.0.1:9") == \
        ListenSpec("tcp", host="127.0.0.1", port=9)
    assert parse_listen_spec(":7777") == \
        ListenSpec("tcp", host="0.0.0.0", port=7777)
    assert parse_listen_spec("5433", default_host="10.0.0.1") == \
        ListenSpec("tcp", host="10.0.0.1", port=5433)
    assert parse_listen_spec("unix:///tmp/s.sock") == \
        ListenSpec("unix", path="/tmp/s.sock")
    assert parse_listen_spec("unix:/tmp/s2.sock") == \
        ListenSpec("unix", path="/tmp/s2.sock")
    assert parse_listen_spec("[::1]:6000") == \
        ListenSpec("tcp", host="::1", port=6000)
    for bad in ("unix://", "nonsense", ""):
        with pytest.raises(ValueError):
            parse_listen_spec(bad)


@pytest.fixture
def multi_server(tmp_path):
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE t (a INT)")
    c.execute("INSERT INTO t VALUES (42)")
    sock_path = str(tmp_path / "pg.sock")
    srv = PgServer(db, port=0,
                   listen=["tcp://127.0.0.1:0", f"unix://{sock_path}"])
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def go():
            await srv.start()
            started.set()
            await asyncio.Event().wait()
        try:
            loop.run_until_complete(go())
        except RuntimeError:
            pass
    t = threading.Thread(target=run, daemon=True)
    t.start()
    started.wait(10)
    yield srv, sock_path, loop
    fut = asyncio.run_coroutine_threadsafe(srv.stop(), loop)
    fut.result(timeout=10)
    loop.call_soon_threadsafe(loop.stop)


from test_pgwire import RawPg  # noqa: E402  (proven raw-wire client)


def test_unix_socket_listener(multi_server):
    srv, sock_path, _ = multi_server
    # reuse RawPg's protocol implementation over an AF_UNIX transport
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(15)
    sock.connect(sock_path)
    orig = socket.create_connection
    socket.create_connection = lambda *a, **k: sock
    try:
        cl = RawPg(0)
    finally:
        socket.create_connection = orig
    hdr, rows, tags, errs = cl.query("SELECT a FROM t")
    assert rows == [("42",)], rows
    sock.close()


def test_extra_tcp_listener(multi_server):
    srv, _, _ = multi_server
    port = srv._extra_servers[0].sockets[0].getsockname()[1]
    cl = RawPg(port)
    assert cl.query("SELECT a FROM t")[1] == [("42",)]
    cl2 = RawPg(srv.port)   # the primary listener still answers too
    assert cl2.query("SELECT a FROM t")[1] == [("42",)]


def test_unix_socket_removed_on_stop(tmp_path):
    import os
    db = Database()
    path = str(tmp_path / "gone.sock")

    async def cycle():
        srv = PgServer(db, port=0, listen=[f"unix://{path}"])
        await srv.start()
        assert os.path.exists(path)
        await srv.stop()

    asyncio.run(cycle())
    assert not os.path.exists(path)


def test_build_id():
    import serenedb_tpu
    s = serenedb_tpu.build_id()
    assert s.startswith("serenedb-tpu 0.1.0")
    assert "(" in s


def test_hba_unix_vs_host_rules():
    from serenedb_tpu.server.hba import match_rule, parse_hba
    rules = parse_hba("host all all all trust\n"
                      "local all all scram-sha-256\n")
    # TCP peer hits the host rule
    assert match_rule(rules, "db", "u", "10.0.0.1", False).method == "trust"
    # unix peer must NOT fail open through 'host all all all'
    r = match_rule(rules, "db", "u", "/unix-socket", False)
    assert r.method == "scram-sha-256"
    # and local rules never match TCP peers
    rules2 = parse_hba("local all all trust\n")
    assert match_rule(rules2, "db", "u", "10.0.0.1", False) is None


def test_stale_socket_guard(tmp_path):
    import os

    from serenedb_tpu import errors
    from serenedb_tpu.server.pgwire import _remove_stale_unix_socket
    # regular file at the path: refuse to delete
    f = tmp_path / "not_a_socket"
    f.write_text("precious")
    with pytest.raises(errors.SqlError):
        _remove_stale_unix_socket(str(f))
    assert f.read_text() == "precious"
    # stale socket: removed
    import socket as s
    sp = str(tmp_path / "stale.sock")
    sk = s.socket(s.AF_UNIX)
    sk.bind(sp)
    sk.close()   # bound but never listened/closed -> connect refused
    _remove_stale_unix_socket(sp)
    assert not os.path.exists(sp)


def test_live_socket_not_stolen(tmp_path):
    from serenedb_tpu import errors
    from serenedb_tpu.server.pgwire import _remove_stale_unix_socket
    db = Database()
    path = str(tmp_path / "live.sock")

    async def check():
        srv = PgServer(db, port=0, listen=[f"unix://{path}"])
        await srv.start()
        try:
            with pytest.raises(errors.SqlError):
                _remove_stale_unix_socket(path)
        finally:
            await srv.stop()

    asyncio.run(check())


def test_sql_features_dashed_ids():
    db = Database()
    c = db.connect()
    r = c.execute("SELECT is_supported FROM information_schema."
                  "sql_features WHERE feature_id = 'E061-04'").rows()
    assert r == [("YES",)]


def test_serened_rejects_bad_listen_spec(capsys):
    from serenedb_tpu.serened import main
    with pytest.raises(SystemExit):
        main(["--listen", "unix://", "--pg-port", "0",
              "--http-port", "0"])
    with pytest.raises(SystemExit):
        main(["--listen", "[::1", "--pg-port", "0", "--http-port", "0"])
