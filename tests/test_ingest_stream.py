"""Streaming-ingest path: parallel analysis parity (bit-identical to the
serial oracle at any worker count), group-commit windows (coalesced fsync +
coalesced publish, recovery-complete), background segment maintenance
(bounded tiers off the query path), parallel parquet column building, and
the write-path observability surface."""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.search.analysis import get_analyzer
from serenedb_tpu.search.segment import (build_field_index,
                                         build_field_index_auto)
from serenedb_tpu.utils.config import REGISTRY


class _globals:
    """Set registry globals for one test, restoring previous values on
    exit (same contract as tests/test_admission.py: the process-wide
    ingest knobs must be left exactly as the verify_tier1.sh env hooks
    set them)."""

    def __init__(self, **kv):
        self.kv = kv
        self.prev = {}

    def __enter__(self):
        for k, v in self.kv.items():
            self.prev[k] = REGISTRY.get_global(k)
            REGISTRY.set_global(k, v)
        return self

    def __exit__(self, *exc):
        for k, v in self.prev.items():
            REGISTRY.set_global(k, v)
        return False


def _corpus(n, seed=3):
    rng = np.random.default_rng(seed)
    vocab = ["alpha", "beta", "gamma", "delta", "omega", "Sigma", "nu",
             "stream", "ingest", "merge", "segment", "wal", "fsync"]
    docs = []
    for i in range(n):
        if i % 17 == 5:
            docs.append(None)            # NULL rows must keep norms aligned
            continue
        k = int(rng.integers(1, 12))
        docs.append(" ".join(rng.choice(vocab, size=k)))
    return docs


def _assert_field_index_equal(a, b):
    assert [str(t) for t in a.terms] == [str(t) for t in b.terms]
    for name in ("doc_freq", "offsets", "post_docs", "post_tfs",
                 "pos_offsets", "positions", "norms", "block_max_tf",
                 "block_offsets"):
        av, bv = getattr(a, name), getattr(b, name)
        assert av.dtype == bv.dtype, name
        assert np.array_equal(av, bv), name
    assert a.total_tokens == b.total_tokens


@pytest.mark.parametrize("workers", [1, 4])
@pytest.mark.parametrize("analyzer", ["text", "simple"])
def test_parallel_analysis_bit_identical(workers, analyzer):
    """The tentpole parity contract: chunk-split analysis + deterministic
    merge is BIT-IDENTICAL to the serial build — on/off × workers 1/4,
    python and native (ascii+simple) chunk builders alike."""
    an = get_analyzer(analyzer)
    docs = _corpus(700)
    with _globals(serene_parallel_ingest=False):
        serial = build_field_index(list(docs), an)
    for on in (True, False):
        with _globals(serene_parallel_ingest=on,
                      serene_ingest_chunk_docs=64,
                      serene_workers=workers):
            out = build_field_index_auto(list(docs), an)
        _assert_field_index_equal(out, serial)


def test_parallel_merge_handles_empty_and_tiny_chunks():
    """Chunks that tokenize to nothing (all NULL / empty) must merge
    cleanly — the norms still land, term-less parts contribute nothing."""
    an = get_analyzer("text")
    docs = [None] * 70 + ["alpha beta"] * 70 + [""] * 70
    serial = build_field_index(list(docs), an)
    with _globals(serene_parallel_ingest=True,
                  serene_ingest_chunk_docs=64, serene_workers=4):
        out = build_field_index_auto(list(docs), an)
    _assert_field_index_equal(out, serial)


@pytest.mark.parametrize("parallel", [True, False])
@pytest.mark.parametrize("workers", [1, 4])
def test_readers_during_ingest_parity(parallel, workers):
    """Readers racing a sustained ingest stream must only ever observe
    fully-published states: hit counts grow monotonically, and the final
    index state is identical across the on/off × workers matrix."""
    with _globals(serene_parallel_ingest=parallel,
                  serene_ingest_chunk_docs=64,
                  serene_workers=workers):
        db = Database()
        c = db.connect()
        c.execute("CREATE TABLE docs (id INT, body TEXT)")
        c.execute("INSERT INTO docs VALUES (0, 'alpha seed')")
        c.execute("CREATE INDEX ON docs USING inverted (body)")
        stop = threading.Event()
        counts, errors = [], []

        def reader():
            rc = db.connect()
            while not stop.is_set():
                try:
                    counts.append(rc.execute(
                        "SELECT count(*) FROM docs WHERE body @@ 'alpha'"
                    ).scalar())
                except Exception as e:   # pragma: no cover - fails test
                    errors.append(e)
                    return

        rt = threading.Thread(target=reader, daemon=True)
        rt.start()
        wc = db.connect()
        for i in range(1, 41):
            wc.execute(f"INSERT INTO docs VALUES ({i}, 'alpha doc {i}'), "
                       f"({i + 1000}, 'filler {i}')")
        stop.set()
        rt.join(timeout=30)
        assert not errors
        # monotone: a reader can never see a count regress (no partial
        # or torn segment publish)
        assert counts == sorted(counts)
        assert c.execute("SELECT count(*) FROM docs WHERE body @@ 'alpha'"
                         ).scalar() == 41
        rows = wc.execute(
            "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'alpha' "
            "ORDER BY s DESC, id LIMIT 5").rows()
        assert len(rows) == 5


@pytest.mark.parametrize("group_commit", [True, False])
def test_concurrent_inserts_publish_all_rows(group_commit):
    """Coalesced publication (group-commit windows) must lose nothing and
    publish in tick order — every row from every writer lands exactly
    once, with the off pass as the serial-publish oracle."""
    with _globals(serene_group_commit=group_commit):
        db = Database()
        c = db.connect()
        c.execute("CREATE TABLE t (w INT, i INT)")
        errs = []

        def writer(w):
            conn = db.connect()
            try:
                for i in range(25):
                    conn.execute(f"INSERT INTO t VALUES ({w}, {i})")
            except Exception as e:       # pragma: no cover - fails test
                errs.append(e)

        ths = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=60)
        assert not errs
        assert c.execute("SELECT count(*) FROM t").scalar() == 100
        rows = c.execute("SELECT w, count(*) FROM t GROUP BY w "
                         "ORDER BY w").rows()
        assert rows == [(w, 25) for w in range(4)]


@pytest.mark.parametrize("group_commit", [True, False])
def test_wal_recovery_across_group_commit_windows(tmp_path, group_commit):
    """Every commit of every window must replay after a restart: the
    shared-fsync frames are just frames to recovery, and a window's
    boundary can fall anywhere in the writer interleaving."""
    d = str(tmp_path / f"data-{group_commit}")
    from serenedb_tpu.utils import metrics as _m
    with _globals(serene_group_commit=group_commit):
        db = Database(d)
        c = db.connect()
        c.execute("CREATE TABLE t (w INT, i INT)")
        fsyncs0 = _m.REGISTRY.snapshot().get("WalFsyncs", 0)
        errs = []

        def writer(w):
            conn = db.connect()
            try:
                for i in range(15):
                    conn.execute(f"INSERT INTO t VALUES ({w}, {i})")
            except Exception as e:       # pragma: no cover - fails test
                errs.append(e)

        ths = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=60)
        assert not errs
        assert _m.REGISTRY.snapshot().get("WalFsyncs", 0) > fsyncs0
        db.close()

        db2 = Database(d)
        c2 = db2.connect()
        assert c2.execute("SELECT count(*) FROM t").scalar() == 60
        rows = c2.execute("SELECT w, count(*) FROM t GROUP BY w "
                          "ORDER BY w").rows()
        assert rows == [(w, 15) for w in range(4)]
        db2.close()


def test_parquet_parallel_columns_match_serial(tmp_path):
    """Concurrent column building must decode byte-identical columns to
    the serial fallback (PR 1's workaround, revisited)."""
    from serenedb_tpu.columnar.arrow_io import (read_parquet_snapshot,
                                                write_parquet_snapshot)
    from serenedb_tpu.columnar.column import Batch
    rng = np.random.default_rng(11)
    n = 4000
    b = Batch.from_pydict({
        "i": [int(x) if x % 7 else None for x in rng.integers(0, 1e6, n)],
        "f": [float(x) for x in rng.random(n)],
        "s": [None if x % 13 == 0 else f"doc-{x % 97}"
              for x in rng.integers(0, 1e6, n)],
        "b": [bool(x % 2) for x in rng.integers(0, 2, n)],
    })
    p = str(tmp_path / "snap.parquet")
    write_parquet_snapshot(p, b)
    with _globals(serene_parallel_ingest=True, serene_workers=4):
        par = read_parquet_snapshot(p)
    with _globals(serene_parallel_ingest=False):
        ser = read_parquet_snapshot(p)
    assert par.to_pydict() == ser.to_pydict() == b.to_pydict()


PYARROW_DAEMON_SCRIPT = r"""
import sys, threading
sys.path.insert(0, {repo!r})
from serenedb_tpu.columnar.arrow_io import (read_parquet_snapshot,
                                            write_parquet_snapshot)
from serenedb_tpu.columnar.column import Batch
from serenedb_tpu.utils.config import REGISTRY
path = {path!r}
b = Batch.from_pydict({{"s": [f"w {{i % 31}}" for i in range(20000)],
                       "i": list(range(20000))}})
# the original crash recipe: a parquet WRITE on another daemon thread,
# then column work afterwards on the main thread
t = threading.Thread(target=write_parquet_snapshot, args=(path, b),
                     daemon=True)
t.start(); t.join()
REGISTRY.set_global("serene_parallel_ingest", True)
REGISTRY.set_global("serene_workers", 4)
out = read_parquet_snapshot(path)
assert out.to_pydict() == b.to_pydict()
from serenedb_tpu.exec.tables import ParquetTable
pt = ParquetTable(path)
assert pt.full_batch().num_rows == 20000
print("PARQUET-OK")
"""


def test_pyarrow_write_on_daemon_thread_then_parallel_read(tmp_path):
    """The PR 1 segfault scenario, re-driven against the parallel column
    builder: write on a daemon thread, then fan column conversions out
    over OUR pool. pyarrow's internal pool stays dark (file reads remain
    use_threads=False), so the process must exit 0 — a segfault here is
    the regression. Subprocess-isolated so a crash fails one test, not
    the run."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = PYARROW_DAEMON_SCRIPT.format(
        repo=repo, path=str(tmp_path / "t.parquet"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, (p.returncode, p.stdout[-2000:],
                               p.stderr[-2000:])
    assert "PARQUET-OK" in p.stdout


def _segments_of(db, table="docs", col="body"):
    t = db.schemas["main"].tables[table]
    idx = next(iter(t.indexes.values()))
    return t, idx, idx.searchers[col].segments


def test_background_merge_keeps_query_path_delta_only():
    """With background maintenance on, the read-repair leg builds ONLY the
    bounded delta tail (segments may exceed the cap between ticks); one
    maintenance pass then compacts the tier below the cap without changing
    a single result."""
    from serenedb_tpu.storage.maintenance import MaintenanceManager
    with _globals(serene_background_merge=True, serene_max_segments=3):
        db = Database()
        c = db.connect()
        c.execute("CREATE TABLE docs (id INT, body TEXT)")
        c.execute("INSERT INTO docs VALUES (0, 'alpha base')")
        c.execute("CREATE INDEX ON docs USING inverted (body)")
        for i in range(1, 7):
            c.execute(f"INSERT INTO docs VALUES ({i}, 'alpha doc {i}')")
            c.execute("SELECT count(*) FROM docs WHERE body @@ 'alpha'")
        _, _, segs = _segments_of(db)
        assert len(segs) > 3          # queries paid no merge work
        before = c.execute(
            "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'alpha' "
            "ORDER BY s DESC, id").rows()
        mm = MaintenanceManager(db)
        assert mm.run_once() is True   # needs_merge fires the ladder
        _, idx, segs = _segments_of(db)
        assert len(segs) < 3
        after = c.execute(
            "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'alpha' "
            "ORDER BY s DESC, id").rows()
        assert [r[0] for r in after] == [r[0] for r in before]
        np.testing.assert_allclose([r[1] for r in after],
                                   [r[1] for r in before],
                                   rtol=1e-4, atol=1e-5)


def test_foreground_merge_when_background_off():
    """serene_background_merge=off restores the old behavior: the query
    path itself runs the ladder, so readers never see a tier at the cap."""
    with _globals(serene_background_merge=False, serene_max_segments=3):
        db = Database()
        c = db.connect()
        c.execute("CREATE TABLE docs (id INT, body TEXT)")
        c.execute("INSERT INTO docs VALUES (0, 'alpha base')")
        c.execute("CREATE INDEX ON docs USING inverted (body)")
        for i in range(1, 9):
            c.execute(f"INSERT INTO docs VALUES ({i}, 'alpha doc {i}')")
            c.execute("SELECT count(*) FROM docs WHERE body @@ 'alpha'")
        _, _, segs = _segments_of(db)
        assert len(segs) < 3
        assert c.execute("SELECT count(*) FROM docs WHERE body @@ 'alpha'"
                         ).scalar() == 9


def test_full_rebuild_reason_is_logged():
    """The silent full-rebuild cliff is gone: when a mutation forces one,
    the maintenance topic records WHICH trigger (epoch bump vs shrink)."""
    from serenedb_tpu.utils import log
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT)")
    c.execute("INSERT INTO docs VALUES (1, 'alpha'), (2, 'beta')")
    c.execute("CREATE INDEX ON docs USING inverted (body)")
    n0 = len(log.MANAGER.records())
    c.execute("DELETE FROM docs WHERE id = 1")
    assert c.execute("SELECT count(*) FROM docs WHERE body @@ 'beta'"
                     ).scalar() == 1
    msgs = [r.message for r in log.MANAGER.records()[n0:]
            if r.topic == "maintenance"]
    assert any("full index rebuild" in m and "epoch advanced" in m
               for m in msgs), msgs


def test_ingest_metrics_and_stats_surface(tmp_path):
    """Ingest{Docs,Bytes,Batches}, SegmentBuilds and the WalFsync
    histogram move with the write path, and /_stats carries the ingest
    section."""
    from serenedb_tpu.obs.export import prometheus_text, stats_json
    from serenedb_tpu.utils import metrics as _m
    s0 = _m.REGISTRY.snapshot()
    db = Database(str(tmp_path / "data"))
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT)")
    c.execute("INSERT INTO docs VALUES (1, 'alpha one'), (2, 'beta two')")
    c.execute("CREATE INDEX ON docs USING inverted (body)")
    c.execute("INSERT INTO docs VALUES (3, 'alpha three')")
    c.execute("SELECT count(*) FROM docs WHERE body @@ 'alpha'")
    s1 = _m.REGISTRY.snapshot()
    assert s1.get("IngestDocs", 0) - s0.get("IngestDocs", 0) == 3
    assert s1.get("IngestBatches", 0) - s0.get("IngestBatches", 0) == 2
    assert s1.get("IngestBytes", 0) > s0.get("IngestBytes", 0)
    assert s1.get("SegmentBuilds", 0) > s0.get("SegmentBuilds", 0)
    assert s1.get("WalFsyncs", 0) > s0.get("WalFsyncs", 0)
    ingest = stats_json()["ingest"]
    for key in ("docs", "bytes", "batches", "segment_builds",
                "segment_merges", "wal_commits", "wal_fsyncs"):
        assert key in ingest
    assert ingest["wal_fsync"]["count"] > 0
    text = prometheus_text()
    assert "serenedb_ingest_docs" in text
    assert "serenedb_wal_fsync_seconds_bucket" in text
    db.close()


def test_ingest_settings_do_not_key_result_cache():
    """The five ingest knobs are publish-mechanics only — flipping them
    must not fragment the result cache key space (parity asserted by this
    suite's matrix; cache/result.py carries the static assert)."""
    from serenedb_tpu.cache.result import RESULT_AFFECTING_SETTINGS
    for s in ("serene_parallel_ingest", "serene_ingest_chunk_docs",
              "serene_group_commit", "serene_background_merge",
              "serene_max_segments"):
        assert s not in RESULT_AFFECTING_SETTINGS
