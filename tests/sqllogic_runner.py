"""sqllogictest-style golden file runner.

Reference analog: the sqllogictest-rs harness over tests/sqllogic/
(1,642 .test files; SURVEY.md §4) — behavior files are the parity contract.

File format (the common sqllogictest subset):

    statement ok
    CREATE TABLE t (a INT)

    statement error <optional substring>
    SELECT nope

    query <types, e.g. ITR>          # I int, T text, R real (informational)
    SELECT a FROM t ORDER BY a
    ----
    1
    2

Multi-column rows print values separated by a single space (tab in files is
normalized); NULL prints as "NULL"; `rowsort` after the types sorts expected
and actual rows before comparing.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional


@dataclass
class Record:
    kind: str                 # 'statement' | 'query'
    sql: str
    line: int
    expect_error: Optional[str] = None   # None = ok; '' = any error
    expected: Optional[list[str]] = None
    rowsort: bool = False


def parse_test_file(path: str) -> list[Record]:
    with open(path) as f:
        lines = f.read().split("\n")
    records = []
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if not line or line.startswith("#"):
            i += 1
            continue
        header = line.split()
        start_line = i + 1
        if header[0] == "statement":
            expect_error = None
            if len(header) > 1 and header[1] == "error":
                expect_error = " ".join(header[2:])
            elif len(header) > 1 and header[1] != "ok":
                raise ValueError(f"{path}:{i+1}: bad statement header")
            i += 1
            sql_lines = []
            while i < len(lines) and lines[i].strip():
                sql_lines.append(lines[i])
                i += 1
            records.append(Record("statement", "\n".join(sql_lines),
                                  start_line, expect_error))
        elif header[0] == "query":
            rowsort = "rowsort" in header[2:] or \
                (len(header) > 2 and header[2] == "rowsort")
            i += 1
            sql_lines = []
            while i < len(lines) and lines[i].strip() != "----":
                sql_lines.append(lines[i])
                i += 1
            i += 1  # skip ----
            expected = []
            while i < len(lines) and lines[i].strip():
                expected.append(lines[i].rstrip())
                i += 1
            records.append(Record("query", "\n".join(sql_lines),
                                  start_line, None, expected, rowsort))
        else:
            raise ValueError(f"{path}:{i+1}: unknown directive {header[0]}")
    return records


def format_value(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return f"{v:.3f}".rstrip("0").rstrip(".")
    return str(v)


def run_test_file(conn, path: str) -> list[str]:
    """Run one file; returns a list of failure descriptions (empty = pass)."""
    from serenedb_tpu.errors import SqlError
    failures = []
    for rec in parse_test_file(path):
        where = f"{path}:{rec.line}"
        try:
            result = conn.execute(rec.sql)
            if rec.kind == "statement" and rec.expect_error is not None:
                failures.append(f"{where}: expected error, got success")
                continue
            if rec.kind == "query":
                actual = [" ".join(format_value(v) for v in row)
                          for row in result.rows()]
                expected = [e.replace("\t", " ") for e in rec.expected]
                if rec.rowsort:
                    actual = sorted(actual)
                    expected = sorted(expected)
                if actual != expected:
                    failures.append(
                        f"{where}: mismatch\n  expected: {expected}\n"
                        f"  actual:   {actual}")
        except SqlError as e:
            if rec.expect_error is None:
                failures.append(f"{where}: unexpected error: {e.message}")
            elif rec.expect_error and rec.expect_error not in e.message \
                    and rec.expect_error != e.sqlstate:
                failures.append(
                    f"{where}: error mismatch: wanted {rec.expect_error!r} "
                    f"in {e.message!r}")
    return failures
