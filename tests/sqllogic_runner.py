"""sqllogictest-style golden file runner.

Reference analog: the sqllogictest-rs harness over tests/sqllogic/
(1,642 .test files; SURVEY.md §4) — behavior files are the parity contract.

File format (the common sqllogictest subset):

    statement ok
    CREATE TABLE t (a INT)

    statement error <optional substring>
    SELECT nope

    query <types, e.g. ITR>          # I int, T text, R real (informational)
    SELECT a FROM t ORDER BY a
    ----
    1
    2

Multi-column rows print values separated by a single space (tab in files is
normalized); NULL prints as "NULL"; `rowsort` after the types sorts expected
and actual rows before comparing.

Recovery extensions (tests/sqllogic/recovery/, durable runner only —
reference analog: fault-armed crash+restart .test files,
/root/reference/tests/sqllogic/recovery/ 162 files):

    restart            # clean close + reopen of the datadir (checkpoint ok)

    statement crash    # statement must die on an armed crash fault; the
    INSERT ...         # runner then abandons the db (no close/flush) and
                       # reopens from disk — a kill at the fault point
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional


@dataclass
class Record:
    kind: str                 # 'statement' | 'query'
    sql: str
    line: int
    expect_error: Optional[str] = None   # None = ok; '' = any error
    expected: Optional[list[str]] = None
    rowsort: bool = False


def substitute_tmpdir(sql: str, tmpdir: Optional[str]) -> str:
    """Replace the `__TMPDIR__` placeholder in behavior-file SQL with the
    run's scratch directory, so COPY TO/FROM and read_csv/read_parquet
    paths land in per-test tmp instead of whatever the process CWD is
    (historically the repo root, which collected stray artifacts)."""
    if "__TMPDIR__" not in sql:
        return sql
    if tmpdir is None:
        raise ValueError("behavior file uses __TMPDIR__ but the runner "
                         "was not given a tmpdir")
    return sql.replace("__TMPDIR__", str(tmpdir))


def parse_test_file(path: str) -> list[Record]:
    with open(path) as f:
        lines = f.read().split("\n")
    records = []
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if not line or line.startswith("#"):
            i += 1
            continue
        header = line.split()
        start_line = i + 1
        if header[0] == "restart":
            records.append(Record("restart", "", start_line))
            i += 1
            continue
        if header[0] == "connection":
            # multi-connection directive (reference: concurrency corpus):
            # switches the active session; new names open new sessions
            records.append(Record("connection", header[1], start_line))
            i += 1
            continue
        if header[0] == "statement":
            expect_error = None
            if len(header) > 1 and header[1] == "error":
                expect_error = " ".join(header[2:])
            elif len(header) > 1 and header[1] == "crash":
                expect_error = "__crash__"
            elif len(header) > 1 and header[1] != "ok":
                raise ValueError(f"{path}:{i+1}: bad statement header")
            i += 1
            sql_lines = []
            while i < len(lines) and lines[i].strip():
                sql_lines.append(lines[i])
                i += 1
            records.append(Record("statement", "\n".join(sql_lines),
                                  start_line, expect_error))
        elif header[0] == "query":
            rowsort = "rowsort" in header[2:] or \
                (len(header) > 2 and header[2] == "rowsort")
            i += 1
            sql_lines = []
            while i < len(lines) and lines[i].strip() != "----":
                sql_lines.append(lines[i])
                i += 1
            i += 1  # skip ----
            expected = []
            while i < len(lines) and lines[i].strip():
                expected.append(lines[i].rstrip())
                i += 1
            records.append(Record("query", "\n".join(sql_lines),
                                  start_line, None, expected, rowsort))
        else:
            raise ValueError(f"{path}:{i+1}: unknown directive {header[0]}")
    return records


def format_value(v, typ=None) -> str:
    if v is None:
        return "NULL"
    if typ is not None:
        # temporal types render as PG text, not raw epoch ints — the same
        # encoding the wire sends (serenedb_tpu/server/pgwire.py pg_text)
        from serenedb_tpu.columnar import dtypes as dt
        if typ.id is dt.TypeId.TIMESTAMP:
            from serenedb_tpu.sql.binder import format_timestamp
            return format_timestamp(int(v))
        if typ.id is dt.TypeId.DATE:
            import numpy as np
            return str(np.datetime64(int(v), "D"))
        if typ.id is dt.TypeId.INTERVAL:
            from serenedb_tpu.sql.binder import format_interval
            return format_interval(int(v))
        if typ.id is dt.TypeId.ARRAY:
            from serenedb_tpu.server.pgwire import _pg_array_text
            return _pg_array_text(str(v)).decode()
        if typ.id is dt.TypeId.RECORD:
            from serenedb_tpu.columnar.pgcopy import record_text
            return record_text(str(v))
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "Infinity" if v > 0 else "-Infinity"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return f"{v:.3f}".rstrip("0").rstrip(".")
    return str(v)


def compare_query(rec: Record, actual: list[str], where: str,
                  failures: list[str]) -> None:
    """Golden comparison shared by the in-process and wire runners."""
    expected = [e.replace("\t", " ") for e in rec.expected]
    if rec.rowsort:
        actual = sorted(actual)
        expected = sorted(expected)
    if actual != expected:
        failures.append(f"{where}: mismatch\n  expected: {expected}\n"
                        f"  actual:   {actual}")


def run_test_file_wire(execute, path: str,
                       tmpdir: Optional[str] = None) -> list[str]:
    """Run one behavior file over a LIVE pg-wire connection — the parity
    contract crosses the protocol serde it certifies (reference: the
    sqllogictest-rs harness runs every file over 4 wire protocol modes,
    tests/sqllogic/run.sh, CONTRIBUTING.md:57-72).

    `execute(sql) -> (rows, err)`: rows = sqllogic-normalized text values
    per row; err = None or (sqlstate, message). The protocol mode (simple /
    extended text / extended binary) lives inside `execute`. Recovery
    directives are wire-runner failures — those files need process
    orchestration."""
    failures = []
    for rec in parse_test_file(path):
        where = f"{path}:{rec.line}"
        if rec.kind == "restart" or rec.kind == "connection" or \
                rec.expect_error == "__crash__":
            failures.append(f"{where}: recovery/connection directive in "
                            "a wire run")
            break
        rows, err = execute(substitute_tmpdir(rec.sql, tmpdir))
        if rec.kind == "statement":
            if rec.expect_error is None:
                if err is not None:
                    failures.append(
                        f"{where}: unexpected error: {err[1]}")
            elif err is None:
                failures.append(f"{where}: expected error, got success")
            elif rec.expect_error and rec.expect_error not in err[1] \
                    and rec.expect_error != err[0]:
                failures.append(
                    f"{where}: error mismatch: wanted "
                    f"{rec.expect_error!r} in {err[1]!r}")
            continue
        if err is not None:
            failures.append(f"{where}: unexpected error: {err[1]}")
            continue
        compare_query(rec, [" ".join(row) for row in rows], where, failures)
    return failures


def run_test_file(conn, path: str, reopen=None, crash_reopen=None,
                  tmpdir: Optional[str] = None) -> list[str]:
    """Run one file; returns a list of failure descriptions (empty = pass).

    `reopen()` → fresh conn after a clean close (the `restart` directive);
    `crash_reopen()` → fresh conn after abandoning the db without close
    (after a `statement crash`). Recovery directives in a file without the
    matching callback are reported as failures, not silently skipped."""
    from serenedb_tpu.errors import SqlError
    from serenedb_tpu.utils.faults import FaultInjected
    failures = []
    conns = {"default": conn}
    for rec in parse_test_file(path):
        where = f"{path}:{rec.line}"
        if rec.kind == "connection":
            name = rec.sql
            if name not in conns:
                conns[name] = conn.db.connect()
            conn = conns[name]
            continue
        if rec.kind == "restart":
            if reopen is None:
                failures.append(f"{where}: restart in non-durable run")
                break
            conn = reopen()
            conns = {"default": conn}
            continue
        if rec.kind == "statement" and rec.expect_error == "__crash__":
            try:
                conn.execute(substitute_tmpdir(rec.sql, tmpdir))
                failures.append(f"{where}: expected crash, got success")
            except FaultInjected:
                if crash_reopen is None:
                    failures.append(f"{where}: crash in non-durable run")
                    break
                conn = crash_reopen()
            except SqlError as e:
                failures.append(f"{where}: wanted crash fault, got {e!r}")
            continue
        try:
            result = conn.execute(substitute_tmpdir(rec.sql, tmpdir))
            if rec.kind == "statement" and rec.expect_error is not None:
                failures.append(f"{where}: expected error, got success")
                continue
            if rec.kind == "query":
                tys = [c.type for c in result.batch.columns]
                actual = [" ".join(format_value(v, tys[i])
                                   for i, v in enumerate(row))
                          for row in result.rows()]
                compare_query(rec, actual, where, failures)
        except SqlError as e:
            if rec.expect_error is None:
                failures.append(f"{where}: unexpected error: {e.message}")
            elif rec.expect_error and rec.expect_error not in e.message \
                    and rec.expect_error != e.sqlstate:
                failures.append(
                    f"{where}: error mismatch: wanted {rec.expect_error!r} "
                    f"in {e.message!r}")
    return failures
