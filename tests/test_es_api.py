"""ES-compatible HTTP API tests via urllib against a live HttpServer."""

import json
import urllib.error
import urllib.request

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.server.http_server import HttpServer


@pytest.fixture(scope="module")
def srv():
    db = Database()
    s = HttpServer(db, port=0)
    s.start()
    yield s
    s.stop()


def req(srv, method, path, body=None, raw=False):
    data = None
    headers = {}
    if body is not None:
        data = body.encode() if isinstance(body, str) else \
            json.dumps(body).encode()
        headers["Content-Type"] = "application/json" if not raw else \
            "application/x-ndjson"
    r = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}", data=data, headers=headers,
        method=method)
    try:
        with urllib.request.urlopen(r, timeout=30) as resp:
            ct = resp.headers.get("Content-Type", "")
            raw_body = resp.read().decode()
            return resp.status, (json.loads(raw_body)
                                 if "json" in ct else raw_body)
    except urllib.error.HTTPError as e:
        raw_body = e.read().decode()
        try:
            return e.code, json.loads(raw_body)
        except json.JSONDecodeError:
            return e.code, raw_body


def test_root_and_health(srv):
    status, body = req(srv, "GET", "/")
    assert status == 200 and body["tagline"] == "You Know, for Search"
    status, body = req(srv, "GET", "/_cluster/health")
    assert body["status"] == "green"


def test_index_lifecycle_and_docs(srv):
    status, body = req(srv, "PUT", "/books")
    assert status == 200 and body["acknowledged"]
    status, body = req(srv, "PUT", "/books")
    assert status == 400  # already exists
    status, body = req(srv, "PUT", "/books/_doc/1",
                       {"title": "The quick brown fox", "pages": 120})
    assert status == 201 and body["result"] == "created"
    req(srv, "PUT", "/books/_doc/2",
        {"title": "lazy dogs sleeping", "pages": 300})
    req(srv, "POST", "/books/_doc", {"title": "quick reference", "pages": 50})
    status, body = req(srv, "GET", "/books/_doc/1")
    assert status == 200 and body["_source"]["pages"] == 120
    status, body = req(srv, "GET", "/books/_doc/404")
    assert status == 404 and body["found"] is False

    status, body = req(srv, "GET", "/books/_count")
    assert body["count"] == 3

    # match query with scoring
    status, body = req(srv, "POST", "/books/_search",
                       {"query": {"match": {"title": "quick"}}})
    assert status == 200
    hits = body["hits"]["hits"]
    assert body["hits"]["total"]["value"] == 2
    ids = {h["_id"] for h in hits}
    assert "1" in ids and len(ids) == 2   # doc 1 + the auto-id doc
    scores = [h["_score"] for h in hits]
    assert scores == sorted(scores, reverse=True)

    # range + bool
    status, body = req(srv, "POST", "/books/_search", {
        "query": {"bool": {
            "must": [{"match": {"title": "quick"}}],
            "filter": [{"range": {"pages": {"gte": 100}}}]}}})
    assert [h["_id"] for h in body["hits"]["hits"]] == ["1"]

    # match_phrase
    status, body = req(srv, "POST", "/books/_search",
                       {"query": {"match_phrase": {"title": "quick brown"}}})
    assert [h["_id"] for h in body["hits"]["hits"]] == ["1"]

    # delete doc
    status, body = req(srv, "DELETE", "/books/_doc/2")
    assert body["result"] == "deleted"
    status, body = req(srv, "GET", "/books/_count")
    assert body["count"] == 2


def test_bulk_and_cat(srv):
    ndjson = "\n".join([
        json.dumps({"index": {"_index": "logs", "_id": "a"}}),
        json.dumps({"msg": "disk error on node1", "level": "error"}),
        json.dumps({"index": {"_index": "logs", "_id": "b"}}),
        json.dumps({"msg": "all systems normal", "level": "info"}),
        json.dumps({"delete": {"_index": "logs", "_id": "missing"}}),
    ]) + "\n"
    status, body = req(srv, "POST", "/_bulk", ndjson, raw=True)
    assert status == 200
    assert len(body["items"]) == 3
    status, body = req(srv, "GET", "/_cat/indices?format=json")
    names = {r["index"] for r in body}
    assert "logs" in names
    status, body = req(srv, "POST", "/logs/_search",
                       {"query": {"term": {"level": "error"}}})
    assert [h["_id"] for h in body["hits"]["hits"]] == ["a"]


def test_search_sort_and_pagination(srv):
    req(srv, "PUT", "/nums")
    for i in range(5):
        req(srv, "PUT", f"/nums/_doc/{i}", {"v": i})
    status, body = req(srv, "POST", "/nums/_search", {
        "query": {"match_all": {}}, "size": 2, "from": 1,
        "sort": [{"v": {"order": "desc"}}]})
    assert [h["_source"]["v"] for h in body["hits"]["hits"]] == [3, 2]
    assert body["hits"]["total"]["value"] == 5


def test_mapping_reflects_fields(srv):
    req(srv, "PUT", "/m1")
    req(srv, "PUT", "/m1/_doc/1", {"name": "x", "n": 3, "f": 1.5, "b": True})
    status, body = req(srv, "GET", "/m1/_mapping")
    props = body["m1"]["mappings"]["properties"]
    assert props["name"]["type"] == "text"
    assert props["n"]["type"] == "long"
    assert props["f"]["type"] == "double"
    assert props["b"]["type"] == "boolean"


def test_sql_endpoint(srv):
    status, body = req(srv, "POST", "/_sql", {"query": "SELECT 1 + 1 AS two"})
    assert status == 200
    assert body["columns"] == [{"name": "two"}]
    assert body["rows"] == [[2]]


def test_error_shapes(srv):
    status, body = req(srv, "GET", "/missing_index/_search")
    assert status == 404
    assert body["error"]["type"] == "index_not_found_exception"
    status, body = req(srv, "POST", "/_sql", {"query": "SELECT FROM"})
    assert status == 400 and body["error"]["type"] == "sql_exception"


def test_refresh_enables_index_scoring(srv):
    req(srv, "PUT", "/scored")
    # equal doc lengths so tf dominates (BM25 length normalization would
    # otherwise favor the shorter doc)
    req(srv, "PUT", "/scored/_doc/1", {"body": "alpha alpha beta"})
    req(srv, "PUT", "/scored/_doc/2", {"body": "alpha beta gamma"})
    status, body = req(srv, "POST", "/scored/_refresh")
    assert status == 200
    status, body = req(srv, "POST", "/scored/_search",
                       {"query": {"match": {"body": "alpha"}}})
    hits = body["hits"]["hits"]
    assert len(hits) == 2
    assert hits[0]["_id"] == "1"           # higher tf ranks first
    assert hits[0]["_score"] > hits[1]["_score"] > 0


def test_sql_injection_via_sort_and_fields_rejected(srv):
    req(srv, "PUT", "/inj")
    req(srv, "PUT", "/inj/_doc/1", {"v": 1})
    # injection through sort order
    status, body = req(srv, "POST", "/inj/_search", {
        "query": {"match_all": {}},
        "sort": [{"v": "asc; DROP TABLE inj; SELECT 1"}]})
    assert status == 400
    # injection through field names
    status, body = req(srv, "POST", "/inj/_search", {
        "query": {"term": {'v" = 1; DROP TABLE inj; --': 1}}})
    assert status == 400
    # table still there
    status, body = req(srv, "GET", "/inj/_count")
    assert status == 200 and body["count"] == 1


def test_unmatched_routes_respond(srv):
    req(srv, "PUT", "/resp")
    status, _ = req(srv, "POST", "/resp")      # no verb, POST
    assert status == 405
    status, _ = req(srv, "GET", "/resp/_doc")  # _doc without id
    assert status == 405


def test_sql_endpoint_does_not_poison_shared_state(srv):
    req(srv, "PUT", "/iso")
    req(srv, "PUT", "/iso/_doc/1", {"v": 1})
    req(srv, "POST", "/_sql", {"query": "BEGIN"})
    req(srv, "POST", "/_sql", {"query": "SELECT broken FROM nowhere"})
    status, body = req(srv, "GET", "/iso/_count")
    assert status == 200 and body["count"] == 1


def test_bulk_partial_failure_reports_per_item(srv):
    req(srv, "PUT", "/pb")
    req(srv, "PUT", "/pb/_doc/1", {"n": 5})
    ndjson = "\n".join([
        json.dumps({"index": {"_index": "pb", "_id": "2"}}),
        json.dumps({"n": 7}),
        json.dumps({"index": {"_index": "DROP TABLE pb", "_id": "3"}}),
        json.dumps({"n": 9}),
    ]) + "\n"
    status, body = req(srv, "POST", "/_bulk", ndjson, raw=True)
    assert status == 200
    assert body["errors"] is True
    assert body["items"][0]["index"]["status"] == 201
    assert body["items"][1]["index"]["status"] == 400


def test_scroll_pagination(srv):
    req(srv, "PUT", "/scr")
    for i in range(7):
        req(srv, "PUT", f"/scr/_doc/{i}", {"n": i})
    status, body = req(srv, "POST", "/scr/_search?scroll=1m",
                       {"query": {"match_all": {}}, "size": 3,
                        "sort": [{"n": "asc"}]})
    assert status == 200
    sid = body["_scroll_id"]
    assert [h["_source"]["n"] for h in body["hits"]["hits"]] == [0, 1, 2]
    status, body = req(srv, "POST", "/_search/scroll",
                       {"scroll_id": sid, "size": 3})
    assert [h["_source"]["n"] for h in body["hits"]["hits"]] == [3, 4, 5]
    status, body = req(srv, "POST", "/_search/scroll",
                       {"scroll_id": sid, "size": 3})
    assert [h["_source"]["n"] for h in body["hits"]["hits"]] == [6]
    status, body = req(srv, "DELETE", "/_search/scroll",
                       {"scroll_id": sid})
    assert body["succeeded"] is True
    status, body = req(srv, "POST", "/_search/scroll", {"scroll_id": sid})
    assert status == 404


def test_mget_and_stats(srv):
    req(srv, "PUT", "/mg")
    req(srv, "PUT", "/mg/_doc/a", {"v": 1})
    req(srv, "PUT", "/mg/_doc/b", {"v": 2})
    status, body = req(srv, "POST", "/mg/_mget", {"ids": ["a", "b", "zz"]})
    assert [d["found"] for d in body["docs"]] == [True, True, False]
    status, body = req(srv, "GET", "/mg/_stats")
    assert body["indices"]["mg"]["primaries"]["docs"]["count"] == 2


def test_scroll_covers_all_hits_and_keeps_size(srv):
    req(srv, "PUT", "/deep")
    ndjson = "\n".join(
        json.dumps({"index": {"_index": "deep", "_id": str(i)}}) + "\n" +
        json.dumps({"n": i}) for i in range(25)) + "\n"
    req(srv, "POST", "/_bulk", ndjson, raw=True)
    status, body = req(srv, "POST", "/deep/_search?scroll=30s",
                       {"size": 7, "sort": [{"n": "asc"}],
                        "query": {"match_all": {}}})
    sid = body["_scroll_id"]
    seen = [h["_source"]["n"] for h in body["hits"]["hits"]]
    assert len(seen) == 7
    while True:
        status, body = req(srv, "POST", "/_search/scroll",
                           {"scroll_id": sid})  # no size: reuse initial 7
        page = [h["_source"]["n"] for h in body["hits"]["hits"]]
        if not page:
            break
        assert len(page) <= 7
        seen += page
    assert seen == list(range(25))   # every hit reached, in order


def test_scroll_expiry():
    from serenedb_tpu.server.es_api import EsApi
    from serenedb_tpu.engine import Database
    api = EsApi(Database())
    api.index_doc("exp", {"n": 1}, "1")
    res = api.search_scroll_start("exp", {"size": 1}, "1ms")
    import time
    time.sleep(0.01)
    import pytest as _pytest
    from serenedb_tpu.server.es_api import EsError
    with _pytest.raises(EsError):
        api.search_scroll_next(res["_scroll_id"])


def test_mget_standard_docs_shape_and_errors(srv):
    req(srv, "PUT", "/mgs")
    req(srv, "PUT", "/mgs/_doc/x", {"v": 1})
    # per-doc _index (standard ES shape) at the top-level endpoint
    status, body = req(srv, "POST", "/_mget",
                       {"docs": [{"_index": "mgs", "_id": "x"},
                                 {"_index": "mgs", "_id": "nope"}]})
    assert status == 200
    assert [d["found"] for d in body["docs"]] == [True, False]
    # malformed doc entry → 400, not a phantom id
    status, body = req(srv, "POST", "/mgs/_mget", {"docs": [{"_idd": "x"}]})
    assert status == 400
    # stats on a missing index → 404
    status, body = req(srv, "GET", "/no_such/_stats")
    assert status == 404


def test_scroll_delete_list_form_and_refresh(srv):
    req(srv, "PUT", "/scr2")
    for i in range(4):
        req(srv, "PUT", f"/scr2/_doc/{i}", {"n": i})
    status, body = req(srv, "POST", "/scr2/_search?scroll=30s",
                       {"size": 2, "sort": [{"n": "asc"}]})
    sid = body["_scroll_id"]
    # continuation with the standard body shape refreshes keepalive
    status, body = req(srv, "POST", "/_search/scroll",
                       {"scroll": "30s", "scroll_id": sid})
    assert [h["_source"]["n"] for h in body["hits"]["hits"]] == [2, 3]
    # ES list form of delete
    status, body = req(srv, "DELETE", "/_search/scroll",
                       {"scroll_id": [sid]})
    assert body["succeeded"] is True and body["num_freed"] == 1


def test_msearch(srv):
    for i, txt in enumerate(["quick brown fox", "lazy dog", "quick wit"]):
        req(srv, "PUT", f"/ms/_doc/{i}", {"body": txt})
    nd = "\n".join([
        json.dumps({"index": "ms"}),
        json.dumps({"query": {"match": {"body": "quick"}}}),
        json.dumps({}),
        json.dumps({"query": {"match": {"body": "dog"}}, "size": 1}),
        json.dumps({"index": "nope"}),
        json.dumps({"query": {"match_all": {}}}),
    ]) + "\n"
    status, body = req(srv, "POST", "/ms/_msearch", nd, raw=True)
    assert status == 200
    rs = body["responses"]
    assert len(rs) == 3
    assert rs[0]["status"] == 200
    assert rs[0]["hits"]["total"]["value"] == 2
    assert rs[1]["hits"]["total"]["value"] == 1
    assert len(rs[1]["hits"]["hits"]) == 1
    # bad index fails only its own item
    assert rs[2]["status"] == 404 and "error" in rs[2]

    # top-level _msearch requires index per item
    nd = json.dumps({}) + "\n" + json.dumps({"query": {"match_all": {}}}) \
        + "\n"
    status, body = req(srv, "POST", "/_msearch", nd, raw=True)
    assert status == 200
    assert body["responses"][0]["status"] == 400

    # odd line count is a request-level error
    status, body = req(srv, "POST", "/_msearch",
                       json.dumps({"index": "ms"}) + "\n", raw=True)
    assert status == 400


def test_cat_health_and_count(srv):
    status, body = req(srv, "GET", "/_cat/health?format=json")
    assert status == 200 and body[0]["status"] == "green"
    status, body = req(srv, "GET", "/_cat/count/ms?format=json")
    assert status == 200 and body[0]["count"] == "3"
    status, body = req(srv, "GET", "/_cat/count?format=json")
    assert status == 200 and int(body[0]["count"]) >= 3
    status, body = req(srv, "GET", "/_cat/count/doesnotexist?format=json")
    assert status == 404
    status, body = req(srv, "GET", "/_cat/health")
    assert status == 200 and "green" in body
    status, body = req(srv, "GET", "/_cat/nosuch")
    assert status == 400


def test_msearch_empty_header_line(srv):
    # ES allows a blank header line meaning "defaults" — pairing must hold
    nd = "\n" + json.dumps({"query": {"match": {"body": "quick"}}}) + "\n"
    status, body = req(srv, "POST", "/ms/_msearch", nd, raw=True)
    assert status == 200
    assert body["responses"][0]["hits"]["total"]["value"] == 2
    # blank header item mixed with an explicit-index item
    nd = "\n" + json.dumps({"query": {"match_all": {}}}) + "\n" + \
        json.dumps({"index": "ms"}) + "\n" + \
        json.dumps({"query": {"match": {"body": "dog"}}}) + "\n"
    status, body = req(srv, "POST", "/ms/_msearch", nd, raw=True)
    rs = body["responses"]
    assert rs[0]["hits"]["total"]["value"] == 3
    assert rs[1]["hits"]["total"]["value"] == 1
    # blank BODY line is a per-item parse error, not mis-pairing
    nd = json.dumps({"index": "ms"}) + "\n\n"
    status, body = req(srv, "POST", "/ms/_msearch", nd + nd, raw=True)
    assert status == 200
    assert all(r["status"] == 400 for r in body["responses"])


def test_cat_indices_text_four_columns(srv):
    status, body = req(srv, "GET", "/_cat/indices")
    assert status == 200
    line = next(ln for ln in body.splitlines() if " ms " in f" {ln} ")
    assert line.split() == ["green", "open", "ms", "3"]


def test_analyze(srv):
    status, body = req(srv, "POST", "/_analyze",
                       {"analyzer": "standard", "text": "Quick-Brown Foxes"})
    assert status == 200
    toks = [t["token"] for t in body["tokens"]]
    assert toks == ["quick", "brown", "foxes"]
    assert body["tokens"][0]["start_offset"] == 0
    # stemming analyzer
    status, body = req(srv, "POST", "/_analyze",
                       {"analyzer": "text", "text": "running dogs"})
    assert [t["token"] for t in body["tokens"]] == ["run", "dog"]
    # unknown analyzer
    status, body = req(srv, "POST", "/_analyze",
                       {"analyzer": "nope", "text": "x"})
    assert status == 400
    # empty body → no tokens
    status, body = req(srv, "POST", "/_analyze", {})
    assert status == 200 and body["tokens"] == []


def test_analyze_index_scoped(srv):
    req(srv, "PUT", "/anz")
    req(srv, "PUT", "/anz/_doc/1", {"body": "running dogs"})
    # index-scoped without explicit analyzer uses the index's analyzer
    # (inverted default "text": stemming) — the terms the index stores
    status, body = req(srv, "POST", "/anz/_analyze",
                       {"text": "running dogs"})
    assert status == 200
    assert [t["token"] for t in body["tokens"]] == ["run", "dog"]
    # field routing
    status, body = req(srv, "POST", "/anz/_analyze",
                       {"field": "body", "text": "running"})
    assert [t["token"] for t in body["tokens"]] == ["run"]
    # explicit analyzer wins
    status, body = req(srv, "POST", "/anz/_analyze",
                       {"analyzer": "keyword", "text": "One Two"})
    assert [t["token"] for t in body["tokens"]] == ["One Two"]
    # unknown index 404s
    status, body = req(srv, "POST", "/ghost_idx/_analyze", {"text": "x"})
    assert status == 404
    # non-object body is a 400, not a 500
    status, body = req(srv, "POST", "/_analyze", '"hello"')
    assert status == 400


def test_update_doc(srv):
    req(srv, "PUT", "/upd")
    req(srv, "PUT", "/upd/_doc/1", {"title": "old", "count": 1})
    # partial merge
    status, body = req(srv, "POST", "/upd/_update/1",
                       {"doc": {"title": "new"}})
    assert status == 200 and body["result"] == "updated"
    status, body = req(srv, "GET", "/upd/_doc/1")
    assert body["_source"] == {"title": "new", "count": 1}
    # noop when nothing changes
    status, body = req(srv, "POST", "/upd/_update/1",
                       {"doc": {"title": "new"}})
    assert body["result"] == "noop"
    # missing doc without upsert -> 404
    status, body = req(srv, "POST", "/upd/_update/ghost",
                       {"doc": {"x": 1}})
    assert status == 404
    # upsert creates
    status, body = req(srv, "POST", "/upd/_update/2",
                       {"doc": {"x": 1}, "upsert": {"title": "fresh"}})
    assert body["result"] == "created"
    status, body = req(srv, "GET", "/upd/_doc/2")
    assert body["_source"] == {"title": "fresh"}
    # doc_as_upsert
    status, body = req(srv, "POST", "/upd/_update/3",
                       {"doc": {"v": 7}, "doc_as_upsert": True})
    assert body["result"] == "created"
    status, body = req(srv, "GET", "/upd/_doc/3")
    assert body["_source"] == {"v": 7}
    # malformed body
    status, body = req(srv, "POST", "/upd/_update/1", {})
    assert status == 400


def test_concurrent_updates_lose_no_fields(srv):
    import threading as _t
    req(srv, "PUT", "/cu")
    req(srv, "PUT", "/cu/_doc/1", {"base": 0})
    errs = []

    def worker(field):
        for i in range(10):
            st, body = req(srv, "POST", "/cu/_update/1",
                           {"doc": {field: i}})
            if st != 200:
                errs.append(body)

    ts = [_t.Thread(target=worker, args=(f"f{k}",)) for k in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errs
    st, body = req(srv, "GET", "/cu/_doc/1")
    src = body["_source"]
    # every thread's final write must survive (atomic read-merge-write)
    assert src["f0"] == 9 and src["f1"] == 9 and src["f2"] == 9
    assert src["base"] == 0


def test_update_empty_upsert_and_bulk_parity(srv):
    # {} upsert is legal and indexes an empty doc
    st, body = req(srv, "POST", "/eu/_update/1", {"upsert": {}})
    assert st == 200 and body["result"] == "created"
    # bulk update now shares update_doc semantics: missing doc -> error item
    nd = "\n".join([
        json.dumps({"update": {"_index": "eu", "_id": "ghost"}}),
        json.dumps({"doc": {"x": 1}}),
    ]) + "\n"
    st, body = req(srv, "POST", "/_bulk", nd, raw=True)
    assert body["errors"] is True
    assert body["items"][0]["update"]["status"] == 404
    # non-dict doc -> 400, not 500
    st, body = req(srv, "POST", "/eu/_update/1", {"doc": [1, 2]})
    assert st == 400


def test_delete_by_query(srv):
    req(srv, "PUT", "/dbq")
    for i, lvl in enumerate(["err", "err", "ok"]):
        req(srv, "PUT", f"/dbq/_doc/{i}", {"level": lvl})
    st, body = req(srv, "POST", "/dbq/_delete_by_query",
                   {"query": {"term": {"level": "err"}}})
    assert st == 200 and body["deleted"] == 2
    st, body = req(srv, "GET", "/dbq/_count")
    assert body["count"] == 1
    # match_all wipes the rest
    st, body = req(srv, "POST", "/dbq/_delete_by_query",
                   {"query": {"match_all": {}}})
    assert body["deleted"] == 1
    # missing query -> 400; unknown index -> 404
    st, _ = req(srv, "POST", "/dbq/_delete_by_query", {})
    assert st == 400
    st, _ = req(srv, "POST", "/ghostdbq/_delete_by_query",
                {"query": {"match_all": {}}})
    assert st == 404


def test_delete_by_query_max_docs_and_bad_body(srv):
    req(srv, "PUT", "/dbm")
    for i in range(4):
        req(srv, "PUT", f"/dbm/_doc/{i}", {"x": 1})
    st, body = req(srv, "POST", "/dbm/_delete_by_query",
                   {"query": {"match_all": {}}, "max_docs": 2})
    assert st == 200 and body["deleted"] == 2
    st, body = req(srv, "GET", "/dbm/_count")
    assert body["count"] == 2
    st, _ = req(srv, "POST", "/dbm/_delete_by_query", "[1, 2]")
    assert st == 400
