"""Native C++ indexer: exact parity with the Python builder + speed."""

import time

import numpy as np
import pytest

from serenedb_tpu.native import build_field_index_native, load
from serenedb_tpu.search.analysis import get_analyzer
from serenedb_tpu.search.segment import build_field_index


@pytest.fixture(scope="module")
def native_available():
    if load() is None:
        pytest.skip("native toolchain unavailable")


def make_docs(n=500, seed=9):
    rng = np.random.default_rng(seed)
    words = [f"word{i}" for i in range(300)] + ["The", "Quick", "FOX_7"]
    docs = []
    for i in range(n):
        docs.append(" ".join(rng.choice(words, rng.integers(3, 40))) +
                    (".,;! punct-uation" if i % 7 == 0 else ""))
    docs[3] = None
    docs[4] = ""
    return docs


def test_native_matches_python_builder(native_available):
    docs = make_docs()
    an = get_analyzer("simple")
    # python reference build (bypass the native fast path with a copy class)
    py = _python_build(docs, an)
    nat = build_field_index_native(docs)
    assert nat is not None
    assert list(nat.terms) == list(py.terms)
    np.testing.assert_array_equal(nat.doc_freq, py.doc_freq)
    np.testing.assert_array_equal(nat.offsets, py.offsets)
    np.testing.assert_array_equal(nat.post_docs, py.post_docs)
    np.testing.assert_array_equal(nat.post_tfs, py.post_tfs)
    np.testing.assert_array_equal(nat.pos_offsets, py.pos_offsets)
    np.testing.assert_array_equal(nat.positions, py.positions)
    np.testing.assert_array_equal(nat.norms, py.norms)
    assert nat.total_tokens == py.total_tokens


def _python_build(docs, an):
    """Invoke the pure-Python path by disguising the analyzer name."""

    class _NotSimple(type(an)):
        name = "simple-py"
    a2 = _NotSimple()
    return build_field_index(docs, a2)


def test_build_field_index_uses_native_for_ascii(native_available):
    docs = ["hello world hello", "quick brown fox"]
    an = get_analyzer("simple")
    fi = build_field_index(docs, an)
    assert fi.term_id("hello") >= 0
    assert fi.block_offsets[-1] == len(fi.block_max_tf)
    # non-ascii falls back to python, whose simple analyzer accent-folds
    # (héllo → hello) — exactly the divergence the ASCII gate protects
    fi2 = build_field_index(["héllo wörld"], an)
    assert fi2.term_id("hello") >= 0
    assert fi2.term_id("world") >= 0


def test_native_speedup(native_available):
    docs = make_docs(n=3000)
    an = get_analyzer("simple")
    t0 = time.perf_counter()
    build_field_index_native(docs)
    t_nat = time.perf_counter() - t0
    t0 = time.perf_counter()
    _python_build(docs, an)
    t_py = time.perf_counter() - t0
    assert t_nat < t_py, (t_nat, t_py)  # native must actually be faster


def _assert_fi_equal(a, b):
    assert list(a.terms) == list(b.terms)
    np.testing.assert_array_equal(a.doc_freq, b.doc_freq)
    np.testing.assert_array_equal(a.offsets, b.offsets)
    np.testing.assert_array_equal(a.post_docs, b.post_docs)
    np.testing.assert_array_equal(a.post_tfs, b.post_tfs)
    np.testing.assert_array_equal(a.pos_offsets, b.pos_offsets)
    np.testing.assert_array_equal(a.positions, b.positions)
    np.testing.assert_array_equal(a.norms, b.norms)
    assert a.total_tokens == b.total_tokens


def test_parallel_build_matches_single_thread(native_available):
    """ParallelSink analog: sharded multithreaded build must be byte-
    identical to the 1-thread build (contiguous shards, in-order merge)."""
    docs = make_docs(n=2000, seed=11)
    one = build_field_index_native(docs, n_threads=1)
    for nt in (2, 3, 4, 7):
        mt = build_field_index_native(docs, n_threads=nt)
        _assert_fi_equal(one, mt)


def test_parallel_build_more_threads_than_docs(native_available):
    docs = ["alpha beta", None, "beta gamma"]
    one = build_field_index_native(docs, n_threads=1)
    mt = build_field_index_native(docs, n_threads=16)
    _assert_fi_equal(one, mt)


def test_parallel_build_empty_and_null_heavy(native_available):
    docs = [None, "", None, "", "x"] * 50
    one = build_field_index_native(docs, n_threads=1)
    mt = build_field_index_native(docs, n_threads=5)
    _assert_fi_equal(one, mt)


def test_ingest_threads_env(monkeypatch):
    from serenedb_tpu.native import ingest_threads
    monkeypatch.setenv("SDB_INGEST_THREADS", "3")
    assert ingest_threads() == 3
    monkeypatch.setenv("SDB_INGEST_THREADS", "bogus")
    assert ingest_threads() >= 1
    monkeypatch.delenv("SDB_INGEST_THREADS")
    assert ingest_threads() >= 1
