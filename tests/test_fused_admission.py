"""PR 17: extended fused-tier admission + whole-query device residency.

Contract under test: every admission shape the fused pipeline gained —
FILTER / DISTINCT aggregates, string min/max over dictionary codes,
LEFT/RIGHT/FULL outer joins, residual join predicates, and the chained
agg→top-N handoff — is BIT-IDENTICAL to the host oracle
(`serene_device_fused = off`) across the full execution matrix
(workers 1/4 × shards 1/4 × zonemap on/off), and the machinery around
it holds:

- compile hygiene: varying row counts land in pow2 buckets, so the
  per-family compile counts stay bounded and `DeviceRecompileStorms`
  stays quiet;
- whole-query residency: a warm chained repeat moves ZERO host→device
  transfers (the stage-1 accumulators hand off to the top-N program
  inside HBM);
- decline observability: EXPLAIN ANALYZE's `Device:` line carries
  `declined=<reason>` and the per-reason counters accumulate;
- budget trade (`serene_device_cache_trade`): posting-pool residency
  squeezes the column cache's cap inside the one
  `serene_device_cache_mb` envelope, floored at a quarter of it.
"""

import pytest

from serenedb_tpu.obs import device as obs_device
from serenedb_tpu.utils import metrics
from serenedb_tpu.utils.config import REGISTRY as SETTINGS
from tests.test_device_pipeline import _mk_conn, _rows

# every NEW admission family; the host path is the oracle for each
NEW_SHAPES = [
    # FILTER aggregates (TRUE-only semantics; NULL predicate drops)
    "SELECT l.sk, count(*) FILTER (WHERE v > 0), sum(w) "
    "FROM l JOIN r ON l.ik = r.ik GROUP BY l.sk ORDER BY l.sk",
    "SELECT count(*) FILTER (WHERE w > 250), "
    "sum(v) FILTER (WHERE v < 0) FROM l JOIN r ON l.ik = r.ik",
    "SELECT l.ik, count(w) FILTER (WHERE w > 0), min(w) FILTER "
    "(WHERE w < 100) FROM l JOIN r ON l.ik = r.ik "
    "GROUP BY l.ik ORDER BY l.ik NULLS LAST",
    # DISTINCT aggregates (probe-side presence grids)
    "SELECT l.sk, count(DISTINCT l.ik) FROM l JOIN r ON l.ik = r.ik "
    "GROUP BY l.sk ORDER BY l.sk",
    "SELECT count(DISTINCT l.sk), sum(DISTINCT l.v) "
    "FROM l JOIN r ON l.ik = r.ik WHERE v > 400",
    "SELECT l.ik, count(DISTINCT l.sk), avg(DISTINCT l.v), count(*) "
    "FROM l JOIN r ON l.ik = r.ik GROUP BY l.ik ORDER BY l.ik NULLS LAST",
    # string min/max over sorted-dictionary codes
    "SELECT l.ik, min(l.sk), max(r.sk) FROM l JOIN r ON l.ik = r.ik "
    "GROUP BY l.ik ORDER BY l.ik NULLS LAST",
    "SELECT min(r.sk), max(r.sk), count(*) FROM l JOIN r ON l.sk = r.sk "
    "WHERE v > 450",
    # residual join predicates (extra ON conjuncts beyond the equi-key)
    "SELECT l.sk, count(*), sum(w) FROM l JOIN r "
    "ON l.ik = r.ik AND l.v < r.w GROUP BY l.sk ORDER BY l.sk",
    "SELECT count(*), sum(v) FROM l JOIN r "
    "ON l.ik = r.ik AND r.w > 0 AND l.v > -400",
    # outer joins (NULL-extended rows land in the all-NULL key group)
    "SELECT l.sk, count(*), count(w), sum(w) FROM l LEFT JOIN r "
    "ON l.ik = r.ik GROUP BY l.sk ORDER BY l.sk",
    "SELECT r.sk, count(*), sum(l.v) FROM l RIGHT JOIN r "
    "ON l.ik = r.ik GROUP BY r.sk ORDER BY r.sk",
    "SELECT l.sk, count(*), min(w), max(w) FROM l FULL JOIN r "
    "ON l.ik = r.ik GROUP BY l.sk ORDER BY l.sk",
    "SELECT count(*), count(l.v), count(r.w), sum(l.bv) "
    "FROM l FULL JOIN r ON l.sk = r.sk",
    # combinations across the new families
    "SELECT l.sk, count(DISTINCT l.ik), min(r.sk), "
    "count(*) FILTER (WHERE w > 0) FROM l LEFT JOIN r ON l.ik = r.ik "
    "GROUP BY l.sk ORDER BY l.sk",
]

CHAINED_SHAPES = [
    "SELECT l.ik, count(*) AS n FROM l JOIN r ON l.ik = r.ik "
    "GROUP BY l.ik ORDER BY n DESC LIMIT 5",
    "SELECT l.sk, count(*), sum(w) FROM l JOIN r ON l.ik = r.ik "
    "GROUP BY l.sk ORDER BY l.sk LIMIT 3",
    "SELECT l.ik, count(w) AS c FROM l LEFT JOIN r ON l.ik = r.ik "
    "GROUP BY l.ik ORDER BY c LIMIT 4 OFFSET 2",
    "SELECT count(*) AS n, l.sk FROM l JOIN r ON l.ik = r.ik "
    "GROUP BY l.sk ORDER BY l.sk DESC LIMIT 2",
]


@pytest.mark.parametrize("q", NEW_SHAPES + CHAINED_SHAPES)
def test_new_shape_parity_matrix(q):
    """workers 1/4 × shards 1/4 × zonemap on/off, oracle = fused off."""
    c = _mk_conn()
    c.execute("SET serene_device_fused = off")
    c.execute("SET serene_workers = 1")
    oracle = _rows(c, q)
    c.execute("SET serene_device_fused = on")
    for workers in (1, 4):
        c.execute(f"SET serene_workers = {workers}")
        for shards in (1, 4):
            c.execute(f"SET serene_shards = {shards}")
            for zm in ("on", "off"):
                c.execute(f"SET serene_zonemap = {zm}")
                got = _rows(c, q)
                assert got == oracle, (
                    f"diverged (workers={workers}, shards={shards}, "
                    f"zonemap={zm}): {q}")


def test_ext_off_restores_walls():
    """`serene_device_fused_ext = off` is the PR-7 oracle switch: the
    new shapes still answer (host fallback) and stay bit-identical."""
    c = _mk_conn()
    c.execute("SET serene_device_fused_ext = off")
    for q in NEW_SHAPES[:4]:
        on = _rows(c, q)
        c.execute("SET serene_device_fused = off")
        assert _rows(c, q) == on
        c.execute("SET serene_device_fused = on")


# -- compile hygiene ---------------------------------------------------------


def _family(name: str) -> dict:
    for p in obs_device.stats_section()["programs"]:
        if p["family"] == name:
            return p
    return {"compiles": 0, "storms": 0}


def test_row_count_churn_stays_in_pow2_buckets():
    """The same query over 6 different table sizes inside one pow2
    bucket pair must reuse ONE fused executable; crossing a bucket
    boundary may add one more — never one per size. Storms stay 0."""
    q = ("SELECT l.sk, count(*), count(DISTINCT l.ik) FROM l "
         "JOIN r ON l.ik = r.ik GROUP BY l.sk ORDER BY l.sk")
    storms0 = metrics.DEVICE_RECOMPILE_STORMS.value
    fam0 = _family("fused")["storms"]
    c0 = _family("fused")["compiles"]
    buckets = set()
    for nl, nr in ((4100, 2100), (4600, 2300), (5200, 2700),
                   (6000, 3000), (7100, 3500), (8100, 3900)):
        c = _mk_conn(nl=nl, nr=nr)
        got = _rows(c, q)
        c.execute("SET serene_device_fused = off")
        assert got == _rows(c, q), f"diverged at nl={nl}"
        from serenedb_tpu.exec.device_pipeline import _pow2_rows
        buckets.add((_pow2_rows(nl), _pow2_rows(nr)))
    compiled = _family("fused")["compiles"] - c0
    assert compiled <= len(buckets), (
        f"{compiled} fused compiles across 6 sizes in {len(buckets)} "
        f"pow2 buckets — bucketing failed")
    # deltas, not absolutes: earlier tests in the process legitimately
    # compile many DISTINCT query shapes in under a minute (the detector
    # fires on those by design); row-count churn must add none
    assert metrics.DEVICE_RECOMPILE_STORMS.value == storms0
    assert _family("fused")["storms"] == fam0


# -- whole-query residency ---------------------------------------------------


def _require_ext():
    """verify_tier1 pass 16 leg (b) forces the PR-7 walls back
    globally; the chained-device assertions are vacuous there."""
    if not SETTINGS.get_global("serene_device_fused_ext"):
        pytest.skip("serene_device_fused_ext forced off for this pass")


def test_chained_warm_repeat_zero_uploads():
    """After the cold run uploads the columns, a chained agg→top-N
    repeat is fully device-resident: zero host→device transfers, both
    program families warm, and the chained-stage gauge advances."""
    _require_ext()
    c = _mk_conn()
    q = ("SELECT l.ik, count(*) AS n FROM l JOIN r ON l.ik = r.ik "
         "GROUP BY l.ik ORDER BY n DESC LIMIT 5")
    chain0 = metrics.REGISTRY.gauge("DeviceChainedStages").value
    cold = _rows(c, q)
    assert metrics.REGISTRY.gauge("DeviceChainedStages").value > chain0, \
        "chained device path did not fire"
    ups0 = metrics.DEVICE_TRANSFERS_UP.value
    assert _rows(c, q) == cold
    assert metrics.DEVICE_TRANSFERS_UP.value == ups0, \
        "warm chained repeat moved host→device bytes"


def test_chained_declines_unsupported_sort_key():
    """min/max/sum sort keys have no NULL-consistent device order: the
    chain declines (reason recorded), the host answers, results match."""
    _require_ext()
    c = _mk_conn()
    q = ("SELECT l.ik, min(w) AS m FROM l JOIN r ON l.ik = r.ik "
         "GROUP BY l.ik ORDER BY m LIMIT 4")
    before = obs_device.fused_declines().get("chain_sort_key", 0)
    on = _rows(c, q)
    assert obs_device.fused_declines().get("chain_sort_key", 0) > before
    c.execute("SET serene_device_fused = off")
    assert _rows(c, q) == on


# -- decline observability ---------------------------------------------------


def test_explain_analyze_declined_reason():
    c = _mk_conn()
    # float aggregate argument: exactness wall → agg_type decline
    q = ("EXPLAIN ANALYZE SELECT l.sk, sum(l.fk) FROM l "
         "JOIN r ON l.ik = r.ik GROUP BY l.sk ORDER BY l.sk")
    before = obs_device.fused_declines().get("agg_type", 0)
    lines = [r[0] for r in c.execute(q).rows()]
    assert any("declined=agg_type" in ln for ln in lines), lines
    assert obs_device.fused_declines().get("agg_type", 0) > before
    # the per-reason counters surface in the device stats section
    assert obs_device.stats_section()["fused_declines"]["agg_type"] > 0


# -- budget trade ------------------------------------------------------------


def test_cache_cap_trades_against_pool_residency():
    from serenedb_tpu.exec.device_pipeline import DEVICE_CACHE
    from serenedb_tpu.search.posting_pool import POOL

    env = int(SETTINGS.get_global("serene_device_cache_mb")) << 20
    old_trade = SETTINGS.get_global("serene_device_cache_trade")
    try:
        SETTINGS.set_global("serene_device_cache_trade", True)
        live = POOL.live_bytes()
        cap = DEVICE_CACHE.stats()["cap_bytes"]
        assert cap == max(env // 4, env - live)
        SETTINGS.set_global("serene_device_cache_trade", False)
        assert DEVICE_CACHE.stats()["cap_bytes"] == env
    finally:
        SETTINGS.set_global("serene_device_cache_trade", old_trade)


def test_pool_sheds_colder_tail():
    """shed_colder frees LRU pages idle longer than the threshold and
    stops at the first warmer entry — the column cache's cross-eviction
    primitive."""
    from serenedb_tpu.search.posting_pool import PAGE, POOL, _Entry

    POOL.clear()
    with POOL._lock:
        POOL._region()
        # hand-plant two entries: a cold tail and a hot head
        slots_a = POOL._alloc(2, set())
        slots_b = POOL._alloc(1, set())
        ea = _Entry(("t", 1), slots_a, 2 * PAGE, 1, None)
        eb = _Entry(("t", 2), slots_b, PAGE, 2, None)
        import time as _t
        ea.last_ns = _t.perf_counter_ns() - int(60e9)   # idle 60 s
        POOL._entries[ea.key] = ea
        POOL._entries[eb.key] = eb
    assert POOL.live_bytes() == 3 * PAGE * 8
    # threshold 30 s: only the 60 s-idle tail qualifies
    freed = POOL.shed_colder(int(30e9), 10 * PAGE * 8)
    assert freed == 2 * PAGE * 8
    assert POOL.live_bytes() == PAGE * 8
    # the warm survivor blocks further shedding
    assert POOL.shed_colder(int(30e9), PAGE * 8) == 0
    POOL.clear()
