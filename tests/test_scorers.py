"""LM-family scorers: LM-Dirichlet, Jelinek-Mercer, DFI.

Reference parity surface: libs/iresearch/search/lm_dirichlet.cpp,
jelinek_mercer smoothing, dfi.cpp. Checks hand-computed formulas against
the device kernel, CPU/device consistency, multi-segment global stats,
and the SQL ORDER BY scorer pushdown."""

import math

import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.ops import bm25 as bm25_ops
from serenedb_tpu.search.analysis import get_analyzer
from serenedb_tpu.search.query import parse_query
from serenedb_tpu.search.searcher import SegmentSearcher
from serenedb_tpu.search.segment import build_field_index

DOCS = [
    "apple banana apple cherry",        # 0: tf(apple)=2, dl=4
    "apple banana",                     # 1: tf(apple)=1, dl=2
    "banana cherry banana grape kiwi",  # 2: no apple, dl=5
    "apple apple apple apple",          # 3: tf(apple)=4, dl=4
]


@pytest.fixture(scope="module")
def searcher():
    an = get_analyzer("simple")
    fi = build_field_index(DOCS, an)
    return SegmentSearcher(fi, an, len(DOCS))


def _stats(searcher, term):
    fi = searcher.index
    tid = fi.term_id(term)
    return (float(fi.ctf[tid]), float(fi.total_tokens),
            fi.norms.astype(float))


def hand_lm_dirichlet(tf, dl, p, mu=bm25_ops.LM_MU):
    return max(0.0, math.log(1 + tf / (mu * p)) +
               math.log(mu / (dl + mu))) + bm25_ops.MATCH_EPS


def hand_jm(tf, dl, p, lam=bm25_ops.JM_LAMBDA):
    return math.log(1 + ((1 - lam) * tf / max(dl, 1.0)) / (lam * p))


def hand_dfi(tf, dl, p):
    e = p * dl
    base = math.log2(1 + (tf - e) / math.sqrt(e)) if tf > e else 0.0
    return base + bm25_ops.MATCH_EPS


def test_ctf_property(searcher):
    fi = searcher.index
    assert int(fi.ctf[fi.term_id("apple")]) == 7
    assert int(fi.ctf[fi.term_id("banana")]) == 4
    assert int(fi.total_tokens) == 15


@pytest.mark.parametrize("scorer,hand", [
    ("lm_dirichlet", hand_lm_dirichlet),
    ("jelinek_mercer", hand_jm),
    ("dfi", hand_dfi),
])
def test_single_term_formula(searcher, scorer, hand):
    an = get_analyzer("simple")
    node = parse_query("apple", an)
    scores, docs = searcher.topk(node, 4, scorer=scorer)
    ctf, total, norms = _stats(searcher, "apple")
    p = ctf / total
    tf = {0: 2, 1: 1, 3: 4}
    expect = {d: hand(tf[d], norms[d], p) for d in tf}
    got = dict(zip(docs.tolist(), scores.tolist()))
    for d, s in expect.items():
        if s > 0:
            assert d in got, (scorer, d, got)
            # f32 kernel vs f64 hand computation
            assert got[d] == pytest.approx(s, rel=2e-3), (scorer, d)


def test_ranking_order_lm(searcher):
    an = get_analyzer("simple")
    node = parse_query("apple", an)
    for scorer in ("lm_dirichlet", "jelinek_mercer", "dfi"):
        scores, docs = searcher.topk(node, 4, scorer=scorer)
        # doc 3 (tf=4, dl=4) must outrank doc 1 (tf=1, dl=2)
        pos = {int(d): i for i, d in enumerate(docs)}
        assert pos[3] < pos[1], scorer


def test_multi_term_additive(searcher):
    an = get_analyzer("simple")
    node = parse_query("apple | banana", an)
    scores, docs = searcher.topk(node, 4, scorer="jelinek_mercer")
    # doc 0 has both terms; its score is the sum of both contributions
    ctf_a, total, norms = _stats(searcher, "apple")
    ctf_b = float(searcher.index.ctf[searcher.index.term_id("banana")])
    want = (hand_jm(2, 4, ctf_a / total) + hand_jm(1, 4, ctf_b / total))
    got = dict(zip(docs.tolist(), scores.tolist()))
    assert got[0] == pytest.approx(want, rel=1e-4)


def test_multisegment_global_stats():
    """Scores over two segments equal the single-segment scores (global
    collection stats, not per-segment)."""
    from serenedb_tpu.search.searcher import MultiSearcher
    an = get_analyzer("simple")
    one = SegmentSearcher(build_field_index(DOCS, an), an, len(DOCS))
    a = SegmentSearcher(build_field_index(DOCS[:2], an), an, 2)
    b = SegmentSearcher(build_field_index(DOCS[2:], an), an, 2)
    multi = MultiSearcher(an)
    multi.add_segment(a, 0)
    multi.add_segment(b, 2)
    node = parse_query("apple", an)
    for scorer in ("lm_dirichlet", "jelinek_mercer", "dfi"):
        s1, d1 = one.topk(node, 4, scorer=scorer)
        sm, dm = multi.topk_batch([node], 4, scorer=scorer)[0]
        m1 = dict(zip(d1.tolist(), s1.tolist()))
        mm = dict(zip(dm.tolist(), sm.tolist()))
        assert set(m1) == set(mm), scorer
        for d in m1:
            assert m1[d] == pytest.approx(mm[d], rel=2e-3), (scorer, d)


def test_sql_scorer_pushdown():
    c = Database().connect()
    c.execute("CREATE TABLE sdocs (id INT, body TEXT)")
    rows = ", ".join(f"({i}, '{d}')" for i, d in enumerate(DOCS))
    c.execute(f"INSERT INTO sdocs VALUES {rows}")
    c.execute("CREATE INDEX ON sdocs USING inverted (body simple)")
    for scorer in ("lm_dirichlet", "jelinek_mercer", "dfi"):
        got = c.execute(
            f"SELECT id, {scorer}(body, 'apple') AS s FROM sdocs "
            f"WHERE body @@ 'apple' ORDER BY s DESC LIMIT 3").rows()
        assert got[0][0] == 3, (scorer, got)     # highest tf ranks first
        assert all(r[1] >= 0 for r in got)
        assert got[0][1] > 0


def test_bm25_unaffected(searcher):
    an = get_analyzer("simple")
    node = parse_query("apple", an)
    scores, docs = searcher.topk(node, 4, scorer="bm25")
    assert len(scores) == 3 and scores[0] > 0


def test_weak_match_not_dropped(searcher):
    """lm_dirichlet/dfi score weak matches ~0 but the doc must still be
    returned (score>0 ⇔ matched invariant via MATCH_EPS)."""
    an = get_analyzer("simple")
    node = parse_query("banana", an)
    for scorer in ("lm_dirichlet", "dfi"):
        scores, docs = searcher.topk(node, 4, scorer=scorer)
        # banana appears in docs 0, 1, 2 — all three must come back
        assert set(docs.tolist()) == {0, 1, 2}, (scorer, docs)
        assert (scores > 0).all(), scorer
