"""Timeline tracing (ISSUE 10): trace parity, span well-formedness,
coalesced-batch span fan-out, latency histograms, flight recorder,
sdb_trace / GET /trace/<id>, EXPLAIN (FORMAT JSON), pool gauges."""

import json
import re
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.obs.trace import FLIGHT, chrome_trace, top_spans
from serenedb_tpu.utils import metrics as sdb_metrics
from serenedb_tpu.utils.config import REGISTRY as SETTINGS


def _db_with_tables(n=16384):
    """Fact + build tables sized for the morsel-parallel path at
    serene_morsel_rows=1024 and for the fused device pipeline at
    serene_device_min_rows=1024 (cpu-backend jit)."""
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE facts (ts BIGINT, k BIGINT, v BIGINT)")
    rng = np.random.default_rng(11)
    db.schemas["main"].tables["facts"].replace(Batch.from_pydict({
        "ts": Column.from_numpy(np.arange(n, dtype=np.int64)),
        "k": Column.from_numpy(rng.integers(0, 100, n, dtype=np.int64)),
        "v": Column.from_numpy(
            rng.integers(0, 1000, n, dtype=np.int64))}))
    c.execute("CREATE TABLE build (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["build"].replace(Batch.from_pydict({
        "k": Column.from_numpy(np.arange(100, dtype=np.int64)),
        "w": Column.from_numpy(np.arange(100, dtype=np.int64) * 10)}))
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_morsel_rows = 1024")
    c.execute("SET serene_parallel_min_rows = 1024")
    return db, c


AGG_Q = ("SELECT k, count(*), sum(v) FROM facts "
         "WHERE ts < 8192 GROUP BY k ORDER BY k")
JOIN_Q = ("SELECT count(*), sum(v + w) FROM facts "
          "JOIN build ON facts.k = build.k WHERE facts.ts < 8192")
FUSED_Q = ("SELECT count(*), sum(v) FROM facts "
           "JOIN build ON facts.k = build.k WHERE facts.v > 3")


def _last_entry(c):
    """The flight-recorder entry of the connection's LAST traced
    statement (capture the id before running anything else — the
    sdb_trace query itself is traced too)."""
    return FLIGHT.get(c._active_trace.trace_id)


def _spans_of(c, sql):
    c.execute(sql)
    return _last_entry(c)


# -- bit-identity: tracing observes, never steers ----------------------------


@pytest.mark.parametrize("query", [AGG_Q, JOIN_Q])
def test_trace_on_off_workers_shards_parity(query):
    db, c = _db_with_tables()
    results = {}
    for tr in ("on", "off"):
        for workers in (1, 4):
            for shards in (1, 4):
                c.execute(f"SET serene_trace = {tr}")
                c.execute(f"SET serene_workers = {workers}")
                c.execute(f"SET serene_shards = {shards}")
                results[(tr, workers, shards)] = c.execute(query).rows()
    base = results[("on", 1, 1)]
    assert base  # non-trivial result
    for key, rows in results.items():
        assert rows == base, f"{key} diverged from (on, 1, 1)"


# -- span tree well-formedness ----------------------------------------------

#: wait-category spans describe time spent OUTSIDE the recording thread
#: (queued behind another task / another group's dispatch) — they may
#: legitimately straddle an executing span on the same worker thread, so
#: the strict-nesting property applies to the execution spans only
_WAIT_SPANS = {"queue_wait", "batch_wait"}


def _assert_well_formed(entry):
    dur = entry["duration_ns"]
    root = [s for s in entry["spans"] if s["cat"] == "query"]
    assert len(root) == 1 and root[0]["begin_ns"] == 0 \
        and root[0]["end_ns"] == dur
    by_tid = {}
    for s in entry["spans"]:
        assert 0 <= s["begin_ns"] <= s["end_ns"], s
        # finalization happens after every span closed, so no span may
        # outlive the trace
        assert s["end_ns"] <= dur, s
        if s["cat"] != "query" and s["name"] not in _WAIT_SPANS:
            by_tid.setdefault(s["tid"], []).append(s)
    for tid, spans in by_tid.items():
        spans.sort(key=lambda s: (s["begin_ns"], -s["end_ns"]))
        stack = []
        for s in spans:
            while stack and stack[-1]["end_ns"] <= s["begin_ns"]:
                stack.pop()
            if stack:
                assert s["end_ns"] <= stack[-1]["end_ns"], \
                    f"partial overlap on tid {tid}: {stack[-1]} vs {s}"
            stack.append(s)


def test_span_tree_well_formed_parallel():
    db, c = _db_with_tables()
    c.execute("SET serene_workers = 4")
    entry = _spans_of(c, AGG_Q)
    _assert_well_formed(entry)
    names = [s["name"] for s in entry["spans"]]
    assert "plan" in names and "morsel_pipeline" in names
    # every pool task has a queue-wait span (recorded as a pair by the
    # worker that picked the task up)
    assert names.count("task") >= 1
    assert names.count("queue_wait") == names.count("task")


def test_span_tree_well_formed_sharded_device():
    db, c = _db_with_tables()
    c.execute("SET serene_workers = 4")
    c.execute("SET serene_shards = 2")
    c.execute("SET serene_device = 'auto'")
    c.execute("SET serene_device_min_rows = 1024")
    entry = _spans_of(c, FUSED_Q)
    _assert_well_formed(entry)
    cats = {s["cat"] for s in entry["spans"]}
    assert "device" in cats, f"no device spans in {cats}"
    names = [s["name"] for s in entry["spans"]]
    # the sharded fused join dispatches per shard (host combine:
    # device_dispatch lanes) or as ONE shard_map program
    # (serene_shard_combine=device: a collective_dispatch span)
    assert "device_dispatch" in names or "collective_dispatch" in names
    assert "shard_pipeline" in names or "device_upload" in names


def _union_coverage(entry) -> float:
    """Fraction of the query's wall time covered by the UNION of its
    non-root spans — the root `query` span equals the duration by
    construction, so it must not count toward coverage."""
    iv = sorted((s["begin_ns"], s["end_ns"]) for s in entry["spans"]
                if s["cat"] != "query")
    total, cur_b, cur_e = 0, None, None
    for b, e in iv:
        if cur_b is None:
            cur_b, cur_e = b, e
        elif b <= cur_e:
            cur_e = max(cur_e, e)
        else:
            total += cur_e - cur_b
            cur_b, cur_e = b, e
    if cur_b is not None:
        total += cur_e - cur_b
    return total / entry["duration_ns"]


def test_trace_coverage_at_workers_shards():
    """Acceptance shape: workers=4, shards=2 — the union of the
    attributed (non-root) spans covers >=95% of measured wall time,
    with queue-wait and device-dispatch phases present. The agg leg
    runs device=cpu so the morsel pipeline (pool queue waits)
    executes; the join leg runs device=auto so the fused pipeline
    dispatches."""
    db, c = _db_with_tables()
    c.execute("SET serene_workers = 4")
    c.execute("SET serene_shards = 2")
    c.execute(AGG_Q)
    entry_agg = _last_entry(c)
    c.execute("SET serene_device = 'auto'")
    c.execute("SET serene_device_min_rows = 1024")
    entry_dev = _spans_of(c, FUSED_Q)
    for entry in (entry_agg, entry_dev):
        cov = _union_coverage(entry)
        assert cov >= 0.95, \
            f"span coverage {cov:.3f} < 0.95 for {entry['query']}"
    assert any(s["name"] == "queue_wait" for s in entry_agg["spans"])
    assert any(s["name"] in ("device_dispatch", "collective_dispatch")
               for s in entry_dev["spans"])


# -- coalesced-batch span fan-out -------------------------------------------


def test_coalesced_batch_span_fanout():
    """A coalesced search dispatch stamps its spans under EVERY member
    query's trace: concurrent identical top-k searches must yield at
    least one trace whose batch_dispatch span carries queries >= 2,
    and every member of that dispatch must carry the span too."""
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT)")
    vals = ", ".join(f"({i}, 'quick brown fox number{i % 7} jumps')"
                     for i in range(512))
    c.execute("INSERT INTO docs VALUES " + vals)
    c.execute("CREATE INDEX ON docs USING inverted (body)")
    # the fragment cache would serve repeats without dispatching — force
    # misses so every thread really submits to the batcher
    prior = SETTINGS.get_global("serene_result_cache")
    SETTINGS.set_global("serene_result_cache", False)
    try:
        tids = []
        tid_lock = threading.Lock()

        def search():
            cc = db.connect()
            cc.execute("SELECT id, bm25(body) s FROM docs "
                       "WHERE body @@ 'fox jumps' "
                       "ORDER BY s DESC, id LIMIT 5")
            with tid_lock:
                tids.append(cc._active_trace.trace_id)

        for _ in range(6):   # repeat rounds until coalescing happens
            ts = [threading.Thread(target=search) for _ in range(8)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            fanout = {}
            for tid in tids:
                e = FLIGHT.get(tid)
                if e is None:
                    continue
                for s in e["spans"]:
                    if s["name"] == "batch_dispatch" and \
                            (s["args"] or {}).get("queries", 1) >= 2:
                        fanout.setdefault(
                            s["args"]["dispatch"], []).append(tid)
            coalesced = [m for m in fanout.values() if len(m) >= 2]
            if coalesced:
                break
        assert coalesced, "no coalesced dispatch fanned spans out to " \
                          "multiple member traces"
        # every member of the shared dispatch carries the span with the
        # same batch size
        members = coalesced[0]
        sizes = set()
        for tid in members:
            e = FLIGHT.get(tid)
            sizes.update(s["args"]["queries"] for s in e["spans"]
                         if s["name"] == "batch_dispatch")
        assert len(sizes) >= 1 and max(sizes) >= len(members)
    finally:
        SETTINGS.set_global("serene_result_cache", prior)


# -- histogram bucket math + Prometheus text --------------------------------


def test_histogram_bucket_math():
    h = sdb_metrics.Histogram("TestHist", "unit test")
    assert h.quantile_ns(0.5) == 0.0                      # empty
    # bucket boundaries: an observation exactly on a bound lands in
    # that bound's bucket (le semantics)
    assert sdb_metrics.hist_bucket_index(0) == 0
    assert sdb_metrics.hist_bucket_index(1000) == 0
    assert sdb_metrics.hist_bucket_index(1001) == 1
    assert sdb_metrics.hist_bucket_index(10 ** 18) == \
        len(sdb_metrics.HIST_BOUNDS_NS)                   # +Inf bucket
    for ns in (5_000, 5_000, 5_000, 1_000_000_000):
        h.observe_ns(ns)
    counts, sum_ns = h.snapshot()
    assert sum(counts) == 4 and sum_ns == 15_000 + 10 ** 9
    # p50 sits inside the 5µs observations' bucket, p99 near the 1s one
    assert h.quantile_ns(0.50) <= 8192 * 1000
    assert h.quantile_ns(0.99) > 5e8
    assert h.quantile_ns(0.50) < h.quantile_ns(0.99)
    p = h.percentiles_ms()
    assert p["count"] == 4 and p["p50_ms"] <= p["p99_ms"]
    # monotone in q
    qs = [h.quantile_ns(q) for q in (0.1, 0.5, 0.9, 0.99)]
    assert qs == sorted(qs)


def test_histogram_prometheus_text_parses():
    db, c = _db_with_tables()
    c.execute(AGG_Q)
    from serenedb_tpu.obs.export import prometheus_text
    txt = prometheus_text()
    assert "# TYPE serenedb_query_latency_seconds histogram" in txt
    buckets = re.findall(
        r'serenedb_query_latency_seconds_bucket\{le="([^"]+)"\} (\d+)',
        txt)
    assert len(buckets) == len(sdb_metrics.HIST_BOUNDS_NS) + 1
    # cumulative and monotone; +Inf bucket equals _count
    counts = [int(v) for _, v in buckets]
    assert counts == sorted(counts)
    assert buckets[-1][0] == "+Inf"
    m = re.search(r"serenedb_query_latency_seconds_count (\d+)", txt)
    assert m and int(m.group(1)) == counts[-1] and counts[-1] >= 1
    assert re.search(r"serenedb_query_latency_seconds_sum \d", txt)
    # finite le values parse as seconds and ascend
    les = [float(v) for v, _ in buckets[:-1]]
    assert les == sorted(les) and les[0] == 1e-06
    # the other tentpole histograms export too
    for series in ("serenedb_pool_queue_wait_seconds",
                   "serenedb_search_batch_window_seconds",
                   "serenedb_device_dispatch_seconds"):
        assert f"# TYPE {series} histogram" in txt


def test_stats_json_latency_percentiles():
    db, c = _db_with_tables()
    c.execute(AGG_Q)
    from serenedb_tpu.obs.export import stats_json
    sj = stats_json()
    lat = sj["latency"]["QueryLatency"]
    assert lat["count"] >= 1
    assert 0 <= lat["p50_ms"] <= lat["p95_ms"] <= lat["p99_ms"]
    assert "PoolQueueWait" in sj["latency"]
    assert any(t["trace_id"] for t in sj["traces"])


def test_stat_statements_percentiles():
    db, c = _db_with_tables()
    q = "SELECT count(*) FROM facts WHERE v < 500"
    for _ in range(5):
        c.execute(q)
    rows = c.execute(
        "SELECT calls, p50_time_ms, p95_time_ms, p99_time_ms "
        "FROM sdb_stat_statements() WHERE query LIKE "
        "'select count ( * ) from facts%'").rows()
    assert rows, "statement not tracked"
    calls, p50, p95, p99 = rows[-1]
    assert calls >= 5
    assert 0 < p50 <= p95 <= p99


# -- flight recorder --------------------------------------------------------


def test_flight_recorder_eviction_order():
    prior = SETTINGS.get_global("serene_flight_recorder_queries")
    SETTINGS.set_global("serene_flight_recorder_queries", 4)
    try:
        db, c = _db_with_tables(2048)
        ids = []
        for i in range(7):
            c.execute(f"SELECT count(*) FROM facts WHERE v <> {i}")
            ids.append(c._active_trace.trace_id)
        assert all(FLIGHT.get(t) is None for t in ids[:3]), \
            "oldest entries must evict"
        assert all(FLIGHT.get(t) is not None for t in ids[-4:]), \
            "newest entries must survive"
        listed = [e["trace_id"] for e in FLIGHT.snapshot()]
        assert listed == sorted(listed), "ring must list oldest->newest"
        assert len(listed) <= 4
    finally:
        SETTINGS.set_global("serene_flight_recorder_queries", prior)


def test_error_path_dumps_timeline():
    db, c = _db_with_tables(2048)
    with pytest.raises(Exception):
        c.execute("SELECT 1/0 FROM facts")
    entry = _last_entry(c)
    assert entry is not None and entry["error"]
    assert "division" in entry["error"]


def test_sdb_trace_table_function():
    db, c = _db_with_tables(2048)
    c.execute(AGG_Q)
    tid = c._active_trace.trace_id
    listing = c.execute("SELECT trace_id, query, duration_ms, spans "
                        "FROM sdb_trace()").rows()
    assert any(r[0] == tid and AGG_Q in r[1] for r in listing)
    spans = c.execute(
        f"SELECT span, category, begin_ms, end_ms, duration_ms "
        f"FROM sdb_trace({tid})").rows()
    assert spans[0][0] == "query"
    for name, cat, b, e, d in spans:
        assert 0 <= b <= e and abs((e - b) - d) < 0.01
    begins = [r[2] for r in spans]
    assert begins == sorted(begins), "spans must be begin-ordered"
    # unknown ids yield an empty relation (entry may have aged out)
    assert c.execute("SELECT * FROM sdb_trace(999999999)").rows() == []
    # sdb_trace also resolves as a bare system table (the listing)
    assert c.execute("SELECT count(*) FROM sdb_trace").rows()[0][0] >= 1


def test_trace_disabled_records_nothing():
    db, c = _db_with_tables(2048)
    c.execute("SET serene_trace = off")
    c.execute(AGG_Q)
    assert c._active_trace is None


def test_utility_statements_not_flight_recorded():
    """SET/SHOW/txn statements are bookkeeping, not work: they must not
    churn the bounded flight recorder (a per-query SET would halve the
    ring's post-incident reach)."""
    db, c = _db_with_tables(2048)
    c.execute(AGG_Q)
    tid = c._active_trace.trace_id
    c.execute("SET application_name = 'noise'")
    c.execute("SHOW application_name")
    c.execute("BEGIN")
    c.execute("COMMIT")
    assert c._active_trace is None
    listing = [e["trace_id"] for e in FLIGHT.snapshot()]
    assert tid in listing
    queries = [e["query"] for e in FLIGHT.snapshot()]
    assert not any(q.startswith(("SET ", "SHOW ", "BEGIN", "COMMIT"))
                   for q in queries)


# -- /trace endpoint --------------------------------------------------------


def test_trace_endpoint_chrome_json():
    from serenedb_tpu.server.http_server import HttpServer
    db, c = _db_with_tables()
    c.execute("SET serene_workers = 4")
    c.execute(AGG_Q)
    tid = c._active_trace.trace_id
    srv = HttpServer(db)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        doc = json.loads(urllib.request.urlopen(
            f"{base}/trace/{tid}").read())
        events = doc["traceEvents"]
        assert doc["displayTimeUnit"] == "ms"
        x = [e for e in events if e["ph"] == "X"]
        m = [e for e in events if e["ph"] == "M"]
        assert x and m
        for e in x:
            assert e["ts"] >= 0 and e["dur"] >= 0
            assert e["pid"] == 1 and "tid" in e and e["name"]
        root = [e for e in x if e["name"] == "query"]
        assert len(root) == 1 and \
            root[0]["args"]["trace_id"] == tid
        assert doc["otherData"]["trace_id"] == tid
        # /trace/last serves the newest entry; the listing includes tid
        last = json.loads(urllib.request.urlopen(
            f"{base}/trace/last").read())
        assert last["otherData"]["trace_id"] >= tid
        listing = json.loads(urllib.request.urlopen(
            f"{base}/trace").read())
        assert any(e["trace_id"] == tid for e in listing)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/trace/999999999")
        assert ei.value.code == 404
    finally:
        srv.stop()


# -- EXPLAIN (ANALYZE, FORMAT JSON) -----------------------------------------


def test_explain_format_json_plain():
    db, c = _db_with_tables(2048)
    out = c.execute(f"EXPLAIN (FORMAT JSON) {AGG_Q}").rows()
    doc = json.loads(out[0][0])
    assert isinstance(doc, list) and "Plan" in doc[0]
    plan = doc[0]["Plan"]
    assert plan["Node Type"]
    assert "Actual Rows" not in plan          # structure only
    kids = plan.get("Plans", [])
    assert kids, "tree must nest"


def test_explain_analyze_format_json():
    db, c = _db_with_tables()
    c.execute("SET serene_workers = 4")
    expected = len(c.execute(AGG_Q).rows())
    out = c.execute(f"EXPLAIN (ANALYZE, FORMAT JSON) {AGG_Q}").rows()
    doc = json.loads(out[0][0])
    top = doc[0]
    assert top["Rows Returned"] == expected
    assert top["Execution Time"] > 0

    def walk(node):
        yield node
        for k in node.get("Plans", []):
            yield from walk(k)

    nodes = list(walk(top["Plan"]))
    agg = [n for n in nodes if "Actual Rows" in n]
    assert agg, "annotated nodes missing"
    scan = [n for n in nodes if "Morsels Scheduled" in n]
    assert scan, "prune counters missing from JSON tree"
    assert all("Actual Total Time" in n for n in agg)
    # text form unchanged alongside
    text = c.execute(f"EXPLAIN (ANALYZE) {AGG_Q}").rows()
    assert any("actual time=" in r[0] for r in text)


def test_explain_json_device_and_shard_keys():
    db, c = _db_with_tables()
    c.execute("SET serene_device = 'auto'")
    c.execute("SET serene_device_min_rows = 1024")
    c.execute("SET serene_shards = 2")
    out = c.execute(f"EXPLAIN (ANALYZE, FORMAT JSON) {FUSED_Q}").rows()
    doc = json.loads(out[0][0])

    def walk(node):
        yield node
        for k in node.get("Plans", []):
            yield from walk(k)

    nodes = list(walk(doc[0]["Plan"]))
    assert any("Device Time" in n for n in nodes), \
        "device attribution missing from JSON plan"


def test_explain_option_list_errors():
    db, c = _db_with_tables(2048)
    with pytest.raises(Exception):
        c.execute(f"EXPLAIN (FORMAT yaml) {AGG_Q}")
    with pytest.raises(Exception):
        c.execute(f"EXPLAIN (bogus) {AGG_Q}")
    # bare ANALYZE keyword form still works
    assert c.execute(f"EXPLAIN ANALYZE {AGG_Q}").rows()


# -- slow-query log timeline ------------------------------------------------


def test_slow_log_attaches_timeline():
    db, c = _db_with_tables()
    c.execute("SET serene_workers = 4")
    c.execute("SET serene_log_min_duration_ms = 0")
    c.execute(AGG_Q)
    rows = c.execute("SELECT message FROM sdb_log() "
                     "WHERE topic = 'slow_query'").rows()
    msgs = [m[0] for m in rows if AGG_Q.split()[1] in m[0]]
    assert msgs, "slow-query entry missing"
    last = msgs[-1]
    assert "timeline: trace_id=" in last
    assert "span " in last
    # top-5 widest spans: no more than 5 span lines after the header
    span_lines = [ln for ln in last.splitlines()
                  if ln.strip().startswith("span ")]
    assert 1 <= len(span_lines) <= 5
    # the plan tree still rides along
    assert "actual time=" in last


def test_top_spans_widest_first():
    db, c = _db_with_tables()
    c.execute("SET serene_workers = 4")
    entry = _spans_of(c, AGG_Q)
    tops = top_spans(entry, 5)
    widths = [s["end_ns"] - s["begin_ns"] for s in tops]
    assert widths == sorted(widths, reverse=True)
    assert all(s["cat"] != "query" for s in tops)


# -- pool observability gauges ----------------------------------------------


def test_pool_gauges_quiesce_and_accumulate():
    db, c = _db_with_tables()
    c.execute("SET serene_workers = 4")
    wait0 = sdb_metrics.POOL_TASK_WAIT_NS.value
    c.execute(AGG_Q)
    # live gauges settle back to idle once the statement drained
    assert sdb_metrics.POOL_QUEUE_DEPTH.value == 0
    assert sdb_metrics.POOL_RUNNING.value == 0
    assert sdb_metrics.POOL_TASK_WAIT_NS.value >= wait0
    # the ns counter and the histogram see the same task stream
    counts, _ = sdb_metrics.POOL_QUEUE_WAIT_HIST.snapshot()
    assert sum(counts) >= 1
    # the gauges surface through /metrics naming
    from serenedb_tpu.obs.export import prometheus_text
    txt = prometheus_text()
    assert "serenedb_pool_queue_depth" in txt
    assert "serenedb_pool_running_tasks" in txt
    assert "serenedb_pool_task_wait_ns" in txt


def test_chrome_trace_roundtrip_unit():
    entry = {"trace_id": 42, "query": "SELECT 1",
             "begin_epoch_us": 1000, "duration_ns": 5_000_000,
             "error": None, "spans_dropped": 0,
             "spans": [
                 {"name": "query", "cat": "query", "tid": 0,
                  "thread": "query", "begin_ns": 0,
                  "end_ns": 5_000_000,
                  "args": {"query": "SELECT 1", "trace_id": 42}},
                 {"name": "task", "cat": "pool", "tid": 7,
                  "thread": "sdb-morsel-0", "begin_ns": 1_000_000,
                  "end_ns": 2_000_000, "args": None}]}
    doc = chrome_trace(entry)
    json.loads(json.dumps(doc))      # serializable
    x = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in x} == {"query", "task"}
    task = [e for e in x if e["name"] == "task"][0]
    assert task["ts"] == 1000.0 and task["dur"] == 1000.0
    names = [e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"]
    assert "sdb-morsel-0" in names


def test_trace_not_result_affecting():
    from serenedb_tpu.cache.result import RESULT_AFFECTING_SETTINGS
    assert "serene_trace" not in RESULT_AFFECTING_SETTINGS
    assert "serene_flight_recorder_queries" not in \
        RESULT_AFFECTING_SETTINGS
