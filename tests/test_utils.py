import pytest

from serenedb_tpu.utils import config, faults, log, metrics, ticks


def test_settings_session_overrides_global():
    s = config.SessionSettings()
    assert s.get("sdb_nprobe") == 8
    s.set("sdb_nprobe", "16")
    assert s.get("sdb_nprobe") == 16
    s.reset("sdb_nprobe")
    assert s.get("sdb_nprobe") == 8
    with pytest.raises(KeyError):
        s.get("no_such_setting")


def test_settings_bool_coercion():
    s = config.SessionSettings()
    s.set("sdb_strict_ddl", "on")
    assert s.get("sdb_strict_ddl") is True
    s.set("sdb_strict_ddl", "off")
    assert s.get("sdb_strict_ddl") is False
    with pytest.raises(ValueError):
        s.set("sdb_strict_ddl", "maybe")


def test_fault_arming_spec():
    faults.arm_from_spec("a,b")
    assert faults.armed("a") and faults.armed("b")
    faults.arm_from_spec("-a")
    assert not faults.armed("a") and faults.armed("b")
    faults.arm_from_spec("+c")
    assert faults.armed("b") and faults.armed("c")
    faults.arm_from_spec("")
    assert not faults.armed("b")
    faults.arm_from_spec("x")
    with pytest.raises(faults.FaultInjected):
        faults.if_failure("x")
    faults.if_failure("unarmed")  # no-op


def test_gauge_scoped():
    g = metrics.REGISTRY.gauge("TestGauge")
    with g.scoped():
        assert g.value == 1
    assert g.value == 0


def test_log_ring():
    log.info("test", "hello")
    recs = log.MANAGER.records()
    assert any(r.message == "hello" and r.topic == "test" for r in recs)


def test_tick_bands():
    t = ticks.TickServer()
    first = t.next(5)
    assert first == 1
    assert t.current() == 5
    assert t.next() == 6
    t.advance_to(100)
    assert t.next() == 101
