"""TLS in-band upgrade + host-based auth (reference:
server/network/tls_context.cpp, server/network/pg/hba.cpp).

The TLS tests generate a self-signed cert with the openssl CLI; the client
is the same raw-socket RawPg used by the wire tests, upgraded via
SSLRequest → 'S' → wrap. psycopg2/asyncpg are not in this image (by
design); the raw client plus these rules cover the same contract the
reference's driver matrix exercises for auth/TLS."""

import shutil
import socket
import struct
import subprocess

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.server.hba import HbaError, match_rule, parse_hba

from test_pgwire import RawPg, _run_pg_server

HAVE_OPENSSL = shutil.which("openssl") is not None


@pytest.fixture(scope="module")
def certpair(tmp_path_factory):
    if not HAVE_OPENSSL:
        pytest.skip("openssl CLI unavailable")
    d = tmp_path_factory.mktemp("tls")
    cert, key = str(d / "server.crt"), str(d / "server.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-keyout", key,
         "-out", cert, "-days", "2", "-nodes", "-subj", "/CN=localhost"],
        check=True, capture_output=True)
    return cert, key


# -- HBA rule engine (pure) -------------------------------------------------

HBA_SAMPLE = """
# comment line
host    all  all   127.0.0.1/32   trust
hostssl all  app   0.0.0.0/0      scram-sha-256
host    db1  alice 10.0.0.0/8     password
local   all  all   trust
host    all  all   all            reject
"""


def test_hba_parse_and_first_match():
    rules = parse_hba(HBA_SAMPLE)
    assert len(rules) == 5
    r = match_rule(rules, "any", "bob", "127.0.0.1", tls=False)
    assert r.method == "trust"
    # hostssl only matches TLS connections
    r = match_rule(rules, "db", "app", "10.1.2.3", tls=False)
    assert r.method == "reject"
    r = match_rule(rules, "db", "app", "10.1.2.3", tls=True)
    assert r.method == "scram-sha-256"
    # db/user/CIDR matching
    r = match_rule(rules, "db1", "alice", "10.9.9.9", tls=False)
    assert r.method == "password"
    r = match_rule(rules, "db2", "alice", "10.9.9.9", tls=False)
    assert r.method == "reject"
    # no rules matching → None
    assert match_rule(rules[:1], "d", "u", "192.168.0.1", tls=False) is None


def test_hba_netmask_and_lists():
    rules = parse_hba(
        "host db1,db2 u1,u2 192.168.0.0 255.255.0.0 scram-sha-256\n")
    assert match_rule(rules, "db2", "u1", "192.168.5.5", False) is not None
    assert match_rule(rules, "db3", "u1", "192.168.5.5", False) is None
    assert match_rule(rules, "db1", "u3", "192.168.5.5", False) is None
    assert match_rule(rules, "db1", "u1", "192.169.0.1", False) is None


def test_hba_rejects_malformed():
    with pytest.raises(HbaError):
        parse_hba("host all all 127.0.0.1/32 frobnicate\n")
    with pytest.raises(HbaError):
        parse_hba("teleport all all 127.0.0.1/32 trust\n")
    with pytest.raises(HbaError):
        parse_hba("host all all not-an-ip trust\n")


# -- live server: TLS upgrade ----------------------------------------------

def test_tls_upgrade_and_query(certpair):
    cert, key = certpair
    srv, stop = _run_pg_server(Database(), tls_cert=cert, tls_key=key)
    try:
        pg = RawPg(srv.port, tls=True)
        cols, rows, tags, errs = pg.query("SELECT 41 + 1")
        assert rows == [("42",)]
        pg.close()
        # non-TLS connections still work on the same listener
        pg = RawPg(srv.port, tls=False)
        assert pg.query("SELECT 1")[1] == [("1",)]
        pg.close()
    finally:
        stop()


def test_tls_scram_auth(certpair):
    cert, key = certpair
    srv, stop = _run_pg_server(Database(), password="s3cret",
                               tls_cert=cert, tls_key=key)
    try:
        pg = RawPg(srv.port, tls=True, password="s3cret")
        assert pg.query("SELECT 7")[1] == [("7",)]
        pg.close()
        with pytest.raises(AssertionError):
            RawPg(srv.port, tls=True, password="wrong")
    finally:
        stop()


def test_no_tls_configured_answers_N():
    srv, stop = _run_pg_server(Database())
    try:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        s.sendall(struct.pack("!II", 8, 80877103))
        assert s.recv(1) == b"N"
        s.close()
    finally:
        stop()


# -- live server: HBA enforcement ------------------------------------------

def test_hba_reject_rule_blocks_connection():
    db = Database()
    srv, stop = _run_pg_server(db, hba_conf="host all all all reject\n")
    try:
        with pytest.raises(AssertionError, match="reject"):
            RawPg(srv.port)
    finally:
        stop()


def test_hba_trust_rule_allows_without_password():
    db = Database()
    db.connect().execute(
        "CREATE ROLE secured LOGIN PASSWORD 'pw123'")
    srv, stop = _run_pg_server(
        db, hba_conf="host all all 127.0.0.1/32 trust\n")
    try:
        # trust overrides the role password requirement
        pg = RawPg(srv.port, user="secured")
        assert pg.query("SELECT 1")[1] == [("1",)]
        pg.close()
    finally:
        stop()


def test_hba_scram_rule_requires_password():
    db = Database()
    db.connect().execute("CREATE ROLE locked LOGIN PASSWORD 'hunter2'")
    srv, stop = _run_pg_server(
        db, hba_conf="host all all 127.0.0.1/32 scram-sha-256\n")
    try:
        pg = RawPg(srv.port, user="locked", password="hunter2")
        assert pg.query("SELECT 1")[1] == [("1",)]
        pg.close()
        with pytest.raises(AssertionError):
            RawPg(srv.port, user="locked", password="bad")
        # a role with no password cannot satisfy a scram rule
        with pytest.raises(AssertionError):
            RawPg(srv.port, user="tester", password="anything")
    finally:
        stop()


def test_hba_hostssl_requires_tls(certpair):
    cert, key = certpair
    db = Database()
    srv, stop = _run_pg_server(
        db, tls_cert=cert, tls_key=key,
        hba_conf="hostssl all all all trust\nhost all all all reject\n")
    try:
        pg = RawPg(srv.port, tls=True)
        assert pg.query("SELECT 1")[1] == [("1",)]
        pg.close()
        with pytest.raises(AssertionError, match="reject"):
            RawPg(srv.port, tls=False)
    finally:
        stop()


def test_hba_database_scoping():
    db = Database()
    srv, stop = _run_pg_server(
        db, hba_conf=("host db_ok all 127.0.0.1/32 trust\n"
                      "host all   all all          reject\n"))
    try:
        pg = RawPg(srv.port, database="db_ok")
        assert pg.query("SELECT 1")[1] == [("1",)]
        pg.close()
        with pytest.raises(AssertionError):
            RawPg(srv.port, database="other_db")
    finally:
        stop()


def test_hba_password_method_verifies_scram_roles():
    """HBA method=password against a role stored as a SCRAM verifier must
    verify the cleartext against the verifier — never fall open (review
    regression: auth bypass)."""
    db = Database()
    db.connect().execute("CREATE ROLE vaulted LOGIN PASSWORD 'realpw'")
    srv, stop = _run_pg_server(
        db, hba_conf="host all all 127.0.0.1/32 password\n")
    try:
        pg = RawPg(srv.port, user="vaulted", password="realpw")
        assert pg.query("SELECT 1")[1] == [("1",)]
        pg.close()
        with pytest.raises(AssertionError):
            RawPg(srv.port, user="vaulted", password="anything-else")
        # passwordless role under method=password: fail closed
        with pytest.raises(AssertionError):
            RawPg(srv.port, user="tester", password="whatever")
    finally:
        stop()


def test_hba_samehost_and_samenet():
    rules = parse_hba("host all all samehost trust\n")
    assert match_rule(rules, "d", "u", "127.0.0.1", False) is not None
    assert match_rule(rules, "d", "u", "::1", False) is not None
    assert match_rule(rules, "d", "u", "203.0.113.9", False) is None
    with pytest.raises(HbaError, match="samenet"):
        parse_hba("host all all samenet trust\n")
