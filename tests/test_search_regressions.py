"""Regressions for search review findings: absent-term conjunctions,
pure-negation top-k, per-column scorer wiring, stream-mode scores."""

import numpy as np
import pytest

from serenedb_tpu.engine import Database


@pytest.fixture
def conn():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT, title TEXT)")
    c.execute("INSERT INTO docs VALUES "
              "(1, 'apple pie recipe', 'cooking'),"
              "(2, 'apple orchard tour', 'travel'),"
              "(3, 'banana bread', 'cooking')")
    c.execute("CREATE INDEX ON docs USING inverted (body)")
    return c


def test_conjunction_with_absent_term_matches_nothing(conn):
    rows = conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ "
        "'apple & zzznothere' ORDER BY s DESC LIMIT 5").rows()
    assert rows == []
    assert conn.execute("SELECT count(*) FROM docs WHERE body @@ "
                        "'apple & zzznothere'").scalar() == 0


def test_pure_negation_topk(conn):
    rows = conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ '!apple' "
        "ORDER BY s DESC LIMIT 5").rows()
    assert [r[0] for r in rows] == [3]
    assert rows[0][1] == 0.0


def test_scorer_of_other_column_not_rewired(conn):
    rows = conn.execute(
        "SELECT id, bm25(body) AS s, bm25(title) AS t FROM docs "
        "WHERE body @@ 'apple' ORDER BY s DESC LIMIT 5").rows()
    assert len(rows) == 2
    for _, s, t in rows:
        assert s > 0.0
        assert t == 0.0  # title has no index/pushdown → default score


def test_stream_mode_scores_nonzero(conn):
    # no ORDER BY/LIMIT: scores must still be real, consistent with top-k
    rows = dict((r[0], r[1]) for r in conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple'").rows())
    topk = dict((r[0], r[1]) for r in conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple' "
        "ORDER BY s DESC LIMIT 10").rows())
    assert rows.keys() == topk.keys()
    for k in rows:
        assert rows[k] == pytest.approx(topk[k], rel=1e-5)
        assert rows[k] > 0.0
