"""Regressions for search review findings: absent-term conjunctions,
pure-negation top-k, per-column scorer wiring, stream-mode scores."""

import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError


@pytest.fixture
def conn():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT, title TEXT)")
    c.execute("INSERT INTO docs VALUES "
              "(1, 'apple pie recipe', 'cooking'),"
              "(2, 'apple orchard tour', 'travel'),"
              "(3, 'banana bread', 'cooking')")
    c.execute("CREATE INDEX ON docs USING inverted (body)")
    return c


def test_conjunction_with_absent_term_matches_nothing(conn):
    rows = conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ "
        "'apple & zzznothere' ORDER BY s DESC LIMIT 5").rows()
    assert rows == []
    assert conn.execute("SELECT count(*) FROM docs WHERE body @@ "
                        "'apple & zzznothere'").scalar() == 0


def test_pure_negation_topk(conn):
    rows = conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ '!apple' "
        "ORDER BY s DESC LIMIT 5").rows()
    assert [r[0] for r in rows] == [3]
    assert rows[0][1] == 0.0


def test_scorer_of_other_column_not_rewired(conn):
    rows = conn.execute(
        "SELECT id, bm25(body) AS s, bm25(title) AS t FROM docs "
        "WHERE body @@ 'apple' ORDER BY s DESC LIMIT 5").rows()
    assert len(rows) == 2
    for _, s, t in rows:
        assert s > 0.0
        assert t == 0.0  # title has no index/pushdown → default score


def test_stream_mode_scores_nonzero(conn):
    # no ORDER BY/LIMIT: scores must still be real, consistent with top-k
    rows = dict((r[0], r[1]) for r in conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple'").rows())
    topk = dict((r[0], r[1]) for r in conn.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple' "
        "ORDER BY s DESC LIMIT 10").rows())
    assert rows.keys() == topk.keys()
    for k in rows:
        assert rows[k] == pytest.approx(topk[k], rel=1e-5)
        assert rows[k] > 0.0


def test_regex_terms_indexed_matches_brute():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE rx (id INT, body TEXT)")
    c.execute("INSERT INTO rx VALUES"
              " (1, 'server restarted cleanly'),"
              " (2, 'browser rendering issue'),"
              " (3, 'observer pattern applied'),"
              " (4, 'totally unrelated words'),"
              " (5, NULL)")
    queries = ["/.*server.*/", "/rest.*/", "/[bo]+.*er/",
               "/rest.*/ & cleanly", "! /.*er.*/"]
    brute = [c.execute(
        f"SELECT id FROM rx WHERE body @@ '{q}' ORDER BY id").rows()
        for q in queries]
    c.execute("CREATE INDEX ON rx USING inverted (body)")
    for q, expect in zip(queries, brute):
        got = c.execute(
            f"SELECT id FROM rx WHERE body @@ '{q}' ORDER BY id").rows()
        assert got == expect, (q, got, expect)
    # sanity on actual values (porter2: 'observer'→'observ', so only the
    # literal 'server' doc matches the .*server.* term regex)
    assert c.execute("SELECT id FROM rx WHERE body @@ '/.*server.*/' "
                     "ORDER BY id").rows() == [(1,)]


def test_regex_invalid_pattern_errors():
    c = Database().connect()
    c.execute("CREATE TABLE rxe (body TEXT)")
    c.execute("INSERT INTO rxe VALUES ('abc')")
    with pytest.raises(SqlError) as e:
        c.execute("SELECT count(*) FROM rxe WHERE body @@ '/[unclosed/'")
    assert e.value.sqlstate == "2201B"


def test_regex_headline():
    c = Database().connect()
    c.execute("CREATE TABLE rxh (body TEXT)")
    c.execute("INSERT INTO rxh VALUES ('the server restarted')")
    assert c.execute(
        "SELECT ts_headline(body, '/.*start.*/') FROM rxh").scalar() \
        == "the server <b>restarted</b>"


def test_regex_escaped_slash_in_pattern():
    c = Database().connect()
    c.execute("CREATE TABLE rxs (body TEXT)")
    # keyword-style terms containing slashes need \/ inside /pattern/
    c.execute("CREATE TEXT SEARCH DICTIONARY kw_rx(template = 'keyword')")
    c.execute("INSERT INTO rxs VALUES ('etc/hosts'), ('etc/passwd'), "
              "('var/log')")
    c.execute("CREATE INDEX ON rxs USING inverted (body kw_rx)")
    rows = c.execute(
        r"SELECT body FROM rxs WHERE body @@ '/etc\/[a-z]+/' ORDER BY body"
    ).rows()
    assert rows == [("etc/hosts",), ("etc/passwd",)]
    c.execute("DROP TABLE rxs")
    c.execute("DROP TEXT SEARCH DICTIONARY kw_rx")


def test_regex_case_folds_like_bare_terms():
    # review finding: '/Alpha.*/' silently matched nothing while 'Alpha'
    # matched — regex literals must fold exactly when the analyzer does
    c = Database().connect()
    c.execute("CREATE TABLE rxc (body TEXT)")
    c.execute("INSERT INTO rxc VALUES ('Alpha beta')")
    assert c.execute(
        "SELECT count(*) FROM rxc WHERE body @@ '/Alpha.*/'").scalar() == 1
    c.execute("CREATE INDEX ON rxc USING inverted (body)")
    assert c.execute(
        "SELECT count(*) FROM rxc WHERE body @@ '/Alpha.*/'").scalar() == 1
    # keyword analyzer preserves case → pattern stays verbatim
    c.execute("CREATE TEXT SEARCH DICTIONARY kw_c(template = 'keyword')")
    c.execute("CREATE TABLE rxk (body TEXT)")
    c.execute("INSERT INTO rxk VALUES ('Alpha'), ('alpha')")
    c.execute("CREATE INDEX ON rxk USING inverted (body kw_c)")
    assert c.execute(
        "SELECT count(*) FROM rxk WHERE body @@ '/Alpha/'").scalar() == 1
    c.execute("DROP TABLE rxk")
    c.execute("DROP TEXT SEARCH DICTIONARY kw_c")


def test_prefix_respects_case_preserving_analyzer():
    # review finding: prefixes were unconditionally lowercased, silently
    # matching nothing under keyword/whitespace analyzers
    c = Database().connect()
    c.execute("CREATE TEXT SEARCH DICTIONARY kw_p(template = 'keyword')")
    c.execute("CREATE TABLE pfx (body TEXT)")
    c.execute("INSERT INTO pfx VALUES ('Alpha'), ('alpine')")
    # the dictionary binds via the index: only the indexed path has
    # case-preserving terms (un-indexed @@ uses the default text analyzer)
    c.execute("CREATE INDEX ON pfx USING inverted (body kw_p)")
    assert c.execute(
        "SELECT count(*) FROM pfx WHERE body @@ 'Alph*'").scalar() == 1
    assert c.execute(
        "SELECT count(*) FROM pfx WHERE body @@ 'alp*'").scalar() == 1
    c.execute("DROP TABLE pfx")
    c.execute("DROP TEXT SEARCH DICTIONARY kw_p")
