"""PG wire protocol tests with a minimal raw-socket client (no driver deps —
the reference tests this with real drivers; a raw client checks framing)."""

import asyncio
import socket
import struct

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.server.pgwire import PgServer


class RawPg:
    def __init__(self, port, user="tester", password=None, tls=False,
                 database=None):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=15)
        self.buf = b""
        if tls:
            import ssl
            self.sock.sendall(struct.pack("!II", 8, 80877103))  # SSLRequest
            resp = self.sock.recv(1)
            assert resp == b"S", f"server declined TLS: {resp!r}"
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE   # self-signed test certs
            self.sock = ctx.wrap_socket(self.sock)
        params = f"user\x00{user}\x00".encode()
        if database is not None:
            params += f"database\x00{database}\x00".encode()
        params += b"\x00"
        body = struct.pack("!I", 196608) + params
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)
        self.params = {}
        self.backend_key = None
        scram_cont = scram_verify = None
        while True:
            kind, payload = self.read_msg()
            if kind == b"R":
                (code,) = struct.unpack("!I", payload[:4])
                if code == 3:
                    assert password is not None, "server demands password"
                    pw = password.encode() + b"\x00"
                    self.send(b"p", pw)
                elif code == 10:   # AuthenticationSASL → SCRAM-SHA-256
                    assert password is not None, "server demands password"
                    from serenedb_tpu.scram import client_exchange
                    mechs = payload[4:].split(b"\x00")
                    assert b"SCRAM-SHA-256" in mechs
                    first, scram_cont, scram_verify = client_exchange(
                        password)
                    init = first.encode()
                    self.send(b"p", b"SCRAM-SHA-256\x00" +
                              struct.pack("!i", len(init)) + init)
                elif code == 11:   # SASLContinue
                    final = scram_cont(payload[4:].decode())
                    self.send(b"p", final.encode())
                elif code == 12:   # SASLFinal
                    assert scram_verify(payload[4:].decode()), \
                        "server signature mismatch"
                elif code == 0:
                    pass
                else:
                    raise AssertionError(f"unexpected auth {code}")
            elif kind == b"S":
                k, v = payload.split(b"\x00")[:2]
                self.params[k.decode()] = v.decode()
            elif kind == b"K":
                self.backend_key = struct.unpack("!II", payload)
            elif kind == b"Z":
                self.status = payload
                return
            elif kind == b"E":
                raise AssertionError(f"error in startup: {payload}")

    def send(self, kind, payload=b""):
        self.sock.sendall(kind + struct.pack("!I", len(payload) + 4) + payload)

    def read_msg(self):
        while len(self.buf) < 5:
            data = self.sock.recv(65536)
            if not data:
                raise ConnectionError("closed")
            self.buf += data
        kind = self.buf[:1]
        (ln,) = struct.unpack("!I", self.buf[1:5])
        while len(self.buf) < 1 + ln:
            self.buf += self.sock.recv(65536)
        payload = self.buf[5:1 + ln]
        self.buf = self.buf[1 + ln:]
        return kind, payload

    def query(self, sql):
        """Simple query; returns (columns, rows, tags, errors)."""
        self.send(b"Q", sql.encode() + b"\x00")
        cols, rows, tags, errs = [], [], [], []
        while True:
            kind, payload = self.read_msg()
            if kind == b"T":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                cols = []
                for _ in range(n):
                    end = payload.index(b"\x00", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
            elif kind == b"D":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                row = []
                for _ in range(n):
                    (ln,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(tuple(row))
            elif kind == b"C":
                tags.append(payload[:-1].decode())
            elif kind == b"E":
                errs.append(_parse_err(payload))
            elif kind == b"Z":
                self.status = payload
                return cols, rows, tags, errs

    def extended(self, sql, params=()):
        """Parse/Bind/Describe/Execute/Sync round."""
        self.send(b"P", b"\x00" + sql.encode() + b"\x00" + b"\x00\x00")
        parts = [b"\x00", b"\x00", struct.pack("!H", 0),
                 struct.pack("!H", len(params))]
        for p in params:
            if p is None:
                parts.append(struct.pack("!i", -1))
            else:
                enc = str(p).encode()
                parts.append(struct.pack("!i", len(enc)) + enc)
        parts.append(struct.pack("!H", 0))
        self.send(b"B", b"".join(parts))
        self.send(b"D", b"P\x00")
        self.send(b"E", b"\x00" + struct.pack("!I", 0))
        self.send(b"S")
        cols, rows, tags, errs = [], [], [], []
        while True:
            kind, payload = self.read_msg()
            if kind == b"T":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                for _ in range(n):
                    end = payload.index(b"\x00", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
            elif kind == b"D":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                row = []
                for _ in range(n):
                    (ln,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(tuple(row))
            elif kind == b"C":
                tags.append(payload[:-1].decode())
            elif kind == b"E":
                errs.append(_parse_err(payload))
            elif kind == b"Z":
                return cols, rows, tags, errs

    def close(self):
        try:
            self.send(b"X")
        except OSError:
            pass
        self.sock.close()


def _parse_err(payload):
    fields = {}
    for part in payload.split(b"\x00"):
        if part:
            fields[chr(part[0])] = part[1:].decode()
    return fields


@pytest.fixture(scope="module")
def server():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE t (a INT, s TEXT)")
    c.execute("INSERT INTO t VALUES (1, 'x'), (2, NULL)")
    srv = PgServer(db, port=0)
    loop = asyncio.new_event_loop()
    import threading

    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def go():
            await srv.start()
            started.set()
            await asyncio.Event().wait()
        try:
            loop.run_until_complete(go())
        except RuntimeError:
            pass
    t = threading.Thread(target=run, daemon=True)
    t.start()
    started.wait(10)
    yield srv
    loop.call_soon_threadsafe(loop.stop)


def test_startup_and_simple_query(server):
    c = RawPg(server.port)
    assert c.params.get("server_encoding") == "UTF8"
    cols, rows, tags, errs = c.query("SELECT a, s FROM t ORDER BY a")
    assert cols == ["a", "s"]
    assert rows == [("1", "x"), ("2", None)]
    assert tags == ["SELECT 2"]
    assert not errs
    c.close()


def test_multi_statement_and_tags(server):
    c = RawPg(server.port)
    cols, rows, tags, errs = c.query("SELECT 1; SELECT 2;")
    assert tags == ["SELECT 1", "SELECT 1"]
    assert rows == [("1",), ("2",)]
    c.close()


def test_error_has_sqlstate(server):
    c = RawPg(server.port)
    _, _, _, errs = c.query("SELECT * FROM missing_table")
    assert errs and errs[0]["C"] == "42P01"
    # session still usable after error
    _, rows, _, _ = c.query("SELECT 42")
    assert rows == [("42",)]
    c.close()


def test_extended_protocol_with_params(server):
    c = RawPg(server.port)
    cols, rows, tags, errs = c.extended(
        "SELECT a, s FROM t WHERE a > $1 ORDER BY a", (0,))
    assert not errs, errs
    assert rows == [("1", "x"), ("2", None)]
    cols, rows, tags, errs = c.extended(
        "SELECT a FROM t WHERE s = $1", ("x",))
    assert rows == [("1",)]
    c.close()


def test_extended_error_then_sync_recovers(server):
    c = RawPg(server.port)
    _, _, _, errs = c.extended("SELECT * FROM nope")
    assert errs and errs[0]["C"] == "42P01"
    _, rows, _, errs = c.extended("SELECT 7")
    assert rows == [("7",)] and not errs
    c.close()


def test_password_auth():
    db = Database()
    srv = PgServer(db, port=0, password="sesame")
    loop = asyncio.new_event_loop()
    import threading
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def go():
            await srv.start()
            started.set()
            await asyncio.Event().wait()
        try:
            loop.run_until_complete(go())
        except RuntimeError:
            pass
    threading.Thread(target=run, daemon=True).start()
    started.wait(10)
    c = RawPg(srv.port, password="sesame")
    _, rows, _, _ = c.query("SELECT 1")
    assert rows == [("1",)]
    c.close()
    with pytest.raises(AssertionError):
        RawPg(srv.port, password=None)
    loop.call_soon_threadsafe(loop.stop)


def test_transaction_status_bytes(server):
    c = RawPg(server.port)
    c.query("BEGIN")
    assert c.status == b"T"
    c.query("SELECT broken syntax here from")
    assert c.status == b"E"   # failed transaction block
    _, _, _, errs = c.query("SELECT 1")
    assert errs and errs[0]["C"] == "25P02"
    c.query("ROLLBACK")
    assert c.status == b"I"
    c.close()


def test_copy_from_stdin_and_to_stdout(server):
    c = RawPg(server.port)
    c.query("CREATE TABLE cp (a INT, s TEXT)")
    # COPY FROM STDIN: expect CopyInResponse then send data
    c.send(b"Q", b"COPY cp FROM STDIN\x00")
    kind, payload = c.read_msg()
    assert kind == b"G", kind
    c.send(b"d", b"1\thello\n2\t\\N\n")
    c.send(b"c")
    tags = []
    while True:
        kind, payload = c.read_msg()
        if kind == b"C":
            tags.append(payload[:-1].decode())
        elif kind == b"Z":
            break
    assert tags == ["COPY 2"]
    _, rows, _, _ = c.query("SELECT a, s FROM cp ORDER BY a")
    assert rows == [("1", "hello"), ("2", None)]
    # COPY TO STDOUT
    c.send(b"Q", b"COPY cp TO STDOUT\x00")
    kind, payload = c.read_msg()
    assert kind == b"H"
    data = []
    while True:
        kind, payload = c.read_msg()
        if kind == b"d":
            data.append(payload)
        elif kind == b"c":
            pass
        elif kind == b"C":
            assert payload[:-1] == b"COPY 2"
        elif kind == b"Z":
            break
    assert b"".join(data) == b"1\thello\n2\t\\N\n"
    c.query("DROP TABLE cp")
    c.close()


def test_copy_literal_backslash_n_roundtrip(server):
    c = RawPg(server.port)
    c.query("CREATE TABLE cpb (s TEXT)")
    c.send(b"Q", b"COPY cpb FROM STDIN\x00")
    k, _ = c.read_msg(); assert k == b"G"
    # literal backslash-N is escaped as \\N — must NOT become NULL
    c.send(b"d", b"\\\\N\n\\N\nplain\n")
    c.send(b"c")
    while True:
        k, p = c.read_msg()
        if k == b"Z":
            break
    _, rows, _, _ = c.query(
        "SELECT s IS NULL, coalesce(s, '<null>') FROM cpb")
    got = sorted(rows)
    assert ("f", "\\N") in got       # the literal two-char value survives
    assert ("t", "<null>") in got    # the bare marker is NULL
    assert ("f", "plain") in got
    c.query("DROP TABLE cpb")
    c.close()


def test_copy_rejected_in_aborted_txn(server):
    c = RawPg(server.port)
    c.query("CREATE TABLE cpt (a INT)")
    c.query("BEGIN")
    c.query("SELECT broken from syntax here")
    c.send(b"Q", b"COPY cpt FROM STDIN\x00")
    errs = []
    while True:
        k, p = c.read_msg()
        if k == b"E":
            errs.append(_parse_err(p))
        elif k == b"G":
            raise AssertionError("CopyInResponse in aborted txn")
        elif k == b"Z":
            break
    assert errs and errs[0]["C"] == "25P02"
    c.query("ROLLBACK")
    assert c.query("SELECT count(*) FROM cpt")[1] == [("0",)]
    c.query("DROP TABLE cpt")
    c.close()


def test_portal_row_paging_with_suspension(server):
    c = RawPg(server.port)
    c.query("CREATE TABLE pg_page (n INT)")
    c.query("INSERT INTO pg_page VALUES (1),(2),(3),(4),(5)")
    # Parse + Bind once, Execute with max_rows=2 repeatedly
    c.send(b"P", b"cur\x00SELECT n FROM pg_page ORDER BY n\x00\x00\x00")
    c.send(b"B", b"p1\x00cur\x00" + struct.pack("!HHH", 0, 0, 0))
    rows, suspended, complete = [], 0, 0
    for _ in range(4):
        c.send(b"E", b"p1\x00" + struct.pack("!I", 2))
        c.send(b"H")
        while True:
            kind, payload = c.read_msg()
            if kind == b"D":
                (ncols,) = struct.unpack("!H", payload[:2])
                (ln,) = struct.unpack("!i", payload[2:6])
                rows.append(payload[6:6 + ln].decode())
            elif kind == b"s":
                suspended += 1
                break
            elif kind == b"C":
                complete += 1
                break
            elif kind in (b"1", b"2"):
                continue
        if complete:
            break
    c.send(b"S")
    while c.read_msg()[0] != b"Z":
        pass
    assert rows == ["1", "2", "3", "4", "5"]
    assert suspended == 2 and complete == 1
    c.query("DROP TABLE pg_page")
    c.close()


class TestBinaryResults:
    def _extended_raw(self, pg, sql, rfmts, params=()):
        """Parse/Bind(with result formats)/Execute/Sync; raw value bytes."""
        pg.send(b"P", b"\x00" + sql.encode() + b"\x00" + b"\x00\x00")
        parts = [b"\x00", b"\x00", struct.pack("!H", 0),
                 struct.pack("!H", len(params))]
        for p in params:
            enc = str(p).encode()
            parts.append(struct.pack("!i", len(enc)) + enc)
        parts.append(struct.pack("!H", len(rfmts)))
        parts.extend(struct.pack("!h", f) for f in rfmts)
        pg.send(b"B", b"".join(parts))
        pg.send(b"D", b"P\x00")
        pg.send(b"E", b"\x00" + struct.pack("!I", 0))
        pg.send(b"S")
        rows, errs, desc_fmts = [], [], []
        while True:
            kind, payload = pg.read_msg()
            if kind == b"T":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                for _ in range(n):
                    end = payload.index(b"\x00", off)
                    off = end + 1 + 18
                    desc_fmts.append(struct.unpack(
                        "!h", payload[off - 2:off])[0])
            elif kind == b"D":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                row = []
                for _ in range(n):
                    (ln,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln])
                        off += ln
                rows.append(row)
            elif kind == b"E":
                errs.append(_parse_err(payload))
            elif kind == b"Z":
                return rows, errs, desc_fmts

    def test_all_binary(self, server):
        pg = RawPg(server.port)
        pg.query("CREATE TABLE bin (b BOOL, i INT, l BIGINT, d DOUBLE, "
                 "s TEXT)")
        pg.query("INSERT INTO bin VALUES (true, -7, 5000000000, 2.5, 'hi'),"
                 " (false, NULL, 1, -0.5, NULL)")
        rows, errs, fmts = self._extended_raw(
            pg, "SELECT b, i, l, d, s FROM bin ORDER BY i NULLS LAST", [1])
        assert not errs and fmts == [1, 1, 1, 1, 1]
        assert rows[0][0] == b"\x01"
        assert struct.unpack("!i", rows[0][1])[0] == -7
        assert struct.unpack("!q", rows[0][2])[0] == 5000000000
        assert struct.unpack("!d", rows[0][3])[0] == 2.5
        assert rows[0][4] == b"hi"
        assert rows[1][0] == b"\x00" and rows[1][1] is None \
            and rows[1][4] is None
        pg.close()

    def test_per_column_formats(self, server):
        pg = RawPg(server.port)
        rows, errs, fmts = self._extended_raw(
            pg, "SELECT 300, 'x', 1.5", [1, 0, 1])
        assert not errs and fmts == [1, 0, 1]
        assert struct.unpack("!i", rows[0][0])[0] == 300
        assert rows[0][1] == b"x"
        assert struct.unpack("!d", rows[0][2])[0] == 1.5
        pg.close()

    def test_binary_timestamp_date(self, server):
        pg = RawPg(server.port)
        rows, errs, _ = self._extended_raw(
            pg, "SELECT TIMESTAMP '2000-01-01 00:00:01', "
                "DATE '2000-01-02'", [1])
        assert not errs
        assert struct.unpack("!q", rows[0][0])[0] == 1_000_000
        assert struct.unpack("!i", rows[0][1])[0] == 1
        pg.close()

    def test_invalid_format_code(self, server):
        pg = RawPg(server.port)
        rows, errs, _ = self._extended_raw(pg, "SELECT 1", [7])
        assert errs and errs[0]["C"] == "08P01"
        pg.close()

    def test_text_default_unchanged(self, server):
        pg = RawPg(server.port)
        rows, errs, fmts = self._extended_raw(pg, "SELECT 42", [])
        assert not errs and fmts == [0] and rows[0][0] == b"42"
        pg.close()


def test_truncated_bind_result_formats(server):
    # declared 3 format codes, sent 1: must answer 08P01, not kill the
    # session
    pg = RawPg(server.port)
    pg.send(b"P", b"\x00SELECT 1\x00\x00\x00")
    body = (b"\x00\x00" + struct.pack("!H", 0) + struct.pack("!H", 0) +
            struct.pack("!H", 3) + struct.pack("!h", 1))
    pg.send(b"B", body)
    pg.send(b"S")
    errs = []
    while True:
        kind, payload = pg.read_msg()
        if kind == b"E":
            errs.append(_parse_err(payload))
        elif kind == b"Z":
            break
    assert errs and errs[0]["C"] == "08P01"
    cols, rows, tags, qerrs = pg.query("SELECT 7")
    assert rows == [("7",)] and not qerrs
    pg.close()


def test_scram_auth_role_password(server):
    pg0 = RawPg(server.port)
    pg0.query("CREATE ROLE scrammy LOGIN PASSWORD 'tops3cret'")
    # correct password over SCRAM
    pg = RawPg(server.port, user="scrammy", password="tops3cret")
    cols, rows, tags, errs = pg.query("SELECT 1")
    assert rows == [("1",)] and not errs
    pg.close()
    # wrong password rejected
    with pytest.raises(AssertionError):
        RawPg(server.port, user="scrammy", password="wrong")
    pg0.query("DROP ROLE scrammy")
    pg0.close()


def _run_pg_server(db, password=None, **kwargs):
    """Start a PgServer via its real start() in a thread; returns
    (srv, stop_fn) — same bootstrap the module `server` fixture uses."""
    import threading
    srv = PgServer(db, port=0, password=password, **kwargs)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def go():
            await srv.start()
            started.set()
            await asyncio.Event().wait()
        try:
            loop.run_until_complete(go())
        except RuntimeError:
            pass
    t = threading.Thread(target=run, daemon=True)
    t.start()
    started.wait(10)
    return srv, (lambda: loop.call_soon_threadsafe(loop.stop))


def test_scram_server_password():
    srv, stop = _run_pg_server(Database(), password="gatekeeper")
    try:
        pg = RawPg(srv.port, user="serene", password="gatekeeper")
        cols, rows, tags, errs = pg.query("SELECT 2")
        assert rows == [("2",)]
        pg.close()
        with pytest.raises(AssertionError):
            RawPg(srv.port, user="serene", password="nope")
    finally:
        stop()


def test_scram_saslprep_unicode_password():
    # U+00A0 no-break space must normalize to a plain space on both sides
    # (RFC 4013 / pg_saslprep) so drivers that normalize interoperate
    srv, stop = _run_pg_server(Database(), password="pa\u00a0ss")
    try:
        pg = RawPg(srv.port, user="serene", password="pa ss")
        assert pg.query("SELECT 5")[1] == [("5",)]
        pg.close()
        with pytest.raises(AssertionError):
            RawPg(srv.port, user="serene", password="pass")
    finally:
        stop()


def test_scram_login_after_password_rotation():
    db = Database()
    srv, stop = _run_pg_server(db)
    try:
        admin = RawPg(srv.port, user="serene")
        admin.query("CREATE ROLE rotor LOGIN PASSWORD 'first'")
        pg = RawPg(srv.port, user="rotor", password="first")
        pg.close()
        admin.query("ALTER ROLE rotor PASSWORD 'second'")
        with pytest.raises(AssertionError):
            RawPg(srv.port, user="rotor", password="first")
        pg = RawPg(srv.port, user="rotor", password="second")
        assert pg.query("SELECT 1")[1] == [("1",)]
        pg.close()
        admin.close()
    finally:
        stop()


def test_listen_notify(server):
    listener = RawPg(server.port)
    sender = RawPg(server.port)
    assert listener.query("LISTEN events")[2] == ["LISTEN"]
    assert sender.query("NOTIFY events, 'payload-1'")[2] == ["NOTIFY"]
    # notification arrives at the listener's next statement boundary
    listener.send(b"Q", b"SELECT 1\x00")
    got = []
    while True:
        kind, payload = listener.read_msg()
        if kind == b"A":
            pid = struct.unpack("!I", payload[:4])[0]
            channel, load = payload[4:-1].split(b"\x00")[:2]
            got.append((pid, channel.decode(), load.decode()))
        elif kind == b"Z":
            break
    assert got == [(sender.backend_key[0], "events", "payload-1")]
    # UNLISTEN stops delivery
    listener.query("UNLISTEN events")
    sender.query("NOTIFY events, 'after'")
    kinds = []
    listener.send(b"Q", b"SELECT 1\x00")
    while True:
        kind, _ = listener.read_msg()
        kinds.append(kind)
        if kind == b"Z":
            break
    assert b"A" not in kinds
    # notify with no listeners is a no-op; self-notify works
    sender.query("NOTIFY nowhere")
    sender.query("LISTEN selfchan")
    # self-notify is delivered at the NOTIFY's own statement boundary
    sender.send(b"Q", b"NOTIFY selfchan, 'me'\x00")
    got = []
    while True:
        kind, payload = sender.read_msg()
        if kind == b"A":
            got.append(payload[4:-1].split(b"\x00")[1].decode())
        elif kind == b"Z":
            break
    assert got == ["me"]
    listener.close()
    sender.close()


def test_notify_pushed_to_idle_listener(server):
    import select
    lis, snd = RawPg(server.port), RawPg(server.port)
    lis.query("LISTEN idlechan")
    snd.query("NOTIFY idlechan, 'wake'")
    # listener sends NOTHING: the 'A' must arrive as an async push
    ready, _, _ = select.select([lis.sock], [], [], 5.0)
    assert ready, "no async NotificationResponse within 5s"
    kind, payload = lis.read_msg()
    assert kind == b"A"
    assert payload[4:-1].split(b"\x00")[:2] == [b"idlechan", b"wake"]
    lis.close()
    snd.close()


def test_notify_in_txn_is_transactional(server):
    lis, snd = RawPg(server.port), RawPg(server.port)
    lis.query("LISTEN txchan")
    snd.query("BEGIN")
    snd.query("NOTIFY txchan, 'rolled-back'")
    snd.query("ROLLBACK")
    snd.query("BEGIN")
    snd.query("NOTIFY txchan, 'committed'")
    snd.query("COMMIT")
    import select
    ready, _, _ = select.select([lis.sock], [], [], 5.0)
    assert ready
    kind, payload = lis.read_msg()
    assert kind == b"A"
    # only the committed txn's notification arrives
    assert payload[4:-1].split(b"\x00")[1] == b"committed"
    # nothing else pending
    ready, _, _ = select.select([lis.sock], [], [], 0.3)
    assert not ready
    lis.close()
    snd.close()


def test_returning_described_in_extended_protocol(server):
    pg = RawPg(server.port)
    pg.query("CREATE TABLE retd (a INT, b TEXT)")
    cols, rows, tags, errs = pg.extended(
        "INSERT INTO retd VALUES ($1, 'p') RETURNING a, b", ["5"])
    assert not errs
    assert cols == ["a", "b"]          # Describe produced RowDescription
    assert rows == [("5", "p")]
    pg.query("DROP TABLE retd")
    pg.close()


# -- streaming wire collector (reference: wire_collector.h:20-60) -----------

def test_streaming_select_flushes_per_batch():
    """A large SELECT must stream: multiple flushes (one per executor
    batch), not one materialized send."""
    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.exec.tables import MemTable
    from serenedb_tpu.server import pgwire as pgwire_mod

    db = Database()
    n = 400_000   # > 3 executor batches of 2^17 rows
    batch = Batch.from_pydict({
        "id": Column.from_numpy(np.arange(n, dtype=np.int64))})
    db.schemas["main"].tables["big"] = MemTable("big", batch)
    srv, stop = _run_pg_server(db)
    flushes = []
    orig_flush = pgwire_mod.Writer.flush

    async def counting_flush(self):
        flushes.append(1)
        await orig_flush(self)
    pgwire_mod.Writer.flush = counting_flush
    try:
        pg = RawPg(srv.port)
        before = len(flushes)
        cols, rows, tags, errs = pg.query("SELECT id FROM big")
        assert len(rows) == n
        assert tags == [f"SELECT {n}"]
        # at least one flush per executor batch (4 batches for 400k rows)
        assert len(flushes) - before >= 4
        pg.close()
    finally:
        pgwire_mod.Writer.flush = orig_flush
        stop()


def test_streaming_select_midstream_error():
    """An error in a later batch arrives after earlier DataRows; the
    session stays usable (ErrorResponse then ReadyForQuery)."""
    import numpy as np

    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.exec.tables import MemTable

    db = Database()
    n = 300_000
    ids = np.arange(n, dtype=np.int64)
    batch = Batch.from_pydict({"id": Column.from_numpy(ids)})
    db.schemas["main"].tables["big2"] = MemTable("big2", batch)
    srv, stop = _run_pg_server(db)
    try:
        pg = RawPg(srv.port)
        # division by zero on a row in the third executor batch
        cols, rows, tags, errs = pg.query(
            "SELECT 100 / (id - 280000) FROM big2")
        assert errs, "expected a mid-stream error"
        assert len(rows) >= (1 << 17), "rows before the error must stream"
        assert not tags     # no CommandComplete after an error
        # session still alive
        assert pg.query("SELECT 5")[1] == [("5",)]
        pg.close()
    finally:
        stop()


class TestProxyProtocol:
    """HAProxy PROXY v1/v2 preface (reference: proxy_protocol.cpp)."""

    def _server(self, mode):
        import asyncio
        import threading

        from serenedb_tpu.engine import Database
        from serenedb_tpu.server.pgwire import PgServer
        db = Database(None)
        srv = PgServer(db, "127.0.0.1", 0, proxy_protocol=mode)
        loop = asyncio.new_event_loop()
        started = threading.Event()
        port = {}

        async def boot():
            server = await asyncio.start_server(
                lambda r, w: __import__(
                    "serenedb_tpu.server.pgwire",
                    fromlist=["PgSession"]).PgSession(srv, r, w).run(),
                "127.0.0.1", 0)
            port["p"] = server.sockets[0].getsockname()[1]
            started.set()
            async with server:
                await server.serve_forever()

        t = threading.Thread(target=lambda: loop.run_until_complete(boot()),
                             daemon=True)
        t.start()
        started.wait(10)
        return port["p"]

    def _query(self, port, sql, preface=b""):
        import socket
        import struct as st
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        if preface:
            s.sendall(preface)
        body = st.pack("!i", 196608) + b"user\x00u\x00database\x00d\x00\x00"
        s.sendall(st.pack("!i", len(body) + 4) + body)

        def read_msg():
            t = s.recv(1)
            if not t:
                raise ConnectionError("closed")
            ln = st.unpack("!i", s.recv(4))[0]
            p = b""
            while len(p) < ln - 4:
                p += s.recv(ln - 4 - len(p))
            return t, p

        while True:
            t, p = read_msg()
            if t == b"Z":
                break
        b2 = sql.encode() + b"\x00"
        s.sendall(b"Q" + st.pack("!i", len(b2) + 4) + b2)
        rows = []
        while True:
            t, p = read_msg()
            if t == b"D":
                rows.append(p)
            elif t == b"Z":
                s.close()
                return rows

    def test_v1_preface(self):
        port = self._server("optional")
        rows = self._query(port, "SELECT 1",
                           b"PROXY TCP4 10.1.2.3 10.0.0.1 5555 5432\r\n")
        assert len(rows) == 1

    def test_v2_preface(self):
        import struct as st
        port = self._server("optional")
        sig = b"\r\n\r\n\x00\r\nQUIT\n"
        addr = (bytes([10, 1, 2, 3]) + bytes([10, 0, 0, 1]) +
                st.pack("!HH", 5555, 5432))
        preface = sig + bytes([0x21, 0x11]) + st.pack("!H", len(addr)) + addr
        rows = self._query(port, "SELECT 1", preface=preface)
        assert len(rows) == 1

    def test_optional_without_preface(self):
        port = self._server("optional")
        assert len(self._query(port, "SELECT 1")) == 1

    def test_require_rejects_plain(self):
        import pytest
        port = self._server("require")
        with pytest.raises((ConnectionError, OSError)):
            self._query(port, "SELECT 1")
