"""bench.py harness contract tests (no device dispatch).

The one-JSON-line contract and the BENCH_LEDGER.json fallback (device
evidence captured opportunistically during the round must surface,
marked stale, when the round-end liveness probe fails — VERDICT r4 #1).
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "LEDGER_PATH", str(tmp_path / "LEDGER.json"))
    monkeypatch.setattr(mod, "_LOCK_PATH", str(tmp_path / "bench.lock"))
    monkeypatch.setattr(mod, "_STOP_PATH", str(tmp_path / "ledger_stop"))
    return mod


def test_ledger_roundtrip(bench):
    led = bench._load_ledger()
    assert led == {"entries": {}}
    led["entries"]["q1"] = {"speedup": 3.5, "ts": "t", "git": "g"}
    bench._save_ledger(led)
    assert bench._load_ledger()["entries"]["q1"]["speedup"] == 3.5
    import glob
    assert glob.glob(bench.LEDGER_PATH + ".*.tmp") == []


def test_ledger_corrupt_file_is_empty(bench):
    with open(bench.LEDGER_PATH, "w") as f:
        f.write("{not json")
    assert bench._load_ledger() == {"entries": {}}


def _run_main(bench, capsys):
    bench.main()
    lines = capsys.readouterr().out.strip().splitlines()
    return json.loads(lines[-1])


def _now_iso():
    import datetime
    return datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds")


def test_main_falls_back_to_ledger_when_device_dead(bench, capsys,
                                                    monkeypatch):
    bench._save_ledger({"entries": {
        "q1": {"speedup": 4.0, "ts": _now_iso(),
               "git": "abc", "extra": {"cold_s": 1.5}},
        "bm25": {"speedup": 2.25, "ts": _now_iso(),
                 "git": "abc", "extra": {}},
    }})
    monkeypatch.setattr(bench, "_probe_device",
                        lambda t=75.0: (False, True, "tunnel down"))
    monkeypatch.setenv("SDB_BENCH_BUDGET_S", "1")
    out = _run_main(bench, capsys)
    assert out["stale"] is True
    assert sorted(out["stale_shapes"]) == ["bm25", "q1"]
    assert out["value"] == 3.0  # geomean(4.0, 2.25)
    assert out["vs_baseline"] == 3.0
    assert out["detail"]["q1_speedup"] == 4.0
    assert out["detail"]["q1_cold_s"] == 1.5
    assert out["detail"]["q1_ledger_git"] == "abc"
    assert "device" in out["errors"]


def test_main_no_ledger_no_device_reports_zero(bench, capsys, monkeypatch):
    monkeypatch.setattr(bench, "_probe_device",
                        lambda t=75.0: (False, True, "tunnel down"))
    monkeypatch.setenv("SDB_BENCH_BUDGET_S", "1")
    out = _run_main(bench, capsys)
    assert out["value"] == 0.0
    assert "stale" not in out


def test_live_results_preferred_over_ledger(bench, capsys, monkeypatch):
    bench._save_ledger({"entries": {
        "q1": {"speedup": 99.0, "ts": _now_iso(), "git": "old",
               "extra": {}}}})
    monkeypatch.setattr(bench, "_probe_device",
                        lambda t=75.0: (True, False, ""))
    monkeypatch.setattr(
        bench, "_run_shape_subprocess",
        lambda name, timeout_s, **kw: ({"speedup": 5.0, "extra": {}}, "")
        if name == "q1" else ({}, "boom"))
    monkeypatch.setenv("SDB_BENCH_BUDGET_S", "100000")
    out = _run_main(bench, capsys)
    assert out["detail"]["q1_speedup"] == 5.0  # live beats ledger
    assert "q1" not in out.get("stale_shapes", [])


def test_deterministic_shape_failure_does_not_use_ledger(bench, capsys,
                                                         monkeypatch):
    """A parity-assertion crash in the CURRENT code must surface as an
    error, not be papered over by an old passing ledger number."""
    bench._save_ledger({"entries": {
        "q1": {"speedup": 4.0, "ts": _now_iso(), "git": "abc",
               "extra": {}}}})
    monkeypatch.setattr(bench, "_probe_device",
                        lambda t=75.0: (True, False, ""))
    monkeypatch.setattr(
        bench, "_run_shape_subprocess",
        lambda name, timeout_s, **kw:
        ({}, "AssertionError: device/CPU result mismatch in Q1 bench"))
    monkeypatch.setenv("SDB_BENCH_BUDGET_S", "100000")
    out = _run_main(bench, capsys)
    assert "q1_speedup" not in out["detail"]
    assert out["value"] == 0.0
    assert "mismatch" in out["errors"]["q1"]
    assert "stale" not in out


def test_timeout_failure_does_use_ledger(bench, capsys, monkeypatch):
    bench._save_ledger({"entries": {
        "q1": {"speedup": 4.0, "ts": _now_iso(), "git": "abc",
               "extra": {}}}})
    monkeypatch.setattr(bench, "_probe_device",
                        lambda t=75.0: (True, False, ""))
    monkeypatch.setattr(
        bench, "_run_shape_subprocess",
        lambda name, timeout_s, **kw:
        ({}, "timeout: shape timed out (device hang mid-run?)"))
    monkeypatch.setenv("SDB_BENCH_BUDGET_S", "100000")
    out = _run_main(bench, capsys)
    assert out["detail"]["q1_speedup"] == 4.0
    assert "q1" in out["stale_shapes"]


def test_expired_ledger_entry_rejected(bench, capsys, monkeypatch):
    bench._save_ledger({"entries": {
        "q1": {"speedup": 4.0, "ts": "2026-07-01T00:00:00+00:00",
               "git": "abc", "extra": {}}}})
    monkeypatch.setattr(bench, "_probe_device",
                        lambda t=75.0: (False, True, "tunnel down"))
    monkeypatch.setenv("SDB_BENCH_BUDGET_S", "1")
    out = _run_main(bench, capsys)
    assert out["value"] == 0.0
    assert "expired" in out["errors"]["q1"]
