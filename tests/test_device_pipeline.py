"""Parity suite for the fused device relational pipeline (ISSUE 7).

Contract under test: `serene_device_fused = on` (the default) compiles
Scan→Filter→Join→Aggregate chains and filtered top-N into ONE jitted
device program (exec/device_pipeline.py) whose results are BIT-IDENTICAL
to the host oracle (`serene_device_fused = off`) across the full matrix —
fused on/off × `serene_workers` 1/N × `serene_zonemap` on/off — including
NULL and NaN join keys, dictionary-encoded strings, and empty /
all-zone-pruned scans. Plus the publication-keyed device column cache:
repeat queries hit HBM-resident uploads, any write moves the key, the
byte cap LRU-evicts, and superseded generations are swept on store.
"""

import numpy as np
import pytest

from serenedb_tpu.columnar import dtypes as dt
from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.exec.tables import MemTable
from serenedb_tpu.utils import metrics
from serenedb_tpu.utils.config import REGISTRY as SETTINGS


def _mk_conn(nl=6000, nr=3000, seed=3):
    """Two joinable tables covering every key/arg dtype the matrix
    needs: INT keys with NULLs, dictionary TEXT, DOUBLE keys with NULLs
    and NaNs, clustered BIGINT for zone-map pruning, int payloads."""
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE l (ik INT, sk TEXT, fk DOUBLE, ts BIGINT, "
              "v BIGINT, bv BIGINT)")
    c.execute("CREATE TABLE r (ik INT, sk TEXT, fk DOUBLE, w BIGINT, "
              "bv BIGINT)")

    def mk(n, null_frac, sd, payload, with_ts):
        rng = np.random.default_rng(sd)
        ik = rng.integers(0, 40, n).astype(np.int32)
        ikv = rng.random(n) > null_frac
        fk = np.round(rng.normal(size=n), 1)    # rounding ⇒ cross-side dups
        fk[rng.random(n) < 0.05] = np.nan
        fkv = rng.random(n) > 0.1
        cols = {
            "ik": Column(dt.INT, ik, ikv),
            "sk": Column.from_numpy(
                rng.choice(["alpha", "beta", "gamma", "delta"], n)),
            "fk": Column(dt.DOUBLE, fk, fkv),
        }
        if with_ts:
            cols["ts"] = Column.from_numpy(np.arange(n, dtype=np.int64))
        cols[payload] = Column.from_numpy(
            rng.integers(-500, 500, n, dtype=np.int64))
        # wide values: |bv|·pairs overflows the direct-scatter bound, so
        # plain-column sums of bv exercise the limb path
        cols["bv"] = Column.from_numpy(
            rng.integers(-(10 ** 9), 10 ** 9, n, dtype=np.int64))
        return Batch.from_pydict(cols)

    db.schemas["main"].tables["l"] = MemTable(
        "l", mk(nl, 0.1, seed, "v", True))
    db.schemas["main"].tables["r"] = MemTable(
        "r", mk(nr, 0.15, seed + 1, "w", False))
    c.execute("SET serene_device = 'tpu'")       # force the device tier
    c.execute("SET serene_device_fused = on")    # deterministic vs globals
    c.execute("SET serene_result_cache = off")   # assert EXECUTION internals
    c.execute("SET serene_morsel_rows = 1024")   # zone maps at test size
    c.execute("SET serene_parallel_min_rows = 1024")
    return c


def _rows(c, q):
    """repr-keyed capture: bit-identical comparison that still treats a
    NaN as equal to itself (tuple == would fail NaN-bearing rows even
    when both sides are the same bits)."""
    return repr(c.execute(q).rows())


FUSED_QUERIES = [
    # scalar aggregates, both-side args, every admitted function
    "SELECT count(*), sum(v), sum(w), min(v), max(w), avg(v) "
    "FROM l JOIN r ON l.ik = r.ik",
    # probe-side / build-side / both-side filters (scan-level + post-join)
    "SELECT count(*), sum(v) FROM l JOIN r ON l.ik = r.ik WHERE v > 100",
    "SELECT count(*), sum(w) FROM l JOIN r ON l.ik = r.ik WHERE w < 250",
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.ik "
    "WHERE v > 0 AND w < 400",
    # NULL int keys never match (ik has ~10-15% NULLs per side)
    "SELECT count(*), sum(v + w) FROM l JOIN r ON l.ik = r.ik "
    "WHERE v % 2 = 0",
    # dictionary-string join keys
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.sk = r.sk "
    "WHERE v > 350",
    # float keys with NaNs (NaN ≠ NaN, every occurrence its own code)
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.fk = r.fk",
    # composite int+string key
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r "
    "ON l.ik = r.ik AND l.sk = r.sk",
    # grouped: dictionary-string key, int key, composite — probe side
    "SELECT l.sk, count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.ik "
    "GROUP BY l.sk ORDER BY l.sk",
    "SELECT l.ik, count(*), min(w), max(w) FROM l JOIN r ON l.ik = r.ik "
    "WHERE v > -250 GROUP BY l.ik ORDER BY l.ik NULLS LAST",
    "SELECT l.sk, l.ik, count(*), avg(w) FROM l JOIN r ON l.ik = r.ik "
    "GROUP BY l.sk, l.ik ORDER BY l.sk, l.ik NULLS LAST",
    # count(col) with NULL-bearing argument on each side
    "SELECT count(l.ik), count(r.fk) FROM l JOIN r ON l.sk = r.sk "
    "WHERE v > 440",
    # wide-value plain-column sums: |bv|·pairs overflows the direct
    # bound, forcing the limb decomposition on both sides
    "SELECT l.sk, sum(l.bv), sum(r.bv) FROM l JOIN r ON l.ik = r.ik "
    "WHERE v > 0 GROUP BY l.sk ORDER BY l.sk",
    "SELECT count(*), sum(l.bv), avg(r.bv) FROM l JOIN r ON l.sk = r.sk",
    # zone-prunable clustered predicate feeding the join
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.ik "
    "WHERE ts < 1500",
    "SELECT l.sk, count(*) FROM l JOIN r ON l.ik = r.ik "
    "WHERE ts >= 2048 AND ts < 3072 GROUP BY l.sk ORDER BY l.sk",
]

TOPN_QUERIES = [
    "SELECT * FROM l WHERE v > 250 ORDER BY v DESC LIMIT 7",
    "SELECT * FROM l WHERE v > 250 ORDER BY v LIMIT 7",
    "SELECT * FROM l WHERE sk = 'beta' AND v < 0 ORDER BY ts DESC LIMIT 5",
    "SELECT * FROM l WHERE ts < 900 ORDER BY ts LIMIT 4 OFFSET 2",
    # zone-prunable filter + top-N
    "SELECT * FROM l WHERE ts >= 5000 ORDER BY v DESC LIMIT 3",
]


@pytest.mark.parametrize("q", FUSED_QUERIES)
def test_fused_join_agg_parity(q):
    c = _mk_conn()
    c.execute("SET serene_device_fused = off")
    c.execute("SET serene_workers = 1")
    oracle = _rows(c, q)
    c.execute("SET serene_device_fused = on")
    for workers in (1, 4):
        c.execute(f"SET serene_workers = {workers}")
        for zm in ("on", "off"):
            c.execute(f"SET serene_zonemap = {zm}")
            got = _rows(c, q)
            assert got == oracle, \
                f"fused pipeline diverged (workers={workers}, zonemap={zm})"


@pytest.mark.parametrize("q", TOPN_QUERIES)
def test_fused_topn_parity(q):
    c = _mk_conn()
    c.execute("SET serene_device_fused = off")
    oracle = _rows(c, q)
    c.execute("SET serene_device_fused = on")
    for zm in ("on", "off"):
        c.execute(f"SET serene_zonemap = {zm}")
        assert _rows(c, q) == oracle, f"fused top-N diverged (zonemap={zm})"


def test_fused_topn_projection_expr_falls_back():
    """The host oracle evaluates projection expressions over EVERY
    filter-surviving row before sorting; the fused path selects its k
    rows first. An expression that raises on a surviving row OUTSIDE
    the top k must therefore raise identically in both modes — computed
    projections decline the fused path."""
    from serenedb_tpu import errors
    c = _mk_conn()
    c.execute("CREATE TABLE pz (a BIGINT, b BIGINT)")
    c.execute("INSERT INTO pz VALUES (1, 1), (2, 1), (3, 1), (9, 0)")
    q = "SELECT a, 100 / b FROM pz WHERE a > 0 ORDER BY a LIMIT 2"
    for mode in ("off", "on"):
        c.execute(f"SET serene_device_fused = {mode}")
        with pytest.raises(errors.SqlError, match="division by zero"):
            c.execute(q)
    # plain column selection/reorder still compiles
    before = metrics.DEVICE_OFFLOADS.value
    rows = c.execute(
        "SELECT v, ts FROM l WHERE v > 250 ORDER BY v DESC LIMIT 7").rows()
    assert metrics.DEVICE_OFFLOADS.value == before + 1
    c.execute("SET serene_device_fused = off")
    assert repr(c.execute(
        "SELECT v, ts FROM l WHERE v > 250 ORDER BY v DESC LIMIT 7"
    ).rows()) == repr(rows)


def test_fragment_cache_drains_dead_segments_when_gated_off():
    """Finalizer-enqueued drops must reclaim bytes on the next cached()
    call even when the session gate is off — the deferred-drop design
    may not retain dead-segment arrays for the process lifetime."""
    from serenedb_tpu.cache import fragments as fr
    store = fr.FragmentCache()
    seg = type("Seg", (), {})()
    arr = np.arange(1024, dtype=np.int64)
    store.cached(seg, ("sig", 1), lambda: arr)
    assert store.stats()["entries"] == 1
    uid = seg._frag_uid
    store.drop_segment(uid)            # what the weakref finalizer does
    # gate off: early return — but the drain must already have happened
    store.cached(seg, None, lambda: 0)
    with store._lock:
        assert uid not in store._seg_keys
    assert store._lru.get((uid, ("sig", 1))) is None


def test_fused_path_actually_fires():
    """The canonical join→agg and filtered top-N shapes must offload —
    not silently fall back to the host oracle. Under the sharded tier
    (verify_tier1.sh pass 8 forces SERENE_SHARDS=4 globally) the fused
    join is one build dispatch plus one probe dispatch per non-empty
    shard with the host combine, and ONE collective shard_map dispatch
    with serene_shard_combine resolving to device; top-N stays a single
    dispatch either way."""
    from serenedb_tpu.exec import shard as shard_mod
    c = _mk_conn()
    shards = int(SETTINGS.get_global("serene_shards"))
    n_blocks = -(-6000 // 1024)            # _mk_conn's probe block count
    if shards <= 1:
        exp_join = 1
    elif shard_mod.combine_mode(None) == "device":
        # cold publication: one build dispatch + ONE collective (the
        # warm repeat is exactly 1, proven in tests/test_multichip.py)
        exp_join = 2
    else:
        exp_join = 1 + min(shards, n_blocks)
    before = metrics.DEVICE_OFFLOADS.value
    c.execute("SELECT l.sk, count(*), sum(v), sum(w) FROM l JOIN r "
              "ON l.ik = r.ik WHERE v > 0 GROUP BY l.sk ORDER BY l.sk")
    assert metrics.DEVICE_OFFLOADS.value == before + exp_join
    c.execute("SELECT * FROM l WHERE v > 250 ORDER BY v DESC LIMIT 7")
    assert metrics.DEVICE_OFFLOADS.value == before + exp_join + 1


def test_fused_off_never_offloads():
    c = _mk_conn()
    c.execute("SET serene_device_fused = off")
    c.execute("SET serene_device = 'cpu'")
    before = metrics.DEVICE_OFFLOADS.value
    c.execute("SELECT count(*), sum(v) FROM l JOIN r ON l.ik = r.ik")
    c.execute("SELECT * FROM l WHERE v > 250 ORDER BY v DESC LIMIT 7")
    assert metrics.DEVICE_OFFLOADS.value == before


def test_empty_and_all_pruned_scans():
    """A genuinely empty side and an all-zone-pruned side both produce
    the host oracle's results (the zero-accumulator short-circuit)."""
    c = _mk_conn()
    c.execute("CREATE TABLE e (ik INT, u BIGINT)")
    for q in [
        "SELECT count(*), sum(v), sum(u) FROM l JOIN e ON l.ik = e.ik",
        "SELECT count(*), sum(u) FROM e JOIN r ON e.ik = r.ik",
        "SELECT l.sk, count(*) FROM l JOIN e ON l.ik = e.ik "
        "GROUP BY l.sk ORDER BY l.sk",
        # ts is clustered 0..5999: ts > 90000 prunes every block
        "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.ik "
        "WHERE ts > 90000",
        "SELECT * FROM l WHERE ts > 90000 ORDER BY v DESC LIMIT 5",
    ]:
        c.execute("SET serene_device_fused = off")
        oracle = _rows(c, q)
        c.execute("SET serene_device_fused = on")
        assert _rows(c, q) == oracle, q


def test_device_cache_hits_and_write_invalidation():
    """Repeat queries serve columns from the device cache (no re-upload);
    any write moves the publication tuple so the next run re-uploads and
    sees fresh data."""
    c = _mk_conn()
    q = ("SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.ik "
         "WHERE v > 0")
    first = _rows(c, q)
    hits0 = metrics.DEVICE_CACHE_HITS.value
    misses0 = metrics.DEVICE_CACHE_MISSES.value
    assert _rows(c, q) == first
    assert metrics.DEVICE_CACHE_HITS.value > hits0
    assert metrics.DEVICE_CACHE_MISSES.value == misses0

    c.execute("INSERT INTO l VALUES (1, 'alpha', 0.5, 99999, 7, 1000)")
    misses1 = metrics.DEVICE_CACHE_MISSES.value
    c.execute("SET serene_device_fused = off")
    oracle = _rows(c, q)
    c.execute("SET serene_device_fused = on")
    fresh = _rows(c, q)
    assert fresh == oracle
    assert fresh != first                       # the write is visible
    assert metrics.DEVICE_CACHE_MISSES.value > misses1


def test_device_cache_lru_eviction_and_generation_sweep():
    """Unit-level DeviceColumnCache: the byte cap LRU-evicts oldest
    first, and storing a newer publication of the same column sweeps the
    superseded generation eagerly."""
    from serenedb_tpu.exec.device_pipeline import DeviceColumnCache
    old_cap = SETTINGS.get_global("serene_device_cache_mb")
    SETTINGS.set_global("serene_device_cache_mb", 1)
    try:
        cache = DeviceColumnCache()
        a = np.zeros(8)
        cache.put(((1, 0, 0), "c1", "col", None), a, 400_000)
        cache.put(((2, 0, 0), "c2", "col", None), a, 400_000)
        ev0 = metrics.DEVICE_CACHE_EVICTIONS.value
        cache.put(((3, 0, 0), "c3", "col", None), a, 400_000)
        # 1.2 MB > 1 MB cap: the oldest entry goes, newer two stay
        assert metrics.DEVICE_CACHE_EVICTIONS.value == ev0 + 1
        assert cache.get(((1, 0, 0), "c1", "col", None)) is None
        assert cache.get(((2, 0, 0), "c2", "col", None)) is not None
        assert cache.get(((3, 0, 0), "c3", "col", None)) is not None

        # generation sweep: same token+column, bumped data_version
        cache.put(((7, 1, 0), "k", "col", None), a, 1000)
        ev1 = metrics.DEVICE_CACHE_EVICTIONS.value
        cache.put(((7, 2, 0), "k", "col", None), a, 1000)
        assert metrics.DEVICE_CACHE_EVICTIONS.value == ev1 + 1
        assert cache.get(((7, 1, 0), "k", "col", None)) is None
        assert cache.get(((7, 2, 0), "k", "col", None)) is not None
    finally:
        SETTINGS.set_global("serene_device_cache_mb", old_cap)


def test_explain_analyze_attributes_device_time():
    """EXPLAIN ANALYZE of a fused query carries per-stage Device: lines
    (transfer + dispatch accounting from the PR 4 profiler)."""
    c = _mk_conn()
    q = ("SELECT l.sk, count(*), sum(v) FROM l JOIN r ON l.ik = r.ik "
         "WHERE v > 0 GROUP BY l.sk ORDER BY l.sk")
    plain = _rows(c, q)
    out = "\n".join(r[0] for r in
                    c.execute(f"EXPLAIN ANALYZE {q}").rows())
    assert "Device: time=" in out
    # and EXPLAIN ANALYZE itself never perturbs results
    assert _rows(c, q) == plain


def test_fused_respects_device_auto_min_rows():
    """Under serene_device = auto, tables below serene_device_min_rows
    stay on host — the fused tier must honor the same admission knob."""
    c = _mk_conn(nl=500, nr=300)
    c.execute("SET serene_device = 'auto'")
    before = metrics.DEVICE_OFFLOADS.value
    c.execute("SELECT count(*), sum(v) FROM l JOIN r ON l.ik = r.ik")
    c.execute("SELECT * FROM l WHERE v > 0 ORDER BY v LIMIT 3")
    assert metrics.DEVICE_OFFLOADS.value == before
