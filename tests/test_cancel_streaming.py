"""Extended-protocol streaming Execute + mid-query cancellation.

Reference parity: wire_collector.h:20-60 (rows leave the socket during
execution), pg_wire_session.h:293-300 (portal row budgets) and
pg_wire_session.h:205-220 (interrupting execution tasks on cancel)."""

import asyncio
import socket
import struct
import sys
import threading
import time

import pytest

sys.path.insert(0, "tests")

from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError
from serenedb_tpu.server.pgwire import PgServer


@pytest.fixture(scope="module")
def server():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE big (i INT, s TEXT)")
    # several executor batches (batch is 128k rows)
    n = 300_000
    import numpy as np
    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.columnar import dtypes as dt
    ints = np.arange(n, dtype=np.int32)
    strs = np.asarray([f"row{i % 1000}x" for i in range(n)], dtype=object)
    t = db.resolve_table(["big"])
    t.append_batch(Batch(["i", "s"], [
        Column(dt.INT, ints),
        Column.from_numpy(strs.astype(str))]))
    srv = PgServer(db, port=0)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def go():
            await srv.start()
            started.set()
            await asyncio.Event().wait()
        try:
            loop.run_until_complete(go())
        except RuntimeError:
            pass
    threading.Thread(target=run, daemon=True).start()
    started.wait(10)
    return srv


def _client(server):
    from test_pgwire import RawPg
    return RawPg(server.port)


def test_extended_streaming_full_fetch(server):
    c = _client(server)
    cols, rows, tags, errs = c.extended(
        "SELECT i FROM big WHERE i < 200000")
    assert not errs
    assert len(rows) == 200_000
    assert tags == ["SELECT 200000"]
    c.close()


def test_extended_portal_row_budget_streams(server):
    c = _client(server)
    c.send(b"P", b"\x00SELECT i FROM big ORDER BY i\x00\x00\x00")
    c.send(b"B", b"\x00\x00" + struct.pack("!H", 0) +
           struct.pack("!H", 0) + struct.pack("!H", 0))
    c.send(b"E", b"\x00" + struct.pack("!I", 5))     # 5-row budget
    c.send(b"H")                                     # Flush
    rows, suspended = [], False
    while True:
        kind, payload = c.read_msg()
        if kind == b"D":
            rows.append(payload)
        elif kind == b"s":
            suspended = True
            break
        elif kind == b"E":
            raise AssertionError(payload)
    assert suspended and len(rows) == 5
    # resume for 3 more
    c.send(b"E", b"\x00" + struct.pack("!I", 3))
    c.send(b"H")
    more = []
    while True:
        kind, payload = c.read_msg()
        if kind == b"D":
            more.append(payload)
        elif kind == b"s":
            break
    assert len(more) == 3
    # fetch the rest (0 = no limit) and complete
    c.send(b"E", b"\x00" + struct.pack("!I", 0))
    c.send(b"S")
    rest, tag = 0, None
    while True:
        kind, payload = c.read_msg()
        if kind == b"D":
            rest += 1
        elif kind == b"C":
            tag = payload[:-1].decode()
        elif kind == b"Z":
            break
    assert rest == 300_000 - 8
    assert tag == "SELECT 300000"
    c.close()


def test_engine_cancel_interrupts_running_query():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE slow (i INT, s TEXT)")
    import numpy as np
    from serenedb_tpu.columnar.column import Batch, Column
    from serenedb_tpu.columnar import dtypes as dt
    n = 400_000
    t = db.resolve_table(["slow"])
    t.append_batch(Batch(["i", "s"], [
        Column(dt.INT, np.arange(n, dtype=np.int32)),
        Column.from_numpy(np.asarray(
            [f"text value {i}" for i in range(n)], dtype=object
        ).astype(str))]))
    timer = threading.Timer(0.2, c.request_cancel)
    timer.start()
    t0 = time.monotonic()
    with pytest.raises(SqlError) as e:
        # regex over every row: seconds of CPU without cancellation
        c.execute("SELECT count(*) FROM slow "
                  "WHERE s ~ '.*value.*9.*7.*' OR s ~ '.*x.*y.*'")
    assert e.value.sqlstate == "57014"
    timer.cancel()
    # next statement runs normally (flag cleared)
    assert c.execute("SELECT count(*) FROM slow").scalar() == n


def test_wire_cancel_request(server):
    c = _client(server)
    assert c.backend_key is not None
    pid, key = c.backend_key

    def fire_cancel():
        time.sleep(0.3)
        s = socket.create_connection(("127.0.0.1", server.port), timeout=10)
        body = struct.pack("!III", 80877102, pid, key)
        s.sendall(struct.pack("!I", len(body) + 4) + body)
        s.close()
    threading.Thread(target=fire_cancel, daemon=True).start()
    cols, rows, tags, errs = c.extended(
        "SELECT count(*) FROM big "
        "WHERE s ~ '.*row.*1.*2.*' OR s ~ '.*x.*0.*9.*'")
    assert errs and errs[0]["C"] == "57014", (errs, tags)
    # session survives: simple query still works
    _, rows2, _, errs2 = c.query("SELECT 1")
    assert not errs2 and rows2 == [("1",)]
    c.close()


class TestCancelableDeviceExecution:
    """Chunked device dispatch: cancel / statement_timeout interrupt a
    long aggregate between chunks instead of waiting out one monolithic
    program (reference: pg_wire_session.h:205-220 interrupt checks)."""

    def _big(self, n=4_000_000):
        import numpy as np

        from serenedb_tpu.columnar import dtypes as dt
        from serenedb_tpu.columnar.column import Batch, Column
        from serenedb_tpu.engine import Database
        from serenedb_tpu.exec.tables import MemTable
        db = Database(None)
        rng = np.random.default_rng(0)
        t = MemTable("big", Batch(
            ["k", "v"],
            [Column(dt.INT, rng.integers(0, 50, n).astype(np.int32)),
             Column(dt.INT, rng.integers(-99, 99, n).astype(np.int32))]))
        db.schemas["main"].tables["big"] = t
        c = db.connect()
        c.execute("SET serene_device = 'device'")
        return db, c

    Q = "SELECT k, count(*), sum(v), min(v), max(v) FROM big GROUP BY k ORDER BY k"

    def test_chunked_parity(self):
        db, c = self._big()
        c.execute("SET serene_device_chunk_rows = 0")
        ref = c.execute(self.Q).rows()
        c.execute("SET serene_device_chunk_rows = 524288")
        assert c.execute(self.Q).rows() == ref

    def test_cancel_mid_aggregate(self):
        import threading
        import time
        db, c = self._big()
        c.execute("SET serene_device_chunk_rows = 262144")
        got = {}

        def run():
            try:
                c.execute(self.Q)
                got["r"] = "completed"
            except Exception as e:
                got["r"] = str(e)

        th = threading.Thread(target=run)
        th.start()
        time.sleep(0.15)
        c.request_cancel()
        th.join(30)
        assert not th.is_alive()
        # either the cancel landed mid-run, or the query was already done
        # (fast machines) — a hang or another error is the failure mode
        assert got["r"] == "completed" or "cancel" in got["r"], got

    def test_statement_timeout_mid_aggregate(self):
        import time

        import pytest

        from serenedb_tpu.errors import SqlError
        db, c = self._big()
        c.execute("SET serene_device_chunk_rows = 262144")
        c.execute("SET statement_timeout = 1")
        t0 = time.monotonic()
        with pytest.raises(SqlError) as e:
            c.execute(self.Q)
        assert "timeout" in str(e.value)
        assert time.monotonic() - t0 < 10
        # and the session recovers once the timeout is lifted
        c.execute("SET statement_timeout = 0")
        assert c.execute("SELECT count(*) FROM big").scalar() == 4_000_000
