"""Analyzer breadth: stemmers, locale text, CJK, synonyms, pipeline,
minhash, and end-to-end non-ASCII indexing + search.

Reference parity surface: libs/iresearch/include/iresearch/analysis/
(text/segmentation/normalizing/collation/stemming/pattern/path_hierarchy/
synonyms/pipeline/union/minhash tokenizers)."""

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.search import analysis
from serenedb_tpu.search.stemmers import (porter2, stem_de, stem_fr,
                                          stem_ru, stemmer_for)


def terms(name, text, **opts):
    return analysis.get_analyzer(name).terms(text)


# -- stemmers --------------------------------------------------------------

def test_porter2_snowball_vocabulary():
    cases = {
        "consigned": "consign", "consisting": "consist",
        "consistently": "consist", "caresses": "caress", "flies": "fli",
        "dies": "die", "mules": "mule", "denied": "deni",
        "agreed": "agre", "owned": "own", "humbled": "humbl",
        "meeting": "meet", "stating": "state", "itemization": "item",
        "sensational": "sensat", "traditional": "tradit",
        "reference": "refer", "colonizer": "colon", "plotted": "plot",
        "generate": "generat", "generally": "general", "happy": "happi",
        "skies": "sky", "dying": "die", "cats": "cat", "running": "run",
    }
    for w, want in cases.items():
        assert porter2(w) == want, (w, porter2(w), want)


def test_language_stemmers_collapse_variants():
    # each language: morphological variants map to a shared stem
    assert stem_de("häuser") == stem_de("hauses") == "haus"
    assert stem_fr("nationalité") == stem_fr("national")
    assert stem_ru("программирования") == stem_ru("программирование")
    assert stemmer_for("de_DE.utf-8") is stem_de
    assert stemmer_for("pt-BR") is not None
    assert stemmer_for("xx") is None


# -- locale text analyzers -------------------------------------------------

def test_text_de_stopwords_and_stemming():
    out = terms("text_de", "Die Häuser und die Wohnungen")
    assert "die" not in out and "und" not in out
    assert "haus" in out


def test_text_fr_accents():
    out = terms("text_fr", "les nationalités européennes")
    assert "les" not in out
    # accent-folded + stemmed to the shared base form
    assert "national" in out


def test_text_ru():
    out = terms("text_ru", "быстрое программирование на сервере")
    assert "на" not in out
    assert any(t.startswith("программ") for t in out)


def test_cjk_bigrams():
    out = terms("text", "机器学习")
    assert out == ["机器", "器学", "学习"]
    out = terms("text", "日本語のtokenizer")
    assert "日本" in out and "本語" in out
    # single CJK char is a unigram
    assert terms("text", "猫") == ["猫"]


def test_korean_and_kana():
    assert "한국" in terms("text", "한국어")
    assert "かた" in terms("text", "かたかな")


# -- structural analyzers --------------------------------------------------

def test_segmentation_modes():
    a = analysis.SegmentationAnalyzer(break_mode="alpha", case="lower")
    assert a.terms("Quick 123 Brown!") == ["quick", "brown"]
    a = analysis.SegmentationAnalyzer(break_mode="word", case="none")
    assert a.terms("Quick 123") == ["Quick", "123"]
    a = analysis.SegmentationAnalyzer(break_mode="graphic", case="upper")
    assert a.terms("a-b c") == ["A-B", "C"]


def test_normalizing_and_collation():
    a = analysis.NormalizingAnalyzer(case="lower", accent=False)
    assert a.terms("Crème BRÛLÉE") == ["creme brulee"]
    c = analysis.CollationAnalyzer("de")
    assert c.terms("Straße")[0] == c.terms("strasse")[0]


def test_stem_analyzer():
    a = analysis.StemAnalyzer("en")
    assert a.terms("Running") == ["run"]


def test_pattern_analyzer():
    a = analysis.PatternAnalyzer(r"[A-Z][a-z]+")
    assert a.terms("CamelCaseWords here") == ["Camel", "Case", "Words"]
    s = analysis.PatternAnalyzer(r"[,;]\s*", mode="split")
    assert s.terms("a, b; c") == ["a", "b", "c"]
    with pytest.raises(Exception):
        analysis.PatternAnalyzer("(unclosed")


def test_multi_delimiter():
    a = analysis.MultiDelimiterAnalyzer([",", ";", "|"])
    assert a.terms("a,b;c|d") == ["a", "b", "c", "d"]


def test_path_hierarchy():
    a = analysis.PathHierarchyAnalyzer()
    assert a.terms("/usr/local/bin") == ["/usr", "/usr/local",
                                         "/usr/local/bin"]
    r = analysis.PathHierarchyAnalyzer(".", reverse=True)
    assert r.terms("a.b.c") == ["a.b.c", "b.c", "c"]
    # ancestors share position 0 so a term filter hits any level
    assert {t.position for t in a.tokenize("/x/y")} == {0}


def test_synonyms_same_position():
    a = analysis.SynonymAnalyzer(["tv => television", "fast,quick"])
    toks = a.tokenize("fast tv")
    by_term = {t.term: t.position for t in toks}
    assert by_term["television"] == by_term["tv"]
    assert by_term["quick"] == by_term["fast"]


def test_pipeline_composition():
    p = analysis.PipelineAnalyzer([
        analysis.DelimiterAnalyzer(","),
        analysis.TextAnalyzer(stopwords=frozenset())])
    assert p.terms("Running Fast,Jumped High") == \
        ["run", "fast", "jump", "high"]


def test_union_dedup():
    u = analysis.UnionAnalyzer([
        analysis.SimpleTextAnalyzer(),
        analysis.TextAnalyzer(stopwords=frozenset())])
    out = u.terms("running")
    assert "running" in out and "run" in out


def test_minhash_similarity():
    a = analysis.MinHashAnalyzer(k=16)
    s1 = set(a.terms("the quick brown fox jumps over the lazy dog"))
    s2 = set(a.terms("the quick brown fox jumps over the lazy cat"))
    s3 = set(a.terms("completely different sentence about databases"))
    assert 0 < len(s1) <= 16   # k caps the signature; fewer shingles → fewer
    assert len(s1 & s2) > len(s1 & s3)
    # deterministic
    assert a.terms("same input") == a.terms("same input")


# -- SQL end-to-end --------------------------------------------------------

@pytest.fixture
def conn():
    return Database().connect()


def test_german_corpus_end_to_end(conn):
    conn.execute("CREATE TABLE de_docs (id INT, body TEXT)")
    conn.execute("INSERT INTO de_docs VALUES "
                 "(1, 'Die Häuser der Stadt'), "
                 "(2, 'Ein Haus am See'), "
                 "(3, 'Der Garten und die Bäume')")
    conn.execute("CREATE INDEX ON de_docs USING inverted (body text_de)")
    # 'Häusern' stems to the same term as 'Haus'/'Häuser'
    rows = conn.execute(
        "SELECT id FROM de_docs WHERE body ## 'Häusern' ORDER BY id").rows()
    assert rows == [(1,), (2,)]


def test_cjk_corpus_end_to_end(conn):
    conn.execute("CREATE TABLE zh_docs (id INT, body TEXT)")
    conn.execute("INSERT INTO zh_docs VALUES "
                 "(1, '机器学习与数据库'), (2, '数据库系统'), "
                 "(3, '自然语言处理')")
    conn.execute("CREATE INDEX ON zh_docs USING inverted (body)")
    rows = conn.execute(
        "SELECT id FROM zh_docs WHERE body ## '数据库' ORDER BY id").rows()
    assert rows == [(1,), (2,)]


def test_synonym_dictionary_end_to_end(conn):
    conn.execute("CREATE TEXT SEARCH DICTIONARY tvsyn("
                 "template = 'synonyms', "
                 "synonyms = 'tv => television; couch,sofa')")
    conn.execute("CREATE TABLE furn (id INT, body TEXT)")
    conn.execute("INSERT INTO furn VALUES "
                 "(1, 'a tv stand'), (2, 'a sofa cushion'), "
                 "(3, 'a wooden table')")
    conn.execute("CREATE INDEX ON furn USING inverted (body tvsyn)")
    assert conn.execute("SELECT id FROM furn WHERE body ## 'television'"
                        ).rows() == [(1,)]
    assert conn.execute("SELECT id FROM furn WHERE body ## 'couch'"
                        ).rows() == [(2,)]


def test_pipeline_dictionary_end_to_end(conn):
    conn.execute("CREATE TEXT SEARCH DICTIONARY csv_text("
                 "template = 'pipeline', stages = 'delimiter,text')")
    conn.execute("CREATE TABLE tags (id INT, body TEXT)")
    conn.execute("INSERT INTO tags VALUES (1, 'Databases,Searching'), "
                 "(2, 'Compilers,Parsing')")
    conn.execute("CREATE INDEX ON tags USING inverted (body csv_text)")
    assert conn.execute("SELECT id FROM tags WHERE body ## 'search'"
                        ).rows() == [(1,)]


def test_locale_dictionary_option(conn):
    conn.execute("CREATE TEXT SEARCH DICTIONARY fr_dict("
                 "template = 'text', locale = 'fr_FR.utf-8', "
                 "stopwords = 'true')")
    conn.execute("CREATE TABLE fr_docs (id INT, body TEXT)")
    conn.execute("INSERT INTO fr_docs VALUES "
                 "(1, 'les nationalités des pays')")
    conn.execute("CREATE INDEX ON fr_docs USING inverted (body fr_dict)")
    assert conn.execute("SELECT id FROM fr_docs WHERE body ## 'nationalité'"
                        ).rows() == [(1,)]
    # stopword never indexed
    assert conn.execute("SELECT id FROM fr_docs WHERE body ## 'les'"
                        ).rows() == []


def test_classification_analyzer():
    from serenedb_tpu.search.analysis import (drop_dictionary,
                                              register_dictionary)
    a = register_dictionary("t_cls", {
        "template": "classification",
        "labels": "sports: football basketball goalkeeper; "
                  "tech: compiler software kernel"})
    try:
        assert [t.term for t in a.tokenize("football match")] == ["sports"]
        assert [t.term for t in a.tokenize("compiler bug")] == ["tech"]
        # label names classify to themselves (centroid includes the label)
        assert [t.term for t in a.tokenize("sports")] == ["sports"]
        assert a.tokenize("") == []
        # top=2 emits both labels, best first
        b = register_dictionary("t_cls2", {
            "template": "classification", "top": 2,
            "labels": "sports: football; tech: compiler"})
        terms = [t.term for t in b.tokenize("football")]
        assert terms[0] == "sports" and sorted(terms) == ["sports", "tech"]
    finally:
        drop_dictionary("t_cls")
        drop_dictionary("t_cls2")


def test_classification_requires_labels():
    import pytest as _pytest

    from serenedb_tpu import errors
    from serenedb_tpu.search.analysis import register_dictionary
    with _pytest.raises(errors.SqlError):
        register_dictionary("t_cls3", {"template": "classification"})


def test_nearest_neighbors_analyzer():
    from serenedb_tpu.search.analysis import (drop_dictionary,
                                              register_dictionary)
    a = register_dictionary("t_nn", {
        "template": "nearest_neighbors", "top": 1,
        "vocab": "football basketball compiler software kernel"})
    try:
        # typo maps to its orthographic nearest vocabulary term
        assert [t.term for t in a.tokenize("footbal")] == ["football"]
        out = a.tokenize("compilr kernel")
        assert [(t.term, t.position) for t in out] == \
            [("compiler", 0), ("kernel", 1)]
    finally:
        drop_dictionary("t_nn")


def test_new_locale_stemmers():
    from serenedb_tpu.search.stemmers import (stem_da, stem_hu, stem_no,
                                              stem_ro, stem_tr,
                                              stemmer_for)
    # each language: inflected forms collapse onto one stem
    assert stem_da("hastighederne") == stem_da("hastigheden")
    assert stem_no("hemmeligheten") == stem_no("hemmelighetene")
    assert stem_ro("abilitățile")[:7] == stem_ro("abilității")[:7]
    assert stem_tr("kitaplardan") == stem_tr("kitaplar")
    assert stem_hu("szabadságok") == stem_hu("szabadság")
    for loc in ("da", "no", "nb", "ro", "tr", "hu", "danish", "turkish"):
        assert stemmer_for(loc) is not None


def test_new_locale_text_analyzers():
    from serenedb_tpu.search.analysis import get_analyzer
    for lang, stop, keep in [
        ("da", "ikke", "hastighed"), ("no", "ikke", "hemmelighet"),
        ("ro", "pentru", "libertate"), ("tr", "için", "kitap"),
        ("hu", "hogy", "szabadság"),
    ]:
        a = get_analyzer(f"text_{lang}")
        terms = [t.term for t in a.tokenize(f"{stop} {keep}")]
        assert len(terms) == 1, (lang, terms)  # stopword removed


def test_locale_dictionary_new_languages():
    from serenedb_tpu.search.analysis import (drop_dictionary,
                                              register_dictionary)
    a = register_dictionary("t_tr", {"template": "text", "locale": "tr",
                                     "stopwords": True})
    try:
        # Turkish dotless ı folds in the stemmer: kitabı ~ kitab
        t1 = [t.term for t in a.tokenize("kitaplardan")]
        t2 = [t.term for t in a.tokenize("kitaplar")]
        assert t1 == t2 and t1
    finally:
        drop_dictionary("t_tr")


def test_accent_option_reference_contract():
    """accent=true keeps accents; accent=false/unset removes them
    (text_tokenizer.hpp:61, normalizing_tokenizer.hpp:49)."""
    from serenedb_tpu.search.analysis import (drop_dictionary,
                                              register_dictionary)
    keep = register_dictionary("t_acc_keep", {"template": "text",
                                              "accent": True,
                                              "stemming": False})
    strip = register_dictionary("t_acc_strip", {"template": "text",
                                                "accent": False,
                                                "stemming": False})
    default = register_dictionary("t_acc_def", {"template": "text",
                                                "stemming": False})
    try:
        assert [t.term for t in keep.tokenize("café")] == ["café"]
        assert [t.term for t in strip.tokenize("café")] == ["cafe"]
        assert [t.term for t in default.tokenize("café")] == ["cafe"]
    finally:
        drop_dictionary("t_acc_keep")
        drop_dictionary("t_acc_strip")
        drop_dictionary("t_acc_def")
