"""pg_catalog emulation + PG pseudo-types: the psql \\d-family workflow.

Query texts below are the literal queries psql 14 issues for \\dt, \\d tbl,
\\di, \\dn, \\du, \\l, \\df (reference parity surface:
server/pg/pg_catalog/, server/query/server_engine.cpp:61-216).
"""

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError


@pytest.fixture
def conn():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE users (id INT PRIMARY KEY, name TEXT, "
              "score DOUBLE)")
    c.execute("CREATE INDEX users_name ON users USING inverted (name)")
    c.execute("CREATE VIEW v_users AS SELECT id FROM users")
    c.execute("CREATE SEQUENCE user_seq")
    return c


def test_psql_dt(conn):
    rows = conn.execute("""
        SELECT n.nspname, c.relname, c.relkind,
               pg_catalog.pg_get_userbyid(c.relowner)
        FROM pg_catalog.pg_class c
             LEFT JOIN pg_catalog.pg_namespace n ON n.oid = c.relnamespace
        WHERE c.relkind IN ('r','p','')
              AND n.nspname <> 'pg_catalog'
              AND n.nspname !~ '^pg_toast'
              AND n.nspname <> 'information_schema'
          AND pg_catalog.pg_table_is_visible(c.oid)
        ORDER BY 1,2""").rows()
    assert ("main", "users", "r", "serene") in rows


def test_psql_d_table_full_flow(conn):
    # query 1: resolve the name to an oid
    rows = conn.execute("""
        SELECT c.oid, n.nspname, c.relname
        FROM pg_catalog.pg_class c
             LEFT JOIN pg_catalog.pg_namespace n ON n.oid = c.relnamespace
        WHERE c.relname OPERATOR(pg_catalog.~) '^(users)$'
              COLLATE pg_catalog.default
          AND pg_catalog.pg_table_is_visible(c.oid)
        ORDER BY 2, 3""").rows()
    assert len(rows) == 1
    oid = rows[0][0]
    assert oid >= 16384

    # query 2: relation detail (incl. chained reg casts)
    det = conn.execute(f"""
        SELECT c.relchecks, c.relkind, c.relhasindex,
          CASE WHEN c.reloftype = 0 THEN ''
               ELSE c.reloftype::pg_catalog.regtype::pg_catalog.text END,
          c.relpersistence
        FROM pg_catalog.pg_class c WHERE c.oid = '{oid}'""").rows()
    assert det == [(0, "r", True, "", "p")]

    # query 3: columns via pg_attribute + format_type
    cols = conn.execute(f"""
        SELECT a.attname, pg_catalog.format_type(a.atttypid, a.atttypmod),
          a.attnotnull
        FROM pg_catalog.pg_attribute a
        WHERE a.attrelid = '{oid}' AND a.attnum > 0
              AND NOT a.attisdropped
        ORDER BY a.attnum""").rows()
    assert cols == [("id", "integer", True), ("name", "text", False),
                    ("score", "double precision", False)]

    # query 4: indexes (comma joins + LEFT JOIN + pg_get_indexdef)
    idx = conn.execute(f"""
        SELECT c2.relname, i.indisprimary, i.indisunique,
          pg_catalog.pg_get_indexdef(i.indexrelid, 0, true)
        FROM pg_catalog.pg_class c, pg_catalog.pg_class c2,
             pg_catalog.pg_index i
          LEFT JOIN pg_catalog.pg_constraint con
            ON (con.conrelid = i.indrelid AND con.conindid = i.indexrelid
                AND con.contype IN ('p','u','x'))
        WHERE c.oid = '{oid}' AND c.oid = i.indrelid
              AND i.indexrelid = c2.oid
        ORDER BY i.indisprimary DESC, c2.relname""").rows()
    assert idx == [("users_name", False, False,
                    "CREATE INDEX users_name ON users "
                    "USING inverted (name)")]


def test_psql_du_array_subquery(conn):
    rows = conn.execute("""
        SELECT r.rolname, r.rolsuper, r.rolcanlogin,
          ARRAY(SELECT b.rolname FROM pg_catalog.pg_auth_members m
                JOIN pg_catalog.pg_roles b ON (m.roleid = b.oid)
                WHERE m.member = r.oid) as memberof
        FROM pg_catalog.pg_roles r WHERE r.rolname !~ '^pg_'
        ORDER BY 1""").rows()
    assert rows[0][:3] == ("serene", True, True)
    assert rows[0][3] == "[]"


def test_psql_l(conn):
    rows = conn.execute("""
        SELECT d.datname, pg_catalog.pg_get_userbyid(d.datdba),
          pg_catalog.pg_encoding_to_char(d.encoding), d.datcollate
        FROM pg_catalog.pg_database d ORDER BY 1""").rows()
    assert rows == [("serene", "serene", "UTF8", "C")]


def test_psql_dn(conn):
    rows = conn.execute("""
        SELECT n.nspname, pg_catalog.pg_get_userbyid(n.nspowner)
        FROM pg_catalog.pg_namespace n
        WHERE n.nspname !~ '^pg_' AND n.nspname <> 'information_schema'
        ORDER BY 1""").rows()
    assert ("main", "serene") in rows


def test_psql_df(conn):
    rows = conn.execute("""
        SELECT n.nspname, p.proname,
          pg_catalog.pg_get_function_result(p.oid)
        FROM pg_catalog.pg_proc p
          LEFT JOIN pg_catalog.pg_namespace n ON n.oid = p.pronamespace
        WHERE p.proname OPERATOR(pg_catalog.~) '^(abs)$'
        ORDER BY 1, 2""").rows()
    assert rows == [("pg_catalog", "abs", None)]


def test_regclass_casts(conn):
    r = conn.execute("SELECT 'users'::regclass::text, "
                     "'users'::regclass::int8").rows()[0]
    assert r[0] == "users"
    assert r[1] >= 16384
    # schema-qualified and quoted forms
    assert conn.execute(
        "SELECT 'main.users'::regclass::text").scalar() == "users"
    with pytest.raises(SqlError):
        conn.execute("SELECT 'nope_missing'::regclass")
    # to_regclass returns NULL instead of raising
    assert conn.execute("SELECT to_regclass('nope_missing')").scalar() is None
    assert conn.execute(
        "SELECT to_regclass('users')::text").scalar() == "users"


def test_regtype_regproc(conn):
    # PG renders regtype as the canonical SQL name (format_type)
    assert conn.execute("SELECT 23::regtype::text").scalar() == "integer"
    assert conn.execute("SELECT 'integer'::regtype::int").scalar() == 23
    assert conn.execute(
        "SELECT 'bigint'::regtype = 20::regtype").scalar() is True
    assert conn.execute(
        "SELECT 'abs'::regproc::text").scalar() == "abs"


def test_regnamespace(conn):
    assert conn.execute(
        "SELECT 'pg_catalog'::regnamespace::int").scalar() == 11
    assert conn.execute(
        "SELECT 'main'::regnamespace::text").scalar() == "main"
    with pytest.raises(SqlError):
        conn.execute("SELECT 'no_such_schema'::regnamespace")


def test_view_columns_in_pg_attribute(conn):
    rows = conn.execute("""
        SELECT a.attname, pg_catalog.format_type(a.atttypid, a.atttypmod)
        FROM pg_catalog.pg_attribute a
        JOIN pg_catalog.pg_class c ON c.oid = a.attrelid
        WHERE c.relname = 'v_users' ORDER BY a.attnum""").rows()
    assert rows == [("id", "integer")]


def test_quote_ident_reserved(conn):
    assert conn.execute("SELECT quote_ident('select')").scalar() == '"select"'
    assert conn.execute("SELECT quote_ident('order')").scalar() == '"order"'


def test_mixed_numeric_text_quant(conn):
    # numeric-vs-text coerces numerically, never lexicographically
    assert conn.execute("SELECT 9 < ALL(ARRAY['10'])").scalar() is True
    assert conn.execute("SELECT 9 = ANY(ARRAY['9'])").scalar() is True


def test_view_definition_is_single_statement(conn):
    conn.execute("CREATE TABLE vd (x INT); "
                 "CREATE VIEW vd_v AS SELECT x FROM vd; "
                 "INSERT INTO vd VALUES (1)")
    d = conn.execute("SELECT definition FROM pg_views "
                     "WHERE viewname = 'vd_v'").scalar()
    # PG semantics: the definition is the SELECT body, not CREATE VIEW
    assert d == "SELECT x FROM vd"


def test_quantified_comparisons(conn):
    assert conn.execute(
        "SELECT 'main' = ANY(current_schemas(true))").scalar() is True
    assert conn.execute("SELECT 3 > ALL(ARRAY[1,2])").scalar() is True
    assert conn.execute("SELECT 3 > ALL(ARRAY[1,4])").scalar() is False
    assert conn.execute("SELECT 2 = SOME(ARRAY[1,2,3])").scalar() is True
    # NULL element: ANY stays unknown when no match
    assert conn.execute(
        "SELECT 9 = ANY(ARRAY[1,NULL])").scalar() is None
    assert conn.execute(
        "SELECT id = ANY(ARRAY[1,3]) FROM users").rows() == []
    # subquery forms
    conn.execute("INSERT INTO users VALUES (1,'a',0.5),(2,'b',1.5)")
    assert conn.execute(
        "SELECT count(*) FROM users WHERE id = "
        "ANY(SELECT id FROM users WHERE score > 1)").scalar() == 1
    assert conn.execute(
        "SELECT count(*) FROM users WHERE id <> "
        "ALL(SELECT id FROM users WHERE score > 1)").scalar() == 1


def test_info_schema_breadth(conn):
    conn.execute("INSERT INTO users VALUES (1,'a',0.5)")
    assert conn.execute(
        "SELECT schema_name FROM information_schema.schemata "
        "WHERE schema_name = 'main'").rows() == [("main",)]
    assert conn.execute(
        "SELECT constraint_type FROM information_schema.table_constraints "
        "WHERE table_name = 'users'").rows() == [("PRIMARY KEY",)]
    assert conn.execute(
        "SELECT column_name FROM information_schema.key_column_usage "
        "WHERE table_name = 'users'").rows() == [("id",)]
    assert conn.execute(
        "SELECT table_name FROM information_schema.views "
        "WHERE table_name = 'v_users'").rows() == [("v_users",)]
    assert conn.execute(
        "SELECT sequence_name FROM information_schema.sequences "
        "WHERE sequence_name = 'user_seq'").rows() == [("user_seq",)]


def test_catalog_stubs_join_cleanly(conn):
    # empty catalogs psql/ORMs join against: zero rows, correct columns
    for t in ("pg_locks", "pg_trigger", "pg_policy", "pg_inherits",
              "pg_extension", "pg_depend", "pg_matviews",
              "pg_auth_members", "pg_description"):
        assert conn.execute(f"SELECT count(*) FROM {t}").scalar() == 0
    # pg_type joins
    assert conn.execute(
        "SELECT t.typname FROM pg_catalog.pg_type t "
        "WHERE t.oid = 25").rows() == [("text",)]


def test_sizes_and_misc_functions(conn):
    conn.execute("INSERT INTO users VALUES (1,'a',0.5)")
    size = conn.execute(
        "SELECT pg_total_relation_size('users'::regclass)").scalar()
    assert size > 0
    assert conn.execute(
        "SELECT pg_size_pretty(10)").scalar() == "10 bytes"
    assert conn.execute(
        "SELECT pg_size_pretty(20480)").scalar() == "20 kB"
    assert conn.execute("SELECT quote_ident('x y')").scalar() == '"x y"'
    assert conn.execute("SELECT quote_ident('xy')").scalar() == "xy"
    assert conn.execute("SELECT quote_literal('o''x')").scalar() == "'o''x'"
    assert conn.execute("SELECT current_database()").scalar() == "serene"
    assert conn.execute("SELECT current_user()").scalar() == "serene"
    assert conn.execute("SELECT pg_backend_pid()").scalar() == 1
    assert conn.execute("SELECT pg_is_in_recovery()").scalar() is False
    assert conn.execute(
        "SELECT has_table_privilege('serene','users','SELECT')"
    ).scalar() is True


def test_pg_get_viewdef(conn):
    oid = conn.execute("SELECT c.oid FROM pg_class c "
                       "WHERE c.relname = 'v_users'").scalar()
    d = conn.execute(f"SELECT pg_get_viewdef({oid})").scalar()
    assert "SELECT" in (d or "").upper()


def test_sequences_catalog(conn):
    rows = conn.execute(
        "SELECT sequencename, data_type FROM pg_sequences").rows()
    assert ("user_seq", "bigint") in rows


def test_oid_stability(conn):
    a = conn.execute("SELECT 'users'::regclass::int8").scalar()
    b = conn.execute("SELECT oid FROM pg_class "
                     "WHERE relname = 'users'").scalar()
    c2 = conn.execute("SELECT attrelid FROM pg_attribute "
                      "WHERE attname = 'score'").scalar()
    assert a == b == c2
