"""Incremental multi-segment search: append-only refresh adds segments;
results and scores match a fresh single-segment build (global stats)."""

import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.search.index import build_index_for_table, refresh_index


@pytest.fixture
def db_conn():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT)")
    c.execute("INSERT INTO docs VALUES "
              "(1, 'alpha beta gamma'), (2, 'alpha alpha delta'), "
              "(3, 'beta beta beta')")
    c.execute("CREATE INDEX ON docs USING inverted (body)")
    return db, c


def _index(db):
    t = db.schemas["main"].tables["docs"]
    return t, next(iter(t.indexes.values()))


def test_append_adds_segment_not_rebuild(db_conn):
    db, c = db_conn
    t, idx = _index(db)
    seg0 = idx.searchers["body"].segments[0][0]
    c.execute("INSERT INTO docs VALUES (4, 'alpha omega'), (5, 'omega')")
    t.indexes[next(iter(t.indexes))] = refresh_index(t, idx)
    _, idx2 = _index(db)
    ms = idx2.searchers["body"]
    assert len(ms.segments) == 2
    # first segment object reused — no rebuild of old rows
    assert ms.segments[0][0] is seg0
    assert ms.segments[1][1] == 3  # base row of the delta segment


def test_multi_segment_matches_fresh_build(db_conn):
    db, c = db_conn
    t, idx = _index(db)
    c.execute("INSERT INTO docs VALUES (4, 'alpha omega'), (5, 'omega nu')")
    incr = refresh_index(t, idx)
    fresh = build_index_for_table(t, ["body"], "inverted", {})
    for q in ["alpha", "omega", "alpha & omega", "beta | omega", "nu*"]:
        from serenedb_tpu.search.query import parse_query
        from serenedb_tpu.search.analysis import get_analyzer
        node = parse_query(q, get_analyzer("text"))
        mi = set(incr.searchers["body"].eval_filter(node).tolist())
        mf = set(fresh.searchers["body"].eval_filter(node).tolist())
        assert mi == mf, q
        si, di = incr.searchers["body"].topk(node, 10)
        sf, df_ = fresh.searchers["body"].topk(node, 10)
        # global stats ⇒ identical scores and ordering
        assert di.tolist() == df_.tolist(), q
        np.testing.assert_allclose(si, sf, rtol=1e-4, atol=1e-5)


def test_mutation_forces_rebuild(db_conn):
    db, c = db_conn
    t, idx = _index(db)
    c.execute("INSERT INTO docs VALUES (4, 'zeta')")
    c.execute("DELETE FROM docs WHERE id = 1")   # mutation: epoch bump
    idx2 = refresh_index(t, idx)
    assert len(idx2.searchers["body"].segments) == 1  # rebuilt
    assert c.execute("SELECT count(*) FROM docs WHERE body @@ 'alpha'"
                     ).scalar() == 1


def test_sql_search_through_segments(db_conn):
    db, c = db_conn
    c.execute("INSERT INTO docs VALUES (4, 'alpha fresh segment doc')")
    c.execute("VACUUM REFRESH docs")   # incremental refresh
    ex = c.execute(
        "EXPLAIN SELECT count(*) FROM docs WHERE body @@ 'alpha'").rows()
    assert any("SearchScan" in r[0] for r in ex)
    assert c.execute(
        "SELECT count(*) FROM docs WHERE body @@ 'alpha'").scalar() == 3
    rows = c.execute(
        "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'alpha' "
        "ORDER BY s DESC LIMIT 10").rows()
    assert {r[0] for r in rows} == {1, 2, 4}
    scores = [r[1] for r in rows]
    assert scores == sorted(scores, reverse=True)


def test_segment_cap_triggers_merge(db_conn):
    db, c = db_conn
    t, idx = _index(db)
    from serenedb_tpu.search.index import MAX_SEGMENTS
    for i in range(MAX_SEGMENTS + 1):
        c.execute(f"INSERT INTO docs VALUES ({10 + i}, 'filler doc {i}')")
        idx = refresh_index(t, idx)
        t.indexes[next(iter(t.indexes))] = idx
    assert len(idx.searchers["body"].segments) <= MAX_SEGMENTS
