"""Parity suite for the vectorized relational tier (ISSUE 3).

Contract under test: the vectorized hash join / set-op / DISTINCT ON
paths (`serene_join_vectorized = on`, the default) must produce results
BIT-IDENTICAL to the legacy row-tuple interpreter across the full
matrix — inner/left/right/full/cross joins × NULL keys × mixed key
dtypes (int / dictionary-string / float-with-NaN) × residual ON
predicates × `serene_workers` 1 vs N × `serene_join_filter` on/off.
Plus join-filter behavior: pruning fires only where it is sound
(inner/right), never changes results, and bumps its own gauges.
"""

import numpy as np
import pytest

from serenedb_tpu.columnar import dtypes as dt
from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.exec.tables import MemTable
from serenedb_tpu.utils import metrics


def _mk_conn(nl=3000, nr=2000, seed=2):
    """Two joinable tables with every key dtype the matrix needs: INT
    with NULLs, dictionary TEXT, DOUBLE with NULLs and NaNs, plus BIGINT
    payloads."""
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE l (ik INT, sk TEXT, fk DOUBLE, v BIGINT)")
    c.execute("CREATE TABLE r (ik INT, sk TEXT, fk DOUBLE, w BIGINT)")

    def mk(n, null_frac, sd, payload):
        rng = np.random.default_rng(sd)
        ik = rng.integers(0, 50, n).astype(np.int32)
        ikv = rng.random(n) > null_frac
        fk = np.round(rng.normal(size=n), 1)     # rounding ⇒ cross-side dups
        fk[rng.random(n) < 0.05] = np.nan
        fkv = rng.random(n) > 0.1
        return Batch.from_pydict({
            "ik": Column(dt.INT, ik, ikv),
            "sk": Column.from_numpy(
                rng.choice(["alpha", "beta", "gamma", "delta"], n)),
            "fk": Column(dt.DOUBLE, fk, fkv),
            payload: Column.from_numpy(
                rng.integers(0, 1000, n, dtype=np.int64)),
        })

    db.schemas["main"].tables["l"] = MemTable("l", mk(nl, 0.1, seed, "v"))
    db.schemas["main"].tables["r"] = MemTable("r", mk(nr, 0.15, seed + 1, "w"))
    c.execute("SET serene_device = 'cpu'")
    # engage morsel-parallel probes and zone maps at test-sized data
    c.execute("SET serene_parallel_min_rows = 1024")
    c.execute("SET serene_morsel_rows = 1024")
    return c


JOIN_QUERIES = [
    # kinds × key dtypes
    "SELECT * FROM l JOIN r ON l.ik = r.ik ORDER BY v, w, l.sk, r.sk, l.fk, r.fk",
    "SELECT * FROM l LEFT JOIN r ON l.ik = r.ik ORDER BY v, w, l.sk, r.sk, l.fk, r.fk",
    "SELECT * FROM l RIGHT JOIN r ON l.ik = r.ik ORDER BY v, w, l.sk, r.sk, l.fk, r.fk",
    "SELECT count(*), sum(v), sum(w), sum(ik) FROM l FULL JOIN r USING (ik)",
    "SELECT count(*), sum(v+w) FROM l JOIN r ON l.sk = r.sk",
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.fk = r.fk",
    # multi-column keys, mixed dtypes in one composite
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.ik AND l.sk = r.sk",
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r USING (ik, sk, fk)",
    # residual ON predicates (candidate-pair semantics, outer variants)
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.ik AND v < w",
    "SELECT count(*), sum(v), sum(w) FROM l LEFT JOIN r ON l.ik = r.ik AND v < w",
    "SELECT count(*), sum(v), sum(w) FROM l RIGHT JOIN r ON l.sk = r.sk AND v % 3 = w % 3",
    "SELECT count(*), sum(v), sum(w), sum(l.ik) FROM l FULL JOIN r ON l.ik = r.ik AND v + w < 900",
    # cross join
    "SELECT count(*), sum(v*w) FROM l CROSS JOIN r WHERE v = w",
    # int key against float key (numeric promotion must match python ==)
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.fk",
]


def _rows(c, q):
    """repr-keyed row capture: bit-identical comparison that still treats
    a NaN as equal to itself (tuple == would fail rows CONTAINING NaN
    payloads even when both sides are the same bits)."""
    return repr(c.execute(q).rows())


@pytest.mark.parametrize("q", JOIN_QUERIES)
def test_join_parity_vectorized_vs_legacy(q):
    c = _mk_conn()
    c.execute("SET serene_join_vectorized = off")
    oracle = _rows(c, q)
    c.execute("SET serene_join_vectorized = on")
    for workers in (1, 4):
        c.execute(f"SET serene_workers = {workers}")
        for jf in ("on", "off"):
            c.execute(f"SET serene_join_filter = {jf}")
            got = _rows(c, q)
            assert got == oracle, \
                f"vectorized join diverged (workers={workers}, filter={jf})"


SETOP_QUERIES = [
    "SELECT ik, sk FROM l UNION SELECT ik, sk FROM r ORDER BY ik NULLS LAST, sk",
    "SELECT ik FROM l UNION ALL SELECT ik FROM r ORDER BY ik NULLS LAST LIMIT 50",
    "SELECT ik, sk FROM l INTERSECT SELECT ik, sk FROM r ORDER BY ik NULLS LAST, sk",
    "SELECT ik FROM l INTERSECT ALL SELECT ik FROM r ORDER BY ik NULLS LAST",
    "SELECT ik, sk FROM l EXCEPT SELECT ik, sk FROM r ORDER BY ik NULLS LAST, sk",
    "SELECT sk FROM l EXCEPT ALL SELECT sk FROM r ORDER BY sk",
    # NaN / NULL float semantics: every NaN occurrence is distinct,
    # NULL = NULL (python row-tuple semantics preserved exactly)
    "SELECT count(*) FROM (SELECT fk FROM l EXCEPT SELECT fk FROM r) t",
    "SELECT count(*) FROM (SELECT fk FROM l INTERSECT ALL SELECT fk FROM r) t",
    # numeric type unification across arms (INT vs BIGINT)
    "SELECT ik FROM l INTERSECT SELECT w FROM r ORDER BY ik NULLS LAST",
]


@pytest.mark.parametrize("q", SETOP_QUERIES)
def test_setop_parity_vectorized_vs_legacy(q):
    c = _mk_conn()
    c.execute("SET serene_join_vectorized = off")
    oracle = _rows(c, q)
    c.execute("SET serene_join_vectorized = on")
    assert _rows(c, q) == oracle


DISTINCT_ON_QUERIES = [
    "SELECT DISTINCT ON (ik) ik, v FROM l ORDER BY ik NULLS LAST, v DESC",
    "SELECT DISTINCT ON (sk) sk, v FROM l ORDER BY sk, v",
    "SELECT DISTINCT ON (ik, sk) ik, sk, v FROM l ORDER BY ik NULLS LAST, sk, v",
    "SELECT DISTINCT ON (fk) fk, v FROM l ORDER BY fk, v LIMIT 40",
]


@pytest.mark.parametrize("q", DISTINCT_ON_QUERIES)
def test_distinct_on_parity_vectorized_vs_legacy(q):
    c = _mk_conn()
    c.execute("SET serene_join_vectorized = off")
    oracle = _rows(c, q)
    c.execute("SET serene_join_vectorized = on")
    assert _rows(c, q) == oracle


def test_distinct_on_cross_batch_dedup():
    """Cross-batch first-occurrence: the columnar winners accumulator
    must dedup against EVERY prior batch, not just the current one."""
    from serenedb_tpu.exec.plan import DistinctOnNode, ExecContext, PlanNode

    class MultiBatch(PlanNode):
        def __init__(self, batches):
            self._batches = batches
            self.names = list(batches[0].names)
            self.types = [c.type for c in batches[0].columns]

        def batches(self, ctx):
            yield from self._batches

    def mk(vals, payload):
        return Batch.from_pydict({
            "k": Column.from_pylist(vals, dt.BIGINT),
            "v": Column.from_pylist(payload, dt.BIGINT)})

    batches = [mk([1, 2, 2, None], [10, 20, 21, 30]),
               mk([2, 3, None, 1], [22, 40, 31, 11]),
               mk([4, 4, 3], [50, 51, 41])]
    node = DistinctOnNode(MultiBatch(batches), [0])
    got = node.execute(ExecContext()).to_pydict()
    assert got == {"k": [1, 2, None, 3, 4], "v": [10, 20, 30, 40, 50]}

    # string keys: dictionaries re-encode across batches
    def mks(vals, payload):
        return Batch.from_pydict({
            "k": Column.from_pylist(vals, dt.VARCHAR),
            "v": Column.from_pylist(payload, dt.BIGINT)})

    sbatches = [mks(["b", "a", "b"], [1, 2, 3]),
                mks(["c", "a", "d"], [4, 5, 6]),
                mks(["d", "b", "e"], [7, 8, 9])]
    node = DistinctOnNode(MultiBatch(sbatches), [0])
    got = node.execute(ExecContext()).to_pydict()
    assert got == {"k": ["b", "a", "c", "d", "e"], "v": [1, 2, 4, 6, 9]}


def _mk_clustered(n=100_000, nb=500, lo=40_000, hi=42_000):
    """Probe table clustered on the key (the shape zone maps exist for)
    plus a small build table confined to [lo, hi) — the join filter must
    prune every probe morsel outside that window."""
    db = Database()
    c = db.connect()
    rng = np.random.default_rng(31)
    c.execute("CREATE TABLE p (k BIGINT, v BIGINT)")
    c.execute("CREATE TABLE b (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["p"] = MemTable("p", Batch.from_pydict({
        "k": Column.from_numpy(np.arange(n, dtype=np.int64)),
        "v": Column.from_numpy(rng.integers(0, 100, n, dtype=np.int64))}))
    db.schemas["main"].tables["b"] = MemTable("b", Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(lo, hi, nb, dtype=np.int64)),
        "w": Column.from_numpy(rng.integers(0, 100, nb, dtype=np.int64))}))
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_morsel_rows = 4096")
    c.execute("SET serene_parallel_min_rows = 1024")
    c.execute("SET serene_join_filter = on")
    return c


def test_join_filter_prunes_probe_morsels():
    c = _mk_clustered()
    q = "SELECT count(*), sum(v+w) FROM p JOIN b ON p.k = b.k"
    p0 = metrics.JOIN_FILTER_PRUNED.value
    on = c.execute(q).rows()
    pruned = metrics.JOIN_FILTER_PRUNED.value - p0
    assert pruned > 0, "join filter never pruned a clustered probe scan"
    c.execute("SET serene_join_filter = off")
    p1 = metrics.JOIN_FILTER_PRUNED.value
    off = c.execute(q).rows()
    assert metrics.JOIN_FILTER_PRUNED.value == p1
    assert on == off
    assert on[0][0] == 500          # every build row found its partner


def test_join_filter_right_join_prunes_left_and_full_never():
    c = _mk_clustered()
    qr = "SELECT count(*), sum(w) FROM p RIGHT JOIN b ON p.k = b.k"
    p0 = metrics.JOIN_FILTER_PRUNED.value
    r_on = c.execute(qr).rows()
    assert metrics.JOIN_FILTER_PRUNED.value > p0
    c.execute("SET serene_join_filter = off")
    assert c.execute(qr).rows() == r_on
    c.execute("SET serene_join_filter = on")
    # left/full joins emit unmatched probe rows — pruning would drop them
    for q in ("SELECT count(*), sum(v) FROM p LEFT JOIN b ON p.k = b.k",
              "SELECT count(*), sum(v) FROM p FULL JOIN b ON p.k = b.k"):
        before = metrics.JOIN_FILTER_PRUNED.value
        rows = c.execute(q).rows()
        assert metrics.JOIN_FILTER_PRUNED.value == before
        assert rows[0][0] >= 100_000      # every probe row survived


def test_join_filter_legacy_match_still_prunes_identically():
    c = _mk_clustered()
    # asserts EXECUTION internals (prune gauges on the repeat run) —
    # the result cache would serve the identical statement without
    # executing, which is correct but not what this test probes
    c.execute("SET serene_result_cache = off")
    q = ("SELECT count(*), sum(v+w) FROM p JOIN b ON p.k = b.k "
         "AND v + w > 20")
    c.execute("SET serene_join_vectorized = on")
    vec = c.execute(q).rows()
    c.execute("SET serene_join_vectorized = off")
    p0 = metrics.JOIN_FILTER_PRUNED.value
    leg = c.execute(q).rows()
    assert metrics.JOIN_FILTER_PRUNED.value > p0
    assert vec == leg


def test_join_filter_empty_and_null_build_side():
    c = _mk_clustered()
    c.execute("DELETE FROM b")
    q = "SELECT count(*) FROM p JOIN b ON p.k = b.k"
    assert c.execute(q).rows() == [(0,)]
    c.execute("INSERT INTO b VALUES (NULL, 1), (NULL, 2)")
    assert c.execute(q).rows() == [(0,)]       # NULL keys never match


def test_full_join_using_merges_right_only_rows():
    """merge_pairs (np.where path): the USING column must carry the
    right side's key on right-only rows, for numeric AND string keys."""
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE a (k BIGINT, s TEXT, v BIGINT)")
    c.execute("CREATE TABLE z (k BIGINT, s TEXT, w BIGINT)")
    c.execute("INSERT INTO a VALUES (1, 'x', 10), (2, 'y', 20)")
    c.execute("INSERT INTO z VALUES (2, 'y', 200), (3, 'z', 300)")
    for vec in ("on", "off"):
        c.execute(f"SET serene_join_vectorized = {vec}")
        rows = c.execute(
            "SELECT k, v, w FROM a FULL JOIN z USING (k) "
            "ORDER BY k").rows()
        assert rows == [(1, 10, None), (2, 20, 200), (3, None, 300)]
        rows = c.execute(
            "SELECT s, k, v, w FROM a FULL JOIN z USING (s, k) "
            "ORDER BY s").rows()
        assert rows == [("x", 1, 10, None), ("y", 2, 20, 200),
                        ("z", 3, None, 300)]


def test_huge_int_keys_never_collapse_through_float():
    """BIGINT keys beyond 2**53 must not meet each other (or a float
    partner) through float64 promotion: 2**53 and 2**53 + 1 are distinct
    ints but the same double. Composite int+float keys and int-vs-float
    key pairs both fall back to exact comparison."""
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE hl (k BIGINT, f DOUBLE, v BIGINT)")
    c.execute("CREATE TABLE hr (k BIGINT, f DOUBLE, g DOUBLE, w BIGINT)")
    base = 2 ** 53
    c.execute(f"INSERT INTO hl VALUES ({base}, 1.5, 1), "
              f"({base + 1}, 1.5, 2), (7, 2.5, 3)")
    c.execute(f"INSERT INTO hr VALUES ({base + 1}, 1.5, {float(base)}, 10), "
              f"(7, 2.5, 7.0, 30)")
    queries = [
        # composite int64+float key: a mixed-dtype stack must not
        # promote the int row
        ("SELECT v, w FROM hl JOIN hr ON hl.k = hr.k AND hl.f = hr.f "
         "ORDER BY v", [(2, 10), (3, 30)]),
        # int key against float key across sides: 2**53 equals the
        # double exactly, 2**53 + 1 must NOT
        ("SELECT v, w FROM hl JOIN hr ON hl.k = hr.g ORDER BY v",
         [(1, 10), (3, 30)]),
        ("SELECT count(*) FROM (SELECT k, f FROM hl INTERSECT "
         "SELECT k, f FROM hr) t", [(2,)]),
    ]
    for q, expected in queries:
        for vec in ("on", "off"):
            c.execute(f"SET serene_join_vectorized = {vec}")
            assert c.execute(q).rows() == expected, (q, vec)


def test_setop_huge_int_vs_float_arm_stays_exact():
    """An integer arm unified to DOUBLE must not collapse 2**53-adjacent
    values through the cast — those shapes defer to the row-tuple
    oracle (python int == float compares exactly)."""
    db = Database()
    c = db.connect()
    big = 2 ** 53 + 1
    for vec in ("on", "off"):
        c.execute(f"SET serene_join_vectorized = {vec}")
        assert c.execute(
            f"SELECT {big} INTERSECT SELECT {float(2 ** 53)!r}"
        ).rows() == [], vec
        assert len(c.execute(
            f"SELECT {big} EXCEPT SELECT {float(2 ** 53)!r}"
        ).rows()) == 1, vec


def test_full_join_using_overflow_raises_not_wraps():
    """A right-only USING key too wide for the left column's type must
    raise 22003 (as the row-wise merge did), never wrap through astype."""
    from serenedb_tpu.errors import SqlError

    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE na (k INT, v BIGINT)")
    c.execute("CREATE TABLE nb (k BIGINT, w BIGINT)")
    c.execute("INSERT INTO na VALUES (1, 10)")
    c.execute(f"INSERT INTO nb VALUES ({2 ** 33}, 20)")
    for vec in ("on", "off"):
        c.execute(f"SET serene_join_vectorized = {vec}")
        with pytest.raises(SqlError) as exc:
            c.execute("SELECT k, v, w FROM na FULL JOIN nb USING (k)")
        assert exc.value.sqlstate == "22003"


def test_join_workers_parity_large_probe():
    """Morsel-parallel probe expansion merges in morsel order: workers=1
    and =N must be bit-identical on a probe spanning many morsels."""
    db = Database()
    c = db.connect()
    rng = np.random.default_rng(41)
    n, nb = 200_000, 30_000
    c.execute("CREATE TABLE p (k BIGINT, v BIGINT)")
    c.execute("CREATE TABLE b (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["p"] = MemTable("p", Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 60_000, n, dtype=np.int64)),
        "v": Column.from_numpy(rng.integers(0, 100, n, dtype=np.int64))}))
    db.schemas["main"].tables["b"] = MemTable("b", Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 60_000, nb, dtype=np.int64)),
        "w": Column.from_numpy(rng.integers(0, 100, nb, dtype=np.int64))}))
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_morsel_rows = 16384")
    c.execute("SET serene_parallel_min_rows = 1024")
    q = ("SELECT count(*), sum(v*w), min(v-w), max(v+w) "
         "FROM p JOIN b ON p.k = b.k")
    c.execute("SET serene_workers = 4")
    par = c.execute(q).rows()
    c.execute("SET serene_workers = 1")
    assert c.execute(q).rows() == par
    c.execute("SET serene_join_vectorized = off")
    assert c.execute(q).rows() == par
