"""Parity suite for the morsel-driven parallel execution layer.

Contract under test (ISSUE 1): `serene_workers = 1` and `= N` must
produce IDENTICAL results — aggregates bit-for-bit, top-k including
tie order, ingest row-for-row — because the morsel split and merge
order are pure functions of the data, never of scheduling. Plus pool
behavior: ordered results, lowest-index error, cancellation draining
without poisoning the shared pool.
"""

import threading
import time

import numpy as np
import pytest

from serenedb_tpu.columnar import dtypes as dt
from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError
from serenedb_tpu.exec.tables import MemTable


def _mk_conn(n=60_000, seed=5):
    rng = np.random.default_rng(seed)
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE t (k INT, g TEXT, v BIGINT, f DOUBLE, nv INT)")
    validity = rng.random(n) > 0.15
    nv = rng.integers(0, 7, n).astype(np.int32)
    batch = Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 40, n).astype(np.int32)),
        "g": Column.from_numpy(
            rng.choice(["alpha", "beta", "gamma", "delta"], n)),
        "v": Column.from_numpy(
            rng.integers(-(10 ** 6), 10 ** 6, n, dtype=np.int64)),
        "f": Column.from_numpy(rng.normal(size=n)),
        "nv": Column(dt.INT, nv, validity),
    })
    db.schemas["main"].tables["t"] = MemTable("t", batch)
    c.execute("SET serene_device = 'cpu'")
    # engage the morsel path at test-sized data
    c.execute("SET serene_parallel_min_rows = 1024")
    c.execute("SET serene_morsel_rows = 4096")
    return c


AGG_QUERIES = [
    "SELECT count(*) FROM t",
    "SELECT count(*), sum(v), min(v), max(v), avg(v) FROM t",
    "SELECT sum(f), min(f), max(f), avg(f), stddev(f) FROM t",
    "SELECT count(nv), sum(nv), avg(nv) FROM t",          # NULLs in agg arg
    "SELECT k, count(*), sum(v) FROM t GROUP BY k ORDER BY k",
    "SELECT g, min(g), max(g), count(*) FROM t GROUP BY g ORDER BY g",
    "SELECT nv, count(*), sum(v) FROM t GROUP BY nv ORDER BY nv NULLS LAST",
    ("SELECT k, g, sum(v) FILTER (WHERE f > 0), avg(f), bool_and(v > -999999)"
     " FROM t GROUP BY k, g ORDER BY k, g"),
    ("SELECT k, count(*), stddev_pop(f), var_samp(f) FROM t "
     "WHERE v % 3 <> 0 GROUP BY k ORDER BY k"),
    # expression keys defeat the direct coding → factorize merge path
    "SELECT k % 7, count(*), sum(v) FROM t GROUP BY k % 7 ORDER BY k % 7",
]


@pytest.mark.parametrize("q", AGG_QUERIES)
def test_aggregate_parity_workers_1_vs_n(q):
    c = _mk_conn()
    c.execute("SET serene_workers = 4")
    par = c.execute(q).rows()
    c.execute("SET serene_workers = 1")
    one = c.execute(q).rows()
    assert par == one  # bit-identical, including float bits and order


def test_parallel_path_actually_engages():
    from serenedb_tpu.parallel.pool import get_pool
    from serenedb_tpu.utils import metrics
    if get_pool().size < 2:
        pytest.skip("shared pool has a single worker on this host")
    c = _mk_conn()
    c.execute("SET serene_workers = 4")
    before = metrics.POOL_MORSELS.value
    c.execute("SELECT k, sum(v) FROM t GROUP BY k")
    assert metrics.POOL_MORSELS.value > before


def test_aggregate_matches_serial_oracle(monkeypatch):
    """The morsel path must agree with the serial CPU oracle on exact
    (integer / selection) results."""
    from serenedb_tpu.exec import morsel
    c = _mk_conn()
    q = ("SELECT k, g, count(*), sum(v), min(v), max(v), min(g), max(g) "
         "FROM t GROUP BY k, g ORDER BY k, g")
    c.execute("SET serene_workers = 4")
    par = c.execute(q).rows()
    monkeypatch.setattr(morsel, "try_parallel_aggregate",
                        lambda node, ctx: None)
    ser = c.execute(q).rows()
    assert par == ser


# -- top-k over parallel segment collectors ---------------------------------


def _mk_multi(texts_per_seg):
    from serenedb_tpu.search.analysis import get_analyzer
    from serenedb_tpu.search.index import build_field_index
    from serenedb_tpu.search.searcher import MultiSearcher, SegmentSearcher
    an = get_analyzer("text")
    ms = MultiSearcher(an)
    base = 0
    for texts in texts_per_seg:
        fi = build_field_index(texts, an)
        ms.add_segment(SegmentSearcher(fi, an, len(texts)), base)
        base += len(texts)
    return ms


def _set_global_workers(n):
    from serenedb_tpu.utils.config import REGISTRY
    old = REGISTRY.get_global("serene_workers")
    REGISTRY.set_global("serene_workers", n)
    return old


def test_topk_parity_with_ties_across_segments():
    """Identical documents in different segments score identically; the
    merged ranking must break those ties by ascending global doc id, at
    any worker count."""
    from serenedb_tpu.search.query import parse_query
    seg_texts = [
        ["quick brown fox", "lazy dog sleeps", "quick fox again"],
        ["quick brown fox", "dog and fox play", "nothing here"],
        ["quick brown fox", "brown bear", "fox fox fox den"],
    ]
    ms = _mk_multi(seg_texts)
    node = parse_query("quick fox")
    old = _set_global_workers(4)
    try:
        s4, d4 = ms.topk(node, 6)
        _set_global_workers(1)
        s1, d1 = ms.topk(node, 6)
    finally:
        _set_global_workers(old)
    np.testing.assert_array_equal(d4, d1)
    np.testing.assert_array_equal(s4, s1)
    # the three identical "quick brown fox" docs (rows 0, 3, 6) tie —
    # they must appear in ascending doc-id order
    tie_pos = [list(d4).index(i) for i in (0, 3, 6)]
    assert tie_pos == sorted(tie_pos)
    for a, b in zip(tie_pos, tie_pos[1:]):
        assert s4[a] == s4[b]


def test_cpu_topk_parallel_matches_single_heap():
    from serenedb_tpu.search.query import parse_query
    rng = np.random.default_rng(9)
    vocab = [f"w{i}" for i in range(50)]
    seg_texts = [[" ".join(rng.choice(vocab, 12)) for _ in range(200)]
                 for _ in range(4)]
    ms = _mk_multi(seg_texts)
    node = parse_query("w1 w2 w3")
    old = _set_global_workers(4)
    try:
        s4, d4 = ms.cpu_topk(node, 10)
        _set_global_workers(1)
        s1, d1 = ms.cpu_topk(node, 10)
    finally:
        _set_global_workers(old)
    np.testing.assert_array_equal(d4, d1)
    np.testing.assert_array_equal(s4, s1)
    # cpu path and device-route path agree on the ranked doc set
    sd, dd = ms.topk(node, 10)
    np.testing.assert_allclose(s1, sd, rtol=2e-3, atol=1e-3)


# -- ingest ------------------------------------------------------------------


def test_copy_ingest_parity(tmp_path):
    rng = np.random.default_rng(2)
    n = 40_000   # > 2 parse chunks of 16384
    path = tmp_path / "in.csv"
    with open(path, "w") as f:
        for i in range(n):
            s = "" if i % 97 == 0 else f"name{int(rng.integers(0, 500))}"
            f.write(f"{i},{s},{float(rng.normal()):.6f}\n")

    def ingest(workers):
        db = Database()
        c = db.connect()
        c.execute("CREATE TABLE imp (i INT, s TEXT, x DOUBLE)")
        c.execute(f"SET serene_workers = {workers}")
        res = c.execute(f"COPY imp FROM '{path}' WITH (format csv)")
        rows = c.execute("SELECT * FROM imp").rows()
        return res.command_tag, rows

    tag4, rows4 = ingest(4)
    tag1, rows1 = ingest(1)
    assert tag4 == tag1 == f"COPY {n}"
    assert rows4 == rows1
    assert len(rows4) == n


# -- cancellation / pool hygiene --------------------------------------------


def test_cancel_drains_morsels_without_poisoning_pool():
    rng = np.random.default_rng(1)
    n = 1_500_000
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE big (k INT, v BIGINT, f DOUBLE)")
    batch = Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 100, n).astype(np.int32)),
        "v": Column.from_numpy(rng.integers(0, 10 ** 6, n, dtype=np.int64)),
        "f": Column.from_numpy(rng.normal(size=n)),
    })
    db.schemas["main"].tables["big"] = MemTable("big", batch)
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_workers = 4")
    c.execute("SET serene_parallel_min_rows = 1024")
    c.execute("SET serene_morsel_rows = 2048")   # ~700 morsels to drain
    q = ("SELECT k, sum(v), avg(f), stddev(f) FROM big "
         "WHERE v % 7 <> 0 AND f * f < 9 GROUP BY k")
    timer = threading.Timer(0.05, c.request_cancel)
    timer.start()
    try:
        c.execute(q)
        cancelled = False   # machine fast enough to finish: still valid
    except SqlError as e:
        assert e.sqlstate == "57014"
        cancelled = True
    timer.cancel()
    # the pool must be fully drained — no orphan morsels left queued
    from serenedb_tpu.parallel.pool import get_pool
    pool = get_pool()
    deadline = time.monotonic() + 5.0
    while any(dq for dq in pool._deques) and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not any(dq for dq in pool._deques)
    # and the NEXT parallel query on the same pool runs clean
    c.execute("SET serene_morsel_rows = 65536")
    out = c.execute("SELECT count(*), sum(v) FROM big").rows()
    c.execute("SET serene_workers = 1")
    assert c.execute("SELECT count(*), sum(v) FROM big").rows() == out
    assert cancelled or out[0][0] == n


# -- pool unit behavior ------------------------------------------------------


def test_map_ordered_preserves_order_and_raises_lowest_index():
    from serenedb_tpu.parallel.pool import WorkerPool
    pool = WorkerPool(4).ensure_started()
    try:
        out = pool.map_ordered(lambda x: x * x, list(range(100)))
        assert out == [x * x for x in range(100)]

        def boom(x):
            if x in (7, 13):
                raise ValueError(f"bad {x}")
            time.sleep(0.001)
            return x

        with pytest.raises(ValueError, match="bad 7"):
            pool.map_ordered(boom, list(range(50)))
        # pool still serviceable after the failure drained
        assert pool.map_ordered(lambda x: x + 1, [1, 2, 3]) == [2, 3, 4]
    finally:
        pool.shutdown()


def test_nested_map_runs_inline_no_deadlock():
    from serenedb_tpu.parallel.pool import WorkerPool
    pool = WorkerPool(2).ensure_started()
    try:
        def outer(x):
            # nested fan-out from a worker thread must run inline
            return sum(pool.map_ordered(lambda y: y * 2, [x, x + 1]))

        assert pool.map_ordered(outer, [1, 2, 3, 4]) == [6, 10, 14, 18]
    finally:
        pool.shutdown()
