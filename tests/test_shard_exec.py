"""Parity suite for the sharded execution tier (ISSUE 9).

Contract under test: `serene_shards = N` partitions scans into
round-robin morsel-block shards and runs the UNCHANGED morsel / fused
device / segment-search pipelines once per shard, with the engine's
deterministic merge sinks acting as cross-shard combiners — and results
are BIT-IDENTICAL to `serene_shards = 1` (the parity oracle) across the
whole matrix: shards 1/2/4 × workers 1/4 × zonemap on/off ×
device_fused on/off, over joins, grouped aggregates, top-N, search
top-k, and empty / all-pruned shards. Plus: the shard-to-shard join
filter (per-build-shard key min/max) prunes strictly more than the
global range on gapped key distributions, `serene_shards` stays OUT of
the result cache's settings digest, and the Shard* gauges/EXPLAIN line
attribute the tier's work.
"""

import numpy as np
import pytest

from serenedb_tpu.columnar import dtypes as dt
from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.exec import shard as shard_mod
from serenedb_tpu.exec.tables import MemTable
from serenedb_tpu.utils import metrics
from serenedb_tpu.utils.config import REGISTRY as SETTINGS


def _mk_conn(nl=6000, nr=3000, seed=11):
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE l (ik INT, sk TEXT, ts BIGINT, v BIGINT)")
    c.execute("CREATE TABLE r (ik INT, sk TEXT, w BIGINT)")

    def mk(n, null_frac, sd, payload, with_ts):
        rng = np.random.default_rng(sd)
        ik = rng.integers(0, 40, n).astype(np.int32)
        ikv = rng.random(n) > null_frac
        cols = {
            "ik": Column(dt.INT, ik, ikv),
            "sk": Column.from_numpy(
                rng.choice(["alpha", "beta", "gamma", "delta"], n)),
        }
        if with_ts:
            cols["ts"] = Column.from_numpy(np.arange(n, dtype=np.int64))
        cols[payload] = Column.from_numpy(
            rng.integers(-500, 500, n, dtype=np.int64))
        return Batch.from_pydict(cols)

    db.schemas["main"].tables["l"] = MemTable(
        "l", mk(nl, 0.1, seed, "v", True))
    db.schemas["main"].tables["r"] = MemTable(
        "r", mk(nr, 0.15, seed + 1, "w", False))
    c.execute("SET serene_result_cache = off")
    c.execute("SET serene_morsel_rows = 1024")
    c.execute("SET serene_parallel_min_rows = 1024")
    return c


def _rows(c, q):
    return repr(c.execute(q).rows())


#: the parity query set: grouped aggregate over a plain scan (morsel
#: pipeline), joins scalar + grouped (fused/host), top-N, empty and
#: all-pruned shapes
QUERIES = [
    # morsel-parallel grouped aggregate (host tier)
    "SELECT sk, count(*), sum(v), avg(v), min(v), max(v) FROM l "
    "WHERE v > -400 GROUP BY sk ORDER BY sk",
    # scalar aggregate over a zone-prunable clustered predicate
    "SELECT count(*), sum(v) FROM l WHERE ts >= 1024 AND ts < 3072",
    # joins: scalar + grouped, int and dictionary-string keys
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.ik "
    "WHERE v > 0",
    "SELECT l.sk, count(*), sum(v), sum(w), min(w), max(v) FROM l "
    "JOIN r ON l.ik = r.ik GROUP BY l.sk ORDER BY l.sk",
    "SELECT l.ik, count(*), avg(w) FROM l JOIN r ON l.sk = r.sk "
    "WHERE v > 250 GROUP BY l.ik ORDER BY l.ik NULLS LAST",
    # top-N over a filtered scan
    "SELECT ts, v FROM l WHERE v > 150 ORDER BY ts DESC LIMIT 9",
    # empty result / all-pruned shards (ts is clustered: zone maps
    # prune every block)
    "SELECT count(*), sum(v) FROM l WHERE ts < -1",
    "SELECT sk, sum(v) FROM l WHERE ts < -1 GROUP BY sk ORDER BY sk",
]


@pytest.mark.parametrize("mode", ["host", "fused"])
@pytest.mark.parametrize("zonemap", ["on", "off"])
def test_shard_parity_matrix(mode, zonemap):
    """shards 1/2/4 × workers 1/4, per (device tier, zonemap) leg —
    every cell bit-identical to shards=1 at the same settings."""
    c = _mk_conn()
    if mode == "fused":
        c.execute("SET serene_device = 'tpu'")
        c.execute("SET serene_device_fused = on")
    else:
        c.execute("SET serene_device = 'cpu'")
        c.execute("SET serene_device_fused = off")
    c.execute(f"SET serene_zonemap = {zonemap}")
    for q in QUERIES:
        ref = None
        for workers in (1, 4):
            c.execute(f"SET serene_workers = {workers}")
            c.execute("SET serene_shards = 1")
            base = _rows(c, q)
            if ref is None:
                ref = base
            assert base == ref, f"workers perturbed results: {q}"
            for shards in (2, 4):
                c.execute(f"SET serene_shards = {shards}")
                got = _rows(c, q)
                assert got == ref, \
                    f"shards={shards} workers={workers} diverged: {q}"
        c.execute("SET serene_shards = 1")


def test_shard_pipelines_gauge_and_fanout():
    c = _mk_conn()
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_shards = 4")
    c.execute("SET serene_workers = 4")
    before = metrics.SHARD_PIPELINES.value
    c.execute("SELECT sk, sum(v) FROM l GROUP BY sk ORDER BY sk")
    assert metrics.SHARD_PIPELINES.value - before >= 4


def test_fused_shard_dispatch_count():
    """Sharded fused execution with the HOST combine = one build
    dispatch + one probe dispatch per non-empty shard (the PR 9 shape;
    the device-combine single dispatch is proven in
    tests/test_multichip.py)."""
    c = _mk_conn()
    c.execute("SET serene_device = 'tpu'")
    c.execute("SET serene_device_fused = on")
    c.execute("SET serene_shard_combine = host")
    q = ("SELECT l.sk, count(*), sum(v), sum(w) FROM l JOIN r "
         "ON l.ik = r.ik GROUP BY l.sk ORDER BY l.sk")
    c.execute("SET serene_shards = 1")
    ref = _rows(c, q)
    c.execute("SET serene_shards = 4")
    before = metrics.DEVICE_OFFLOADS.value
    got = _rows(c, q)
    assert got == ref
    assert metrics.DEVICE_OFFLOADS.value - before == 5  # build + 4 shards


def _gapped_join_conn():
    """Probe sorted by key (tight per-block zone ranges); build holds
    two DISJOINT key clusters, one per morsel block — so per-shard
    ranges leave a wide gap the single global range cannot prune."""
    db = Database()
    c = db.connect()
    rng = np.random.default_rng(7)
    n = 40000
    pk = np.sort(rng.integers(0, 40000, n).astype(np.int64))
    c.execute("CREATE TABLE p (k BIGINT, v BIGINT)")
    db.schemas["main"].tables["p"] = MemTable("p", Batch.from_pydict({
        "k": Column.from_numpy(pk),
        "v": Column.from_numpy(rng.integers(0, 100, n, dtype=np.int64))}))
    bk = np.concatenate([rng.integers(0, 500, 1024),
                         rng.integers(39000, 39500, 1024)]).astype(np.int64)
    c.execute("CREATE TABLE b (k BIGINT)")
    db.schemas["main"].tables["b"] = MemTable("b", Batch.from_pydict({
        "k": Column.from_numpy(bk)}))
    c.execute("SET serene_morsel_rows = 1024")
    c.execute("SET serene_result_cache = off")
    return c


def test_shard_join_filter_prunes_more_than_global():
    c = _gapped_join_conn()
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_device_fused = off")
    q = "SELECT count(*), sum(v) FROM p JOIN b ON p.k = b.k"
    c.execute("SET serene_shards = 1")
    j0 = metrics.JOIN_FILTER_PRUNED.value
    ref = _rows(c, q)
    global_pruned = metrics.JOIN_FILTER_PRUNED.value - j0
    c.execute("SET serene_shards = 2")
    j0 = metrics.JOIN_FILTER_PRUNED.value
    s0 = metrics.SHARD_MORSELS_PRUNED.value
    got = _rows(c, q)
    sharded_pruned = metrics.JOIN_FILTER_PRUNED.value - j0
    assert got == ref
    assert sharded_pruned > global_pruned, \
        "per-shard ranges should prune the inter-cluster gap"
    assert metrics.SHARD_MORSELS_PRUNED.value - s0 == sharded_pruned


def test_shard_join_filter_survives_verify_mode():
    """serene_zonemap_verify re-scans every shard-pruned block against
    every shard's range conjunction — a divergence would raise."""
    c = _gapped_join_conn()
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_shards = 2")
    q = "SELECT count(*), sum(v) FROM p JOIN b ON p.k = b.k"
    ref = _rows(c, q)
    prior = SETTINGS.get_global("serene_zonemap_verify")
    SETTINGS.set_global("serene_zonemap_verify", True)
    try:
        assert _rows(c, q) == ref
    finally:
        SETTINGS.set_global("serene_zonemap_verify", prior)


def test_fused_shard_upload_skip_bytes():
    """The device tier skips uploads for shard-pruned probe blocks and
    accounts the saved transfer in ShardBytesSkipped."""
    c = _gapped_join_conn()
    c.execute("SET serene_device = 'tpu'")
    c.execute("SET serene_device_fused = on")
    q = "SELECT count(*), sum(v) FROM p JOIN b ON p.k = b.k"
    c.execute("SET serene_shards = 1")
    ref = _rows(c, q)
    c.execute("SET serene_shards = 2")
    b0 = metrics.SHARD_BYTES_SKIPPED.value
    assert _rows(c, q) == ref
    assert metrics.SHARD_BYTES_SKIPPED.value > b0


def _search_conn():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT)")
    words = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta"]
    rng = np.random.default_rng(5)
    vals = ", ".join(f"({i}, '{' '.join(rng.choice(words, 5))}')"
                     for i in range(2000))
    c.execute(f"INSERT INTO docs VALUES {vals}")
    c.execute("CREATE INDEX ON docs USING inverted (body)")
    # appends create extra segments → a real multi-segment searcher
    for j in range(4):
        vals = ", ".join(f"({10000 + 100 * j + i}, "
                         f"'{' '.join(rng.choice(words, 5))}')"
                         for i in range(100))
        c.execute(f"INSERT INTO docs VALUES {vals}")
        c.execute("SELECT count(*) FROM docs WHERE body @@ 'alpha'")
    c.execute("SET serene_result_cache = off")
    return db, c


SEARCH_QUERIES = [
    "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'alpha | beta' "
    "ORDER BY s DESC, id LIMIT 25",
    "SELECT id FROM docs WHERE body @@ 'alpha & beta' ORDER BY id "
    "LIMIT 20",
    "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'zzz_nothing' "
    "ORDER BY s DESC LIMIT 5",
]


def test_search_topk_shard_parity():
    _db, c = _search_conn()
    for q in SEARCH_QUERIES:
        c.execute("SET serene_shards = 1")
        ref = _rows(c, q)
        for shards in (2, 4):
            c.execute(f"SET serene_shards = {shards}")
            for workers in (1, 4):
                c.execute(f"SET serene_workers = {workers}")
                assert _rows(c, q) == ref, (q, shards, workers)
        c.execute("SET serene_shards = 1")


def test_multisearcher_shard_parity_direct():
    """Segment-set sharding at the MultiSearcher layer: topk and
    cpu_topk bit-identical (scores, doc ids, tie order) at any shard
    count."""
    db, c = _search_conn()
    from serenedb_tpu.search.index import find_index
    from serenedb_tpu.search.query import parse_query
    provider = db.resolve_table(["docs"])
    ms = find_index(provider, "body").searchers["body"]
    assert len(ms.segments) > 2
    node = parse_query("alpha | gamma", ms.analyzer)
    # restore the PRIOR global afterwards — verify_tier1.sh pass 8 pins
    # it to 4 for the whole run, and hardcoding 1 here would silently
    # strip the forced sharding from every later test in that pass
    prior = SETTINGS.get_global("serene_shards")
    SETTINGS.set_global("serene_shards", 1)
    try:
        s1, d1 = ms.topk(node, 10)
        c1, cd1 = ms.cpu_topk(node, 10)
        for shards in (2, 4):
            SETTINGS.set_global("serene_shards", shards)
            s, d = ms.topk(node, 10)
            cs, cd = ms.cpu_topk(node, 10)
            assert np.array_equal(s.view(np.uint32), s1.view(np.uint32))
            assert np.array_equal(d, d1)
            assert np.array_equal(cs.view(np.uint32), c1.view(np.uint32))
            assert np.array_equal(cd, cd1)
    finally:
        SETTINGS.set_global("serene_shards", prior)


# -- unit tier ---------------------------------------------------------------


def test_shard_spans_round_robin():
    spans = shard_mod.shard_spans(10_000, 1024, 4)
    # 10 blocks round-robin over 4 shards: 3/3/2/2, tail short block
    assert [len(s) for s in spans] == [3, 3, 2, 2]
    assert spans[0][0] == (0, 1024)
    assert spans[1][0] == (1024, 2048)
    assert spans[0][1] == (4096, 5120)
    assert spans[1][-1] == (9216, 10_000)
    flat = sorted(sp for s in spans for sp in s)
    assert flat == [(i * 1024, min((i + 1) * 1024, 10_000))
                    for i in range(10)]


def test_shard_spans_append_only_touches_tail():
    """Round-robin assignment pins existing blocks to their shard: an
    append extends/creates only tail blocks, every earlier block keeps
    its shard (the zone-map append-friendliness argument)."""
    before = shard_mod.shard_spans(10_000, 1024, 4)
    after = shard_mod.shard_spans(13_000, 1024, 4)
    for s in range(4):
        for sp in before[s]:
            if sp[1] % 1024 != 0 and sp[1] != 10_000:
                continue
            full = (sp[0], min(sp[0] + 1024, 13_000))
            assert full in after[s]


def test_provider_shard_view():
    t = MemTable("t", Batch.from_pydict(
        {"a": Column.from_numpy(np.arange(5000, dtype=np.int64))}))
    view = t.shard_view(2, 1024)
    assert view == shard_mod.shard_spans(5000, 1024, 2)


def test_group_round_robin():
    assert shard_mod.group_round_robin([1, 2, 3, 4, 5], 2) == \
        [[1, 3, 5], [2, 4]]
    assert shard_mod.group_round_robin([1], 4) == [[1]]
    assert shard_mod.group_round_robin([], 4) == []


def test_serene_shards_not_result_affecting():
    """Bit-identity is the documented contract, so the sharded tier
    must never split the result cache (PR 8's serene_search_batch
    pattern)."""
    from serenedb_tpu.cache.result import RESULT_AFFECTING_SETTINGS
    assert "serene_shards" not in RESULT_AFFECTING_SETTINGS


def test_result_cache_shared_across_shard_settings():
    c = _mk_conn()
    c.execute("SET serene_result_cache = on")
    c.execute("SET serene_device = 'cpu'")
    q = "SELECT sk, sum(v) FROM l GROUP BY sk ORDER BY sk"
    c.execute("SET serene_shards = 1")
    ref = _rows(c, q)
    h0 = metrics.RESULT_CACHE_HITS.value
    c.execute("SET serene_shards = 4")
    assert _rows(c, q) == ref
    assert metrics.RESULT_CACHE_HITS.value > h0, \
        "shards=4 must hit the entry stored under shards=1"


def test_explain_analyze_shards_line():
    c = _mk_conn()
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_shards = 4")
    c.execute("SET serene_workers = 4")
    out = c.execute(
        "EXPLAIN ANALYZE SELECT sk, sum(v) FROM l GROUP BY sk "
        "ORDER BY sk").rows()
    text = "\n".join(r[0] for r in out)
    assert "Shards: n=" in text, text


def test_metrics_export_shard_gauges():
    from serenedb_tpu.obs.export import prometheus_text, stats_json
    text = prometheus_text()
    assert "serenedb_shard_pipelines" in text
    assert "serenedb_shard_morsels_pruned" in text
    assert "serenedb_shard_bytes_skipped" in text
    snap = stats_json()["metrics"]
    assert "ShardPipelines" in snap and "ShardBytesSkipped" in snap


def test_sharded_write_invalidation():
    """A write between sharded executions must surface fresh data (the
    per-shard device caches key on publications)."""
    c = _mk_conn()
    c.execute("SET serene_device = 'tpu'")
    c.execute("SET serene_device_fused = on")
    c.execute("SET serene_shards = 2")
    q = "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.ik"
    first = c.execute(q).rows()
    c.execute("INSERT INTO r VALUES (1, 'alpha', 7)")
    second = c.execute(q).rows()
    assert second != first, "write must invalidate sharded caches"
    # parity against the unsharded oracle on the NEW publication
    c.execute("SET serene_shards = 1")
    assert c.execute(q).rows() == second
