"""Geo shapes: WKT/WKB/GeoJSON codecs, predicates, measures, SQL ST_*
functions, and ES geo queries (reference parity: libs/geo/)."""

import json
import math

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError
from serenedb_tpu.geo import ops as geo_ops
from serenedb_tpu.geo import shapes as gs


# -- codecs ----------------------------------------------------------------

WKT_SAMPLES = [
    "POINT(1.0 2.0)",
    "LINESTRING(0.0 0.0, 1.0 1.0, 2.0 0.0)",
    "POLYGON((0.0 0.0, 10.0 0.0, 10.0 10.0, 0.0 10.0, 0.0 0.0))",
    "POLYGON((0.0 0.0, 10.0 0.0, 10.0 10.0, 0.0 10.0, 0.0 0.0), "
    "(4.0 4.0, 6.0 4.0, 6.0 6.0, 4.0 6.0, 4.0 4.0))",
    "MULTIPOINT(1.0 1.0, 2.0 2.0)",
    "MULTILINESTRING((0.0 0.0, 1.0 1.0), (2.0 2.0, 3.0 3.0))",
    "MULTIPOLYGON(((0.0 0.0, 1.0 0.0, 1.0 1.0, 0.0 0.0)), "
    "((5.0 5.0, 6.0 5.0, 6.0 6.0, 5.0 5.0)))",
    "GEOMETRYCOLLECTION(POINT(1.0 2.0), LINESTRING(0.0 0.0, 1.0 1.0))",
]


@pytest.mark.parametrize("wkt", WKT_SAMPLES)
def test_wkt_roundtrip(wkt):
    assert gs.to_wkt(gs.from_wkt(wkt)) == wkt


@pytest.mark.parametrize("wkt", WKT_SAMPLES)
def test_wkb_roundtrip(wkt):
    g = gs.from_wkt(wkt)
    assert gs.to_wkt(gs.from_wkb(gs.to_wkb(g))) == wkt


@pytest.mark.parametrize("wkt", WKT_SAMPLES)
def test_geojson_roundtrip(wkt):
    g = gs.from_wkt(wkt)
    assert gs.to_wkt(gs.from_geojson(gs.to_geojson(g))) == wkt


def test_wkt_forgiving_forms():
    assert gs.from_wkt("point ( 1 2 )").coords == (1.0, 2.0)
    assert gs.from_wkt("MULTIPOINT((1 2), (3 4))").coords == \
        [(1.0, 2.0), (3.0, 4.0)]
    assert gs.from_wkt("POINT EMPTY").coords == ()
    with pytest.raises(SqlError):
        gs.from_wkt("CIRCLE(1 2, 3)")
    with pytest.raises(SqlError):
        gs.from_wkt("POINT(1)")


def test_wkb_big_endian_and_ewkb_srid():
    import struct
    # big-endian point
    be = b"\x00" + struct.pack(">I", 1) + struct.pack(">dd", 3.0, 4.0)
    assert gs.from_wkb(be).coords == (3.0, 4.0)
    # EWKB with SRID flag
    ewkb = b"\x01" + struct.pack("<I", 1 | 0x20000000) + \
        struct.pack("<I", 4326) + struct.pack("<dd", 1.0, 2.0)
    assert gs.from_wkb(ewkb).coords == (1.0, 2.0)
    with pytest.raises(SqlError):
        gs.from_wkb(b"\x01\x63\x00\x00\x00")


def test_parse_any_es_formats():
    assert gs.parse_any({"lat": 40.7, "lon": -74.0}).coords == (-74.0, 40.7)
    assert gs.parse_any("40.7, -74.0").coords == (-74.0, 40.7)
    assert gs.parse_any("[-74.0, 40.7]").coords == (-74.0, 40.7)


# -- predicates ------------------------------------------------------------

SQUARE = gs.from_wkt("POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))")
DONUT = gs.from_wkt("POLYGON((0 0, 10 0, 10 10, 0 10, 0 0), "
                    "(4 4, 6 4, 6 6, 4 6, 4 4))")


def test_point_in_polygon():
    assert geo_ops.contains(SQUARE, gs.from_wkt("POINT(5 5)"))
    assert not geo_ops.contains(SQUARE, gs.from_wkt("POINT(15 5)"))
    # boundary counts as inside (ST_Covers semantics)
    assert geo_ops.contains(SQUARE, gs.from_wkt("POINT(0 5)"))
    # inside the hole is outside the donut
    assert not geo_ops.contains(DONUT, gs.from_wkt("POINT(5 5)"))
    assert geo_ops.contains(DONUT, gs.from_wkt("POINT(2 2)"))


def test_polygon_contains_shapes():
    assert geo_ops.contains(
        SQUARE, gs.from_wkt("LINESTRING(1 1, 9 9)"))
    assert not geo_ops.contains(
        SQUARE, gs.from_wkt("LINESTRING(5 5, 15 5)"))
    assert geo_ops.contains(
        SQUARE, gs.from_wkt("POLYGON((1 1, 9 1, 9 9, 1 9, 1 1))"))
    assert not geo_ops.contains(
        SQUARE, gs.from_wkt("POLYGON((5 5, 15 5, 15 15, 5 15, 5 5))"))
    # both endpoints inside but the segment crosses the hole: not contained
    assert not geo_ops.contains(
        DONUT, gs.from_wkt("LINESTRING(2 5, 8 5)"))


def test_intersects():
    assert geo_ops.intersects(gs.from_wkt("LINESTRING(0 0, 10 10)"),
                              gs.from_wkt("LINESTRING(0 10, 10 0)"))
    assert not geo_ops.intersects(gs.from_wkt("LINESTRING(0 0, 1 1)"),
                                  gs.from_wkt("LINESTRING(2 2, 3 3)"))
    assert geo_ops.intersects(SQUARE, gs.from_wkt(
        "POLYGON((5 5, 15 5, 15 15, 5 15, 5 5))"))
    assert geo_ops.intersects(SQUARE, gs.from_wkt("POINT(10 10)"))
    # polygon fully inside another intersects
    assert geo_ops.intersects(
        SQUARE, gs.from_wkt("POLYGON((1 1, 2 1, 2 2, 1 1))"))


# -- measures --------------------------------------------------------------

def test_distance_and_length():
    # one degree of latitude ≈ 111.2 km
    d = geo_ops.distance_m(gs.from_wkt("POINT(0 0)"),
                           gs.from_wkt("POINT(0 1)"))
    assert d == pytest.approx(111195, rel=1e-3)
    # point to segment: closest approach, not vertex distance
    d = geo_ops.distance_m(gs.from_wkt("POINT(5 1)"),
                           gs.from_wkt("LINESTRING(0 0, 10 0)"))
    assert d == pytest.approx(111195, rel=1e-2)
    d = geo_ops.distance_m(gs.from_wkt("POINT(5 5)"), SQUARE)
    assert d == 0.0
    ln = geo_ops.length_m(gs.from_wkt("LINESTRING(0 0, 0 1, 0 2)"))
    assert ln == pytest.approx(2 * 111195, rel=1e-3)


def test_area():
    # 1°×1° at the equator ≈ 12,364 km²
    a = geo_ops.area_m2(gs.from_wkt("POLYGON((0 0, 1 0, 1 1, 0 1, 0 0))"))
    assert a == pytest.approx(12364e6, rel=2e-2)
    # donut area = outer − hole
    outer = geo_ops.area_m2(SQUARE)
    donut = geo_ops.area_m2(DONUT)
    hole = geo_ops.area_m2(gs.from_wkt(
        "POLYGON((4 4, 6 4, 6 6, 4 6, 4 4))"))
    assert donut == pytest.approx(outer - hole, rel=1e-6)


# -- SQL surface -----------------------------------------------------------

@pytest.fixture
def conn():
    return Database().connect()


def test_sql_st_functions(conn):
    assert conn.execute(
        "SELECT ST_Contains('POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))', "
        "'POINT(5 5)')").scalar() is True
    assert conn.execute(
        "SELECT ST_Within('POINT(5 5)', "
        "'POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))')").scalar() is True
    assert conn.execute(
        "SELECT ST_Disjoint('POINT(50 50)', "
        "'POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))')").scalar() is True
    assert conn.execute(
        "SELECT ST_DWithin('POINT(0 0)', 'POINT(0 1)', 120000)"
    ).scalar() is True
    assert conn.execute(
        "SELECT ST_DWithin('POINT(0 0)', 'POINT(0 1)', 100000)"
    ).scalar() is False
    assert conn.execute(
        "SELECT ST_GeometryType('LINESTRING(0 0, 1 1)')"
    ).scalar() == "ST_LineString"
    assert conn.execute(
        "SELECT ST_NPoints('POLYGON((0 0, 1 0, 1 1, 0 0))')"
    ).scalar() == 4
    assert conn.execute(
        "SELECT ST_Centroid('POLYGON((0 0, 2 0, 2 2, 0 2, 0 0))')"
    ).scalar() == "POINT(1.0 1.0)"
    assert conn.execute(
        "SELECT ST_Envelope('LINESTRING(0 0, 3 4)')"
    ).scalar() == "POLYGON((0.0 0.0, 3.0 0.0, 3.0 4.0, 0.0 4.0, 0.0 0.0))"
    j = json.loads(conn.execute(
        "SELECT ST_AsGeoJSON('POINT(1 2)')").scalar())
    assert j == {"type": "Point", "coordinates": [1.0, 2.0]}
    # WKB hex round trip through SQL
    assert conn.execute(
        "SELECT ST_GeomFromWKB(ST_AsBinary('POINT(3 4)'))"
    ).scalar() == "POINT(3.0 4.0)"
    # geometry column filters
    conn.execute("CREATE TABLE places (name TEXT, geom TEXT)")
    conn.execute("INSERT INTO places VALUES "
                 "('in', 'POINT(5 5)'), ('out', 'POINT(50 50)'), "
                 "('edge', 'POINT(10 5)')")
    rows = conn.execute(
        "SELECT name FROM places WHERE ST_Contains("
        "'POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))', geom) "
        "ORDER BY name").rows()
    assert rows == [("edge",), ("in",)]


def test_sql_errors(conn):
    with pytest.raises(SqlError):
        conn.execute("SELECT ST_Contains('NOT A SHAPE', 'POINT(1 1)')")


# -- ES geo queries --------------------------------------------------------

def _es_server():
    from serenedb_tpu.server.http_server import HttpServer
    db = Database()
    s = HttpServer(db, port=0)
    s.start()
    return s


def _req(srv, method, path, body=None):
    import urllib.request
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}", data=data,
        headers={"Content-Type": "application/json"}, method=method)
    try:
        with urllib.request.urlopen(r, timeout=30) as resp:
            return resp.status, json.loads(resp.read().decode())
    except Exception as e:
        import urllib.error
        if isinstance(e, urllib.error.HTTPError):
            return e.code, json.loads(e.read().decode())
        raise


@pytest.fixture(scope="module")
def es():
    srv = _es_server()
    _req(srv, "PUT", "/shops")
    docs = [
        ("1", {"name": "downtown", "location": [-73.99, 40.72]}),
        ("2", {"name": "uptown", "location": [-73.95, 40.80]}),
        ("3", {"name": "far", "location": [-118.24, 34.05]}),
    ]
    for _id, d in docs:
        _req(srv, "PUT", f"/shops/_doc/{_id}", d)
    yield srv
    srv.stop()


def test_es_geo_bounding_box(es):
    status, body = _req(es, "POST", "/shops/_search", {
        "query": {"geo_bounding_box": {"location": {
            "top_left": {"lat": 40.9, "lon": -74.1},
            "bottom_right": {"lat": 40.6, "lon": -73.9}}}}})
    assert status == 200
    ids = {h["_id"] for h in body["hits"]["hits"]}
    assert ids == {"1", "2"}


def test_es_geo_distance(es):
    status, body = _req(es, "POST", "/shops/_search", {
        "query": {"geo_distance": {
            "distance": "10km",
            "location": {"lat": 40.72, "lon": -73.99}}}})
    assert status == 200
    ids = {h["_id"] for h in body["hits"]["hits"]}
    assert ids == {"1", "2"}
    status, body = _req(es, "POST", "/shops/_search", {
        "query": {"geo_distance": {
            "distance": "1km",
            "location": {"lat": 40.72, "lon": -73.99}}}})
    assert {h["_id"] for h in body["hits"]["hits"]} == {"1"}


def test_es_geo_polygon(es):
    status, body = _req(es, "POST", "/shops/_search", {
        "query": {"geo_polygon": {"location": {"points": [
            {"lat": 40.6, "lon": -74.1}, {"lat": 40.9, "lon": -74.1},
            {"lat": 40.9, "lon": -73.9}, {"lat": 40.6, "lon": -73.9}]}}}})
    assert status == 200
    assert {h["_id"] for h in body["hits"]["hits"]} == {"1", "2"}


def test_es_geo_shape(es):
    shape = {"type": "Polygon", "coordinates": [[
        [-74.1, 40.6], [-73.9, 40.6], [-73.9, 40.9], [-74.1, 40.9],
        [-74.1, 40.6]]]}
    status, body = _req(es, "POST", "/shops/_search", {
        "query": {"geo_shape": {"location": {
            "shape": shape, "relation": "within"}}}})
    assert status == 200
    assert {h["_id"] for h in body["hits"]["hits"]} == {"1", "2"}
    status, body = _req(es, "POST", "/shops/_search", {
        "query": {"geo_shape": {"location": {
            "shape": shape, "relation": "bogus"}}}})
    assert status == 400


def test_es_bad_geo_inputs(es):
    status, _ = _req(es, "POST", "/shops/_search", {
        "query": {"geo_distance": {"distance": "10 parsecs",
                                   "location": [0, 0]}}})
    assert status == 400


def test_es_geo_option_keys_tolerated(es):
    # ES option keys must not be mistaken for the field
    status, body = _req(es, "POST", "/shops/_search", {
        "query": {"geo_bounding_box": {
            "validation_method": "STRICT",
            "location": {"top_left": {"lat": 40.9, "lon": -74.1},
                         "bottom_right": {"lat": 40.6, "lon": -73.9}}}}})
    assert status == 200
    assert {h["_id"] for h in body["hits"]["hits"]} == {"1", "2"}
    status, body = _req(es, "POST", "/shops/_search", {
        "query": {"geo_distance": {"distance": "10km", "boost": 2.0,
                                   "location": [-73.99, 40.72]}}})
    assert status == 200
    # empty body → 400, not a 500
    status, _ = _req(es, "POST", "/shops/_search", {
        "query": {"geo_bounding_box": {}}})
    assert status == 400


class TestGeoIndex:
    """Cell-term geo index (reference: geo_filter_builder.cpp GeoFilter
    pushdown): candidates from posting lists + exact post-verification."""

    def _mk(self, n=120_000):
        import random

        from serenedb_tpu.engine import Database
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE gp (id INT, loc TEXT)")
        rng = random.Random(42)
        c.execute("INSERT INTO gp VALUES " + ", ".join(
            f"({i}, 'POINT({rng.uniform(-179, 179):.5f} "
            f"{rng.uniform(-85, 85):.5f})')" for i in range(n)))
        return db, c

    def test_intersects_parity_and_candidate_bound(self):
        db, c = self._mk()
        poly = "POLYGON((10 10, 20 10, 20 20, 10 20, 10 10))"
        q = f"SELECT count(*) FROM gp WHERE st_intersects(loc, '{poly}')"
        full = c.execute(q).scalar()
        c.execute("CREATE INDEX ON gp USING geo (loc)")
        plan = "\n".join(r[0] for r in c.execute("EXPLAIN " + q).rows())
        assert "GeoScan" in plan
        assert c.execute(q).scalar() == full

        # the index must narrow candidates to a small fraction of the
        # table — the point of cell terms vs the old per-row post-filter
        from serenedb_tpu.exec.search_scan import GeoScanNode
        from serenedb_tpu.geo import cells as geo_cells
        from serenedb_tpu.geo import shapes as geo_shapes
        from serenedb_tpu.search.index import find_geo_index
        t = db.resolve_table(["gp"])
        idx = find_geo_index(t, "loc")
        probe = geo_cells.query_terms(geo_shapes.parse_any(poly))
        cand = len(idx.candidates(probe))
        assert cand < t.row_count() // 50, \
            f"geo index barely narrows: {cand} of {t.row_count()}"
        assert cand >= full

    def test_dwithin_parity(self):
        db, c = self._mk(50_000)
        q = ("SELECT count(*) FROM gp WHERE "
             "st_dwithin(loc, 'POINT(0 0)', 500000)")
        full = c.execute(q).scalar()
        c.execute("CREATE INDEX ON gp USING geo (loc)")
        plan = "\n".join(r[0] for r in c.execute("EXPLAIN " + q).rows())
        assert "GeoScan" in plan
        assert c.execute(q).scalar() == full

    def test_polygons_indexed_coarse_query_fine(self):
        """A big indexed polygon must be found by a tiny query (ancestor
        terms), and a tiny indexed point by a big query."""
        from serenedb_tpu.engine import Database
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE gs (id INT, g TEXT)")
        c.execute("INSERT INTO gs VALUES "
                  "(1, 'POLYGON((-60 -30, 60 -30, 60 30, -60 30, -60 -30))'), "
                  "(2, 'POINT(0.001 0.001)'), "
                  "(3, 'POINT(100 50)')")
        c.execute("CREATE INDEX ON gs USING geo (g)")
        q = ("SELECT id FROM gs WHERE "
             "st_intersects(g, 'POLYGON((-0.01 -0.01, 0.01 -0.01, "
             "0.01 0.01, -0.01 0.01, -0.01 -0.01))') ORDER BY id")
        plan = "\n".join(r[0] for r in c.execute("EXPLAIN " + q).rows())
        assert "GeoScan" in plan
        assert c.execute(q).rows() == [(1,), (2,)]
        big = ("SELECT id FROM gs WHERE st_intersects(g, "
               "'POLYGON((-170 -80, 170 -80, 170 80, -170 80, -170 -80))')"
               " ORDER BY id")
        assert c.execute(big).rows() == [(1,), (2,), (3,)]

    def test_index_repairs_on_dml(self):
        from serenedb_tpu.engine import Database
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE gd (id INT, g TEXT)")
        c.execute("INSERT INTO gd VALUES (1, 'POINT(5 5)')")
        c.execute("CREATE INDEX ON gd USING geo (g)")
        c.execute("INSERT INTO gd VALUES (2, 'POINT(5.01 5.01)')")
        q = ("SELECT count(*) FROM gd WHERE st_dwithin(g, "
             "'POINT(5 5)', 10000)")
        assert c.execute(q).scalar() == 2
        c.execute("DELETE FROM gd WHERE id = 1")
        assert c.execute(q).scalar() == 1


class TestGeoIndexRegressions:
    def test_dwithin_across_antimeridian(self):
        from serenedb_tpu.engine import Database
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE am (id INT, loc TEXT)")
        c.execute("INSERT INTO am VALUES (1, 'POINT(-179.9 0)'), "
                  "(2, 'POINT(179.9 0)')")
        q = ("SELECT count(*) FROM am WHERE "
             "st_dwithin(loc, 'POINT(179.9 0)', 50000)")
        full = c.execute(q).scalar()
        c.execute("CREATE INDEX ON am USING geo (loc)")
        assert c.execute(q).scalar() == full == 2

    def test_null_radius_falls_back(self):
        from serenedb_tpu.engine import Database
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE nr (loc TEXT)")
        c.execute("INSERT INTO nr VALUES ('POINT(0 0)')")
        # with and without an index: NULL radius must not crash planning
        assert c.execute("SELECT count(*) FROM nr WHERE "
                         "st_dwithin(loc, 'POINT(0 0)', NULL)").scalar() == 0
        c.execute("CREATE INDEX ON nr USING geo (loc)")
        assert c.execute("SELECT count(*) FROM nr WHERE "
                         "st_dwithin(loc, 'POINT(0 0)', NULL)").scalar() == 0


class TestGeoPoleAndErrors:
    def test_dwithin_over_the_pole(self):
        from serenedb_tpu.engine import Database
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE pp (loc TEXT)")
        c.execute("INSERT INTO pp VALUES ('POINT(0 89.9)'), "
                  "('POINT(180 89.9)')")
        q = ("SELECT count(*) FROM pp WHERE "
             "st_dwithin(loc, 'POINT(0 89.9)', 30000)")
        full = c.execute(q).scalar()
        c.execute("CREATE INDEX ON pp USING geo (loc)")
        assert c.execute(q).scalar() == full == 2

    def test_unparseable_geometry_fails_build(self):
        import pytest

        from serenedb_tpu.engine import Database
        from serenedb_tpu.errors import SqlError
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE bad (loc TEXT)")
        c.execute("INSERT INTO bad VALUES ('POINT(1 1)'), ('not wkt')")
        with pytest.raises(SqlError):
            c.execute("CREATE INDEX ON bad USING geo (loc)")


# -- adaptive covering levels (S2 RegionCoverer analog) --------------------

def test_point_covering_uses_finest_level():
    from serenedb_tpu.geo import cells
    terms = cells.point_terms(13.4, 52.5)
    levels = sorted({(t & ~(1 << 62)) >> 56 for t in terms})
    assert max(levels) == max(cells.LEVELS)   # ~38m tiles for points
    # one covering cell + one ancestor per coarser level
    assert len(terms) == len(cells.LEVELS)


def test_large_polygon_stays_coarse():
    from serenedb_tpu.geo import cells, shapes
    g = shapes.parse_any(
        "POLYGON((-30 -30, 30 -30, 30 30, -30 30, -30 -30))")
    terms = cells.geometry_terms(g)
    levels = {(t & ~(1 << 62)) >> 56 for t in terms}
    assert max(levels) <= 8   # continental extent: coarse covering


def test_city_density_candidate_selectivity():
    """At city density (100k points inside ~10km x 10km), a small-radius
    query's probed terms must select a tiny candidate fraction — the
    over-fetch the fixed level-12 scheme had (VERDICT r4 weak #7)."""
    import numpy as np

    from serenedb_tpu.geo import cells, shapes
    rng = np.random.default_rng(11)
    n = 100_000
    lons = 13.30 + rng.random(n) * 0.15    # ~10km box (Berlin-ish)
    lats = 52.45 + rng.random(n) * 0.10
    # index: term -> count of points carrying it (covering space only)
    from collections import Counter
    counts = Counter()
    for lon, lat in zip(lons.tolist(), lats.tolist()):
        for t in cells.point_terms(lon, lat):
            counts[t] += 1
    probe = cells.query_terms(
        shapes.parse_any("POINT(13.375 52.5)"), radius_m=200.0)
    candidates = sum(counts.get(t, 0) for t in probe)
    # exact matches ~ pi*r^2 density ~= 170; allow generous tile slack,
    # but the candidate set must stay far below a level-12 tile's
    # ~whole-city catchment (the old behavior pulled ~all 100k rows)
    assert candidates < 4000, candidates
    assert candidates > 0


def test_query_across_levels_still_matches(geo_conn=None):
    """Intersecting shapes indexed at different adaptive levels share a
    term (the covering/ancestor invariant with the widened LEVELS)."""
    from serenedb_tpu.geo import cells, shapes
    point = shapes.parse_any("POINT(10.0 50.0)")
    big = shapes.parse_any(
        "POLYGON((0 40, 20 40, 20 60, 0 60, 0 40))")
    small_q = set(cells.query_terms(point))
    big_idx = set(cells.geometry_terms(big))
    assert small_q & big_idx
    big_q = set(cells.query_terms(big))
    small_idx = set(cells.point_terms(10.0, 50.0))
    assert big_q & small_idx
