import numpy as np
import pytest

from serenedb_tpu.columnar import (Batch, Column, concat_batches, dtypes,
                                   to_device_column)


def test_int_column_roundtrip():
    c = Column.from_pylist([1, 2, None, 4])
    assert c.type == dtypes.BIGINT
    assert c.to_pylist() == [1, 2, None, 4]
    assert c.has_nulls


def test_string_dictionary_sorted_codes_compare_like_strings():
    c = Column.from_pylist(["pear", "apple", "pear", None, "banana"])
    assert c.type == dtypes.VARCHAR
    assert c.to_pylist() == ["pear", "apple", "pear", None, "banana"]
    # sorted dictionary: code order == lexicographic order
    d = list(c.dictionary)
    assert d == sorted(d)
    codes = c.data
    assert (codes[0] > codes[1]) == ("pear" > "apple")


def test_filter_take_slice():
    b = Batch.from_pydict({"a": [1, 2, 3, 4], "s": ["x", "y", "z", "w"]})
    f = b.filter(np.array([True, False, True, False]))
    assert f.to_pydict() == {"a": [1, 3], "s": ["x", "z"]}
    assert b.slice(1, 3).to_pydict() == {"a": [2, 3], "s": ["y", "z"]}


def test_concat_merges_dictionaries():
    b1 = Batch.from_pydict({"s": ["b", "a"]})
    b2 = Batch.from_pydict({"s": ["c", "a"]})
    c = concat_batches([b1, b2])
    assert c.to_pydict() == {"s": ["b", "a", "c", "a"]}
    col = c.column("s")
    assert list(col.dictionary) == ["a", "b", "c"]


def test_device_column_padding_and_mask():
    c = Column.from_pylist(list(range(10)))
    dc = to_device_column(c)
    assert dc.data.shape == (8, 128)
    assert dc.length == 10
    assert int(dc.mask.sum()) == 10
    np.testing.assert_array_equal(
        np.asarray(dc.data).reshape(-1)[:10], np.arange(10))


def test_device_column_nulls_not_in_mask():
    c = Column.from_pylist([1, None, 3])
    dc = to_device_column(c)
    m = np.asarray(dc.mask).reshape(-1)
    assert m[:3].tolist() == [True, False, True]


def test_numpy_column_infers_type():
    c = Column.from_numpy(np.array([1.5, 2.5], dtype=np.float64))
    assert c.type == dtypes.DOUBLE
    c32 = Column.from_numpy(np.array([1, 2], dtype=np.int32))
    assert c32.type == dtypes.INT


def test_common_numeric_widening():
    assert dtypes.common_numeric(dtypes.INT, dtypes.DOUBLE) == dtypes.DOUBLE
    assert dtypes.common_numeric(dtypes.BOOL, dtypes.BIGINT) == dtypes.BIGINT
    with pytest.raises(TypeError):
        dtypes.common_numeric(dtypes.VARCHAR, dtypes.INT)
