"""Regression tests for review findings (stale device cache, outer-join
semantics, lexer hang, sort precision, PG rounding)."""

import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError


def test_device_cache_invalidated_on_insert():
    db = Database()
    c = db.connect()
    c.execute("SET serene_device = 'tpu'")
    c.execute("CREATE TABLE t (k INT, v INT)")
    c.execute("INSERT INTO t VALUES (10,1),(11,2),(12,3)")
    r1 = c.execute("SELECT k, sum(v) FROM t GROUP BY k ORDER BY k").rows()
    assert r1 == [(10, 1), (11, 2), (12, 3)]
    c.execute("INSERT INTO t VALUES (5, 100)")
    r2 = c.execute("SELECT k, sum(v) FROM t GROUP BY k ORDER BY k").rows()
    assert r2 == [(5, 100), (10, 1), (11, 2), (12, 3)]


def test_left_join_on_extra_condition_stays_outer():
    c = Database().connect()
    c.execute("CREATE TABLE a (id INT, x INT)")
    c.execute("CREATE TABLE b (id INT, y INT)")
    c.execute("INSERT INTO a VALUES (1,10),(2,20)")
    c.execute("INSERT INTO b VALUES (1,5)")
    rows = c.execute("SELECT a.id, b.y FROM a LEFT JOIN b "
                     "ON a.id = b.id AND b.y > 100 ORDER BY a.id").rows()
    assert rows == [(1, None), (2, None)]


def test_left_join_empty_right():
    c = Database().connect()
    c.execute("CREATE TABLE a (id INT)")
    c.execute("CREATE TABLE b (id INT, y INT)")
    c.execute("INSERT INTO a VALUES (1),(2)")
    rows = c.execute("SELECT a.id, b.y FROM a LEFT JOIN b ON a.id = b.id "
                     "ORDER BY a.id").rows()
    assert rows == [(1, None), (2, None)]


def test_right_join():
    c = Database().connect()
    c.execute("CREATE TABLE a (id INT, x TEXT)")
    c.execute("CREATE TABLE b (id INT, y TEXT)")
    c.execute("INSERT INTO a VALUES (1,'a')")
    c.execute("INSERT INTO b VALUES (1,'A'),(2,'B')")
    rows = c.execute("SELECT a.x, b.y FROM a RIGHT JOIN b ON a.id = b.id "
                     "ORDER BY b.y").rows()
    assert rows == [("a", "A"), (None, "B")]


def test_unterminated_dollar_quote_errors_not_hangs():
    c = Database().connect()
    with pytest.raises(SqlError) as e:
        c.execute("select $abc")
    assert e.value.sqlstate == "42601"


def test_order_by_bigint_beyond_2_53():
    c = Database().connect()
    c.execute("CREATE TABLE t (v BIGINT)")
    c.execute("INSERT INTO t VALUES (9007199254740993), (9007199254740992)")
    rows = c.execute("SELECT v FROM t ORDER BY v").rows()
    assert rows == [(9007199254740992,), (9007199254740993,)]


def test_cast_rounds_half_away_from_zero():
    c = Database().connect()
    assert c.execute("SELECT CAST(0.5 AS INT)").scalar() == 1
    assert c.execute("SELECT CAST(1.5 AS INT)").scalar() == 2
    assert c.execute("SELECT CAST(2.5 AS INT)").scalar() == 3
    assert c.execute("SELECT CAST(-0.5 AS INT)").scalar() == -1


def test_device_is_not_null_predicate():
    # fuzz-found: the binder named IS NULL and IS NOT NULL identically, so
    # the device compiler always emitted the IS NULL mask
    c = Database().connect()
    c.execute("CREATE TABLE nn (a INT, g INT)")
    c.execute("INSERT INTO nn VALUES (1, 0), (NULL, 0), (2, 1), (NULL, 1),"
              " (3, 1)")
    for dev in ("cpu", "tpu"):
        c.execute(f"SET serene_device = '{dev}'")
        c.execute("SET serene_device_min_rows = 1")
        assert c.execute(
            "SELECT count(*) FROM nn WHERE a IS NOT NULL").scalar() == 3
        assert c.execute(
            "SELECT count(*) FROM nn WHERE a IS NULL").scalar() == 2
        rows = c.execute("SELECT g, sum(a) FROM nn WHERE a IS NOT NULL "
                         "GROUP BY g ORDER BY g").rows()
        assert rows == [(0, 1), (1, 5)]


def test_sum_over_varchar_errors_not_codes():
    # probe-found: sum/avg over a string column silently aggregated the
    # dictionary CODES (sum('4','5','6') returned 3.0)
    c = Database().connect()
    c.execute("CREATE TABLE sv (v TEXT)")
    c.execute("INSERT INTO sv VALUES ('4'), ('5'), ('6')")
    for fn in ("sum", "avg"):
        with pytest.raises(SqlError) as e:
            c.execute(f"SELECT {fn}(v) FROM sv")
        assert e.value.sqlstate == "42883"
    # min/max on strings stay legal (lexicographic)
    assert c.execute("SELECT min(v), max(v) FROM sv").rows() == [("4", "6")]
