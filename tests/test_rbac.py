"""RBAC: roles, grants, enforcement, persistence."""

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError


@pytest.fixture
def db():
    d = Database()
    c = d.connect()
    c.execute("CREATE TABLE secrets (v TEXT)")
    c.execute("INSERT INTO secrets VALUES ('classified')")
    c.execute("CREATE ROLE bob PASSWORD 'pw'")
    return d


def test_role_denied_then_granted(db):
    admin = db.connect()
    bob = db.connect()
    bob.execute("SET ROLE bob")
    with pytest.raises(SqlError) as e:
        bob.execute("SELECT * FROM secrets")
    assert e.value.sqlstate == "42501"
    admin.execute("GRANT SELECT ON secrets TO bob")
    assert bob.execute("SELECT v FROM secrets").scalar() == "classified"
    # write still denied
    with pytest.raises(SqlError):
        bob.execute("INSERT INTO secrets VALUES ('x')")
    admin.execute("GRANT INSERT, DELETE ON secrets TO bob")
    bob.execute("INSERT INTO secrets VALUES ('x')")
    bob.execute("DELETE FROM secrets WHERE v = 'x'")
    admin.execute("REVOKE SELECT ON secrets FROM bob")
    with pytest.raises(SqlError):
        bob.execute("SELECT * FROM secrets")


def test_public_grant(db):
    admin = db.connect()
    admin.execute("CREATE ROLE alice")
    admin.execute("GRANT SELECT ON secrets TO public")
    alice = db.connect()
    alice.execute("SET ROLE alice")
    assert alice.execute("SELECT count(*) FROM secrets").scalar() == 1


def test_reset_role_and_unknown_role(db):
    c = db.connect()
    c.execute("SET ROLE bob")
    c.execute("RESET ROLE")
    assert c.execute("SELECT count(*) FROM secrets").scalar() == 1
    with pytest.raises(SqlError):
        c.execute("SET ROLE nobody")


def test_drop_role_removes_grants(db):
    admin = db.connect()
    admin.execute("GRANT SELECT ON secrets TO bob")
    admin.execute("DROP ROLE bob")
    with pytest.raises(SqlError):
        admin.execute("SET ROLE bob")
    with pytest.raises(SqlError):
        admin.execute("DROP ROLE serene")  # bootstrap superuser protected


def test_system_catalogs_not_blocked(db):
    c = db.connect()
    c.execute("SET ROLE bob")
    # introspection stays open (reference surfaces catalogs to all roles)
    assert c.execute("SELECT count(*) FROM sdb_settings").scalar() > 0


def test_rbac_persists(tmp_path):
    d = str(tmp_path / "data")
    db1 = Database(d)
    c = db1.connect()
    c.execute("CREATE TABLE t (a INT)")
    c.execute("CREATE ROLE carol PASSWORD 's3'")
    c.execute("GRANT SELECT ON t TO carol")
    db1.close()
    db2 = Database(d)
    c2 = db2.connect()
    c2.execute("SET ROLE carol")
    assert c2.execute("SELECT count(*) FROM t").scalar() == 0
    with pytest.raises(SqlError):
        c2.execute("INSERT INTO t VALUES (1)")
    db2.close()


def test_wire_auth_against_roles():
    import asyncio
    import threading
    import sys
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_pgwire import RawPg
    from serenedb_tpu.server.pgwire import PgServer
    db = Database()
    admin = db.connect()
    admin.execute("CREATE TABLE t (a INT)")
    admin.execute("INSERT INTO t VALUES (1)")
    admin.execute("CREATE ROLE dave PASSWORD 'pw'")
    admin.execute("GRANT SELECT ON t TO dave")
    srv = PgServer(db, port=0)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def go():
            await srv.start()
            started.set()
            await asyncio.Event().wait()
        try:
            loop.run_until_complete(go())
        except RuntimeError:
            pass
    threading.Thread(target=run, daemon=True).start()
    started.wait(10)
    # correct password: session runs as dave with dave's privileges
    c = RawPg(srv.port, user="dave", password="pw")
    assert c.query("SELECT a FROM t")[1] == [("1",)]
    _, _, _, errs = c.query("INSERT INTO t VALUES (2)")
    assert errs and errs[0]["C"] == "42501"
    c.close()
    # wrong password rejected
    import pytest as _pytest
    with _pytest.raises(AssertionError):
        RawPg(srv.port, user="dave", password="wrong")
    loop.call_soon_threadsafe(loop.stop)


def test_non_superuser_cannot_ddl(db):
    bob = db.connect()
    bob.execute("SET ROLE bob")
    for sql in ["DROP TABLE secrets", "ALTER TABLE secrets ADD COLUMN x INT",
                "CREATE ROLE eve", "GRANT SELECT ON secrets TO bob",
                "CREATE INDEX ON secrets USING inverted (v)"]:
        with pytest.raises(SqlError) as e:
            bob.execute(sql)
        assert e.value.sqlstate == "42501", sql
    # creating an own table works and is fully usable
    bob.execute("CREATE TABLE bobs (n INT)")
    bob.execute("INSERT INTO bobs VALUES (1)")
    assert bob.execute("SELECT n FROM bobs").scalar() == 1


def test_set_role_cannot_escalate(db):
    bob = db.connect()
    bob.session_role = "bob"
    bob.current_role = "bob"
    with pytest.raises(SqlError) as e:
        bob.execute("SET ROLE serene")
    assert e.value.sqlstate == "42501"
    with pytest.raises(SqlError):
        bob.execute("RESET ROLE; DROP TABLE secrets")  # reset -> still bob
    bob.execute("SET ROLE bob")  # own role always allowed


def test_insert_only_role(db):
    admin = db.connect()
    admin.execute("GRANT INSERT ON secrets TO bob")
    bob = db.connect()
    bob.session_role = "bob"
    bob.current_role = "bob"
    bob.execute("INSERT INTO secrets VALUES ('logline')")  # no SELECT needed
    with pytest.raises(SqlError):
        bob.execute("SELECT * FROM secrets")


def test_grant_on_view_clean_error(db):
    admin = db.connect()
    admin.execute("CREATE VIEW sv AS SELECT v FROM secrets")
    with pytest.raises(SqlError) as e:
        admin.execute("GRANT SELECT ON sv TO bob")
    assert e.value.sqlstate == "42809"


def test_dictionary_ddl_superuser_only(db):
    bob = db.connect()
    bob.session_role = "bob"
    bob.current_role = "bob"
    with pytest.raises(SqlError) as e:
        bob.execute("CREATE TEXT SEARCH DICTIONARY bobd(template = 'text')")
    assert e.value.sqlstate == "42501"
    admin = db.connect()
    admin.execute("CREATE TEXT SEARCH DICTIONARY dropd(template = 'text')")
    with pytest.raises(SqlError) as e:
        bob.execute("DROP TEXT SEARCH DICTIONARY dropd")
    assert e.value.sqlstate == "42501"
    admin.execute("DROP TEXT SEARCH DICTIONARY dropd")


def test_role_passwords_never_stored_plaintext(tmp_path):
    import json as _json
    db = Database(str(tmp_path / "data"))
    c = db.connect()
    c.execute("CREATE ROLE sec LOGIN PASSWORD 'hunter2'")
    db.close()
    blob = "".join(open(f).read() for f in
                   (tmp_path / "data").glob("*.json"))
    assert "hunter2" not in blob
    assert "stored_key" in blob
    # verifier works after reload
    db2 = Database(str(tmp_path / "data"))
    assert db2.roles.scram_verifier("sec") is not None
    assert db2.roles.has_password("sec")
    db2.close()


def test_alter_role_password_rotation():
    db = Database()
    c = db.connect()
    c.execute("CREATE ROLE rot LOGIN PASSWORD 'old'")
    v1 = db.roles.scram_verifier("rot")
    c.execute("ALTER ROLE rot PASSWORD 'new'")
    v2 = db.roles.scram_verifier("rot")
    assert v1 != v2 and v2 is not None
    c.execute("ALTER ROLE rot PASSWORD NULL")
    assert db.roles.scram_verifier("rot") is None
    assert not db.roles.has_password("rot")
    c.execute("ALTER ROLE rot NOLOGIN")
    assert not db.roles.can_login("rot")
    c.execute("ALTER ROLE rot LOGIN SUPERUSER")
    assert db.roles.can_login("rot") and db.roles.is_superuser("rot")
    with pytest.raises(SqlError) as e:
        c.execute("ALTER ROLE ghost PASSWORD 'x'")
    assert e.value.sqlstate == "42704"
    with pytest.raises(SqlError):
        c.execute("ALTER ROLE serene NOLOGIN")
    # non-superusers cannot alter roles
    c.execute("CREATE ROLE peon LOGIN")
    c2 = db.connect()
    c2.execute("SET ROLE peon")
    with pytest.raises(SqlError) as e:
        c2.execute("ALTER ROLE rot PASSWORD 'pwn'")
    assert e.value.sqlstate == "42501"


def test_alter_role_option_validation():
    c = Database().connect()
    c.execute("CREATE ROLE optr LOGIN")
    for bad in ["ALTER ROLE optr",
                "ALTER ROLE optr LOGIN NOLOGIN",
                "ALTER ROLE optr PASSWORD 'a' PASSWORD 'b'",
                "ALTER ROLE optr SUPERUSER NOSUPERUSER"]:
        with pytest.raises(SqlError) as e:
            c.execute(bad)
        assert e.value.sqlstate == "42601", bad
    c.execute("ALTER ROLE optr WITH NOLOGIN")   # WITH prefix still legal


def test_returning_requires_select_privilege():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE secret (v TEXT)")
    c.execute("INSERT INTO secret VALUES ('classified')")
    c.execute("CREATE ROLE bob LOGIN")
    c.execute("GRANT DELETE ON secret TO bob")
    c2 = db.connect()
    c2.execute("SET ROLE bob")
    with pytest.raises(SqlError) as e:
        c2.execute("DELETE FROM secret RETURNING *")
    assert e.value.sqlstate == "42501"
    # plain DELETE still allowed
    c2.execute("DELETE FROM secret")
    assert c.execute("SELECT count(*) FROM secret").scalar() == 0
