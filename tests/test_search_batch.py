"""Batched ragged search serving (search/batcher.py): parity matrix,
coalescing mechanics, error isolation, metrics, and the cache contract.

The core contract under test: per-query top-k results are BIT-IDENTICAL
(scores, doc ids, tie order) between `serene_search_batch = on` (queries
coalesce into shared scoring dispatches) and `= off` (the serial-dispatch
parity oracle), at any worker count, with the fragment cache on or off —
which is also exactly why serene_search_batch stays out of the result
cache's RESULT_AFFECTING_SETTINGS digest.
"""

import threading
import time

import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.search.analysis import get_analyzer
from serenedb_tpu.search.batcher import BATCHER, SearchBatcher, batched_topk
from serenedb_tpu.search.query import parse_query
from serenedb_tpu.search.searcher import MultiSearcher, SegmentSearcher
from serenedb_tpu.search.segment import build_field_index
from serenedb_tpu.utils import metrics

WORDS = ("apple banana cherry quick brown fox jumps over lazy dog search "
         "engine database index query term").split()


def _make_db(n=600, seed=7):
    rng = np.random.default_rng(seed)
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT)")
    vals = []
    for i in range(n):
        if i % 97 == 0:
            vals.append(f"({i}, NULL)")          # NULL text rows
        elif i % 13 == 0:
            # tie-heavy: identical docs score identically — tie order
            # must be the deterministic doc-id order in both modes
            vals.append(f"({i}, 'apple banana apple')")
        else:
            body = " ".join(rng.choice(WORDS, rng.integers(3, 24)))
            vals.append(f"({i}, '{body}')")
    c.execute("INSERT INTO docs VALUES " + ", ".join(vals))
    c.execute("CREATE INDEX ON docs USING inverted (body)")
    return db


@pytest.fixture(scope="module")
def db():
    return _make_db()


#: the parity query set: single-term, 2-term conjunction, phrase,
#: filtered (residual keeps it off the top-k pushdown → stream+score
#: path), tie-heavy, empty-result, k > hits, and a tfidf scorer
QUERIES = [
    ("SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple' "
     "ORDER BY s DESC LIMIT 10"),
    ("SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple & banana' "
     "ORDER BY s DESC LIMIT 10"),
    ("SELECT id, bm25(body) AS s FROM docs WHERE body ## 'quick brown' "
     "ORDER BY s DESC LIMIT 10"),
    ("SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple | dog' "
     "AND id < 300 ORDER BY s DESC, id LIMIT 10"),
    ("SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'banana' "
     "ORDER BY s DESC LIMIT 10"),
    ("SELECT id FROM docs WHERE body @@ 'zzzznothing' "
     "ORDER BY bm25(body) DESC LIMIT 5"),
    ("SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'quick & fox' "
     "ORDER BY s DESC LIMIT 5000"),
    ("SELECT id, tfidf(body) AS s FROM docs WHERE body @@ 'cherry | dog' "
     "ORDER BY s DESC LIMIT 10"),
]


def _run_queries(db, queries, batch, workers, cache, threads=4):
    """Each query executed `threads` times concurrently on separate
    sessions; returns {query: [rows per thread]}."""
    out = {}
    errs = []

    def run(q, slot, res):
        try:
            conn = db.connect()
            conn.execute(f"SET serene_search_batch = {batch}")
            conn.execute(f"SET serene_workers = {workers}")
            conn.execute(f"SET serene_result_cache = {cache}")
            bar.wait(timeout=30)
            res[slot] = conn.execute(q).rows()
        except Exception as e:                     # pragma: no cover
            errs.append(e)

    for q in queries:
        res = [None] * threads
        bar = threading.Barrier(threads)
        ts = [threading.Thread(target=run, args=(q, i, res))
              for i in range(threads)]
        [t.start() for t in ts]
        [t.join(timeout=60) for t in ts]
        assert not errs, errs
        out[q] = res
    return out


def test_parity_matrix(db):
    """batched on/off × workers 1/4 × fragment cache on/off: every
    combination returns the serial oracle's exact rows (scores included —
    engine rows surface the f32 bits as python floats)."""
    # oracle context: defaults except batching off
    oc = db.connect()
    oc.execute("SET serene_search_batch = off")
    oc.execute("SET serene_result_cache = off")
    oc.execute("SET serene_workers = 1")
    oracle = {q: oc.execute(q).rows() for q in QUERIES}
    for batch in ("on", "off"):
        for workers in (1, 4):
            for cache in ("on", "off"):
                got = _run_queries(db, QUERIES, batch, workers, cache)
                for q in QUERIES:
                    for rows in got[q]:
                        assert rows == oracle[q], \
                            (batch, workers, cache, q, rows, oracle[q])


def test_query_batched_with_itself(db):
    """The same query coalescing with itself (8 concurrent submissions)
    returns identical rows on every thread."""
    q = QUERIES[0]
    oc = db.connect()
    oc.execute("SET serene_search_batch = off")
    oc.execute("SET serene_result_cache = off")
    ref = oc.execute(q).rows()
    got = _run_queries(db, [q], "on", 4, "off", threads=8)
    assert all(rows == ref for rows in got[q])


def test_ragged_path_parity_packed_regime(db, monkeypatch):
    """Force the packed-plane regime (no dense matmul) so the ragged host
    resolver actually fires on this corpus, then assert searcher-level
    bit parity: batched+ragged vs solo dispatch, including duplicate
    nodes, ties, and k > hits."""
    from serenedb_tpu.ops import bm25 as bm25_ops
    monkeypatch.setattr(bm25_ops, "DENSE_HBM_BUDGET", 0)
    an = get_analyzer("text")
    rng = np.random.default_rng(11)
    docs = [" ".join(rng.choice(WORDS, rng.integers(3, 24)))
            for _ in range(700)]
    docs[::13] = ["apple banana apple"] * len(docs[::13])   # ties
    fi = build_field_index(docs, an)
    ms = MultiSearcher(an)
    ms.add_segment(SegmentSearcher(fi, an, len(docs)), 0)
    qs = ["apple", "apple | dog", "apple & banana", '"quick brown"',
          "zzznothing", "banana | fox | dog", "apple"]
    nodes = [parse_query(q, an) for q in qs]
    for k in (3, 10, 5000):
        solo = [ms.topk_batch([n], k)[0] for n in nodes]
        batched = ms.topk_batch(nodes, k, ragged=True)
        for i in range(len(nodes)):
            assert np.array_equal(batched[i][0].view(np.uint32),
                                  solo[i][0].view(np.uint32)), (k, qs[i])
            assert np.array_equal(batched[i][1], solo[i][1]), (k, qs[i])


def test_multi_segment_ragged_parity(monkeypatch):
    """Global idf/avgdl spanning segments: ragged batched per-segment
    results merge to the solo bits."""
    from serenedb_tpu.ops import bm25 as bm25_ops
    monkeypatch.setattr(bm25_ops, "DENSE_HBM_BUDGET", 0)
    an = get_analyzer("text")
    ms = MultiSearcher(an)
    base = 0
    for si in range(3):
        rng = np.random.default_rng(20 + si)
        docs = [" ".join(rng.choice(WORDS, rng.integers(3, 24)))
                for _ in range(300 + 40 * si)]
        fi = build_field_index(docs, an)
        ms.add_segment(SegmentSearcher(fi, an, len(docs)), base)
        base += len(docs)
    nodes = [parse_query(q, an)
             for q in ("apple", "apple | dog", "cherry | term")]
    solo = [ms.topk_batch([n], 10)[0] for n in nodes]
    batched = ms.topk_batch(nodes, 10, ragged=True)
    for i in range(len(nodes)):
        assert np.array_equal(batched[i][0].view(np.uint32),
                              solo[i][0].view(np.uint32))
        assert np.array_equal(batched[i][1], solo[i][1])


# -- batcher mechanics (stub searcher) ------------------------------------


class _StubSearcher:
    def __init__(self, delay=0.0, poison=None):
        self.delay = delay
        self.poison = poison
        self.calls: list[list] = []
        self._lock = threading.Lock()

    def topk_batch(self, nodes, k, scorer="bm25", mesh_n=0, ragged=False):
        with self._lock:
            self.calls.append(list(nodes))
        if self.delay:
            time.sleep(self.delay)
        if self.poison is not None and any(n is self.poison for n in nodes):
            raise ValueError("poisoned query")
        return [(np.asarray([float(len(nodes))], dtype=np.float32),
                 np.asarray([hash(n) % 97], dtype=np.int64))
                for n in nodes]

    def topk(self, node, k, scorer="bm25", mesh_n=0):
        return self.topk_batch([node], k, scorer, mesh_n)[0]

    def probe_topk(self, node, k, scorer="bm25", mesh_n=0):
        return None


def test_batcher_coalesces_under_load():
    """While one dispatch is in flight, arrivals queue and fold into the
    next dispatch — group-commit batching."""
    b = SearchBatcher()
    stub = _StubSearcher(delay=0.15)
    results = {}

    def submit(name):
        results[name] = b.submit(stub, name, 10, "bm25", 0, 0.5, 128)

    t1 = threading.Thread(target=submit, args=("q0",))
    t1.start()
    time.sleep(0.05)          # q0 is mid-dispatch now
    rest = [threading.Thread(target=submit, args=(f"q{i}",))
            for i in range(1, 6)]
    [t.start() for t in rest]
    t1.join(timeout=10)
    [t.join(timeout=10) for t in rest]
    assert len(results) == 6
    sizes = sorted(len(c) for c in stub.calls)
    assert sizes[0] == 1 and sizes[-1] >= 2, sizes     # coalescing happened
    for name, (out, stats) in results.items():
        assert stats["queries"] == float(out[0][0])    # batch size echoed


def test_batcher_lone_query_never_waits():
    """A query alone in its group dispatches immediately — far faster
    than the configured window."""
    b = SearchBatcher()
    stub = _StubSearcher()
    t0 = time.perf_counter()
    out, stats = b.submit(stub, "solo", 10, "bm25", 0, 5.0, 128)
    assert time.perf_counter() - t0 < 1.0
    assert stats["queries"] == 1


def test_batcher_batch_max_splits():
    b = SearchBatcher()
    stub = _StubSearcher(delay=0.1)
    done = []

    def submit(name):
        done.append(b.submit(stub, name, 10, "bm25", 0, 0.4, 2))

    t1 = threading.Thread(target=submit, args=("a",))
    t1.start()
    time.sleep(0.03)
    rest = [threading.Thread(target=submit, args=(n,))
            for n in ("b", "c", "d", "e")]
    [t.start() for t in rest]
    t1.join(timeout=10)
    [t.join(timeout=10) for t in rest]
    assert len(done) == 5
    assert max(len(c) for c in stub.calls) <= 2
    # every query scored exactly once — a claimer whose queue overflowed
    # batch_max must take its own entry along, never leave it orphaned
    # for a redundant later dispatch
    assert sorted(n for c in stub.calls for n in c) == \
        ["a", "b", "c", "d", "e"]
    # and no idle group stays behind pinning the searcher
    assert not b._groups


def test_batcher_error_isolation_serial_retry():
    """A dispatch poisoned by one query retries every member serially:
    siblings succeed, only the poisoned caller raises."""
    b = SearchBatcher()
    stub = _StubSearcher(delay=0.15, poison="BAD")
    outs, errs = {}, {}

    def submit(name):
        try:
            outs[name] = b.submit(stub, name, 10, "bm25", 0, 0.5, 128)
        except ValueError as e:
            errs[name] = e

    t1 = threading.Thread(target=submit, args=("g1",))
    t1.start()
    time.sleep(0.05)
    others = [threading.Thread(target=submit, args=(n,))
              for n in ("BAD", "g2", "g3")]
    [t.start() for t in others]
    t1.join(timeout=10)
    [t.join(timeout=10) for t in others]
    assert set(outs) == {"g1", "g2", "g3"}
    assert set(errs) == {"BAD"}
    # the poisoned coalesced dispatch really happened before the retries
    assert any(len(c) > 1 and "BAD" in c for c in stub.calls)


def test_batched_topk_cache_hit_skips_batch(db):
    """A fragment-cache hit returns immediately (stats None) and never
    occupies a batch slot."""
    from serenedb_tpu.engine import CURRENT_CONNECTION
    from serenedb_tpu.search.index import find_index
    conn = db.connect()
    # explicit: this test exercises ON-mode mechanics even under the
    # verify_tier1.sh SERENE_SEARCH_BATCH=off global pass
    conn.execute("SET serene_search_batch = on")
    t = db.resolve_table(["docs"])
    idx = find_index(t, "body")
    searcher = idx.searcher("body")
    an = get_analyzer("text")
    node = parse_query("apple | term", an)
    tok = CURRENT_CONNECTION.set(conn)
    try:
        out1, stats1 = batched_topk(searcher, node, 10, "bm25", 0,
                                    conn.settings)
        assert stats1 is not None          # miss: went through the batcher
        d0 = metrics.SEARCH_BATCH_QUERIES.value
        out2, stats2 = batched_topk(searcher, node, 10, "bm25", 0,
                                    conn.settings)
        assert stats2 is None              # probe hit: no batch entry
        assert metrics.SEARCH_BATCH_QUERIES.value == d0
        assert np.array_equal(out1[0].view(np.uint32),
                              out2[0].view(np.uint32))
        assert np.array_equal(out1[1], out2[1])
    finally:
        CURRENT_CONNECTION.reset(tok)


# -- satellites -----------------------------------------------------------


def test_msearch_error_isolation(db):
    """A malformed body sandwiched between valid items reports inline on
    that item only — siblings in the same coalesced dispatch succeed."""
    from serenedb_tpu.server.es_api import EsApi
    es = EsApi(db)
    for i in range(30):
        es.index_doc("msi", {"body": WORDS[i % len(WORDS)] + " apple"})
    es.refresh("msi")
    body = "\n".join([
        '{"index": "msi"}',
        '{"query": {"match": {"body": "apple"}}}',
        '{"index": "msi"}',
        '{"query": {"bogus_kind": {}}}',                    # bad query type
        '{"index": "msi"}',
        'not valid json {{{',                               # bad JSON
        '{"index": "msi"}',
        '{"query": {"match": {"body": "banana"}}}',
    ]) + "\n"
    res = es.msearch(body)
    r = res["responses"]
    assert len(r) == 4
    assert r[0]["status"] == 200 and r[0]["hits"]["total"]["value"] > 0
    assert r[1]["status"] == 400 and "error" in r[1]
    assert r[2]["status"] == 400 and "error" in r[2]
    assert r[3]["status"] == 200
    # and the batch never poisoned the siblings' result content
    solo = es.search("msi", {"query": {"match": {"body": "apple"}}})
    assert solo["hits"]["hits"] == r[0]["hits"]["hits"]


def test_gauges_and_exports(db):
    """SearchBatch{Dispatches,Queries,WindowWaitNs,Coalesced} exist, move
    under load, and surface through /metrics and the /_stats metric
    map."""
    base = {g: metrics.REGISTRY.snapshot()[g]
            for g in ("SearchBatchDispatches", "SearchBatchQueries",
                      "SearchBatchWindowWaitNs", "SearchBatchCoalesced")}
    _run_queries(db, [QUERIES[0], QUERIES[4]], "on", 4, "off", threads=6)
    snap = metrics.REGISTRY.snapshot()
    assert snap["SearchBatchDispatches"] > base["SearchBatchDispatches"]
    assert snap["SearchBatchQueries"] > base["SearchBatchQueries"]
    from serenedb_tpu.obs.export import prometheus_text, stats_json
    text = prometheus_text()
    for prom in ("serenedb_search_batch_dispatches",
                 "serenedb_search_batch_queries",
                 "serenedb_search_batch_window_wait_ns",
                 "serenedb_search_batch_coalesced"):
        assert prom in text
    assert "SearchBatchDispatches" in stats_json()["metrics"]


def test_result_cache_settings_exclusion():
    """serene_search_batch must NOT key the result cache: batching is
    bit-identical by contract (the parity matrix above is the proof), so
    keying on it would split identical entries."""
    from serenedb_tpu.cache.result import RESULT_AFFECTING_SETTINGS
    assert "serene_search_batch" not in RESULT_AFFECTING_SETTINGS
    assert "serene_search_batch_window_ms" not in RESULT_AFFECTING_SETTINGS
    assert "serene_search_batch_max" not in RESULT_AFFECTING_SETTINGS


def test_explain_analyze_batch_line(db):
    conn = db.connect()
    conn.execute("SET serene_search_batch = on")
    conn.execute("SET serene_result_cache = off")
    rows = conn.execute("EXPLAIN ANALYZE " + QUERIES[0]).rows()
    lines = [r[0] for r in rows]
    assert any("Batch: queries=" in ln and "shared_scoring=" in ln
               for ln in lines), lines


@pytest.mark.slow
def test_qps_smoke():
    """Aggregate throughput smoke: 16 concurrent distinct 2-term top-10
    searches, batched vs serial — batched must not lose, and with the
    ragged path live it should win. Kept loose (this is a smoke test;
    bench.py `search_batch` carries the real ≥5x assertion)."""
    db = _make_db(n=4000, seed=3)

    def drive(batch):
        qs = [f"SELECT id, bm25(body) AS s FROM docs WHERE body @@ "
              f"'{WORDS[i % 10]} | {WORDS[(i + 5) % 13]}' "
              f"ORDER BY s DESC LIMIT 10" for i in range(16)]
        bar = threading.Barrier(16)

        def run(i):
            conn = db.connect()
            conn.execute(f"SET serene_search_batch = {batch}")
            conn.execute("SET serene_result_cache = off")
            bar.wait(timeout=30)
            for _ in range(3):
                conn.execute(qs[i])
        ts = [threading.Thread(target=run, args=(i,)) for i in range(16)]
        t0 = time.perf_counter()
        [t.start() for t in ts]
        [t.join(timeout=120) for t in ts]
        return time.perf_counter() - t0

    drive("on")                    # warm compiles
    t_on = drive("on")
    t_off = drive("off")
    assert t_on < t_off * 1.5, (t_on, t_off)
