"""Multi-tier query cache (ISSUE 5): correctness first.

The wall is determinism — cached and uncached executions must be
bit-identical at any `serene_workers`, and a write interleaved between
two identical statements must always surface fresh data. Everything
else (gauges, sdb_cache, LRU order, fragment survival) is attribution.
"""

import numpy as np
import pytest

from serenedb_tpu.cache.fragments import FRAGMENTS
from serenedb_tpu.cache.lru import BytesLRU
from serenedb_tpu.cache.result import RESULT_CACHE
from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.exec.tables import MemTable
from serenedb_tpu.utils import metrics
from serenedb_tpu.utils.config import REGISTRY as SETTINGS


def _mk(n=5000, seed=7):
    rng = np.random.default_rng(seed)
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE t (k INT, v BIGINT, s TEXT)")
    words = np.asarray(["ash", "birch", "cedar", "oak", None], dtype=object)
    db.schemas["main"].tables["t"] = MemTable("t", Batch.from_pydict({
        "k": Column.from_numpy(rng.integers(0, 50, n).astype(np.int32)),
        "v": Column.from_numpy(
            rng.integers(-1000, 1000, n, dtype=np.int64)),
        "s": Column.from_pylist(list(words[rng.integers(0, 5, n)])),
    }))
    c.execute("SET serene_device = 'cpu'")
    return db, c


QUERIES = (
    "SELECT k, count(*), sum(v) FROM t GROUP BY k ORDER BY k",
    "SELECT s, min(v), max(v) FROM t WHERE v > 0 GROUP BY s ORDER BY s",
    "SELECT DISTINCT k FROM t WHERE v % 3 = 0 ORDER BY k LIMIT 10",
    "SELECT a.k, count(*) FROM t a JOIN t b ON a.k = b.k "
    "WHERE a.v > 900 GROUP BY a.k ORDER BY a.k",
)


def _hits():
    return metrics.RESULT_CACHE_HITS.value


def _misses():
    return metrics.RESULT_CACHE_MISSES.value


# -- hit/miss parity matrix -------------------------------------------------

def test_parity_cached_vs_uncached_across_workers():
    """Bit-identical results: cache on/off × workers 1/4 × repeat runs.
    The second cached run is a hit (gauge-asserted) and still equals the
    uncached oracle."""
    db, c = _mk()
    for q in QUERIES:
        baseline = None
        for cache in ("off", "on"):
            for workers in (1, 4):
                c.execute(f"SET serene_result_cache = {cache}")
                c.execute(f"SET serene_workers = {workers}")
                first = c.execute(q).rows()
                h0 = _hits()
                again = c.execute(q).rows()
                if baseline is None:
                    baseline = first
                assert first == baseline, (q, cache, workers)
                assert again == baseline, (q, cache, workers)
                if cache == "on":
                    assert _hits() > h0, f"expected a hit: {q}"


def test_settings_digest_partitions_entries():
    """Result-affecting settings are part of the key: flipping one
    creates a separate entry instead of serving the other digest's."""
    db, c = _mk(n=1000)
    q = QUERIES[0]
    c.execute("SET serene_device = 'cpu'")
    r_cpu = c.execute(q).rows()
    m0 = _misses()
    c.execute("SET serene_device = 'auto'")
    r_auto = c.execute(q).rows()
    assert _misses() > m0          # different digest ⇒ no cross-serve
    assert r_cpu == r_auto         # and identical data either way


def test_literal_and_param_values_key_separately():
    db, c = _mk(n=500)
    a = c.execute("SELECT count(*) FROM t WHERE k < 10").scalar()
    b = c.execute("SELECT count(*) FROM t WHERE k < 40").scalar()
    assert a < b                    # same fingerprint, different literals
    pa = c.execute("SELECT count(*) FROM t WHERE k < $1", [10]).scalar()
    pb = c.execute("SELECT count(*) FROM t WHERE k < $1", [40]).scalar()
    assert (pa, pb) == (a, b)


def test_multi_statement_text_no_cross_serve():
    db, c = _mk(n=100)
    for _ in range(2):   # second round would serve both from cache
        r = c.execute_all("SELECT count(*) FROM t WHERE k < 5; "
                          "SELECT count(*) FROM t WHERE k >= 5")
        assert r[0].scalar() + r[1].scalar() == 100
        assert r[0].scalar() != r[1].scalar()


# -- write interleaving: zero stale reads -----------------------------------

def test_write_between_identical_statements_always_fresh():
    db, c = _mk(n=2000)
    q = "SELECT count(*), sum(v) FROM t"
    base = c.execute(q).rows()[0]
    for i in range(1, 6):
        c.execute(f"INSERT INTO t VALUES (99, {1000 + i}, 'new')")
        got = c.execute(q).rows()[0]
        assert got[0] == base[0] + i, f"stale count after write {i}"
        # repeat WITHOUT a write: must hit and still be the fresh data
        h0 = _hits()
        assert c.execute(q).rows()[0] == got
        assert _hits() > h0


def test_update_delete_truncate_invalidate():
    db, c = _mk(n=1000)
    q = "SELECT count(*) FROM t WHERE v > 0"
    n1 = c.execute(q).scalar()
    c.execute("UPDATE t SET v = -1 WHERE v > 0")
    assert c.execute(q).scalar() == 0
    c.execute("INSERT INTO t VALUES (1, 5, 'x')")
    assert c.execute(q).scalar() == 1
    c.execute("DELETE FROM t WHERE v = 5")
    assert c.execute(q).scalar() == 0
    c.execute("TRUNCATE t")
    assert c.execute("SELECT count(*) FROM t").scalar() == 0
    assert n1 > 0


def test_cross_connection_write_invalidates():
    db, c = _mk(n=500)
    c2 = db.connect()
    q = "SELECT count(*) FROM t"
    n = c.execute(q).scalar()
    c2.execute("INSERT INTO t VALUES (1, 1, 'w')")
    assert c.execute(q).scalar() == n + 1


def test_drop_recreate_same_name_never_collides():
    db, c = _mk(n=10)
    q = "SELECT count(*) FROM t"
    assert c.execute(q).scalar() == 10
    c.execute("DROP TABLE t")
    c.execute("CREATE TABLE t (k INT, v BIGINT, s TEXT)")
    c.execute("INSERT INTO t VALUES (1, 1, 'a')")
    # fresh generation at (version, epoch) the old table also had once:
    # the publication token keeps the keys apart
    assert c.execute(q).scalar() == 1


def test_txn_statements_bypass_cache():
    db, c = _mk(n=100)
    q = "SELECT count(*) FROM t"
    n = c.execute(q).scalar()            # cached outside the txn
    c.execute("BEGIN")
    c.execute("INSERT INTO t VALUES (1, 1, 'x')")
    assert c.execute(q).scalar() == n + 1   # read-your-writes, no cache
    c.execute("ROLLBACK")
    assert c.execute(q).scalar() == n


# -- volatility gating ------------------------------------------------------

def test_volatile_functions_never_cache():
    db, c = _mk(n=50)
    before = len(RESULT_CACHE.snapshot())
    r1 = c.execute("SELECT sum(v + random()) FROM t").scalar()
    r2 = c.execute("SELECT sum(v + random()) FROM t").scalar()
    assert r1 != r2
    assert not any("random" in e["query"]
                   for e in RESULT_CACHE.snapshot()[before:])


def test_stable_functions_never_cache():
    """now() is statement-stable but NOT cacheable across statements —
    a cached entry would freeze the clock."""
    db, c = _mk(n=10)
    q = "SELECT k, now() FROM t LIMIT 1"
    c.execute(q)
    assert not any("now" in e["query"] for e in RESULT_CACHE.snapshot())
    m0 = _misses()
    h0 = _hits()
    c.execute(q)
    assert _hits() == h0 and _misses() == m0   # not even probed


def test_values_scalar_subquery_never_caches_stale():
    """The planner evaluates scalar subqueries inside VALUES at plan
    time and materializes the rows — the subplan's tables never reach
    the publication key, so these statements must refuse caching
    entirely or a write to the inner table would go unseen."""
    db, c = _mk(n=10)
    c.execute("CREATE TABLE u (x INT)")
    c.execute("INSERT INTO u VALUES (1)")
    q = "SELECT * FROM (VALUES ((SELECT count(*) FROM u))) v"
    assert c.execute(q).rows() == [(1,)]
    c.execute("INSERT INTO u VALUES (2)")
    assert c.execute(q).rows() == [(2,)]
    # same hole via IN/EXISTS inside VALUES-adjacent expressions: the
    # AST screen refuses every subquery-expression form
    q2 = "SELECT * FROM (VALUES ((SELECT max(x) FROM u))) v"
    assert c.execute(q2).rows() == [(2,)]
    c.execute("UPDATE u SET x = 7 WHERE x = 2")
    assert c.execute(q2).rows() == [(7,)]


def test_sdb_introspection_never_caches():
    db, c = _mk(n=10)
    r1 = c.execute("SELECT count(*) FROM sdb_metrics()").scalar()
    c.execute("SELECT count(*) FROM t")
    r2 = c.execute("SELECT count(*) FROM sdb_metrics()").scalar()
    assert r1 > 0 and r2 > 0    # live engine state, rebuilt per query


# -- bytes-LRU --------------------------------------------------------------

def test_bytes_lru_eviction_order():
    lru = BytesLRU()
    for i in range(4):
        assert lru.put(i, f"v{i}", 100, 350)
    # inserting 4x100 bytes under a 350 cap evicted the oldest
    assert lru.get(0) is None and lru.get(1) == "v1"
    # get(1) refreshed recency: inserting one more evicts 2, not 1
    assert lru.put(9, "v9", 100, 350)
    assert lru.get(2) is None and lru.get(1) == "v1"
    # an entry larger than the whole cap is refused
    assert not lru.put(10, "big", 400, 350)
    assert lru.total_bytes == 300


def test_result_cache_respects_byte_cap_and_evicts():
    old = SETTINGS.get_global("serene_result_cache_mb")
    db, c = _mk(n=200_000)
    try:
        SETTINGS.set_global("serene_result_cache_mb", 1)   # 1 MB
        e0 = metrics.RESULT_CACHE_EVICTIONS.value
        # each projection result is ~1.6MB (200k rows × int64) — bigger
        # than the cap, refused; the aggregate results are tiny and stay
        big = "SELECT v FROM t"
        c.execute(big)
        for i in range(5):
            c.execute(f"SELECT count(*) FROM t WHERE k < {i + 1}")
        assert metrics.RESULT_CACHE_BYTES.value <= 1 << 20
        snap = RESULT_CACHE.snapshot()
        assert not any(e["query"] == "select v from t" for e in snap)
        assert metrics.RESULT_CACHE_EVICTIONS.value >= e0
    finally:
        SETTINGS.set_global("serene_result_cache_mb", old)


def test_session_off_switch():
    db, c = _mk(n=100)
    c.execute("SET serene_result_cache = off")
    q = "SELECT count(*) FROM t WHERE k = 7"
    h0, m0 = _hits(), _misses()
    c.execute(q)
    c.execute(q)
    assert _hits() == h0 and _misses() == m0
    c.execute("SET serene_result_cache = on")
    c.execute(q)
    h1 = _hits()
    c.execute(q)
    assert _hits() == h1 + 1


# -- views ------------------------------------------------------------------

def test_view_redefinition_never_serves_stale():
    db, c = _mk(n=100)
    c.execute("CREATE VIEW hi AS SELECT k FROM t WHERE v > 0")
    a = c.execute("SELECT count(*) FROM hi").scalar()
    c.execute("CREATE OR REPLACE VIEW hi AS SELECT k FROM t WHERE v <= 0")
    b = c.execute("SELECT count(*) FROM hi").scalar()
    assert a + b == 100


# -- fragment cache ---------------------------------------------------------

def _mk_search():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE d (id INT, body TEXT)")
    c.execute("INSERT INTO d VALUES (1,'red fox jumps'),"
              "(2,'lazy dog naps'),(3,'red dog runs'),(4,'gray owl')")
    c.execute("CREATE INDEX ON d USING inverted (body)")
    return db, c


def test_fragment_cache_hit_and_parity():
    db, c = _mk_search()
    # two DIFFERENT statements sharing one filter predicate: the result
    # tier misses (distinct statement digests) while the per-segment
    # filter fragment for 'red' is computed once and reused
    r1 = c.execute(
        "SELECT id FROM d WHERE body ## 'red' ORDER BY id").rows()
    f0 = metrics.FRAGMENT_CACHE_HITS.value
    n = c.execute("SELECT count(*) FROM d WHERE body ## 'red'").scalar()
    assert r1 == [(1,), (3,)] and n == 2
    assert metrics.FRAGMENT_CACHE_HITS.value > f0


def test_fragment_survives_append_not_mutation():
    db, c = _mk_search()
    q = "SELECT id FROM d WHERE body ## 'red' ORDER BY id"
    assert c.execute(q).rows() == [(1,), (3,)]
    t = db.schemas["main"].tables["d"]
    idx = list(t.indexes.values())[0]
    seg_before = idx.searchers["body"].segments[0][0]
    # append → refresh adds a segment; the OLD segment object (and its
    # cached fragments) must survive
    c.execute("INSERT INTO d VALUES (5, 'red crow')")
    f0 = metrics.FRAGMENT_CACHE_HITS.value
    assert c.execute(q).rows() == [(1,), (3,), (5,)]
    idx2 = list(t.indexes.values())[0]
    segs_after = [s for s, _b in idx2.searchers["body"].segments]
    assert seg_before in segs_after and len(segs_after) == 2
    assert metrics.FRAGMENT_CACHE_HITS.value > f0   # old fragment reused
    # mutation → full rebuild: new segment objects, fresh results
    c.execute("UPDATE d SET body = 'blue jay' WHERE id = 1")
    assert c.execute(q).rows() == [(3,), (5,)]
    idx3 = list(t.indexes.values())[0]
    assert seg_before not in [s for s, _b in
                              idx3.searchers["body"].segments]


def test_fragment_finalizer_lock_free_and_deferred():
    """drop_segment is a weakref-finalizer target: GC can run it on a
    thread that is ALREADY inside the cache holding its lock (observed
    as a tier-1 deadlock at sqllogic sdb/search tests), so it must only
    enqueue — reclaim happens at the next cache operation."""
    import threading as _threading

    from serenedb_tpu.cache.fragments import FRAGMENTS
    db, c = _mk_search()
    c.execute("SELECT id FROM d WHERE body ## 'red' ORDER BY id")
    done = _threading.Event()

    def finalizer_while_locked():
        FRAGMENTS.drop_segment(999_999_999)
        done.set()

    with FRAGMENTS._lock:                   # the interrupted frame
        t = _threading.Thread(target=finalizer_while_locked, daemon=True)
        t.start()
        t.join(timeout=10)
    assert done.is_set(), "drop_segment blocked on the cache lock"
    assert 999_999_999 in list(FRAGMENTS._pending_drops)
    FRAGMENTS._drain_drops()                # next cache op reclaims
    assert 999_999_999 not in list(FRAGMENTS._pending_drops)


def test_fragment_cache_disabled_with_session_switch():
    db, c = _mk_search()
    c.execute("SET serene_result_cache = off")
    q = "SELECT id FROM d WHERE body ## 'dog' ORDER BY id"
    c.execute(q)
    h0, m0 = (metrics.FRAGMENT_CACHE_HITS.value,
              metrics.FRAGMENT_CACHE_MISSES.value)
    c.execute(q)
    assert (metrics.FRAGMENT_CACHE_HITS.value,
            metrics.FRAGMENT_CACHE_MISSES.value) == (h0, m0)


# -- observability ----------------------------------------------------------

def test_sdb_cache_and_stat_statements_attribution():
    db, c = _mk(n=300)
    q = "SELECT k, sum(v) FROM t GROUP BY k ORDER BY k"
    c.execute(q)
    c.execute(q)
    c.execute(q)
    rows = c.execute(
        "SELECT query, hits, bytes FROM sdb_cache() "
        "WHERE tier = 'result' AND query LIKE '%group by k%'").rows()
    assert rows and any(r[1] >= 2 for r in rows)
    assert all(r[2] > 0 for r in rows)
    ss = c.execute(
        "SELECT calls, cache_hits FROM sdb_stat_statements() "
        "WHERE query LIKE '%sum ( v ) from t group by%'").rows()
    assert ss and ss[0][0] >= 3 and ss[0][1] >= 2
    # the objects column names the source table
    assert any("main.t" in r[0] for r in c.execute(
        "SELECT objects FROM sdb_cache() WHERE tier='result'").rows())


def test_explain_analyze_reports_cache_state():
    db, c = _mk(n=100)
    q = "SELECT count(*) FROM t WHERE k < 9"
    lines = [r[0] for r in c.execute(f"EXPLAIN ANALYZE {q}").rows()]
    assert "Result Cache: miss" in lines
    lines = [r[0] for r in c.execute(f"EXPLAIN ANALYZE {q}").rows()]
    assert "Result Cache: hit" in lines
    # and ANALYZE still really executed: per-operator actuals present
    assert any("actual time=" in ln for ln in lines)


def test_streaming_path_hits_and_stores():
    db, c = _mk(n=2000)
    from serenedb_tpu.sql import parser
    q = "SELECT k, count(*) FROM t GROUP BY k ORDER BY k"
    st = parser.parse(q)[0]
    names, types, it = c.execute_streaming(st, sql_text=q)
    streamed = [tuple(r) for b in it for r in b.rows()]
    h0 = _hits()
    names2, types2, it2 = c.execute_streaming(st, sql_text=q)
    streamed2 = [tuple(r) for b in it2 for r in b.rows()]
    assert _hits() > h0
    assert streamed == streamed2 == [tuple(r)
                                     for r in c.execute(q).rows()]
    assert names == names2


def test_sweep_reclaims_superseded_generations():
    db, c = _mk(n=100)
    # a table name unique to THIS test: the process-wide cache may
    # still hold entries for other suites' tables named `t` whose
    # normalized text would collide with the label counted below
    c.execute("CREATE TABLE sweep_gen_t (k INT)")
    c.execute("INSERT INTO sweep_gen_t VALUES (7)")
    q = "SELECT count(*) FROM sweep_gen_t"
    c.execute(q)
    c.execute("INSERT INTO sweep_gen_t VALUES (1)")
    c.execute(q)
    # two generations of the same statement live until the lazy sweep
    assert RESULT_CACHE.sweep() >= 1
    labels = [e["query"] for e in RESULT_CACHE.snapshot()]
    assert labels.count("select count ( * ) from sweep_gen_t") == 1


def test_prometheus_and_stats_export_cache_sections():
    from serenedb_tpu.obs.export import prometheus_text, stats_json
    db, c = _mk(n=50)
    q = "SELECT count(*) FROM t"
    c.execute(q)
    c.execute(q)
    text = prometheus_text()
    assert "serenedb_result_cache_hits" in text
    assert "serenedb_statement_cache_hits" in text
    s = stats_json()
    assert s["cache"]["result"]["entries"] >= 1
    assert "fragments" in s["cache"]
