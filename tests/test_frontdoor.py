"""Front-door tests: the unified asyncio serving tier
(server/frontdoor.py) — socket-level admission (53300/429 before any
parse), keep-alive pipelining semantics, slow-reader backpressure, idle
reaping, deterministic shutdown, connection observability, and
bit-identity with the legacy ThreadingHTTPServer parity oracle."""

import http.client
import json
import socket
import threading
import time

import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.sched.governor import CONNGATE
from serenedb_tpu.server.http_server import HttpServer, LegacyHttpServer
from serenedb_tpu.utils import metrics
from serenedb_tpu.utils.config import REGISTRY as SETTINGS


@pytest.fixture()
def setting():
    """Set globals for one test, restoring priors afterwards (pass 19
    runs this suite with SERENE_MAX_CONNECTIONS=8 forced — tests must
    put back what they found, not a hardcoded default)."""
    prior = {}

    def set_(name, value):
        if name not in prior:
            prior[name] = SETTINGS.get_global(name)
        SETTINGS.set_global(name, value)

    yield set_
    for name, value in prior.items():
        SETTINGS.set_global(name, value)


@pytest.fixture(scope="module")
def db():
    d = Database()
    c = d.connect()
    c.execute("CREATE TABLE kv (k INT, v VARCHAR)")
    c.execute("INSERT INTO kv VALUES (1, 'one'), (2, 'two')")
    yield d
    d.close()


@pytest.fixture(scope="module")
def front(db):
    s = HttpServer(db, port=0)   # serene_frontdoor defaults on
    s.start()
    from serenedb_tpu.server.frontdoor import FrontDoor
    assert isinstance(s._impl, FrontDoor)
    yield s
    s.stop()


# -- raw h1 client helpers ---------------------------------------------------

def _request_bytes(method, path, body=b"", headers=()):
    head = [f"{method} {path} HTTP/1.1", "Host: x",
            f"Content-Length: {len(body)}"]
    head += [f"{k}: {v}" for k, v in headers]
    return ("\r\n".join(head) + "\r\n\r\n").encode() + body


def _read_response(sock):
    """One HTTP/1.1 response off a raw socket: (status, headers, body)."""
    buf = b""
    while b"\r\n\r\n" not in buf:
        d = sock.recv(65536)
        assert d, f"peer closed mid-header: {buf[:200]!r}"
        buf += d
    head, _, rest = buf.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split(" ")[1])
    headers = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        headers[k.strip().lower()] = v.strip()
    ln = int(headers.get("content-length") or 0)
    while len(rest) < ln:
        d = sock.recv(65536)
        assert d, "peer closed mid-body"
        rest += d
    return status, headers, rest[:ln], rest[ln:]


def _sql(port, query, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", "/_sql", json.dumps({"query": query}),
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, json.loads(body)


# -- parity oracle -----------------------------------------------------------

def test_parity_frontdoor_vs_legacy(db, setting):
    """The acceptance bit: identical requests through the asyncio front
    door and the legacy ThreadingHTTPServer produce byte-identical
    bodies (both run the same pure Router, so this is structural — the
    test guards the transports' body handling)."""
    legacy = LegacyHttpServer(db, port=0)
    legacy.start()
    front = HttpServer(db, port=0)
    front.start()
    try:
        # seed through ONE server only (mutations must not run twice)
        conn = http.client.HTTPConnection("127.0.0.1", front.port)
        nd = (json.dumps({"index": {"_index": "par", "_id": "1"}}) + "\n" +
              json.dumps({"title": "quick brown fox", "n": 1}) + "\n" +
              json.dumps({"index": {"_index": "par", "_id": "2"}}) + "\n" +
              json.dumps({"title": "lazy dog", "n": 2}) + "\n")
        conn.request("POST", "/_bulk", nd,
                     {"Content-Type": "application/x-ndjson"})
        assert conn.getresponse().read()
        conn.close()

        reads = [
            ("GET", "/", None),
            ("GET", "/_cluster/health", None),
            ("GET", "/_cat/indices?format=json", None),
            ("GET", "/_cat/count/par", None),
            ("GET", "/par/_mapping", None),
            ("POST", "/par/_count", None),
            ("GET", "/par/_doc/1", None),
            ("HEAD", "/par", None),
            ("HEAD", "/nosuch", None),
            ("POST", "/par/_search", json.dumps(
                {"query": {"match": {"title": "fox"}}})),
            ("POST", "/par/_msearch",
             '{}\n{"query": {"match_all": {}}, "sort": ["n"]}\n'),
            ("POST", "/_analyze", json.dumps({"text": "Quick Brown"})),
            ("POST", "/_mget", json.dumps(
                {"index": "par", "ids": ["1", "2"]})),
            ("POST", "/_sql", json.dumps(
                {"query": "SELECT k, v FROM kv ORDER BY k"})),
            ("POST", "/_test/echo", '{"a": 1}'),
            ("GET", "/_test/ping", None),
            ("GET", "/_unknown_endpoint", None),
            ("POST", "/par/_nosuchverb", None),
        ]
        for method, path, body in reads:
            results = []
            for srv in (front, legacy):
                c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                               timeout=30)
                c.request(method, path, body,
                          {"Content-Type": "application/json"}
                          if body else {})
                r = c.getresponse()
                results.append((r.status, r.read(),
                                r.getheader("Content-Type")))
                c.close()
            assert results[0] == results[1], \
                f"parity break on {method} {path}: {results}"
    finally:
        front.stop()
        legacy.stop()


# -- socket-level admission --------------------------------------------------

def test_http_429_past_max_connections(db, setting):
    srv = HttpServer(db, port=0)
    srv.start()
    try:
        setting("serene_max_connections", 1)
        hold = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=30)
        hold.request("GET", "/_test/ping")
        assert hold.getresponse().read() == b'{"ok": true}'
        # the keep-alive connection above holds the only slot: the next
        # SOCKET is answered 429 without us sending a single byte —
        # rejection strictly before any request parse
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        status, headers, body, _ = _read_response(s)
        assert status == 429
        assert headers.get("retry-after") == "1"
        assert b"too_many_connections" in body
        s.close()
        assert CONNGATE.snapshot()["rejected_total"] >= 1
        assert metrics.CONNECTIONS_REJECTED.value >= 1
        # releasing the slot re-opens the door
        hold.close()
        deadline = time.time() + 5
        while time.time() < deadline:
            s2 = socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=10)
            s2.sendall(_request_bytes("GET", "/_test/ping"))
            status, _, body, _ = _read_response(s2)
            s2.close()
            if status == 200:
                break
            time.sleep(0.05)
        assert status == 200 and body == b'{"ok": true}'
    finally:
        srv.stop()


def test_pg_53300_shares_gate_with_http(db, setting):
    """Both protocols drain ONE serene_max_connections budget: with an
    HTTP keep-alive holding the only slot, a pgwire connect gets a
    clean 53300 ErrorResponse before any startup parse."""
    from serenedb_tpu.server.frontdoor import FrontDoor
    from serenedb_tpu.server.pgwire import PgServer

    pg = PgServer(db, port=0)
    fd = FrontDoor(db, http_port=0, pg=pg)
    fd.start()
    try:
        assert pg.pool is fd.executor    # one engine-boundary pool
        hold = http.client.HTTPConnection("127.0.0.1", fd.port,
                                          timeout=30)
        hold.request("GET", "/_test/ping")
        hold.getresponse().read()
        setting("serene_max_connections", 1)
        s = socket.create_connection(("127.0.0.1", pg.port), timeout=10)
        data = s.recv(4096)       # server speaks first: ErrorResponse
        assert data[:1] == b"E" and b"53300" in data
        s.close()
        hold.close()
    finally:
        fd.stop()


# -- keep-alive pipelining (PR 8 isolation contract over the new tier) -------

def test_pipelined_requests_serialized_on_one_connection(db, front):
    """Pipelined requests on ONE connection are processed strictly in
    order — the second statement observes the first's write — and an
    error response doesn't kill the keep-alive session."""
    port = front.port
    _sql(port, "CREATE TABLE IF NOT EXISTS pipe (n INT)")
    _sql(port, "DELETE FROM pipe")
    s = socket.create_connection(("127.0.0.1", port), timeout=30)
    b1 = json.dumps({"query": "INSERT INTO pipe VALUES (7)"}).encode()
    b2 = json.dumps({"query": "SELECT count(*) AS c FROM pipe"}).encode()
    s.sendall(_request_bytes("POST", "/_sql", b1) +
              _request_bytes("POST", "/_sql", b2) +
              _request_bytes("POST", "/_sql", b"{not json") +
              _request_bytes("GET", "/_test/ping"))
    st1, _, r1, rest = _read_response(s)
    assert st1 == 200
    status, _, r2, rest = _read_response(s)
    assert status == 200
    assert json.loads(r2)["rows"] == [[1]]   # saw the pipelined INSERT
    status, _, r3, rest = _read_response(s)
    assert status == 400                      # malformed fails ALONE
    status, _, r4, _ = _read_response(s)
    assert status == 200 and r4 == b'{"ok": true}'  # session survived
    s.close()


def test_concurrent_across_connections_serial_within(front):
    """Transport concurrency contract: two connections run their
    requests CONCURRENTLY (wall ≈ one sleep), while two pipelined
    requests on one connection run back-to-back (wall ≈ two sleeps)."""
    port = front.port

    def timed_single():
        s = socket.create_connection(("127.0.0.1", port), timeout=30)
        s.sendall(_request_bytes("GET", "/_test/sleep?ms=400"))
        _read_response(s)
        s.close()

    t0 = time.perf_counter()
    threads = [threading.Thread(target=timed_single) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    concurrent_s = time.perf_counter() - t0
    assert concurrent_s < 0.75, \
        f"two connections did not run concurrently: {concurrent_s:.2f}s"

    s = socket.create_connection(("127.0.0.1", port), timeout=30)
    t0 = time.perf_counter()
    s.sendall(_request_bytes("GET", "/_test/sleep?ms=400") +
              _request_bytes("GET", "/_test/sleep?ms=400"))
    _read_response(s)
    _read_response(s)
    pipelined_s = time.perf_counter() - t0
    s.close()
    assert pipelined_s >= 0.8, \
        f"pipelined requests overlapped on one connection: " \
        f"{pipelined_s:.2f}s"


def test_msearch_and_bulk_keepalive_one_connection(db, front):
    """ES _bulk/_msearch over the new frontend on a single keep-alive
    connection: a malformed bulk item still fails alone (PR 8 isolation
    survives the port), and _msearch works on the same socket after."""
    conn = http.client.HTTPConnection("127.0.0.1", front.port,
                                      timeout=30)
    nd = (json.dumps({"index": {"_index": "iso", "_id": "1"}}) + "\n" +
          json.dumps({"v": 1}) + "\n" +
          json.dumps({"index": {"_index": "DROP TABLE iso",
                                "_id": "2"}}) + "\n" +
          json.dumps({"v": 2}) + "\n" +
          json.dumps({"index": {"_index": "iso", "_id": "3"}}) + "\n" +
          json.dumps({"v": 3}) + "\n")
    conn.request("POST", "/_bulk", nd,
                 {"Content-Type": "application/x-ndjson"})
    r = conn.getresponse()
    body = json.loads(r.read())
    assert r.status == 200 and body["errors"] is True
    states = [next(iter(i.values())) for i in body["items"]]
    assert any("error" in s for s in states)          # the bad item
    assert any("error" not in s for s in states)      # good ones landed
    # same socket, next request: keep-alive survived the item error
    conn.request("POST", "/iso/_msearch",
                 '{}\n{"query": {"match_all": {}}}\n',
                 {"Content-Type": "application/x-ndjson"})
    r = conn.getresponse()
    ms = json.loads(r.read())
    assert r.status == 200
    assert ms["responses"][0]["hits"]["total"]["value"] == 2
    conn.close()


def test_chunked_request_body(front):
    s = socket.create_connection(("127.0.0.1", front.port), timeout=30)
    payload = b'{"chunked": true}'
    req = (b"POST /_test/echo HTTP/1.1\r\nHost: x\r\n"
           b"Transfer-Encoding: chunked\r\n\r\n")
    for i in range(0, len(payload), 5):
        part = payload[i:i + 5]
        req += f"{len(part):x}\r\n".encode() + part + b"\r\n"
    req += b"0\r\n\r\n"
    s.sendall(req)
    status, _, body, _ = _read_response(s)
    assert status == 200 and body == payload
    s.close()


# -- slow-client robustness --------------------------------------------------

def test_slow_reader_triggers_pause_reading_bounded_buffer(front, setting):
    """A reader that stops consuming mid-resultset: the session hits the
    write high-water mark, pauses reading, and buffers a BOUNDED number
    of bytes (PR 12 RSS accounting confirms no unbounded growth) until
    the client drains."""
    from serenedb_tpu.obs.resources import read_rss_bytes

    setting("serene_conn_write_high_kb", 64)
    n = 16 * 1024 * 1024
    payload = b"x" * n
    pauses0 = CONNGATE.snapshot()["pause_reads_total"]
    rss0 = read_rss_bytes()

    s = socket.create_connection(("127.0.0.1", front.port), timeout=60)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16 * 1024)
    s.sendall(_request_bytes("POST", "/_test/echo", payload))
    first = s.recv(1024)          # a taste of the response, then stall
    assert first
    deadline = time.time() + 20
    while time.time() < deadline:
        snap = CONNGATE.snapshot()
        if snap["pause_reads_total"] > pauses0:
            break
        time.sleep(0.05)
    assert snap["pause_reads_total"] > pauses0, \
        "write high-water never paused reading"
    # bounded buffering while stalled: the transport holds at most the
    # high-water mark plus one write chunk, not the 16 MB body
    assert snap["buffered_bytes"] <= 64 * 1024 + 64 * 1024 + 4096
    rss_stalled = read_rss_bytes()
    assert rss_stalled - rss0 < 200 * 1024 * 1024
    # drain: the full, correct response arrives
    expect_total = None
    buf = first
    while True:
        d = s.recv(1 << 20)
        if not d:
            break
        buf += d
        if expect_total is None and b"\r\n\r\n" in buf:
            head, _, _rest = buf.partition(b"\r\n\r\n")
            for ln in head.split(b"\r\n"):
                if ln.lower().startswith(b"content-length"):
                    expect_total = len(head) + 4 + int(ln.split(b":")[1])
        if expect_total is not None and len(buf) >= expect_total:
            break
    s.close()
    assert buf.endswith(payload[-1024:])
    assert buf.count(b"x" * 4096) > 0
    head, _, got_body = buf.partition(b"\r\n\r\n")
    assert got_body == payload, \
        f"drained body mismatch: {len(got_body)} vs {len(payload)}"


def test_half_open_client_reaped_without_pool_slot(db, setting):
    """SYN, no bytes, silence: the idle timeout reaps the socket and
    its admission slot; the engine-boundary executor never sees it."""
    setting("serene_idle_conn_timeout_s", 0.4)
    srv = HttpServer(db, port=0)
    srv.start()
    try:
        impl = srv._impl
        exec_threads0 = len(getattr(impl.executor, "_threads", ()))
        open0 = metrics.CONNECTIONS_OPEN.value
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        s.settimeout(5)
        t0 = time.time()
        data = s.recv(1024)       # blocks until the server reaps us
        assert data == b""        # clean close, no bytes ever exchanged
        assert time.time() - t0 < 4
        s.close()
        deadline = time.time() + 5
        while time.time() < deadline and \
                metrics.CONNECTIONS_OPEN.value > open0:
            time.sleep(0.05)
        assert metrics.CONNECTIONS_OPEN.value == open0
        assert len(getattr(impl.executor, "_threads", ())) == \
            exec_threads0, "half-open client burned an executor slot"
    finally:
        srv.stop()


def test_half_open_pg_client_reaped(db, setting):
    from serenedb_tpu.server.pgwire import PgServer

    setting("serene_idle_conn_timeout_s", 0.4)
    from serenedb_tpu.server.frontdoor import FrontDoor
    pg = PgServer(db, port=0)
    fd = FrontDoor(db, http_port=0, pg=pg)
    fd.start()
    try:
        s = socket.create_connection(("127.0.0.1", pg.port), timeout=10)
        s.settimeout(5)
        assert s.recv(1024) == b""    # reaped mid-handshake
        s.close()
    finally:
        fd.stop()


# -- shutdown ---------------------------------------------------------------

def test_shutdown_deterministic_no_lingering_threads(db):
    before = set(threading.enumerate())
    srv = HttpServer(db, port=0)
    srv.start()
    # leave one idle keep-alive session parked in a read and one
    # completed request behind — both must be reaped by stop()
    idle = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
    idle.request("GET", "/_test/ping")
    idle.getresponse().read()
    open0 = metrics.CONNECTIONS_OPEN.value
    assert open0 >= 1
    impl = srv._impl
    srv.stop()
    # stop() joined the loop thread (or raised) and shut the executor
    # down with wait=True — every thread THIS server started is gone
    assert impl._thread is None
    for t in getattr(impl.executor, "_threads", ()):
        assert not t.is_alive(), f"executor thread leaked: {t.name}"
    leaked = [t.name for t in set(threading.enumerate()) - before
              if t.is_alive()]
    assert not leaked, f"threads outlived stop(): {leaked}"
    idle.close()
    deadline = time.time() + 5
    while time.time() < deadline and \
            metrics.CONNECTIONS_OPEN.value > open0 - 1:
        time.sleep(0.05)
    assert metrics.CONNECTIONS_OPEN.value <= open0 - 1


# -- observability -----------------------------------------------------------

def test_connection_observability_surfaces(db, front):
    port = front.port
    # hold one idle keep-alive connection so the surfaces have a row
    hold = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    hold.request("GET", "/_test/ping")
    hold.getresponse().read()
    time.sleep(0.1)

    # /_stats.connections
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", "/_stats")
    stats = json.loads(conn.getresponse().read())
    conn.close()
    cs = stats["connections"]
    assert cs["open"] >= 2                 # hold + the _stats request
    assert cs["idle"] >= 1
    assert set(cs) >= {"open", "idle", "active", "max_connections",
                       "rejected_total", "pause_reads_total",
                       "buffered_bytes"}
    assert stats["metrics"]["ConnectionsOpen"] >= 2

    # /metrics Prometheus exposition
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", "/metrics")
    text = conn.getresponse().read().decode()
    conn.close()
    for series in ("serenedb_connections_open",
                   "serenedb_connections_idle",
                   "serenedb_connections_active",
                   "serenedb_connections_rejected",
                   "serenedb_socket_bytes_buffered",
                   "serenedb_accept_queue_wait_seconds_bucket"):
        assert series in text, f"missing {series} in /metrics"

    # sdb_connections(): the pg_stat_activity analog at the socket
    c = db.connect()
    rows = list(c.execute(
        "SELECT pid, protocol, state, idle_s FROM sdb_connections() "
        "ORDER BY pid").rows())
    assert any(p == "http" and s == "idle" and i >= 0
               for _, p, s, i in rows), rows
    assert all(pid > 0 for pid, _, _, _ in rows)
    # the bare-relation spelling works too, like sdb_admission
    rows2 = list(c.execute("SELECT protocol FROM sdb_connections").rows())
    assert len(rows2) >= 1
    c.close()
    hold.close()


def test_accept_queue_wait_histogram_observes(front):
    counts0, _ = metrics.ACCEPT_QUEUE_WAIT_HIST.snapshot()
    s = socket.create_connection(("127.0.0.1", front.port), timeout=10)
    s.sendall(_request_bytes("GET", "/_test/ping"))
    _read_response(s)
    s.close()
    counts1, _ = metrics.ACCEPT_QUEUE_WAIT_HIST.snapshot()
    assert sum(counts1) > sum(counts0)


# -- scale smoke -------------------------------------------------------------

@pytest.mark.slow
def test_10k_idle_connections_near_zero_threads(db, setting):
    """The tentpole target: 10k idle sockets at near-zero thread count
    — RSS growth < 10 KB/connection, zero per-connection threads on
    the HTTP tier (loopback; scaled down only if the fd rlimit is
    low)."""
    import gc
    import resource

    from serenedb_tpu.obs.resources import read_rss_bytes

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want = 10_000
    need = want * 2 + 512        # client + server end per connection
    if soft < need:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(hard, need), hard))
            soft, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
        except (ValueError, OSError):
            pass
    n = min(want, max(0, (soft - 512) // 2))
    if n < 1000:
        pytest.skip(f"fd rlimit too low for an idle-fleet smoke "
                    f"(soft={soft})")
    setting("serene_max_connections", 0)
    setting("serene_idle_conn_timeout_s", 0.0)
    srv = HttpServer(db, port=0)
    srv.start()
    socks = []
    try:
        # settle: one request warms the route/executor path
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        s.sendall(_request_bytes("GET", "/_test/ping"))
        _read_response(s)
        s.close()
        gc.collect()
        threads0 = threading.active_count()
        rss0 = read_rss_bytes()
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.connect(("127.0.0.1", srv.port))
            socks.append(s)
        deadline = time.time() + 120
        while time.time() < deadline and \
                metrics.CONNECTIONS_OPEN.value < n:
            time.sleep(0.2)
        assert metrics.CONNECTIONS_OPEN.value >= n
        gc.collect()
        rss1 = read_rss_bytes()
        per_conn = (rss1 - rss0) / n
        assert per_conn < 10 * 1024, \
            f"{per_conn:.0f} B/connection idle RSS (target < 10 KiB)"
        # zero per-connection threads: the fleet added NO threads
        assert threading.active_count() == threads0, \
            (threads0, threading.active_count())
        # and the fleet still serves: a request through the pile works
        q = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        q.sendall(_request_bytes("GET", "/_test/ping"))
        status, _, body, _ = _read_response(q)
        q.close()
        assert status == 200 and body == b'{"ok": true}'
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        srv.stop()
