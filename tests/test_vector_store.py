"""Vector retrieval parity suite (ISSUE 19 tentpole).

Contract under test: the IVF cluster-probe at `nprobe = lists` is
BIT-identical to the brute-force oracle — device and host — because the
probe tier is the exact path restricted to a candidate set, not an
approximation of it. The parity corpora are grid-quantized (entries
k/2^g with every product and partial sum exactly representable in f32),
which makes the distance bits independent of the backend's FMA grouping
(see ops/vector.host_dist); on such data every path — probe, brute,
pool-resident, pool-cold, starved — must agree to the bit, and the
MaxSim device scorer must agree with the f64 host oracle exactly.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.obs.device import LEDGER
from serenedb_tpu.ops import vector as vops
from serenedb_tpu.search.ivf import IvfIndex, MaxSimIndex, VecSegment
from serenedb_tpu.search.vector_store import VPOOL
from serenedb_tpu.utils import metrics
from serenedb_tpu.utils.config import REGISTRY


def grid(rng, shape, lo=-64, hi=64, denom=16.0):
    """Grid-quantized f32 array: entries k/denom — exact chain
    arithmetic in f32 for the sizes used here."""
    return rng.integers(lo, hi, shape).astype(np.float32) / \
        np.float32(denom)


def build_idx(mat, lists, metric="l2", centroids=None):
    n, d = mat.shape
    if centroids is None:
        init = vops.init_centroids(mat, lists)
        centroids = np.asarray(vops.kmeans_fit(
            jnp.asarray(vops.pad_rows(mat)), jnp.asarray(init), lists,
            4))
    centroids = np.ascontiguousarray(centroids, np.float32)
    codes = np.asarray(vops.assign_clusters(
        jnp.asarray(vops.pad_rows(mat)), jnp.asarray(centroids)))[:n]
    return IvfIndex(
        column="v", dim=d, lists=lists, metric=metric,
        centroids=centroids,
        segs=[VecSegment(mat, np.arange(n, dtype=np.int64), codes,
                         lists)],
        num_rows=n, data_version=1)


def host_topk(idx, queries, k, member=None):
    """Numpy oracle: host_dist bits + (dist asc, row asc) tie order.
    `member` optionally restricts to a logical-position mask (the
    probed-clusters candidate set)."""
    lay = idx.layout()
    mat = idx.host_logical()[:lay["ntot"]]
    rowids = lay["rowids"].astype(np.int64)
    if member is not None:
        mat, rowids = mat[member], rowids[member]
    ds, rs = [], []
    for q in np.asarray(queries, np.float32):
        dd = vops.host_dist(mat, q, idx.metric)
        order = np.lexsort((rowids, dd))[:k]
        ds.append(dd[order].astype(np.float32))
        rs.append(rowids[order])
    return np.stack(ds), np.stack(rs)


def bits_equal(a, b):
    return np.array_equal(np.asarray(a, np.float32).view(np.uint32),
                          np.asarray(b, np.float32).view(np.uint32))


# -- device vs host parity ----------------------------------------------------


@pytest.mark.parametrize("metric", ["l2", "ip", "cos"])
def test_full_probe_bitexact_vs_host_oracle(metric, rng):
    mat = grid(rng, (300, 16))
    # duplicated vectors: identical distances must surface in row-asc
    # order (the exact tie contract)
    mat[37] = mat[11]
    mat[205] = mat[11]
    idx = build_idx(mat, lists=8, metric=metric)
    qs = grid(rng, (9, 16))
    qs[3] = mat[11]
    d, r = idx.search(qs, 10, idx.lists)
    hd, hr = host_topk(idx, qs, 10)
    assert bits_equal(d, hd)
    assert np.array_equal(r, hr)
    tied = [row for row in r[3] if row in (11, 37, 205)]
    assert tied == [11, 37, 205]


def test_device_brute_oracle_bitexact(rng):
    mat = grid(rng, (257, 16))
    idx = build_idx(mat, lists=8)
    qs = grid(rng, (5, 16))
    db, rb = idx.brute_search(qs, 10)
    hd, hr = host_topk(idx, qs, 10)
    assert bits_equal(db, hd)
    assert np.array_equal(rb.astype(np.int64), hr)
    # and the probe program at nprobe=lists returns the same bits
    dp, rp = idx.search(qs, 10, idx.lists)
    assert bits_equal(dp, db) and np.array_equal(rp, rb.astype(np.int64))


def test_partial_probe_matches_restricted_oracle(rng):
    # grid CENTROIDS (sampled corpus rows) make the cluster selection
    # itself replicable on the host: top-nprobe centroid distances are
    # exact, ties break toward the lower cluster index — so the full
    # result must equal the oracle restricted to the probed clusters
    mat = grid(rng, (400, 16))
    lists, nprobe, k = 16, 4, 12
    cents = mat[rng.choice(400, lists, replace=False)].copy()
    idx = build_idx(mat, lists=lists, centroids=cents)
    lay = idx.layout()
    qs = grid(rng, (6, 16))
    d, r = idx.search(qs, k, nprobe)
    pos_cluster = np.repeat(np.arange(lists),
                            lay["counts"].astype(np.int64))
    for qi in range(len(qs)):
        cd = vops.host_dist(cents, qs[qi], idx.metric)
        probed = np.lexsort((np.arange(lists), cd))[:nprobe]
        member = np.isin(pos_cluster, probed)
        hd, hr = host_topk(idx, qs[qi:qi + 1], k, member=member)
        live = np.isfinite(hd[0])
        assert bits_equal(d[qi][live], hd[0][live])
        assert np.array_equal(r[qi][live], hr[0][live])


def test_multi_segment_layout_parity(rng):
    # two published segments (the incremental-append shape): the
    # cluster-major logical layout must stitch them without changing a
    # bit vs the single-segment oracle
    base = grid(rng, (200, 8))
    tail = grid(rng, (60, 8))
    lists = 8
    cents = base[rng.choice(200, lists, replace=False)].copy()
    idx = build_idx(base, lists=lists, centroids=cents)
    codes_t = np.asarray(vops.assign_clusters(
        jnp.asarray(vops.pad_rows(tail)), jnp.asarray(cents)))[:60]
    idx2 = IvfIndex(
        column="v", dim=8, lists=lists, metric="l2", centroids=cents,
        segs=idx.segs + [VecSegment(
            tail, np.arange(200, 260, dtype=np.int64), codes_t, lists)],
        num_rows=260, data_version=2)
    qs = grid(rng, (4, 8))
    d, r = idx2.search(qs, 10, lists)
    hd, hr = host_topk(idx2, qs, 10)
    assert bits_equal(d, hd) and np.array_equal(r, hr)


# -- pool residency -----------------------------------------------------------


def _with_pool(value, pages=None):
    olds = (REGISTRY.get_global("serene_vector_pool"),
            REGISTRY.get_global("serene_vector_pages"))
    REGISTRY.set_global("serene_vector_pool", value)
    if pages is not None:
        REGISTRY.set_global("serene_vector_pages", pages)
    return olds


def _restore_pool(olds):
    REGISTRY.set_global("serene_vector_pool", olds[0])
    REGISTRY.set_global("serene_vector_pages", olds[1])
    VPOOL.clear()


def test_pool_on_off_and_starved_bit_parity(rng):
    # 500 x 64-d rows need 8 pages (64 rows/page) — over the 4-page
    # starvation budget below, so that leg exercises the cold path
    mat = grid(rng, (500, 64))
    idx = build_idx(mat, lists=8)
    qs = grid(rng, (7, 64))
    olds = _with_pool(True)
    try:
        VPOOL.clear()
        d_on, r_on = idx.search(qs, 10, 4)
        assert VPOOL.stats()["pages_used"] > 0
        REGISTRY.set_global("serene_vector_pool", False)
        VPOOL.clear()
        d_off, r_off = idx.search(qs, 10, 4)
        # starved: a 4-page budget can't hold the segment → cold path
        REGISTRY.set_global("serene_vector_pool", True)
        REGISTRY.set_global("serene_vector_pages", 4)
        VPOOL.clear()
        d_st, r_st = idx.search(qs, 10, 4)
        assert VPOOL.stats()["pages_used"] == 0
        assert bits_equal(d_on, d_off) and np.array_equal(r_on, r_off)
        assert bits_equal(d_on, d_st) and np.array_equal(r_on, r_st)
    finally:
        _restore_pool(olds)


def test_warm_batch_one_dispatch_zero_vector_upload(rng):
    # the acceptance gate: a warm coalesced knn batch is ONE device
    # dispatch and uploads no vector bytes — only the (tiny) padded
    # query block crosses the bus
    mat = grid(rng, (512, 16))
    idx = build_idx(mat, lists=8)
    qs = grid(rng, (4, 16))
    olds = _with_pool(True)
    try:
        VPOOL.clear()
        idx.search(qs, 10, 4)    # residency + compile + map memos
        idx.search(qs, 10, 4)
        before = LEDGER.snapshot()
        d, r = idx.search(qs, 10, 4)
        after = LEDGER.snapshot()
        disp = sum(s["dispatches"] for s in after.values()) - \
            sum(s["dispatches"] for s in before.values())
        up = sum(s["bytes_up"] for s in after.values()) - \
            sum(s["bytes_up"] for s in before.values())
        assert disp == 1
        q_block = 4 * 16 * 4    # qp x dp x f32 — far below one page
        assert up <= q_block, \
            f"warm knn uploaded {up} bytes (query block is {q_block})"
        # still a correct answer, not just a cheap dispatch: every
        # returned candidate is exactly rescored (host-bit distances)
        for qi in range(len(qs)):
            live = np.isfinite(d[qi])
            hd = vops.host_dist(mat[r[qi][live]], qs[qi], "l2")
            assert bits_equal(d[qi][live], hd)
    finally:
        _restore_pool(olds)


def test_vector_metrics_and_stats_surface(rng):
    mat = grid(rng, (128, 16))
    idx = build_idx(mat, lists=4)
    olds = _with_pool(True)
    try:
        VPOOL.clear()
        q0 = metrics.VECTOR_SEARCH_QUERIES.value
        d0 = metrics.VECTOR_SEARCH_DISPATCHES.value
        idx.search(grid(rng, (3, 16)), 5, 2)
        assert metrics.VECTOR_SEARCH_QUERIES.value == q0 + 3
        assert metrics.VECTOR_SEARCH_DISPATCHES.value == d0 + 1
        assert metrics.VECTOR_BYTES_RESIDENT.value > 0
        from serenedb_tpu.obs import device as obs_device
        sec = obs_device.stats_section()
        assert "vector_pool" in sec and \
            sec["vector_pool"]["pages_used"] > 0
    finally:
        _restore_pool(olds)


# -- MaxSim -------------------------------------------------------------------


def build_maxsim(rng, ndocs=40, dim=8):
    toks, codes, tok_rows = [], [], []
    for di in range(ndocs):
        t = rng.integers(1, 5)
        toks.append(grid(rng, (t, dim), lo=-16, hi=16, denom=4.0))
        codes.append(np.full(t, di, np.int32))
        tok_rows.append(np.full(t, di, np.int32))
    vals = np.concatenate(toks, axis=0)
    seg = VecSegment(vals, np.concatenate(tok_rows),
                     np.concatenate(codes), ndocs)
    return MaxSimIndex(
        column="v", dim=dim, segs=[seg],
        doc_rows=np.arange(ndocs, dtype=np.int32), num_rows=ndocs,
        data_version=1)


def test_maxsim_device_matches_f64_host_oracle(rng):
    idx = build_maxsim(rng)
    q = grid(rng, (3, 8), lo=-16, hi=16, denom=4.0)
    scores, rows = idx.search(q, 10)
    hs = idx.host_scores(q)
    order = np.lexsort((idx.doc_rows, -hs))[:10]
    live = np.isfinite(scores)
    assert np.array_equal(rows[live],
                          idx.doc_rows[order][:live.sum()])
    # grid tokens: the f32 device score IS the f64 oracle value
    assert np.array_equal(scores[live].astype(np.float64), hs[order])


def test_maxsim_batch_matches_single(rng):
    idx = build_maxsim(rng, ndocs=25)
    qs = [grid(rng, (s, 8), lo=-16, hi=16, denom=4.0)
          for s in (2, 4, 3)]
    outs = idx.topk_batch(qs, 6, "maxsim")
    for q, (keys, rows) in zip(qs, outs):
        s1, r1 = idx.search(np.asarray(q), 6)
        live = np.isfinite(keys)
        assert bits_equal(-keys[live], s1[:live.sum()])
        assert np.array_equal(rows[live], r1[:live.sum()])


# -- engine-level matrix ------------------------------------------------------


def _grid_sql_table(c, rng, n=240, d=8, lists=8):
    vecs = grid(rng, (n, d))
    c.execute("CREATE TABLE gv (id INT, v TEXT)")
    rows = ", ".join(
        f"({i}, '{json.dumps([float(x) for x in vecs[i]])}')"
        for i in range(n))
    c.execute(f"INSERT INTO gv VALUES {rows}")
    c.execute(f"CREATE INDEX ON gv USING ivf (v) WITH (lists = {lists})")
    return vecs


@pytest.mark.parametrize("workers", [1, 4])
@pytest.mark.parametrize("shards", [1, 4])
@pytest.mark.parametrize("batcher", ["on", "off"])
def test_knn_sql_matrix_bit_identical(workers, shards, batcher, rng):
    # grid corpus through SQL: every worker/shard/batcher combination
    # must return the same rows and the same distance bits as the
    # full-scan oracle (nprobe = lists → exact)
    db = Database()
    c = db.connect()
    vecs = _grid_sql_table(c, rng)
    qs = json.dumps([float(x) for x in vecs[17]])
    c.execute(f"SET serene_workers = {workers}")
    c.execute(f"SET serene_shards = {shards}")
    c.execute(f"SET serene_search_batch = {batcher}")
    c.execute("SET serene_nprobe = 8")
    ex = c.execute(
        f"EXPLAIN SELECT id FROM gv ORDER BY v <-> '{qs}' LIMIT 7"
    ).rows()
    assert any("IvfScan" in r[0] for r in ex)
    got = c.execute(
        f"SELECT id, v <-> '{qs}' AS d FROM gv ORDER BY d LIMIT 7"
    ).rows()
    ref = c.execute(
        f"SELECT id, d FROM (SELECT id, v <-> '{qs}' AS d FROM gv) s "
        "ORDER BY d, id LIMIT 7").rows()
    assert got == ref
    assert got[0][0] == 17


def test_serene_nprobe_is_result_affecting():
    from serenedb_tpu.cache.result import RESULT_AFFECTING_SETTINGS
    assert "serene_nprobe" in RESULT_AFFECTING_SETTINGS
    assert "serene_maxsim" in RESULT_AFFECTING_SETTINGS
    assert "serene_vector_pool" not in RESULT_AFFECTING_SETTINGS
    assert "serene_vector_pages" not in RESULT_AFFECTING_SETTINGS


def test_maxsim_sql_device_vs_host_oracle(rng):
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE ms (id INT, v TEXT)")
    rows = []
    for i in range(30):
        toks = grid(rng, (int(rng.integers(1, 4)), 4),
                    lo=-16, hi=16, denom=4.0)
        rows.append(f"({i}, '{json.dumps([[float(x) for x in t] for t in toks])}')")
    c.execute(f"INSERT INTO ms VALUES {', '.join(rows)}")
    c.execute("CREATE INDEX ON ms USING maxsim (v)")
    q = grid(np.random.default_rng(3), (2, 4), lo=-16, hi=16, denom=4.0)
    qs = json.dumps([[float(x) for x in t] for t in q])
    ex = c.execute(
        f"EXPLAIN SELECT id FROM ms ORDER BY vec_maxsim(v, '{qs}') DESC "
        "LIMIT 5").rows()
    assert any("MaxSimScan" in r[0] for r in ex)
    dev = c.execute(
        f"SELECT id, vec_maxsim(v, '{qs}') AS s FROM ms "
        "ORDER BY s DESC LIMIT 5").rows()
    # the scalar-function oracle (subquery defeats the pushdown)
    ref = c.execute(
        f"SELECT id, s FROM (SELECT id, vec_maxsim(v, '{qs}') AS s "
        "FROM ms) t ORDER BY s DESC, id LIMIT 5").rows()
    assert dev == ref
    # host-oracle serving path (serene_maxsim = off): same rows
    c.execute("SET serene_maxsim = off")
    host = c.execute(
        f"SELECT id, vec_maxsim(v, '{qs}') AS s FROM ms "
        "ORDER BY s DESC LIMIT 5").rows()
    assert host == dev


def test_maxsim_index_append_invalidation(rng):
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE mi (id INT, v TEXT)")
    c.execute("INSERT INTO mi VALUES "
              "(1, '[[1,0],[0,1]]'), (2, '[[0.5,0.5]]')")
    c.execute("CREATE INDEX ON mi USING maxsim (v)")
    ex = c.execute("EXPLAIN SELECT id FROM mi ORDER BY "
                   "vec_maxsim(v, '[[1,0]]') DESC LIMIT 2").rows()
    assert any("MaxSimScan" in r[0] for r in ex)
    # any write invalidates the maxsim index (exact data_version match
    # only) — the query answers from the scalar function path
    c.execute("INSERT INTO mi VALUES (3, '[[1,1]]')")
    ex = c.execute("EXPLAIN SELECT id FROM mi ORDER BY "
                   "vec_maxsim(v, '[[1,0]]') DESC LIMIT 3").rows()
    assert not any("MaxSimScan" in r[0] for r in ex)
    got = c.execute("SELECT id FROM mi ORDER BY "
                    "vec_maxsim(v, '[[1,0]]') DESC LIMIT 3").rows()
    assert len(got) == 3
