"""ROW(...) anonymous composites: text rendering, binary record format
(oid 2249), COPY (query) TO. Reference: server/pg/serialize.cpp record
path (record_out / record_send)."""

import struct

import pytest

from serenedb_tpu.columnar import dtypes as dt
from serenedb_tpu.columnar.pgcopy import (FIELD_OID, record_parts,
                                          record_text)
from serenedb_tpu.engine import Database


@pytest.fixture
def conn():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE t (a INT, b TEXT, f DOUBLE, ts TIMESTAMP)")
    c.execute("INSERT INTO t VALUES "
              "(1, 'plain', 1.5, '2020-01-02 03:04:05'), "
              "(2, 'needs,quote', -2.25, NULL), "
              "(3, NULL, NULL, NULL)")
    return c


def test_row_returns_record_type(conn):
    r = conn.execute("SELECT ROW(1, 'x')")
    assert str(r.batch.columns[0].type) == "record"
    oids, vals = record_parts(r.batch.columns[0].to_pylist()[0])
    assert oids == [23, 25] and vals == [1, "x"]


def test_record_text_rendering(conn):
    rows = conn.execute(
        "SELECT ROW(a, b) FROM t ORDER BY a").batch.columns[0].to_pylist()
    assert [record_text(v) for v in rows] == [
        "(1,plain)", '(2,"needs,quote")', "(3,)"]


def test_record_text_quoting_rules():
    import json
    def rec(oids, vals):
        return record_text(json.dumps({"o": oids, "v": vals}))
    assert rec([25], [""]) == '("")'
    assert rec([25], ['has"quote']) == '("has""quote")'
    assert rec([25], ["back\\slash"]) == '("back\\\\slash")'
    assert rec([25], ["a b"]) == '("a b")'
    assert rec([16, 16], [True, False]) == "(t,f)"
    assert rec([701], [2.5]) == "(2.5)"
    assert rec([1082], [0]) == "(1970-01-01)"
    assert rec([23, 25], [None, None]) == "(,)"


def test_record_binary_format(conn):
    from serenedb_tpu.columnar.pgcopy import encode_value
    val = conn.execute(
        "SELECT ROW(7, 'ab', NULL)").batch.columns[0].to_pylist()[0]
    raw = encode_value(val, dt.RECORD)
    (nf,) = struct.unpack_from("!i", raw, 0)
    assert nf == 3
    off = 4
    fields = []
    for _ in range(nf):
        oid, ln = struct.unpack_from("!Ii", raw, off)
        off += 8
        payload = raw[off:off + max(ln, 0)]
        off += max(ln, 0)
        fields.append((oid, ln, payload))
    assert fields[0][0] == 23 and fields[0][2] == struct.pack("!i", 7)
    assert fields[1][0] == 25 and fields[1][2] == b"ab"
    assert fields[2][1] == -1   # NULL field
    assert off == len(raw)


def test_record_over_wire_text_and_binary(conn):
    from serenedb_tpu.server.pgwire import oid_of_type, pg_text
    val = conn.execute("SELECT ROW(1, 'x y')").batch.columns[0]
    assert oid_of_type(val.type) == 2249
    assert pg_text(val.to_pylist()[0], val.type) == b'(1,"x y")'


def test_copy_query_to_csv(conn, tmp_path):
    p = tmp_path / "rec.csv"
    conn.execute(f"COPY (SELECT a, ROW(a, b) FROM t ORDER BY a) "
                 f"TO '{p}' (FORMAT csv)")
    lines = p.read_text().splitlines()
    assert lines[0] == '1,"(1,plain)"'
    assert lines[1] == '2,"(2,""needs,quote"")"'


def test_copy_query_to_binary_roundtrip_scalar(conn, tmp_path):
    """COPY (query) TO binary with scalar output decodes back exactly."""
    p = tmp_path / "q.bin"
    conn.execute(f"COPY (SELECT a, b FROM t ORDER BY a) TO '{p}' "
                 "(FORMAT binary)")
    conn.execute("CREATE TABLE t2 (a INT, b TEXT)")
    conn.execute(f"COPY t2 FROM '{p}' (FORMAT binary)")
    assert conn.execute("SELECT * FROM t2 ORDER BY a").rows() == \
        conn.execute("SELECT a, b FROM t ORDER BY a").rows()


def test_copy_query_from_is_an_error(conn, tmp_path):
    from serenedb_tpu import errors
    with pytest.raises(errors.SqlError):
        conn.execute("COPY (SELECT 1) FROM 'x.csv'")


def test_row_field_oids_cover_scalar_types():
    for tid in (dt.TypeId.BOOL, dt.TypeId.INT, dt.TypeId.BIGINT,
                dt.TypeId.DOUBLE, dt.TypeId.VARCHAR, dt.TypeId.DATE,
                dt.TypeId.TIMESTAMP):
        assert tid in FIELD_OID


def test_row_in_where_and_equality(conn):
    # field-wise comparison; row 3 has b NULL, so its self-comparison is
    # SQL NULL and the row filters out (PG record_eq semantics)
    r = conn.execute("SELECT count(*) FROM t "
                     "WHERE ROW(a, b) = ROW(a, b)").scalar()
    assert r == 2


def test_record_fieldwise_compare_and_order(conn):
    assert conn.execute("SELECT ROW(10) > ROW(2)").scalar() is True
    assert conn.execute("SELECT ROW(1, 'a') < ROW(1, 'b')").scalar() is True
    assert conn.execute("SELECT ROW(1,NULL) = ROW(2,NULL)").scalar() is False
    assert conn.execute("SELECT ROW(1,NULL) = ROW(1,NULL)").scalar() is None
    rows = [r[0] for r in conn.execute(
        "SELECT a FROM t ORDER BY ROW(a) DESC").rows()]
    assert rows == [3, 2, 1]
    import pytest as _pytest

    from serenedb_tpu import errors as _errors
    with _pytest.raises(_errors.SqlError):
        conn.execute("SELECT ROW(1) = ROW(1, 2)")


def test_nested_record_and_array_fields(conn):
    v = conn.execute("SELECT ROW(ROW(1,2),3)").batch.columns[0].to_pylist()[0]
    assert record_text(v) == '("(1,2)",3)'
    v2 = conn.execute(
        "SELECT ROW(ARRAY[1,2],'x')").batch.columns[0].to_pylist()[0]
    assert record_text(v2) == '("{1,2}",x)'


def test_record_field_whitespace_quoting(conn):
    v = conn.execute(
        "SELECT ROW('a' || chr(9) || 'b')").batch.columns[0].to_pylist()[0]
    assert record_text(v) == '("a\tb")'
