"""In-program multi-chip execution (ISSUE 12): shard_map/psum combines.

Contract under test: with `serene_shard_combine = device` the sharded
fused join/aggregate executes as ONE shard_map-partitioned program over
the parallel/mesh.py data axis — psum/pmin/pmax collectives reduce the
integer accumulators/limb stacks/min-max partials in HBM and the host
sees only the final combined result (proven by dispatch count) — and
sharded search top-k merges with an in-program per-shard top-k plus one
all_gather hop. Every accumulator is an integer add or a min/max
selection, exact in any reduction order, so results are BIT-IDENTICAL
to the host-side combine (`= host`, the PR 9 oracle) and to shards=1
across the whole matrix: combine device/host × shards 1/2/4 × workers
1/4 × zonemap on/off, including ragged last shards, empty/all-pruned
shards, and multi-segment search (engine-level + MultiSearcher-direct).
`serene_shard_combine` stays OUT of the result cache's settings digest
(bit-identity is the contract), and the Collective* gauges / `Shards:
combine=` EXPLAIN line attribute the tier's work.
"""

import json

import numpy as np
import pytest

from serenedb_tpu.columnar import dtypes as dt
from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.exec import shard as shard_mod
from serenedb_tpu.exec.tables import MemTable
from serenedb_tpu.utils import metrics
from serenedb_tpu.utils.config import REGISTRY as SETTINGS


def _mk_conn(nl=6000, nr=3000, seed=11):
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE l (ik INT, sk TEXT, ts BIGINT, v BIGINT)")
    c.execute("CREATE TABLE r (ik INT, sk TEXT, w BIGINT)")

    def mk(n, null_frac, sd, payload, with_ts):
        rng = np.random.default_rng(sd)
        ik = rng.integers(0, 40, n).astype(np.int32)
        ikv = rng.random(n) > null_frac
        cols = {
            "ik": Column(dt.INT, ik, ikv),
            "sk": Column.from_numpy(
                rng.choice(["alpha", "beta", "gamma", "delta"], n)),
        }
        if with_ts:
            cols["ts"] = Column.from_numpy(np.arange(n, dtype=np.int64))
        cols[payload] = Column.from_numpy(
            rng.integers(-500, 500, n, dtype=np.int64))
        return Batch.from_pydict(cols)

    db.schemas["main"].tables["l"] = MemTable(
        "l", mk(nl, 0.1, seed, "v", True))
    db.schemas["main"].tables["r"] = MemTable(
        "r", mk(nr, 0.15, seed + 1, "w", False))
    c.execute("SET serene_result_cache = off")
    c.execute("SET serene_morsel_rows = 1024")
    c.execute("SET serene_parallel_min_rows = 1024")
    c.execute("SET serene_device = 'tpu'")
    c.execute("SET serene_device_fused = on")
    return c


def _rows(c, q):
    return repr(c.execute(q).rows())


JOIN_Q = ("SELECT l.sk, count(*), sum(v), sum(w) FROM l JOIN r "
          "ON l.ik = r.ik GROUP BY l.sk ORDER BY l.sk")

#: grouped/scalar aggregates, joins (incl. min/max + avg limb paths),
#: top-N, empty and all-pruned shapes — every cell of the matrix must
#: be bit-identical to shards=1
QUERIES = [
    # morsel/device grouped aggregate (single table)
    "SELECT sk, count(*), sum(v), min(v), max(v) FROM l "
    "WHERE v > -400 GROUP BY sk ORDER BY sk",
    # joins: scalar + grouped; min/max partials ride pmin/pmax, avg and
    # sum exercise the limb/direct psum paths
    "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.ik "
    "WHERE v > 0",
    "SELECT l.sk, count(*), sum(v), sum(w), min(w), max(v) FROM l "
    "JOIN r ON l.ik = r.ik GROUP BY l.sk ORDER BY l.sk",
    "SELECT l.ik, count(*), avg(w) FROM l JOIN r ON l.sk = r.sk "
    "WHERE v > 250 GROUP BY l.ik ORDER BY l.ik NULLS LAST",
    # top-N over a filtered scan
    "SELECT ts, v FROM l WHERE v > 150 ORDER BY ts DESC LIMIT 9",
    # empty result / all-pruned shards
    "SELECT count(*), sum(v) FROM l WHERE ts < -1",
]


@pytest.mark.parametrize("zonemap", ["on", "off"])
@pytest.mark.parametrize("combine", ["device", "host"])
def test_multichip_parity_matrix(combine, zonemap):
    """combine device/host × shards 1/2/4 × workers 1/4 per zonemap
    leg — every cell bit-identical to shards=1 at the same settings."""
    c = _mk_conn()
    c.execute(f"SET serene_zonemap = {zonemap}")
    c.execute(f"SET serene_shard_combine = {combine}")
    for q in QUERIES:
        ref = None
        for workers in (1, 4):
            c.execute(f"SET serene_workers = {workers}")
            c.execute("SET serene_shards = 1")
            base = _rows(c, q)
            if ref is None:
                ref = base
            assert base == ref, f"workers perturbed results: {q}"
            for shards in (2, 4):
                c.execute(f"SET serene_shards = {shards}")
                got = _rows(c, q)
                assert got == ref, \
                    f"combine={combine} shards={shards} " \
                    f"workers={workers} diverged: {q}"
        c.execute("SET serene_shards = 1")


def test_collective_single_dispatch():
    """THE dispatch-count proof: with the device combine the whole
    sharded fused join/agg is ONE dispatch (host sees only the final
    combined result) — not build + N probe dispatches."""
    c = _mk_conn()
    c.execute("SET serene_shards = 1")
    ref = _rows(c, JOIN_Q)
    c.execute("SET serene_shards = 4")
    c.execute("SET serene_shard_combine = device")
    _rows(c, JOIN_Q)                      # warm compile + upload caches
    before = metrics.DEVICE_OFFLOADS.value
    cb = metrics.COLLECTIVE_DISPATCHES.value
    ns0 = metrics.COLLECTIVE_COMBINE_NS.value
    assert _rows(c, JOIN_Q) == ref
    assert metrics.DEVICE_OFFLOADS.value - before == 1, \
        "device combine must be ONE dispatch, not build+N"
    assert metrics.COLLECTIVE_DISPATCHES.value - cb == 1
    assert metrics.COLLECTIVE_COMBINE_NS.value > ns0
    # the host combine on the same query really is build+N (the shape
    # the collective dispatch replaces); build output is cached, so
    # expect the N probe dispatches at minimum
    c.execute("SET serene_shard_combine = host")
    before = metrics.DEVICE_OFFLOADS.value
    assert _rows(c, JOIN_Q) == ref
    assert metrics.DEVICE_OFFLOADS.value - before >= 4


def test_collective_ragged_last_shard():
    """A row count that leaves the last block (and thus the last
    shard's span set) short exercises pad_to_multiple masking: padded
    rows must never count."""
    c = _mk_conn(nl=4097, nr=1500, seed=23)
    c.execute("SET serene_shards = 1")
    ref = _rows(c, JOIN_Q)
    c.execute("SET serene_shards = 4")
    for combine in ("device", "host"):
        c.execute(f"SET serene_shard_combine = {combine}")
        assert _rows(c, JOIN_Q) == ref, combine


def test_collective_empty_and_all_pruned():
    c = _mk_conn()
    c.execute("SET serene_shards = 4")
    c.execute("SET serene_shard_combine = device")
    for q in ("SELECT count(*), sum(v) FROM l WHERE ts < -1",
              "SELECT sk, sum(v) FROM l WHERE ts < -1 GROUP BY sk "
              "ORDER BY sk",
              "SELECT count(*), sum(v), sum(w) FROM l JOIN r "
              "ON l.ik = r.ik WHERE ts < -1"):
        c.execute("SET serene_shards = 1")
        ref = _rows(c, q)
        c.execute("SET serene_shards = 4")
        assert _rows(c, q) == ref, q


def test_collective_write_invalidation():
    """A write between collective executions must surface fresh data:
    the mesh-sharded stacked uploads key on publications."""
    c = _mk_conn()
    c.execute("SET serene_shards = 2")
    c.execute("SET serene_shard_combine = device")
    q = "SELECT count(*), sum(v), sum(w) FROM l JOIN r ON l.ik = r.ik"
    first = c.execute(q).rows()
    c.execute("INSERT INTO r VALUES (1, 'alpha', 7)")
    second = c.execute(q).rows()
    assert second != first, "write must invalidate mesh-sharded caches"
    c.execute("SET serene_shards = 1")
    assert c.execute(q).rows() == second


# -- search: in-program per-shard top-k + all_gather merge -------------------


def _search_conn():
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT)")
    words = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta"]
    rng = np.random.default_rng(5)
    vals = ", ".join(f"({i}, '{' '.join(rng.choice(words, 5))}')"
                     for i in range(2000))
    c.execute(f"INSERT INTO docs VALUES {vals}")
    c.execute("CREATE INDEX ON docs USING inverted (body)")
    for j in range(4):            # appends → a real multi-segment set
        vals = ", ".join(f"({10000 + 100 * j + i}, "
                         f"'{' '.join(rng.choice(words, 5))}')"
                         for i in range(100))
        c.execute(f"INSERT INTO docs VALUES {vals}")
        c.execute("SELECT count(*) FROM docs WHERE body @@ 'alpha'")
    c.execute("SET serene_result_cache = off")
    return db, c


SEARCH_QUERIES = [
    "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'alpha | beta' "
    "ORDER BY s DESC, id LIMIT 25",
    "SELECT id FROM docs WHERE body @@ 'alpha & beta' ORDER BY id "
    "LIMIT 20",
    "SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'zzz_nothing' "
    "ORDER BY s DESC LIMIT 5",
]


def test_search_topk_combine_parity_engine():
    _db, c = _search_conn()
    for q in SEARCH_QUERIES:
        c.execute("SET serene_shards = 1")
        ref = _rows(c, q)
        for shards in (2, 4):
            for combine in ("device", "host"):
                c.execute(f"SET serene_shards = {shards}")
                c.execute(f"SET serene_shard_combine = {combine}")
                for workers in (1, 4):
                    c.execute(f"SET serene_workers = {workers}")
                    assert _rows(c, q) == ref, (q, shards, combine,
                                                workers)
        c.execute("SET serene_shards = 1")


def test_multisearcher_combine_parity_direct():
    """MultiSearcher layer: topk and cpu_topk bit-identical (scores,
    doc ids, tie order) under the in-program merge, and the merge
    really dispatches a collective."""
    db, c = _search_conn()
    from serenedb_tpu.search.index import find_index
    from serenedb_tpu.search.query import parse_query
    provider = db.resolve_table(["docs"])
    ms = find_index(provider, "body").searchers["body"]
    assert len(ms.segments) > 2
    node = parse_query("alpha | gamma", ms.analyzer)
    prior_sh = SETTINGS.get_global("serene_shards")
    prior_cb = SETTINGS.get_global("serene_shard_combine")
    try:
        SETTINGS.set_global("serene_shards", 1)
        s1, d1 = ms.topk(node, 10)
        c1, cd1 = ms.cpu_topk(node, 10)
        for shards in (2, 4):
            SETTINGS.set_global("serene_shards", shards)
            for combine in ("device", "host"):
                SETTINGS.set_global("serene_shard_combine", combine)
                before = metrics.COLLECTIVE_DISPATCHES.value
                s, d = ms.topk(node, 10)
                cs, cd = ms.cpu_topk(node, 10)
                assert np.array_equal(s.view(np.uint32),
                                      s1.view(np.uint32))
                assert np.array_equal(d, d1)
                assert np.array_equal(cs.view(np.uint32),
                                      c1.view(np.uint32))
                assert np.array_equal(cd, cd1)
                got = metrics.COLLECTIVE_DISPATCHES.value - before
                if combine == "device":
                    assert got >= 1, "device combine must dispatch"
                else:
                    assert got == 0, "host combine must not dispatch"
    finally:
        SETTINGS.set_global("serene_shards", prior_sh)
        SETTINGS.set_global("serene_shard_combine", prior_cb)


def test_device_merge_tie_order_exact():
    """Crafted score ties across shards (incl. a -0.0 vs 0.0 pair):
    the in-program two-key sort must reproduce the heap merge's
    (score desc, doc asc) order bit for bit."""
    from serenedb_tpu.search.searcher import (_device_merge_topk,
                                              merge_segment_topk)
    rng = np.random.default_rng(3)
    seg_outs, bases = [], []
    base = 0
    for si in range(5):
        n = int(rng.integers(3, 9))
        sc = rng.choice(np.asarray(
            [2.5, 2.5, 1.25, 0.0, -0.0, 3.75], dtype=np.float32), n)
        dd = np.sort(rng.choice(50, n, replace=False)).astype(np.int64)
        seg_outs.append([(sc, dd)])
        bases.append(base)
        base += 50
    ref = merge_segment_topk(seg_outs, bases, 1, 7)
    got = _device_merge_topk(seg_outs, bases, 1, 7, 3)
    assert got is not None
    assert np.array_equal(got[0][1], ref[0][1])
    assert np.array_equal(got[0][0].view(np.uint32),
                          ref[0][0].view(np.uint32))


def test_device_merge_inadmissible_falls_back():
    """Doc ids at/above the int32 padding sentinel refuse the device
    merge (None → host heap)."""
    from serenedb_tpu.search.searcher import _device_merge_topk
    seg_outs = [[(np.asarray([1.0], np.float32),
                  np.asarray([2**31 - 1], np.int64))],
                [(np.asarray([2.0], np.float32),
                  np.asarray([3], np.int64))]]
    assert _device_merge_topk(seg_outs, [0, 0], 1, 5, 2) is None


# -- settings / observability satellites -------------------------------------


def test_combine_mode_resolution():
    import jax
    prior = SETTINGS.get_global("serene_shard_combine")
    try:
        SETTINGS.set_global("serene_shard_combine", "auto")
        expect = "device" if len(jax.devices()) > 1 else "host"
        assert shard_mod.combine_mode(None) == expect
        SETTINGS.set_global("serene_shard_combine", "host")
        assert shard_mod.combine_mode(None) == "host"
        SETTINGS.set_global("serene_shard_combine", "device")
        assert shard_mod.combine_mode(None) == "device"
        with pytest.raises(Exception):
            SETTINGS.set_global("serene_shard_combine", "bogus")
    finally:
        SETTINGS.set_global("serene_shard_combine", prior)


def test_shard_combine_not_result_affecting():
    """Bit-identity is the documented contract, so the combine location
    must never split the result cache (the serene_shards pattern)."""
    from serenedb_tpu.cache.result import RESULT_AFFECTING_SETTINGS
    assert "serene_shard_combine" not in RESULT_AFFECTING_SETTINGS


def test_result_cache_shared_across_combine_settings():
    c = _mk_conn()
    c.execute("SET serene_result_cache = on")
    c.execute("SET serene_shards = 4")
    c.execute("SET serene_shard_combine = host")
    ref = _rows(c, JOIN_Q)
    h0 = metrics.RESULT_CACHE_HITS.value
    c.execute("SET serene_shard_combine = device")
    assert _rows(c, JOIN_Q) == ref
    assert metrics.RESULT_CACHE_HITS.value > h0, \
        "combine=device must hit the entry stored under combine=host"


def test_explain_analyze_combine_line():
    c = _mk_conn()
    c.execute("SET serene_shards = 4")
    c.execute("SET serene_shard_combine = device")
    out = c.execute(f"EXPLAIN ANALYZE {JOIN_Q}").rows()
    text = "\n".join(r[0] for r in out)
    assert "combine=device" in text, text
    c.execute("SET serene_shard_combine = host")
    out = c.execute(f"EXPLAIN ANALYZE {JOIN_Q}").rows()
    text = "\n".join(r[0] for r in out)
    assert "combine=host" in text, text


def test_explain_json_combine_key():
    c = _mk_conn()
    c.execute("SET serene_shards = 4")
    c.execute("SET serene_shard_combine = device")
    out = c.execute(f"EXPLAIN (ANALYZE, FORMAT JSON) {JOIN_Q}").rows()
    doc = json.loads(out[0][0])

    def walk(node):
        yield node
        for kid in node.get("Plans", []):
            yield from walk(kid)

    nodes = list(walk(doc[0]["Plan"]))
    assert any(n.get("Shard Combine") == "device" for n in nodes), \
        "Shard Combine key missing from JSON plan"


def test_collective_trace_span():
    c = _mk_conn()
    c.execute("SET serene_trace = on")
    c.execute("SET serene_shards = 4")
    c.execute("SET serene_shard_combine = device")
    c.execute(JOIN_Q)
    from serenedb_tpu.obs.trace import FLIGHT
    entry = FLIGHT.get(c._active_trace.trace_id)
    names = [s["name"] for s in entry["spans"]]
    assert "collective_dispatch" in names, names
    assert "shard_pipeline" not in names, \
        "the collective dispatch subsumes the per-shard device lanes"


def test_metrics_export_collective_gauges():
    from serenedb_tpu.obs.export import prometheus_text, stats_json
    text = prometheus_text()
    assert "serenedb_collective_dispatches" in text
    assert "serenedb_collective_combine_ns" in text
    snap = stats_json()["metrics"]
    assert "CollectiveDispatches" in snap
    assert "CollectiveCombineNs" in snap
