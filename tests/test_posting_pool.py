"""Device-resident paged postings (ISSUE 16, search/posting_pool.py).

Contract under test: `serene_posting_pool` (default on) moves WHERE
ragged-admitted postings are scored — page-resident coalesced batches
run as ONE jitted gather-and-segment-accumulate program over the pool's
HBM page tables — but never a result bit: every cell of the pool on/off
× workers × shards × cache matrix is bit-identical to the host ragged
oracle, including partial residency (device prefix + host suffix merge)
and LRU eviction mid-stream under a starved page budget. The transfer
ledger proves the perf claim: a warm repeat of a coalesced batch
uploads ZERO host→device posting bytes and performs exactly ONE
dispatch. Observability: pool gauges, `sdb_posting_pool()` rows keyed
by publication, the `GET /device` posting_pool section, and quiet
DeviceRecompileStorms across batch sizes.
"""

import json
import threading
import types
import urllib.request

import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.obs import device as obs_device
from serenedb_tpu.ops import bm25 as bm25_ops
from serenedb_tpu.search import posting_pool
from serenedb_tpu.search.analysis import get_analyzer
from serenedb_tpu.search.batcher import SearchBatcher
from serenedb_tpu.search.posting_pool import POOL
from serenedb_tpu.search.query import parse_query
from serenedb_tpu.search.searcher import SegmentSearcher
from serenedb_tpu.search.segment import build_field_index
from serenedb_tpu.utils import faults, metrics
from serenedb_tpu.utils.config import REGISTRY as SETTINGS

WORDS = ("apple banana cherry quick brown fox jumps over lazy dog search "
         "engine database index query term").split()


class _global:
    """Set a GLOBAL setting for the scope, restore on exit."""

    def __init__(self, name, value):
        self.name, self.value = name, value

    def __enter__(self):
        self.old = SETTINGS.get_global(self.name)
        SETTINGS.set_global(self.name, self.value)

    def __exit__(self, *exc):
        SETTINGS.set_global(self.name, self.old)
        return False


@pytest.fixture(autouse=True)
def _ragged_regime(monkeypatch):
    """Force the packed-plane regime (no dense matmul) so the ragged
    resolver — and with it the posting pool — actually fires on these
    small corpora, and start every test from an empty pool region."""
    monkeypatch.setattr(bm25_ops, "DENSE_HBM_BUDGET", 0)
    POOL.clear()
    yield
    POOL.clear()


def _make_db(n=600, seed=7):
    rng = np.random.default_rng(seed)
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE docs (id INT, body TEXT)")
    vals = []
    for i in range(n):
        if i % 97 == 0:
            vals.append(f"({i}, NULL)")
        elif i % 13 == 0:
            vals.append(f"({i}, 'apple banana apple')")   # tie-heavy
        else:
            body = " ".join(rng.choice(WORDS, rng.integers(3, 24)))
            vals.append(f"({i}, '{body}')")
    c.execute("INSERT INTO docs VALUES " + ", ".join(vals))
    c.execute("CREATE INDEX ON docs USING inverted (body)")
    return db


@pytest.fixture(scope="module")
def db():
    return _make_db()


#: the PR 8 parity query set (tests/test_search_batch.py) plus two
#: large-limit disjunctions — k past the MaxScore sparse path, so these
#: are the queries that actually reach the ragged resolver and the pool
QUERIES = [
    ("SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple' "
     "ORDER BY s DESC LIMIT 10"),
    ("SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple & banana' "
     "ORDER BY s DESC LIMIT 10"),
    ("SELECT id, bm25(body) AS s FROM docs WHERE body ## 'quick brown' "
     "ORDER BY s DESC LIMIT 10"),
    ("SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple | dog' "
     "AND id < 300 ORDER BY s DESC, id LIMIT 10"),
    ("SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'banana' "
     "ORDER BY s DESC LIMIT 10"),
    ("SELECT id FROM docs WHERE body @@ 'zzzznothing' "
     "ORDER BY bm25(body) DESC LIMIT 5"),
    ("SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'quick & fox' "
     "ORDER BY s DESC LIMIT 5000"),
    ("SELECT id, tfidf(body) AS s FROM docs WHERE body @@ 'cherry | dog' "
     "ORDER BY s DESC LIMIT 10"),
    ("SELECT id, bm25(body) AS s FROM docs WHERE body @@ 'apple | dog' "
     "ORDER BY s DESC, id LIMIT 5000"),
    ("SELECT id, bm25(body) AS s FROM docs "
     "WHERE body @@ 'banana | fox | engine' ORDER BY s DESC, id LIMIT 5000"),
]


def _seg(n=700, seed=11, vocab=WORDS):
    an = get_analyzer("text")
    rng = np.random.default_rng(seed)
    docs = [" ".join(rng.choice(vocab, rng.integers(3, 24)))
            for _ in range(n)]
    fi = build_field_index(docs, an)
    return SegmentSearcher(fi, an, len(docs)), an


def _bits_equal(a, b):
    return (np.array_equal(a[0].view(np.uint32), b[0].view(np.uint32))
            and np.array_equal(a[1], b[1]))


# -- parity ---------------------------------------------------------------


def test_parity_matrix_pool(db):
    """pool on/off × workers 1/4 × shards 1/4 × result cache on/off:
    every combination returns the pool-off serial oracle's exact rows
    (scores included — engine rows surface the f32 bits)."""
    oc = db.connect()
    oc.execute("SET serene_result_cache = off")
    oc.execute("SET serene_workers = 1")
    with _global("serene_posting_pool", False):
        oracle = {q: oc.execute(q).rows() for q in QUERIES}
    for pool in (True, False):
        with _global("serene_posting_pool", pool):
            for workers in (1, 4):
                for shards in (1, 4):
                    for cache in ("on", "off"):
                        c = db.connect()
                        c.execute(f"SET serene_workers = {workers}")
                        c.execute(f"SET serene_shards = {shards}")
                        c.execute(f"SET serene_result_cache = {cache}")
                        for q in QUERIES:
                            got = c.execute(q).rows()
                            assert got == oracle[q], \
                                (pool, workers, shards, cache, q)
    # the on-cells actually exercised the device tier
    assert metrics.POSTING_POOL_DEVICE_QUERIES.value > 0


def test_searcher_parity_and_warm_hits():
    """Searcher-level: pool on vs off bit parity on cold AND warm
    dispatches; the warm repeat serves every slice from resident pages
    (hits only, no new misses)."""
    seg, an = _seg()
    nodes = [parse_query(q, an)
             for q in ("apple | dog", "banana | fox | dog",
                       "cherry | term | engine", "apple")]
    with _global("serene_posting_pool", False):
        ref = seg.topk_batch(nodes, 5000, ragged=True)
    cold = seg.topk_batch(nodes, 5000, ragged=True)
    m0 = metrics.POSTING_POOL_MISSES.value
    warm = seg.topk_batch(nodes, 5000, ragged=True)
    assert metrics.POSTING_POOL_MISSES.value == m0   # all resident
    for i in range(len(nodes)):
        assert _bits_equal(cold[i], ref[i]), i
        assert _bits_equal(warm[i], ref[i]), i


def test_partial_residency_and_eviction_mid_stream():
    """A starved page budget forces partial residency (device scores
    the resident slice prefix, the host merges the suffix) and LRU
    eviction between queries — results stay bit-identical to the
    pool-off oracle throughout the stream."""
    seg, an = _seg(n=3000, seed=5)
    qs = ["apple | banana | cherry | quick | brown | fox",
          "dog | fox | lazy | brown | jumps | over",
          "search | engine | database | index | query | term",
          "apple | dog",
          "query | term | jumps | over | lazy | cherry"]
    nodes = [parse_query(q, an) for q in qs]
    with _global("serene_posting_pool", False):
        ref = [seg.topk_batch([n], 5000, ragged=True)[0] for n in nodes]
    with _global("serene_posting_pages", 8):
        e0 = metrics.POSTING_POOL_EVICTIONS.value
        p0 = metrics.POSTING_POOL_PARTIAL.value
        for rep in range(2):     # second sweep re-faults evicted terms
            for i, n in enumerate(nodes):
                got = seg.topk_batch([n], 5000, ragged=True)[0]
                assert _bits_equal(got, ref[i]), (rep, qs[i])
        assert metrics.POSTING_POOL_EVICTIONS.value > e0
        assert metrics.POSTING_POOL_PARTIAL.value > p0
        assert POOL.stats()["pages_used"] <= 8


# -- the perf claim: warm repeats never leave HBM -------------------------


def test_warm_repeat_zero_upload_one_dispatch():
    """Transfer-ledger proof of the tentpole: a warm repeat of the same
    coalesced batch moves ZERO host→device bytes and performs exactly
    ONE device dispatch (the batched gather-accumulate program)."""
    seg, an = _seg()
    nodes = [parse_query(q, an)
             for q in ("apple | dog", "banana | fox | dog",
                       "cherry | term | engine")]
    out1 = seg.topk_batch(nodes, 5000, ragged=True)   # faults pages in
    seg.topk_batch(nodes, 5000, ragged=True)          # warms batch memo

    def _sums():
        snap = obs_device.LEDGER.snapshot().values()
        return (sum(s["bytes_up"] for s in snap),
                sum(s["dispatches"] for s in snap))
    up0, disp0 = _sums()
    out3 = seg.topk_batch(nodes, 5000, ragged=True)
    up1, disp1 = _sums()
    assert up1 - up0 == 0, "warm repeat uploaded posting bytes"
    assert disp1 - disp0 == 1, "warm repeat was not a single dispatch"
    for i in range(len(nodes)):
        assert _bits_equal(out3[i], out1[i]), i


def test_no_recompile_storm_across_batch_sizes():
    """Coalesced batches arrive at every size; the pow2-padded program
    axes keep the compile ledger quiet (no DeviceRecompileStorms).
    Starts from a cleared ledger — the storm window is per-family and
    minutes wide, so compiles from unrelated suite tests would prime
    it."""
    obs_device.PROGRAMS.clear()
    seg, an = _seg()
    nodes = [parse_query(q, an)
             for q in ("apple | dog", "banana | fox", "cherry | term",
                       "apple | engine", "dog | lazy | fox")]
    s0 = metrics.DEVICE_RECOMPILE_STORMS.value
    for size in (1, 2, 3, 4, 5):
        seg.topk_batch(nodes[:size], 5000, ragged=True)
    assert metrics.DEVICE_RECOMPILE_STORMS.value == s0


# -- bounded memos (satellite 1) ------------------------------------------


def test_ragged_memo_charge_clears_past_cap(monkeypatch):
    """Crossing RAGGED_MEMO_BYTES_CAP clears every ragged memo —
    plan slices, candidate tables, plain-store slices, and the pool's
    batch descriptor memo — then restarts the byte count."""
    monkeypatch.setattr(SegmentSearcher, "RAGGED_MEMO_BYTES_CAP", 100)
    plan = types.SimpleNamespace(_ragged_slices={"x": 1},
                                 _ragged_accum=("c", ["i"]))
    store = types.SimpleNamespace(
        _plan_cache={"k": plan, "none": None},
        _ragged_plain={(2, 7): ("d", "t", None)},
        _pool_batch_memo={"mk": {"si": 1}})
    SegmentSearcher._ragged_memo_charge(store, 60)
    assert store._ragged_memo_bytes == 60
    assert hasattr(plan, "_ragged_accum")          # under cap: kept
    SegmentSearcher._ragged_memo_charge(store, 60)
    assert store._ragged_memo_bytes == 60          # reset to new charge
    assert not hasattr(plan, "_ragged_accum")
    assert not hasattr(plan, "_ragged_slices")
    assert store._ragged_plain == {}
    assert store._pool_batch_memo == {}


def test_ragged_memo_bounded_in_flight(monkeypatch):
    """Integration bound: under a small cap, a stream of novel query
    shapes keeps the accounted memo bytes at/below the cap and the pool
    batch memo at/below its entry cap."""
    monkeypatch.setattr(SegmentSearcher, "RAGGED_MEMO_BYTES_CAP", 32 << 10)
    seg, an = _seg()
    terms = ["apple", "banana", "cherry", "dog", "fox", "term",
             "engine", "lazy", "quick", "brown", "search", "index"]
    store = seg._device_store()
    for i in range(len(terms) - 1):
        node = parse_query(f"{terms[i]} | {terms[i + 1]}", an)
        seg.topk_batch([node], 5000, ragged=True)
        assert getattr(store, "_ragged_memo_bytes", 0) <= 32 << 10
        memo = getattr(store, "_pool_batch_memo", {})
        assert len(memo) <= posting_pool._BATCH_MEMO_CAP


# -- error isolation under the device tier (satellite 3) ------------------


class _PoisonWrap:
    """Real scoring, except batches containing the poison node raise —
    the batcher must serial-retry every member on its own thread."""

    def __init__(self, seg, poison):
        self.seg, self.poison = seg, poison

    def topk_batch(self, nodes, k, scorer="bm25", mesh_n=0, ragged=False):
        if any(n is self.poison for n in nodes):
            raise ValueError("poisoned query")
        return self.seg.topk_batch(nodes, k, scorer, mesh_n=mesh_n,
                                   ragged=ragged)

    def topk(self, node, k, scorer="bm25", mesh_n=0):
        return self.topk_batch([node], k, scorer, mesh_n)[0]

    def probe_topk(self, node, k, scorer="bm25", mesh_n=0):
        return None


def test_batcher_poison_isolated_under_device_tier(db):
    """A poisoned query coalesced with pool-served siblings fails ONLY
    its own caller; every sibling's serial retry returns the oracle's
    exact bits."""
    seg, an = _seg()
    good = [parse_query(q, an)
            for q in ("apple | dog", "banana | fox | dog")]
    poison = parse_query("cherry | term", an)
    ref = [seg.topk_batch([n], 5000, ragged=True)[0] for n in good]
    wrap = _PoisonWrap(seg, poison)
    b = SearchBatcher()
    results, errors = {}, {}
    bar = threading.Barrier(3)

    def run(node, slot):
        bar.wait(timeout=30)
        try:
            results[slot] = b.submit(wrap, node, 5000, "bm25", 0, 0.5, 128)
        except ValueError as e:
            errors[slot] = e
    ts = [threading.Thread(target=run, args=(n, i))
          for i, n in enumerate(good + [poison])]
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    assert set(errors) == {2}, "poison must fail exactly its own caller"
    for i in range(2):
        out, _stats = results[i]
        assert _bits_equal(out, ref[i]), i


def test_pool_dispatch_fault_falls_back_serially():
    """An armed posting_pool_dispatch fault poisons the coalesced device
    dispatch; the batcher's serial retry (host oracle path) still hands
    every caller bit-exact results — the pool can never fail a query."""
    seg, an = _seg()
    nodes = [parse_query(q, an)
             for q in ("apple | dog", "banana | fox | dog",
                       "cherry | term")]
    ref = []
    for n in nodes:
        with _global("serene_posting_pool", False):
            ref.append(seg.topk_batch([n], 5000, ragged=True)[0])
    faults.arm_from_spec("posting_pool_dispatch")
    b = SearchBatcher()
    results = {}
    bar = threading.Barrier(len(nodes))

    def run(node, slot):
        bar.wait(timeout=30)
        results[slot] = b.submit(seg, node, 5000, "bm25", 0, 0.5, 128)
    ts = [threading.Thread(target=run, args=(n, i))
          for i, n in enumerate(nodes)]
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    assert len(results) == len(nodes)
    for i in range(len(nodes)):
        out, _stats = results[i]
        assert _bits_equal(out, ref[i]), i


# -- observability surfaces (satellite 2) ---------------------------------


def test_sql_and_http_surfaces(db):
    """sdb_posting_pool() rows resolve the publication and count the
    resident pages; sdb_device() folds the region into hbm_bytes_est;
    GET /device and /_stats carry the posting_pool section."""
    c = db.connect()
    c.execute("SET serene_result_cache = off")
    c.execute("SELECT id, bm25(body) AS s FROM docs "
              "WHERE body @@ 'apple | dog' ORDER BY s DESC, id LIMIT 5000")
    rows = c.execute(
        "SELECT table_name, token, data_version, mutation_epoch, segment, "
        "terms, pages, bytes, hits FROM sdb_posting_pool").rows()
    assert rows, "pool-engaging query must leave resident pages"
    assert any(r[0] == "docs" and r[5] > 0 and r[6] > 0 for r in rows), rows
    st = obs_device.stats_section()
    assert st["posting_pool"]["pages_used"] > 0
    assert st["posting_pool"]["resident_terms"] > 0
    pool_hbm = sum(POOL.device_bytes().values())
    dev = c.execute("SELECT sum(hbm_bytes_est) FROM sdb_device").rows()
    assert dev[0][0] >= pool_hbm > 0
    from serenedb_tpu.server.http_server import HttpServer
    srv = HttpServer(c.db)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        payload = json.load(urllib.request.urlopen(base + "/device"))
        assert payload["posting_pool"]["pages_used"] > 0
        stats = json.load(urllib.request.urlopen(base + "/_stats"))
        assert "posting_pool" in stats["device"]
    finally:
        srv.stop()


def test_pool_off_stays_dark(db):
    """With serene_posting_pool=off nothing touches the pool: no pages,
    no gauges moving — the host ragged path runs alone."""
    with _global("serene_posting_pool", False):
        d0 = metrics.POSTING_POOL_DEVICE_QUERIES.value
        m0 = metrics.POSTING_POOL_MISSES.value
        c = db.connect()
        c.execute("SET serene_result_cache = off")
        c.execute("SELECT id, bm25(body) AS s FROM docs "
                  "WHERE body @@ 'apple | dog' "
                  "ORDER BY s DESC, id LIMIT 5000")
        assert metrics.POSTING_POOL_DEVICE_QUERIES.value == d0
        assert metrics.POSTING_POOL_MISSES.value == m0
        assert c.execute("SELECT count(*) FROM sdb_posting_pool").rows() \
            == [(0,)]
