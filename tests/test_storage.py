"""Storage tests: WAL roundtrip, durable DDL/DML, checkpoint + GC,
crash recovery with fault points (reference: tests/sqllogic/recovery/)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from serenedb_tpu.columnar.column import Batch
from serenedb_tpu.storage.wal import (CommitRecord, SearchDbWal, WalOp,
                                      _decode_record, _encode_ops)


def test_wal_record_roundtrip():
    b = Batch.from_pydict({"a": [1, 2, None], "s": ["x", None, "z"]})
    rec = CommitRecord(7, [WalOp("main.t", "insert", b),
                           WalOp("main.t", "delete",
                                 rows=np.array([0, 2])),
                           WalOp("main.u", "truncate")])
    out = _decode_record(rec.tick, _encode_ops(rec.ops))
    assert out.tick == 7
    assert [o.kind for o in out.ops] == ["insert", "delete", "truncate"]
    assert out.ops[0].batch.to_pydict() == b.to_pydict()
    assert out.ops[1].rows.tolist() == [0, 2]


def test_wal_append_recover_and_torn_tail(tmp_path):
    wal = SearchDbWal(str(tmp_path))
    b = Batch.from_pydict({"a": [1]})
    wal.append_commit(CommitRecord(1, [WalOp("t", "insert", b)]))
    wal.append_commit(CommitRecord(2, [WalOp("t", "insert", b)]))
    wal.close()
    # corrupt the tail: append garbage half-frame
    seg = sorted(os.listdir(tmp_path))[0]
    with open(tmp_path / seg, "ab") as f:
        f.write(b"\x99\x00\x00\x00garbage")
    wal2 = SearchDbWal(str(tmp_path))
    seen = []
    mx = wal2.recover(lambda t: 0, lambda tick, op: seen.append(tick))
    assert mx == 2
    assert seen == [1, 2]
    # delta replay: committed tick 1 skips the first record
    seen2 = []
    wal2.recover(lambda t: 1, lambda tick, op: seen2.append(tick))
    assert seen2 == [2]
    wal2.close()


def test_durable_dml_and_restart(tmp_path):
    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c = db.connect()
    c.execute("CREATE TABLE t (a INT, s TEXT)")
    c.execute("INSERT INTO t VALUES (1, 'x'), (2, 'y'), (3, 'z')")
    c.execute("DELETE FROM t WHERE a = 2")
    c.execute("UPDATE t SET s = 'xx' WHERE a = 1")
    c.execute("CREATE VIEW v AS SELECT a FROM t WHERE a > 1")
    db.close()

    db2 = Database(d)
    c2 = db2.connect()
    rows = c2.execute("SELECT a, s FROM t ORDER BY a").rows()
    assert rows == [(1, "xx"), (3, "z")]
    assert c2.execute("SELECT count(*) FROM v").scalar() == 1
    db2.close()


def test_checkpoint_gc_and_delta_replay(tmp_path):
    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c = db.connect()
    c.execute("CREATE TABLE t (a INT)")
    c.execute("INSERT INTO t VALUES (1), (2)")
    c.execute("VACUUM t")  # checkpoint: snapshot + cursor advance
    c.execute("INSERT INTO t VALUES (3)")
    db.close()

    db2 = Database(d)
    c2 = db2.connect()
    assert [r[0] for r in c2.execute("SELECT a FROM t ORDER BY a").rows()] \
        == [1, 2, 3]
    db2.close()


def test_index_definition_survives_restart(tmp_path):
    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c = db.connect()
    c.execute("CREATE TABLE docs (body TEXT)")
    c.execute("INSERT INTO docs VALUES ('hello world'), ('other things')")
    c.execute("CREATE INDEX ON docs USING inverted (body)")
    db.close()

    db2 = Database(d)
    c2 = db2.connect()
    ex = c2.execute("EXPLAIN SELECT count(*) FROM docs WHERE body @@ 'hello'")
    assert any("SearchScan" in r[0] for r in ex.rows())
    assert c2.execute(
        "SELECT count(*) FROM docs WHERE body @@ 'hello'").scalar() == 1
    db2.close()


def test_datadir_lock(tmp_path):
    from serenedb_tpu.engine import Database
    from serenedb_tpu.errors import SqlError
    d = str(tmp_path / "data")
    db = Database(d)
    with pytest.raises(SqlError):
        Database(d)
    db.close()
    db2 = Database(d)  # released lock can be re-acquired
    db2.close()


CRASH_SCRIPT = r"""
import sys
sys.path.insert(0, {repo!r})
from serenedb_tpu.engine import Database
db = Database({datadir!r})
c = db.connect()
c.execute("CREATE TABLE t (a INT)")
c.execute("INSERT INTO t VALUES (1), (2)")
c.execute("SET sdb_faults = {fault!r}")
try:
    c.execute("INSERT INTO t VALUES (3)")
except BaseException:
    pass
print("SURVIVED")
"""


@pytest.mark.parametrize("fault,expect_third_row", [
    ("crash_before_search_wal_commit", False),  # crash pre-append: lost
    ("crash_after_search_wal_commit", True),    # crash post-fsync: durable
])
def test_crash_recovery_fault_points(tmp_path, fault, expect_third_row):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    d = str(tmp_path / "data")
    script = CRASH_SCRIPT.format(repo=repo, datadir=d, fault=fault)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 137, (p.returncode, p.stdout, p.stderr)
    assert "SURVIVED" not in p.stdout

    from serenedb_tpu.engine import Database
    db = Database(d)  # stale lockfile of the dead pid must not block
    c = db.connect()
    rows = [r[0] for r in c.execute("SELECT a FROM t ORDER BY a").rows()]
    if expect_third_row:
        assert rows == [1, 2, 3]
    else:
        assert rows == [1, 2]
    db.close()


def test_tick_restored_from_checkpoint_cursor_after_gc(tmp_path):
    """Review regression: ticks must resume above checkpoint cursors even
    when every WAL segment was GC'd, or new commits replay as already-seen."""
    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c = db.connect()
    c.execute("CREATE TABLE t (a INT)")
    c.execute("INSERT INTO t VALUES (1)")
    c.execute("VACUUM t")          # checkpoint + GC all WAL
    db.close()
    db2 = Database(d)              # no WAL left; ticks from cursor
    c2 = db2.connect()
    c2.execute("INSERT INTO t VALUES (2)")
    db2.close()
    db3 = Database(d)
    rows = [r[0] for r in db3.connect().execute(
        "SELECT a FROM t ORDER BY a").rows()]
    assert rows == [1, 2]
    db3.close()


def test_recreated_table_does_not_resurrect_old_wal(tmp_path):
    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c = db.connect()
    c.execute("CREATE TABLE t (a INT)")
    c.execute("INSERT INTO t VALUES (1)")
    c.execute("DROP TABLE t")
    c.execute("CREATE TABLE t (a INT)")
    db.close()
    db2 = Database(d)
    assert db2.connect().execute("SELECT count(*) FROM t").scalar() == 0
    db2.close()


def test_append_after_torn_tail_survives_next_recovery(tmp_path):
    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c = db.connect()
    c.execute("CREATE TABLE t (a INT)")
    c.execute("INSERT INTO t VALUES (1)")
    db.close()
    # simulate crash mid-append: garbage at the tail of the open segment
    wal_dir = os.path.join(d, "wal")
    seg = sorted(f for f in os.listdir(wal_dir) if f.endswith(".wal"))[-1]
    with open(os.path.join(wal_dir, seg), "ab") as f:
        f.write(b"\xff\xff\xff\x7fgarbage-torn-frame")
    db2 = Database(d)              # recovery truncates the torn tail
    c2 = db2.connect()
    c2.execute("INSERT INTO t VALUES (2)")   # lands where garbage was
    db2.close()
    db3 = Database(d)              # second recovery must see row 2
    rows = [r[0] for r in db3.connect().execute(
        "SELECT a FROM t ORDER BY a").rows()]
    assert rows == [1, 2]
    db3.close()


def test_drop_schema_cascade_survives_restart(tmp_path):
    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c = db.connect()
    c.execute("CREATE SCHEMA s2")
    c.execute("CREATE TABLE s2.t (a INT)")
    c.execute("INSERT INTO s2.t VALUES (1)")
    c.execute("DROP SCHEMA s2 CASCADE")
    db.close()
    db2 = Database(d)              # must not KeyError on orphan defs
    assert "s2" not in db2.schemas
    db2.close()


def test_alter_table_survives_restart(tmp_path):
    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c = db.connect()
    c.execute("CREATE TABLE t (a INT)")
    c.execute("INSERT INTO t VALUES (1)")
    c.execute("ALTER TABLE t ADD COLUMN note TEXT")
    c.execute("UPDATE t SET note = 'hello' WHERE a = 1")
    c.execute("ALTER TABLE t RENAME TO t2")
    db.close()
    db2 = Database(d)
    rows = db2.connect().execute("SELECT a, note FROM t2").rows()
    assert rows == [(1, "hello")]
    db2.close()


def test_drop_index_case_insensitive_survives_restart(tmp_path):
    from serenedb_tpu.engine import Database
    d = str(tmp_path / "data")
    db = Database(d)
    c = db.connect()
    c.execute("CREATE TABLE t (a INT)")
    c.execute("CREATE INDEX MyIdx ON t USING btree (a)")
    c.execute("DROP INDEX MYIDX")
    db.close()
    db2 = Database(d)
    t = db2.schemas["main"].tables["t"]
    assert not getattr(t, "indexes", {})   # no resurrection on reboot
    db2.close()


def test_upsert_survives_recovery(tmp_path):
    from serenedb_tpu.engine import Database
    path = str(tmp_path / "data")
    db = Database(path)
    c = db.connect()
    c.execute("CREATE TABLE up (id INT PRIMARY KEY, v TEXT)")
    c.execute("INSERT INTO up VALUES (1, 'a')")
    c.execute("INSERT INTO up VALUES (1, 'b'), (2, 'c') "
              "ON CONFLICT (id) DO UPDATE SET v = excluded.v")
    db.close()
    db2 = Database(path)
    rows = sorted(db2.connect().execute("SELECT id, v FROM up").rows())
    assert rows == [(1, "b"), (2, "c")]
    db2.close()


def test_wal_incompatible_version_rejected(tmp_path):
    """A segment without the current SEGMENT_MAGIC must fail with an
    explicit 58030 'incompatible WAL version', not corruption semantics
    (ADVICE r2: format change silently truncated old-format tails)."""
    from serenedb_tpu.errors import SqlError
    from serenedb_tpu.storage.wal import SEGMENT_MAGIC
    # simulate an old-format segment: frames with no segment header
    with open(tmp_path / "000000000001.wal", "wb") as f:
        f.write(b"\x10\x00\x00\x00" + b"x" * 32)
    wal = SearchDbWal(str(tmp_path))
    with pytest.raises(SqlError) as e:
        wal.recover(lambda t: 0, lambda tick, op: None)
    assert e.value.sqlstate == "58030"
    assert "incompatible WAL version" in str(e.value)
    wal.close()
    # a torn header (strict prefix of the magic) in the LAST segment is an
    # uncommitted empty segment, not an error
    with open(tmp_path / "000000000002.wal", "wb") as f:
        f.write(SEGMENT_MAGIC[:3])
    os.remove(tmp_path / "000000000001.wal")
    wal2 = SearchDbWal(str(tmp_path))
    assert wal2.recover(lambda t: 0, lambda tick, op: None) == 0
    assert os.path.getsize(tmp_path / "000000000002.wal") == 0
    wal2.close()


def test_wal_failed_group_write_rolled_back(tmp_path, monkeypatch):
    """Frames of a FAILED group-commit batch must not become durable behind
    a later commit's fsync (ADVICE r2 medium): the leader truncates the
    segment back to its pre-batch offset."""
    wal = SearchDbWal(str(tmp_path))
    b = Batch.from_pydict({"a": [1]})
    wal.append_commit(CommitRecord(1, [WalOp("t", "insert", b)]))

    real_fsync = os.fsync
    calls = {"n": 0}

    def failing_fsync(fd):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("injected fsync failure")
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", failing_fsync)
    with pytest.raises(OSError):
        wal.append_commit(CommitRecord(2, [WalOp("t", "insert", b)]))
    # next commit succeeds and recovery must see ONLY ticks 1 and 3
    wal.append_commit(CommitRecord(3, [WalOp("t", "insert", b)]))
    wal.close()
    wal2 = SearchDbWal(str(tmp_path))
    seen = []
    wal2.recover(lambda t: 0, lambda tick, op: seen.append(tick))
    assert seen == [1, 3]
    wal2.close()


def test_async_drop_tombstones(tmp_path):
    """DROP tombstones the snapshot (O(1) rename); the maintenance GC
    pass reclaims it; a boot after a crash-between also reclaims
    (reference: server/catalog/drop_task.cpp)."""
    import os

    from serenedb_tpu.engine import Database
    d = str(tmp_path / "dd")
    db = Database(d)
    c = db.connect()
    c.execute("CREATE TABLE victim (a INT)")
    c.execute("INSERT INTO victim VALUES (1), (2)")
    c.execute("VACUUM")  # force a checkpoint so a snapshot exists
    tdir = os.path.join(d, "tables")
    snaps = [f for f in os.listdir(tdir) if f.endswith(".parquet")]
    assert snaps
    c.execute("DROP TABLE victim")
    dropped = [f for f in os.listdir(tdir) if f.endswith(".dropped")]
    live = [f for f in os.listdir(tdir) if f.endswith(".parquet")]
    assert dropped and not live
    n = db.store.gc_tombstones()
    assert n == len(dropped)
    assert not [f for f in os.listdir(tdir) if f.endswith(".dropped")]
    db.close()
    # crash-between simulation: plant a tombstone, re-open reclaims it
    with open(os.path.join(tdir, "999.parquet.dropped"), "w") as f:
        f.write("x")
    db2 = Database(d)
    assert not [f for f in os.listdir(tdir) if f.endswith(".dropped")]
    db2.close()


def test_maintenance_runs_drop_gc(tmp_path):
    from serenedb_tpu.engine import Database
    from serenedb_tpu.storage.maintenance import MaintenanceManager
    d = str(tmp_path / "dd2")
    db = Database(d)
    c = db.connect()
    c.execute("CREATE TABLE v2 (a INT)")
    c.execute("INSERT INTO v2 VALUES (1)")
    c.execute("VACUUM")
    c.execute("DROP TABLE v2")
    import os
    tdir = os.path.join(d, "tables")
    assert [f for f in os.listdir(tdir) if f.endswith(".dropped")]
    mm = MaintenanceManager(db)
    assert mm.run_once() is True
    assert not [f for f in os.listdir(tdir) if f.endswith(".dropped")]
    db.close()
