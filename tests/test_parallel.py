"""Mesh-sharded execution tests (8 virtual CPU devices; conftest forces the
mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serenedb_tpu.parallel import (combine_agg_partials, make_mesh,
                                   sharded_agg_step, sharded_bm25_topk,
                                   sharded_query_step, shard_rows)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest should provide 8 cpu devices"
    return make_mesh(8)


def test_sharded_agg_exact(mesh):
    rng = np.random.default_rng(0)
    vals = rng.integers(0, 2**30, (64, 128)).astype(np.int32)
    mask = rng.random((64, 128)) > 0.2
    step = sharded_agg_step(mesh)
    cnt, partials = step(jnp.asarray(vals), jnp.asarray(mask),
                         jnp.int32(1000), jnp.int32(2**29))
    sel = mask & (vals >= 1000) & (vals < 2**29)
    assert int(cnt) == int(sel.sum())
    assert combine_agg_partials(partials) == int(vals[sel].astype(np.int64).sum())


def test_sharded_agg_no_int32_wrap(mesh):
    # values near 65535 in the low half across many rows — the old
    # whole-shard int32 accumulation wrapped here
    vals = np.full((512, 128), 65535, dtype=np.int32)
    mask = np.ones((512, 128), dtype=bool)
    step = sharded_agg_step(mesh)
    cnt, partials = step(jnp.asarray(vals), jnp.asarray(mask),
                         jnp.int32(0), jnp.int32(2**30))
    assert combine_agg_partials(partials) == 512 * 128 * 65535


def test_sharded_bm25_matches_single_device(mesh):
    rng = np.random.default_rng(1)
    p = 8 * 128
    flat_docs = jnp.asarray(np.sort(rng.integers(0, p, p)).astype(np.int32))
    flat_tfs = jnp.asarray(rng.integers(1, 5, p).astype(np.int32))
    norms = jnp.asarray(rng.integers(5, 50, p).astype(np.int32))
    gidx = jnp.asarray(np.arange(p, dtype=np.int32).reshape(-1, 128))
    block_term = jnp.asarray(np.zeros(p // 128, dtype=np.int32))
    idf = jnp.asarray(np.asarray([1.7], dtype=np.float32))
    topk = sharded_bm25_topk(mesh, p, 10)
    s, d = topk(flat_docs, flat_tfs, norms, gidx, block_term, idf,
                jnp.float32(20.0))
    # reference: same math single-device with numpy
    docs = np.asarray(flat_docs)
    tfs = np.asarray(flat_tfs).astype(np.float64)
    dl = np.asarray(norms)[docs].astype(np.float64)
    contrib = 1.7 * 2.2 * tfs / (tfs + 1.2 * (1 - 0.75 + 0.75 * dl / 20.0))
    ref = np.zeros(p)
    np.add.at(ref, docs, contrib)
    order = np.argsort(-ref, kind="stable")[:10]
    np.testing.assert_allclose(np.sort(np.asarray(s)), np.sort(ref[order]),
                               rtol=1e-4)


def test_sharded_query_step_conserves_rows(mesh):
    rng = np.random.default_rng(2)
    g = 16
    vals = jnp.asarray(rng.integers(0, 100, (16, 128)).astype(np.int32))
    mask = jnp.ones((16, 128), dtype=bool)
    codes = jnp.asarray(rng.integers(0, g, (16, 128)).astype(np.int32))
    p = 8 * 128
    flat_docs = jnp.asarray(np.sort(rng.integers(0, p, p)).astype(np.int32))
    flat_tfs = jnp.asarray(rng.integers(1, 5, p).astype(np.int32))
    gidx = jnp.asarray(np.arange(p, dtype=np.int32).reshape(-1, 128))
    block_term = jnp.asarray(np.zeros(p // 128, dtype=np.int32))
    step = sharded_query_step(mesh, g)
    counts, sums, scores = step(vals, mask, codes, flat_docs, flat_tfs,
                                gidx, block_term)
    assert int(np.asarray(counts).sum()) == 16 * 128


def test_shard_rows_pads():
    m = make_mesh(8)
    a = np.ones((13, 4))
    out = shard_rows(a, m)
    assert out.shape[0] % 8 == 0


class TestShardedSql:
    """End-to-end SQL over the mesh (SURVEY §5.7): GROUP BY + BM25 top-k
    through Connection.execute with SET serene_mesh, parity-checked
    against the single-device path. conftest forces 8 virtual CPU
    devices, matching the driver's dryrun."""

    def _db(self):
        from serenedb_tpu.engine import Database
        import random
        db = Database(None)
        c = db.connect()
        c.execute("CREATE TABLE st (k INT, v INT, f DOUBLE, body TEXT)")
        rng = random.Random(5)
        words = ["alpha", "beta", "gamma", "delta", "epsilon", "common"]
        c.execute("INSERT INTO st VALUES " + ", ".join(
            f"({rng.randint(0, 9)}, {rng.randint(-500, 500)}, "
            f"{rng.random() * 10:.4f}, "
            f"'{' '.join(rng.choices(words, k=6))}')"
            for _ in range(20000)))
        c.execute("CREATE INDEX ON st USING inverted (body)")
        c.execute("SET serene_device = 'device'")
        return db, c

    def test_group_by_parity(self):
        db, c = self._db()
        q = ("SELECT k, count(*), sum(v), min(v), max(v) FROM st "
             "WHERE v > -300 GROUP BY k ORDER BY k")
        single = c.execute(q).rows()
        c.execute("SET serene_mesh = 8")
        mesh = c.execute(q).rows()
        assert mesh == single     # int aggregates are exact on both paths

    def test_scalar_agg_parity(self):
        db, c = self._db()
        q = "SELECT count(*), sum(v), min(v), max(v) FROM st WHERE k < 7"
        single = c.execute(q).rows()
        c.execute("SET serene_mesh = 8")
        assert c.execute(q).rows() == single

    def test_float_agg_close(self):
        db, c = self._db()
        q = "SELECT k, avg(f) FROM st GROUP BY k ORDER BY k"
        single = c.execute(q).rows()
        c.execute("SET serene_mesh = 8")
        mesh = c.execute(q).rows()
        for s, m in zip(single, mesh):
            assert s[0] == m[0]
            assert abs(s[1] - m[1]) / max(abs(s[1]), 1e-9) < 1e-4

    def test_bm25_topk_parity(self):
        db, c = self._db()
        q = ("SELECT k, bm25(body, 'common alpha') AS s FROM st "
             "WHERE body @@ 'common alpha' ORDER BY s DESC, k LIMIT 10")
        single = c.execute(q).rows()
        c.execute("SET serene_mesh = 8")
        mesh = c.execute(q).rows()
        assert [r[0] for r in single] == [r[0] for r in mesh]
        for s, m in zip(single, mesh):
            assert abs(s[1] - m[1]) < 1e-3

    def test_mesh_larger_than_devices_falls_back(self):
        db, c = self._db()
        c.execute("SET serene_mesh = 4096")   # > devices: single-device
        q = "SELECT count(*) FROM st WHERE v > 0"
        assert c.execute(q).scalar() > 0
