"""Mesh-sharded execution tests (8 virtual CPU devices; conftest forces the
mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serenedb_tpu.parallel import (combine_agg_partials, make_mesh,
                                   sharded_agg_step, sharded_bm25_topk,
                                   sharded_query_step, shard_rows)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest should provide 8 cpu devices"
    return make_mesh(8)


def test_sharded_agg_exact(mesh):
    rng = np.random.default_rng(0)
    vals = rng.integers(0, 2**30, (64, 128)).astype(np.int32)
    mask = rng.random((64, 128)) > 0.2
    step = sharded_agg_step(mesh)
    cnt, partials = step(jnp.asarray(vals), jnp.asarray(mask),
                         jnp.int32(1000), jnp.int32(2**29))
    sel = mask & (vals >= 1000) & (vals < 2**29)
    assert int(cnt) == int(sel.sum())
    assert combine_agg_partials(partials) == int(vals[sel].astype(np.int64).sum())


def test_sharded_agg_no_int32_wrap(mesh):
    # values near 65535 in the low half across many rows — the old
    # whole-shard int32 accumulation wrapped here
    vals = np.full((512, 128), 65535, dtype=np.int32)
    mask = np.ones((512, 128), dtype=bool)
    step = sharded_agg_step(mesh)
    cnt, partials = step(jnp.asarray(vals), jnp.asarray(mask),
                         jnp.int32(0), jnp.int32(2**30))
    assert combine_agg_partials(partials) == 512 * 128 * 65535


def test_sharded_bm25_matches_single_device(mesh):
    rng = np.random.default_rng(1)
    p = 8 * 128
    flat_docs = jnp.asarray(np.sort(rng.integers(0, p, p)).astype(np.int32))
    flat_tfs = jnp.asarray(rng.integers(1, 5, p).astype(np.int32))
    norms = jnp.asarray(rng.integers(5, 50, p).astype(np.int32))
    gidx = jnp.asarray(np.arange(p, dtype=np.int32).reshape(-1, 128))
    block_term = jnp.asarray(np.zeros(p // 128, dtype=np.int32))
    idf = jnp.asarray(np.asarray([1.7], dtype=np.float32))
    topk = sharded_bm25_topk(mesh, p, 10)
    s, d = topk(flat_docs, flat_tfs, norms, gidx, block_term, idf,
                jnp.float32(20.0))
    # reference: same math single-device with numpy
    docs = np.asarray(flat_docs)
    tfs = np.asarray(flat_tfs).astype(np.float64)
    dl = np.asarray(norms)[docs].astype(np.float64)
    contrib = 1.7 * 2.2 * tfs / (tfs + 1.2 * (1 - 0.75 + 0.75 * dl / 20.0))
    ref = np.zeros(p)
    np.add.at(ref, docs, contrib)
    order = np.argsort(-ref, kind="stable")[:10]
    np.testing.assert_allclose(np.sort(np.asarray(s)), np.sort(ref[order]),
                               rtol=1e-4)


def test_sharded_query_step_conserves_rows(mesh):
    rng = np.random.default_rng(2)
    g = 16
    vals = jnp.asarray(rng.integers(0, 100, (16, 128)).astype(np.int32))
    mask = jnp.ones((16, 128), dtype=bool)
    codes = jnp.asarray(rng.integers(0, g, (16, 128)).astype(np.int32))
    p = 8 * 128
    flat_docs = jnp.asarray(np.sort(rng.integers(0, p, p)).astype(np.int32))
    flat_tfs = jnp.asarray(rng.integers(1, 5, p).astype(np.int32))
    gidx = jnp.asarray(np.arange(p, dtype=np.int32).reshape(-1, 128))
    block_term = jnp.asarray(np.zeros(p // 128, dtype=np.int32))
    step = sharded_query_step(mesh, g)
    counts, sums, scores = step(vals, mask, codes, flat_docs, flat_tfs,
                                gidx, block_term)
    assert int(np.asarray(counts).sum()) == 16 * 128


def test_shard_rows_pads():
    m = make_mesh(8)
    a = np.ones((13, 4))
    out = shard_rows(a, m)
    assert out.shape[0] % 8 == 0
