import numpy as np
import pytest

from serenedb_tpu.columnar import Column, to_device_column
from serenedb_tpu.ops import agg


def dev(vals, validity=None):
    c = Column.from_numpy(np.asarray(vals), validity=validity)
    return to_device_column(c)


def test_masked_count_and_sum_int():
    dc = dev(np.arange(1000, dtype=np.int64))
    assert int(agg.masked_count(dc.mask)) == 1000
    assert agg.masked_sum_int(dc.decode(dc.data), dc.mask) == 499500


def test_masked_sum_int_negative_and_large():
    rng = np.random.default_rng(0)
    vals = rng.integers(-2**30, 2**30, size=5000, dtype=np.int64)
    dc = dev(vals)
    assert agg.masked_sum_int(dc.decode(dc.data), dc.mask) == int(vals.sum())


def test_masked_sum_float_and_minmax():
    vals = np.array([1.5, -2.0, 3.25, 100.0], dtype=np.float64)
    dc = dev(vals)
    assert float(agg.masked_sum_float(dc.data, dc.mask)) == pytest.approx(102.75)
    assert float(agg.masked_minmax(dc.data, dc.mask, "min")) == -2.0
    assert float(agg.masked_minmax(dc.data, dc.mask, "max")) == 100.0


def test_nulls_excluded():
    validity = np.array([True, False, True, True])
    dc = dev(np.array([10, 99, 20, 30], dtype=np.int64), validity)
    assert int(agg.masked_count(dc.mask)) == 3
    assert agg.masked_sum_int(dc.decode(dc.data), dc.mask) == 60


@pytest.mark.parametrize("num_groups", [3, 2000])  # onehot path and scatter path
def test_group_count_paths(num_groups):
    rng = np.random.default_rng(1)
    codes_np = rng.integers(0, num_groups, size=4000).astype(np.int64)
    dc = dev(codes_np)
    counts = agg.group_count(dc.decode(dc.data), dc.mask, num_groups)
    expected = np.bincount(codes_np, minlength=num_groups)
    np.testing.assert_array_equal(counts, expected)


def test_group_sum_int_exact_with_negatives():
    rng = np.random.default_rng(2)
    g = 17
    codes_np = rng.integers(0, g, size=3000).astype(np.int64)
    vals_np = rng.integers(-2**30, 2**30, size=3000, dtype=np.int64)
    dcodes, dvals = dev(codes_np), dev(vals_np)
    sums = agg.group_sum_int(dcodes.data, dcodes.mask, dvals.data, g)
    expected = np.zeros(g, dtype=np.int64)
    np.add.at(expected, codes_np, vals_np)
    np.testing.assert_array_equal(sums, expected)


def test_group_min_max_and_float_sum():
    codes_np = np.array([0, 1, 0, 1, 2], dtype=np.int64)
    vals_np = np.array([5.0, -1.0, 3.0, 7.0, 0.5])
    dcodes, dvals = dev(codes_np), dev(vals_np)
    mn = agg.group_min(dcodes.data, dcodes.mask, dvals.data, 3)
    mx = agg.group_max(dcodes.data, dcodes.mask, dvals.data, 3)
    s = np.asarray(agg.group_sum_float(dcodes.data, dcodes.mask, dvals.data, 3))
    assert mn[:3].tolist() == [3.0, -1.0, 0.5]
    assert mx[:3].tolist() == [5.0, 7.0, 0.5]
    np.testing.assert_allclose(s[:3], [8.0, 6.0, 0.5])


def test_factorize_composite_keys_with_nulls():
    a = np.array([1, 1, 2, 1], dtype=np.int64)
    b = np.array([7, 7, 7, 8], dtype=np.int64)
    valid_b = np.array([True, True, True, False])
    codes, uniq, uniq_valid = agg.factorize_keys([a, b], [None, valid_b])
    # groups: (1,7), (1,7), (2,7), (1,NULL) → 3 groups
    assert codes[0] == codes[1]
    assert len(set(codes.tolist())) == 3
    assert len(uniq[0]) == 3
    # the NULL group's b-validity is False
    null_group = codes[3]
    assert not uniq_valid[1][null_group]


def test_factorize_empty():
    codes, uniq, uniq_valid = agg.factorize_keys(
        [np.array([], dtype=np.int64)], [None])
    assert len(codes) == 0
    assert len(uniq[0]) == 0
