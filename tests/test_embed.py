"""ai_embed provider layer (reference: connector/functions/embedding/)."""

import json

import numpy as np
import pytest

from serenedb_tpu.engine import Database
from serenedb_tpu.errors import SqlError
from serenedb_tpu.functions.embedfns import local_embed


@pytest.fixture
def conn():
    return Database().connect()


def test_local_embed_deterministic_and_normalized():
    a = local_embed("the quick brown fox", 64)
    b = local_embed("the quick brown fox", 64)
    c = local_embed("a completely different text", 64)
    assert np.allclose(a, b)
    assert not np.allclose(a, c)
    assert np.linalg.norm(a) == pytest.approx(1.0)
    # similar texts are closer than dissimilar ones
    d = local_embed("the quick brown foxes", 64)
    assert a @ d > a @ c


def test_sql_ai_embed_default_and_dim(conn):
    v = json.loads(conn.execute("SELECT ai_embed('hello world')").scalar())
    assert len(v) == 64
    v = json.loads(conn.execute(
        "SELECT ai_embed('hello world', 'local:128')").scalar())
    assert len(v) == 128
    assert conn.execute("SELECT ai_embed(NULL)").scalar() is None
    with pytest.raises(SqlError):
        conn.execute("SELECT ai_embed('x', 'local:99999')")
    with pytest.raises(SqlError):
        conn.execute("SELECT ai_embed('x', 'quantum:q1')")


def test_ai_embed_feeds_vector_ops(conn):
    sim = conn.execute(
        "SELECT vec_cos(ai_embed('database search engine'), "
        "ai_embed('database search engines'))").scalar()
    far = conn.execute(
        "SELECT vec_cos(ai_embed('database search engine'), "
        "ai_embed('grilled cheese recipe'))").scalar()
    assert sim < far   # cosine DISTANCE: similar pair is closer


def test_remote_provider_gating(conn):
    # no secret → clear error, no network attempt
    with pytest.raises(SqlError) as e:
        conn.execute("SELECT ai_embed('x', 'openai:text-embedding-3-small', "
                     "'nope')")
    assert "secret" in str(e.value)
    # missing secret arg
    with pytest.raises(SqlError):
        conn.execute("SELECT ai_embed('x', 'openai:m')")
    # with a secret the request is attempted and fails on the
    # network boundary (zero egress) with the provider SQLSTATE
    conn.execute("SELECT create_secret('k1', 'sk-test')")
    with pytest.raises(SqlError) as e:
        conn.execute("SELECT ai_embed('x', 'openai:m', 'k1')")
    assert e.value.sqlstate == "58030"
    assert conn.execute("SELECT drop_secret('k1')").scalar() is True
    assert conn.execute("SELECT drop_secret('k1')").scalar() is False


def test_per_row_model_and_errors(conn):
    conn.execute("CREATE TABLE em (t TEXT, mo TEXT)")
    conn.execute("INSERT INTO em VALUES ('a','local:8'), ('b','local:16')")
    rows = conn.execute("SELECT ai_embed(t, mo) FROM em").rows()
    assert [len(json.loads(r[0])) for r in rows] == [8, 16]
    with pytest.raises(SqlError):
        conn.execute("SELECT ai_embed('x', 'local:abc')")
    # zero-row input → zero output rows
    conn.execute("CREATE TABLE em0 (a TEXT, b TEXT)")
    assert conn.execute("SELECT create_secret(a, b) FROM em0").rows() == []
