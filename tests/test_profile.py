"""Query observability (ISSUE 4): profiler parity, EXPLAIN ANALYZE,
sdb_stat_statements, slow-query log, /metrics + /_stats exports."""

import json
import re
import urllib.request

import numpy as np
import pytest

from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.obs.statements import STATEMENTS, fingerprint, normalize
from serenedb_tpu.utils import log as sdb_log
from serenedb_tpu.utils import metrics as sdb_metrics
from serenedb_tpu.utils.config import REGISTRY as SETTINGS


def _db_with_tables(n=8192):
    """Clustered fact table + small build table: enough rows for the
    morsel-parallel path at serene_morsel_rows=1024, ts clustered so
    zone maps prune, build keys [0,100) so the join filter prunes."""
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE facts (ts BIGINT, k BIGINT, v BIGINT)")
    rng = np.random.default_rng(7)
    db.schemas["main"].tables["facts"].replace(Batch.from_pydict({
        "ts": Column.from_numpy(np.arange(n, dtype=np.int64)),
        "k": Column.from_numpy(
            rng.integers(0, 100, n, dtype=np.int64)),
        "v": Column.from_numpy(
            rng.integers(0, 1000, n, dtype=np.int64))}))
    c.execute("CREATE TABLE build (k BIGINT, w BIGINT)")
    db.schemas["main"].tables["build"].replace(Batch.from_pydict({
        "k": Column.from_numpy(np.arange(100, dtype=np.int64)),
        "w": Column.from_numpy(np.arange(100, dtype=np.int64) * 10)}))
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_morsel_rows = 1024")
    c.execute("SET serene_parallel_min_rows = 1024")
    return db, c


AGG_Q = ("SELECT k, count(*), sum(v) FROM facts "
         "WHERE ts < 2048 GROUP BY k ORDER BY k")
JOIN_Q = ("SELECT count(*), sum(v + w) FROM facts "
          "JOIN build ON facts.k = build.k WHERE facts.ts < 4096")


# -- bit-identity: profiling observes, never steers -------------------------


@pytest.mark.parametrize("query", [AGG_Q, JOIN_Q])
def test_profile_on_off_workers_parity(query):
    db, c = _db_with_tables()
    results = {}
    for prof in ("on", "off"):
        for workers in (1, 4):
            c.execute(f"SET serene_profile = {prof}")
            c.execute(f"SET serene_workers = {workers}")
            results[(prof, workers)] = c.execute(query).rows()
    base = results[("on", 1)]
    assert base  # non-trivial result
    for key, rows in results.items():
        assert rows == base, f"{key} diverged from (on, 1)"


def test_explain_analyze_does_not_perturb():
    db, c = _db_with_tables()
    before = c.execute(AGG_Q).rows()
    c.execute(f"EXPLAIN ANALYZE {AGG_Q}")
    assert c.execute(AGG_Q).rows() == before


# -- EXPLAIN ANALYZE --------------------------------------------------------


def _plan_lines(c, sql):
    return [r[0] for r in c.execute(sql).rows()]


def _rows_of(lines, label_sub):
    for ln in lines:
        if label_sub in ln:
            m = re.search(r"rows=(\d+)", ln)
            assert m, f"no rows= on line: {ln}"
            return int(m.group(1))
    raise AssertionError(f"no line containing {label_sub!r} in {lines}")


def test_explain_analyze_parallel_aggregate_exact_rows():
    db, c = _db_with_tables()
    c.execute("SET serene_workers = 4")
    lines = _plan_lines(c, f"EXPLAIN ANALYZE {AGG_Q}")
    # per-operator actual rows are exact at any worker count
    assert _rows_of(lines, "Scan facts") == 2048
    assert _rows_of(lines, "Aggregate") == 100
    assert _rows_of(lines, "Sort") == 100
    # per-operator timing fields present
    assert all("actual time=" in ln for ln in lines
               if ln.strip().startswith(("Scan", "Aggregate", "Sort")))
    # zone maps pruned the ts >= 2048 blocks: 2 of 8 scheduled
    morsels = next(ln for ln in lines if "Morsels:" in ln)
    assert "scheduled=2" in morsels and "zonemap_pruned=6" in morsels
    assert any(ln.startswith("Execution Time:") for ln in lines)


def test_explain_analyze_join_shows_join_filter_pruning():
    db, c = _db_with_tables()
    # probe keys clustered on ts? no — the JOIN FILTER prunes on k's
    # build range [0,100): make the probe key the clustered ts column so
    # only the first block can hold partners
    lines = _plan_lines(
        c, "EXPLAIN ANALYZE SELECT count(*) FROM facts "
           "JOIN build ON facts.ts = build.k")
    assert _rows_of(lines, "HashJoin") == 100
    scan_i = next(i for i, ln in enumerate(lines) if "Scan facts" in ln)
    # the surviving probe block scans whole (range conjuncts prune
    # blocks, never filter rows): exactly one 1024-row morsel
    assert _rows_of(lines, "Scan facts") == 1024
    morsels = lines[scan_i + 1]
    assert "Morsels:" in morsels
    assert "join_filter_pruned=7" in morsels and "scheduled=1" in morsels


def test_explain_analyze_ignores_profile_setting():
    db, c = _db_with_tables()
    c.execute("SET serene_profile = off")
    lines = _plan_lines(c, "EXPLAIN ANALYZE SELECT count(*) FROM facts")
    assert any("actual time=" in ln for ln in lines)


def test_explain_plain_unchanged():
    db, c = _db_with_tables()
    lines = _plan_lines(c, f"EXPLAIN {AGG_Q}")
    assert not any("actual time=" in ln for ln in lines)


# -- EXPLAIN of DML ---------------------------------------------------------


def test_explain_dml_plain_and_analyze():
    db, c = _db_with_tables()
    lines = _plan_lines(c, "EXPLAIN INSERT INTO build VALUES (500, 0)")
    assert lines[0] == "Insert on build"
    assert any("Values (1 rows)" in ln for ln in lines)

    lines = _plan_lines(
        c, "EXPLAIN INSERT INTO build SELECT k + 1000, w FROM build")
    assert lines[0] == "Insert on build"
    assert any("Scan build" in ln for ln in lines)

    before = c.execute("SELECT count(*) FROM build").scalar()
    lines = _plan_lines(
        c, "EXPLAIN ANALYZE INSERT INTO build VALUES (600, 0), (601, 0)")
    assert "Insert on build" in lines[0]
    assert "rows=2" in lines[0] and "actual time=" in lines[0]
    # ANALYZE really executes the DML (PG semantics)
    assert c.execute("SELECT count(*) FROM build").scalar() == before + 2

    lines = _plan_lines(
        c, "EXPLAIN ANALYZE UPDATE build SET w = 1 WHERE k >= 600")
    assert "Update on build" in lines[0] and "rows=2" in lines[0]
    lines = _plan_lines(
        c, "EXPLAIN ANALYZE DELETE FROM build WHERE k >= 500")
    assert "Delete on build" in lines[0] and "rows=2" in lines[0]
    assert c.execute("SELECT count(*) FROM build").scalar() == before


# -- statement fingerprints / sdb_stat_statements ---------------------------


def test_normalize_collapses_literals_params_case_whitespace():
    a = normalize("SELECT * FROM t WHERE x = 5 AND s = 'abc'")
    b = normalize("select *\n  from T\twhere X=$1 and S = 'zzz';")
    assert a == b == "select * from t where x = ? and s = ?"
    assert fingerprint(a) == fingerprint(b)
    assert normalize("SELECT 1") != normalize("SELECT 1, 2")


def test_stat_statements_aggregation_and_view():
    db, c = _db_with_tables()
    STATEMENTS.reset()
    c.execute("SELECT sum(v) FROM facts WHERE ts < 10")
    c.execute("SELECT sum(v) FROM facts WHERE ts < 999")
    rows = c.execute(
        "SELECT query, calls, rows, total_time_ms, mean_time_ms "
        "FROM sdb_stat_statements WHERE query LIKE '%sum%'").rows()
    assert len(rows) == 1                     # literals collapsed → one entry
    q, calls, nrows, total, mean = rows[0]
    assert calls == 2 and nrows == 2
    assert q == "select sum ( v ) from facts where ts < ?"
    # view columns round to 6 decimals: mean ≈ total/2 within rounding
    assert total > 0 and abs(mean - total / 2) < 1e-5


def test_stat_statements_morsels_pruned_attribution():
    db, c = _db_with_tables()
    STATEMENTS.reset()
    c.execute(AGG_Q)
    row = c.execute(
        "SELECT morsels_pruned FROM sdb_stat_statements "
        "WHERE query LIKE '%group by%'").rows()
    assert row and row[0][0] == 6


def test_stat_statements_lru_eviction_at_cap():
    db, c = _db_with_tables()
    STATEMENTS.reset()
    old = SETTINGS.get_global("serene_stat_statements_max")
    SETTINGS.set_global("serene_stat_statements_max", 3)
    try:
        for i in range(6):
            c.execute(f"SELECT {i} AS c{i}")   # distinct fingerprints
        assert len(STATEMENTS) <= 3
        queries = [e["query"] for e in STATEMENTS.snapshot()]
        assert "select ? as c5" in queries     # most recent survives
        assert "select ? as c0" not in queries  # oldest evicted
    finally:
        SETTINGS.set_global("serene_stat_statements_max", old)
        STATEMENTS.reset()


def test_profile_off_records_nothing():
    db, c = _db_with_tables()
    c.execute("SET serene_profile = off")
    STATEMENTS.reset()
    c.execute("SELECT 42")
    assert len(STATEMENTS) == 0


# -- slow-query log ---------------------------------------------------------


def _slow_records():
    return [r for r in sdb_log.MANAGER.records() if r.topic == "slow_query"]


def test_slow_query_log_threshold():
    db, c = _db_with_tables()
    c.execute("SET serene_log_min_duration_ms = 100000")
    n0 = len(_slow_records())
    c.execute("SELECT count(*) FROM facts")
    assert len(_slow_records()) == n0          # under threshold: silent
    c.execute("SET serene_log_min_duration_ms = 0")
    c.execute(AGG_Q)
    recs = _slow_records()
    assert len(recs) > n0
    # the profiled tree rides along in the message
    assert "Scan facts" in recs[-1].message
    assert "actual time=" in recs[-1].message
    c.execute("SET serene_log_min_duration_ms = -1")   # default: disabled
    n1 = len(_slow_records())
    c.execute("SELECT count(*) FROM facts")
    assert len(_slow_records()) == n1


# -- pg_stat_activity -------------------------------------------------------


def test_pg_stat_activity_live_query_and_id():
    db, c = _db_with_tables()
    c.execute("SELECT count(*) FROM facts")
    rows = c.execute(
        "SELECT pid, state, query_id, query FROM pg_stat_activity").rows()
    me = [r for r in rows if "pg_stat_activity" in r[3]]
    assert me and me[0][1] == "active"
    # query_id is the previous completed statement's fingerprint
    assert me[0][2] == fingerprint(
        normalize("SELECT count(*) FROM facts"))


# -- gauge helpers ----------------------------------------------------------


def test_gauge_delta_and_registry_snapshot():
    g = sdb_metrics.Gauge("TestTimer")
    base = g.value
    g.add(500)
    assert g.delta(base) == 500

    snap = sdb_metrics.REGISTRY.snapshot()
    assert isinstance(snap, dict) and "QueriesActive" in snap
    assert set(snap) == {x.name for x in sdb_metrics.REGISTRY.all()}
    assert all(isinstance(v, int) for v in snap.values())


# -- HTTP exports -----------------------------------------------------------


@pytest.fixture(scope="module")
def srv():
    from serenedb_tpu.server.http_server import HttpServer
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE m (x INT)")
    c.execute("INSERT INTO m VALUES (1), (2), (3)")
    c.execute("SELECT count(*) FROM m")
    s = HttpServer(db, port=0)
    s.start()
    yield s
    s.stop()


_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?\d+(\.\d+)?([eE][+-]?\d+)?$")


def test_metrics_endpoint_parses_as_prometheus(srv):
    # ensure at least one recorded statement regardless of test order
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/_sql",
        data=json.dumps({"query": "SELECT count(*) FROM m"}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    urllib.request.urlopen(req, timeout=30).read()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=30) as r:
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        body = r.read().decode()
    lines = [ln for ln in body.splitlines() if ln]
    assert lines
    for ln in lines:
        if ln.startswith("#"):
            assert ln.startswith(("# HELP ", "# TYPE "))
        else:
            assert _PROM_LINE.match(ln), f"bad prometheus line: {ln}"
    assert any(ln.startswith("serenedb_queries_executed") for ln in lines)
    assert any(ln.startswith("serenedb_statement_calls{") for ln in lines)


def test_stats_endpoint_exports_metrics_and_statements(srv):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/_stats", timeout=30) as r:
        payload = json.loads(r.read().decode())
    # ES sections intact, observability sections added
    assert "_all" in payload and "indices" in payload
    assert payload["metrics"]["QueriesActive"] >= 0
    assert isinstance(payload["statements"], list)
