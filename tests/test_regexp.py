"""The linear-time regexp engine: differential vs Python `re` on safe
patterns, ReDoS immunity, and error handling."""

import re
import time

import pytest

from serenedb_tpu.search.regexp import RegexpError, compile_regexp

CASES = [
    ("abc", ["abc", "ab", "abcd", ""]),
    ("a.c", ["abc", "axc", "ac", "abbc"]),
    ("a*", ["", "a", "aaaa", "b", "ab"]),
    ("a+b", ["ab", "aaab", "b", "a"]),
    ("ab?c", ["ac", "abc", "abbc"]),
    ("a{3}", ["aa", "aaa", "aaaa"]),
    ("a{2,4}", ["a", "aa", "aaa", "aaaa", "aaaaa"]),
    ("a{2,}", ["a", "aa", "aaaaaa"]),
    ("(ab)+", ["ab", "abab", "aba", ""]),
    ("a|bc", ["a", "bc", "b", "abc"]),
    ("(a|b)*c", ["c", "abbac", "abba"]),
    ("[abc]+", ["a", "cab", "d", ""]),
    ("[a-f0-9]+", ["deadbeef", "cafe42", "xyz"]),
    ("[^a-c]+", ["xyz", "axy", ""]),
    (r"\d{2,3}", ["1", "12", "123", "1234", "ab"]),
    (r"\w+", ["hello_1", "a b", ""]),
    (r"\.x", [".x", "ax"]),
    (r"a\\b", ["a\\b", "ab"]),
    (".*serv.*", ["server", "observer", "nope"]),
    ("rest.*", ["restart", "arrest", "rest"]),
    ("x(y(z|w))?", ["x", "xyz", "xyw", "xy"]),
    ("[]a]+", ["]a]", "b"]),
    ("", ["", "a"]),
]


def test_matches_python_re():
    for pat, subjects in CASES:
        ours = compile_regexp(pat)
        theirs = re.compile(pat)
        for s in subjects:
            assert ours.fullmatch(s) == (theirs.fullmatch(s) is not None), \
                (pat, s)


def test_redos_pattern_is_linear():
    # (a+)+c on a long run of 'a's: exponential for backtracking engines
    r = compile_regexp("(a+)+c")
    t0 = time.monotonic()
    assert not r.fullmatch("a" * 200)
    assert r.fullmatch("a" * 200 + "c")
    assert time.monotonic() - t0 < 2.0


def test_nested_quantifier_blowup_is_linear():
    r = compile_regexp("(a|a)*b")
    t0 = time.monotonic()
    assert not r.fullmatch("a" * 300)
    assert time.monotonic() - t0 < 2.0


@pytest.mark.parametrize("bad", [
    "[unclosed", "(unclosed", "a{2,1}", "a{", "*a", "+", "a\\",
    "a{999}",
])
def test_bad_patterns_raise(bad):
    with pytest.raises(RegexpError):
        compile_regexp(bad)


def test_repeat_cap_rejects_state_blowup():
    with pytest.raises(RegexpError):
        compile_regexp("(a{100}){100}")


def test_case_fold_literals_and_ranges():
    r = compile_regexp("Alpha.*", case_fold=True)
    assert r.fullmatch("alphabet")
    assert not compile_regexp("Alpha.*").fullmatch("alphabet")
    r = compile_regexp("[A-F]+", case_fold=True)
    assert r.fullmatch("cafe") and r.fullmatch("CAFE")
    # negated classes stay verbatim under folding
    r = compile_regexp("[^A-Z]+", case_fold=True)
    assert r.fullmatch("abc")


def test_anchor_assertions():
    # ^/$ are zero-width assertions, composing with unanchored wrappers
    r = compile_regexp("(.|\n)*(^a|b)(.|\n)*")
    assert r.fullmatch("xb") and r.fullmatch("ab")
    assert not r.fullmatch("xa")
    r = compile_regexp("(.|\n)*(a$|b)(.|\n)*")
    assert r.fullmatch("za") and r.fullmatch("bz")
    assert not r.fullmatch("az")
    assert compile_regexp("^abc$").fullmatch("abc")
    assert not compile_regexp("a^b").fullmatch("ab")
    assert compile_regexp("^$").fullmatch("")
    assert not compile_regexp("^$").fullmatch("x")
