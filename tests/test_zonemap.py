"""Zone-map subsystem parity and pruning suite (ISSUE 2).

Contract under test: `serene_zonemap = on` and `= off` must be
bit-identical at ANY worker count — pruning is an optimization layer,
never a semantics layer — including over NULLs, NaNs, dictionary
strings, and after UPDATE/DELETE/append invalidation. The debug assert
mode (`serene_zonemap_verify`) re-scans every pruned morsel and must
fail loudly when block statistics diverge from table data.
"""

import numpy as np
import pytest

from serenedb_tpu.columnar import dtypes as dt
from serenedb_tpu.columnar.column import Batch, Column
from serenedb_tpu.engine import Database
from serenedb_tpu.exec import zonemap
from serenedb_tpu.exec.tables import MemTable
from serenedb_tpu.utils import metrics


def _mk_conn(n=120_000, seed=11, morsel_rows=4096):
    """Mixed-type table: clustered ts (the pruning axis), random values,
    NULLs in nv/f, NaNs in f, dictionary strings in g."""
    rng = np.random.default_rng(seed)
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE z (ts BIGINT, v BIGINT, g TEXT, f DOUBLE, "
              "nv INT, b BOOLEAN)")
    f = rng.normal(size=n)
    f[rng.random(n) < 0.02] = np.nan
    fvalid = rng.random(n) > 0.1
    nv = rng.integers(0, 9, n).astype(np.int32)
    batch = Batch.from_pydict({
        "ts": Column.from_numpy(np.arange(n, dtype=np.int64)),
        "v": Column.from_numpy(
            rng.integers(-(10 ** 6), 10 ** 6, n, dtype=np.int64)),
        "g": Column.from_numpy(
            rng.choice(["alpha", "beta", "gamma", "delta"], n)),
        "f": Column(dt.DOUBLE, f, fvalid),
        "nv": Column(dt.INT, nv, rng.random(n) > 0.2),
        "b": Column.from_numpy(rng.random(n) > 0.5),
    })
    db.schemas["main"].tables["z"] = MemTable("z", batch)
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_parallel_min_rows = 1024")
    c.execute(f"SET serene_morsel_rows = {morsel_rows}")
    return c


PRUNE_QUERIES = [
    "SELECT count(*), sum(v) FROM z WHERE ts < 5000",
    "SELECT count(*), sum(v), avg(f) FROM z WHERE ts BETWEEN 7000 AND 9000",
    "SELECT g, count(*), sum(v) FROM z WHERE ts >= 110000 "
    "GROUP BY g ORDER BY g",
    "SELECT count(*) FROM z WHERE ts IN (3, 4096, 100000)",
    "SELECT count(*), min(f), max(f) FROM z WHERE ts > 115000 OR ts < 100",
    "SELECT count(*) FROM z WHERE nv IS NULL AND ts < 3000",
    "SELECT count(*) FROM z WHERE nv IS NOT NULL AND ts < 3000",
    "SELECT count(*) FROM z WHERE g = 'alpha' AND ts < 2500",
    "SELECT count(*) FROM z WHERE g > 'gamma'",          # no prunable range
    "SELECT count(*) FROM z WHERE NOT (ts >= 2000)",
    "SELECT count(*) FROM z WHERE ts NOT IN (1, 2)",
    "SELECT count(*) FROM z WHERE b AND ts < 1500",
    "SELECT count(*) FROM z WHERE f > 1e12",             # NaN blocks survive
    "SELECT count(*) FROM z WHERE ts < 0",               # everything pruned
    "SELECT * FROM z WHERE ts = 54321",                  # serial scan path
    "SELECT ts, v FROM z WHERE ts >= 119000 ORDER BY ts LIMIT 7",
]


@pytest.mark.parametrize("workers", [1, 4])
@pytest.mark.parametrize("q", PRUNE_QUERIES)
def test_parity_zonemap_on_off_x_workers(q, workers):
    c = _mk_conn()
    c.execute(f"SET serene_workers = {workers}")
    c.execute("SET serene_zonemap = on")
    on = repr(c.execute(q).rows())
    c.execute("SET serene_zonemap = off")
    off = repr(c.execute(q).rows())
    assert on == off  # bit-identical, incl. float bits, NaNs, order


def test_parity_after_update_delete_append():
    c = _mk_conn(n=40_000)
    steps = [
        "UPDATE z SET ts = 1000000 + v WHERE ts >= 39000",
        "DELETE FROM z WHERE ts < 2000",
        "INSERT INTO z SELECT ts + 2000000, v, g, f, nv, b FROM z "
        "WHERE ts < 10000",
    ]
    probes = [
        "SELECT count(*), sum(v) FROM z WHERE ts < 8000",
        "SELECT count(*) FROM z WHERE ts >= 1000000",
        "SELECT g, count(*) FROM z WHERE ts >= 2000000 GROUP BY g "
        "ORDER BY g",
    ]
    for step in steps:
        # warm the stats, mutate, then every probe must match zonemap=off
        for p in probes:
            c.execute(p)
        c.execute(step)
        for p in probes:
            on = repr(c.execute(p).rows())
            c.execute("SET serene_zonemap = off")
            off = repr(c.execute(p).rows())
            c.execute("SET serene_zonemap = on")
            assert on == off, (step, p)


def test_metrics_move_under_selective_filter():
    c = _mk_conn()
    pruned0 = metrics.ZONEMAP_PRUNED.value
    scanned0 = metrics.ZONEMAP_SCANNED.value
    c.execute("SELECT count(*), sum(v) FROM z WHERE ts < 4000")
    assert metrics.ZONEMAP_PRUNED.value > pruned0
    assert metrics.ZONEMAP_SCANNED.value > scanned0


def test_stale_rebuild_metric_update_vs_append():
    c = _mk_conn(n=30_000)
    c.execute("SELECT count(*) FROM z WHERE ts < 1000")   # build stats
    stale0 = metrics.ZONEMAP_STALE_REBUILDS.value
    # pure append: prefix block stats extend, no stale rebuild
    c.execute("INSERT INTO z VALUES (900000, 1, 'tail', 0.5, 1, true)")
    assert c.execute(
        "SELECT count(*) FROM z WHERE ts = 900000").scalar() == 1
    assert metrics.ZONEMAP_STALE_REBUILDS.value == stale0
    # UPDATE bumps the mutation epoch: next build is from scratch
    c.execute("UPDATE z SET ts = 0 WHERE ts = 900000")
    assert c.execute("SELECT count(*) FROM z WHERE ts = 0").scalar() == 2
    assert metrics.ZONEMAP_STALE_REBUILDS.value > stale0


def test_incremental_append_extends_blocks():
    rng = np.random.default_rng(0)
    t = MemTable("m", Batch.from_pydict(
        {"x": Column.from_numpy(np.arange(10_000, dtype=np.int64))}))
    z1 = zonemap.column_zones(t, "x", 1024, t.try_pin())
    assert z1.n_blocks == 10 and z1.mins[0] == 0 and z1.maxs[-1] == 9999
    t.append_batch(Batch.from_pydict(
        {"x": Column.from_numpy(
            rng.integers(20_000, 30_000, 5000, dtype=np.int64))}))
    z2 = zonemap.column_zones(t, "x", 1024, t.try_pin())
    assert z2.n_blocks == 15 and z2.nrows == 15_000
    # complete prefix blocks carried over verbatim
    assert z2.mins[:9] == z1.mins[:9] and z2.maxs[:9] == z1.maxs[:9]
    assert min(z2.mins[9:]) >= 9216 and max(z2.maxs[10:]) < 30_000


def test_verify_mode_catches_corrupt_stats():
    c = _mk_conn(n=20_000)
    # this test asserts EXECUTION internals (the verify re-scan must
    # run): the result cache would legitimately serve the repeat query
    # without executing at all, hiding the corruption probe
    c.execute("SET serene_result_cache = off")
    c.execute("SET serene_zonemap_verify = on")
    q = "SELECT count(*), sum(v) FROM z WHERE ts < 3000"
    expect = c.execute(q).rows()    # clean stats: no error, right answer
    assert expect[0][0] == 3000
    # corrupt the cached stats so a matching block looks prunable
    t = c.db.schemas["main"].tables["z"]
    for (name, _), (ver, ep, pos, zones) in t._zonemap_cache.items():
        if name == "ts" and zones is not None:
            zones.mins = [10 ** 9] * zones.n_blocks
            zones.maxs = [10 ** 9 + 1] * zones.n_blocks
    with pytest.raises(AssertionError, match="zonemap_verify"):
        c.execute(q)
    # without verify, the corruption would return wrong results —
    # proving the assert mode is the structural guard
    c.execute("SET serene_zonemap_verify = off")
    assert c.execute(q).rows()[0][0] != 3000
    # invalidation clears the corruption: UPDATE bumps the mutation
    # epoch, forcing a from-scratch rebuild of the stats
    c.execute("INSERT INTO z VALUES (0, 0, 'x', 0, 0, true)")
    c.execute("UPDATE z SET v = v + 0 WHERE ts = 0")
    assert c.execute(q).rows()[0][0] == 3001


def test_scan_node_prunes_serial_path():
    c = _mk_conn()
    c.execute("SET serene_workers = 1")
    pruned0 = metrics.ZONEMAP_PRUNED.value
    rows = c.execute("SELECT ts, g FROM z WHERE ts BETWEEN 50000 AND 50004 "
                     "ORDER BY ts").rows()
    assert [r[0] for r in rows] == list(range(50000, 50005))
    assert metrics.ZONEMAP_PRUNED.value > pruned0


def test_parquet_scan_prunes(tmp_path):
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq
    n = 40_000
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({
        "ts": np.arange(n, dtype=np.int64),
        "v": np.random.default_rng(1).integers(0, 100, n),
        "s": np.array(["ab", "cd"] * (n // 2)),
    }), path)
    db = Database()
    c = db.connect()
    c.execute("SET serene_device = 'cpu'")
    c.execute("SET serene_parallel_min_rows = 1024")
    c.execute("SET serene_morsel_rows = 4096")
    q = (f"SELECT count(*), sum(v) FROM read_parquet('{path}') "
         f"WHERE ts < 5000")
    on = c.execute(q).rows()
    c.execute("SET serene_zonemap = off")
    off = c.execute(q).rows()
    assert on == off and on[0][0] == 5000


def test_alter_rename_invalidates_stats():
    """Epoch-preserving ALTERs move values under old names; zone stats
    must never survive them (review finding: RENAME swap returned wrong
    counts before drop/rename bumped the mutation epoch)."""
    c = _mk_conn(n=30_000)
    assert c.execute(
        "SELECT count(*) FROM z WHERE ts >= 1000").scalar() == 29_000
    c.execute("ALTER TABLE z RENAME COLUMN ts TO old_ts")
    c.execute("ALTER TABLE z RENAME COLUMN v TO ts")
    on = c.execute("SELECT count(*) FROM z WHERE ts >= 1000").scalar()
    c.execute("SET serene_zonemap = off")
    off = c.execute("SELECT count(*) FROM z WHERE ts >= 1000").scalar()
    c.execute("SET serene_zonemap = on")
    assert on == off
    # drop + re-add the same name: fresh all-NULL column, fresh stats
    c.execute("ALTER TABLE z DROP COLUMN ts")
    c.execute("ALTER TABLE z ADD COLUMN ts BIGINT")
    assert c.execute("SELECT count(*) FROM z WHERE ts >= 1000").scalar() == 0
    assert c.execute(
        "SELECT count(*) FROM z WHERE ts IS NULL").scalar() == 30_000


def test_search_scores_survive_doc_pruning():
    """Stream-mode bm25() scores must be identical with zone maps on/off
    even when the residual prunes candidate docs (review finding: the
    score pass was sized by the post-prune count, zeroing survivors)."""
    db = Database()
    c = db.connect()
    c.execute("CREATE TABLE d (id INT, body TEXT, v INT)")
    for i in range(0, 30_000, 2000):
        vals = ",".join(
            f"({j}, '{'apple pie' if j % 4 == 0 else 'banana split'}', {j})"
            for j in range(i, i + 2000))
        c.execute(f"INSERT INTO d VALUES {vals}")
    c.execute("CREATE INDEX ON d USING inverted (body)")
    c.execute("SET serene_morsel_rows = 2048")
    q = ("SELECT id, bm25(body) FROM d WHERE body @@ 'apple' AND v < 3000 "
         "ORDER BY id LIMIT 10")
    on = repr(c.execute(q).rows())
    c.execute("SET serene_zonemap = off")
    off = repr(c.execute(q).rows())
    c.execute("SET serene_zonemap = on")
    assert on == off
    assert "0.0)" not in on     # survivors keep their real scores


# -- analyzer unit coverage ---------------------------------------------------


def test_analyzer_three_state_semantics():
    n = 8192
    t = MemTable("a", Batch.from_pydict({
        "x": Column.from_numpy(np.arange(n, dtype=np.int64)),
        "s": Column.from_numpy(np.array(["aa", "bb"] * (n // 2),
                                        dtype=object)),
    }))
    pin = t.try_pin()
    zx = zonemap.column_zones(t, "x", 1024, pin)
    zs = zonemap.column_zones(t, "s", 1024, pin)
    assert zx.n_blocks == 8
    # numeric three-state: block 0 is [0,1023]
    assert zonemap._cmp_set("<", zx, 0, 5000) == zonemap._T
    assert zonemap._cmp_set("<", zx, 4, 4096) == zonemap._F
    assert zonemap._cmp_set("=", zx, 0, 500) == (zonemap._T | zonemap._F)
    assert zonemap._cmp_set(">", zx, 7, 7167) == zonemap._T
    # string stats decode through the dictionary
    assert zs.mins[0] == "aa" and zs.maxs[0] == "bb"
    assert zonemap._cmp_set("<", zs, 0, "zz") == zonemap._T
    assert zonemap._cmp_set(">", zs, 0, "cc") == zonemap._F
    # type confusion degrades to unknown, never to a wrong prune
    assert zonemap._cmp_set("<", zx, 0, "text") == zonemap._TFN
    assert zonemap._cmp_set("<", zs, 0, 7) == zonemap._TFN


def test_analyzer_nan_and_null_sets():
    f = np.array([1.0, 2.0, np.nan, 3.0] * 256)
    t = MemTable("f", Batch.from_pydict({
        "f": Column(dt.DOUBLE, f, np.array([True, True, True, False] * 256)),
    }))
    zf = zonemap.column_zones(t, "f", 1024, t.try_pin())
    assert bool(zf.nans[0]) and int(zf.nulls[0]) == 256
    # NaN is the PG-greatest float: f > 100 can still be true via NaN
    s = zonemap._cmp_set(">", zf, 0, 100.0)
    assert s & zonemap._T and s & zonemap._N
    # f < 0: no value (NaN included) can satisfy it → F/N only
    s = zonemap._cmp_set("<", zf, 0, 0.0)
    assert not (s & zonemap._T)


def test_fold_constant_and_comparison_parts():
    from serenedb_tpu.sql import binder
    from serenedb_tpu.sql.expr import BoundColumn, BoundLiteral
    from serenedb_tpu.functions import scalar as fnlib
    from serenedb_tpu.sql.expr import BoundFunc

    col = BoundColumn(2, dt.BIGINT, "x")
    lit = BoundLiteral(41, dt.INT)

    def cmp_f(name, a, b):
        res = fnlib.resolve(name, [a.type, b.type])
        return BoundFunc(name, [a, b],
                         dt.BOOL, lambda cols, bt, _i=res.impl:
                         _i(cols, bt.num_rows))

    assert binder.comparison_parts(cmp_f("op<", col, lit)) == (2, "<", 41)
    # mirrored: 41 > x  ≡  x < 41
    assert binder.comparison_parts(cmp_f("op>", lit, col)) == (2, "<", 41)
    assert binder.comparison_parts(cmp_f("op<", col, col)) is None
    assert binder.fold_constant(lit) == 41
    assert binder.fold_constant(col) is binder._NOT_CONST
